"""vmalert-tool: promtool-style unit testing for rule files (reference
app/vmalert-tool/unittest).

Test file format (promtool-compatible subset):

  rule_files: [rules.yml]
  evaluation_interval: 1m
  tests:
  - interval: 1m
    input_series:
    - series: 'errs{job="api"}'
      values: '0+10x10'            # expanding notation: start+stepxcount
    alert_rule_test:
    - eval_time: 5m
      alertname: ErrsHigh
      exp_alerts:
      - exp_labels: {job: api, severity: crit}
    metricsql_expr_test:
    - expr: sum(errs)
      eval_time: 5m
      exp_samples:
      - labels: '{}'
        value: 50
"""

from __future__ import annotations

import argparse
import re
import sys
import tempfile

from ..utils import logger


def parse_series_values(spec: str) -> list[float]:
    """promtool expanding notation: 'a+bxn' / 'a-bxn' / 'axn' / literals,
    space separated; '_' = missing, 'stale' = staleness marker."""
    out: list[float] = []
    for tok in str(spec).split():
        m = re.fullmatch(r"(-?[\d.]+)([+-][\d.]+)x(\d+)", tok)
        if m:
            start, step, n = float(m.group(1)), float(m.group(2)), int(m.group(3))
            out.extend(start + step * i for i in range(n + 1))
            continue
        m = re.fullmatch(r"(-?[\d.]+)x(\d+)", tok)
        if m:
            out.extend([float(m.group(1))] * (int(m.group(2)) + 1))
            continue
        if tok == "_":
            out.append(float("nan"))
        elif tok == "stale":
            from ..ops.decimal import STALE_NAN
            out.append(STALE_NAN)
        else:
            out.append(float(tok))
    return out


def _parse_series_selector(s: str) -> dict:
    from ..query.metricsql import parse
    from ..query.metricsql.ast import MetricExpr
    e = parse(s)
    if not isinstance(e, MetricExpr):
        raise ValueError(f"input_series must be a plain series: {s}")
    labels = {}
    for f in e.label_filters:
        if f.is_negative or f.is_regexp:
            raise ValueError(f"input_series labels must be exact: {s}")
        labels[f.label] = f.value
    return labels


def run_test_file(path: str) -> list[str]:
    """Returns a list of failure messages (empty = all passed)."""
    import math
    import os

    import yaml

    from ..query.types import EvalConfig
    from ..storage.storage import Storage
    from .vmalert import Datasource

    cfg = yaml.safe_load(open(path).read()) or {}
    failures: list[str] = []

    rule_groups = []
    for rf in cfg.get("rule_files", []):
        full = rf if os.path.isabs(rf) else \
            os.path.join(os.path.dirname(os.path.abspath(path)), rf)
        rcfg = yaml.safe_load(open(full).read()) or {}
        rule_groups.extend(rcfg.get("groups", []))

    for ti, test in enumerate(cfg.get("tests", [])):
        from ..query.metricsql.parser import parse_duration_ms
        interval_ms = int(parse_duration_ms(
            str(test.get("interval", cfg.get("evaluation_interval", "1m"))))[0])
        with tempfile.TemporaryDirectory() as tmp:
            storage = Storage(tmp)
            # test epoch: use a fixed recent-ish base so per-day index works
            t0 = 1_700_000_000_000
            rows = []
            for inp in test.get("input_series", []):
                labels = _parse_series_selector(inp["series"])
                vals = parse_series_values(inp.get("values", ""))
                for i, v in enumerate(vals):
                    if isinstance(v, float) and math.isnan(v) and \
                            not _is_stale(v):
                        continue
                    rows.append((labels, t0 + i * interval_ms, v))
            storage.add_rows(rows)
            storage.force_flush()

            class _LocalDS(Datasource):
                def __init__(self):
                    pass

                def query(self, expr, ts=None):
                    ec = EvalConfig(start=int(ts * 1000), end=int(ts * 1000),
                                    step=interval_ms, storage=storage,
                                    lookback_delta=5 * interval_ms)
                    from ..query.exec import exec_query
                    rows_ = exec_query(ec, expr)
                    out = []
                    for r in rows_:
                        v = float(r.values[-1])
                        if math.isnan(v):
                            continue
                        out.append({"metric": r.metric_name.to_dict(),
                                    "value": v, "ts": ts})
                    return out

            ds = _LocalDS()

            for at in test.get("alert_rule_test", []):
                eval_ms = int(parse_duration_ms(str(at["eval_time"]))[0])
                want = at.get("exp_alerts") or []
                got = _eval_alert(rule_groups, ds, at["alertname"],
                                  (t0 + eval_ms) / 1e3, interval_ms)
                got_lbls = sorted(
                    tuple(sorted({k: v for k, v in g.items()
                                  if k != "alertname"}.items()))
                    for g in got)
                want_lbls = sorted(
                    tuple(sorted({str(k): str(v)
                                  for k, v in (w.get("exp_labels") or {}).items()
                                  }.items()))
                    for w in want)
                if got_lbls != want_lbls:
                    failures.append(
                        f"test #{ti} alert {at['alertname']} at "
                        f"{at['eval_time']}: expected {want_lbls}, "
                        f"got {got_lbls}")

            for et in test.get("metricsql_expr_test", []) + \
                    test.get("promql_expr_test", []):
                eval_ms = int(parse_duration_ms(str(et["eval_time"]))[0])
                res = ds.query(et["expr"], (t0 + eval_ms) / 1e3)
                want = et.get("exp_samples") or []
                if len(res) != len(want):
                    failures.append(
                        f"test #{ti} expr {et['expr']!r}: expected "
                        f"{len(want)} samples, got {len(res)}")
                    continue
                remaining = list(res)
                for w in want:
                    wv = float(w.get("value", 0))
                    w_labels = (_parse_series_selector(w["labels"])
                                if w.get("labels") else None)
                    # match by labels when given, else by value
                    match = None
                    for g in remaining:
                        if w_labels is not None:
                            if g["metric"] == w_labels:
                                match = g
                                break
                        elif abs(g["value"] - wv) <= 1e-9 * max(abs(wv), 1):
                            match = g
                            break
                    if match is None:
                        failures.append(
                            f"test #{ti} expr {et['expr']!r}: no result "
                            f"matching {w}")
                        continue
                    remaining.remove(match)
                    if abs(match["value"] - wv) > 1e-9 * max(abs(wv), 1):
                        failures.append(
                            f"test #{ti} expr {et['expr']!r} "
                            f"{w.get('labels', '')}: expected {wv}, "
                            f"got {match['value']}")
            storage.close()
    return failures


def _is_stale(v: float) -> bool:
    import numpy as np

    from ..ops import decimal as dec
    return bool(dec.is_stale_nan(np.array([v])).any())


def _eval_alert(rule_groups, ds, alertname, now_s, interval_ms):
    """Evaluate matching alerting rules stepwise up to now_s so `for`
    durations behave; returns firing label sets."""
    from .vmalert import STATE_FIRING, AlertingRule, Group

    out = []
    for g in rule_groups:
        for rc in g.get("rules", []):
            if rc.get("alert") != alertname:
                continue
            rule = AlertingRule(rc, None)
            t = 1_700_000_000_000 / 1e3
            while t <= now_s:
                rule.eval(ds, t)
                t += interval_ms / 1e3
            for st in rule._active.values():
                if st["state"] == STATE_FIRING or rule.for_s == 0:
                    out.append(st["labels"])
    return out


def main(argv=None):
    p = argparse.ArgumentParser(prog="vmalert-tool")
    sub = p.add_subparsers(dest="cmd", required=True)
    ut = sub.add_parser("unittest")
    ut.add_argument("--files", action="append", required=True)
    args = p.parse_args(argv if argv is not None else sys.argv[1:])
    all_ok = True
    for f in args.files:
        failures = run_test_file(f)
        if failures:
            all_ok = False
            for msg in failures:
                logger.errorf("FAILED: %s", msg)
        else:
            logger.infof("%s: OK", f)
    sys.exit(0 if all_ok else 1)


if __name__ == "__main__":
    main()
