"""vmsingle: the single-binary server (reference app/victoria-metrics/
main.go:53-125) — storage + query engine + HTTP API in one process.

Flags follow the reference's conventions (-storageDataPath,
-httpListenAddr, -retentionPeriod, -dedup.minScrapeInterval); every flag is
also settable via env var VM_<FLAGNAME> (lib/envflag analog).

Run: python -m victoriametrics_tpu.apps.vmsingle -storageDataPath=/tmp/vm
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from ..utils import logger


def parse_flags(argv=None):
    p = argparse.ArgumentParser(prog="vmsingle", prefix_chars="-")
    p.add_argument("-storageDataPath", default="victoria-metrics-data")
    p.add_argument("-httpListenAddr", default=":8428")
    p.add_argument("-retentionPeriod", default="13m",
                   help="duration: 30d, 13m(onths) etc")
    p.add_argument("-dedup.minScrapeInterval", dest="dedup_interval",
                   default="0s")
    p.add_argument("-storage.maxHourlySeries", dest="max_hourly_series",
                   type=int, default=0)
    p.add_argument("-storage.maxDailySeries", dest="max_daily_series",
                   type=int, default=0)
    p.add_argument("-search.maxUniqueTimeseries", dest="max_series",
                   type=int, default=300_000)
    p.add_argument("-search.maxSamplesPerQuery", dest="max_samples_per_query",
                   type=int, default=1_000_000_000)
    p.add_argument("-search.maxMemoryPerQuery", dest="max_memory_per_query",
                   type=int, default=0)
    p.add_argument("-search.maxQueryDuration", dest="max_query_duration",
                   default="30s")
    p.add_argument("-search.maxStalenessInterval", dest="lookback",
                   default="5m")
    p.add_argument("-search.tpuBackend", dest="tpu", action="store_true",
                   help="route supported rollups to the TPU")
    p.add_argument("-graphiteListenAddr", dest="graphite_addr", default="")
    p.add_argument("-influxListenAddr", dest="influx_addr", default="")
    p.add_argument("-opentsdbListenAddr", dest="opentsdb_addr", default="")
    p.add_argument("-relabelConfig", dest="relabel_config", default="",
                   help="path to global relabeling rules YAML")
    p.add_argument("-streamAggr.config", dest="streamaggr_config", default="",
                   help="path to stream aggregation config YAML")
    p.add_argument("-streamAggr.keepInput", dest="streamaggr_keep_input",
                   action="store_true")
    p.add_argument("-maxLabelsPerTimeseries", type=int, default=40)
    p.add_argument("-maxLabelValueLen", type=int, default=4096)
    p.add_argument("-maxIngestionRate", dest="max_ingestion_rate",
                   type=int, default=0,
                   help="rows/s ingest ceiling, 0 = unlimited "
                        "(lib/ratelimiter analog: bursts within ~1s are "
                        "smoothed by blocking; sustained overload gets "
                        "429 + Retry-After)")
    p.add_argument("-maxTenantIngestionRate",
                   dest="max_tenant_ingestion_rate", type=int, default=0,
                   help="per-tenant rows/s ingest ceiling, 0 = unlimited")
    p.add_argument("-selfScrapeInterval", dest="self_scrape_interval",
                   default="",
                   help="scrape own /metrics into storage every "
                        "interval (15s when set to 1); empty/0 = off")
    p.add_argument("-pushmetrics.url", dest="pushmetrics_urls",
                   action="append", default=[])
    p.add_argument("-pushmetrics.interval", dest="pushmetrics_interval",
                   default="10s")
    p.add_argument("-pushmetrics.extraLabel", dest="pushmetrics_extra",
                   default="")
    p.add_argument("-rule", action="append", default=[],
                   help="vmalert-format rule file evaluated SERVER-SIDE "
                        "through the materialized-stream engine (rules "
                        "sharing an expression share one fetch+rollup "
                        "per interval); repeatable")
    p.add_argument("-evaluationInterval", dest="eval_interval",
                   default="1m")
    p.add_argument("-loggerLevel", default="INFO")
    p.add_argument("-tls", action="store_true")
    p.add_argument("-tlsCertFile", default="")
    p.add_argument("-tlsKeyFile", default="")
    args, _ = p.parse_known_args(argv)
    # env overrides: VM_STORAGEDATAPATH etc (envflag analog)
    for name in vars(args):
        env = os.environ.get("VM_" + name.upper().replace(".", "_"))
        if env is not None:
            cur = getattr(args, name)
            if isinstance(cur, bool):
                setattr(args, name, env not in ("0", "false", ""))
            elif isinstance(cur, list):
                setattr(args, name, [x for x in env.split(",") if x])
            else:
                setattr(args, name, type(cur)(env))
    return args


def _dur_ms(s: str, months_ok=False) -> int:
    from ..query.metricsql.parser import parse_duration_ms
    s = s.strip()
    if months_ok and s.endswith("m") and s[:-1].isdigit():
        # retentionPeriod bare "13m" means months per reference semantics
        return int(float(s[:-1]) * 31 * 86_400_000)
    ms, step_based = parse_duration_ms(s)
    return int(ms)


def _attach_tpu_engine(api, enabled: bool):
    """-search.tpuBackend startup: probe the accelerator with a hard
    deadline BEFORE any in-process jax init (a hung TPU plugin must degrade
    the server to the host path, not wedge startup). The probe + engine
    build + kernel warmup all run on a daemon thread: the HTTP listener
    comes up immediately serving the host path, and `api.tpu` is attached
    the moment the device is proven healthy (a hung plugin therefore costs
    the server NOTHING — queries just keep the host path)."""
    if not enabled:
        return
    import threading

    from ..utils.tpu_probe import probe_backend

    def _provision():
        timeout = float(os.environ.get("VM_TPU_PROBE_TIMEOUT_S", "600"))
        res = probe_backend(timeout)
        if res.error is not None:
            logger.errorf("tpu backend requested but unavailable (%s); "
                          "serving on the host path", res.error)
            if res.stack:
                logger.errorf("hung probe's last stack:\n%s", res.stack)
            return
        logger.infof("accelerator probe: %d %s device(s)", res.n,
                     res.platform)
        from ..query.tpu_engine import (TPUEngine, auto_mesh,
                                        is_tpu_platform, warmup)
        if not is_tpu_platform(res.platform):
            # Pin jax to the probed backend (the axon TPU plugin overrides
            # JAX_PLATFORMS at import, so a hung plugin could still wedge
            # the in-process init the probe just rejected), and enable
            # x64: CPU-XLA f64 tiles silently truncate to f32 without it.
            # Must be set before the engine's first jax trace.
            os.environ.setdefault("JAX_ENABLE_X64", "1")
            import jax
            jax.config.update("jax_platforms", res.platform)
            jax.config.update("jax_enable_x64", True)
        engine = TPUEngine(mesh=auto_mesh())
        # pre-compile the hot kernels BEFORE exposing the engine (also
        # seeds the persistent compilation cache, so restarts stay warm)
        warmup(engine)
        api.tpu = engine
        logger.infof("tpu engine attached (%s tiles)",
                     getattr(engine, "value_dtype", "?"))

    threading.Thread(target=_provision, daemon=True,
                     name="tpu-provision").start()


def build(args):
    from ..httpapi.prometheus_api import PrometheusAPI
    from ..httpapi.server import HTTPServer
    from ..storage.storage import Storage

    retention = _dur_ms(args.retentionPeriod, months_ok=True)
    dedup = _dur_ms(args.dedup_interval) if args.dedup_interval != "0s" else 0
    storage = Storage(args.storageDataPath, retention_ms=retention,
                      dedup_interval_ms=dedup,
                      max_hourly_series=args.max_hourly_series,
                      max_daily_series=args.max_daily_series)
    relabel = None
    if args.relabel_config:
        from ..ingest.relabel import parse_relabel_configs
        relabel = parse_relabel_configs(open(args.relabel_config).read())
    stream_aggr = None
    if args.streamaggr_config:
        from ..ingest.streamaggr import load_from_text
        stream_aggr = load_from_text(open(args.streamaggr_config).read(),
                                     lambda rows: storage.add_rows(rows))
        stream_aggr.start()
    host, _, port = args.httpListenAddr.rpartition(":")
    srv = HTTPServer(host or "0.0.0.0", int(port),
                     tls_cert_file=args.tlsCertFile if args.tls else "",
                     tls_key_file=args.tlsKeyFile if args.tls else "")
    from ..ingest.serieslimits import SeriesLimits
    limits = SeriesLimits(max_labels_per_series=args.maxLabelsPerTimeseries,
                          max_label_value_len=args.maxLabelValueLen)
    rate_limiter = None
    if args.max_ingestion_rate > 0 or args.max_tenant_ingestion_rate > 0:
        from ..ingest.ratelimiter import TenantRateLimiters
        rate_limiter = TenantRateLimiters(
            global_limit=args.max_ingestion_rate,
            per_tenant_limit=args.max_tenant_ingestion_rate)
    api = PrometheusAPI(storage, None,
                        lookback_delta=_dur_ms(args.lookback),
                        max_series=args.max_series,
                        relabel_configs=relabel, stream_aggr=stream_aggr,
                        stream_aggr_keep_input=args.streamaggr_keep_input,
                        series_limits=limits,
                        max_samples_per_query=args.max_samples_per_query,
                        max_memory_per_query=args.max_memory_per_query,
                        max_query_duration_ms=_dur_ms(
                            args.max_query_duration),
                        rate_limiter=rate_limiter)
    _attach_tpu_engine(api, args.tpu)
    api.flags_map = {k: v for k, v in vars(args).items()}
    api.register(srv)
    from ..utils import profiler
    profiler.ensure_started()
    # self-monitoring plane: own registry -> own storage as real series;
    # the SLO engine's burn-rate evals ride each scrape tick
    from ..utils import selfscrape
    api.selfscraper = selfscrape.maybe_start(
        storage.add_rows, "vmsingle", int(port),
        flag_value=args.self_scrape_interval, extra=api.app_metrics,
        on_tick=lambda now_ms: api.init_sloplane().maybe_eval(now_ms))
    from ..httpapi.graphite_api import GraphiteAPI
    GraphiteAPI(storage).register(srv)
    if args.pushmetrics_urls:
        from ..utils.pushmetrics import MetricsPusher
        api.pusher = MetricsPusher(
            args.pushmetrics_urls,
            lambda: api.h_metrics(None).body.decode(),
            interval_s=_dur_ms(args.pushmetrics_interval) / 1e3,
            extra_labels=args.pushmetrics_extra)
        api.pusher.start()
    api.rule_groups = []
    if getattr(args, "rule", None):
        # server-side recording/alerting rules (the reference evaluates
        # recording rules in vmalert against vmselect; here they run
        # in-process through the shared materialized-stream engine, so
        # rules and watch subscribers amortize one evaluation per
        # distinct expression)
        import yaml

        from ..httpapi.server import Response as _Resp
        from . import vmalert as vmalert_mod
        ds = vmalert_mod.EngineDatasource(api)
        rw = vmalert_mod.LocalWriter(api)
        for path in args.rule:
            cfg = yaml.safe_load(open(path).read()) or {}
            for g in cfg.get("groups", []):
                api.rule_groups.append(vmalert_mod.Group(
                    g, ds, [], rw,
                    vmalert_mod._dur_s(args.eval_interval, 60.0)))
        for g in api.rule_groups:
            g.start()
        srv.route("/api/v1/rules", lambda req: _Resp.json(
            {"status": "success",
             "data": {"groups": [g.api_dict()
                                 for g in api.rule_groups]}}))
        logger.infof("vmsingle: %d server-side rule group(s) armed",
                     len(api.rule_groups))
    api.ingest_servers = []
    for proto, addr in (("graphite", args.graphite_addr),
                        ("influx", args.influx_addr),
                        ("opentsdb", args.opentsdb_addr)):
        if addr:
            from ..ingest.ingestserver import IngestServer
            h, _, p_ = addr.rpartition(":")
            isrv = IngestServer(proto, h or "0.0.0.0", int(p_),
                                api._add_rows)
            isrv.start()
            api.ingest_servers.append(isrv)
    return storage, srv, api


def main(argv=None):
    import threading
    import faulthandler
    faulthandler.register(signal.SIGUSR1)

    args = parse_flags(argv)
    logger.set_level(args.loggerLevel)
    storage, srv, _api = build(args)
    logger.infof("vmsingle started: data=%s listen=%s",
                 args.storageDataPath, args.httpListenAddr)

    # serve from a daemon thread; the main thread blocks on the stop event.
    # Calling HTTPServer.shutdown() from inside a signal handler interrupting
    # serve_forever deadlocks (shutdown() joins the loop it interrupted).
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    def _reload(*_):
        # SIGHUP hot-reload of -relabelConfig and -streamAggr.config
        try:
            if args.relabel_config:
                from ..ingest.relabel import parse_relabel_configs
                _api.relabel = parse_relabel_configs(
                    open(args.relabel_config).read())
            if args.streamaggr_config:
                from ..ingest.streamaggr import load_from_text
                new = load_from_text(
                    open(args.streamaggr_config).read(),
                    lambda rows: storage.add_rows(rows))
                old = _api.stream_aggr
                new.start()
                _api.stream_aggr = new
                if old is not None:
                    old.stop()
            logger.infof("vmsingle: config reloaded")
        except Exception as e:
            logger.errorf("vmsingle: reload failed, keeping old config: %s",
                          e)
    signal.signal(signal.SIGHUP, _reload)
    srv.start()
    try:
        while not stop.wait(1.0):
            pass
    finally:
        logger.infof("vmsingle: shutting down")
        for g in getattr(_api, "rule_groups", []):
            g.stop()
        srv.stop()
        for isrv in getattr(_api, "ingest_servers", []):
            isrv.stop()
        if getattr(_api, "pusher", None) is not None:
            _api.pusher.stop()
        if getattr(_api, "selfscraper", None) is not None:
            # before storage.close(): a late scrape must not write into
            # a closed storage
            _api.selfscraper.stop()
        if _api.stream_aggr is not None:
            # final window flush BEFORE storage closes (streamaggr MustStop
            # ordering): dropping the open window on every restart would
            # lose data, and a late flusher tick must not write into a
            # closed storage
            _api.stream_aggr.stop(final_flush=True)
        storage.close()
        logger.infof("vmsingle: shutdown complete")


if __name__ == "__main__":
    main()
