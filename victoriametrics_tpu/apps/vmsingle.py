"""vmsingle: the single-binary server (reference app/victoria-metrics/
main.go:53-125) — storage + query engine + HTTP API in one process.

Flags follow the reference's conventions (-storageDataPath,
-httpListenAddr, -retentionPeriod, -dedup.minScrapeInterval); every flag is
also settable via env var VM_<FLAGNAME> (lib/envflag analog).

Run: python -m victoriametrics_tpu.apps.vmsingle -storageDataPath=/tmp/vm
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from ..utils import logger


def parse_flags(argv=None):
    p = argparse.ArgumentParser(prog="vmsingle", prefix_chars="-")
    p.add_argument("-storageDataPath", default="victoria-metrics-data")
    p.add_argument("-httpListenAddr", default=":8428")
    p.add_argument("-retentionPeriod", default="13m",
                   help="duration: 30d, 13m(onths) etc")
    p.add_argument("-dedup.minScrapeInterval", dest="dedup_interval",
                   default="0s")
    p.add_argument("-storage.maxHourlySeries", dest="max_hourly_series",
                   type=int, default=0)
    p.add_argument("-storage.maxDailySeries", dest="max_daily_series",
                   type=int, default=0)
    p.add_argument("-search.maxUniqueTimeseries", dest="max_series",
                   type=int, default=300_000)
    p.add_argument("-search.maxSamplesPerQuery", dest="max_samples_per_query",
                   type=int, default=1_000_000_000)
    p.add_argument("-search.maxMemoryPerQuery", dest="max_memory_per_query",
                   type=int, default=0)
    p.add_argument("-search.maxQueryDuration", dest="max_query_duration",
                   default="30s")
    p.add_argument("-search.maxStalenessInterval", dest="lookback",
                   default="5m")
    p.add_argument("-search.tpuBackend", dest="tpu", action="store_true",
                   help="route supported rollups to the TPU")
    p.add_argument("-graphiteListenAddr", dest="graphite_addr", default="")
    p.add_argument("-influxListenAddr", dest="influx_addr", default="")
    p.add_argument("-opentsdbListenAddr", dest="opentsdb_addr", default="")
    p.add_argument("-relabelConfig", dest="relabel_config", default="",
                   help="path to global relabeling rules YAML")
    p.add_argument("-streamAggr.config", dest="streamaggr_config", default="",
                   help="path to stream aggregation config YAML")
    p.add_argument("-streamAggr.keepInput", dest="streamaggr_keep_input",
                   action="store_true")
    p.add_argument("-maxLabelsPerTimeseries", type=int, default=40)
    p.add_argument("-maxLabelValueLen", type=int, default=4096)
    p.add_argument("-pushmetrics.url", dest="pushmetrics_urls",
                   action="append", default=[])
    p.add_argument("-pushmetrics.interval", dest="pushmetrics_interval",
                   default="10s")
    p.add_argument("-pushmetrics.extraLabel", dest="pushmetrics_extra",
                   default="")
    p.add_argument("-loggerLevel", default="INFO")
    p.add_argument("-tls", action="store_true")
    p.add_argument("-tlsCertFile", default="")
    p.add_argument("-tlsKeyFile", default="")
    args, _ = p.parse_known_args(argv)
    # env overrides: VM_STORAGEDATAPATH etc (envflag analog)
    for name in vars(args):
        env = os.environ.get("VM_" + name.upper().replace(".", "_"))
        if env is not None:
            cur = getattr(args, name)
            if isinstance(cur, bool):
                setattr(args, name, env not in ("0", "false", ""))
            elif isinstance(cur, list):
                setattr(args, name, [x for x in env.split(",") if x])
            else:
                setattr(args, name, type(cur)(env))
    return args


def _dur_ms(s: str, months_ok=False) -> int:
    from ..query.metricsql.parser import parse_duration_ms
    s = s.strip()
    if months_ok and s.endswith("m") and s[:-1].isdigit():
        # retentionPeriod bare "13m" means months per reference semantics
        return int(float(s[:-1]) * 31 * 86_400_000)
    ms, step_based = parse_duration_ms(s)
    return int(ms)


def _make_tpu_engine(enabled: bool):
    """-search.tpuBackend startup: probe the accelerator with a hard
    deadline BEFORE any in-process jax init (a hung TPU plugin must degrade
    the server to the host path, not wedge startup), then build the engine
    with its auto dtype (f32 tiles on real TPU, f64 elsewhere)."""
    if not enabled:
        return None
    from ..utils.tpu_probe import probe_backend
    timeout = float(os.environ.get("VM_TPU_PROBE_TIMEOUT_S", "90"))
    platform, n, err = probe_backend(timeout)
    if err is not None:
        logger.errorf("tpu backend requested but unavailable (%s); "
                      "serving on the host path", err)
        return None
    logger.infof("accelerator probe: %d %s device(s)", n, platform)
    from ..query.tpu_engine import TPUEngine, auto_mesh
    return TPUEngine(mesh=auto_mesh())


def build(args):
    from ..httpapi.prometheus_api import PrometheusAPI
    from ..httpapi.server import HTTPServer
    from ..storage.storage import Storage

    retention = _dur_ms(args.retentionPeriod, months_ok=True)
    dedup = _dur_ms(args.dedup_interval) if args.dedup_interval != "0s" else 0
    storage = Storage(args.storageDataPath, retention_ms=retention,
                      dedup_interval_ms=dedup,
                      max_hourly_series=args.max_hourly_series,
                      max_daily_series=args.max_daily_series)
    tpu_engine = _make_tpu_engine(args.tpu)
    relabel = None
    if args.relabel_config:
        from ..ingest.relabel import parse_relabel_configs
        relabel = parse_relabel_configs(open(args.relabel_config).read())
    stream_aggr = None
    if args.streamaggr_config:
        from ..ingest.streamaggr import load_from_text
        stream_aggr = load_from_text(open(args.streamaggr_config).read(),
                                     lambda rows: storage.add_rows(rows))
        stream_aggr.start()
    host, _, port = args.httpListenAddr.rpartition(":")
    srv = HTTPServer(host or "0.0.0.0", int(port),
                     tls_cert_file=args.tlsCertFile if args.tls else "",
                     tls_key_file=args.tlsKeyFile if args.tls else "")
    from ..ingest.serieslimits import SeriesLimits
    limits = SeriesLimits(max_labels_per_series=args.maxLabelsPerTimeseries,
                          max_label_value_len=args.maxLabelValueLen)
    api = PrometheusAPI(storage, tpu_engine,
                        lookback_delta=_dur_ms(args.lookback),
                        max_series=args.max_series,
                        relabel_configs=relabel, stream_aggr=stream_aggr,
                        stream_aggr_keep_input=args.streamaggr_keep_input,
                        series_limits=limits,
                        max_samples_per_query=args.max_samples_per_query,
                        max_memory_per_query=args.max_memory_per_query,
                        max_query_duration_ms=_dur_ms(
                            args.max_query_duration))
    api.flags_map = {k: v for k, v in vars(args).items()}
    api.register(srv)
    from ..httpapi.graphite_api import GraphiteAPI
    GraphiteAPI(storage).register(srv)
    if args.pushmetrics_urls:
        from ..utils.pushmetrics import MetricsPusher
        api.pusher = MetricsPusher(
            args.pushmetrics_urls,
            lambda: api.h_metrics(None).body.decode(),
            interval_s=_dur_ms(args.pushmetrics_interval) / 1e3,
            extra_labels=args.pushmetrics_extra)
        api.pusher.start()
    api.ingest_servers = []
    for proto, addr in (("graphite", args.graphite_addr),
                        ("influx", args.influx_addr),
                        ("opentsdb", args.opentsdb_addr)):
        if addr:
            from ..ingest.ingestserver import IngestServer
            h, _, p_ = addr.rpartition(":")
            isrv = IngestServer(proto, h or "0.0.0.0", int(p_),
                                api._add_rows)
            isrv.start()
            api.ingest_servers.append(isrv)
    return storage, srv, api


def main(argv=None):
    import threading
    import faulthandler
    faulthandler.register(signal.SIGUSR1)

    args = parse_flags(argv)
    logger.set_level(args.loggerLevel)
    storage, srv, _api = build(args)
    logger.infof("vmsingle started: data=%s listen=%s",
                 args.storageDataPath, args.httpListenAddr)

    # serve from a daemon thread; the main thread blocks on the stop event.
    # Calling HTTPServer.shutdown() from inside a signal handler interrupting
    # serve_forever deadlocks (shutdown() joins the loop it interrupted).
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    def _reload(*_):
        # SIGHUP hot-reload of -relabelConfig and -streamAggr.config
        try:
            if args.relabel_config:
                from ..ingest.relabel import parse_relabel_configs
                _api.relabel = parse_relabel_configs(
                    open(args.relabel_config).read())
            if args.streamaggr_config:
                from ..ingest.streamaggr import load_from_text
                new = load_from_text(
                    open(args.streamaggr_config).read(),
                    lambda rows: storage.add_rows(rows))
                old = _api.stream_aggr
                new.start()
                _api.stream_aggr = new
                if old is not None:
                    old.stop()
            logger.infof("vmsingle: config reloaded")
        except Exception as e:
            logger.errorf("vmsingle: reload failed, keeping old config: %s",
                          e)
    signal.signal(signal.SIGHUP, _reload)
    srv.start()
    try:
        while not stop.wait(1.0):
            pass
    finally:
        logger.infof("vmsingle: shutting down")
        srv.stop()
        for isrv in getattr(_api, "ingest_servers", []):
            isrv.stop()
        if getattr(_api, "pusher", None) is not None:
            _api.pusher.stop()
        if _api.stream_aggr is not None:
            # final window flush BEFORE storage closes (streamaggr MustStop
            # ordering): dropping the open window on every restart would
            # lose data, and a late flusher tick must not write into a
            # closed storage
            _api.stream_aggr.stop(final_flush=True)
        storage.close()
        logger.infof("vmsingle: shutdown complete")


if __name__ == "__main__":
    main()
