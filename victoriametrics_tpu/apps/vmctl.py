"""vmctl: data migration CLI (reference app/vmctl): modes

  vm-native   copy series between instances via /api/v1/export + import
  prometheus  import a Prometheus text/OpenMetrics dump file
  influx      import an InfluxDB line-protocol file
  opentsdb    import an OpenTSDB telnet-format file

with interval chunking and selector filtering.
"""

from __future__ import annotations

import argparse
import sys
import urllib.parse
import urllib.request

from ..utils import logger


def _post(url: str, data: bytes, timeout=120) -> None:
    req = urllib.request.Request(url, data=data, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        r.read()


def vm_native(src: str, dst: str, match: str, start: str = "", end: str = "",
              chunk_rows: int = 10_000) -> int:
    """Stream JSONL export from src into dst."""
    params = {"match[]": match}
    if start:
        params["start"] = start
    if end:
        params["end"] = end
    url = src.rstrip("/") + "/api/v1/export?" + urllib.parse.urlencode(params)
    total = 0
    buf: list[bytes] = []
    with urllib.request.urlopen(url, timeout=300) as r:
        for line in r:
            line = line.strip()
            if not line:
                continue
            buf.append(line)
            total += 1
            if len(buf) >= chunk_rows:
                _post(dst.rstrip("/") + "/api/v1/import", b"\n".join(buf))
                buf = []
    if buf:
        _post(dst.rstrip("/") + "/api/v1/import", b"\n".join(buf))
    logger.infof("vmctl vm-native: migrated %d series chunks", total)
    return total


def remote_read(src: str, dst: str, match: str, start_ms: int,
                end_ms: int, chunk_rows: int = 50_000) -> int:
    """Migrate from any Prometheus remote_read endpoint (prometheus, mimir,
    thanos — the reference vmctl's remote-read mode) into dst."""
    import json as _json
    import re as _re

    from ..ingest import remote_write as rw
    from ..ingest.parsers import series_to_jsonl
    matchers = []
    m = _re.match(r"\{(.*)\}$", match.strip()) if match.strip().startswith("{") else None
    body_expr = m.group(1) if m else ""
    if body_expr or m:
        for mm in _re.finditer(
                r'([A-Za-z_][\w]*)\s*(=~|!~|!=|=)\s*"((?:[^"\\]|\\.)*)"',
                body_expr):
            matchers.append((mm.group(2), mm.group(1),
                             mm.group(3).replace('\\"', '"')))
        if not matchers:
            raise ValueError(f"cannot parse matchers in {match!r}")
    else:
        matchers.append(("=", "__name__", match.strip()))
    body = rw.build_read_request(start_ms, end_ms, matchers)
    req = urllib.request.Request(
        src.rstrip("/") + "/api/v1/read", data=body, method="POST",
        headers={"Content-Encoding": "snappy",
                 "Content-Type": "application/x-protobuf",
                 "X-Prometheus-Remote-Read-Version": "0.1.0"})
    with urllib.request.urlopen(req, timeout=300) as r:
        resp = r.read()
    total = 0
    _flushed = {"n": 0}
    buf: list[bytes] = []
    for labels, samples in rw.parse_read_response(resp):
        if not samples:
            continue
        d = {k.decode() if isinstance(k, bytes) else k:
             v.decode() if isinstance(v, bytes) else v
             for k, v in labels}
        buf.append(series_to_jsonl(d, [t for t, _ in samples],
                                   [v for _, v in samples]).encode())
        total += len(samples)
        if total - _flushed["n"] >= max(chunk_rows, 1):
            _post(dst.rstrip("/") + "/api/v1/import", b"\n".join(buf))
            _flushed["n"] = total
            buf = []
    if buf:
        _post(dst.rstrip("/") + "/api/v1/import", b"\n".join(buf))
    logger.infof("vmctl remote-read: migrated %d samples", total)
    return total


def import_file(path: str, dst: str, fmt: str, chunk_lines: int = 50_000) -> int:
    endpoint = {"prometheus": "/api/v1/import/prometheus",
                "influx": "/write",
                "opentsdb": None}[fmt]
    total = 0
    if fmt == "opentsdb":
        # convert telnet puts -> prometheus text
        from ..ingest.parsers import parse_opentsdb_telnet
        lines = []
        for row in parse_opentsdb_telnet(open(path).read()):
            labels = dict(row.labels)
            name = labels.pop("__name__")
            lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
            lines.append(f"{name}{{{lab}}} {row.value} {row.timestamp}")
            total += 1
        _post(dst.rstrip("/") + "/api/v1/import/prometheus",
              "\n".join(lines).encode())
        return total
    buf: list[str] = []
    for line in open(path):
        if not line.strip():
            continue
        buf.append(line.rstrip("\n"))
        total += 1
        if len(buf) >= chunk_lines:
            _post(dst.rstrip("/") + endpoint, "\n".join(buf).encode())
            buf = []
    if buf:
        _post(dst.rstrip("/") + endpoint, "\n".join(buf).encode())
    logger.infof("vmctl %s: imported %d lines", fmt, total)
    return total


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    p = argparse.ArgumentParser(prog="vmctl")
    sub = p.add_subparsers(dest="mode", required=True)

    pn = sub.add_parser("vm-native")
    pn.add_argument("--vm-native-src-addr", required=True)
    pn.add_argument("--vm-native-dst-addr", required=True)
    pn.add_argument("--vm-native-filter-match", default='{__name__=~".*"}')
    pn.add_argument("--vm-native-filter-time-start", default="")
    pn.add_argument("--vm-native-filter-time-end", default="")

    for fmt in ("prometheus", "influx", "opentsdb"):
        pf = sub.add_parser(fmt)
        pf.add_argument("--file", required=True)
        pf.add_argument("--dst-addr", required=True)

    args = p.parse_args(argv)
    if args.mode == "vm-native":
        vm_native(args.vm_native_src_addr, args.vm_native_dst_addr,
                  args.vm_native_filter_match,
                  args.vm_native_filter_time_start,
                  args.vm_native_filter_time_end)
    else:
        import_file(args.file, args.dst_addr, args.mode)


if __name__ == "__main__":
    main()
