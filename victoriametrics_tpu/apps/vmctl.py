"""vmctl: data migration CLI (reference app/vmctl): modes

  vm-native   copy series between instances via /api/v1/export + import
  prometheus  import a Prometheus text/OpenMetrics dump file
  influx      import an InfluxDB line-protocol file
  opentsdb    import an OpenTSDB telnet-format file

with interval chunking and selector filtering.
"""

from __future__ import annotations

import argparse
import sys
import urllib.parse
import urllib.request

from ..utils import logger


def _post(url: str, data: bytes, timeout=120) -> None:
    req = urllib.request.Request(url, data=data, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        r.read()


def vm_native(src: str, dst: str, match: str, start: str = "", end: str = "",
              chunk_rows: int = 10_000) -> int:
    """Stream JSONL export from src into dst."""
    params = {"match[]": match}
    if start:
        params["start"] = start
    if end:
        params["end"] = end
    url = src.rstrip("/") + "/api/v1/export?" + urllib.parse.urlencode(params)
    total = 0
    buf: list[bytes] = []
    with urllib.request.urlopen(url, timeout=300) as r:
        for line in r:
            line = line.strip()
            if not line:
                continue
            buf.append(line)
            total += 1
            if len(buf) >= chunk_rows:
                _post(dst.rstrip("/") + "/api/v1/import", b"\n".join(buf))
                buf = []
    if buf:
        _post(dst.rstrip("/") + "/api/v1/import", b"\n".join(buf))
    logger.infof("vmctl vm-native: migrated %d series chunks", total)
    return total


def remote_read(src: str, dst: str, match: str, start_ms: int,
                end_ms: int, chunk_rows: int = 50_000) -> int:
    """Migrate from any Prometheus remote_read endpoint (prometheus, mimir,
    thanos — the reference vmctl's remote-read mode) into dst."""
    import json as _json
    import re as _re

    from ..ingest import remote_write as rw
    from ..ingest.parsers import series_to_jsonl
    matchers = []
    m = _re.match(r"\{(.*)\}$", match.strip()) if match.strip().startswith("{") else None
    body_expr = m.group(1) if m else ""
    if body_expr or m:
        for mm in _re.finditer(
                r'([A-Za-z_][\w]*)\s*(=~|!~|!=|=)\s*"((?:[^"\\]|\\.)*)"',
                body_expr):
            matchers.append((mm.group(2), mm.group(1),
                             mm.group(3).replace('\\"', '"')))
        if not matchers:
            raise ValueError(f"cannot parse matchers in {match!r}")
    else:
        matchers.append(("=", "__name__", match.strip()))
    body = rw.build_read_request(start_ms, end_ms, matchers)
    req = urllib.request.Request(
        src.rstrip("/") + "/api/v1/read", data=body, method="POST",
        headers={"Content-Encoding": "snappy",
                 "Content-Type": "application/x-protobuf",
                 "X-Prometheus-Remote-Read-Version": "0.1.0"})
    with urllib.request.urlopen(req, timeout=300) as r:
        resp = r.read()
    total = 0
    _flushed = {"n": 0}
    buf: list[bytes] = []
    for labels, samples in rw.parse_read_response(resp):
        if not samples:
            continue
        d = {k.decode() if isinstance(k, bytes) else k:
             v.decode() if isinstance(v, bytes) else v
             for k, v in labels}
        buf.append(series_to_jsonl(d, [t for t, _ in samples],
                                   [v for _, v in samples]).encode())
        total += len(samples)
        if total - _flushed["n"] >= max(chunk_rows, 1):
            _post(dst.rstrip("/") + "/api/v1/import", b"\n".join(buf))
            _flushed["n"] = total
            buf = []
    if buf:
        _post(dst.rstrip("/") + "/api/v1/import", b"\n".join(buf))
    logger.infof("vmctl remote-read: migrated %d samples", total)
    return total


def import_file(path: str, dst: str, fmt: str, chunk_lines: int = 50_000) -> int:
    endpoint = {"prometheus": "/api/v1/import/prometheus",
                "influx": "/write",
                "opentsdb": None}[fmt]
    total = 0
    if fmt == "opentsdb":
        # convert telnet puts -> prometheus text
        from ..ingest.parsers import parse_opentsdb_telnet
        lines = []
        for row in parse_opentsdb_telnet(open(path).read()):
            labels = dict(row.labels)
            name = labels.pop("__name__")
            lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
            lines.append(f"{name}{{{lab}}} {row.value} {row.timestamp}")
            total += 1
        _post(dst.rstrip("/") + "/api/v1/import/prometheus",
              "\n".join(lines).encode())
        return total
    buf: list[str] = []
    for line in open(path):
        if not line.strip():
            continue
        buf.append(line.rstrip("\n"))
        total += 1
        if len(buf) >= chunk_lines:
            _post(dst.rstrip("/") + endpoint, "\n".join(buf).encode())
            buf = []
    if buf:
        _post(dst.rstrip("/") + endpoint, "\n".join(buf).encode())
    logger.infof("vmctl %s: imported %d lines", fmt, total)
    return total


def _esc_label(v: str) -> str:
    """Prometheus text-format label value escaping."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def prometheus_tsdb(path: str, dst: str, chunk_lines: int = 50_000) -> int:
    """Migrate a Prometheus TSDB data dir (or one block dir) into dst —
    the reference vmctl `prometheus` snapshot mode (app/vmctl/main.go:259
    via prometheus/tsdb). Reads the binary block format directly
    (utils/promtsdb: index + XOR chunks) and streams prometheus text."""
    import os

    from ..utils.promtsdb import read_block
    if os.path.exists(os.path.join(path, "index")):
        blocks = [path]
    else:
        blocks = sorted(
            os.path.join(path, d) for d in os.listdir(path)
            if os.path.isdir(os.path.join(path, d)) and
            os.path.exists(os.path.join(path, d, "index")))
    if not blocks:
        raise SystemExit(f"vmctl prometheus-tsdb: no blocks under {path}")
    total = 0
    skipped = [0]
    buf: list[str] = []

    def on_unsupported(labels, err):
        skipped[0] += 1
        logger.errorf("vmctl prometheus-tsdb: skipping series %s: %s",
                      labels.get("__name__", "?"), err)
    for bdir in blocks:
        logger.infof("vmctl prometheus-tsdb: reading block %s", bdir)
        from ..query.format_value import fmt_value
        for labels, ts, vals in read_block(bdir,
                                           on_unsupported=on_unsupported):
            name = labels.get("__name__", "")
            if not name:
                continue
            rest = ",".join(f'{k}="{_esc_label(v)}"'
                            for k, v in sorted(labels.items())
                            if k != "__name__")
            head = f"{name}{{{rest}}}" if rest else name
            for t, v in zip(ts.tolist(), vals.tolist()):
                buf.append(f"{head} {fmt_value(v)} {t}")
                total += 1
                if len(buf) >= chunk_lines:
                    _post(dst.rstrip("/") + "/api/v1/import/prometheus",
                          "\n".join(buf).encode())
                    buf = []
    if buf:
        _post(dst.rstrip("/") + "/api/v1/import/prometheus",
              "\n".join(buf).encode())
    logger.infof("vmctl prometheus-tsdb: migrated %d samples from %d "
                 "block(s); %d series skipped (unsupported chunk "
                 "encodings)", total, len(blocks), skipped[0])
    return total


def verify_block_cmd(path: str) -> int:
    """vmctl verify-block (app/vmctl/main.go:514): walk one TSDB block's
    structures + CRCs, print the report, exit nonzero on problems."""
    import json as _json

    from ..utils.promtsdb import verify_block
    rep = verify_block(path)
    try:
        print(_json.dumps(rep, indent=1))
    except BrokenPipeError:  # e.g. piped into head
        pass
    return 0 if rep["ok"] else 1


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    p = argparse.ArgumentParser(prog="vmctl")
    sub = p.add_subparsers(dest="mode", required=True)

    pn = sub.add_parser("vm-native")
    pn.add_argument("--vm-native-src-addr", required=True)
    pn.add_argument("--vm-native-dst-addr", required=True)
    pn.add_argument("--vm-native-filter-match", default='{__name__=~".*"}')
    pn.add_argument("--vm-native-filter-time-start", default="")
    pn.add_argument("--vm-native-filter-time-end", default="")

    for fmt in ("prometheus", "influx", "opentsdb"):
        pf = sub.add_parser(fmt)
        pf.add_argument("--file", required=True)
        pf.add_argument("--dst-addr", required=True)

    pt = sub.add_parser("prometheus-tsdb",
                        help="migrate Prometheus TSDB blocks (binary "
                             "snapshot format)")
    pt.add_argument("--tsdb-path", required=True,
                    help="a data dir of blocks, or one block dir")
    pt.add_argument("--dst-addr", required=True)

    pv = sub.add_parser("verify-block",
                        help="validate one TSDB block's structure + CRCs")
    pv.add_argument("--block-path", required=True)

    args = p.parse_args(argv)
    if args.mode == "vm-native":
        vm_native(args.vm_native_src_addr, args.vm_native_dst_addr,
                  args.vm_native_filter_match,
                  args.vm_native_filter_time_start,
                  args.vm_native_filter_time_end)
    elif args.mode == "prometheus-tsdb":
        prometheus_tsdb(args.tsdb_path, args.dst_addr)
    elif args.mode == "verify-block":
        raise SystemExit(verify_block_cmd(args.block_path))
    else:
        import_file(args.file, args.dst_addr, args.mode)


if __name__ == "__main__":
    main()
