"""vminsert: ingestion router (reference app/vminsert in cluster mode):
accepts every ingest protocol over HTTP and shards rows across vmstorage
nodes by consistent hash with replication + rerouting."""

from __future__ import annotations

import argparse
import os
import signal
import threading

from ..utils import logger


def parse_flags(argv=None):
    p = argparse.ArgumentParser(prog="vminsert")
    p.add_argument("-storageNode", action="append", default=[],
                   help="host:insertPort:selectPort, repeatable")
    p.add_argument("-httpListenAddr", default=":8480")
    p.add_argument("-replicationFactor", type=int, default=1)
    p.add_argument("-rpc.timeout", dest="rpc_timeout", type=float,
                   default=10.0, help="storage-node RPC timeout, seconds "
                   "(-vmstorageDialTimeout analog)")
    p.add_argument("-clusternativeListenAddr", dest="native_addr", default="",
                   help="expose the vminsert RPC API so a higher-level "
                        "vminsert can chain into this one (multilevel)")
    p.add_argument("-loggerLevel", default="INFO")
    p.add_argument("-maxIngestionRate", dest="max_ingestion_rate",
                   type=int, default=0,
                   help="rows/s ingest ceiling, 0 = unlimited")
    p.add_argument("-selfScrapeInterval", dest="self_scrape_interval",
                   default="",
                   help="scrape own /metrics into the cluster every "
                        "interval (15s when set to 1); empty/0 = off")
    args, _ = p.parse_known_args(argv)
    env = os.environ.get("VM_STORAGENODE")
    if env:
        args.storageNode = env.split(",")
    return args


def make_nodes(specs: list[str], timeout: float = 10.0):
    from ..parallel.cluster_api import StorageNodeClient, parse_node_spec
    nodes = []
    for spec in specs:
        # host:insertPort:selectPort (vmstorage) or host:port (a
        # multilevel child's -clusternativeListenAddr)
        host, ip_, sp_ = parse_node_spec(spec)
        nodes.append(StorageNodeClient(host, ip_, sp_, timeout=timeout))
    return nodes


def build(args):
    from ..httpapi.prometheus_api import PrometheusAPI
    from ..httpapi.server import HTTPServer
    from ..parallel.cluster_api import ClusterStorage

    if not args.storageNode:
        raise SystemExit("vminsert: at least one -storageNode is required")
    cluster = ClusterStorage(
        make_nodes(args.storageNode, getattr(args, "rpc_timeout", 10.0)),
        replication_factor=args.replicationFactor)
    hh, _, hp = args.httpListenAddr.rpartition(":")
    srv = HTTPServer(hh or "0.0.0.0", int(hp))
    rate_limiter = None
    if getattr(args, "max_ingestion_rate", 0) > 0:
        from ..ingest.ratelimiter import TenantRateLimiters
        rate_limiter = TenantRateLimiters(
            global_limit=args.max_ingestion_rate)
    api = PrometheusAPI(cluster, rate_limiter=rate_limiter)
    api.register(srv, mode="insert")
    from ..parallel.cluster_api import register_cluster_admin
    register_cluster_admin(srv, cluster)
    # self-monitoring plane: own registry -> cluster write path (no SLO
    # pump here — a vminsert has no select channel to evaluate over)
    from ..utils import selfscrape
    api.selfscraper = selfscrape.maybe_start(
        cluster.add_rows, "vminsert", int(hp),
        flag_value=args.self_scrape_interval, extra=api.app_metrics)
    native_srv = None
    if getattr(args, "native_addr", ""):
        from ..parallel.cluster_api import start_native_server
        from ..parallel.rpc import HELLO_INSERT
        native_srv = start_native_server(args.native_addr, HELLO_INSERT,
                                         cluster,
                                         rate_limiter=rate_limiter)
    return cluster, srv, api, native_srv


def main(argv=None):
    import faulthandler
    faulthandler.register(signal.SIGUSR1)
    args = parse_flags(argv)
    logger.set_level(args.loggerLevel)
    cluster, srv, _api, native_srv = build(args)
    srv.start()
    logger.infof("vminsert started: nodes=%d rf=%d http=%d",
                 len(cluster.nodes), cluster.rf, srv.port)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.wait(1.0):
            pass
    finally:
        srv.stop()
        if getattr(_api, "selfscraper", None) is not None:
            _api.selfscraper.stop()
        if native_srv is not None:
            native_srv.stop()
        cluster.close()
        logger.infof("vminsert: shutdown complete")


if __name__ == "__main__":
    main()
