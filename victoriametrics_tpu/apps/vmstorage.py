"""vmstorage: storage node process (reference app/vmstorage/main.go:114-217):
the Storage engine + vminsert/vmselect RPC servers + maintenance HTTP
(/metrics, /snapshot/*, /internal/force_*)."""

from __future__ import annotations

import argparse
import os
import signal
import threading

from ..utils import logger


def parse_flags(argv=None):
    p = argparse.ArgumentParser(prog="vmstorage")
    p.add_argument("-storageDataPath", default="vmstorage-data")
    p.add_argument("-httpListenAddr", default=":8482")
    p.add_argument("-vminsertAddr", default=":8400")
    p.add_argument("-vmselectAddr", default=":8401")
    p.add_argument("-retentionPeriod", default="13m")
    p.add_argument("-dedup.minScrapeInterval", dest="dedup_interval",
                   default="0s")
    p.add_argument("-selfScrapeInterval", dest="self_scrape_interval",
                   default="",
                   help="scrape own /metrics into local storage every "
                        "interval (15s when set to 1); empty/0 = off")
    p.add_argument("-loggerLevel", default="INFO")
    args, _ = p.parse_known_args(argv)
    for name in vars(args):
        env = os.environ.get("VM_" + name.upper().replace(".", "_"))
        if env is not None:
            setattr(args, name, env)
    return args


def build(args):
    from ..httpapi.server import HTTPServer, Response
    from ..parallel.cluster_api import make_storage_handlers
    from ..parallel.rpc import HELLO_INSERT, HELLO_SELECT, RPCServer
    from ..storage.storage import Storage
    from .vmsingle import _dur_ms

    storage = Storage(args.storageDataPath,
                      retention_ms=_dur_ms(args.retentionPeriod, months_ok=True),
                      dedup_interval_ms=_dur_ms(args.dedup_interval)
                      if args.dedup_interval != "0s" else 0)
    handlers = make_storage_handlers(storage)
    ih, _, ip = args.vminsertAddr.rpartition(":")
    sh, _, sp = args.vmselectAddr.rpartition(":")
    insert_srv = RPCServer(ih or "0.0.0.0", int(ip), HELLO_INSERT, handlers)
    select_srv = RPCServer(sh or "0.0.0.0", int(sp), HELLO_SELECT, handlers)

    hh, _, hp = args.httpListenAddr.rpartition(":")
    http = HTTPServer(hh or "0.0.0.0", int(hp))
    http.route("/health", lambda req: Response.text("OK"))
    from ..utils import metrics as metricslib
    http.route("/metrics", lambda req: Response.text(
        metricslib.REGISTRY.write_prometheus(extra=storage.metrics())))
    http.route("/snapshot/create", lambda req: Response.json(
        {"status": "ok", "snapshot": storage.create_snapshot()}))
    http.route("/snapshot/list", lambda req: Response.json(
        {"status": "ok", "snapshots": storage.list_snapshots()}))
    http.route("/internal/force_flush",
               lambda req: (storage.force_flush(), Response.text("OK"))[1])
    http.route("/internal/force_merge",
               lambda req: (storage.force_merge(), Response.text("OK"))[1])
    # integrity quarantine listing (parts moved aside by the open-time
    # checksum verification; non-empty => this node serves partial)
    def h_quarantine(req):
        rep = storage.quarantine_report()
        return Response.json(
            {"status": "success",
             "data": {"quarantined": rep, "count": len(rep),
                      "partial": bool(rep)}})
    http.route("/api/v1/status/quarantine", h_quarantine)

    # chaos control seam (devtools/faultinject, shared handler): GET
    # lists, ?set= replaces, ?clear=1 disarms; 403 unless the process
    # opted into chaos via VM_FAULT_INJECT=1 / VM_FAULTS
    from ..devtools import faultinject
    http.route("/internal/faults",
               lambda req: faultinject.handle_http(req, Response))
    # cost-and-profile plane: the node's continuous profiler (also
    # served over profile_v1 to vmselects) + its node-local per-tenant
    # usage table (search RPCs account into it)
    from ..utils import costacc, profiler
    profiler.ensure_started()
    http.route("/api/v1/status/profile",
               lambda req: profiler.handle_http(req, Response))
    http.route("/api/v1/status/usage", lambda req: Response.json(
        {"status": "success",
         "data": {"tenants": costacc.TENANT_USAGE.snapshot(
             reset=req.arg("reset") == "1")}}))
    # node-local health verdict, also served to vmselects as health_v1
    from ..query import sloplane
    http.route("/api/v1/status/health", lambda req: Response.json(
        sloplane.local_health(storage=storage, role="vmstorage")))
    # self-monitoring plane: own registry -> own storage as real series
    from ..utils import selfscrape
    scraper = selfscrape.maybe_start(
        storage.add_rows, "vmstorage", int(hp),
        flag_value=args.self_scrape_interval,
        extra=lambda: dict(storage.metrics()))
    return storage, insert_srv, select_srv, http, scraper


def main(argv=None):
    import faulthandler
    faulthandler.register(signal.SIGUSR1)
    args = parse_flags(argv)
    logger.set_level(args.loggerLevel)
    storage, insert_srv, select_srv, http, scraper = build(args)
    insert_srv.start()
    select_srv.start()
    http.start()
    logger.infof("vmstorage started: data=%s insert=%d select=%d http=%d",
                 args.storageDataPath, insert_srv.port, select_srv.port,
                 http.port)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.wait(1.0):
            pass
    finally:
        logger.infof("vmstorage: shutting down")
        insert_srv.stop()
        select_srv.stop()
        http.stop()
        if scraper is not None:
            # before storage.close(): a late scrape must not write into
            # a closed storage
            scraper.stop()
        storage.close()
        logger.infof("vmstorage: shutdown complete")


if __name__ == "__main__":
    main()
