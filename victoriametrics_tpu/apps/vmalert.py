"""vmalert: alerting + recording rule engine (reference app/vmalert:
rule/group.go eval loop, rule/alerting.go state machine, notifier/,
remotewrite/, datasource/).

Groups of rules from Prometheus-compatible YAML; each group has a jittered
eval loop. Alerting rules run the pending->firing state machine, notify
Alertmanager-compatible endpoints, and export ALERTS/ALERTS_FOR_STATE
series; recording rules remote-write their results.
"""

from __future__ import annotations

import argparse
import json
import math
import signal
import threading
import time
import urllib.parse
import urllib.request

from ..utils import fasttime, logger
from ..utils import metrics as metricslib

STATE_INACTIVE = "inactive"
STATE_PENDING = "pending"
STATE_FIRING = "firing"


class Datasource:
    """Prometheus-querying datasource (datasource/ analog)."""

    def __init__(self, url: str, timeout=30):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def query(self, expr: str, ts: float | None = None) -> list[dict]:
        params = {"query": expr}
        if ts is not None:
            params["time"] = ts
        url = f"{self.url}/api/v1/query?" + urllib.parse.urlencode(params)
        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            data = json.loads(r.read())
        if data.get("status") != "success":
            raise RuntimeError(f"datasource error: {data}")
        out = []
        for item in data["data"]["result"]:
            out.append({"metric": item["metric"],
                        "value": float(item["value"][1]),
                        "ts": item["value"][0]})
        return out


class Notifier:
    """Alertmanager client (notifier/ analog)."""

    def __init__(self, url: str, timeout=10):
        self.url = url.rstrip("/")
        self.timeout = timeout
        # registry-backed, per-notifier (reference vmalert
        # vmalert_alerts_sent_total{addr=...})
        self._sent = metricslib.REGISTRY.counter(metricslib.format_name(
            "vm_vmalert_alerts_sent_total", {"addr": self.url}))
        self._errors = metricslib.REGISTRY.counter(metricslib.format_name(
            "vm_vmalert_alerts_send_errors_total", {"addr": self.url}))

    @property
    def sent(self) -> int:
        return self._sent.get()

    @property
    def errors(self) -> int:
        return self._errors.get()

    def send(self, alerts: list[dict]) -> None:
        body = json.dumps(alerts).encode()
        req = urllib.request.Request(
            self.url + "/api/v2/alerts", data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                self._sent.inc(len(alerts))
        except OSError as e:
            self._errors.inc()
            logger.throttled_warnf("notifier", 10, "notifier %s: %s",
                                   self.url, e)


class RemoteWriter:
    """Writes recording results / alert state series via JSONL import."""

    def __init__(self, url: str, timeout=30):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def write(self, rows: list[tuple[dict, int, float]]) -> None:
        from ..ingest.parsers import series_to_jsonl
        lines = [series_to_jsonl(labels, [ts], [v]) for labels, ts, v in rows]
        req = urllib.request.Request(
            self.url + "/api/v1/import", data="\n".join(lines).encode(),
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
        except OSError as e:
            logger.throttled_warnf("rw", 10, "vmalert remote write: %s", e)


class EngineDatasource:
    """In-process datasource for rule groups colocated with a serving
    instance (vmsingle ``-rule`` / embedded tests): expressions evaluate
    through the engine's materialized-stream registry
    (``query/matstream.instant_vector``) — ONE evaluation per distinct
    (expression, timestamp) shared by every rule and counted once in
    the per-tenant cost plane, instead of one HTTP poll per rule.
    Returns the same row shape as :class:`Datasource` with the same
    value formatting (``float(fmt_value(v))``), so rule results are
    identical to the legacy poll path by construction; with
    ``VM_MATSTREAM=0`` the memo is bypassed and every rule evaluates
    itself — exactly the legacy behavior (the equality oracle)."""

    def __init__(self, api, tenant: tuple = (0, 0)):
        self.api = api          # httpapi.prometheus_api.PrometheusAPI
        self.tenant = tenant

    def query(self, expr: str, ts: float | None = None) -> list[dict]:
        ts_ms = fasttime.unix_ms() if ts is None else int(float(ts) * 1000)
        return self.api.matstreams.instant_vector(expr, ts_ms, self.tenant)


class LocalWriter:
    """RemoteWriter twin for embedded rule groups: recording results and
    alert state land directly in the colocated storage, no HTTP hop."""

    def __init__(self, api, tenant: tuple = (0, 0)):
        self.api = api
        self.tenant = tenant

    def write(self, rows: list[tuple[dict, int, float]]) -> None:
        self.api._ingest([(dict(labels), int(ts), float(v))
                          for labels, ts, v in rows], self.tenant)


def _dur_s(s, default=0.0) -> float:
    if s in (None, ""):
        return default
    from ..query.metricsql.parser import parse_duration_ms
    return parse_duration_ms(str(s))[0] / 1e3


def _template(s: str, labels: dict, value: float) -> str:
    """Minimal Go-template-ish expansion: {{ $labels.x }} and {{ $value }}."""
    import re as _re
    out = s.replace("{{ $value }}", repr(value)).replace(
        "{{$value}}", repr(value))
    def sub(m):
        return labels.get(m.group(1), "")
    out = _re.sub(r"\{\{\s*\$labels\.(\w+)\s*\}\}", sub, out)
    return out


class AlertingRule:
    def __init__(self, cfg: dict, group: "Group"):
        self.name = cfg["alert"]
        self.expr = cfg["expr"]
        self.for_s = _dur_s(cfg.get("for"), 0.0)
        self.labels = {str(k): str(v)
                       for k, v in (cfg.get("labels") or {}).items()}
        self.annotations = cfg.get("annotations") or {}
        self.group = group
        self._active: dict[tuple, dict] = {}  # labelset -> state
        self.last_error = ""

    def eval(self, ds: Datasource, now: float) -> list[dict]:
        """Returns the list of active alerts after this eval."""
        try:
            results = self.datasource_results(ds, now)
            self.last_error = ""
        except (OSError, RuntimeError, ValueError) as e:
            self.last_error = str(e)
            return list(self._active.values())
        seen = set()
        for r in results:
            labels = {**r["metric"], **self.labels,
                      "alertname": self.name}
            labels.pop("__name__", None)
            key = tuple(sorted(labels.items()))
            seen.add(key)
            st = self._active.get(key)
            if st is None:
                st = {"labels": labels, "state": STATE_PENDING,
                      "activeAt": now, "value": r["value"]}
                self._active[key] = st
            st["value"] = r["value"]
            if st["state"] == STATE_PENDING and \
                    now - st["activeAt"] >= self.for_s:
                st["state"] = STATE_FIRING
            st["annotations"] = {
                k: _template(str(v), labels, r["value"])
                for k, v in self.annotations.items()}
        for key in list(self._active):
            if key not in seen:
                del self._active[key]   # resolved
        return list(self._active.values())

    def datasource_results(self, ds: Datasource, now: float):
        return ds.query(self.expr, now)

    def restore(self, ds: Datasource, now: float, lookback_s: float):
        """Restore pending/firing state after a restart from the
        ALERTS_FOR_STATE series written by the previous instance
        (app/vmalert/rule/alerting.go Restore). Only useful for rules
        with a `for` duration."""
        if self.for_s <= 0 or self._active:
            return
        sel = "{alertname=%r" % self.name
        for k, v in sorted(self.labels.items()):
            sel += ",%s=%r" % (k, v)
        sel += "}"
        expr = f"last_over_time(ALERTS_FOR_STATE{sel}[{int(lookback_s)}s])"
        try:
            results = ds.query(expr, now)
        except (OSError, RuntimeError, ValueError) as e:
            self.last_error = f"restore: {e}"
            return
        for r in results:
            labels = dict(r["metric"])
            labels.pop("__name__", None)
            key = tuple(sorted(labels.items()))
            self._active[key] = {
                "labels": labels, "state": STATE_PENDING,
                "activeAt": float(r["value"]), "value": float("nan"),
                "annotations": {}}
            logger.infof("restored alert state %s activeAt=%s",
                         self.name, r["value"])

    def state_rows(self, now_ms: int) -> list:
        rows = []
        for st in self._active.values():
            labels = {"__name__": "ALERTS", "alertstate": st["state"],
                      **st["labels"]}
            rows.append((labels, now_ms, 1.0))
            rows.append(({"__name__": "ALERTS_FOR_STATE", **st["labels"]},
                         now_ms, st["activeAt"]))
        return rows


class RecordingRule:
    def __init__(self, cfg: dict, group: "Group"):
        self.name = cfg["record"]
        self.expr = cfg["expr"]
        self.labels = {str(k): str(v)
                       for k, v in (cfg.get("labels") or {}).items()}
        self.last_error = ""

    def eval(self, ds: Datasource, now: float) -> list:
        try:
            results = ds.query(self.expr, now)
            self.last_error = ""
        except (OSError, RuntimeError, ValueError) as e:
            self.last_error = str(e)
            return []
        rows = []
        now_ms = int(now * 1000)
        for r in results:
            labels = {**r["metric"], **self.labels, "__name__": self.name}
            if not math.isnan(r["value"]):
                rows.append((labels, now_ms, r["value"]))
        return rows


class Group:
    def __init__(self, cfg: dict, ds: Datasource, notifiers: list[Notifier],
                 rw: RemoteWriter | None, default_interval=60.0):
        self.name = cfg.get("name", "")
        self.interval = _dur_s(cfg.get("interval"), default_interval)
        self.ds = ds
        self.notifiers = notifiers
        self.rw = rw
        self.rules: list = []
        for rc in cfg.get("rules", []):
            if "alert" in rc:
                self.rules.append(AlertingRule(rc, self))
            elif "record" in rc:
                self.rules.append(RecordingRule(rc, self))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self.last_eval = 0.0

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        import random
        if self._stop.wait(random.random() * self.interval):
            return
        while True:
            t0 = fasttime.unix_seconds()
            try:
                self.eval_once(t0)
            except Exception as e:  # pragma: no cover
                logger.errorf("group %s eval: %s", self.name, e)
            if self._stop.wait(max(self.interval -
                                   (fasttime.unix_seconds() - t0), 0.1)):
                return

    def restore(self, ds: Datasource, lookback_s: float = 86_400.0):
        now = fasttime.unix_seconds()
        for rule in self.rules:
            if isinstance(rule, AlertingRule):
                rule.restore(ds, now, lookback_s)

    def eval_once(self, now: float, notify: bool = True) -> None:
        self.last_eval = now
        now_ms = int(now * 1000)
        state_rows = []
        firing = []
        for rule in self.rules:
            if isinstance(rule, AlertingRule):
                active = rule.eval(self.ds, now)
                state_rows.extend(rule.state_rows(now_ms))
                for st in active:
                    if st["state"] == STATE_FIRING:
                        firing.append({
                            "labels": st["labels"],
                            "annotations": st.get("annotations", {}),
                            "startsAt": time.strftime(
                                "%Y-%m-%dT%H:%M:%SZ",
                                time.gmtime(st["activeAt"])),
                            "generatorURL": "",
                        })
            else:
                state_rows.extend(rule.eval(self.ds, now))
        if firing and notify:
            for n in self.notifiers:
                n.send(firing)
        if state_rows and self.rw is not None:
            self.rw.write(state_rows)

    def api_dict(self) -> dict:
        rules = []
        for r in self.rules:
            if isinstance(r, AlertingRule):
                rules.append({
                    "name": r.name, "query": r.expr, "type": "alerting",
                    "duration": r.for_s, "labels": r.labels,
                    "annotations": r.annotations,
                    "lastError": r.last_error,
                    "state": ("firing" if any(
                        s["state"] == STATE_FIRING
                        for s in r._active.values()) else
                        "pending" if r._active else "inactive"),
                    "alerts": [
                        {"labels": s["labels"], "state": s["state"],
                         "value": str(s["value"]),
                         "annotations": s.get("annotations", {})}
                        for s in r._active.values()],
                })
            else:
                rules.append({"name": r.name, "query": r.expr,
                              "type": "recording", "labels": r.labels,
                              "lastError": r.last_error})
        return {"name": self.name, "interval": self.interval, "rules": rules}


def replay(groups: list, time_from_ms: int, time_to_ms: int) -> int:
    """Replay mode (app/vmalert/replay.go): walk each group's rules over
    the historical range at the group interval, remote-writing recording
    results and alert state; notifications are suppressed. Returns the
    number of evaluations performed."""
    evals = 0
    for g in groups:
        step_ms = int(g.interval * 1000)
        t = time_from_ms
        while t <= time_to_ms:
            g.eval_once(t / 1000.0, notify=False)
            evals += 1
            t += step_ms
        logger.infof("replay: group %s evaluated %d steps", g.name,
                     (time_to_ms - time_from_ms) // step_ms + 1)
    return evals


def parse_flags(argv=None):
    p = argparse.ArgumentParser(prog="vmalert")
    p.add_argument("-rule", action="append", default=[],
                   help="rule file path, repeatable")
    p.add_argument("-datasource.url", dest="datasource_url",
                   default="http://127.0.0.1:8428")
    p.add_argument("-notifier.url", dest="notifier_urls", action="append",
                   default=[])
    p.add_argument("-remoteWrite.url", dest="remote_write_url", default="")
    p.add_argument("-evaluationInterval", dest="eval_interval", default="1m")
    p.add_argument("-remoteRead.url", dest="remote_read_url", default="",
                   help="restore alert state from this datasource on start")
    p.add_argument("-replay.timeFrom", dest="replay_from", default="",
                   help="replay mode: evaluate rules from this time")
    p.add_argument("-replay.timeTo", dest="replay_to", default="")
    p.add_argument("-httpListenAddr", default=":8880")
    p.add_argument("-loggerLevel", default="INFO")
    args, _ = p.parse_known_args(argv)
    return args


def build(args):
    import yaml

    from ..httpapi.server import HTTPServer, Response

    ds = Datasource(args.datasource_url)
    notifiers = [Notifier(u) for u in args.notifier_urls]
    rw = RemoteWriter(args.remote_write_url) if args.remote_write_url else None
    groups: list[Group] = []
    for path in args.rule:
        cfg = yaml.safe_load(open(path).read()) or {}
        for g in cfg.get("groups", []):
            groups.append(Group(g, ds, notifiers, rw,
                                _dur_s(args.eval_interval, 60.0)))

    hh, _, hp = args.httpListenAddr.rpartition(":")
    srv = HTTPServer(hh or "0.0.0.0", int(hp))
    srv.route("/health", lambda req: Response.text("OK"))
    srv.route("/api/v1/rules", lambda req: Response.json(
        {"status": "success",
         "data": {"groups": [g.api_dict() for g in groups]}}))

    def h_alerts(req):
        alerts = []
        for g in groups:
            for r in g.rules:
                if isinstance(r, AlertingRule):
                    for s in r._active.values():
                        alerts.append({"labels": s["labels"],
                                       "state": s["state"],
                                       "value": str(s["value"]),
                                       "annotations": s.get("annotations", {}),
                                       "activeAt": s["activeAt"]})
        return Response.json({"status": "success",
                              "data": {"alerts": alerts}})

    srv.route("/api/v1/alerts", h_alerts)
    return groups, srv


def main(argv=None):
    import faulthandler
    faulthandler.register(signal.SIGUSR1)
    args = parse_flags(argv)
    logger.set_level(args.loggerLevel)
    groups, srv = build(args)
    if args.replay_from and args.replay_to:
        from ..httpapi.prometheus_api import parse_time
        frm = parse_time(args.replay_from, 0)
        to = parse_time(args.replay_to, 0)
        n = replay(groups, frm, to)
        logger.infof("vmalert replay finished: %d evaluations", n)
        return
    if args.remote_read_url:
        rr = Datasource(args.remote_read_url)
        for g in groups:
            g.restore(rr)
    for g in groups:
        g.start()
    srv.start()
    logger.infof("vmalert started: groups=%d http=%d", len(groups), srv.port)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.wait(1.0):
            pass
    finally:
        for g in groups:
            g.stop()
        srv.stop()
        logger.infof("vmalert: shutdown complete")


if __name__ == "__main__":
    main()
