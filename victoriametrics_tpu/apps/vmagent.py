"""vmagent: scraper + remote-write forwarder (reference app/vmagent +
lib/promscrape).

- Prometheus-style scrape configs (static_configs + file_sd_configs), jittered
  scrape loops, `up`/scrape_* auto-metrics, metric_relabel_configs.
- Per -remoteWrite.url context: pending buffer -> persistent queue (crash
  safe) -> sender with exponential backoff, snappy remote-write bodies
  (app/vmagent/remotewrite/{remotewrite,pendingseries,client}.go).
- Also accepts every push protocol over HTTP like vminsert, forwarding into
  the same remote-write pipeline.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import threading
import time
import urllib.request

from ..ingest import remote_write
from ..ingest.parsers import parse_prometheus
from ..ingest.persistentqueue import PersistentQueue
from ..ingest.relabel import parse_relabel_configs
from ..utils import fasttime, logger

MAX_ROWS_PER_BLOCK = 10_000


class RemoteWriteCtx:
    """One remote storage destination (remoteWriteCtx analog)."""

    def __init__(self, url: str, queue_dir: str, flush_interval=1.0,
                 send_timeout=30):
        self.url = url
        self.queue = PersistentQueue(queue_dir)
        self._pending: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.flush_interval = flush_interval
        self.send_timeout = send_timeout
        self.sent_rows = 0
        self.send_errors = 0
        self._threads = [
            threading.Thread(target=self._flusher, daemon=True),
            threading.Thread(target=self._sender, daemon=True),
        ]

    def start(self):
        for t in self._threads:
            t.start()

    def push(self, rows: list) -> None:
        """rows: [(labels_dict, ts_ms, value)]"""
        with self._lock:
            self._pending.extend(rows)
            if len(self._pending) >= MAX_ROWS_PER_BLOCK:
                self._flush_locked()

    def _flush_locked(self):
        if not self._pending:
            return
        rows, self._pending = self._pending, []
        series = [([(k, v) for k, v in labels.items()], [(ts, val)])
                  for labels, ts, val in rows]
        body = remote_write.build_write_request(series)
        self.queue.put(body)

    def _flusher(self):
        while not self._stop.wait(self.flush_interval):
            with self._lock:
                self._flush_locked()

    def _sender(self):
        backoff = 1.0
        while not self._stop.is_set():
            block = self.queue.get(timeout=1.0)
            if block is None:
                continue
            while not self._stop.is_set():
                try:
                    req = urllib.request.Request(
                        self.url, data=block, method="POST",
                        headers={"Content-Encoding": "snappy",
                                 "Content-Type": "application/x-protobuf"})
                    with urllib.request.urlopen(req, timeout=self.send_timeout):
                        pass
                    self.sent_rows += 1
                    backoff = 1.0
                    break
                except urllib.error.HTTPError as e:
                    self.send_errors += 1
                    if 400 <= e.code < 500 and e.code != 429:
                        logger.errorf("remote write %s: dropping block: %s",
                                      self.url, e)
                        break  # unretriable
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 60)
                except OSError as e:
                    self.send_errors += 1
                    logger.throttled_warnf(
                        "rw-" + self.url, 10, "remote write %s: %s",
                        self.url, e)
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 60)

    def stop(self):
        self._stop.set()
        with self._lock:
            self._flush_locked()
        self.queue.close()


class ScrapeTarget:
    PUSH_BATCH = 5000

    def __init__(self, url: str, labels: dict, interval_s: float,
                 timeout_s: float, metric_relabel, push_fn):
        self.url = url
        self.labels = labels
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.metric_relabel = metric_relabel
        self.push_fn = push_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self.health = "unknown"
        self.last_error = ""
        self.last_scrape = 0.0
        # series seen in the last successful scrape: key -> labels, used to
        # emit Prometheus staleness markers when they disappear
        # (scrapework.go:441 sendStaleSeries)
        self._prev: dict[int, dict] = {}
        self._scraped_once = False

    def start(self):
        self._thread.start()

    def stop(self, send_stale: bool = True):
        self._stop.set()
        # let an in-flight scrape finish first: samples pushed AFTER the
        # stale markers would resurrect the series forever
        if self._thread.is_alive() and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=self.timeout_s + 2)
        if send_stale and self._scraped_once:
            # target removed (SD change / shutdown): mark every tracked
            # series AND the auto metrics stale so queries stop extending
            # them (the last scrape may have failed, so _prev can be empty
            # while up=0 etc are still live)
            now_ms = fasttime.unix_ms()
            from ..ops.decimal import STALE_NAN
            rows = [(labels, now_ms, STALE_NAN)
                    for labels in self._prev.values()]
            for name in ("up", "scrape_duration_seconds",
                         "scrape_samples_scraped"):
                rows.append(({"__name__": name, **self.labels}, now_ms,
                             STALE_NAN))
            self._prev = {}
            self.push_fn(rows)

    def _loop(self):
        # jitter the start so targets spread over the interval
        if self._stop.wait(random.random() * self.interval_s):
            return
        while True:
            t0 = fasttime.unix_seconds()
            self._scrape_once()
            elapsed = fasttime.unix_seconds() - t0
            if self._stop.wait(max(self.interval_s - elapsed, 0.1)):
                return

    @staticmethod
    def _series_key(labels: dict) -> int:
        return hash(tuple(sorted(labels.items())))

    def _scrape_once(self):
        from ..ops.decimal import STALE_NAN
        now_ms = fasttime.unix_ms()
        rows = []
        cur: dict[int, dict] = {}
        up = 1.0
        samples = 0
        t0 = time.perf_counter()

        def handle_line_block(text):
            nonlocal samples, rows
            for row in parse_prometheus(text, now_ms):
                labels = dict(row.labels)
                labels.update(self.labels)
                if self.metric_relabel is not None:
                    labels = self.metric_relabel.apply(labels)
                    if not labels:
                        continue
                cur[self._series_key(labels)] = labels
                rows.append((labels, row.timestamp or now_ms, row.value))
                samples += 1
                if len(rows) >= self.PUSH_BATCH:
                    self.push_fn(rows)
                    rows = []

        try:
            with urllib.request.urlopen(self.url,
                                        timeout=self.timeout_s) as r:
                # stream-parse unconditionally: bounded memory regardless of
                # Content-Length (chunked responses included;
                # scrapework.go streamParse)
                tail = b""
                while True:
                    chunk = r.read(256 << 10)
                    if not chunk:
                        break
                    buf = tail + chunk
                    cut = buf.rfind(b"\n")
                    if cut < 0:
                        tail = buf
                        continue
                    handle_line_block(
                        buf[:cut + 1].decode("utf-8", "replace"))
                    tail = buf[cut + 1:]
                if tail:
                    handle_line_block(tail.decode("utf-8", "replace"))
            self.health = "up"
            self.last_error = ""
        except OSError as e:
            up = 0.0
            samples = 0
            rows = []  # drop the un-pushed partial batch
            self.health = "down"
            self.last_error = str(e)
            # scrape failed: everything from the previous scrape AND any
            # partially-pushed series from this one goes stale
            self._prev = {**self._prev, **cur}
            cur = {}
        dur = time.perf_counter() - t0
        self.last_scrape = fasttime.unix_seconds()
        self._scraped_once = True
        # staleness markers for series that vanished since the last scrape
        for key, labels in self._prev.items():
            if key not in cur:
                rows.append((labels, now_ms, STALE_NAN))
        self._prev = cur
        auto = [("up", up), ("scrape_duration_seconds", dur),
                ("scrape_samples_scraped", float(samples))]
        for name, v in auto:
            rows.append(({"__name__": name, **self.labels}, now_ms, v))
        self.push_fn(rows)


class VMAgent:
    SD_REFRESH_S = 30.0  # -promscrape.*SDCheckInterval analog

    def __init__(self, scrape_config: dict, remote_urls: list[str],
                 tmp_dir: str, global_relabel=None, sd_refresh_s=None):
        self.rw_ctxs = [
            RemoteWriteCtx(url, os.path.join(tmp_dir, f"q{i}"))
            for i, url in enumerate(remote_urls)]
        self.global_relabel = global_relabel
        self.cfg = scrape_config or {}
        self.sd_refresh_s = sd_refresh_s or self.SD_REFRESH_S
        self.targets: dict[tuple, ScrapeTarget] = {}
        self._started = False
        self._stop = threading.Event()
        self._sync_lock = threading.Lock()
        self._sd_thread = threading.Thread(target=self._sd_loop, daemon=True)
        self._sync_targets()

    def _resolve_specs(self) -> dict[tuple, tuple]:
        """Evaluate every SD provider: spec_key -> (url, labels, interval,
        timeout, metric_relabel). Meta labels flow through relabel_configs,
        then __-prefixed labels are dropped (promscrape/config.go
        mergeLabels semantics)."""
        from ..ingest.discovery import discover_targets
        if not hasattr(self, "_sd_last_good"):
            self._sd_last_good = {}
        cfg = self.cfg
        g = cfg.get("global", {})
        default_interval = _dur_s(g.get("scrape_interval", "1m"))
        specs: dict[tuple, tuple] = {}
        for sc in cfg.get("scrape_configs", []):
            job = sc.get("job_name", "")
            interval = _dur_s(sc.get("scrape_interval")) or default_interval
            timeout = _dur_s(sc.get("scrape_timeout")) or min(interval, 10)
            path = sc.get("metrics_path", "/metrics")
            scheme = sc.get("scheme", "http")
            mrc = sc.get("metric_relabel_configs")
            metric_relabel = parse_relabel_configs(mrc) if mrc else None
            rc = sc.get("relabel_configs")
            relabel = parse_relabel_configs(rc) if rc else None
            target_specs = []
            for stc in sc.get("static_configs", []):
                for t in stc.get("targets", []):
                    target_specs.append((t, stc.get("labels", {})))
            for fsd in sc.get("file_sd_configs", []):
                for fn in fsd.get("files", []):
                    try:
                        data = json.load(open(fn))
                        for entry in data:
                            for t in entry.get("targets", []):
                                target_specs.append(
                                    (t, entry.get("labels", {})))
                    except (OSError, ValueError) as e:
                        logger.errorf("file_sd %s: %s", fn, e)
            target_specs.extend(discover_targets(sc, self._sd_last_good))
            for addr, extra in target_specs:
                labels = {"job": job, "__address__": addr,
                          "__metrics_path__": path, "__scheme__": scheme,
                          **extra}
                if relabel is not None:
                    labels = relabel.apply(labels)
                    if not labels:
                        continue
                addr = labels.get("__address__", addr)
                path_f = labels.get("__metrics_path__", path)
                scheme_f = labels.get("__scheme__", scheme)
                labels.setdefault("instance", addr)
                final = {k: v for k, v in labels.items()
                         if not k.startswith("__")}
                url = f"{scheme_f}://{addr}{path_f}"
                # scrape settings are part of the identity: a reload that
                # changes interval/timeout/relabel must replace the target
                key = (url, tuple(sorted(final.items())), interval, timeout,
                       json.dumps(mrc, sort_keys=True))
                specs[key] = (url, final, interval, timeout, metric_relabel)
        return specs

    def _sync_targets(self):
        """Diff discovered specs against running scrapers; removed targets
        stop WITH staleness markers. Serialized: SIGHUP, /-/reload, and the
        SD refresh thread may all call this concurrently."""
        with self._sync_lock:
            if self._stop.is_set():
                return  # a queued SD refresh must not resurrect targets
            specs = self._resolve_specs()
            for key in list(self.targets):
                if key not in specs:
                    self.targets.pop(key).stop(send_stale=True)
            for key, (url, labels, interval, timeout, mrc) in specs.items():
                if key in self.targets:
                    continue
                t = ScrapeTarget(url, labels, interval, timeout, mrc,
                                 self.push)
                self.targets[key] = t
                if self._started:
                    t.start()

    def _sd_loop(self):
        while not self._stop.wait(self.sd_refresh_s):
            try:
                self._sync_targets()
            except Exception as e:  # pragma: no cover
                logger.errorf("vmagent sd refresh: %s", e)

    def push(self, rows: list):
        if self.global_relabel is not None:
            out = []
            for labels, ts, v in rows:
                labels = self.global_relabel.apply(labels)
                if labels is not None:
                    out.append((labels, ts, v))
            rows = out
        for ctx in self.rw_ctxs:
            ctx.push(rows)

    def start(self):
        self._started = True
        for ctx in self.rw_ctxs:
            ctx.start()
        for t in self.targets.values():
            t.start()
        self._sd_thread.start()

    def stop(self):
        self._stop.set()
        with self._sync_lock:
            targets = list(self.targets.values())
            self.targets = {}
        # signal everything first so hung scrapes time out concurrently,
        # then join + emit stale markers
        for t in targets:
            t._stop.set()
        for t in targets:
            t.stop(send_stale=True)
        for ctx in self.rw_ctxs:
            ctx.stop()

    def reload(self, scrape_config: dict):
        """Swap the scrape config in place (SIGHUP hot-reload)."""
        self.cfg = scrape_config or {}
        self._sync_targets()

    def target_status(self) -> list[dict]:
        with self._sync_lock:
            targets = list(self.targets.values())
        return [{"url": t.url, "labels": t.labels, "health": t.health,
                 "lastError": t.last_error, "lastScrape": t.last_scrape}
                for t in targets]


def _dur_s(s) -> float:
    if not s:
        return 0.0
    from ..query.metricsql.parser import parse_duration_ms
    return parse_duration_ms(str(s))[0] / 1e3


def parse_flags(argv=None):
    p = argparse.ArgumentParser(prog="vmagent")
    p.add_argument("-promscrape.config", dest="scrape_config", default="")
    p.add_argument("-remoteWrite.url", dest="remote_urls", action="append",
                   default=[])
    p.add_argument("-remoteWrite.tmpDataPath", dest="tmp_dir",
                   default="vmagent-remotewrite-data")
    p.add_argument("-remoteWrite.relabelConfig", dest="rw_relabel", default="")
    p.add_argument("-httpListenAddr", default=":8429")
    p.add_argument("-loggerLevel", default="INFO")
    args, _ = p.parse_known_args(argv)
    return args


def build(args):
    import yaml

    from ..httpapi.prometheus_api import PrometheusAPI
    from ..httpapi.server import HTTPServer, Response

    scrape_cfg = {}
    if args.scrape_config:
        scrape_cfg = yaml.safe_load(open(args.scrape_config).read()) or {}
    relabel = None
    if args.rw_relabel:
        relabel = parse_relabel_configs(open(args.rw_relabel).read())
    agent = VMAgent(scrape_cfg, args.remote_urls, args.tmp_dir, relabel)

    class _PushBackend:
        """Duck-storage: push-protocol ingestion forwards to remote write."""

        def add_rows(self, rows):
            batch = [(dict(labels) if not isinstance(labels, dict)
                      else labels, ts, v) for labels, ts, v in rows]
            agent.push([(lb if isinstance(lb, dict) else
                         {k.decode() if isinstance(k, bytes) else k:
                          v.decode() if isinstance(v, bytes) else v
                          for k, v in lb}, ts, val)
                        for lb, ts, val in batch])
            return len(batch)

        def metrics(self):
            return {
                "vmagent_remotewrite_pending_blocks":
                    sum(c.queue.pending for c in agent.rw_ctxs),
                "vmagent_remotewrite_sent_blocks_total":
                    sum(c.sent_rows for c in agent.rw_ctxs),
                "vmagent_remotewrite_errors_total":
                    sum(c.send_errors for c in agent.rw_ctxs),
                "vmagent_targets": len(agent.targets),
            }

    hh, _, hp = args.httpListenAddr.rpartition(":")
    srv = HTTPServer(hh or "0.0.0.0", int(hp))
    api = PrometheusAPI(_PushBackend())
    api.register(srv, mode="insert")
    srv.route("/targets", lambda req: Response.json(
        {"status": "success", "data": {"activeTargets": agent.target_status()}}))
    srv.route("/api/v1/targets", lambda req: Response.json(
        {"status": "success", "data": {"activeTargets": agent.target_status()}}))
    return agent, srv


def main(argv=None):
    import faulthandler
    faulthandler.register(signal.SIGUSR1)
    args = parse_flags(argv)
    logger.set_level(args.loggerLevel)
    agent, srv = build(args)
    agent.start()
    srv.start()
    logger.infof("vmagent started: targets=%d remotes=%d http=%d",
                 len(agent.targets), len(agent.rw_ctxs), srv.port)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    def _reload(*_):
        # SIGHUP hot-reload of -promscrape.config (the reference re-reads
        # scrape configs on SIGHUP and on /-/reload)
        if not args.scrape_config:
            return
        try:
            import yaml
            cfg = yaml.safe_load(open(args.scrape_config).read()) or {}
            agent.reload(cfg)
            logger.infof("vmagent: reloaded %s (%d targets)",
                         args.scrape_config, len(agent.targets))
        except Exception as e:
            logger.errorf("vmagent: reload failed, keeping old config: %s",
                          e)
    signal.signal(signal.SIGHUP, _reload)
    from ..httpapi.server import Response as _Resp

    def h_reload(req):
        _reload()
        return _Resp.text("OK")
    srv.route("/-/reload", h_reload)
    try:
        while not stop.wait(1.0):
            pass
    finally:
        srv.stop()
        agent.stop()
        logger.infof("vmagent: shutdown complete")


if __name__ == "__main__":
    main()
