"""vmbackup / vmrestore (reference app/vmbackup, app/vmrestore,
lib/backup/actions/{backup,restore}.go): incremental part-level sync of an
instant snapshot to a destination, and restore with unchanged-part skip.

Destinations: fs://<path> (the reference additionally ships s3/gcs/azure
drivers behind the same interface; RemoteFS here is that interface and
fs:// its first driver)."""

from __future__ import annotations

import argparse
import json
import os
import shutil
import urllib.error
import urllib.request

from ..utils import logger


class RemoteFS:
    """Destination interface (lib/backup/common/fs.go analog)."""

    def list_files(self) -> dict[str, int]:
        raise NotImplementedError

    def upload(self, rel: str, src_path: str):
        raise NotImplementedError

    def download(self, rel: str, dst_path: str):
        raise NotImplementedError

    def delete(self, rel: str):
        raise NotImplementedError


class FsRemote(RemoteFS):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def list_files(self) -> dict[str, int]:
        out = {}
        for dp, _, fns in os.walk(self.root):
            for fn in fns:
                full = os.path.join(dp, fn)
                out[os.path.relpath(full, self.root)] = os.path.getsize(full)
        return out

    def upload(self, rel: str, src_path: str):
        dst = os.path.join(self.root, rel)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copy2(src_path, dst)

    def download(self, rel: str, dst_path: str):
        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        shutil.copy2(os.path.join(self.root, rel), dst_path)

    def delete(self, rel: str):
        try:
            os.unlink(os.path.join(self.root, rel))
        except FileNotFoundError:
            pass


class S3Remote(RemoteFS):
    """s3://bucket/prefix destination (lib/backup/s3remote/s3.go analog):
    plain S3 REST calls signed with SigV4. `endpoint` override (the
    -customS3Endpoint flag) points it at MinIO / fake servers."""

    def __init__(self, bucket: str, prefix: str, region: str = "us-east-1",
                 endpoint: str = "", access_key: str = "",
                 secret_key: str = ""):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.region = region
        self.endpoint = (endpoint.rstrip("/") if endpoint else
                         f"https://s3.{region}.amazonaws.com")
        self.access_key = access_key or os.environ.get(
            "AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")

    def _url(self, rel: str = "", query: str = "") -> str:
        key = "/".join(x for x in (self.bucket, self.prefix, rel) if x)
        u = f"{self.endpoint}/{key}"
        return u + ("?" + query if query else "")

    def _call(self, method: str, url: str, body: bytes = b"") -> bytes:
        from ..ingest.discovery import _sigv4_headers
        headers = {}
        if self.access_key and self.secret_key:
            headers = _sigv4_headers(method, url, body, self.region,
                                     "s3", self.access_key,
                                     self.secret_key)
        req = urllib.request.Request(url, data=body or None,
                                     headers=headers, method=method)
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.read()

    def list_files(self) -> dict[str, int]:
        import urllib.parse
        import xml.etree.ElementTree as ET
        out: dict[str, int] = {}
        prefix = "/".join(x for x in (self.prefix,) if x)
        token = ""
        while True:
            q = "list-type=2&prefix=" + urllib.parse.quote(
                prefix + "/" if prefix else "")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token)
            data = self._call("GET", f"{self.endpoint}/{self.bucket}?{q}")
            root = ET.fromstring(data)
            ns = root.tag[:root.tag.index("}") + 1] if                 root.tag.startswith("{") else ""
            for c in root.iter(f"{ns}Contents"):
                key = c.find(f"{ns}Key").text
                size = int(c.find(f"{ns}Size").text)
                rel = key[len(prefix) + 1:] if prefix else key
                out[rel] = size
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is None or trunc.text != "true":
                break
            token = root.find(f"{ns}NextContinuationToken").text
        return out

    def upload(self, rel: str, src_path: str):
        with open(src_path, "rb") as f:
            self._call("PUT", self._url(rel), f.read())

    def download(self, rel: str, dst_path: str):
        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        data = self._call("GET", self._url(rel))
        with open(dst_path, "wb") as f:
            f.write(data)

    def delete(self, rel: str):
        try:
            self._call("DELETE", self._url(rel))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class GcsRemote(RemoteFS):
    """gs://bucket/prefix destination (lib/backup/gcsremote/gcs.go analog)
    over the GCS JSON/XML-free REST API. Auth: explicit bearer token
    (GCS_ACCESS_TOKEN / token kwarg) or the GCE metadata server — the
    standard on-GCP path; `endpoint` points it at fake-gcs-server-style
    local fakes."""

    def __init__(self, bucket: str, prefix: str, endpoint: str = "",
                 token: str = ""):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.endpoint = (endpoint.rstrip("/") if endpoint
                         else "https://storage.googleapis.com")
        self._token = token or os.environ.get("GCS_ACCESS_TOKEN", "")
        self._meta_token_exp = 0.0

    def _auth(self) -> dict:
        if not self._token or self._meta_token_exp:
            import time as _t
            now = _t.time()
            if self._meta_token_exp and now < self._meta_token_exp - 60:
                return ({"Authorization": f"Bearer {self._token}"}
                        if self._token else {})
            try:
                req = urllib.request.Request(
                    "http://metadata.google.internal/computeMetadata/v1/"
                    "instance/service-accounts/default/token",
                    headers={"Metadata-Flavor": "Google"})
                with urllib.request.urlopen(req, timeout=5) as r:
                    tok = json.loads(r.read())
                self._token = tok["access_token"]
                self._meta_token_exp = now + tok.get("expires_in", 300)
            except Exception:
                # anonymous (public buckets / auth-free fakes): remember the
                # verdict so every object op doesn't re-stall 5s on a doomed
                # metadata fetch
                self._meta_token_exp = now + 300
        return {"Authorization": f"Bearer {self._token}"} if self._token \
            else {}

    def _key(self, rel: str) -> str:
        return "/".join(x for x in (self.prefix, rel) if x)

    def _call(self, method: str, url: str, body: bytes | None = None,
              headers: dict | None = None) -> bytes:
        h = dict(headers or {})
        h.update(self._auth())
        req = urllib.request.Request(url, data=body, headers=h,
                                     method=method)
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.read()

    def list_files(self) -> dict[str, int]:
        import urllib.parse
        out: dict[str, int] = {}
        prefix = self._key("")
        token = ""
        while True:
            q = "prefix=" + urllib.parse.quote(
                prefix + "/" if prefix else "", safe="")
            if token:
                q += "&pageToken=" + urllib.parse.quote(token)
            data = self._call(
                "GET", f"{self.endpoint}/storage/v1/b/{self.bucket}/o?{q}")
            resp = json.loads(data)
            for item in resp.get("items", []):
                name = item["name"]
                rel = name[len(prefix) + 1:] if prefix else name
                out[rel] = int(item["size"])
            token = resp.get("nextPageToken", "")
            if not token:
                break
        return out

    def upload(self, rel: str, src_path: str):
        import urllib.parse
        with open(src_path, "rb") as f:
            body = f.read()
        self._call(
            "POST",
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
            f"?uploadType=media&name=" +
            urllib.parse.quote(self._key(rel), safe=""),
            body, {"Content-Type": "application/octet-stream"})

    def download(self, rel: str, dst_path: str):
        import urllib.parse
        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        data = self._call(
            "GET", f"{self.endpoint}/storage/v1/b/{self.bucket}/o/" +
            urllib.parse.quote(self._key(rel), safe="") + "?alt=media")
        with open(dst_path, "wb") as f:
            f.write(data)

    def delete(self, rel: str):
        import urllib.parse
        try:
            self._call(
                "DELETE", f"{self.endpoint}/storage/v1/b/{self.bucket}/o/" +
                urllib.parse.quote(self._key(rel), safe=""))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class AzblobRemote(RemoteFS):
    """azblob://container/prefix destination (lib/backup/azremote/azblob.go
    analog). Auth: SAS token (AZURE_STORAGE_SAS_TOKEN) or SharedKey request
    signing with AZURE_STORAGE_ACCOUNT_{NAME,KEY} — pure hmac/hashlib, no
    SDK. `endpoint` override (AZURE_STORAGE_DOMAIN analog) points it at
    Azurite-style local fakes."""

    API_VERSION = "2021-06-08"

    def __init__(self, container: str, prefix: str, account: str = "",
                 key: str = "", sas: str = "", endpoint: str = ""):
        self.container = container
        self.prefix = prefix.strip("/")
        self.account = account or os.environ.get(
            "AZURE_STORAGE_ACCOUNT_NAME", "")
        self.key = key or os.environ.get("AZURE_STORAGE_ACCOUNT_KEY", "")
        self.sas = (sas or os.environ.get(
            "AZURE_STORAGE_SAS_TOKEN", "")).lstrip("?")
        self.endpoint = (endpoint.rstrip("/") if endpoint else
                         f"https://{self.account}.blob.core.windows.net")

    def _key_of(self, rel: str) -> str:
        return "/".join(x for x in (self.prefix, rel) if x)

    def _signed_headers(self, method: str, url: str, body_len: int,
                        headers: dict) -> dict:
        """SharedKey authorization (the x-ms-date + canonicalized string
        HMAC-SHA256 scheme)."""
        import base64
        import hashlib
        import hmac
        import urllib.parse
        from email.utils import formatdate
        h = dict(headers)
        h["x-ms-date"] = formatdate(usegmt=True)
        h["x-ms-version"] = self.API_VERSION
        if not self.key:
            return h
        parsed = urllib.parse.urlsplit(url)
        canon_headers = "".join(
            f"{k.lower()}:{v}\n" for k, v in
            sorted((k, v) for k, v in h.items()
                   if k.lower().startswith("x-ms-")))
        canon_res = f"/{self.account}{parsed.path}"
        if parsed.query:
            params = urllib.parse.parse_qs(parsed.query,
                                           keep_blank_values=True)
            for k in sorted(params):
                canon_res += f"\n{k.lower()}:{','.join(params[k])}"
        cl = str(body_len) if body_len else ""
        to_sign = (f"{method}\n\n\n{cl}\n\n"
                   f"{h.get('Content-Type', '')}\n\n\n\n\n\n\n"
                   f"{canon_headers}{canon_res}")
        sig = base64.b64encode(hmac.new(
            base64.b64decode(self.key), to_sign.encode("utf-8"),
            hashlib.sha256).digest()).decode()
        h["Authorization"] = f"SharedKey {self.account}:{sig}"
        return h

    def _call(self, method: str, path: str, query: str = "",
              body: bytes | None = None,
              headers: dict | None = None) -> bytes:
        import urllib.parse
        q = query
        if self.sas:
            q = (q + "&" if q else "") + self.sas
        url = f"{self.endpoint}{path}" + (f"?{q}" if q else "")
        h = self._signed_headers(method, url, len(body) if body else 0,
                                 headers or {})
        req = urllib.request.Request(url, data=body, headers=h,
                                     method=method)
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.read()

    def list_files(self) -> dict[str, int]:
        import urllib.parse
        import xml.etree.ElementTree as ET
        out: dict[str, int] = {}
        prefix = self._key_of("")
        marker = ""
        while True:
            q = "restype=container&comp=list&prefix=" + urllib.parse.quote(
                prefix + "/" if prefix else "", safe="")
            if marker:
                q += "&marker=" + urllib.parse.quote(marker)
            data = self._call("GET", f"/{self.container}", q)
            root = ET.fromstring(data)
            for b in root.iter("Blob"):
                name = b.find("Name").text
                size = int(b.find("Properties/Content-Length").text)
                rel = name[len(prefix) + 1:] if prefix else name
                out[rel] = size
            nm = root.find("NextMarker")
            marker = (nm.text or "") if nm is not None else ""
            if not marker:
                break
        return out

    def _blob_path(self, rel: str) -> str:
        import urllib.parse
        return f"/{self.container}/" + urllib.parse.quote(
            self._key_of(rel), safe="/")

    def upload(self, rel: str, src_path: str):
        with open(src_path, "rb") as f:
            body = f.read()
        self._call("PUT", self._blob_path(rel), "", body,
                   {"x-ms-blob-type": "BlockBlob",
                    "Content-Type": "application/octet-stream"})

    def download(self, rel: str, dst_path: str):
        os.makedirs(os.path.dirname(dst_path), exist_ok=True)
        data = self._call("GET", self._blob_path(rel))
        with open(dst_path, "wb") as f:
            f.write(data)

    def delete(self, rel: str):
        try:
            self._call("DELETE", self._blob_path(rel))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


def open_remote(dst: str, **kw) -> RemoteFS:
    if dst.startswith("fs://"):
        return FsRemote(dst[5:])
    if dst.startswith("s3://"):
        rest = dst[5:]
        bucket, _, prefix = rest.partition("/")
        return S3Remote(bucket, prefix, **kw)
    for scheme in ("gs://", "gcs://"):
        if dst.startswith(scheme):
            rest = dst[len(scheme):]
            bucket, _, prefix = rest.partition("/")
            return GcsRemote(bucket, prefix, **kw)
    if dst.startswith("azblob://"):
        rest = dst[9:]
        container, _, prefix = rest.partition("/")
        return AzblobRemote(container, prefix, **kw)
    raise ValueError(f"unsupported backup destination {dst!r} "
                     "(supported: fs://, s3://, gs://, azblob://)")


def _local_files(root: str) -> dict[str, int]:
    out = {}
    for dp, _, fns in os.walk(root):
        for fn in fns:
            full = os.path.join(dp, fn)
            out[os.path.relpath(full, root)] = os.path.getsize(full)
    return out


def backup(snapshot_path: str, remote: RemoteFS) -> dict:
    """Incremental: upload only new/changed files, delete removed ones
    (immutable parts mean same name+size => same content)."""
    local = _local_files(snapshot_path)
    existing = remote.list_files()
    uploaded = skipped = deleted = 0
    for rel, size in local.items():
        if existing.get(rel) == size:
            skipped += 1
            continue
        remote.upload(rel, os.path.join(snapshot_path, rel))
        uploaded += 1
    for rel in existing:
        if rel not in local:
            remote.delete(rel)
            deleted += 1
    logger.infof("backup: uploaded=%d skipped=%d deleted=%d",
                 uploaded, skipped, deleted)
    return {"uploaded": uploaded, "skipped": skipped, "deleted": deleted}


def restore(remote: RemoteFS, storage_data_path: str) -> dict:
    """Restore into an (empty or partial) storage dir, skipping files that
    already match (hardlink-reuse analog)."""
    local = _local_files(storage_data_path) if os.path.isdir(
        storage_data_path) else {}
    remote_files = remote.list_files()
    downloaded = skipped = removed = 0
    for rel, size in remote_files.items():
        if local.get(rel) == size:
            skipped += 1
            continue
        remote.download(rel, os.path.join(storage_data_path, rel))
        downloaded += 1
    for rel in local:
        if rel not in remote_files:
            os.unlink(os.path.join(storage_data_path, rel))
            removed += 1
    logger.infof("restore: downloaded=%d skipped=%d removed=%d",
                 downloaded, skipped, removed)
    return {"downloaded": downloaded, "skipped": skipped, "removed": removed}


def create_snapshot_via_http(addr: str) -> str:
    with urllib.request.urlopen(addr.rstrip("/") + "/snapshot/create",
                                timeout=60) as r:
        return json.loads(r.read())["snapshot"]


def main_backup(argv=None):
    p = argparse.ArgumentParser(prog="vmbackup")
    p.add_argument("-storageDataPath", required=True)
    p.add_argument("-snapshotName", default="")
    p.add_argument("-snapshot.createURL", dest="create_url", default="")
    p.add_argument("-dst", required=True)
    args, _ = p.parse_known_args(argv)
    name = args.snapshotName
    if not name and args.create_url:
        name = create_snapshot_via_http(args.create_url)
    if not name:
        raise SystemExit("need -snapshotName or -snapshot.createURL")
    snap = os.path.join(args.storageDataPath, "snapshots", name)
    backup(snap, open_remote(args.dst))


def main_restore(argv=None):
    p = argparse.ArgumentParser(prog="vmrestore")
    p.add_argument("-src", required=True)
    p.add_argument("-storageDataPath", required=True)
    args, _ = p.parse_known_args(argv)
    restore(open_remote(args.src), args.storageDataPath)


if __name__ == "__main__":
    main_backup()
