"""vmauth: auth proxy / load balancer (reference app/vmauth: YAML users with
url_map routing by src_paths, load-balancing across url_prefix lists,
basic-auth + bearer-token matching, unauthorized_user fallback)."""

from __future__ import annotations

import argparse
import base64
import itertools
import re
import signal
import threading
import urllib.parse
import urllib.request

from ..utils import logger


class Backend:
    """A url_prefix group with round-robin (least-loaded approximation)."""

    def __init__(self, prefixes):
        if isinstance(prefixes, str):
            prefixes = [prefixes]
        self.prefixes = [p.rstrip("/") for p in prefixes]
        self._rr = itertools.cycle(range(len(self.prefixes)))
        self._lock = threading.Lock()

    def pick(self) -> str:
        with self._lock:
            return self.prefixes[next(self._rr)]


class URLMapEntry:
    def __init__(self, cfg: dict):
        self.src_paths = [re.compile("(?:" + p + ")\\Z")
                          for p in cfg.get("src_paths", [])]
        self.src_hosts = [re.compile("(?:" + p + ")\\Z")
                          for p in cfg.get("src_hosts", [])]
        self.backend = Backend(cfg["url_prefix"])

    def matches(self, path: str, host: str) -> bool:
        if self.src_paths and not any(r.match(path) for r in self.src_paths):
            return False
        if self.src_hosts and not any(r.match(host) for r in self.src_hosts):
            return False
        return True


class User:
    def __init__(self, cfg: dict):
        self.username = cfg.get("username", "")
        self.password = cfg.get("password", "")
        self.bearer_token = cfg.get("bearer_token", "")
        # JWT auth (lib/jwt analog): HS* shared secrets and/or RS256 PEM
        # public keys; optional required claims, e.g. {"vm_access": ...}
        self.jwt_secrets = list(cfg.get("jwt_secrets", []) or [])
        self.jwt_public_keys = list(cfg.get("jwt_public_keys", []) or [])
        self.jwt_claims = dict(cfg.get("jwt_required_claims", {}) or {})
        self.name = cfg.get("name", self.username or "bearer")
        self.url_map = [URLMapEntry(m) for m in cfg.get("url_map", [])]
        self.default_backend = (Backend(cfg["url_prefix"])
                                if cfg.get("url_prefix") else None)
        self.max_concurrent = int(cfg.get("max_concurrent_requests", 0))
        self._sem = (threading.Semaphore(self.max_concurrent)
                     if self.max_concurrent else None)
        self.requests = 0

    def route(self, path: str, host: str) -> str | None:
        for entry in self.url_map:
            if entry.matches(path, host):
                return entry.backend.pick()
        if self.default_backend is not None:
            return self.default_backend.pick()
        return None


class AuthConfig:
    def __init__(self, cfg: dict):
        self.users = [User(u) for u in cfg.get("users", [])]
        uu = cfg.get("unauthorized_user")
        self.unauthorized_user = User(uu) if uu else None

    def find_user(self, headers) -> User | None:
        auth = headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            token = auth[7:]
            for u in self.users:
                if u.bearer_token and u.bearer_token == token:
                    return u
            if token.count(".") == 2:
                from ..utils.jwt import JWTError, verify
                for u in self.users:
                    if not (u.jwt_secrets or u.jwt_public_keys):
                        continue
                    try:
                        claims = verify(token, u.jwt_secrets,
                                        u.jwt_public_keys)
                    except JWTError:
                        continue
                    if all(claims.get(k) == v
                           for k, v in u.jwt_claims.items()):
                        return u
        if auth.startswith("Basic "):
            try:
                dec = base64.b64decode(auth[6:]).decode()
                name, _, pwd = dec.partition(":")
            except Exception:
                return None
            for u in self.users:
                if u.username == name and u.password == pwd:
                    return u
        return None


def build(args):
    import yaml

    from ..httpapi.server import HTTPServer, Request, Response

    cfg = yaml.safe_load(open(args.auth_config).read()) or {}
    auth = AuthConfig(cfg)
    hh, _, hp = args.httpListenAddr.rpartition(":")
    srv = HTTPServer(hh or "0.0.0.0", int(hp))

    def proxy(req: Request) -> Response:
        user = auth.find_user(req.headers)
        if user is None:
            user = auth.unauthorized_user
        if user is None:
            resp = Response.text("missing or invalid auth", 401)
            resp.headers["WWW-Authenticate"] = 'Basic realm="vmauth"'
            return resp
        host = req.headers.get("Host", "")
        target = user.route(req.path, host)
        if target is None:
            return Response.text("no route for path", 400)
        user.requests += 1
        if user._sem is not None and not user._sem.acquire(timeout=10):
            return Response.text("too many concurrent requests", 429)
        try:
            qs = ""
            if req.query:
                qs = "?" + urllib.parse.urlencode(
                    [(k, v) for k, vs in req.query.items() for v in vs])
            url = target + req.path + qs
            fwd = urllib.request.Request(
                url, data=req.body if req.method in ("POST", "PUT") else None,
                method=req.method)
            ct = req.headers.get("Content-Type")
            if ct:
                fwd.add_header("Content-Type", ct)
            try:
                with urllib.request.urlopen(fwd, timeout=60) as r:
                    return Response(r.status, r.read(),
                                    r.headers.get("Content-Type",
                                                  "application/json"))
            except urllib.error.HTTPError as e:
                return Response(e.code, e.read(),
                                e.headers.get("Content-Type", "text/plain"))
            except OSError as e:
                return Response.text(f"backend error: {e}", 502)
        finally:
            if user._sem is not None:
                user._sem.release()

    srv.route("/", proxy)  # prefix: everything
    srv.routes["/health"] = lambda req: Response.text("OK")
    return auth, srv


def parse_flags(argv=None):
    p = argparse.ArgumentParser(prog="vmauth")
    p.add_argument("-auth.config", dest="auth_config", required=True)
    p.add_argument("-httpListenAddr", default=":8427")
    p.add_argument("-loggerLevel", default="INFO")
    args, _ = p.parse_known_args(argv)
    return args


def main(argv=None):
    import faulthandler
    faulthandler.register(signal.SIGUSR1)
    args = parse_flags(argv)
    logger.set_level(args.loggerLevel)
    _auth, srv = build(args)
    srv.start()
    logger.infof("vmauth started: http=%d", srv.port)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.wait(1.0):
            pass
    finally:
        srv.stop()


if __name__ == "__main__":
    main()
