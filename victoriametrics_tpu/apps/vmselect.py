"""vmselect: query node (reference app/vmselect in cluster mode): the full
MetricsQL engine over a scatter-gather ClusterStorage backend, with
partial-result tracking (isPartial) and -search.denyPartialResponse."""

from __future__ import annotations

import argparse
import os
import signal
import threading

from ..utils import logger
from .vminsert import make_nodes


def parse_flags(argv=None):
    p = argparse.ArgumentParser(prog="vmselect")
    p.add_argument("-storageNode", action="append", default=[],
                   help="host:insertPort:selectPort, repeatable")
    p.add_argument("-httpListenAddr", default=":8481")
    p.add_argument("-search.denyPartialResponse", dest="deny_partial",
                   action="store_true")
    p.add_argument("-rpc.timeout", dest="rpc_timeout", type=float,
                   default=10.0)
    p.add_argument("-replicationFactor", dest="replication_factor",
                   type=int, default=1,
                   help="how many storage nodes hold each series (must "
                        "match vminsert): with RF=N, up to N-1 failed "
                        "nodes keep results complete (replica-covered) "
                        "instead of partial")
    p.add_argument("-search.tpuBackend", dest="tpu", action="store_true")
    p.add_argument("-search.maxUniqueTimeseries", dest="max_series",
                   type=int, default=300_000)
    p.add_argument("-search.maxSamplesPerQuery", dest="max_samples_per_query",
                   type=int, default=1_000_000_000)
    p.add_argument("-search.maxMemoryPerQuery", dest="max_memory_per_query",
                   type=int, default=0)
    p.add_argument("-search.maxQueryDuration", dest="max_query_duration",
                   default="30s")
    p.add_argument("-clusternativeListenAddr", dest="native_addr", default="",
                   help="expose the vmselect RPC API so a higher-level "
                        "vmselect can use this node as a storage backend "
                        "(multilevel federation)")
    p.add_argument("-selfScrapeInterval", dest="self_scrape_interval",
                   default="",
                   help="scrape own /metrics into the cluster every "
                        "interval (15s when set to 1); empty/0 = off")
    p.add_argument("-loggerLevel", default="INFO")
    args, _ = p.parse_known_args(argv)
    env = os.environ.get("VM_STORAGENODE")
    if env:
        args.storageNode = env.split(",")
    return args


def build(args):
    from ..httpapi.prometheus_api import PrometheusAPI
    from ..httpapi.server import HTTPServer
    from ..parallel.cluster_api import ClusterStorage

    if not args.storageNode:
        raise SystemExit("vmselect: at least one -storageNode is required")
    cluster = ClusterStorage(
        make_nodes(args.storageNode, getattr(args, "rpc_timeout", 10.0)),
        replication_factor=getattr(args, "replication_factor", 1),
        deny_partial_response=args.deny_partial)
    hh, _, hp = args.httpListenAddr.rpartition(":")
    srv = HTTPServer(hh or "0.0.0.0", int(hp))
    from .vmsingle import _attach_tpu_engine, _dur_ms
    api = PrometheusAPI(
        cluster, None, max_series=args.max_series,
        max_samples_per_query=args.max_samples_per_query,
        max_memory_per_query=args.max_memory_per_query,
        max_query_duration_ms=_dur_ms(args.max_query_duration))
    _attach_tpu_engine(api, args.tpu)
    api.register(srv, mode="select")
    from ..parallel.cluster_api import register_cluster_admin
    register_cluster_admin(srv, cluster)
    from ..utils import profiler
    profiler.ensure_started()
    # self-monitoring plane: own registry -> cluster write path (sharded
    # + rerouted like any ingested series); SLO evals ride the tick
    from ..utils import selfscrape
    api.selfscraper = selfscrape.maybe_start(
        cluster.add_rows, "vmselect", int(hp),
        flag_value=args.self_scrape_interval, extra=api.app_metrics,
        on_tick=lambda now_ms: api.init_sloplane().maybe_eval(now_ms))
    from ..httpapi.graphite_api import GraphiteAPI
    GraphiteAPI(cluster).register(srv)
    native_srv = None
    if getattr(args, "native_addr", ""):
        from ..parallel.cluster_api import start_native_server
        from ..parallel.rpc import HELLO_SELECT
        native_srv = start_native_server(args.native_addr, HELLO_SELECT,
                                         cluster)
    return cluster, srv, api, native_srv


def main(argv=None):
    import faulthandler
    faulthandler.register(signal.SIGUSR1)
    args = parse_flags(argv)
    logger.set_level(args.loggerLevel)
    cluster, srv, _api, native_srv = build(args)
    srv.start()
    logger.infof("vmselect started: nodes=%d http=%d", len(cluster.nodes),
                 srv.port)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.wait(1.0):
            pass
    finally:
        srv.stop()
        if getattr(_api, "selfscraper", None) is not None:
            _api.selfscraper.stop()
        if native_srv is not None:
            native_srv.stop()
        cluster.close()
        logger.infof("vmselect: shutdown complete")


if __name__ == "__main__":
    main()
