"""NumPy reference semantics for windowed rollup functions.

This module is the ORACLE: it defines, in plain NumPy over one series at a
time, the exact semantics of each rollup function. The TPU kernels in
ops/device_rollup.py must match it bit-for-bit (up to float assoc order), and
the host fallback path uses it directly.

Semantics follow the reference's rollup model (app/vmselect/promql/
rollup.go:688-960, doInternal window walk + removeCounterResets): for each
output timestamp ``t`` in [start, end] stepping by ``step``, the window is
``(t - window, t]``. Functions additionally see the "real previous value" —
the last sample at or before the window start — which powers
delta/increase/rate continuity across windows. Empty windows yield NaN
(gap semantics); staleness markers end a series segment.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .decimal import STALE_NAN_BITS


@dataclasses.dataclass(frozen=True)
class RollupConfig:
    """Static window grid: all values unix ms."""
    start: int
    end: int
    step: int
    window: int  # lookbehind; 0 means "use step"

    @property
    def lookback(self) -> int:
        return self.window if self.window > 0 else self.step

    def out_timestamps(self) -> np.ndarray:
        return np.arange(self.start, self.end + 1, self.step, dtype=np.int64)


def scrape_interval_estimate(ts: np.ndarray, default_ms: int) -> int:
    """0.6 quantile of the last 20 sample intervals (rollup.go:871
    getScrapeInterval)."""
    if ts.size < 2:
        return default_ms
    tail = ts[-21:]
    intervals = np.diff(tail).astype(np.float64)
    if intervals.size == 0:
        return default_ms
    si = int(np.quantile(intervals, 0.6))
    return si if si > 0 else default_ms


def max_prev_interval(scrape_interval: int) -> int:
    """Jitter headroom over the scrape interval (rollup.go:899
    getMaxPrevInterval)."""
    si = scrape_interval
    if si <= 2_000:
        return si + 4 * si
    if si <= 4_000:
        return si + 2 * si
    if si <= 8_000:
        return si + si
    if si <= 16_000:
        return si + si // 2
    if si <= 32_000:
        return si + si // 4
    return si + si // 8


def _max_prev_interval_for(ts: np.ndarray, cfg: "RollupConfig") -> int:
    """rollup.go:720-728: instant queries use step; range queries estimate
    the scrape interval and inflate it for jitter tolerance. The sample just
    before the window seeds prevValue only when it is within this interval
    of the window start."""
    if cfg.start >= cfg.end:
        return cfg.step
    return max_prev_interval(scrape_interval_estimate(ts, cfg.step))


def scrape_interval_estimate_batch(ts2: np.ndarray, counts: np.ndarray,
                                   default_ms: int) -> np.ndarray:
    """Vectorized scrape_interval_estimate over padded (S, N) rows —
    bit-compatible with the scalar version (same 0.6-quantile with numpy's
    linear interpolation, same int() truncation)."""
    S, N = ts2.shape
    k = np.minimum(counts, 21)                    # tail length per row
    start = counts - k
    idx = np.clip(start[:, None] + np.arange(21)[None, :], 0, max(N - 1, 0))
    tail = np.take_along_axis(ts2, idx, axis=1)
    iv = np.diff(tail, axis=1).astype(np.float64)  # (S, 20)
    n_iv = k - 1                                   # valid intervals per row
    valid = np.arange(20)[None, :] < n_iv[:, None]
    iv = np.where(valid, iv, np.inf)
    iv.sort(axis=1)
    m = np.maximum(n_iv, 1).astype(np.float64)
    pos = 0.6 * (m - 1)
    flo = np.floor(pos).astype(np.int64)
    frac = pos - flo
    a = np.take_along_axis(iv, np.clip(flo, 0, 19)[:, None], axis=1)[:, 0]
    b = np.take_along_axis(iv, np.clip(flo + 1, 0, 19)[:, None],
                           axis=1)[:, 0]
    # replicate numpy's _lerp branch (t >= 0.5 computes from b) bit-exactly
    with np.errstate(invalid="ignore"):
        b = np.where(frac > 0, b, a)
        d = b - a
        q = np.where(frac >= 0.5, b - d * (1.0 - frac), a + d * frac)
    si = np.where(np.isfinite(q), q, 0.0).astype(np.int64)
    return np.where((counts < 2) | (n_iv < 1) | (si <= 0), default_ms, si)


def max_prev_interval_batch(si: np.ndarray) -> np.ndarray:
    """Vectorized max_prev_interval (rollup.go:899)."""
    si = np.asarray(si, dtype=np.int64)
    extra = np.select(
        [si <= 2_000, si <= 4_000, si <= 8_000, si <= 16_000, si <= 32_000],
        [4 * si, 2 * si, si, si // 2, si // 4], si // 8)
    return si + extra



def remove_counter_resets(values: np.ndarray) -> np.ndarray:
    """Monotonize a counter series: whenever v[i] < v[i-1] (reset), add the
    lost base back so deltas across resets count from the reset value
    (rollup.go:921 removeCounterResets analog). A drop where the new value
    is still >1/8 of the previous one is a "partial reset" (only the lost
    amount is added back); otherwise it's a full reset (the whole previous
    value is added back, so the counter continues from where it was)."""
    v = np.asarray(values, dtype=np.float64)
    if v.size == 0 or v.shape[-1] == 0:
        return v.copy()
    if v.ndim <= 2:
        try:  # single-pass native kernel (bit-exact with the path below)
            from .. import native as _native
            if _native.available():
                return _native.counter_resets_2d(v)
        except (ImportError, OSError, AttributeError, ValueError):
            pass  # any native-layer trouble falls back to the numpy path
    d = np.diff(v, axis=-1)
    prev = v[..., :-1]
    drop = np.where(d < 0, np.where(-d * 8 < prev, -d, prev), 0.0)
    # reset correction: cumulative sum of drops, shifted to apply from the
    # resetting sample onward
    zeros = np.zeros(v.shape[:-1] + (1,), dtype=np.float64)
    corr = np.concatenate([zeros, np.cumsum(drop, axis=-1)], axis=-1)
    return v + corr


def _new_series_base(w: np.ndarray) -> float:
    """delta/increase baseline for a series whose first sample lies INSIDE
    the window (no sample precedes it): assume the counter was born at 0 —
    a histogram bucket or error counter appearing at value k carries k
    events — unless the first value dwarfs the first in-window step, which
    marks an already-running counter surfacing mid-window (churn, index
    rotation); then it is the baseline (rollup.go:2129 rollupDelta)."""
    d = float(w[1] - w[0]) if w.size > 1 else 0.0
    return 0.0 if abs(w[0]) < 10.0 * (abs(d) + 1.0) else float(w[0])


def _window_bounds(ts: np.ndarray, cfg: RollupConfig) -> tuple[np.ndarray, np.ndarray]:
    """Per output step: [start_idx, end_idx) half-open index range of samples
    inside (t-window, t]."""
    out_ts = cfg.out_timestamps()
    lo = np.searchsorted(ts, out_ts - cfg.lookback, side="right")
    hi = np.searchsorted(ts, out_ts, side="right")
    return lo, hi


def rollup(func: str, ts: np.ndarray, values: np.ndarray, cfg: RollupConfig
           ) -> np.ndarray:
    """Apply one rollup function over a single series. ts must be sorted."""
    ts = np.asarray(ts, dtype=np.int64)
    v = np.asarray(values, dtype=np.float64)
    out_ts = cfg.out_timestamps()
    T = out_ts.size
    out = np.full(T, np.nan)
    lo, hi = _window_bounds(ts, cfg)
    have = hi > lo

    if func in ("count_over_time", "present_over_time", "changes"):
        pass  # handled below without needing per-window values

    corrected = remove_counter_resets(v) if func in (
        "rate", "increase", "irate", "increase_pure") else v

    # prevValue gating (rollup.go:781): the sample before the window seeds
    # prevValue only when within maxPrevInterval of the window start. The
    # delta/increase/changes family keeps the ungated sample — it doubles as
    # realPrevValue, which rollup.go uses ungated when LookbackDelta is 0.
    mpi = _max_prev_interval_for(ts, cfg)

    for j in range(T):
        a, b = lo[j], hi[j]
        prev_idx = a - 1  # last sample at or before window start (realPrev)
        gated_prev = prev_idx if (
            prev_idx >= 0 and ts[prev_idx] > out_ts[j] - cfg.lookback - mpi
        ) else -1
        if func == "count_over_time":
            out[j] = (b - a) if b > a else np.nan
            continue
        if func == "present_over_time":
            out[j] = 1.0 if b > a else np.nan
            continue
        if not have[j]:
            continue
        w = v[a:b]
        cw = corrected[a:b]
        tw = ts[a:b]
        if func == "sum_over_time":
            out[j] = w.sum()
        elif func == "min_over_time":
            out[j] = w.min()
        elif func == "max_over_time":
            out[j] = w.max()
        elif func == "avg_over_time":
            out[j] = w.mean()
        elif func == "stddev_over_time":
            out[j] = w.std()
        elif func == "stdvar_over_time":
            out[j] = w.var()
        elif func == "first_over_time":
            out[j] = w[0]
        elif func == "last_over_time" or func == "default_rollup":
            out[j] = w[-1]
        elif func == "tfirst_over_time":
            out[j] = tw[0] / 1e3
        elif func == "tlast_over_time" or func == "timestamp":
            out[j] = tw[-1] / 1e3
        elif func == "changes":
            prev = v[prev_idx] if prev_idx >= 0 else None
            seq = w if prev is None else np.concatenate([[prev], w])
            out[j] = float((np.diff(seq) != 0).sum())
            if prev is None and w.size:
                out[j] += 0  # first appearance is not a change
        elif func == "delta":
            base = v[prev_idx] if prev_idx >= 0 else _new_series_base(w)
            out[j] = w[-1] - base
        elif func in ("increase", "increase_pure"):
            if prev_idx >= 0:
                base = corrected[prev_idx]
            elif func == "increase_pure":
                base = 0.0  # rollup.go:2169 rollupIncreasePure
            else:
                base = _new_series_base(cw)
            out[j] = cw[-1] - base
        elif func == "rate":
            if gated_prev >= 0:
                dt = (tw[-1] - ts[gated_prev]) / 1e3
                dv = cw[-1] - corrected[gated_prev]
            elif b - a >= 2:
                dt = (tw[-1] - tw[0]) / 1e3
                dv = cw[-1] - cw[0]
            else:
                continue
            out[j] = dv / dt if dt > 0 else np.nan
        elif func == "irate":
            if b - a >= 2:
                dt = (tw[-1] - tw[-2]) / 1e3
                dv = cw[-1] - cw[-2]
            elif gated_prev >= 0:
                dt = (tw[-1] - ts[gated_prev]) / 1e3
                dv = cw[-1] - corrected[gated_prev]
            else:
                continue
            out[j] = dv / dt if dt > 0 else np.nan
        elif func == "idelta":
            if b - a >= 2:
                out[j] = w[-1] - w[-2]
            elif gated_prev >= 0:
                out[j] = w[-1] - v[gated_prev]
        elif func == "deriv_fast":
            if gated_prev >= 0:
                dt = (tw[-1] - ts[gated_prev]) / 1e3
                out[j] = (w[-1] - v[gated_prev]) / dt if dt > 0 else np.nan
            elif b - a >= 2:
                dt = (tw[-1] - tw[0]) / 1e3
                out[j] = (w[-1] - w[0]) / dt if dt > 0 else np.nan
        elif func == "deriv":
            # least-squares slope per second over window samples
            if b - a >= 2:
                t_s = (tw - tw[0]) / 1e3
                n = t_s.size
                st, sv = t_s.sum(), w.sum()
                stt, stv = (t_s * t_s).sum(), (t_s * w).sum()
                den = n * stt - st * st
                out[j] = (n * stv - st * sv) / den if den != 0 else np.nan
        elif func == "lag":
            out[j] = (out_ts[j] - tw[-1]) / 1e3
        elif func == "lifetime":
            first = ts[0] if prev_idx >= 0 else tw[0]
            out[j] = (tw[-1] - first) / 1e3
        elif func == "scrape_interval":
            if prev_idx >= 0:
                out[j] = (tw[-1] - ts[prev_idx]) / 1e3 / (b - a)
            elif b - a >= 2:
                out[j] = (tw[-1] - tw[0]) / 1e3 / (b - a - 1)
        else:
            raise ValueError(f"unsupported numpy rollup func {func!r}")
    return out


# Core funcs: per-series oracle above + device kernels in
# ops/device_rollup (DEVICE_FUNCS there mirrors this tuple).
CORE_SUPPORTED = (
    "count_over_time", "present_over_time", "sum_over_time", "min_over_time",
    "max_over_time", "avg_over_time", "stddev_over_time", "stdvar_over_time",
    "first_over_time", "last_over_time", "default_rollup", "tfirst_over_time",
    "tlast_over_time", "timestamp", "changes", "delta", "increase",
    "increase_pure", "rate", "irate", "idelta", "deriv", "deriv_fast", "lag",
    "lifetime", "scrape_interval",
)

# Long-tail funcs vectorized ONLY in rollup_batch_packed (per-series
# semantics live in query/rollup_funcs.GENERIC_FUNCS; differential-tested
# side by side). Cumsum/gather formulations unless noted.
EXTENDED_SUPPORTED = (
    "sum2_over_time", "range_over_time", "geomean_over_time",
    "count_eq_over_time", "count_ne_over_time", "count_le_over_time",
    "count_gt_over_time", "share_eq_over_time", "share_le_over_time",
    "share_gt_over_time", "sum_eq_over_time", "sum_le_over_time",
    "sum_gt_over_time", "resets", "increases_over_time",
    "decreases_over_time", "ascent_over_time", "descent_over_time",
    "integrate", "duration_over_time", "rate_over_sum", "ideriv",
    "changes_prometheus", "delta_prometheus", "increase_prometheus",
    "rate_prometheus", "predict_linear", "zscore_over_time",
    "hoeffding_bound_lower", "hoeffding_bound_upper", "timestamp_with_name",
    # windowed order statistics (chunked (S, Tc, W) gather + nan-reductions)
    "quantile_over_time", "median_over_time", "mad_over_time",
    "iqr_over_time", "outlier_iqr_over_time", "tmin_over_time",
    "tmax_over_time", "distinct_over_time", "mode_over_time",
    "tlast_change_over_time",
)

# Every rollup the batched (vectorized multi-series) path understands.
SUPPORTED = CORE_SUPPORTED + EXTENDED_SUPPORTED

# exact positional-arg count per func (absent = 0 args)
ARG_COUNTS = {
    "quantile_over_time": 1, "count_eq_over_time": 1,
    "count_ne_over_time": 1, "count_le_over_time": 1,
    "count_gt_over_time": 1, "share_eq_over_time": 1,
    "share_le_over_time": 1, "share_gt_over_time": 1,
    "sum_eq_over_time": 1, "sum_le_over_time": 1, "sum_gt_over_time": 1,
    "predict_linear": 1, "hoeffding_bound_lower": 1,
    "hoeffding_bound_upper": 1,
}


def batch_supported(func: str, args: tuple = ()) -> bool:
    """True when rollup_batch/rollup_batch_packed can run (func, args):
    the eval gates call this instead of `not args and func in SUPPORTED`."""
    if func not in SUPPORTED:
        return False
    want = ARG_COUNTS.get(func, 0)
    if func == "duration_over_time":
        if len(args) > 1:
            return False
    elif len(args) != want:
        return False
    return all(isinstance(a, (int, float, np.integer, np.floating))
               for a in args)


def rollup_batch(func: str, series: list, cfg: RollupConfig,
                 args: tuple = ()):
    """Vectorized multi-series rollup: one (S, T) computation instead of a
    per-series/per-window Python loop — the host-side analog of the device
    tile kernels (ops/device_rollup.py). `series` is a list of (ts, values)
    pairs, each time-sorted.

    Returns an (S, T) float64 array, or None when the inputs need the exact
    per-series path (NaN values poison the cumsum formulation).
    Semantics are bit-compatible with rollup() above (tested side by side).
    """
    if func not in SUPPORTED:
        return None
    S = len(series)
    out_ts = cfg.out_timestamps()
    T = out_ts.size
    if S == 0:
        return np.full((0, T), np.nan)
    arrs_ts = [np.asarray(ts) for ts, _ in series]
    counts = np.fromiter((a.size for a in arrs_ts), dtype=np.int64, count=S)
    N = int(counts.max())
    if N == 0:
        return np.full((S, T), np.nan)
    pad = np.iinfo(np.int64).max
    if bool((counts == N).all()):
        # uniform lengths (the common scrape-grid case): one concatenate +
        # reshape instead of S row assignments
        ts2 = np.ascontiguousarray(
            np.concatenate(arrs_ts).reshape(S, N).astype(np.int64,
                                                         copy=False))
        v2 = np.concatenate([np.asarray(v, dtype=np.float64)
                             for _, v in series]).reshape(S, N)
    else:
        mask = np.arange(N)[None, :] < counts[:, None]
        ts2 = np.full((S, N), pad, dtype=np.int64)
        ts2[mask] = np.concatenate(arrs_ts)
        v2 = np.zeros((S, N), dtype=np.float64)
        v2[mask] = np.concatenate([np.asarray(v, dtype=np.float64)
                                   for _, v in series])
    return rollup_batch_packed(func, ts2, v2, counts, cfg, args)


def rollup_batch_packed(func: str, ts2: np.ndarray, v2: np.ndarray,
                        counts: np.ndarray, cfg: RollupConfig,
                        args: tuple = ()):
    """rollup_batch over pre-packed padded columns: ts2 (S, N) int64 padded
    with INT64_MAX, v2 (S, N) float64 (padding ignored), counts (S,).
    Entry point for callers that already hold packed columns (the columnar
    fetch path), skipping the per-series repack."""
    if not batch_supported(func, args):
        return None
    if func == "timestamp_with_name":
        func = "timestamp"  # same values; eval keeps the metric name
    S, N = ts2.shape
    out_ts = cfg.out_timestamps()
    T = out_ts.size
    if S == 0 or N == 0:
        return np.full((S, T), np.nan)
    # padding is 0.0 by layout contract, so one flat pass suffices.
    # NaN *and* +/-Inf poison the cumsum formulation (inf-inf = nan for
    # every window downstream); the per-series loop is exact
    if not np.isfinite(v2).all():
        return None

    w_lo = out_ts - cfg.lookback
    first_row = ts2[0]
    if bool((counts == counts[0]).all()) and \
            bool((ts2 == first_row[None, :]).all()):
        # every series shares one timestamp grid (common scrape schedule):
        # two searchsorteds total instead of 2*S
        row = first_row[:counts[0]]
        lo = np.broadcast_to(np.searchsorted(row, w_lo, side="right"),
                             (S, T))
        hi = np.broadcast_to(np.searchsorted(row, out_ts, side="right"),
                             (S, T))
    else:
        lo = np.empty((S, T), dtype=np.int64)
        hi = np.empty((S, T), dtype=np.int64)
        for s in range(S):
            row = ts2[s, :counts[s]]
            lo[s] = np.searchsorted(row, w_lo, side="right")
            hi[s] = np.searchsorted(row, out_ts, side="right")
    have = hi > lo
    nwin = hi - lo                       # samples per window
    prev = lo - 1                        # last sample at/before window start
    has_prev = prev >= 0

    def mpi_batch():
        # per-series maxPrevInterval prevValue gate for the deriv family —
        # must stay bit-compatible with rollup() above (same gating rule)
        if cfg.start >= cfg.end:
            return np.full(S, cfg.step, dtype=np.int64)
        return max_prev_interval_batch(
            scrape_interval_estimate_batch(ts2, counts, cfg.step))

    def gated_prev_mask():
        t_prev_raw = np.take_along_axis(ts2, np.clip(prev, 0, N - 1), axis=1)
        return has_prev & (t_prev_raw > w_lo[None, :] - mpi_batch()[:, None])

    out = np.full((S, T), np.nan)

    # flat-index gathers: np.take on precomputed flat indices is ~4x faster
    # than take_along_axis; index arrays repeat across gathers, so the flat
    # form is memoized per identity
    _row_base = (np.arange(S, dtype=np.int64) * N)[:, None]
    _flat_idx: dict = {}
    _flat_arr: dict = {}

    # the memo entries keep a reference to the KEY array: an id() of a freed
    # temporary could be recycled by a later allocation and serve stale
    # indices
    def _fidx(idx):
        hit = _flat_idx.get(id(idx))
        if hit is None:
            hit = (idx, np.clip(idx, 0, N - 1) + _row_base)
            _flat_idx[id(idx)] = hit
        return hit[1]

    def _farr(a):  # flat view; copies once iff the input is a sliced view
        hit = _flat_arr.get(id(a))
        if hit is None:
            hit = (a, np.ascontiguousarray(a).reshape(-1))
            _flat_arr[id(a)] = hit
        return hit[1]

    def gather(arr2d, idx, fill=0.0):
        return np.take(_farr(arr2d), _fidx(idx))

    last_i = np.clip(hi - 1, 0, N - 1)

    if func == "count_over_time":
        return np.where(nwin > 0, nwin.astype(np.float64), np.nan)
    if func == "present_over_time":
        return np.where(have, 1.0, np.nan)

    if func in ("sum_over_time", "avg_over_time", "stddev_over_time",
                "stdvar_over_time"):
        c1 = np.concatenate([np.zeros((S, 1)), np.cumsum(v2, axis=1)], axis=1)
        s1 = np.take_along_axis(c1, hi, axis=1) - \
            np.take_along_axis(c1, lo, axis=1)
        if func == "sum_over_time":
            return np.where(have, s1, np.nan)
        cnt = np.where(nwin > 0, nwin, 1).astype(np.float64)
        if func == "avg_over_time":
            return np.where(have, s1 / cnt, np.nan)
        # center per series before the E[x^2]-E[x]^2 cumsums: variance is
        # shift-invariant and this kills the catastrophic cancellation
        shift = v2[:, :1]
        vc = v2 - shift
        c1c = np.concatenate([np.zeros((S, 1)), np.cumsum(vc, axis=1)],
                             axis=1)
        s1c = np.take_along_axis(c1c, hi, axis=1) - \
            np.take_along_axis(c1c, lo, axis=1)
        c2 = np.concatenate([np.zeros((S, 1)), np.cumsum(vc * vc, axis=1)],
                            axis=1)
        s2 = np.take_along_axis(c2, hi, axis=1) - \
            np.take_along_axis(c2, lo, axis=1)
        var = np.maximum(s2 / cnt - (s1c / cnt) ** 2, 0.0)
        return np.where(have, np.sqrt(var) if func == "stddev_over_time"
                        else var, np.nan)

    if func in ("min_over_time", "max_over_time"):
        red = np.minimum if func == "min_over_time" else np.maximum
        fill = np.inf if func == "min_over_time" else -np.inf
        for s in range(S):
            # one pad element so hi == N is a valid reduceat index; [a,b)
            # pairs land on even slots, inter-window segments are discarded
            arr = np.concatenate([v2[s], [fill]])
            idx = np.stack([lo[s], hi[s]], axis=1).reshape(-1)
            r = red.reduceat(arr, idx)[::2]
            out[s] = np.where(have[s], r, np.nan)
        return out

    if func == "first_over_time":
        return np.where(have, gather(v2, lo), np.nan)
    if func in ("last_over_time", "default_rollup"):
        return np.where(have, gather(v2, last_i), np.nan)
    if func == "tfirst_over_time":
        return np.where(have, gather(ts2, lo) / 1e3, np.nan)
    if func in ("tlast_over_time", "timestamp"):
        return np.where(have, gather(ts2, last_i) / 1e3, np.nan)
    if func == "lag":
        return np.where(have, (out_ts[None, :] - gather(ts2, last_i)) / 1e3,
                        np.nan)
    if func == "lifetime":
        first = np.where(has_prev, ts2[:, :1], gather(ts2, lo))
        return np.where(have, (gather(ts2, last_i) - first) / 1e3, np.nan)
    if func == "scrape_interval":
        t_last = gather(ts2, last_i)
        t_prev = gather(ts2, np.maximum(prev, 0))
        t_first = gather(ts2, lo)
        with np.errstate(all="ignore"):
            r_prev = (t_last - t_prev) / 1e3 / nwin
            r_self = (t_last - t_first) / 1e3 / np.maximum(nwin - 1, 1)
        res = np.where(has_prev, r_prev,
                       np.where(nwin >= 2, r_self, np.nan))
        return np.where(have, res, np.nan)
    if func == "changes":
        ind = np.zeros((S, N))
        ind[:, 1:] = (np.diff(v2, axis=1) != 0).astype(np.float64)
        # mask changes into the padded region
        col = np.arange(N)[None, :]
        ind[col >= counts[:, None]] = 0.0
        cz = np.concatenate([np.zeros((S, 1)), np.cumsum(ind, axis=1)],
                            axis=1)  # cz[k] = sum ind[0..k-1], ind[0] = 0
        # window [a,b): with prev the compared pairs are i in [a,b), without
        # they are i in [1,b) — both reduce to cz[b] - cz[a]
        return np.where(have,
                        np.take_along_axis(cz, hi, axis=1) -
                        np.take_along_axis(cz, lo, axis=1), np.nan)

    # counter / derivative family — fused native window-walk when available
    # (reset-correction + two-pointer windows in one C pass per row)
    if func in ("rate", "increase", "increase_pure", "delta", "deriv_fast",
                "irate", "idelta"):
        try:
            from .. import native as _native
            has_native = _native.available()
        except Exception:
            has_native = False
        if has_native:
            mpi = (mpi_batch() if func in ("rate", "deriv_fast", "irate",
                                           "idelta")
                   else np.zeros(S, dtype=np.int64))  # ungated funcs
            return _native.rollup_counter_2d(
                func, ts2, v2, counts, cfg.start, cfg.end, cfg.step,
                cfg.lookback, mpi)

    # numpy fallback: each branch gathers only what it needs
    # (a gather is a full (S, T) pass — 9 unconditional ones dominated this
    # function's profile before)
    needs_reset = func in ("rate", "increase", "irate", "increase_pure")
    if needs_reset:
        cw2 = remove_counter_resets(v2)
    else:
        cw2 = v2
    pidx = np.maximum(prev, 0)

    with np.errstate(all="ignore"):
        if func in ("delta", "increase", "increase_pure"):
            arr = v2 if func == "delta" else cw2
            a_first = gather(arr, lo)
            if func == "increase_pure":
                nb = np.zeros_like(a_first)  # always born at 0
            else:
                # vectorized _new_series_base (see rollup() above)
                second = gather(arr, np.clip(lo + 1, 0, N - 1))
                d = np.where(nwin >= 2, second - a_first, 0.0)
                nb = np.where(np.abs(a_first) < 10.0 * (np.abs(d) + 1.0),
                              0.0, a_first)
            base = np.where(has_prev, gather(arr, pidx), nb)
            return np.where(have, gather(arr, last_i) - base, np.nan)
        if func in ("rate", "deriv_fast"):
            arr = cw2 if func == "rate" else v2
            has_gated_prev = gated_prev_mask()
            t_last = gather(ts2, last_i)
            a_last = gather(arr, last_i)
            dt = np.where(has_gated_prev, t_last - gather(ts2, pidx),
                          t_last - gather(ts2, lo)) / 1e3
            dv = np.where(has_gated_prev, a_last - gather(arr, pidx),
                          a_last - gather(arr, lo))
            ok = have & (has_gated_prev | (nwin >= 2))
            res = np.where(dt > 0, dv / dt, np.nan)
            return np.where(ok, res, np.nan)
        if func in ("irate", "idelta"):
            arr = cw2 if func == "irate" else v2
            has_gated_prev = gated_prev_mask()
            i2 = np.clip(hi - 2, 0, N - 1)
            a_last = gather(arr, last_i)
            a_pen = gather(arr, i2)
            a_prev = gather(arr, pidx)
            two = nwin >= 2
            if func == "idelta":
                res = np.where(two, a_last - a_pen,
                               np.where(has_gated_prev, a_last - a_prev,
                                        np.nan))
                return np.where(have, res, np.nan)
            t_last = gather(ts2, last_i)
            t_pen = gather(ts2, i2)
            t_prev = gather(ts2, pidx)
            dt = np.where(two, t_last - t_pen, t_last - t_prev) / 1e3
            dv = np.where(two, a_last - a_pen, a_last - a_prev)
            ok = have & (two | has_gated_prev)
            res = np.where(dt > 0, dv / dt, np.nan)
            return np.where(ok, res, np.nan)
        if func == "deriv":
            # least-squares slope; shift t by cfg.start for numerics
            t_rel = (ts2 - cfg.start) / 1e3
            t_rel = np.where(np.arange(N)[None, :] < counts[:, None],
                             t_rel, 0.0)
            ct = np.concatenate([np.zeros((S, 1)), np.cumsum(t_rel, axis=1)],
                                axis=1)
            ctt = np.concatenate([np.zeros((S, 1)),
                                  np.cumsum(t_rel * t_rel, axis=1)], axis=1)
            cv = np.concatenate([np.zeros((S, 1)), np.cumsum(v2, axis=1)],
                                axis=1)
            ctv = np.concatenate([np.zeros((S, 1)),
                                  np.cumsum(t_rel * v2, axis=1)], axis=1)

            def wsum(c):
                return (np.take_along_axis(c, hi, axis=1) -
                        np.take_along_axis(c, lo, axis=1))
            n = nwin.astype(np.float64)
            st, sv, stt, stv = wsum(ct), wsum(cv), wsum(ctt), wsum(ctv)
            den = n * stt - st * st
            res = np.where(den != 0, (n * stv - st * sv) / den, np.nan)
            return np.where(have & (nwin >= 2), res, np.nan)

    # ---- long-tail family (GENERIC_FUNCS semantics, vectorized) ----------
    # Per-series twins: query/rollup_funcs.py window callables run under
    # generic_rollup, whose prevValue is mpi-gated — every prev use below
    # goes through gated_prev_mask() to match bit-for-bit.
    validc = np.arange(N)[None, :] < counts[:, None]

    def cum0(x):
        return np.concatenate([np.zeros((S, 1)), np.cumsum(x, axis=1)],
                              axis=1)

    def wsum_of(c):
        return (np.take_along_axis(c, hi, axis=1) -
                np.take_along_axis(c, lo, axis=1))

    def window_min_max():
        mn_w = np.empty((S, T))
        mx_w = np.empty((S, T))
        for s in range(S):
            arr_mn = np.concatenate([v2[s], [np.inf]])
            arr_mx = np.concatenate([v2[s], [-np.inf]])
            idx = np.stack([lo[s], hi[s]], axis=1).reshape(-1)
            mn_w[s] = np.minimum.reduceat(arr_mn, idx)[::2]
            mx_w[s] = np.maximum.reduceat(arr_mx, idx)[::2]
        return mn_w, mx_w

    with np.errstate(all="ignore"):
        if func == "sum2_over_time":
            return np.where(have, wsum_of(cum0(v2 * v2)), np.nan)

        if func == "range_over_time":
            mn_w, mx_w = window_min_max()
            return np.where(have, mx_w - mn_w, np.nan)

        if func in ("count_eq_over_time", "count_ne_over_time",
                    "count_le_over_time", "count_gt_over_time",
                    "share_eq_over_time", "share_le_over_time",
                    "share_gt_over_time", "sum_eq_over_time",
                    "sum_le_over_time", "sum_gt_over_time"):
            x = float(args[0])
            kind = func.split("_")[1]
            ind = {"eq": v2 == x, "ne": v2 != x, "le": v2 <= x,
                   "gt": v2 > x}[kind] & validc
            if func.startswith("sum_"):
                s = wsum_of(cum0(np.where(ind, v2, 0.0)))
            else:
                s = wsum_of(cum0(ind.astype(np.float64)))
                if func.startswith("share_"):
                    s = s / np.where(nwin > 0, nwin, 1)
            return np.where(have, s, np.nan)

        if func in ("resets", "increases_over_time", "decreases_over_time",
                    "ascent_over_time", "descent_over_time"):
            d = np.diff(v2, axis=1)
            e = np.zeros((S, N))
            if func in ("resets", "decreases_over_time"):
                e[:, 1:] = (d < 0).astype(np.float64)
            elif func == "increases_over_time":
                e[:, 1:] = (d > 0).astype(np.float64)
            elif func == "ascent_over_time":
                e[:, 1:] = np.maximum(d, 0.0)
            else:  # descent_over_time
                e[:, 1:] = np.maximum(-d, 0.0)
            e[~validc] = 0.0
            ce = cum0(e)
            gprev = gated_prev_mask()
            start = np.minimum(lo + np.where(gprev, 0, 1), hi)
            s = np.take_along_axis(ce, hi, axis=1) - \
                np.take_along_axis(ce, start, axis=1)
            return np.where(have, s, np.nan)

        if func == "integrate":
            # e[i] = v[i-1] * dt(i-1, i): the prev-pair term rides e[lo]
            e = np.zeros((S, N))
            e[:, 1:] = v2[:, :-1] * (np.diff(ts2, axis=1) / 1e3)
            e[~validc] = 0.0
            ce = cum0(e)
            gprev = gated_prev_mask()
            start = np.minimum(lo + np.where(gprev, 0, 1), hi)
            s = np.take_along_axis(ce, hi, axis=1) - \
                np.take_along_axis(ce, start, axis=1)
            return np.where(have, s, np.nan)

        if func == "duration_over_time":
            e = np.zeros((S, N))
            dms = np.diff(ts2, axis=1).astype(np.float64)
            if args:
                dms = np.where(dms <= float(args[0]) * 1e3, dms, 0.0)
            e[:, 1:] = dms / 1e3
            e[~validc] = 0.0
            ce = cum0(e)
            start = np.minimum(lo + 1, hi)  # strictly in-window pairs
            s = np.take_along_axis(ce, hi, axis=1) - \
                np.take_along_axis(ce, start, axis=1)
            return np.where(have, s, np.nan)

        if func == "rate_over_sum":
            s1 = wsum_of(cum0(v2))
            gprev = gated_prev_mask()
            t_last = gather(ts2, last_i)
            t_base = np.where(gprev, gather(ts2, pidx), gather(ts2, lo))
            dt = (t_last - t_base) / 1e3
            return np.where(have & (dt > 0), s1 / dt, np.nan)

        if func == "geomean_over_time":
            if bool(((v2 == 0) & validc).any()):
                return None  # log-sum form breaks on zeros: per-series path
            lg = np.where(validc, np.log(np.abs(v2)), 0.0)
            s = wsum_of(cum0(lg))
            return np.where(have,
                            np.exp(s / np.where(nwin > 0, nwin, 1)), np.nan)

        if func == "ideriv":
            i2 = np.clip(hi - 2, 0, N - 1)
            two = nwin >= 2
            v_last = gather(v2, last_i)
            t_last = gather(ts2, last_i)
            dt2 = (t_last - gather(ts2, i2)) / 1e3
            dv2 = v_last - gather(v2, i2)
            gprev = gated_prev_mask()
            dt1 = (t_last - gather(ts2, pidx)) / 1e3
            dv1 = v_last - gather(v2, pidx)
            r2 = np.where(dt2 > 0, dv2 / dt2, np.nan)
            r1 = np.where(dt1 > 0, dv1 / dt1, np.nan)
            res = np.where(two, r2,
                           np.where((nwin == 1) & gprev, r1, np.nan))
            return np.where(have, res, np.nan)

        if func == "changes_prometheus":
            ind = np.zeros((S, N))
            ind[:, 1:] = (np.diff(v2, axis=1) != 0).astype(np.float64)
            ind[~validc] = 0.0
            cz = cum0(ind)
            start = np.minimum(lo + 1, hi)
            s = np.take_along_axis(cz, hi, axis=1) - \
                np.take_along_axis(cz, start, axis=1)
            return np.where(have, s, np.nan)

        if func in ("delta_prometheus", "increase_prometheus",
                    "rate_prometheus"):
            arr = v2 if func == "delta_prometheus" \
                else remove_counter_resets(v2)
            d = gather(arr, last_i) - gather(arr, lo)
            if func == "rate_prometheus":
                d = d / (cfg.lookback / 1e3)
            return np.where(have & (nwin >= 2), d, np.nan)

        if func == "predict_linear":
            t_rel = np.where(validc, (ts2 - cfg.start) / 1e3, 0.0)
            vv = np.where(validc, v2, 0.0)
            ct_, ctt = cum0(t_rel), cum0(t_rel * t_rel)
            cv_, ctv = cum0(vv), cum0(t_rel * vv)
            n = nwin.astype(np.float64)
            st, sv = wsum_of(ct_), wsum_of(cv_)
            stt, stv = wsum_of(ctt), wsum_of(ctv)
            den = n * stt - st * st
            k = np.where(den != 0, (n * stv - st * sv) / den, np.nan)
            u0 = gather(ts2, lo)
            b = sv / np.where(n > 0, n, 1) - \
                k * (st / np.where(n > 0, n, 1) - (u0 - cfg.start) / 1e3)
            dt = (out_ts[None, :] - u0) / 1e3 + float(args[0])
            res = k * dt + b
            return np.where(have & (nwin >= 2) & (den != 0), res, np.nan)

        if func == "zscore_over_time":
            s1 = wsum_of(cum0(v2))
            n = np.where(nwin > 0, nwin, 1).astype(np.float64)
            avg = s1 / n
            shift = v2[:, :1]
            vc = np.where(validc, v2 - shift, 0.0)
            s1c = wsum_of(cum0(vc))
            s2c = wsum_of(cum0(vc * vc))
            var = np.maximum(s2c / n - (s1c / n) ** 2, 0.0)
            sd = np.sqrt(var)
            v_last = gather(v2, last_i)
            t_last = gather(ts2, last_i)
            gprev = gated_prev_mask()
            t_first = gather(ts2, lo)
            # scrape interval per _w_zscore: prev -> (t_last-pt)/n over n
            # samples; else (t_last-t[0])/(n-1), needing >= 2 samples
            si = np.where(gprev, (t_last - gather(ts2, pidx)) / 1e3 / n,
                          (t_last - t_first) / 1e3 /
                          np.maximum(nwin - 1, 1))
            lag = (out_ts[None, :] - t_last) / 1e3
            ok = have & (gprev | (nwin >= 2)) & (lag <= si)
            d = v_last - avg
            res = np.where(d == 0, 0.0, np.where(sd > 0, d / sd, np.nan))
            return np.where(ok, res, np.nan)

        if func in ("hoeffding_bound_lower", "hoeffding_bound_upper"):
            phi = float(args[0])
            s1 = wsum_of(cum0(v2))
            n = np.where(nwin > 0, nwin, 1).astype(np.float64)
            avg = s1 / n
            mn_w, mx_w = window_min_max()
            rng = mx_w - mn_w
            if 0 < phi < 1:
                bound = np.where(
                    (nwin >= 2) & (rng != 0),
                    rng * np.sqrt(np.log(1.0 / (1 - phi)) / (2 * n)), 0.0)
            else:
                bound = np.zeros((S, T))
            if func == "hoeffding_bound_lower":
                res = np.maximum(avg - bound, 0.0)
            else:
                res = avg + bound
            return np.where(have, res, np.nan)

        if func in ("quantile_over_time", "median_over_time",
                    "mad_over_time", "iqr_over_time",
                    "outlier_iqr_over_time", "tmin_over_time",
                    "tmax_over_time", "distinct_over_time",
                    "mode_over_time", "tlast_change_over_time"):
            return _order_stat_batch(func, args, ts2, v2, counts, cfg,
                                     out_ts, lo, hi, nwin, have, last_i,
                                     pidx, gated_prev_mask, gather)

    return None


def _order_stat_batch(func, args, ts2, v2, counts, cfg, out_ts, lo, hi,
                      nwin, have, last_i, pidx, gated_prev_mask, gather):
    """Windowed order statistics: windows are materialized as a chunked
    (S, Tc, W) gather (NaN-padded) and reduced with nan-aware numpy ops —
    the vectorized analog of per-window np.quantile/unique loops. Chunks
    are sized to a flat element budget so wide windows degrade to smaller
    T slices instead of blowing memory."""
    S, N = ts2.shape
    T = out_ts.size
    phi = None
    if func == "quantile_over_time":
        phi = float(args[0])
        if phi < 0:
            return np.where(have, -np.inf, np.nan)
        if phi > 1:
            return np.where(have, np.inf, np.nan)
    out = np.full((S, T), np.nan)
    col_w = nwin.max(axis=0)  # worst-case window width per output step
    budget = 4_000_000  # flat elements per chunk (~32MB f64)
    t0 = 0
    import warnings
    with np.errstate(all="ignore"), warnings.catch_warnings():
        # empty windows are legitimately all-NaN slices; `have` masks them
        warnings.simplefilter("ignore", RuntimeWarning)
        while t0 < T:
            w = int(col_w[t0])
            t1 = t0 + 1
            wmax = max(w, 1)
            while t1 < T:
                nw = max(wmax, int(col_w[t1]))
                if S * (t1 + 1 - t0) * nw > budget:
                    break
                wmax = nw
                t1 += 1
            tc = slice(t0, t1)
            if col_w[tc].max() == 0:
                t0 = t1
                continue
            idx = lo[:, tc, None] + np.arange(wmax)[None, None, :]
            valid = idx < hi[:, tc, None]
            flat = np.clip(idx, 0, N - 1) + \
                (np.arange(S, dtype=np.int64) * N)[:, None, None]
            wv = np.where(valid, np.take(v2.reshape(-1), flat), np.nan)
            _order_stat_chunk(func, phi, out, tc, wv, valid, ts2, flat,
                              v2, counts, lo, hi, nwin, have, last_i,
                              pidx, gated_prev_mask, gather)
            t0 = t1
    return np.where(have, out, np.nan)


def _order_stat_chunk(func, phi, out, tc, wv, valid, ts2, flat, v2,
                      counts, lo, hi, nwin, have, last_i, pidx,
                      gated_prev_mask, gather):
    S = out.shape[0]

    def q_sorted(sv, p):
        # np.quantile's linear interpolation over the first m valid (sorted)
        # entries per window; NaN padding sorts to the end. nanquantile
        # itself degrades to apply_along_axis on NaN-bearing 3-D input
        # (~1000x slower) — this is the vectorized equivalent.
        m = nwin[:, tc]
        pos = p * np.maximum(m - 1, 0)
        j0 = np.floor(pos).astype(np.int64)
        frac = pos - j0
        j1 = np.minimum(j0 + 1, np.maximum(m - 1, 0))
        a = np.take_along_axis(sv, j0[:, :, None], axis=2)[:, :, 0]
        b = np.take_along_axis(sv, j1[:, :, None], axis=2)[:, :, 0]
        return a * (1 - frac) + b * frac

    if func in ("quantile_over_time", "median_over_time"):
        out[:, tc] = q_sorted(np.sort(wv, axis=2),
                              phi if func == "quantile_over_time" else 0.5)
    elif func == "mad_over_time":
        med = q_sorted(np.sort(wv, axis=2), 0.5)
        out[:, tc] = q_sorted(np.sort(np.abs(wv - med[:, :, None]), axis=2),
                              0.5)
    elif func == "iqr_over_time":
        sv = np.sort(wv, axis=2)
        out[:, tc] = q_sorted(sv, 0.75) - q_sorted(sv, 0.25)
    elif func == "outlier_iqr_over_time":
        sv = np.sort(wv, axis=2)
        q25, q75 = q_sorted(sv, 0.25), q_sorted(sv, 0.75)
        iqr = 1.5 * (q75 - q25)
        v_last = gather(v2, last_i)[:, tc]
        hit = (v_last > q75 + iqr) | (v_last < q25 - iqr)
        out[:, tc] = np.where((nwin[:, tc] >= 2) & hit, v_last, np.nan)
    elif func in ("tmin_over_time", "tmax_over_time"):
        fill = np.inf if func == "tmin_over_time" else -np.inf
        wf = np.where(valid, wv, fill)
        j = (np.argmin(wf, axis=2) if func == "tmin_over_time"
             else np.argmax(wf, axis=2))
        tflat = np.take(ts2.reshape(-1),
                        np.take_along_axis(flat, j[:, :, None],
                                           axis=2)[:, :, 0])
        out[:, tc] = tflat / 1e3
    elif func == "distinct_over_time":
        sv = np.sort(wv, axis=2)  # NaN sorts to the end
        fresh = np.ones(sv.shape, bool)
        fresh[:, :, 1:] = sv[:, :, 1:] != sv[:, :, :-1]
        out[:, tc] = (fresh & ~np.isnan(sv)).sum(axis=2)
    elif func == "mode_over_time":
        sv = np.sort(wv, axis=2)
        W = sv.shape[2]
        newrun = np.ones(sv.shape, bool)
        newrun[:, :, 1:] = sv[:, :, 1:] != sv[:, :, :-1]
        pos = np.arange(W)
        first = np.maximum.accumulate(np.where(newrun, pos, 0), axis=2)
        # run length at each position's run start = (next run start) - start;
        # count for position i = i - first[i] + 1, max at the run's END
        cnt = pos[None, None, :] - first + 1
        cnt = np.where(np.isnan(sv), -1, cnt)
        j = np.argmax(cnt, axis=2)
        out[:, tc] = np.take_along_axis(sv, j[:, :, None], axis=2)[:, :, 0]
    elif func == "tlast_change_over_time":
        v_last = gather(v2, last_i)[:, tc]
        neq = valid & (wv != v_last[:, :, None])
        W = wv.shape[2]
        jj = np.where(neq, np.arange(W)[None, None, :], -1).max(axis=2)
        changed = jj >= 0
        tflat = np.take(ts2.reshape(-1),
                        np.take_along_axis(flat,
                                           np.clip(jj + 1, 0, W - 1)
                                           [:, :, None], axis=2)[:, :, 0])
        t_first = gather(ts2, lo)[:, tc]
        gprev = gated_prev_mask()[:, tc]
        pv = gather(v2, pidx)[:, tc]
        no_change_val = np.where(~gprev | (pv != v_last),
                                 t_first / 1e3, np.nan)
        out[:, tc] = np.where(changed, tflat / 1e3, no_change_val)
