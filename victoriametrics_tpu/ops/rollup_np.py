"""NumPy reference semantics for windowed rollup functions.

This module is the ORACLE: it defines, in plain NumPy over one series at a
time, the exact semantics of each rollup function. The TPU kernels in
ops/device_rollup.py must match it bit-for-bit (up to float assoc order), and
the host fallback path uses it directly.

Semantics follow the reference's rollup model (app/vmselect/promql/
rollup.go:688-960, doInternal window walk + removeCounterResets): for each
output timestamp ``t`` in [start, end] stepping by ``step``, the window is
``(t - window, t]``. Functions additionally see the "real previous value" —
the last sample at or before the window start — which powers
delta/increase/rate continuity across windows. Empty windows yield NaN
(gap semantics); staleness markers end a series segment.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .decimal import STALE_NAN_BITS


@dataclasses.dataclass(frozen=True)
class RollupConfig:
    """Static window grid: all values unix ms."""
    start: int
    end: int
    step: int
    window: int  # lookbehind; 0 means "use step"

    @property
    def lookback(self) -> int:
        return self.window if self.window > 0 else self.step

    def out_timestamps(self) -> np.ndarray:
        return np.arange(self.start, self.end + 1, self.step, dtype=np.int64)


def remove_counter_resets(values: np.ndarray) -> np.ndarray:
    """Monotonize a counter series: whenever v[i] < v[i-1] (reset), add the
    lost base back so deltas across resets count from the reset value
    (rollup.go:921 removeCounterResets analog). Every negative delta is
    treated as a full reset."""
    v = np.asarray(values, dtype=np.float64)
    if v.size == 0:
        return v.copy()
    d = np.diff(v)
    drop = np.where(d < 0, -d, 0.0)
    # reset correction: cumulative sum of drops, shifted to apply from the
    # resetting sample onward
    corr = np.concatenate([[0.0], np.cumsum(drop)])
    return v + corr


def _window_bounds(ts: np.ndarray, cfg: RollupConfig) -> tuple[np.ndarray, np.ndarray]:
    """Per output step: [start_idx, end_idx) half-open index range of samples
    inside (t-window, t]."""
    out_ts = cfg.out_timestamps()
    lo = np.searchsorted(ts, out_ts - cfg.lookback, side="right")
    hi = np.searchsorted(ts, out_ts, side="right")
    return lo, hi


def rollup(func: str, ts: np.ndarray, values: np.ndarray, cfg: RollupConfig
           ) -> np.ndarray:
    """Apply one rollup function over a single series. ts must be sorted."""
    ts = np.asarray(ts, dtype=np.int64)
    v = np.asarray(values, dtype=np.float64)
    out_ts = cfg.out_timestamps()
    T = out_ts.size
    out = np.full(T, np.nan)
    lo, hi = _window_bounds(ts, cfg)
    have = hi > lo

    if func in ("count_over_time", "present_over_time", "changes"):
        pass  # handled below without needing per-window values

    corrected = remove_counter_resets(v) if func in (
        "rate", "increase", "irate", "increase_pure") else v

    for j in range(T):
        a, b = lo[j], hi[j]
        prev_idx = a - 1  # last sample at or before window start
        if func == "count_over_time":
            out[j] = (b - a) if b > a else np.nan
            continue
        if func == "present_over_time":
            out[j] = 1.0 if b > a else np.nan
            continue
        if not have[j]:
            continue
        w = v[a:b]
        cw = corrected[a:b]
        tw = ts[a:b]
        if func == "sum_over_time":
            out[j] = w.sum()
        elif func == "min_over_time":
            out[j] = w.min()
        elif func == "max_over_time":
            out[j] = w.max()
        elif func == "avg_over_time":
            out[j] = w.mean()
        elif func == "stddev_over_time":
            out[j] = w.std()
        elif func == "stdvar_over_time":
            out[j] = w.var()
        elif func == "first_over_time":
            out[j] = w[0]
        elif func == "last_over_time" or func == "default_rollup":
            out[j] = w[-1]
        elif func == "tfirst_over_time":
            out[j] = tw[0] / 1e3
        elif func == "tlast_over_time" or func == "timestamp":
            out[j] = tw[-1] / 1e3
        elif func == "changes":
            prev = v[prev_idx] if prev_idx >= 0 else None
            seq = w if prev is None else np.concatenate([[prev], w])
            out[j] = float((np.diff(seq) != 0).sum())
            if prev is None and w.size:
                out[j] += 0  # first appearance is not a change
        elif func == "delta":
            base = v[prev_idx] if prev_idx >= 0 else w[0]
            out[j] = w[-1] - base
        elif func in ("increase", "increase_pure"):
            base = corrected[prev_idx] if prev_idx >= 0 else cw[0]
            out[j] = cw[-1] - base
        elif func == "rate":
            if prev_idx >= 0:
                dt = (tw[-1] - ts[prev_idx]) / 1e3
                dv = cw[-1] - corrected[prev_idx]
            elif b - a >= 2:
                dt = (tw[-1] - tw[0]) / 1e3
                dv = cw[-1] - cw[0]
            else:
                continue
            out[j] = dv / dt if dt > 0 else np.nan
        elif func == "irate":
            if b - a >= 2:
                dt = (tw[-1] - tw[-2]) / 1e3
                dv = cw[-1] - cw[-2]
            elif prev_idx >= 0:
                dt = (tw[-1] - ts[prev_idx]) / 1e3
                dv = cw[-1] - corrected[prev_idx]
            else:
                continue
            out[j] = dv / dt if dt > 0 else np.nan
        elif func == "idelta":
            if b - a >= 2:
                out[j] = w[-1] - w[-2]
            elif prev_idx >= 0:
                out[j] = w[-1] - v[prev_idx]
        elif func == "deriv_fast":
            if prev_idx >= 0:
                dt = (tw[-1] - ts[prev_idx]) / 1e3
                out[j] = (w[-1] - v[prev_idx]) / dt if dt > 0 else np.nan
            elif b - a >= 2:
                dt = (tw[-1] - tw[0]) / 1e3
                out[j] = (w[-1] - w[0]) / dt if dt > 0 else np.nan
        elif func == "deriv":
            # least-squares slope per second over window samples
            if b - a >= 2:
                t_s = (tw - tw[0]) / 1e3
                n = t_s.size
                st, sv = t_s.sum(), w.sum()
                stt, stv = (t_s * t_s).sum(), (t_s * w).sum()
                den = n * stt - st * st
                out[j] = (n * stv - st * sv) / den if den != 0 else np.nan
        elif func == "lag":
            out[j] = (out_ts[j] - tw[-1]) / 1e3
        elif func == "lifetime":
            first = ts[0] if prev_idx >= 0 else tw[0]
            out[j] = (tw[-1] - first) / 1e3
        elif func == "scrape_interval":
            if prev_idx >= 0:
                out[j] = (tw[-1] - ts[prev_idx]) / 1e3 / (b - a)
            elif b - a >= 2:
                out[j] = (tw[-1] - tw[0]) / 1e3 / (b - a - 1)
        else:
            raise ValueError(f"unsupported numpy rollup func {func!r}")
    return out


# Rollup functions the oracle (and thus the device kernels) understand.
SUPPORTED = (
    "count_over_time", "present_over_time", "sum_over_time", "min_over_time",
    "max_over_time", "avg_over_time", "stddev_over_time", "stdvar_over_time",
    "first_over_time", "last_over_time", "default_rollup", "tfirst_over_time",
    "tlast_over_time", "timestamp", "changes", "delta", "increase",
    "increase_pure", "rate", "irate", "idelta", "deriv", "deriv_fast", "lag",
    "lifetime", "scrape_interval",
)
