"""Bulk zigzag-varint codecs, fully vectorized in NumPy.

Capability parity with reference lib/encoding/int.go:107-470
(MarshalVarInt64s / UnmarshalVarInt64s bulk fast paths). The reference
hand-unrolls byte loops in Go; here both directions are expressed as dense
array ops (the encode builds an (n, 10) byte matrix and compacts it; the
decode reconstructs values with bitwise_or.reduceat over continuation-bit
groups), which is also the shape a TPU kernel of the same codec would take.
"""

from __future__ import annotations

import numpy as np


def bit_len_u64(u: np.ndarray) -> np.ndarray:
    """floor(log2(u))+1 for u>0, 0 for u==0 — without float round-off.
    Shared by the varint and nearest-delta codecs."""
    u = np.asarray(u, dtype=np.uint64)
    n = np.zeros(u.shape, dtype=np.int64)
    tmp = u.copy()
    for b in (32, 16, 8, 4, 2, 1):
        mask = tmp >= (np.uint64(1) << np.uint64(b))
        n = np.where(mask, n + b, n)
        tmp = np.where(mask, tmp >> np.uint64(b), tmp)
    return np.where(u == 0, 0, n + 1)


def zigzag_encode(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, dtype=np.int64)
    return ((x << np.int64(1)) ^ (x >> np.int64(63))).view(np.uint64)


def zigzag_decode(u: np.ndarray) -> np.ndarray:
    u = np.asarray(u, dtype=np.uint64)
    return ((u >> np.uint64(1)).view(np.int64)) ^ (-(u & np.uint64(1)).view(np.int64))


def marshal_varint64s(values: np.ndarray) -> bytes:
    """Encode int64 array as concatenated zigzag varints."""
    u = zigzag_encode(values)
    n = u.size
    if n == 0:
        return b""
    # Byte i of value v is (v >> 7i) & 0x7f, with the continuation bit set on
    # all but the last byte. Number of bytes = ceil(bitlen/7), min 1.
    shifts = (np.arange(10, dtype=np.uint64) * np.uint64(7))
    chunks = (u[:, None] >> shifts[None, :]) & np.uint64(0x7F)
    nbytes = np.maximum((bit_len_u64(u) + 6) // 7, 1)
    pos = np.arange(10)
    valid = pos[None, :] < nbytes[:, None]
    last = pos[None, :] == (nbytes - 1)[:, None]
    out = chunks | np.where(valid & ~last, np.uint64(0x80), np.uint64(0))
    return out[valid].astype(np.uint8).tobytes()


def unmarshal_varint64s(data: bytes, count: int | None = None) -> np.ndarray:
    """Decode concatenated zigzag varints into an int64 array."""
    b = np.frombuffer(data, dtype=np.uint8)
    if b.size == 0:
        return np.zeros(0, dtype=np.int64)
    cont = (b & 0x80) != 0
    if cont[-1]:
        # Unterminated trailing varint: without this check its bytes would be
        # silently OR-folded into the previous value.
        raise ValueError("varint: truncated trailing value")
    ends = np.flatnonzero(~cont)
    starts = np.empty_like(ends)
    starts[0] = 0
    starts[1:] = ends[:-1] + 1
    if ((ends - starts) >= 10).any():
        # int64 varints are at most 10 bytes; longer means corruption, and
        # uint64 shifts >= 64 would otherwise decode silently to garbage.
        raise ValueError("varint: too long encoded varint")
    # position of each byte within its value
    idx = np.arange(b.size, dtype=np.int64)
    start_per_byte = np.repeat(starts, ends - starts + 1)
    pos = idx - start_per_byte
    contrib = (b.astype(np.uint64) & np.uint64(0x7F)) << (pos.astype(np.uint64) * np.uint64(7))
    u = np.bitwise_or.reduceat(contrib, starts)
    vals = zigzag_decode(u)
    if count is not None and vals.size != count:
        raise ValueError(f"varint: expected {count} values, got {vals.size}")
    return vals


def marshal_varuint64(x: int) -> bytes:
    """Single unsigned varint (headers/metadata)."""
    out = bytearray()
    x = int(x)
    if x < 0:
        raise ValueError("negative varuint")
    while True:
        bb = x & 0x7F
        x >>= 7
        if x:
            out.append(bb | 0x80)
        else:
            out.append(bb)
            return bytes(out)


def unmarshal_varuint64(data: bytes, offset: int = 0) -> tuple[int, int]:
    """Decode one unsigned varint; returns (value, next_offset)."""
    x = 0
    shift = 0
    i = offset
    while True:
        if i >= len(data):
            raise ValueError("varuint: truncated")
        bb = data[i]
        i += 1
        x |= (bb & 0x7F) << shift
        if not bb & 0x80:
            return x, i
        shift += 7
        if shift > 70:
            raise ValueError("varuint: too long")
