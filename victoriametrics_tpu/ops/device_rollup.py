"""TPU rollup kernels: windowed rollups over (series, sample) tiles.

This is the device half of the query engine's north-star hot loop (the
reference's rollupConfig.doInternal window walk, rollup.go:688-825, and the
unpack+merge workers around it). Instead of a per-series sliding-window scan,
everything is expressed as dense, fixed-shape array ops XLA can fuse and tile:

- window endpoints: vmapped ``searchsorted`` over padded timestamp rows
  (the idx-hint binary search of rollup.go:825 becomes one batched gather)
- sum/count/avg/stddev/stdvar/deriv: cumulative-moment prefix sums, window
  value = cum[hi] - cum[lo]
- min/max: sparse-table RMQ (O(N log N) precompute, two gathers per window)
- counter resets: prefix sum of negative jumps (removeCounterResets,
  rollup.go:921, as an associative scan)
- rate/delta/increase continuity: "real previous value" = gather at lo-1

Inputs are padded ragged tiles:
  ts:     int32 [S, N]  sample timestamps, ms, relative to cfg.start,
                        padded with TS_PAD (must exceed any window bound)
  values: float  [S, N] padded with anything (masked via counts)
  counts: int32 [S]     valid samples per row

Empty windows produce NaN, matching the ops/rollup_np.py oracle, which this
module must agree with bit-for-bit up to float association order.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from .rollup_np import RollupConfig

TS_PAD = np.int32(2**31 - 1)

# Funcs whose output embeds absolute time: they read cfg.start and cannot
# run on a start-rebased grid.
TIME_VALUED_FUNCS = frozenset({"tfirst_over_time", "tlast_over_time",
                               "timestamp"})


def normalized_cfg(func: str, cfg: RollupConfig) -> RollupConfig:
    """Rebase the window grid to start=0 for kernel compilation: tile
    timestamps are already relative to cfg.start and the grid is relative,
    so two queries with the same span/step/window share one compiled
    executable. Without this every rolling dashboard refresh (start/end
    advance each time) would recompile — and would miss the mesh layer's
    memoized shard_map closures. Time-valued funcs keep the absolute cfg."""
    if func in TIME_VALUED_FUNCS or cfg.start == 0:
        return cfg
    return RollupConfig(start=0, end=cfg.end - cfg.start, step=cfg.step,
                        window=cfg.window)


def _valid_mask(counts: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.arange(n, dtype=jnp.int32)[None, :] < counts[:, None]


def _cum0(x: jnp.ndarray) -> jnp.ndarray:
    """Prefix sum with leading zero along axis 1: out[:, i] = sum(x[:, :i])."""
    return jnp.pad(jnp.cumsum(x, axis=1), ((0, 0), (1, 0)))


_BOUNDS_CHUNK = 256


def _window_bounds(ts: jnp.ndarray, cfg: RollupConfig) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (lo, hi) int32 [S, T]: half-open sample index range per output
    step, plus the relative output grid.

    Computed as a chunked compare-and-reduce over the sample axis
    (hi[s,t] = sum_i [ts[s,i] <= grid[t]]) instead of a vmapped binary
    search: XLA fuses the [S, chunk, T] comparison into the reduction so
    it runs at VPU rate, while searchsorted lowers to per-element while
    loops that serialize on TPU (measured 1.25s -> ~10ms at 8192x1984x355).
    """
    T = (cfg.end - cfg.start) // cfg.step + 1
    # int32 throughout: tile timestamps are rebased so the grid fits, and
    # this keeps the kernel independent of the jax_enable_x64 flag.
    grid = (jnp.arange(T, dtype=jnp.int32) * np.int32(cfg.step))
    lo_t = grid - np.int32(cfg.lookback)
    S, N = ts.shape
    ch = min(_BOUNDS_CHUNK, N)
    n_ch = (N + ch - 1) // ch
    tp = ts if n_ch * ch == N else jnp.pad(
        ts, ((0, 0), (0, n_ch * ch - N)), constant_values=TS_PAD)
    chunks = jnp.moveaxis(tp.reshape(S, n_ch, ch), 1, 0)  # [n_ch, S, ch]

    def body(carry, chunk):
        lo_a, hi_a = carry
        c = chunk[:, :, None]
        hi_a = hi_a + jnp.sum(c <= grid[None, None, :], axis=1,
                              dtype=jnp.int32)
        lo_a = lo_a + jnp.sum(c <= lo_t[None, None, :], axis=1,
                              dtype=jnp.int32)
        return (lo_a, hi_a), None

    zeros = jnp.zeros((S, T), jnp.int32)
    (lo, hi), _ = jax.lax.scan(body, (zeros, zeros), chunks)
    return lo, hi, grid


def _gather(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Row-wise gather: x [S, N], idx [S, T] -> [S, T], idx clipped."""
    idx = jnp.clip(idx, 0, x.shape[1] - 1)
    return jnp.take_along_axis(x, idx, axis=1)


def _rmq_tables(x: jnp.ndarray, op: Callable, pad_val) -> list[jnp.ndarray]:
    """Sparse-table RMQ precompute: tables[l][s, i] = op over x[s, i:i+2^l]."""
    n = x.shape[1]
    levels = max(int(np.ceil(np.log2(max(n, 1)))) + 1, 1)
    t = x
    tables = [t]
    for l in range(1, levels):
        half = 1 << (l - 1)
        shifted = jnp.concatenate(
            [t[:, half:], jnp.full((x.shape[0], half), pad_val, x.dtype)], axis=1)
        t = op(t, shifted)
        tables.append(t)
    return tables


def _rmq_query(tables: list[jnp.ndarray], lo: jnp.ndarray, hi: jnp.ndarray,
               op: Callable) -> jnp.ndarray:
    """Range op over [lo, hi) via two overlapping power-of-two windows."""
    length = jnp.maximum(hi - lo, 1)
    k = jnp.floor(jnp.log2(length.astype(jnp.float32))).astype(jnp.int32)
    k = jnp.clip(k, 0, len(tables) - 1)
    stacked = jnp.stack(tables)  # [L, S, N]
    S, T = lo.shape
    s_idx = jnp.arange(S, dtype=jnp.int32)[:, None]
    a = stacked[k, s_idx, jnp.clip(lo, 0, tables[0].shape[1] - 1)]
    b_pos = jnp.clip(hi - (1 << k), 0, tables[0].shape[1] - 1)
    b = stacked[k, s_idx, b_pos]
    return op(a, b)


def _remove_counter_resets(v: jnp.ndarray, valid: jnp.ndarray,
                           v0=None) -> jnp.ndarray:
    """Monotonize counters: add back the lost base at each reset (prefix sum
    of negative jumps). Pad positions contribute nothing.

    `v0` is the per-series REBASE offset when the tile holds rebased values
    (f32 tiles store v - v[0]; see tpu_engine f32 design): the
    reset-vs-correction threshold and the restarted base are defined on
    ABSOLUTE values (rollup.go:921 compares against the previous absolute
    sample), so both re-add v0. Classification happens in tile dtype — data
    within one ulp of the 8x-drop boundary may classify differently from
    the f64 host path (documented bound, tests/test_f32_tiles.py)."""
    vm = jnp.where(valid, v, 0.0)
    prev = jnp.concatenate([vm[:, :1], vm[:, :-1]], axis=1)
    pair_valid = valid & jnp.concatenate(
        [jnp.zeros_like(valid[:, :1]), valid[:, :-1]], axis=1)
    prev_abs = prev if v0 is None else prev + v0[:, None].astype(v.dtype)
    drop = jnp.where(pair_valid & (vm < prev),
                     jnp.where((prev - vm) * 8 < prev_abs, prev - vm,
                               prev_abs), 0.0)
    return v + jnp.cumsum(drop, axis=1)


def _max_prev_interval_tile(ts: jnp.ndarray, counts: jnp.ndarray,
                            cfg: RollupConfig, min_ts=None) -> jnp.ndarray:
    """Per-series maxPrevInterval [S], bit-compatible with
    rollup_np._max_prev_interval_for: 0.6 linear-interpolated quantile of the
    last <=20 sample intervals, inflated by the rollup.go:899 jitter table.
    Instant grids (start == end) use the step directly. Samples older than
    `min_ts` are excluded like the host's truncated fetch would."""
    S, N = ts.shape
    step = jnp.asarray(cfg.step, jnp.int32)
    if cfg.start >= cfg.end:
        return jnp.full((S,), step, dtype=jnp.int32)
    c = counts.astype(jnp.int32)
    base = jnp.clip(c - 21, 0, None)
    idx = base[:, None] + jnp.arange(21, dtype=jnp.int32)[None, :]
    tv = jnp.take_along_axis(ts, jnp.clip(idx, 0, N - 1), axis=1)
    valid = idx < c[:, None]
    if min_ts is not None:
        valid = valid & (tv >= jnp.int32(min_ts))
    # float32 is exact for interval magnitudes up to 2^24 ms (~4.6h) and
    # avoids the x64-truncation warning when jax_enable_x64 is off
    d = (tv[:, 1:] - tv[:, :-1]).astype(jnp.float32)
    dvalid = valid[:, 1:] & valid[:, :-1]
    n = dvalid.sum(axis=1)
    dsort = jnp.sort(jnp.where(dvalid, d, jnp.inf), axis=1)
    rank = (0.6 * jnp.maximum(n - 1, 0)).astype(jnp.float32)
    lo_i = jnp.floor(rank).astype(jnp.int32)
    hi_i = jnp.ceil(rank).astype(jnp.int32)
    v_lo = jnp.take_along_axis(dsort, lo_i[:, None], axis=1)[:, 0]
    v_hi = jnp.take_along_axis(dsort, hi_i[:, None], axis=1)[:, 0]
    q = v_lo + (rank - lo_i) * (v_hi - v_lo)
    # zero out the no-interval case BEFORE the int cast: inf -> int32
    # saturates to INT_MAX, which would sneak past the positivity guard
    si = jnp.where(n >= 1, q, 0.0).astype(jnp.int32)
    si = jnp.where(si > 0, si, step)
    mpi = jnp.select(
        [si <= 2_000, si <= 4_000, si <= 8_000, si <= 16_000, si <= 32_000],
        [si + 4 * si, si + 2 * si, si + si, si + si // 2, si + si // 4],
        si + si // 8)
    return mpi


MIN_TS_NONE = np.int32(-2**31 + 1)

_I32_MIN = np.int32(-2**31)
_I32_MAX = np.int32(2**31 - 1)


def _masked_window_reduce(ts: jnp.ndarray, cfg: RollupConfig, specs):
    """ONE fused pass over sample chunks computing several masked
    reductions at once — the TPU-shaped core of the windowed rollups.

    Windowed quantities that classically need per-(step) index gathers
    become masked reductions over the sample axis: gathers lower to slow
    scalar loads on TPU, while a [S, chunk, T] compare+select+reduce fuses
    into pure VPU work (measured ~25ms/gather vs ~5ms for a whole fused
    pass at 8192x1984x355). Monotone quantities (sorted timestamps,
    reset-corrected counters) make first/last/prev exact min/max.

    specs: list of (arr [S,N] | None, kind, op):
      arr None reduces a constant 1 (int32 counting)
      kind 'le_hi': mask ts <= grid[t]
           'le_lo': mask ts <= grid[t] - lookback
           'win'  : grid[t]-lookback < ts <= grid[t]
      op 'sum' | 'max' | 'min'
    Returns (results [S,T] list, grid). Padded samples carry ts == TS_PAD
    and are never selected by any mask.
    """
    T = (cfg.end - cfg.start) // cfg.step + 1
    grid = jnp.arange(T, dtype=jnp.int32) * np.int32(cfg.step)
    lo_t = grid - np.int32(cfg.lookback)
    S, N = ts.shape
    ch = min(_BOUNDS_CHUNK, N)
    n_ch = (N + ch - 1) // ch
    padn = n_ch * ch - N

    def prep(a, fill):
        if padn:
            a = jnp.pad(a, ((0, 0), (0, padn)), constant_values=fill)
        return jnp.moveaxis(a.reshape(S, n_ch, ch), 1, 0)

    ts_ch = prep(ts, TS_PAD)
    xs = {"ts": ts_ch}
    # derive inits from ts so they inherit its sharding variance: a plain
    # jnp.full would be an axis-invariant constant, which shard_map rejects
    # as a scan carry whose output varies over the series axis
    vary0 = (ts[:, :1] * 0)  # int32 [S, 1] of zeros, varying like ts
    inits = []
    for i, (a, kind, op) in enumerate(specs):
        if a is not None:
            xs[f"a{i}"] = prep(a, 0)
            dt = a.dtype
        else:
            dt = jnp.int32
        if op == "sum":
            const = 0
        elif op == "max":
            const = _I32_MIN if dt == jnp.int32 else -jnp.inf
        else:
            const = _I32_MAX if dt == jnp.int32 else jnp.inf
        init = jnp.broadcast_to(vary0.astype(dt), (S, T)) + \
            jnp.asarray(const, dt)
        inits.append(init)

    def body(carry, x):
        tc = x["ts"][:, :, None]
        m_hi = tc <= grid[None, None, :]
        m_lo = tc <= lo_t[None, None, :]
        out = []
        for i, ((a, kind, op), acc) in enumerate(zip(specs, carry)):
            mask = m_hi if kind == "le_hi" else (
                m_lo if kind == "le_lo" else m_hi & ~m_lo)
            if a is None:
                arr = jnp.ones((1, 1, 1), jnp.int32)
            else:
                arr = x[f"a{i}"][:, :, None]
            if op == "sum":
                r = jnp.sum(jnp.where(mask, arr, jnp.zeros((), acc.dtype)),
                            axis=1, dtype=acc.dtype)
                out.append(acc + r)
            elif op == "max":
                fill = _I32_MIN if acc.dtype == jnp.int32 else -jnp.inf
                r = jnp.max(jnp.where(mask, arr, fill), axis=1)
                out.append(jnp.maximum(acc, r))
            else:
                fill = _I32_MAX if acc.dtype == jnp.int32 else jnp.inf
                r = jnp.min(jnp.where(mask, arr, fill), axis=1)
                out.append(jnp.minimum(acc, r))
        return out, None

    res, _ = jax.lax.scan(body, inits, xs)
    return res, grid


@functools.partial(jax.jit, static_argnames=("func", "cfg"))
def rollup_tile(func: str, ts: jnp.ndarray, values: jnp.ndarray,
                counts: jnp.ndarray, cfg: RollupConfig,
                min_ts=MIN_TS_NONE, v0=None) -> jnp.ndarray:
    """Windowed rollup over a padded tile -> [S, T] float array (NaN = gap).

    `min_ts` (traced) reproduces the evaluator's fetch truncation on tiles
    that hold MORE history than the query would fetch (rolling tiles):
    samples older than min_ts never seed prevValue / boundary transitions,
    exactly as if the fetch had started there. Window samples themselves
    are always newer than any fetch bound, so only prev-sample accesses are
    gated."""
    S, N = ts.shape
    dtype = values.dtype
    nan = jnp.asarray(jnp.nan, dtype)
    valid = _valid_mask(counts, N)
    vm = jnp.where(valid, values, 0.0)
    tsf = jnp.where(valid, ts, 0).astype(dtype)

    # Fused masked-reduction plan: every func reduces lo/hi counts and the
    # prev-sample timestamp in ONE chunked pass; func-specific quantities
    # ride the same pass. Monotone quantities (sorted ts, reset-corrected
    # counters) turn first/last/prev gathers into exact min/max reductions.
    specs = [(None, "le_lo", "sum"), (None, "le_hi", "sum"),
             (ts, "le_lo", "max")]

    def run(extra):
        res, grid = _masked_window_reduce(ts, cfg, specs + extra)
        return res[0], res[1], res[2], res[3:], grid

    def finish(lo, hi, t_prev_i):
        n_win = (hi - lo).astype(dtype)
        have = hi > lo
        has_prev = (lo >= 1) & (t_prev_i >= jnp.int32(min_ts))
        return n_win, have, has_prev

    if func in ("count_over_time", "present_over_time"):
        lo, hi, t_prev_i, _, grid = run([])
        n_win, have, _ = finish(lo, hi, t_prev_i)
        out = n_win if func == "count_over_time" else jnp.ones_like(n_win)
        return jnp.where(have, out, nan)

    if func in ("sum_over_time", "avg_over_time"):
        lo, hi, t_prev_i, (s1,), grid = run([(vm, "win", "sum")])
        n_win, have, _ = finish(lo, hi, t_prev_i)
        out = s1 if func == "sum_over_time" else s1 / n_win
        return jnp.where(have, out, nan)
    if func in ("stddev_over_time", "stdvar_over_time"):
        # Center by the per-series mean first: variance is shift-invariant
        # and this keeps the E[x^2]-E[x]^2 cancellation well-conditioned.
        total = jnp.sum(vm, axis=1, keepdims=True)
        cnt_all = jnp.maximum(counts[:, None].astype(dtype), 1.0)
        centered = jnp.where(valid, values - total / cnt_all, 0.0)
        lo, hi, t_prev_i, (s1, s2), grid = run(
            [(centered, "win", "sum"), (centered * centered, "win", "sum")])
        n_win, have, _ = finish(lo, hi, t_prev_i)
        var = jnp.maximum(s2 / n_win - (s1 / n_win) ** 2, 0.0)
        return jnp.where(have,
                         jnp.sqrt(var) if func == "stddev_over_time" else var,
                         nan)
    if func in ("min_over_time", "max_over_time"):
        op = "min" if func == "min_over_time" else "max"
        lo, hi, t_prev_i, (m,), grid = run([(values, "win", op)])
        _, have, _ = finish(lo, hi, t_prev_i)
        return jnp.where(have, m, nan)

    # Timestamps in the tile are relative to cfg.start (int32 rebase);
    # t-valued funcs add the base back to return absolute unix seconds.
    base_s = jnp.asarray(cfg.start, dtype) / 1e3
    if func == "tfirst_over_time":
        lo, hi, t_prev_i, (tf,), grid = run([(ts, "win", "min")])
        _, have, _ = finish(lo, hi, t_prev_i)
        return jnp.where(have, tf.astype(dtype) / 1e3 + base_s, nan)
    if func in ("tlast_over_time", "timestamp", "lag"):
        lo, hi, t_prev_i, (tl,), grid = run([(ts, "le_hi", "max")])
        _, have, _ = finish(lo, hi, t_prev_i)
        tl = tl.astype(dtype)
        if func == "lag":
            return jnp.where(have,
                             (grid.astype(dtype)[None, :] - tl) / 1e3, nan)
        return jnp.where(have, tl / 1e3 + base_s, nan)

    if func == "first_over_time":
        lo, hi, t_prev_i, _, grid = run([])
        _, have, _ = finish(lo, hi, t_prev_i)
        return jnp.where(have, _gather(values, lo), nan)
    if func in ("last_over_time", "default_rollup"):
        lo, hi, t_prev_i, _, grid = run([])
        _, have, _ = finish(lo, hi, t_prev_i)
        return jnp.where(have, _gather(values, hi - 1), nan)

    if func == "changes":
        prev_col = jnp.concatenate([vm[:, :1], vm[:, :-1]], axis=1)
        pair_valid = valid & jnp.concatenate(
            [jnp.zeros_like(valid[:, :1]), valid[:, :-1]], axis=1)
        chg = jnp.where(pair_valid & (vm != prev_col), 1.0, 0.0)
        # chg[i] is the transition (i-1, i); the window sum already counts
        # the boundary transition from the real prev value. With no
        # (eligible) prev sample the first window sample is the baseline:
        # drop the boundary term.
        lo, hi, t_prev_i, (s,), grid = run([(chg, "win", "sum")])
        _, have, has_prev = finish(lo, hi, t_prev_i)
        boundary = _gather(chg, lo)
        return jnp.where(have, s - jnp.where(has_prev, 0.0, boundary), nan)

    if func == "delta":
        lo, hi, t_prev_i, _, grid = run([])
        _, have, has_prev = finish(lo, hi, t_prev_i)
        v_last = _gather(values, hi - 1)
        v_first = _gather(values, lo)
        # new-series baseline (rollup.go:2129, mirrors rollup_np): with no
        # sample before the window the counter is assumed born at 0 unless
        # its first value dwarfs the first in-window step. The compare and
        # the zero base live in ABSOLUTE values, so rebased tiles fold v0
        # back in (same precedent as _remove_counter_resets: the born case
        # only fires on small absolutes, so the f32 addback stays exact).
        v0c = jnp.zeros((), dtype) if v0 is None else \
            v0[:, None].astype(dtype)
        two = hi - lo >= 2
        d = jnp.where(two, _gather(values, lo + 1) - v_first,
                      jnp.zeros((), dtype))
        born = jnp.abs(v_first + v0c) < 10.0 * (jnp.abs(d) + 1.0)
        base = jnp.where(has_prev, _gather(values, lo - 1),
                         jnp.where(born, -v0c, v_first))
        return jnp.where(have, v_last - base, nan)
    if func == "idelta":
        lo, hi, t_prev_i, _, grid = run([])
        n_win, have, has_prev = finish(lo, hi, t_prev_i)
        mpi = _max_prev_interval_tile(ts, counts, cfg, min_ts)
        has_gprev = has_prev & (
            t_prev_i > (grid - cfg.lookback)[None, :] - mpi[:, None])
        two = hi - lo >= 2
        v_last = _gather(values, hi - 1)
        prev = jnp.where(two, _gather(values, hi - 2),
                         _gather(values, lo - 1))
        return jnp.where(have & (two | has_gprev), v_last - prev, nan)

    if func in ("increase", "increase_pure", "rate", "irate"):
        cv = _remove_counter_resets(values, valid, v0)
        # pads/invalid tails carry garbage values but ts == TS_PAD, so no
        # mask ever selects them; cv is non-decreasing on the valid prefix,
        # making last/first/prev exact max/min reductions (zero gathers)
        lo, hi, t_prev_i, red, grid = run([
            (cv, "le_hi", "max"),   # c_last
            (cv, "le_lo", "max"),   # c_prev
            (cv, "win", "min"),     # c_first
            (ts, "le_hi", "max"),   # t_last (int32)
            (ts, "win", "min"),     # t_first (int32)
        ])
        c_last, c_prev, c_first, t_last_i, t_first_i = red
        n_win, have, has_prev = finish(lo, hi, t_prev_i)
        if func in ("increase", "increase_pure"):
            # new-series baseline on the reset-corrected series (see the
            # delta branch above; increase_pure always counts from 0 —
            # rollup.go:2169)
            v0c = jnp.zeros((), dtype) if v0 is None else \
                v0[:, None].astype(dtype)
            if func == "increase_pure":
                nb = jnp.broadcast_to(-v0c, c_first.shape)
            else:
                two = hi - lo >= 2
                d = jnp.where(two, _gather(cv, lo + 1) - c_first,
                              jnp.zeros((), dtype))
                born = jnp.abs(c_first + v0c) < 10.0 * (jnp.abs(d) + 1.0)
                nb = jnp.where(born, -v0c, c_first)
            base = jnp.where(has_prev, c_prev, nb)
            return jnp.where(have, c_last - base, nan)
        # deriv-family prevValue gate (rollup.go:781): the sample before
        # the window seeds prevValue only within maxPrevInterval of the
        # window start
        mpi = _max_prev_interval_tile(ts, counts, cfg, min_ts)
        has_gprev = has_prev & (
            t_prev_i > (grid - cfg.lookback)[None, :] - mpi[:, None])
        t_last = t_last_i.astype(dtype)
        t_first = t_first_i.astype(dtype)
        t_prev = t_prev_i.astype(dtype)
        if func == "rate":
            two = hi - lo >= 2
            ok = have & (has_gprev | two)
            rate_base = jnp.where(has_gprev, c_prev, c_first)
            dt = jnp.where(has_gprev, t_last - t_prev,
                           t_last - t_first) / 1e3
            dv = c_last - rate_base
            return jnp.where(ok & (dt > 0), dv / dt, nan)
        # irate: last two samples
        two = hi - lo >= 2
        ok = have & (two | has_gprev)
        c_l2 = jnp.where(two, _gather(cv, hi - 2), c_prev)
        t_l2 = jnp.where(two, _gather(tsf, hi - 2), t_prev)
        dt = (t_last - t_l2) / 1e3
        return jnp.where(ok & (dt > 0), (c_last - c_l2) / dt, nan)

    if func == "deriv_fast":
        lo, hi, t_prev_i, (t_last_i,), grid = run([(ts, "le_hi", "max")])
        n_win, have, has_prev = finish(lo, hi, t_prev_i)
        mpi = _max_prev_interval_tile(ts, counts, cfg, min_ts)
        has_gprev = has_prev & (
            t_prev_i > (grid - cfg.lookback)[None, :] - mpi[:, None])
        v_last = _gather(values, hi - 1)
        t_last = t_last_i.astype(dtype)
        two = hi - lo >= 2
        base_v = jnp.where(has_gprev, _gather(values, lo - 1),
                           _gather(values, lo))
        base_t = jnp.where(has_gprev, _gather(tsf, lo - 1),
                           _gather(tsf, lo))
        ok = have & (has_gprev | two)
        dt = (t_last - base_t) / 1e3
        return jnp.where(ok & (dt > 0), (v_last - base_v) / dt, nan)

    if func == "deriv":
        # least-squares slope via masked moment sums, t in seconds shifted
        # to each window's first sample (subtracted analytically to keep
        # f32-path cancellation manageable)
        ts_s = jnp.where(valid, ts, 0).astype(dtype) / 1e3
        lo, hi, t_prev_i, red, grid = run([
            (jnp.where(valid, ts_s, 0.0), "win", "sum"),
            (jnp.where(valid, ts_s * ts_s, 0.0), "win", "sum"),
            (vm, "win", "sum"),
            (jnp.where(valid, ts_s * values, 0.0), "win", "sum"),
            (ts, "win", "min"),
        ])
        st, stt, sv, stv, t_first_i = red
        n_win, have, _ = finish(lo, hi, t_prev_i)
        t0 = t_first_i.astype(dtype) / 1e3
        # shift t -> t - t0: st' = st - n*t0; stt' = stt - 2 t0 st + n t0²;
        # stv' = stv - t0*sv
        st_ = st - n_win * t0
        stt_ = stt - 2 * t0 * st + n_win * t0 * t0
        stv_ = stv - t0 * sv
        den = n_win * stt_ - st_ * st_
        ok = have & (hi - lo >= 2)
        return jnp.where(ok & (den != 0),
                         (n_win * stv_ - st_ * sv) / den, nan)

    if func == "lifetime":
        lo, hi, t_prev_i, (t_last_i, t_first_i), grid = run(
            [(ts, "le_hi", "max"), (ts, "win", "min")])
        _, have, has_prev = finish(lo, hi, t_prev_i)
        t_last = t_last_i.astype(dtype)
        t_first = jnp.where(has_prev, tsf[:, :1],
                            t_first_i.astype(dtype))
        return jnp.where(have, (t_last - t_first) / 1e3, nan)
    if func == "scrape_interval":
        lo, hi, t_prev_i, (t_last_i, t_first_i), grid = run(
            [(ts, "le_hi", "max"), (ts, "win", "min")])
        n_win, have, has_prev = finish(lo, hi, t_prev_i)
        t_last = t_last_i.astype(dtype)
        t_first = t_first_i.astype(dtype)
        t_prev = t_prev_i.astype(dtype)
        two = hi - lo >= 2
        ok = have & (has_prev | two)
        dt = jnp.where(has_prev, t_last - t_prev, t_last - t_first) / 1e3
        cnt = jnp.where(has_prev, n_win, n_win - 1)
        return jnp.where(ok & (cnt > 0), dt / cnt, nan)

    raise ValueError(f"unsupported device rollup func {func!r}")


# ---------------------------------------------------------------------------
# Grouped aggregation over series (the incremental-aggregation analog:
# aggr_incremental.go:18-67 becomes one segment-reduction).
# ---------------------------------------------------------------------------

AGGR_FUNCS = ("sum", "count", "avg", "min", "max", "group", "stddev", "stdvar")


def partial_group_moments(aggr: str, rolled: jnp.ndarray,
                          group_ids: jnp.ndarray, num_groups: int
                          ) -> dict[str, tuple[jnp.ndarray, str]]:
    """Per-shard segment moments for one aggregate: {name: (array [G, T],
    cross-shard reduce kind 'sum'|'min'|'max')}. Splitting moments from
    finalization lets the mesh layer psum/pmin/pmax the moments across
    shards before finalizing — combining *finished* per-shard stats would be
    wrong for avg/stddev."""
    present = ~jnp.isnan(rolled)
    zeroed = jnp.where(present, rolled, 0.0)
    # group-sum as a one-hot matmul: [G, S] @ [S, T] runs on the MXU,
    # where segment_sum lowers to a serialized scatter-add on TPU. Gated:
    # the dense one-hot is O(G*S), so near-unique groupings (G ~ S) keep
    # the linear scatter; and a +-Inf value would leak NaN into OTHER
    # groups through 0*Inf, so those (rare) tiles take the scatter via cond.
    S = rolled.shape[0]
    use_matmul = num_groups * S <= (1 << 24)
    if use_matmul:
        onehot = (group_ids[None, :] ==
                  jnp.arange(num_groups, dtype=group_ids.dtype)[:, None]
                  ).astype(rolled.dtype)
        all_finite = jnp.all(jnp.isfinite(zeroed))

        def seg(x):
            return jax.lax.cond(
                all_finite,
                lambda y: onehot @ y,
                lambda y: jax.ops.segment_sum(y, group_ids,
                                              num_segments=num_groups),
                x)

        cnt = onehot @ present.astype(rolled.dtype)
    else:
        def seg(x):
            return jax.ops.segment_sum(x, group_ids,
                                       num_segments=num_groups)

        cnt = seg(present.astype(rolled.dtype))
    m = {"cnt": (cnt, "sum")}
    if aggr in ("sum", "avg", "stddev", "stdvar"):
        m["s1"] = (seg(zeroed), "sum")
    if aggr in ("stddev", "stdvar"):
        m["s2"] = (seg(zeroed * zeroed), "sum")
    if aggr == "min":
        m["min"] = (jax.ops.segment_min(jnp.where(present, rolled, jnp.inf),
                                        group_ids, num_segments=num_groups),
                    "min")
    if aggr == "max":
        m["max"] = (jax.ops.segment_max(jnp.where(present, rolled, -jnp.inf),
                                        group_ids, num_segments=num_groups),
                    "max")
    if aggr not in AGGR_FUNCS:
        raise ValueError(f"unsupported aggregate {aggr!r}")
    return m


def finalize_group_moments(aggr: str, m: dict[str, tuple[jnp.ndarray, str]]
                           ) -> jnp.ndarray:
    """Finalize (possibly cross-shard-reduced) moments into the [G, T]
    aggregate. Groups with no live series at a step yield NaN."""
    cnt = m["cnt"][0]
    nan = jnp.asarray(jnp.nan, cnt.dtype)
    if aggr == "sum":
        out = m["s1"][0]
    elif aggr == "avg":
        out = m["s1"][0] / cnt
    elif aggr in ("stddev", "stdvar"):
        mean = m["s1"][0] / cnt
        var = jnp.maximum(m["s2"][0] / cnt - mean * mean, 0.0)
        out = jnp.sqrt(var) if aggr == "stddev" else var
    elif aggr == "count":
        out = cnt
    elif aggr == "min":
        out = m["min"][0]
    elif aggr == "max":
        out = m["max"][0]
    elif aggr == "group":
        out = jnp.ones_like(cnt)
    else:
        raise ValueError(f"unsupported aggregate {aggr!r}")
    return jnp.where(cnt > 0, out, nan)


def aggregate_groups(aggr: str, rolled: jnp.ndarray, group_ids: jnp.ndarray,
                     num_groups: int) -> jnp.ndarray:
    """Aggregate per-series rollup results [S, T] into [G, T] by group id.
    NaN inputs mean 'series absent at this step' and are skipped."""
    return finalize_group_moments(
        aggr, partial_group_moments(aggr, rolled, group_ids, num_groups))


#: stream-axis aggregate selector for the fleet kernel: the aggregate is
#: a per-stream TRACED code, so streams mixing sum/max/count/... share
#: ONE compiled program per bucket shape instead of one per aggregate
FLEET_AGGR_CODES = {"sum": 0, "count": 1, "avg": 2, "min": 3, "max": 4,
                    "stddev": 5, "stdvar": 6, "group": 7}


def _fleet_group_aggregate(rolled: jnp.ndarray, group_ids: jnp.ndarray,
                           num_groups: int, aggr_code) -> jnp.ndarray:
    """All-moments segment aggregation + finalize-by-code: computes the
    same cnt/s1/s2/min/max moments partial_group_moments would (same ops,
    same order, so each selected aggregate matches the per-stream kernel
    at f64 resolution), finalizes every aggregate, and gathers the one
    `aggr_code` (traced int32) names."""
    present = ~jnp.isnan(rolled)
    zeroed = jnp.where(present, rolled, 0.0)
    S = rolled.shape[0]
    if num_groups * S <= (1 << 24):
        onehot = (group_ids[None, :] ==
                  jnp.arange(num_groups, dtype=group_ids.dtype)[:, None]
                  ).astype(rolled.dtype)
        all_finite = jnp.all(jnp.isfinite(zeroed))

        def seg(x):
            return jax.lax.cond(
                all_finite,
                lambda y: onehot @ y,
                lambda y: jax.ops.segment_sum(y, group_ids,
                                              num_segments=num_groups),
                x)

        cnt = onehot @ present.astype(rolled.dtype)
    else:
        def seg(x):
            return jax.ops.segment_sum(x, group_ids,
                                       num_segments=num_groups)

        cnt = seg(present.astype(rolled.dtype))
    s1 = seg(zeroed)
    s2 = seg(zeroed * zeroed)
    mn = jax.ops.segment_min(jnp.where(present, rolled, jnp.inf),
                             group_ids, num_segments=num_groups)
    mx = jax.ops.segment_max(jnp.where(present, rolled, -jnp.inf),
                             group_ids, num_segments=num_groups)
    mean = s1 / cnt
    var = jnp.maximum(s2 / cnt - mean * mean, 0.0)
    outs = jnp.stack([s1, cnt, mean, mn, mx, jnp.sqrt(var), var,
                      jnp.ones_like(cnt)])
    out = outs[aggr_code]
    nan = jnp.asarray(jnp.nan, cnt.dtype)
    return jnp.where(cnt > 0, out, nan)


def fleet_rollup_aggregate_impl(rollup_func: str, cfg: RollupConfig,
                                num_groups: int, fleet_ts: jnp.ndarray,
                                fleet_values: jnp.ndarray,
                                fleet_counts: jnp.ndarray,
                                fleet_gids: jnp.ndarray,
                                fleet_aggr: jnp.ndarray,
                                fleet_shift: jnp.ndarray,
                                fleet_min_ts: jnp.ndarray,
                                fleet_v0: jnp.ndarray) -> jnp.ndarray:
    """Fleet-batched aggr(rollup(m[d])) over [B, S, N] planes -> [B, G, T]:
    ONE program for every resident stream in a bucket.  Static per bucket:
    rollup_func, the normalized cfg grid, num_groups.  Per-stream traced:
    grid shift, fetch bound min_ts, aggregate code, rebase offsets —
    window masks per stream fall out of shift/min_ts exactly as in the
    per-stream rolling path (the bit-equality oracle).  Padded streams
    carry counts == 0 / ts == TS_PAD and roll up to all-NaN rows."""

    def one(ts, values, counts, gids, aggr_code, shift, min_ts, v0):
        rolled = rollup_tile(rollup_func, ts - jnp.int32(shift), values,
                             counts, cfg, min_ts, v0)
        return _fleet_group_aggregate(rolled, gids, num_groups, aggr_code)

    return jax.vmap(one)(fleet_ts, fleet_values, fleet_counts, fleet_gids,
                         fleet_aggr, fleet_shift, fleet_min_ts, fleet_v0)


@functools.partial(jax.jit,
                   static_argnames=("rollup_func", "cfg", "num_groups"))
def fleet_rollup_aggregate_tile(rollup_func: str, cfg: RollupConfig,
                                num_groups: int, fleet_ts, fleet_values,
                                fleet_counts, fleet_gids, fleet_aggr,
                                fleet_shift, fleet_min_ts, fleet_v0):
    """Single-device jit of fleet_rollup_aggregate_impl (mesh engines go
    through parallel.mesh.cached_fleet_rollup_aggregate instead)."""
    return fleet_rollup_aggregate_impl(rollup_func, cfg, num_groups,
                                       fleet_ts, fleet_values, fleet_counts,
                                       fleet_gids, fleet_aggr, fleet_shift,
                                       fleet_min_ts, fleet_v0)


@functools.partial(jax.jit, static_argnames=("rollup_func", "aggr", "cfg", "num_groups"))
def rollup_aggregate_tile(rollup_func: str, aggr: str, ts: jnp.ndarray,
                          values: jnp.ndarray, counts: jnp.ndarray,
                          group_ids: jnp.ndarray, cfg: RollupConfig,
                          num_groups: int, shift=0,
                          min_ts=MIN_TS_NONE, v0=None) -> jnp.ndarray:
    """Fused aggr(rollup(m[d])) over one tile -> [G, T].

    `shift` (traced int32, ms) rebases tile timestamps onto the cfg grid:
    rolling tiles keep timestamps relative to their original base while the
    query grid advances, so shift = query_start - tile_base. Time-valued
    funcs are not supported with shift != 0 (dispatch excludes them).
    `min_ts` is the query's fetch lower bound in the SHIFTED frame (see
    rollup_tile); `v0` the per-series rebase offsets of f32 tiles."""
    rolled = rollup_tile(rollup_func, ts - jnp.int32(shift), values, counts,
                         cfg, min_ts, v0)
    return aggregate_groups(aggr, rolled, group_ids, num_groups)


def _append_tile_body(ts: jnp.ndarray, values: jnp.ndarray,
                      counts: jnp.ndarray, new_ts: jnp.ndarray,
                      new_values: jnp.ndarray, new_counts: jnp.ndarray):
    S, N = ts.shape
    K = new_ts.shape[1]
    rows = jnp.arange(S, dtype=jnp.int32)[:, None]
    k = jnp.arange(K, dtype=jnp.int32)[None, :]
    live = k < new_counts[:, None]
    pos = jnp.where(live, counts.astype(jnp.int32)[:, None] + k, N)
    ts2 = ts.at[rows, pos].set(new_ts, mode="drop")
    v2 = values.at[rows, pos].set(new_values.astype(values.dtype),
                                  mode="drop")
    return ts2, v2, counts + new_counts.astype(counts.dtype)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def append_tile(ts: jnp.ndarray, values: jnp.ndarray, counts: jnp.ndarray,
                new_ts: jnp.ndarray, new_values: jnp.ndarray,
                new_counts: jnp.ndarray):
    """Rolling-tile advance: scatter newer samples onto each row's tail.

    The buffers are donated — the caller's old tile references become
    invalid and must be replaced with the returned arrays (this is what
    keeps the HBM-resident tile single-copy while ingest appends). New
    samples must be strictly newer than each row's existing samples (the
    eval layer guarantees this via the storage append watermark); per-row
    positions beyond new_counts[row] scatter out of bounds and are dropped."""
    return _append_tile_body(ts, values, counts, new_ts, new_values,
                             new_counts)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def fleet_append_tile(fleet_ts: jnp.ndarray, fleet_values: jnp.ndarray,
                      fleet_counts: jnp.ndarray, new_ts: jnp.ndarray,
                      new_values: jnp.ndarray, new_counts: jnp.ndarray):
    """Batched append over the fleet's leading stream axis: ONE donated
    launch scatters every staged stream's suffix columns [B, S, K] onto
    the packed [B, S, N] planes (query/fleet.py).  Streams with nothing
    staged carry new_counts == 0 rows and are untouched."""
    return jax.vmap(_append_tile_body)(fleet_ts, fleet_values, fleet_counts,
                                       new_ts, new_values, new_counts)


def _compact_tile_body(ts: jnp.ndarray, values: jnp.ndarray,
                       counts: jnp.ndarray, cutoff_rel, delta):
    S, N = ts.shape
    k = jnp.arange(N, dtype=jnp.int32)[None, :]
    valid = k < counts[:, None]
    drop = jnp.sum(valid & (ts < jnp.int32(cutoff_rel)), axis=1,
                   dtype=jnp.int32)
    new_counts = counts - drop
    idx = jnp.clip(drop[:, None] + k, 0, N - 1)
    live = k < new_counts[:, None]
    ts2 = jnp.where(live,
                    jnp.take_along_axis(ts, idx, axis=1) - jnp.int32(delta),
                    TS_PAD)
    v2 = jnp.where(live, jnp.take_along_axis(values, idx, axis=1),
                   jnp.zeros((), values.dtype))
    return ts2, v2, new_counts


@functools.partial(jax.jit, donate_argnums=(0, 1))
def compact_tile(ts: jnp.ndarray, values: jnp.ndarray, counts: jnp.ndarray,
                 cutoff_rel, delta):
    """Window-slide compaction of a rolling tile: drop each row's samples
    older than `cutoff_rel` (tile-relative ms, exclusive — samples AT the
    cutoff survive, matching the inclusive fetch lower bound), shift the
    survivors to the row front and rebase timestamps by `delta`
    (= new_base - old_base; both traced int32, so sliding windows never
    recompile).  The sample buffers are donated like append_tile's — the
    caller replaces its references with the returned arrays.  Freed tail
    positions are restored to TS_PAD so every kernel's masks stay valid.

    Correctness: rows are time-sorted, so dropped samples form a prefix.
    Samples older than the query fetch bound contribute nothing to any
    rollup (window masks exclude them; prev-sample accesses are gated by
    min_ts — see rollup_tile), so compacting at the CURRENT fetch_lo is
    invisible to this and every later query whose fetch bound is >= it;
    older-reaching queries decline via RollingTile.lo_ms and rebuild."""
    return _compact_tile_body(ts, values, counts, cutoff_rel, delta)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def fleet_compact_tile(fleet_ts: jnp.ndarray, fleet_values: jnp.ndarray,
                       fleet_counts: jnp.ndarray, cutoff_rel: jnp.ndarray,
                       delta: jnp.ndarray):
    """Batched window-slide compaction: per-stream cutoffs/deltas [B]
    (traced), one donated launch over the packed [B, S, N] planes.
    Streams with cutoff_rel <= 0 pass (cutoff 0, delta 0) and come back
    unchanged."""
    return jax.vmap(_compact_tile_body)(fleet_ts, fleet_values,
                                        fleet_counts, cutoff_rel, delta)


def pack_series(series: list[tuple[np.ndarray, np.ndarray]], start_ms: int,
                n_pad: int | None = None, dtype=np.float64
                ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side packing: ragged [(ts_ms, values)] -> padded tile arrays
    (ts_rel int32 [S, N], values [S, N], counts int32 [S]).

    Timestamps are re-based to start_ms so they fit int32 (range limit ~24.8
    days; the evaluator chunks longer ranges)."""
    S = len(series)
    counts = np.array([len(t) for t, _ in series], dtype=np.int32)
    N = n_pad or (int(counts.max()) if S else 1)
    N = max(N, 1)
    ts = np.full((S, N), TS_PAD, dtype=np.int32)
    vals = np.zeros((S, N), dtype=dtype)
    for i, (t, v) in enumerate(series):
        c = counts[i]
        rel = np.asarray(t, dtype=np.int64) - start_ms
        if c and (rel.max() >= TS_PAD or rel.min() <= -(2**31)):
            raise ValueError("time range too wide for int32 tile; chunk the query")
        ts[i, :c] = rel.astype(np.int32)
        vals[i, :c] = v
    return ts, vals, counts


@functools.partial(jax.jit, static_argnames=("func", "cfg", "k", "bottom"))
def topk_select_tile(func: str, ts: jnp.ndarray, values: jnp.ndarray,
                     counts: jnp.ndarray, cfg: RollupConfig, k: int,
                     bottom: bool, min_ts=MIN_TS_NONE, v0=None):
    """Per-timestamp topk/bottomk selection over a rolled tile: the [S, T]
    rollup never leaves the device — only [T, k] winner indices (+ NaN
    flags) cross the link, and the caller gathers just the selected rows
    (aggr.go topk/bottomk; host twin aggr_funcs.topk_mask_per_ts).
    Returns (rolled [device-resident], idx [T, k], sel_nan [T, k])."""
    rolled = rollup_tile(func, ts, values, counts, cfg, min_ts, v0)
    bad = jnp.isnan(rolled)
    key = jnp.where(bad, -jnp.inf, -rolled if bottom else rolled)
    _, idx = jax.lax.top_k(key.T, k)                   # [T, k]
    sel_nan = jnp.take_along_axis(bad.T, idx, axis=1)
    return rolled, idx, sel_nan


@functools.partial(jax.jit, static_argnames=("func", "kind", "cfg"))
def rank_tile(func: str, kind: str, ts: jnp.ndarray, values: jnp.ndarray,
              counts: jnp.ndarray, cfg: RollupConfig, min_ts=MIN_TS_NONE,
              v0=None):
    """topk_<kind>/bottomk_<kind> ranking: the whole-series statistic
    (aggr_funcs.series_rank_metric twin) computed on device — D2H is one
    float per series; the caller gathers only the k selected rows."""
    rolled = rollup_tile(func, ts, values, counts, cfg, min_ts, v0)
    bad = jnp.isnan(rolled)
    n = jnp.sum(~bad, axis=1)
    if kind == "max":
        r = jnp.max(jnp.where(bad, -jnp.inf, rolled), axis=1)
    elif kind == "min":
        r = jnp.min(jnp.where(bad, jnp.inf, rolled), axis=1)
    elif kind == "avg":
        r = jnp.sum(jnp.where(bad, 0.0, rolled), axis=1) / \
            jnp.maximum(n, 1).astype(rolled.dtype)
    elif kind == "median":
        sv = jnp.sort(jnp.where(bad, jnp.inf, rolled), axis=1)
        pos = 0.5 * jnp.maximum(n - 1, 0).astype(rolled.dtype)
        j0 = jnp.floor(pos).astype(jnp.int32)
        j1 = jnp.minimum(j0 + 1, jnp.maximum(n - 1, 0).astype(jnp.int32))
        a = jnp.take_along_axis(sv, j0[:, None], axis=1)[:, 0]
        b = jnp.take_along_axis(sv, j1[:, None], axis=1)[:, 0]
        r = a + (pos - j0.astype(rolled.dtype)) * (b - a)
    elif kind == "last":
        T = rolled.shape[1]
        j = T - 1 - jnp.argmax(jnp.flip(~bad, axis=1), axis=1)
        r = jnp.take_along_axis(rolled, j[:, None], axis=1)[:, 0]
    else:
        raise ValueError(f"unknown rank kind {kind!r}")
    nan = jnp.asarray(jnp.nan, rolled.dtype)
    return rolled, jnp.where(n == 0, nan, r)


@jax.jit
def take_rows(rolled: jnp.ndarray, sel: jnp.ndarray) -> jnp.ndarray:
    """Row gather on a device-resident rolled tile (the D2H tail of the
    topk kernels: only selected rows come back)."""
    return jnp.take(rolled, sel, axis=0)


@functools.partial(jax.jit,
                   static_argnames=("rollup_func", "cfg", "num_groups",
                                    "max_group"))
def rollup_quantile_tile(rollup_func: str, phi, ts: jnp.ndarray,
                         values: jnp.ndarray, counts: jnp.ndarray,
                         group_ids: jnp.ndarray, slots: jnp.ndarray,
                         cfg: RollupConfig, num_groups: int,
                         max_group: int, shift=0,
                         min_ts=MIN_TS_NONE, v0=None) -> jnp.ndarray:
    """Fused quantile(phi, rollup(m[d])) by (...) -> [G, T].

    The per-series rollup [S, T] is scattered into a dense [G, M, T] tensor
    (M = largest group, host-precomputed per-series slot within its group),
    sorted along M (NaN gaps sort last), and linearly interpolated at
    phi*(n-1) per (group, step) — matching the host a_quantile /
    np.nanquantile semantics. The caller bounds G*M*T so skewed groupings
    fall back to the host path rather than exploding HBM."""
    rolled = rollup_tile(rollup_func, ts - jnp.int32(shift), values, counts,
                         cfg, min_ts, v0)  # [S, T]
    S, T = rolled.shape
    dtype = rolled.dtype
    nan = jnp.asarray(jnp.nan, dtype)
    dense = jnp.full((num_groups, max_group, T), nan, dtype)
    dense = dense.at[group_ids, slots].set(rolled)
    dsort = jnp.sort(dense, axis=1)  # NaNs last per (g, t)
    valid = ~jnp.isnan(rolled)
    n = jnp.zeros((num_groups, T), jnp.int32).at[group_ids].add(
        valid.astype(jnp.int32))  # live series per (g, t)
    phi_arr = jnp.asarray(phi, dtype)
    rank = jnp.clip(phi_arr, 0.0, 1.0) * jnp.maximum(n - 1, 0)
    lo = jnp.floor(rank).astype(jnp.int32)
    hi = jnp.ceil(rank).astype(jnp.int32)
    g_idx = jnp.arange(num_groups, dtype=jnp.int32)[:, None]
    t_idx = jnp.arange(T, dtype=jnp.int32)[None, :]
    v_lo = dsort[g_idx, lo, t_idx]
    v_hi = dsort[g_idx, hi, t_idx]
    q = v_lo + (rank - lo) * (v_hi - v_lo)
    # reference a_quantile: phi<0 -> -Inf, phi>1 -> +Inf on live steps
    q = jnp.where(phi_arr < 0, -jnp.inf, q)
    q = jnp.where(phi_arr > 1, jnp.inf, q)
    return jnp.where(n > 0, q, nan)
