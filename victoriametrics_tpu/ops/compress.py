"""zstd block compression (reference lib/encoding/compress.go:13-38 and
lib/encoding/zstd — the reference's single cgo/native dependency).

Uses the CPython `zstandard` package (libzstd-backed). Level 1 by default:
block payloads are small (<64KB) and this host has few cores, so speed wins;
the reference reaches the same trade-off via its cgo fast path.
"""

from __future__ import annotations

import zstandard

_compressors: dict[int, zstandard.ZstdCompressor] = {}
_decompressor = zstandard.ZstdDecompressor()

DEFAULT_LEVEL = 1


def compress(data: bytes, level: int = DEFAULT_LEVEL) -> bytes:
    c = _compressors.get(level)
    if c is None:
        c = _compressors[level] = zstandard.ZstdCompressor(level=level)
    return c.compress(data)


def decompress(data: bytes, max_size: int = 1 << 30) -> bytes:
    return _decompressor.decompress(data, max_output_size=max_size)
