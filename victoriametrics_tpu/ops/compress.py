"""zstd block compression (reference lib/encoding/compress.go:13-38 and
lib/encoding/zstd — the reference's single cgo/native dependency).

Uses the CPython `zstandard` package (libzstd-backed). Level 1 by default:
block payloads are small (<64KB) and this host has few cores, so speed wins;
the reference reaches the same trade-off via its cgo fast path.

(De)compressor objects are NOT thread-safe for concurrent use, so they are
kept thread-local — the storage engine decompresses from query threads while
flusher threads compress.
"""

from __future__ import annotations

import threading

import zstandard

DEFAULT_LEVEL = 1

_tls = threading.local()


def _compressor(level: int) -> zstandard.ZstdCompressor:
    cs = getattr(_tls, "compressors", None)
    if cs is None:
        cs = _tls.compressors = {}
    c = cs.get(level)
    if c is None:
        c = cs[level] = zstandard.ZstdCompressor(level=level)
    return c


def _decompressor() -> zstandard.ZstdDecompressor:
    d = getattr(_tls, "decompressor", None)
    if d is None:
        d = _tls.decompressor = zstandard.ZstdDecompressor()
    return d


def compress(data: bytes, level: int = DEFAULT_LEVEL) -> bytes:
    return _compressor(level).compress(data)


def decompress(data: bytes, max_size: int = 1 << 30) -> bytes:
    return _decompressor().decompress(data, max_output_size=max_size)
