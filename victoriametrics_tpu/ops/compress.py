"""zstd block compression (reference lib/encoding/compress.go:13-38 and
lib/encoding/zstd — the reference's single cgo/native dependency).

Uses the CPython `zstandard` package (libzstd-backed). Level 1 by default:
block payloads are small (<64KB) and this host has few cores, so speed wins;
the reference reaches the same trade-off via its cgo fast path.

(De)compressor objects are NOT thread-safe for concurrent use, so they are
kept thread-local — the storage engine decompresses from query threads while
flusher threads compress.

Gated dependency: when the `zstandard` package is absent (minimal dev
containers), `compress` first tries the native codec library's dlopen'd
libzstd.so.1 (victoriametrics_tpu/native — one-shot, thread-safe,
allocation-bounded) and only then falls back to stdlib zlib, so minimal
containers with just the runtime library still write real zstd frames.
`decompress` sniffs the frame magic and accepts BOTH encodings regardless
of which codec produced the part, so data written by any build reads back
on any build; only zstd-compressed data on a host with no libzstd binding
at all fails, and it fails loudly.
"""

from __future__ import annotations

import threading
import zlib

try:
    import zstandard
except ImportError:  # minimal container: stdlib fallback, see docstring
    zstandard = None

DEFAULT_LEVEL = 1

_native_zstd = None  # tri-state: None = unprobed, False = unavailable


def _native():
    """The native module's dlopen'd zstd one-shots, probed once; False
    when the library is missing or libzstd.so.1 did not resolve."""
    global _native_zstd
    if _native_zstd is None:
        try:
            from .. import native
            # benign double-probe: both racers compute the same verdict
            # from the same module state
            _native_zstd = native if native.has_zstd() else False  # vmt: disable=VMT015
        except Exception:
            _native_zstd = False
    return _native_zstd

#: every zstd frame starts with this magic (RFC 8878); zlib streams start
#: with 0x78 — disjoint, so decompress can sniff the producer
_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"

_tls = threading.local()


def zstd_available() -> bool:
    """True when compress() produces zstd frames (python binding or the
    native dlopen'd runtime library)."""
    return zstandard is not None or bool(_native())


def _compressor(level: int):
    cs = getattr(_tls, "compressors", None)
    if cs is None:
        cs = _tls.compressors = {}
    c = cs.get(level)
    if c is None:
        c = cs[level] = zstandard.ZstdCompressor(level=level)
    return c


def _decompressor():
    d = getattr(_tls, "decompressor", None)
    if d is None:
        d = _tls.decompressor = zstandard.ZstdDecompressor()
    return d


def compress(data: bytes, level: int = DEFAULT_LEVEL) -> bytes:
    if zstandard is None:
        nat = _native()
        if nat:
            out = nat.zstd_compress(data, level)
            if out is not None:
                return out
        return zlib.compress(data, level)
    return _compressor(level).compress(data)


def decompress(data: bytes, max_size: int = 1 << 30) -> bytes:
    if data.startswith(_ZSTD_MAGIC):
        if zstandard is None:
            nat = _native()
            if nat:
                out = nat.zstd_decompress(data, max_size=max_size)
                if out is not None:
                    return out
            raise RuntimeError(
                "cannot decompress zstd data: neither the 'zstandard' "
                "package nor a runtime libzstd is available in this build")
        return _decompressor().decompress(data, max_output_size=max_size)
    # bounded like the zstd path's max_output_size: cap BEFORE the whole
    # stream materializes, so a hostile/corrupt frame (zlib bomb over an
    # RPC boundary) cannot balloon memory
    d = zlib.decompressobj()
    out = d.decompress(data, max_size + 1)
    if len(out) > max_size:
        raise ValueError(f"decompressed size exceeds {max_size}")
    return out + d.flush()
