"""Device-side block decode: ship compact delta planes, reconstruct on TPU.

The raw-tile path (ops/device_rollup.pack_series) moves 12 bytes/sample
(int32 ts + float64 val) over the host->device link; on bandwidth-limited
links (axon tunnel ~1.4 GB/s chunked; PCIe on real hosts) the transfer
dominates. This module moves ~2-5 bytes/sample instead: second-order deltas
quantized to the narrowest integer plane that fits (int8/int16/int32), and
reconstructs on device with two cumulative sums — the
`nearest-delta2 decode as associative scan` design from SURVEY §7 — fused
with the rollup kernel so decoded tiles never round-trip.

Host-side packing starts from decoded int64 mantissa arrays (the storage
layer's native varint decode runs at ~300M samples/s, so re-deltaing is
cheap); the win is the transfer, not host CPU.

Overflow safety: the tile is only eligible when every intermediate
(mantissa, delta) fits int32; otherwise callers fall back to the dense path.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from .rollup_np import RollupConfig

TS_PAD = np.int32(2**31 - 1)


@dataclasses.dataclass
class DeltaPlanes:
    """Host-built compact tile; all arrays np arrays ready for device_put."""
    ts_first: np.ndarray    # int32 [S], relative to start_ms
    ts_fdelta: np.ndarray   # int32 [S]
    ts_d2: np.ndarray       # int8/int16/int32 [S, max(N-2,1)]
    val_first: np.ndarray   # int32 [S] mantissas
    val_fdelta: np.ndarray  # int32 [S]
    val_d2: np.ndarray      # int8/int16/int32 [S, max(N-2,1)]
    scale: np.ndarray       # float32/float64 [S] = 10^exponent
    counts: np.ndarray      # int32 [S]

    @property
    def nbytes(self) -> int:
        return sum(getattr(self, f.name).nbytes
                   for f in dataclasses.fields(self))


def _narrowest_plane(d2: np.ndarray):
    if d2.size == 0:
        return np.int8
    m = np.abs(d2).max()
    if m < 127:
        return np.int8
    if m < 32767:
        return np.int16
    return np.int32


def pack_delta_planes(series, start_ms: int, value_dtype=np.float32,
                      rebase: bool = False) -> DeltaPlanes | None:
    """series: [(ts_ms int64[], mantissas int64[], exponent)] — returns None
    when any series needs >int32 intermediates (caller falls back).

    `rebase=True` additionally requires every m - m[0] to fit int32: the
    f32 tile decode reconstructs REBASED mantissas (cumsum from zero), so
    the running offsets are the intermediates (see tpu_engine f32 design)."""
    S = len(series)
    if S == 0:
        return None
    counts = np.array([len(t) for t, _, _ in series], dtype=np.int32)
    if (counts < 1).any():
        return None
    N = int(counts.max())
    ts_first = np.zeros(S, dtype=np.int64)
    ts_fd = np.zeros(S, dtype=np.int64)
    val_first = np.zeros(S, dtype=np.int64)
    val_fd = np.zeros(S, dtype=np.int64)
    scale = np.ones(S, dtype=value_dtype)
    ts_d2 = np.zeros((S, max(N - 2, 1)), dtype=np.int64)
    val_d2 = np.zeros((S, max(N - 2, 1)), dtype=np.int64)
    for i, (ts, m, exp) in enumerate(series):
        rel = np.asarray(ts, dtype=np.int64) - start_ms
        m = np.asarray(m, dtype=np.int64)
        if rel.size and (np.abs(rel).max() >= 2**31 or
                         np.abs(m).max() >= 2**31):
            return None
        if rebase and m.size and np.abs(m - m[0]).max() >= 2**31:
            return None
        ts_first[i] = rel[0]
        val_first[i] = m[0]
        scale[i] = np.float64(10.0) ** exp
        if rel.size >= 2:
            td = np.diff(rel)
            vd = np.diff(m)
            if np.abs(td).max() >= 2**31 or np.abs(vd).max() >= 2**31:
                return None
            ts_fd[i] = td[0]
            val_fd[i] = vd[0]
            if rel.size >= 3:
                t2 = np.diff(td)
                v2 = np.diff(vd)
                if np.abs(t2).max() >= 2**31 or np.abs(v2).max() >= 2**31:
                    return None
                ts_d2[i, :t2.size] = t2
                val_d2[i, :v2.size] = v2
    return DeltaPlanes(
        ts_first=ts_first.astype(np.int32),
        ts_fdelta=ts_fd.astype(np.int32),
        ts_d2=ts_d2.astype(_narrowest_plane(ts_d2)),
        val_first=val_first.astype(np.int32),
        val_fdelta=val_fd.astype(np.int32),
        val_d2=val_d2.astype(_narrowest_plane(val_d2)),
        scale=scale,
        counts=counts,
    )


def _reconstruct(first, fdelta, d2, counts, n):
    """Device: values[i] = first + sum_{k<i} d1[k], d1 = [fdelta, fdelta+cum
    d2...] — double prefix sum in int32."""
    import jax.numpy as jnp
    S = first.shape[0]
    # d1 row: [fdelta, d2...] cumsum -> deltas between consecutive samples
    d1 = jnp.concatenate(
        [fdelta[:, None], d2.astype(jnp.int32)], axis=1)[:, :max(n - 1, 1)]
    d1 = jnp.cumsum(d1, axis=1)
    vals = jnp.concatenate([first[:, None],
                            first[:, None] + jnp.cumsum(d1, axis=1)], axis=1)
    return vals[:, :n]


@functools.partial(__import__("jax").jit,
                   static_argnames=("n", "value_dtype", "rebase"))
def decode_tiles(planes_ts_first, planes_ts_fd, planes_ts_d2,
                 planes_val_first, planes_val_fd, planes_val_d2,
                 scale, counts, n: int, value_dtype=np.float32,
                 rebase: bool = False):
    """On-device decode of delta planes -> (ts int32 [S,n], vals [S,n]).

    `rebase=True` reconstructs mantissas from ZERO instead of the first
    mantissa — the tile then holds v - v0 exactly in integer space before
    the one dtype-rounding scale multiply (the f32 tile contract)."""
    import jax.numpy as jnp
    ts = _reconstruct(planes_ts_first, planes_ts_fd, planes_ts_d2, counts, n)
    valid = jnp.arange(n, dtype=jnp.int32)[None, :] < counts[:, None]
    ts = jnp.where(valid, ts, TS_PAD)
    vfirst = (planes_val_first * 0) if rebase else planes_val_first
    mant = _reconstruct(vfirst, planes_val_fd, planes_val_d2, counts, n)
    vals = mant.astype(value_dtype) * scale[:, None].astype(value_dtype)
    return ts, vals


@functools.partial(__import__("jax").jit,
                   static_argnames=("func", "cfg", "n", "value_dtype"))
def decode_and_rollup(func: str, planes_ts_first, planes_ts_fd, planes_ts_d2,
                      planes_val_first, planes_val_fd, planes_val_d2,
                      scale, counts, cfg: RollupConfig, n: int,
                      value_dtype=np.float32):
    """Fused on-device decode + rollup -> [S, T]."""
    from .device_rollup import rollup_tile
    ts, vals = decode_tiles(planes_ts_first, planes_ts_fd, planes_ts_d2,
                            planes_val_first, planes_val_fd, planes_val_d2,
                            scale, counts, n, value_dtype)
    return rollup_tile(func, ts, vals, counts, cfg)
