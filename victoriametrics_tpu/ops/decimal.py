"""Decimal codec: float64[] <-> (int64 mantissas, common decimal exponent).

Capability parity with reference lib/decimal/decimal.go (AppendFloatToDecimal
decimal.go:173, AppendDecimalToFloat decimal.go:100, CalibrateScale
decimal.go:13, StaleNaN handling decimal.go:394-427), re-designed as
vectorized NumPy: per-value (mantissa, exponent) decomposition with trailing
-zero stripping runs as fixed-trip masked loops so the same code path can be
traced by JAX later.

Values are stored in blocks as int64 mantissas sharing one decimal exponent:
``v ~= m * 10^e``. Special float values map to reserved int64 constants so
they survive integer codecs:

  NaN        -> V_NAN
  StaleNaN   -> V_STALE_NAN  (Prometheus staleness marker, bits 0x7ff0000000000002)
  +Inf/-Inf  -> V_INF_POS / V_INF_NEG

Normal mantissas are bounded by MAX_MANTISSA (1e17, ~17 significant digits —
beyond float64's precision) so deltas of two mantissas never overflow int64.
"""

from __future__ import annotations

import numpy as np

# Reserved int64 sentinels (chosen to leave |m| <= MAX_MANTISSA for normal values).
V_NAN = -(1 << 63)
V_STALE_NAN = -(1 << 63) + 1
V_INF_NEG = -(1 << 63) + 2
V_INF_POS = (1 << 63) - 1

MAX_MANTISSA = 10 ** 17
_SIG_DIGITS = 17  # max significant decimal digits kept

# Prometheus staleness marker: a specific quiet-NaN bit pattern.
STALE_NAN_BITS = np.uint64(0x7FF0000000000002)
STALE_NAN = float(np.uint64(STALE_NAN_BITS).view(np.float64))

_MIN_EXP = -320
_MAX_EXP = 310


def is_stale_nan(values: np.ndarray) -> np.ndarray:
    """Elementwise test for the staleness-marker NaN (bit-exact)."""
    v = np.asarray(values, dtype=np.float64)
    return v.view(np.uint64) == STALE_NAN_BITS


# Power-of-ten table built by the SAME multiplicative recurrence as the
# native codec (T[k] = T[k-1]*10, T[-k] = 1/T[k]): exact for |e| <= 22 and
# bit-identical across the Python and C++ pipelines — np.power's SIMD path
# differs from libm pow by an ulp at large exponents, which would make
# native-encoded mantissas diverge from Python-encoded ones.
_POW10_MAX = 340
_POW10_TABLE = np.empty(2 * _POW10_MAX + 1, dtype=np.float64)
_POW10_TABLE[_POW10_MAX] = 1.0
with np.errstate(over="ignore"):
    for _k in range(1, _POW10_MAX + 1):
        _POW10_TABLE[_POW10_MAX + _k] = \
            _POW10_TABLE[_POW10_MAX + _k - 1] * 10.0
        if _POW10_TABLE[_POW10_MAX + _k] != np.inf:
            _POW10_TABLE[_POW10_MAX - _k] = \
                1.0 / _POW10_TABLE[_POW10_MAX + _k]
        else:  # subnormal range: continue by division (1/inf would be 0)
            _POW10_TABLE[_POW10_MAX - _k] = \
                _POW10_TABLE[_POW10_MAX - _k + 1] / 10.0
del _k


def _pow10_float(e):
    """10^e as float64; exact for |e| <= 22 (table-driven, see above)."""
    idx = np.asarray(e, dtype=np.int64) + _POW10_MAX
    return _POW10_TABLE[np.clip(idx, 0, 2 * _POW10_MAX)]


def _scalar_mantissa(x: float) -> tuple[int, int]:
    """(mantissa, exponent) of one finite nonzero float via repr(), which is
    the shortest decimal that round-trips — exactly the digits we want."""
    if x == int(x) and abs(x) <= MAX_MANTISSA:
        m, e = int(x), 0
    else:
        s = repr(x)
        if "e" in s:
            mant, _, ex = s.partition("e")
            e = int(ex)
        else:
            mant, e = s, 0
        intpart, _, frac = mant.partition(".")
        e -= len(frac)
        m = int(intpart + frac)
        if abs(m) > MAX_MANTISSA:  # >17 significant digits can't happen via
            while abs(m) > MAX_MANTISSA:  # repr, but stay safe
                m = int(round(m / 10))
                e += 1
    while m != 0 and m % 10 == 0:
        m //= 10
        e += 1
    return m, e


def _float_to_decimal_small(v: np.ndarray) -> tuple[np.ndarray, int]:
    """Scalar path for tiny arrays (the per-series streaming-flush case):
    ~100x lower fixed overhead than the vectorized path."""
    ms: list[int] = []
    es: list[int] = []
    out = np.empty(v.size, dtype=np.int64)
    kinds: list[int] = []  # 0=normal 1=zero, negatives = specials
    for x in v.tolist():
        if x != x:  # NaN family: bit-test for the staleness marker
            bits = np.float64(x).view(np.uint64)
            kinds.append(-1 if bits == STALE_NAN_BITS else -2)
        elif x == np.inf:
            kinds.append(-3)
        elif x == -np.inf:
            kinds.append(-4)
        elif x == 0.0:
            kinds.append(1)
        else:
            m, e = _scalar_mantissa(x)
            ms.append(m)
            es.append(e)
            kinds.append(0)
    if ms:
        exp = min(min(es), _MAX_EXP)
        for m, e in zip(ms, es):
            up = 0
            am = abs(m)
            while am * 10 ** (up + 1) <= MAX_MANTISSA:
                up += 1
            if e - up > exp:
                exp = e - up
        exp = max(min(exp, _MAX_EXP), _MIN_EXP)
    else:
        exp = 0
    i = 0
    k = 0
    for j, kind in enumerate(kinds):
        if kind == 0:
            m, e = ms[i], es[i]
            x = float(v[j])
            i += 1
            shift = e - exp
            if shift > 0:
                mm = m * 10 ** shift
                if abs(mm) <= (1 << 53) or x == int(x):
                    # exact: small enough for the float cast, or integer-
                    # origin (decimal_to_float recovers those by exact
                    # integer division)
                    m = mm
                else:
                    # fractional + big mantissa: re-derive at the final
                    # exponent like the vector path — repr() digits are the
                    # SHORTEST form, zero-padding them would round-trip off
                    # by an ulp through the float division
                    if exp < 0:
                        k1 = min(-exp, 300)
                        m = int(round(x * 10.0 ** k1 * 10.0 ** (-exp - k1)))
                    else:
                        m = int(round(x / 10.0 ** exp))
            elif shift < 0:
                m = int(round(m / 10 ** min(-shift, 19)))
            out[j] = min(max(m, -MAX_MANTISSA), MAX_MANTISSA)
        elif kind == 1:
            out[j] = 0
        else:
            out[j] = (V_STALE_NAN, V_NAN, V_INF_POS,
                      V_INF_NEG)[-kind - 1]
    return out, exp


def float_to_decimal(values: np.ndarray) -> tuple[np.ndarray, int]:
    """Convert float64 array to (int64 mantissas, common exponent).

    Lossy only when values span more decimal orders than MAX_MANTISSA allows;
    "nice" decimal values (integers, few decimal places) round-trip exactly.
    """
    v = np.asarray(values, dtype=np.float64)
    n = v.size
    if n == 0:
        return np.zeros(0, dtype=np.int64), 0
    if n <= 8:
        return _float_to_decimal_small(v)
    m, e, normal, specials = _f2d_element_phase(v)
    if normal.any():
        e_norm = e[normal]
        m_norm = m[normal]
        # Common exponent: as small as possible without overflowing mantissas.
        # Scaling m from exponent E down to `exp` multiplies it by 10^(E-exp);
        # the largest allowed up-shift for m is floor(log10(MAX_MANTISSA/|m|)).
        absm = np.abs(m_norm).astype(np.float64)
        absm = np.maximum(absm, 1.0)
        allowed_up = np.floor(np.log10(MAX_MANTISSA / absm)).astype(np.int64)
        exp = int(min(e_norm.min(), _MAX_EXP))
        exp_floor = int((e_norm - allowed_up).max())
        if exp_floor > exp:
            exp = exp_floor
        exp = max(min(exp, _MAX_EXP), _MIN_EXP)
        m = _f2d_rescale(m, e, normal, np.int64(exp))
    else:
        exp = 0
    m = _f2d_apply_specials(m, specials)
    return m, int(exp)


def _f2d_element_phase(v: np.ndarray):
    """Element-wise mantissa/exponent extraction (shared by the per-block
    and grouped entry points): returns (m, e, normal, specials) BEFORE
    common-exponent unification."""
    n = v.size
    stale = is_stale_nan(v)
    nan = np.isnan(v) & ~stale
    posinf = np.isposinf(v)
    neginf = np.isneginf(v)
    zero = v == 0.0
    special = stale | nan | posinf | neginf
    normal = ~special & ~zero

    m = np.zeros(n, dtype=np.int64)
    e = np.zeros(n, dtype=np.int64)

    if normal.any():
        vn = np.where(normal, v, 1.0)
        exp10 = np.floor(np.log10(np.abs(vn))).astype(np.int64)

        def _scale_up(x, e):
            # x * 10^e for e >= 0 without overflowing the float64 power:
            # split the exponent so each factor stays finite (10^e overflows
            # above e=308 even when the product x*10^e is tiny).
            e1 = np.minimum(e, 300)
            e2 = e - e1
            return x * _pow10_float(e1) * _pow10_float(e2)

        def _decompose(digits):
            ei = np.clip(exp10 - (digits - 1), _MIN_EXP, _MAX_EXP)
            # m = round(v / 10^ei); for negative ei multiply by 10^-ei (exact
            # float64 power of ten for small magnitudes) to minimise error.
            scaled = np.where(ei < 0, _scale_up(vn, -ei), vn / _pow10_float(ei))
            mi = np.round(scaled)
            # Guard against 1-off exponent from floor(log10) at power edges.
            over = np.abs(mi) >= 10 ** digits
            if over.any():
                ei = np.where(over, ei + 1, ei)
                scaled = np.where(ei < 0, _scale_up(vn, -ei), vn / _pow10_float(ei))
                mi = np.round(scaled)
            # Clamp before the int64 cast: a residual overflow must saturate
            # at MAX_MANTISSA, never wrap into the reserved sentinel range.
            mi = np.clip(mi, -MAX_MANTISSA, MAX_MANTISSA)
            return mi.astype(np.int64), ei

        # Three-way extraction, first match wins:
        # 1. integer-valued floats up to MAX_MANTISSA: direct int64 cast is
        #    exact (scaling by powers of ten would round above 2^53);
        # 2. 15 significant digits when they reconstruct bit-exactly
        #    (decimal-representable scrape text), giving small mantissas;
        # 3. 17 digits for values needing full float64 precision (e.g. 2/3).
        is_int = (vn == np.floor(vn)) & (np.abs(vn) <= MAX_MANTISSA)
        m15, e15 = _decompose(15)
        recon = np.where(e15 < 0,
                         m15.astype(np.float64) / _pow10_float(-e15),
                         m15.astype(np.float64) * _pow10_float(e15))
        exact15 = recon == vn
        m17, e17 = _decompose(_SIG_DIGITS)
        mi = np.where(is_int, np.where(is_int, vn, 0.0).astype(np.int64),
                      np.where(exact15, m15, m17))
        ei = np.where(is_int, 0, np.where(exact15, e15, e17))
        # Strip trailing decimal zeros (fixed-trip masked loop, max 17 iters).
        for _ in range(_SIG_DIGITS):
            can = (mi != 0) & (mi % 10 == 0) & normal
            if not can.any():
                break
            mi = np.where(can, mi // 10, mi)
            ei = np.where(can, ei + 1, ei)
        m = np.where(normal, mi, m)
        e = np.where(normal, ei, e)
    return m, e, normal, (stale, nan, posinf, neginf)


def _f2d_rescale(m, e, normal, exp):
    """Rescale normal mantissas from their own exponents to `exp` (scalar
    int64 or per-element int64 array)."""
    shift = e - exp
    up = normal & (shift > 0)
    down = normal & (shift < 0)
    if up.any():
        # Exact int64 multiply: the shifted product is bounded by
        # MAX_MANTISSA (1e17 < 2^63) by construction of allowed_up, and a
        # float64 multiply here would corrupt mantissas above 2^53.
        factor = np.power(np.int64(10), np.where(up, shift, 0).astype(np.int64))
        m = np.where(up, m * factor, m)
    if down.any():
        # Lossy: value has more precision than the common scale can hold.
        # Shifts beyond 18 decimal places collapse the mantissa to zero.
        dshift = np.minimum(np.where(down, -shift, 1), 19)
        factor = _pow10_float(dshift)
        m = np.where(down, np.round(m.astype(np.float64) / factor).astype(np.int64), m)
    return m


def _f2d_apply_specials(m, specials):
    stale, nan, posinf, neginf = specials
    m = np.where(stale, V_STALE_NAN, m)
    m = np.where(nan, V_NAN, m)
    m = np.where(posinf, V_INF_POS, m)
    m = np.where(neginf, V_INF_NEG, m)
    return m


def float_to_decimal_grouped(values: np.ndarray, starts: np.ndarray
                             ) -> tuple[np.ndarray, np.ndarray]:
    """Per-group float_to_decimal over a concatenation — bit-identical to
    calling float_to_decimal on each segment, but the element-wise phase
    runs ONCE over the whole array and the per-group common-exponent
    unification is reduceat-vectorized. The flush path batches thousands of
    small per-series blocks through this (the per-call overhead of the
    vectorized pipeline dominates at ~24-sample scrape blocks).

    starts: sorted int group start offsets; ends are implied. Returns
    (mantissas, exps[int64, one per group]). Every group rides the same
    vectorized element phase — one batched call amortizes the per-call
    overhead that makes the scalar path attractive for single tiny
    conversions, and per-group Python would otherwise dominate scrape-flush
    conversion (~25us/group). Full-precision (non-decimal) floats may
    round a final ulp differently than the repr-based scalar path; decimal
    data converts identically."""
    v = np.asarray(values, dtype=np.float64)
    starts = np.asarray(starts, dtype=np.int64)
    n_groups = starts.size
    exps = np.zeros(n_groups, dtype=np.int64)
    if v.size == 0 or n_groups == 0:
        return np.zeros(v.size, dtype=np.int64), exps
    if v.size >= 256:
        # bit-identical native twin (differentially tested, shared pow10
        # table) — the flush hot path
        from .. import native
        got = native.f2d_grouped(v, starts)
        if got is not None:
            return got
    ends = np.append(starts[1:], v.size)
    sizes = ends - starts
    m, e, normal, specials = _f2d_element_phase(v)
    BIG = np.int64(1 << 40)
    absm = np.maximum(np.abs(m).astype(np.float64), 1.0)
    allowed_up = np.floor(np.log10(MAX_MANTISSA / absm)).astype(np.int64)
    emin_g = np.minimum.reduceat(np.where(normal, e, BIG), starts)
    floor_g = np.maximum.reduceat(
        np.where(normal, e - allowed_up, -BIG), starts)
    has_norm_g = np.logical_or.reduceat(normal, starts)
    exp_g = np.minimum(emin_g, _MAX_EXP)
    exp_g = np.where(floor_g > exp_g, floor_g, exp_g)
    exp_g = np.clip(exp_g, _MIN_EXP, _MAX_EXP)
    exp_g = np.where(has_norm_g, exp_g, 0)
    exp_elem = np.repeat(exp_g, sizes)
    m_all = _f2d_rescale(m, e, normal, exp_elem)
    m_out = _f2d_apply_specials(m_all, specials)
    return m_out, exp_g.astype(np.int64)


def decimal_to_float(ints: np.ndarray, exponent: int) -> np.ndarray:
    """Convert (int64 mantissas, exponent) back to float64 values.

    Division by an exact power of ten is used for negative exponents so that
    typical decimal values round-trip bit-exactly.
    """
    m = np.asarray(ints, dtype=np.int64)
    stale = m == V_STALE_NAN
    nan = m == V_NAN
    posinf = m == V_INF_POS
    neginf = m == V_INF_NEG
    special = stale | nan | posinf | neginf

    mn = np.where(special, 0, m)
    mf = mn.astype(np.float64)
    if exponent == 0:
        out = mf
    elif exponent < 0:
        if exponent >= -22:
            out = mf / _pow10_float(-exponent)
            if exponent >= -18:
                # Mantissas above 2^53 round in the int64->float64 cast; when
                # the division is exact in integers, divide first instead.
                p = np.int64(10) ** np.int64(-exponent)
                q = mn // p
                exact = (mn - q * p == 0)
                out = np.where(exact, q.astype(np.float64), out)
        else:
            out = mf * _pow10_float(exponent)
    else:
        out = mf * _pow10_float(exponent)

    out = np.where(stale, STALE_NAN, out)
    out = np.where(nan, np.nan, out)
    out = np.where(posinf, np.inf, out)
    out = np.where(neginf, -np.inf, out)
    return out


#: sample count above which one exponent run is split across pool workers
_BLOCKS_SPLIT_MIN = 1 << 19


def decimal_to_float_blocks_py(mants: np.ndarray, goff: np.ndarray,
                               scales: np.ndarray, out: np.ndarray,
                               pool=None) -> np.ndarray:
    """Pure-numpy twin of native.decimal_to_float_blocks: convert
    per-block (mantissa, exponent) columns into float64 `out` in place.

    ``goff`` is the (K+1,) exclusive block-offset prefix; block k owns
    samples [goff[k], goff[k+1]) at exponent ``scales[k]``.

    One sort-by-scale pass: blocks are argsorted by exponent (K log K on
    BLOCK count, not samples), their sample positions gathered once, and
    each distinct exponent converts its whole sample run in one
    decimal_to_float call — O(samples + K log K), replacing the old
    per-exponent full-length repeat mask that made the fallback
    O(samples x distinct_exponents).

    Disjoint runs (and oversized single runs) optionally split across
    ``pool`` (utils/workpool.WorkPool): every task writes a disjoint
    region of ``out``, so parallel execution is bit-identical."""
    K = int(scales.size)
    if K == 0 or out.size == 0:
        return out
    uniq = np.unique(scales)
    if uniq.size == 1:
        # common case (one part, uniform scrape payloads): no gather at all
        out[:] = decimal_to_float(mants, int(uniq[0]))
        return out
    cnts = goff[1:] - goff[:-1]
    order = np.argsort(scales, kind="stable")
    ss = scales[order]
    sorted_cnts = cnts[order]
    tot = int(sorted_cnts.sum())
    excl = np.cumsum(sorted_cnts) - sorted_cnts
    pos = np.repeat(goff[:-1][order] - excl, sorted_cnts) + \
        np.arange(tot, dtype=np.int64)
    runs = []                       # (sample_lo, sample_hi, exponent)
    bstart = np.flatnonzero(np.concatenate([[True], ss[1:] != ss[:-1]]))
    sstart = excl[bstart]
    send = np.append(sstart[1:], tot)
    for lo, hi, e in zip(sstart, send, ss[bstart]):
        lo, hi, e = int(lo), int(hi), int(e)
        # split giant runs so the pool can overlap them too
        step = max(_BLOCKS_SPLIT_MIN, -(-(hi - lo) // 8))
        for a in range(lo, hi, step):
            runs.append((a, min(a + step, hi), e))

    def conv(lo: int, hi: int, e: int):
        p = pos[lo:hi]
        out[p] = decimal_to_float(mants[p], e)

    if pool is not None and len(runs) > 1 and tot >= _BLOCKS_SPLIT_MIN:
        from functools import partial
        pool.run([partial(conv, *r) for r in runs])
    else:
        for r in runs:
            conv(*r)
    return out


def calibrate_scale(a: np.ndarray, a_exp: int, b: np.ndarray, b_exp: int
                    ) -> tuple[np.ndarray, np.ndarray, int]:
    """Bring two mantissa arrays to a common exponent (reference
    CalibrateScale, decimal.go:13). Scales the larger-exponent side up unless
    that overflows MAX_MANTISSA, in which case the smaller side loses digits.
    """
    if a_exp == b_exp:
        return a, b, a_exp
    if a_exp > b_exp:
        b2, a2, e = calibrate_scale(b, b_exp, a, a_exp)
        return a2, b2, e

    # a_exp < b_exp: try to shift b down to a_exp.
    def _specials(x):
        return (x == V_STALE_NAN) | (x == V_NAN) | (x == V_INF_POS) | (x == V_INF_NEG)

    shift = b_exp - a_exp
    bsp = _specials(b)
    babs = np.abs(np.where(bsp, 0, b)).astype(np.float64)
    maxb = babs.max() if b.size else 0.0
    if maxb == 0.0:
        # b has no normal mantissas — zeros scale to any exponent for free.
        return a, b, a_exp
    if shift <= 18 and maxb * (10.0 ** shift) <= MAX_MANTISSA:
        factor = np.int64(10) ** np.int64(shift)
        b2 = np.where(bsp, b, b * factor)
        return a, b2, a_exp
    # Can't shift b down fully: shift a up (lossy on a).
    asp = _specials(a)
    factor = 10.0 ** shift
    a2 = np.where(asp, a, np.round(np.where(asp, 0, a).astype(np.float64) / factor).astype(np.int64))
    return a2, b, b_exp
