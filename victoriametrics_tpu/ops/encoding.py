"""Adaptive int64-array block encoding (reference lib/encoding/encoding.go).

Pipeline (reference encoding.go:119-170, re-designed around NumPy bulk ops):
int64 array -> pick MarshalType:

  CONST        all values equal                       (encoding.go:82-117 analog)
  DELTA_CONST  arithmetic progression (counters with fixed scrape interval)
  NEAREST_DELTA   gauge-like series: lossy first-order deltas
  NEAREST_DELTA2  counter-like series: lossy second-order deltas

then varint-pack the deltas and zstd them only when the payload is >= 128
bytes and compression saves >= 1/8 of the size (encoding.go:15,136-170).

Timestamps use the same path with precision_bits=64 (lossless); adaptive
choice almost always lands on DELTA_CONST or NEAREST_DELTA2 since timestamps
are near-arithmetic.

The (marshal_type, first_value) pair lives in the block header, not the
payload, mirroring the reference's blockHeader layout.
"""

from __future__ import annotations

import enum

import numpy as np

from . import compress as zstd
from .nearest_delta import (nearest_delta2_decode, nearest_delta2_encode,
                            nearest_delta_decode, nearest_delta_encode)
from .varint import marshal_varint64s, unmarshal_varint64s

try:  # native C++ codec kernels (victoriametrics_tpu/native/codec.cpp)
    from .. import native as _native
    _HAVE_NATIVE = _native.available()
except Exception:  # pragma: no cover - missing compiler
    _native = None
    _HAVE_NATIVE = False


class MarshalType(enum.IntEnum):
    CONST = 1
    DELTA_CONST = 2
    NEAREST_DELTA = 3
    NEAREST_DELTA2 = 4
    ZSTD_NEAREST_DELTA = 5
    ZSTD_NEAREST_DELTA2 = 6

    @property
    def needs_validation(self) -> bool:
        # Uncompressed lossy encodings carry no zstd checksum; decoded
        # timestamp sequences must be re-validated (encoding.go:46-57 analog).
        return self in (MarshalType.NEAREST_DELTA, MarshalType.NEAREST_DELTA2)


MIN_COMPRESSIBLE_BLOCK_SIZE = 128  # bytes; below this zstd never pays off
_MIN_COMPRESS_RATIO = 8 / 7        # require >= 12.5% shrink


def is_const(values: np.ndarray) -> bool:
    v = np.asarray(values)
    return v.size > 0 and bool((v == v[0]).all())


def is_delta_const(values: np.ndarray) -> bool:
    v = np.asarray(values, dtype=np.int64)
    if v.size < 2:
        return False
    d = v[1:] - v[:-1]
    return bool((d == d[0]).all())


def is_gauge(values: np.ndarray) -> bool:
    """Heuristic: counters are (mostly) non-decreasing; a series with more
    than 1/8 negative deltas is treated as a gauge (first-order deltas)."""
    v = np.asarray(values, dtype=np.int64)
    if v.size < 2:
        return False
    neg = int((v[1:] < v[:-1]).sum())
    return neg * 8 > v.size


def _maybe_compress(data: bytes, plain_type: MarshalType,
                    zstd_type: MarshalType) -> tuple[bytes, MarshalType]:
    if len(data) < MIN_COMPRESSIBLE_BLOCK_SIZE:
        return data, plain_type
    packed = zstd.compress(data)
    if len(packed) * _MIN_COMPRESS_RATIO < len(data):
        return packed, zstd_type
    return data, plain_type


def marshal_int64_array(values: np.ndarray, precision_bits: int = 64
                        ) -> tuple[bytes, MarshalType, int]:
    """Returns (payload, marshal_type, first_value)."""
    v = np.asarray(values, dtype=np.int64)
    if v.size == 0:
        raise ValueError("marshal_int64_array: empty input")
    if is_const(v):
        return b"", MarshalType.CONST, int(v[0])
    if is_delta_const(v):
        # wrapping int64 subtraction: sentinel mantissas (stale NaN / inf)
        # sit near the int64 bounds and must round-trip via two's complement
        with np.errstate(over="ignore"):
            d = int(v[1] - v[0])
        return marshal_varint64s(np.array([d], dtype=np.int64)), \
            MarshalType.DELTA_CONST, int(v[0])
    if is_gauge(v):
        if _HAVE_NATIVE and precision_bits >= 64:
            data, first = _native.delta_encode(v)
        else:
            first, deltas = nearest_delta_encode(v, precision_bits)
            data = marshal_varint64s(deltas)
        data, mt = _maybe_compress(data, MarshalType.NEAREST_DELTA,
                                   MarshalType.ZSTD_NEAREST_DELTA)
        return data, mt, first
    if _HAVE_NATIVE and precision_bits >= 64:
        d2_payload, first, first_delta = _native.delta2_encode(v)
        data = _native.varint_encode(
            np.array([first_delta], dtype=np.int64)) + d2_payload
    else:
        first, first_delta, d2 = nearest_delta2_encode(v, precision_bits)
        stream = np.empty(d2.size + 1, dtype=np.int64)
        stream[0] = first_delta
        stream[1:] = d2
        data = marshal_varint64s(stream)
    data, mt = _maybe_compress(data, MarshalType.NEAREST_DELTA2,
                               MarshalType.ZSTD_NEAREST_DELTA2)
    return data, mt, first


def unmarshal_int64_array(data: bytes, marshal_type: MarshalType,
                          first_value: int, count: int) -> np.ndarray:
    mt = MarshalType(marshal_type)
    if count <= 0:
        raise ValueError("unmarshal_int64_array: count must be positive")
    if mt == MarshalType.CONST:
        return np.full(count, first_value, dtype=np.int64)
    if mt == MarshalType.DELTA_CONST:
        d = int(unmarshal_varint64s(data, 1)[0])
        return first_value + np.arange(count, dtype=np.int64) * d
    if mt in (MarshalType.ZSTD_NEAREST_DELTA, MarshalType.ZSTD_NEAREST_DELTA2):
        data = zstd.decompress(data)
        mt = (MarshalType.NEAREST_DELTA
              if mt == MarshalType.ZSTD_NEAREST_DELTA
              else MarshalType.NEAREST_DELTA2)
    if mt == MarshalType.NEAREST_DELTA:
        if _HAVE_NATIVE:
            return _native.delta_decode(data, first_value, count)
        deltas = unmarshal_varint64s(data, count - 1)
        return nearest_delta_decode(first_value, deltas)
    if mt == MarshalType.NEAREST_DELTA2:
        if _HAVE_NATIVE and count >= 2:
            # split off the leading first_delta varint, then fused decode
            i = 0
            while i < len(data) and data[i] & 0x80:
                i += 1
                if i >= 10:
                    raise ValueError("varint: too long encoded varint")
            if i >= len(data):
                raise ValueError("varint: truncated trailing value")
            fd = int(unmarshal_varint64s(data[:i + 1], 1)[0])
            return _native.delta2_decode(data[i + 1:], first_value, fd, count)
        stream = unmarshal_varint64s(data, count - 1)
        return nearest_delta2_decode(first_value, int(stream[0]), stream[1:])
    raise ValueError(f"unknown marshal type {marshal_type}")


def marshal_timestamps(timestamps: np.ndarray, precision_bits: int = 64
                       ) -> tuple[bytes, MarshalType, int]:
    """Timestamps (unix ms) use the lossless path by default
    (encoding.go:82 MarshalTimestamps analog)."""
    return marshal_int64_array(timestamps, precision_bits)


def unmarshal_timestamps(data: bytes, marshal_type: MarshalType,
                         first_value: int, count: int) -> np.ndarray:
    ts = unmarshal_int64_array(data, marshal_type, first_value, count)
    if MarshalType(marshal_type).needs_validation:
        ts = ensure_non_decreasing_sequence(ts)
    return ts


def marshal_values(values: np.ndarray, precision_bits: int = 64
                   ) -> tuple[bytes, MarshalType, int]:
    return marshal_int64_array(values, precision_bits)


unmarshal_values = unmarshal_int64_array


def ensure_non_decreasing_sequence(ts: np.ndarray) -> np.ndarray:
    """Clamp decoded timestamps to be non-decreasing (post-decode validation
    for non-checksummed lossy encodings; encoding.go:258 analog)."""
    return np.maximum.accumulate(np.asarray(ts, dtype=np.int64))
