"""Lossy nearest-delta codecs (reference lib/encoding/nearest_delta.go:15,83
and nearest_delta2.go:15,53).

nearest-delta: store first value + per-sample deltas rounded to keep only the
top `precision_bits` binary digits of each delta (gauges).
nearest-delta2: the same over second-order deltas (counters / timestamps,
which are near-linear so double deltas are tiny).

precision_bits is 1..64; 64 means lossless and runs as a pure vector op.
Lossy encode (<64) uses error feedback — each delta is taken against the
*reconstructed* previous value so rounding error never accumulates — which is
a sequential dependency, kept as a host loop (it is opt-in, off the default
path; the C++ host kernel later replaces it). Decode is always a (double)
prefix sum — exactly the shape that runs on TPU as
`jax.lax.associative_scan` in ops/device_decode.py.
"""

from __future__ import annotations

import numpy as np

from .varint import bit_len_u64


def round_to_precision_bits(d: np.ndarray, precision_bits: int) -> np.ndarray:
    """Zero out low bits of each delta so only precision_bits significant
    binary digits remain (truncation toward zero, like the reference)."""
    d = np.asarray(d, dtype=np.int64)
    if precision_bits >= 64:
        return d
    absd = np.abs(d).astype(np.uint64)
    bits = bit_len_u64(absd)
    drop = np.maximum(bits - precision_bits, 0).astype(np.uint64)
    rounded = ((absd >> drop) << drop).astype(np.int64)
    return np.where(d < 0, -rounded, rounded)


def _wrap64(x: int) -> int:
    """Wrap an unbounded Python int to two's-complement int64 (the lossy
    encode loops must match the wrapping array arithmetic of the lossless
    path when sentinel mantissas sit near the int64 bounds)."""
    return ((x + (1 << 63)) & ((1 << 64) - 1)) - (1 << 63)


def _round_scalar(d: int, precision_bits: int) -> int:
    if precision_bits >= 64:
        return d
    absd = abs(d)
    drop = max(absd.bit_length() - precision_bits, 0)
    rounded = (absd >> drop) << drop
    return -rounded if d < 0 else rounded


def nearest_delta_encode(values: np.ndarray, precision_bits: int
                         ) -> tuple[int, np.ndarray]:
    """Returns (first_value, deltas[1:]) with error feedback when lossy."""
    v = np.asarray(values, dtype=np.int64)
    if v.size == 0:
        raise ValueError("nearest_delta: empty input")
    if precision_bits >= 64:
        return int(v[0]), (v[1:] - v[:-1])
    out = np.empty(v.size - 1, dtype=np.int64)
    rec = int(v[0])
    for i in range(1, v.size):
        d = _round_scalar(_wrap64(int(v[i]) - rec), precision_bits)
        rec = _wrap64(rec + d)
        out[i - 1] = d
    return int(v[0]), out


def nearest_delta_decode(first: int, deltas: np.ndarray) -> np.ndarray:
    out = np.empty(deltas.size + 1, dtype=np.int64)
    out[0] = first
    np.cumsum(deltas, out=out[1:])
    out[1:] += first
    return out


def nearest_delta2_encode(values: np.ndarray, precision_bits: int
                          ) -> tuple[int, int, np.ndarray]:
    """Returns (first_value, first_delta, second deltas) with error feedback."""
    v = np.asarray(values, dtype=np.int64)
    if v.size < 2:
        raise ValueError("nearest_delta2: need >= 2 values")
    if precision_bits >= 64:
        d1 = v[1:] - v[:-1]  # wrapping int64 (sentinels near the bounds)
        return int(v[0]), int(d1[0]), (d1[1:] - d1[:-1])
    out = np.empty(v.size - 2, dtype=np.int64)
    first_delta = _wrap64(int(v[1]) - int(v[0]))
    rec = int(v[1])
    rec_d = first_delta
    for i in range(2, v.size):
        d2 = _round_scalar(_wrap64(int(v[i]) - rec - rec_d), precision_bits)
        rec_d = _wrap64(rec_d + d2)
        rec = _wrap64(rec + rec_d)
        out[i - 2] = d2
    return int(v[0]), first_delta, out


def nearest_delta2_decode(first: int, first_delta: int, d2: np.ndarray) -> np.ndarray:
    d1 = np.empty(d2.size + 1, dtype=np.int64)
    d1[0] = first_delta
    np.cumsum(d2, out=d1[1:])
    d1[1:] += first_delta
    out = np.empty(d1.size + 1, dtype=np.int64)
    out[0] = first
    np.cumsum(d1, out=out[1:])
    out[1:] += first
    return out
