// Native ingest kernels: snappy decode, remote-write protobuf parse,
// influx line-protocol parse, and a raw-key -> dense-id hash map.
//
// The reference treats every ingest protocol as a hot path with pooled
// zero-copy scanners (lib/protoparser/promremotewrite/parser.go,
// lib/protoparser/influx/parser.go, lib/easyproto); the Python parsers top
// out near 20k rows/s and dominate ingest cost. These kernels parse whole
// request bodies in one call and emit COLUMNAR rows:
//   keybuf[key_off[i] : key_off[i]+key_len[i]]  canonical `name{l="v"}` key
//   values[i], tss[i]
// so the Python layer never touches individual rows. The key map assigns
// dense int ids to distinct key byte-strings (vm_keymap_resolve), letting
// storage keep per-id TSID/date state in numpy arrays and resolve an
// entire batch with one native call (the MarshaledMetricNameRaw fast path
// of the reference's storage.go:1874, vectorized).
//
// Fallback contract: parsers return -1 when the payload contains shapes
// the canonical text key cannot round-trip (label names with text-format
// metacharacters, missing __name__); callers fall back to the Python
// parser for the whole body. -2 means an output buffer was too small
// (caller retries with a bigger one).
//
// Build: part of libvmcodec.so (see Makefile).

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// ---------------------------------------------------------------- snappy --

inline bool read_uvarint(const uint8_t* p, int64_t len, int64_t* pos,
                         uint64_t* out) {
    uint64_t v = 0;
    int shift = 0;
    while (*pos < len && shift < 64) {
        uint8_t b = p[(*pos)++];
        v |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) { *out = v; return true; }
        shift += 7;
    }
    return false;
}

}  // namespace

extern "C" {

// Uncompressed length of a snappy block, or -1 if malformed.
int64_t vm_snappy_uncompressed_len(const uint8_t* src, int64_t len) {
    int64_t pos = 0;
    uint64_t n;
    if (!read_uvarint(src, len, &pos, &n)) return -1;
    return (int64_t)n;
}

// Snappy block-format decompress. Returns bytes written or -1 on malformed
// input / undersized dst.
int64_t vm_snappy_uncompress(const uint8_t* src, int64_t len,
                             uint8_t* dst, int64_t dst_cap) {
    int64_t pos = 0;
    uint64_t want;
    if (!read_uvarint(src, len, &pos, &want)) return -1;
    if ((int64_t)want > dst_cap) return -1;
    int64_t d = 0;
    while (pos < len) {
        uint8_t tag = src[pos++];
        uint32_t kind = tag & 3;
        if (kind == 0) {  // literal
            int64_t n = (tag >> 2) + 1;
            if (n > 60) {
                int extra = (int)(n - 60);
                if (pos + extra > len) return -1;
                uint32_t v = 0;
                for (int i = 0; i < extra; i++) v |= (uint32_t)src[pos + i] << (8 * i);
                pos += extra;
                n = (int64_t)v + 1;
            }
            if (pos + n > len || d + n > dst_cap) return -1;
            memcpy(dst + d, src + pos, n);
            pos += n;
            d += n;
        } else {
            int64_t n, off;
            if (kind == 1) {
                if (pos >= len) return -1;
                n = ((tag >> 2) & 7) + 4;
                off = ((int64_t)(tag >> 5) << 8) | src[pos++];
            } else if (kind == 2) {
                if (pos + 2 > len) return -1;
                n = (tag >> 2) + 1;
                off = (int64_t)src[pos] | ((int64_t)src[pos + 1] << 8);
                pos += 2;
            } else {
                if (pos + 4 > len) return -1;
                n = (tag >> 2) + 1;
                off = (int64_t)src[pos] | ((int64_t)src[pos + 1] << 8) |
                      ((int64_t)src[pos + 2] << 16) | ((int64_t)src[pos + 3] << 24);
                pos += 4;
            }
            if (off <= 0 || off > d || d + n > dst_cap) return -1;
            // copies may overlap (run-length encoding): byte loop when close
            if (off >= n) {
                memcpy(dst + d, dst + d - off, n);
            } else {
                for (int64_t i = 0; i < n; i++) dst[d + i] = dst[d + i - off];
            }
            d += n;
        }
    }
    return d == (int64_t)want ? d : -1;
}

}  // extern "C"

namespace {

// -------------------------------------------------- canonical key writing --

// Label NAMES and metric names must survive a prometheus-text round-trip
// (ingest/parsers.labels_from_series_key re-parses the key on TSID-cache
// misses), so text metacharacters in them force the Python fallback.
inline bool name_ok(const uint8_t* p, int64_t n) {
    if (n == 0) return false;
    for (int64_t i = 0; i < n; i++) {
        uint8_t c = p[i];
        if (c == '{' || c == '}' || c == '"' || c == '=' || c == ',' ||
            c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\\')
            return false;
    }
    return true;
}

// Escapes a label VALUE into out (prometheus text escaping). Returns bytes
// written or -1 when cap is exhausted.
inline int64_t write_escaped(const uint8_t* p, int64_t n, uint8_t* out,
                             int64_t cap) {
    int64_t w = 0;
    for (int64_t i = 0; i < n; i++) {
        uint8_t c = p[i];
        if (c == '\\' || c == '"') {
            if (w + 2 > cap) return -1;
            out[w++] = '\\';
            out[w++] = c;
        } else if (c == '\n') {
            if (w + 2 > cap) return -1;
            out[w++] = '\\';
            out[w++] = 'n';
        } else {
            if (w + 1 > cap) return -1;
            out[w++] = c;
        }
    }
    return w;
}

struct Span { const uint8_t* p; int64_t n; };

// Writes `name{k1="v1",...}` (no braces when no labels). Returns bytes
// written or -1 (cap exhausted).
inline int64_t write_key(const Span& name, const Span* lk, const Span* lv,
                         int nlabels, uint8_t* out, int64_t cap) {
    int64_t w = 0;
    if (name.n > cap) return -1;
    memcpy(out, name.p, name.n);
    w = name.n;
    if (nlabels == 0) return w;
    if (w + 1 > cap) return -1;
    out[w++] = '{';
    for (int i = 0; i < nlabels; i++) {
        if (i) {
            if (w + 1 > cap) return -1;
            out[w++] = ',';
        }
        if (w + lk[i].n + 2 > cap) return -1;
        memcpy(out + w, lk[i].p, lk[i].n);
        w += lk[i].n;
        out[w++] = '=';
        out[w++] = '"';
        int64_t e = write_escaped(lv[i].p, lv[i].n, out + w, cap - w);
        if (e < 0) return -1;
        w += e;
        if (w + 1 > cap) return -1;
        out[w++] = '"';
    }
    if (w + 1 > cap) return -1;
    out[w++] = '}';
    return w;
}

// ------------------------------------------------------------- protobuf --

struct PbReader {
    const uint8_t* p;
    int64_t len, pos;
    bool ok;

    uint64_t uvarint() {
        uint64_t v;
        if (!read_uvarint(p, len, &pos, &v)) { ok = false; return 0; }
        return v;
    }
    // Returns field number, sets wire type; false at end / error.
    bool field(uint32_t* fnum, uint32_t* wt) {
        if (pos >= len || !ok) return false;
        uint64_t tag = uvarint();
        if (!ok) return false;
        *fnum = (uint32_t)(tag >> 3);
        *wt = (uint32_t)(tag & 7);
        return true;
    }
    Span bytes_field() {  // wire type 2
        uint64_t n = uvarint();
        if (!ok || pos + (int64_t)n > len) { ok = false; return {nullptr, 0}; }
        Span s{p + pos, (int64_t)n};
        pos += (int64_t)n;
        return s;
    }
    uint64_t fixed64() {
        if (pos + 8 > len) { ok = false; return 0; }
        uint64_t v;
        memcpy(&v, p + pos, 8);
        pos += 8;
        return v;
    }
    void skip(uint32_t wt) {
        switch (wt) {
            case 0: uvarint(); break;
            case 1: pos += 8; if (pos > len) ok = false; break;
            case 2: bytes_field(); break;
            case 5: pos += 4; if (pos > len) ok = false; break;
            default: ok = false;
        }
    }
};

constexpr int kMaxLabels = 128;
constexpr int64_t kTsAbsent = INT64_MIN;

}  // namespace

extern "C" {

// Parses a prompb.WriteRequest (uncompressed) into columnar rows.
// Sample timestamps of 0/absent become default_ts (the HTTP handler's
// `ts or now`). Returns rows written, -1 = fall back to the Python
// parser, -2 = keybuf too small, -3 = max_rows too small.
int64_t vm_parse_rw(const uint8_t* data, int64_t len, int64_t default_ts,
                    uint8_t* keybuf, int64_t keybuf_cap,
                    int64_t* key_off, int64_t* key_len,
                    double* values, int64_t* tss, int64_t max_rows) {
    PbReader top{data, len, 0, true};
    int64_t n = 0, kw = 0;
    uint32_t fnum, wt;
    Span lk[kMaxLabels], lv[kMaxLabels];
    // per-series sample buffer (order of labels/samples fields is free)
    int64_t scap = 1024;
    double* sv = (double*)malloc(scap * sizeof(double));
    int64_t* st = (int64_t*)malloc(scap * sizeof(int64_t));
    if (!sv || !st) { free(sv); free(st); return -1; }
    while (top.field(&fnum, &wt)) {
        if (!(fnum == 1 && wt == 2)) { top.skip(wt); continue; }
        PbReader ts_r{nullptr, 0, 0, true};
        {
            Span s = top.bytes_field();
            if (!top.ok) break;
            ts_r = {s.p, s.n, 0, true};
        }
        int nlabels = 0;
        int64_t nsamples = 0;
        Span name{nullptr, 0};
        bool bad = false;
        uint32_t f2, w2;
        while (ts_r.field(&f2, &w2)) {
            if (f2 == 1 && w2 == 2) {  // Label
                Span lb = ts_r.bytes_field();
                if (!ts_r.ok) break;
                PbReader lr{lb.p, lb.n, 0, true};
                Span ln{nullptr, 0}, lval{nullptr, 0};
                uint32_t f3, w3;
                while (lr.field(&f3, &w3)) {
                    if (f3 == 1 && w3 == 2) ln = lr.bytes_field();
                    else if (f3 == 2 && w3 == 2) lval = lr.bytes_field();
                    else lr.skip(w3);
                }
                if (!lr.ok) { bad = true; break; }
                if (ln.n == 8 && memcmp(ln.p, "__name__", 8) == 0) {
                    name = lval;
                } else {
                    if (nlabels >= kMaxLabels || !name_ok(ln.p, ln.n)) {
                        bad = true;
                        break;
                    }
                    lk[nlabels] = ln;
                    lv[nlabels] = lval;
                    nlabels++;
                }
            } else if (f2 == 2 && w2 == 2) {  // Sample
                Span sb = ts_r.bytes_field();
                if (!ts_r.ok) break;
                PbReader sr{sb.p, sb.n, 0, true};
                double val = 0;
                int64_t t = 0;
                uint32_t f3, w3;
                while (sr.field(&f3, &w3)) {
                    if (f3 == 1 && w3 == 1) {
                        uint64_t bits = sr.fixed64();
                        memcpy(&val, &bits, 8);
                    } else if (f3 == 2 && w3 == 0) {
                        t = (int64_t)sr.uvarint();
                    } else {
                        sr.skip(w3);
                    }
                }
                if (!sr.ok) { bad = true; break; }
                if (nsamples == scap) {
                    scap *= 2;
                    double* nsv = (double*)realloc(sv, scap * sizeof(double));
                    int64_t* nst = (int64_t*)realloc(st, scap * sizeof(int64_t));
                    if (!nsv || !nst) { free(nsv ? nsv : sv); free(nst ? nst : st); return -1; }
                    sv = nsv;
                    st = nst;
                }
                sv[nsamples] = val;
                st[nsamples] = t;
                nsamples++;
            } else {
                ts_r.skip(w2);
            }
        }
        if (bad || !ts_r.ok || !name_ok(name.p, name.n)) {
            free(sv); free(st);
            return -1;  // fallback: Python path decides what to do
        }
        if (nsamples == 0) continue;
        int64_t klen = write_key(name, lk, lv, nlabels, keybuf + kw,
                                 keybuf_cap - kw);
        if (klen < 0) { free(sv); free(st); return -2; }
        if (n + nsamples > max_rows) { free(sv); free(st); return -3; }
        for (int64_t i = 0; i < nsamples; i++) {
            key_off[n] = kw;
            key_len[n] = klen;
            values[n] = sv[i];
            tss[n] = st[i] == 0 ? default_ts : st[i];
            n++;
        }
        kw += klen;
    }
    free(sv);
    free(st);
    if (!top.ok) return -1;
    return n;
}

}  // extern "C"

namespace {

// --------------------------------------------------------------- influx --

// Influx escape: `\X` protects X when X is one of , = space \ (tag/field
// sections). Unescape into tmp; returns length or -1 (too long).
inline int64_t influx_unescape(const uint8_t* p, int64_t n, uint8_t* out,
                               int64_t cap) {
    int64_t w = 0;
    for (int64_t i = 0; i < n; i++) {
        if (p[i] == '\\' && i + 1 < n &&
            (p[i + 1] == ',' || p[i + 1] == '=' || p[i + 1] == ' ' ||
             p[i + 1] == '\\')) {
            i++;
        }
        if (w >= cap) return -1;
        out[w++] = p[i];
    }
    return w;
}

// Scans to the next unescaped `sep` (space/comma/=) outside quotes when
// honor_quotes. Returns index of sep within [i, n) or n.
inline int64_t scan_to(const uint8_t* p, int64_t n, int64_t i, uint8_t sep,
                       bool honor_quotes) {
    bool q = false;
    while (i < n) {
        uint8_t c = p[i];
        if (c == '\\' && i + 1 < n) { i += 2; continue; }
        if (honor_quotes && c == '"') q = !q;
        else if (c == sep && !q) return i;
        i++;
    }
    return n;
}

// Numeric influx field value -> *out. Returns: 1 parsed, 0 skip (string /
// non-numeric).
inline int influx_field_value(const uint8_t* p, int64_t n, double* out) {
    if (n == 0) return 0;
    if (p[0] == '"') return 0;  // string field: not a sample
    if ((n == 1 && (p[0] == 't' || p[0] == 'T')) ||
        (n == 4 && (memcmp(p, "true", 4) == 0 || memcmp(p, "True", 4) == 0 ||
                    memcmp(p, "TRUE", 4) == 0))) {
        *out = 1.0;
        return 1;
    }
    if ((n == 1 && (p[0] == 'f' || p[0] == 'F')) ||
        (n == 5 && (memcmp(p, "false", 5) == 0 || memcmp(p, "False", 5) == 0 ||
                    memcmp(p, "FALSE", 5) == 0))) {
        *out = 0.0;
        return 1;
    }
    if (p[n - 1] == 'i' || p[n - 1] == 'u') n--;
    if (n <= 0 || n >= 63) return 0;
    char buf[64];
    memcpy(buf, p, n);
    buf[n] = 0;
    char* endp = nullptr;
    double v = strtod(buf, &endp);
    if (endp != buf + n) return 0;
    *out = v;
    return 1;
}

constexpr int kMaxTags = 126;   // + db + __name__ headroom vs kMaxLabels
constexpr int kMaxFields = 256;

}  // namespace

extern "C" {

// Parses influx line protocol into columnar rows. Metric name is
// `{measurement}_{field}` (`measurement` alone for the `value` field); tags
// become labels with an optional leading db label. ts is ns -> ms
// (floor-divided); absent -> default_ts. Returns rows written, -1 = fall
// back to Python (metachar names, non-integer timestamps, oversized
// shapes), -2 = keybuf too small, -3 = max_rows too small.
int64_t vm_parse_influx(const uint8_t* data, int64_t len,
                        const uint8_t* db, int64_t db_len,
                        int64_t default_ts,
                        uint8_t* keybuf, int64_t keybuf_cap,
                        int64_t* key_off, int64_t* key_len,
                        double* values, int64_t* tss, int64_t max_rows) {
    int64_t n = 0, kw = 0;
    int64_t i = 0;
    // scratch for unescaped names/tags (bounded per line)
    static thread_local uint8_t* tmp = nullptr;
    static thread_local int64_t tmp_cap = 0;
    if (tmp_cap < 1 << 16) {
        free(tmp);
        tmp_cap = 1 << 16;
        tmp = (uint8_t*)malloc(tmp_cap);
        if (!tmp) { tmp_cap = 0; return -1; }
    }
    Span lk[kMaxLabels], lv[kMaxLabels];
    Span fk[kMaxFields];
    double fv[kMaxFields];
    while (i < len && n < max_rows) {
        int64_t eol = i;
        while (eol < len && data[eol] != '\n') eol++;
        int64_t a = i, b = eol;
        i = eol + 1;
        while (a < b && (data[a] == ' ' || data[a] == '\t' || data[a] == '\r')) a++;
        while (b > a && (data[b - 1] == ' ' || data[b - 1] == '\t' ||
                         data[b - 1] == '\r')) b--;
        if (a >= b || data[a] == '#') continue;
        // sections: key [space] fields [space] ts — first two unescaped,
        // quote-aware spaces split (parsers._parse_influx_line)
        int64_t s1 = scan_to(data, b, a, ' ', true);
        if (s1 >= b) continue;  // no fields section
        int64_t s2 = scan_to(data, b, s1 + 1, ' ', true);
        // timestamp
        int64_t ts = default_ts;
        if (s2 < b) {
            int64_t t0 = s2 + 1;
            while (t0 < b && data[t0] == ' ') t0++;
            if (t0 < b) {
                char buf[32];
                int64_t tn = b - t0;
                if (tn >= (int64_t)sizeof(buf)) return -1;
                memcpy(buf, data + t0, tn);
                buf[tn] = 0;
                char* endp = nullptr;
                long long tv = strtoll(buf, &endp, 10);
                if (endp != buf + tn) return -1;  // Python int() would raise
                // ns -> ms, floor semantics (Python // )
                ts = tv >= 0 ? tv / 1000000
                             : -((-tv + 999999) / 1000000);
            }
        }
        // measurement + tags
        int64_t tw = 0;  // tmp write cursor
        int64_t mend = scan_to(data, s1, a, ',', false);
        int64_t mn = influx_unescape(data + a, mend - a, tmp + tw, tmp_cap - tw);
        if (mn < 0) return -1;
        Span meas{tmp + tw, mn};
        tw += mn;
        if (!name_ok(meas.p, meas.n)) return -1;
        int ntags = 0;
        if (db_len > 0) {
            lk[ntags] = {(const uint8_t*)"db", 2};
            lv[ntags] = {db, db_len};
            ntags++;
        }
        int64_t tp = mend;
        while (tp < s1) {
            tp++;  // skip ','
            int64_t te = scan_to(data, s1, tp, ',', false);
            int64_t eq = scan_to(data, te, tp, '=', false);
            if (eq < te && eq + 1 < te) {  // skip empty values (parity)
                if (ntags >= kMaxTags) return -1;
                int64_t kn = influx_unescape(data + tp, eq - tp, tmp + tw,
                                             tmp_cap - tw);
                if (kn < 0) return -1;
                lk[ntags] = {tmp + tw, kn};
                tw += kn;
                if (!name_ok(lk[ntags].p, lk[ntags].n)) return -1;
                int64_t vn = influx_unescape(data + eq + 1, te - eq - 1,
                                             tmp + tw, tmp_cap - tw);
                if (vn < 0) return -1;
                lv[ntags] = {tmp + tw, vn};
                tw += vn;
                ntags++;
            }
            tp = te;
        }
        // fields
        int nfields = 0;
        int64_t fp = s1 + 1;
        int64_t fend = s2 < b ? s2 : b;
        while (fp < fend) {
            int64_t fe = scan_to(data, fend, fp, ',', true);
            int64_t eq = scan_to(data, fe, fp, '=', false);
            if (eq < fe) {
                double v;
                if (influx_field_value(data + eq + 1, fe - eq - 1, &v)) {
                    if (nfields >= kMaxFields) return -1;
                    int64_t kn = influx_unescape(data + fp, eq - fp, tmp + tw,
                                                 tmp_cap - tw);
                    if (kn < 0) return -1;
                    fk[nfields] = {tmp + tw, kn};
                    tw += kn;
                    fv[nfields] = v;
                    nfields++;
                }
            }
            fp = fe + 1;
        }
        // emit one row per numeric field
        for (int f = 0; f < nfields; f++) {
            Span name;
            uint8_t* nb = tmp + tw;
            if (fk[f].n == 5 && memcmp(fk[f].p, "value", 5) == 0) {
                name = meas;
            } else {
                if (tw + meas.n + 1 + fk[f].n > tmp_cap) return -1;
                memcpy(nb, meas.p, meas.n);
                nb[meas.n] = '_';
                memcpy(nb + meas.n + 1, fk[f].p, fk[f].n);
                name = {nb, meas.n + 1 + fk[f].n};
                tw += name.n;
            }
            if (!name_ok(name.p, name.n)) return -1;
            int64_t klen = write_key(name, lk, lv, ntags, keybuf + kw,
                                     keybuf_cap - kw);
            if (klen < 0) return -2;
            if (n >= max_rows) return -3;
            key_off[n] = kw;
            key_len[n] = klen;
            values[n] = fv[f];
            tss[n] = ts;
            kw += klen;
            n++;
        }
    }
    if (i < len) return -3;  // ran out of row capacity mid-body
    return n;
}

}  // extern "C"

namespace {

// --------------------------------------------------------------- keymap --

struct KeyMap {
    // open addressing, power-of-2 table of dense ids; arena owns key bytes
    int64_t* slots;       // id+1 (0 = empty)
    uint64_t cap, size;
    uint8_t* arena;
    int64_t arena_len, arena_cap;
    int64_t* offs;        // per id: offset into arena
    int32_t* lens;        // per id: key length
    uint64_t* hashes;     // per id: full hash
    int64_t ids_cap;
};

inline uint64_t fnv1a(const uint8_t* p, int64_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (int64_t i = 0; i < n; i++) {
        h ^= p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

bool km_grow(KeyMap* m) {
    uint64_t ncap = m->cap * 2;
    int64_t* ns = (int64_t*)calloc(ncap, sizeof(int64_t));
    if (!ns) return false;
    for (uint64_t i = 0; i < m->cap; i++) {
        int64_t id1 = m->slots[i];
        if (!id1) continue;
        uint64_t h = m->hashes[id1 - 1];
        uint64_t j = h & (ncap - 1);
        while (ns[j]) j = (j + 1) & (ncap - 1);
        ns[j] = id1;
    }
    free(m->slots);
    m->slots = ns;
    m->cap = ncap;
    return true;
}

}  // namespace

extern "C" {

int64_t vm_keymap_new() {
    KeyMap* m = (KeyMap*)calloc(1, sizeof(KeyMap));
    if (!m) return 0;
    m->cap = 1 << 16;
    m->slots = (int64_t*)calloc(m->cap, sizeof(int64_t));
    m->arena_cap = 1 << 20;
    m->arena = (uint8_t*)malloc(m->arena_cap);
    m->ids_cap = 1 << 14;
    m->offs = (int64_t*)malloc(m->ids_cap * sizeof(int64_t));
    m->lens = (int32_t*)malloc(m->ids_cap * sizeof(int32_t));
    m->hashes = (uint64_t*)malloc(m->ids_cap * sizeof(uint64_t));
    if (!m->slots || !m->arena || !m->offs || !m->lens || !m->hashes) {
        free(m->slots); free(m->arena); free(m->offs); free(m->lens);
        free(m->hashes); free(m);
        return 0;
    }
    return (int64_t)(intptr_t)m;
}

void vm_keymap_free(int64_t h) {
    KeyMap* m = (KeyMap*)(intptr_t)h;
    if (!m) return;
    free(m->slots);
    free(m->arena);
    free(m->offs);
    free(m->lens);
    free(m->hashes);
    free(m);
}

int64_t vm_keymap_size(int64_t h) {
    return ((KeyMap*)(intptr_t)h)->size;
}

// Resolves n keys (base[off[i]:off[i]+len[i]]) to dense ids (ids[i]).
// Unknown keys are ADDED with consecutive ids in first-occurrence order.
// Returns number of new ids, or -1 on allocation failure.
int64_t vm_keymap_resolve(int64_t handle, const uint8_t* base,
                          const int64_t* off, const int64_t* klen, int64_t n,
                          int64_t* ids) {
    KeyMap* m = (KeyMap*)(intptr_t)handle;
    int64_t added = 0;
    for (int64_t r = 0; r < n; r++) {
        const uint8_t* kp = base + off[r];
        int64_t kn = klen[r];
        uint64_t hsh = fnv1a(kp, kn);
        uint64_t j = hsh & (m->cap - 1);
        int64_t id = -1;
        while (m->slots[j]) {
            int64_t cand = m->slots[j] - 1;
            if (m->hashes[cand] == hsh && m->lens[cand] == kn &&
                memcmp(m->arena + m->offs[cand], kp, kn) == 0) {
                id = cand;
                break;
            }
            j = (j + 1) & (m->cap - 1);
        }
        if (id < 0) {
            // insert
            if (m->size == (uint64_t)m->ids_cap) {
                int64_t ncap = m->ids_cap * 2;
                int64_t* no = (int64_t*)realloc(m->offs, ncap * sizeof(int64_t));
                int32_t* nl = (int32_t*)realloc(m->lens, ncap * sizeof(int32_t));
                uint64_t* nh = (uint64_t*)realloc(m->hashes, ncap * sizeof(uint64_t));
                if (!no || !nl || !nh) {
                    if (no) m->offs = no;
                    if (nl) m->lens = nl;
                    if (nh) m->hashes = nh;
                    return -1;
                }
                m->offs = no; m->lens = nl; m->hashes = nh;
                m->ids_cap = ncap;
            }
            while (m->arena_len + kn > m->arena_cap) {
                int64_t ncap = m->arena_cap * 2;
                uint8_t* na = (uint8_t*)realloc(m->arena, ncap);
                if (!na) return -1;
                m->arena = na;
                m->arena_cap = ncap;
            }
            memcpy(m->arena + m->arena_len, kp, kn);
            id = (int64_t)m->size;
            m->offs[id] = m->arena_len;
            m->lens[id] = (int32_t)kn;
            m->hashes[id] = hsh;
            m->arena_len += kn;
            m->size++;
            m->slots[j] = id + 1;
            added++;
            if (m->size * 10 >= m->cap * 7) {
                if (!km_grow(m)) return -1;
            }
        }
        ids[r] = id;
    }
    return added;
}

}  // extern "C"
