// Native host codecs: bulk zigzag-varint + delta2 encode/decode.
//
// The reference's hot host loops are hand-tuned Go (lib/encoding/int.go
// varint bulk codecs, nearest_delta2.go) with its only native code being cgo
// zstd (SURVEY §2.9). Here the ingest/scan hot loops get a real native
// implementation, exposed through a C ABI consumed via ctypes
// (victoriametrics_tpu/native/__init__.py). Build: `make -C native` or the
// lazy auto-build in the Python wrapper.
//
// All functions are thread-safe (no global state) and release-the-GIL safe
// (pure C, no Python API).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// zigzag varint
// ---------------------------------------------------------------------------

// Encode n int64s as zigzag varints into out (caller provides >= 10*n bytes).
// Returns bytes written.
int64_t vm_varint_encode(const int64_t* vals, int64_t n, uint8_t* out) {
    uint8_t* p = out;
    for (int64_t i = 0; i < n; i++) {
        uint64_t u = ((uint64_t)vals[i] << 1) ^ (uint64_t)(vals[i] >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

// Decode up to max_vals zigzag varints from data[0:len]. Returns number of
// values decoded, or -1 on malformed input (truncated / overlong varint).
int64_t vm_varint_decode(const uint8_t* data, int64_t len, int64_t* out,
                         int64_t max_vals) {
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    int64_t count = 0;
    while (p < end && count < max_vals) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        out[count++] = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
    }
    if (p != end && count < max_vals) return -1;
    return count;
}

// ---------------------------------------------------------------------------
// delta2 (double-delta) + varint, fused: the block encode/decode hot path
// ---------------------------------------------------------------------------

// vals[0..n) -> first, first_delta, varint(d2 stream) in out.
// Returns payload bytes written; first/first_delta via out params.
int64_t vm_delta2_encode(const int64_t* vals, int64_t n, uint8_t* out,
                         int64_t* first, int64_t* first_delta) {
    if (n < 2) return -1;
    *first = vals[0];
    int64_t prev_d = (int64_t)((uint64_t)vals[1] - (uint64_t)vals[0]);
    *first_delta = prev_d;
    uint8_t* p = out;
    for (int64_t i = 2; i < n; i++) {
        int64_t d = (int64_t)((uint64_t)vals[i] - (uint64_t)vals[i - 1]);
        int64_t d2 = (int64_t)((uint64_t)d - (uint64_t)prev_d);
        prev_d = d;
        uint64_t u = ((uint64_t)d2 << 1) ^ (uint64_t)(d2 >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

// Inverse: reconstruct n values from first, first_delta and the d2 varint
// stream. Returns n on success, -1 on malformed input.
int64_t vm_delta2_decode(const uint8_t* data, int64_t len, int64_t first,
                         int64_t first_delta, int64_t* out, int64_t n) {
    if (n < 1) return -1;
    out[0] = first;
    if (n == 1) return 1;
    int64_t v = first;
    int64_t d = first_delta;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    v = (int64_t)((uint64_t)v + (uint64_t)d);
    out[1] = v;
    for (int64_t i = 2; i < n; i++) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        int64_t d2 = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
        d = (int64_t)((uint64_t)d + (uint64_t)d2);
        v = (int64_t)((uint64_t)v + (uint64_t)d);
        out[i] = v;
    }
    return (p == end) ? n : -1;
}

// ---------------------------------------------------------------------------
// delta1 (single delta) + varint
// ---------------------------------------------------------------------------

int64_t vm_delta_encode(const int64_t* vals, int64_t n, uint8_t* out,
                        int64_t* first) {
    if (n < 1) return -1;
    *first = vals[0];
    uint8_t* p = out;
    for (int64_t i = 1; i < n; i++) {
        int64_t d = (int64_t)((uint64_t)vals[i] - (uint64_t)vals[i - 1]);
        uint64_t u = ((uint64_t)d << 1) ^ (uint64_t)(d >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

int64_t vm_delta_decode(const uint8_t* data, int64_t len, int64_t first,
                        int64_t* out, int64_t n) {
    if (n < 1) return -1;
    out[0] = first;
    int64_t v = first;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    for (int64_t i = 1; i < n; i++) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        int64_t d = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
        v = (int64_t)((uint64_t)v + (uint64_t)d);
        out[i] = v;
    }
    return (p == end) ? n : -1;
}

}  // extern "C"
