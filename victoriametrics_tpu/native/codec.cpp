// Native host codecs: bulk zigzag-varint + delta2 encode/decode.
//
// The reference's hot host loops are hand-tuned Go (lib/encoding/int.go
// varint bulk codecs, nearest_delta2.go) with its only native code being cgo
// zstd (SURVEY §2.9). Here the ingest/scan hot loops get a real native
// implementation, exposed through a C ABI consumed via ctypes
// (victoriametrics_tpu/native/__init__.py). Build: `make -C native` or the
// lazy auto-build in the Python wrapper.
//
// All functions are thread-safe (no global state) and release-the-GIL safe
// (pure C, no Python API).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

#include <dlfcn.h>

#ifdef VM_HAVE_ZSTD
#include <zstd.h>
#endif

// ---------------------------------------------------------------------------
// runtime payload codecs: zstd + zlib
//
// Compressed block payloads (MarshalType 5/6) are zstd frames when the
// Python side has a zstd binding and zlib streams otherwise
// (ops/compress.py falls back to stdlib zlib and sniffs the frame magic on
// read). Minimal containers ship libzstd.so.1 / libz.so.1 without the dev
// headers, so instead of requiring -lzstd at build time the needed entry
// points are resolved with dlopen on first use; a build against real
// headers (VM_HAVE_ZSTD) binds them directly. Everything is one-shot
// stateless API, safe from concurrent GIL-released callers.
// ---------------------------------------------------------------------------

namespace {

struct VmRtCodecs {
    // zstd one-shot API (resolved lazily; null = unavailable)
    size_t (*zd)(void*, size_t, const void*, size_t) = nullptr;
    unsigned (*zerr)(size_t) = nullptr;
    size_t (*zc)(void*, size_t, const void*, size_t, int) = nullptr;
    size_t (*zbound)(size_t) = nullptr;
    unsigned long long (*zsize)(const void*, size_t) = nullptr;
    // zlib one-shot inflate
    int (*inflate_buf)(unsigned char*, unsigned long*, const unsigned char*,
                       unsigned long) = nullptr;

    VmRtCodecs() {
#ifdef VM_HAVE_ZSTD
        zd = ZSTD_decompress;
        zerr = ZSTD_isError;
        zc = ZSTD_compress;
        zbound = ZSTD_compressBound;
        zsize = ZSTD_getFrameContentSize;
#else
        void* hz = dlopen("libzstd.so.1", RTLD_NOW | RTLD_LOCAL);
        if (!hz) hz = dlopen("libzstd.so", RTLD_NOW | RTLD_LOCAL);
        if (hz) {
            zd = reinterpret_cast<size_t (*)(void*, size_t, const void*,
                                             size_t)>(
                dlsym(hz, "ZSTD_decompress"));
            zerr = reinterpret_cast<unsigned (*)(size_t)>(
                dlsym(hz, "ZSTD_isError"));
            zc = reinterpret_cast<size_t (*)(void*, size_t, const void*,
                                             size_t, int)>(
                dlsym(hz, "ZSTD_compress"));
            zbound = reinterpret_cast<size_t (*)(size_t)>(
                dlsym(hz, "ZSTD_compressBound"));
            zsize = reinterpret_cast<unsigned long long (*)(const void*,
                                                            size_t)>(
                dlsym(hz, "ZSTD_getFrameContentSize"));
            if (!zd || !zerr) {  // partial API: treat as absent
                zd = nullptr;
                zc = nullptr;
            }
        }
#endif
        void* hl = dlopen("libz.so.1", RTLD_NOW | RTLD_LOCAL);
        if (!hl) hl = dlopen("libz.so", RTLD_NOW | RTLD_LOCAL);
        if (hl) {
            inflate_buf = reinterpret_cast<int (*)(
                unsigned char*, unsigned long*, const unsigned char*,
                unsigned long)>(dlsym(hl, "uncompress"));
        }
    }
};

const VmRtCodecs& vm_rt() {
    static VmRtCodecs c;  // C++11 thread-safe init
    return c;
}

// Inflate one compressed block payload into dst[0:cap], sniffing the
// producer exactly like ops/compress.py decompress(): zstd frames start
// 28 B5 2F FD, anything else is the zlib fallback stream. Returns
// decompressed size, or -1 (codec unavailable / malformed / overflow).
int64_t vm_inflate(const uint8_t* p, int64_t sz, uint8_t* dst, int64_t cap) {
    const VmRtCodecs& c = vm_rt();
    if (sz >= 4 && p[0] == 0x28 && p[1] == 0xb5 && p[2] == 0x2f &&
        p[3] == 0xfd) {
        if (!c.zd) return -1;
        size_t got = c.zd(dst, (size_t)cap, p, (size_t)sz);
        if (c.zerr(got)) return -1;
        return (int64_t)got;
    }
    if (!c.inflate_buf) return -1;
    unsigned long dlen = (unsigned long)cap;
    if (c.inflate_buf(dst, &dlen, p, (unsigned long)sz) != 0) return -1;
    return (int64_t)dlen;
}

}  // namespace

extern "C" {

// Bitmask of payload codecs the native decode path can inflate: bit 0 =
// zstd frames, bit 1 = zlib streams. The Python gate peeks each
// compressed block's leading byte and checks the matching bit.
int32_t vm_decompress_caps(void) {
    const VmRtCodecs& c = vm_rt();
    return (c.zd ? 1 : 0) | (c.inflate_buf ? 2 : 0);
}

// 1 when zstd frames decode natively (built against libzstd OR resolved
// from libzstd.so.1 at runtime); historical name kept for the ctypes ABI.
int32_t vm_has_zstd(void) {
    return vm_decompress_caps() & 1;
}

// One-shot zstd compress/decompress for ops/compress.py when the Python
// `zstandard` binding is absent but the runtime library exists. Returns
// bytes written, or -1 (unavailable / error / cap exceeded).
int64_t vm_zstd_compress_bound(int64_t n) {
    const VmRtCodecs& c = vm_rt();
    if (!c.zbound) return -1;
    return (int64_t)c.zbound((size_t)n);
}

int64_t vm_zstd_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                         int64_t cap, int32_t level) {
    const VmRtCodecs& c = vm_rt();
    if (!c.zc) return -1;
    size_t got = c.zc(dst, (size_t)cap, src, (size_t)n, (int)level);
    if (c.zerr(got)) return -1;
    return (int64_t)got;
}

// Claimed decompressed size of a zstd frame; -1 = unknown/error (callers
// must then refuse rather than guess — the size caps allocation).
int64_t vm_zstd_content_size(const uint8_t* src, int64_t n) {
    const VmRtCodecs& c = vm_rt();
    if (!c.zsize) return -1;
    unsigned long long s = c.zsize(src, (size_t)n);
    if (s == (unsigned long long)-1 || s == (unsigned long long)-2)
        return -1;
    return (int64_t)s;
}

int64_t vm_zstd_decompress(const uint8_t* src, int64_t n, uint8_t* dst,
                           int64_t cap) {
    const VmRtCodecs& c = vm_rt();
    if (!c.zd) return -1;
    size_t got = c.zd(dst, (size_t)cap, src, (size_t)n);
    if (c.zerr(got)) return -1;
    return (int64_t)got;
}

// ---------------------------------------------------------------------------
// zigzag varint
// ---------------------------------------------------------------------------

// Encode n int64s as zigzag varints into out (caller provides >= 10*n bytes).
// Returns bytes written.
int64_t vm_varint_encode(const int64_t* vals, int64_t n, uint8_t* out) {
    uint8_t* p = out;
    for (int64_t i = 0; i < n; i++) {
        uint64_t u = ((uint64_t)vals[i] << 1) ^ (uint64_t)(vals[i] >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

// Decode up to max_vals zigzag varints from data[0:len]. Returns number of
// values decoded, or -1 on malformed input (truncated / overlong varint).
int64_t vm_varint_decode(const uint8_t* data, int64_t len, int64_t* out,
                         int64_t max_vals) {
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    int64_t count = 0;
    while (p < end && count < max_vals) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        out[count++] = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
    }
    if (p != end && count < max_vals) return -1;
    return count;
}

// ---------------------------------------------------------------------------
// delta2 (double-delta) + varint, fused: the block encode/decode hot path
// ---------------------------------------------------------------------------

// vals[0..n) -> first, first_delta, varint(d2 stream) in out.
// Returns payload bytes written; first/first_delta via out params.
int64_t vm_delta2_encode(const int64_t* vals, int64_t n, uint8_t* out,
                         int64_t* first, int64_t* first_delta) {
    if (n < 2) return -1;
    *first = vals[0];
    int64_t prev_d = (int64_t)((uint64_t)vals[1] - (uint64_t)vals[0]);
    *first_delta = prev_d;
    uint8_t* p = out;
    for (int64_t i = 2; i < n; i++) {
        int64_t d = (int64_t)((uint64_t)vals[i] - (uint64_t)vals[i - 1]);
        int64_t d2 = (int64_t)((uint64_t)d - (uint64_t)prev_d);
        prev_d = d;
        uint64_t u = ((uint64_t)d2 << 1) ^ (uint64_t)(d2 >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

// Inverse: reconstruct n values from first, first_delta and the d2 varint
// stream. Returns n on success, -1 on malformed input.
int64_t vm_delta2_decode(const uint8_t* data, int64_t len, int64_t first,
                         int64_t first_delta, int64_t* out, int64_t n) {
    if (n < 1) return -1;
    out[0] = first;
    if (n == 1) return 1;
    int64_t v = first;
    int64_t d = first_delta;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    v = (int64_t)((uint64_t)v + (uint64_t)d);
    out[1] = v;
    for (int64_t i = 2; i < n; i++) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        int64_t d2 = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
        d = (int64_t)((uint64_t)d + (uint64_t)d2);
        v = (int64_t)((uint64_t)v + (uint64_t)d);
        out[i] = v;
    }
    return (p == end) ? n : -1;
}

// ---------------------------------------------------------------------------
// delta1 (single delta) + varint
// ---------------------------------------------------------------------------

int64_t vm_delta_encode(const int64_t* vals, int64_t n, uint8_t* out,
                        int64_t* first) {
    if (n < 1) return -1;
    *first = vals[0];
    uint8_t* p = out;
    for (int64_t i = 1; i < n; i++) {
        int64_t d = (int64_t)((uint64_t)vals[i] - (uint64_t)vals[i - 1]);
        uint64_t u = ((uint64_t)d << 1) ^ (uint64_t)(d >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

int64_t vm_delta_decode(const uint8_t* data, int64_t len, int64_t first,
                        int64_t* out, int64_t n) {
    if (n < 1) return -1;
    out[0] = first;
    int64_t v = first;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    for (int64_t i = 1; i < n; i++) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        int64_t d = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
        v = (int64_t)((uint64_t)v + (uint64_t)d);
        out[i] = v;
    }
    return (p == end) ? n : -1;
}


// ---------------------------------------------------------------------------
// batched block marshal: type choice + encode for K blocks in one call
// ---------------------------------------------------------------------------

// Marshal types (mirror ops/encoding.py MarshalType)
#define VM_MT_CONST 1
#define VM_MT_DELTA_CONST 2
#define VM_MT_NEAREST_DELTA 3
#define VM_MT_NEAREST_DELTA2 4

// For each block i with values vals[offsets[i]..offsets[i+1]):
// choose CONST / DELTA_CONST / NEAREST_DELTA (gauge: >1/8 negative deltas)
// / NEAREST_DELTA2 exactly like ops/encoding.py marshal_int64_array, encode
// the payload contiguously into out, and record (type, first_value,
// payload_len). Returns total bytes written, or -1 when out_cap would be
// exceeded. offsets has n_blocks+1 entries.
int64_t vm_marshal_i64_many(const int64_t* vals, const int64_t* offsets,
                            int64_t n_blocks, uint8_t* out, int64_t out_cap,
                            int32_t* types, int64_t* firsts, int64_t* lens) {
    int64_t pos = 0;
    for (int64_t i = 0; i < n_blocks; i++) {
        const int64_t* v = vals + offsets[i];
        int64_t n = offsets[i + 1] - offsets[i];
        if (n <= 0) return -1;
        // worst case: 10 bytes per varint
        if (pos + (n + 1) * 10 > out_cap) return -1;
        bool is_const = true;
        for (int64_t j = 1; j < n; j++) {
            if (v[j] != v[0]) { is_const = false; break; }
        }
        if (is_const) {
            types[i] = VM_MT_CONST;
            firsts[i] = v[0];
            lens[i] = 0;
            continue;
        }
        // delta-const (wrapping two's-complement deltas, like np.int64)
        if (n >= 2) {
            uint64_t d0 = (uint64_t)v[1] - (uint64_t)v[0];
            bool dconst = true;
            for (int64_t j = 2; j < n; j++) {
                if ((uint64_t)v[j] - (uint64_t)v[j - 1] != d0) {
                    dconst = false;
                    break;
                }
            }
            if (dconst) {
                int64_t d = (int64_t)d0;
                int64_t len = vm_varint_encode(&d, 1, out + pos);
                types[i] = VM_MT_DELTA_CONST;
                firsts[i] = v[0];
                lens[i] = len;
                pos += len;
                continue;
            }
        }
        int64_t neg = 0;
        for (int64_t j = 1; j < n; j++) {
            if (v[j] < v[j - 1]) neg++;
        }
        if (neg * 8 > n) {
            // gauge: first-order deltas
            int64_t first;
            int64_t len = vm_delta_encode(v, n, out + pos, &first);
            types[i] = VM_MT_NEAREST_DELTA;
            firsts[i] = first;
            lens[i] = len;
            pos += len;
        } else {
            // counter: varint(first_delta) + delta2 stream
            int64_t first, first_delta;
            uint8_t tmp[10];
            int64_t d2len = vm_delta2_encode(v, n, out + pos, &first,
                                             &first_delta);
            int64_t fdlen = vm_varint_encode(&first_delta, 1, tmp);
            // shift payload right to prepend the first_delta varint
            memmove(out + pos + fdlen, out + pos, d2len);
            memcpy(out + pos, tmp, fdlen);
            types[i] = VM_MT_NEAREST_DELTA2;
            firsts[i] = first;
            lens[i] = fdlen + d2len;
            pos += fdlen + d2len;
        }
    }
    return pos;
}

// ---------------------------------------------------------------------------
// batched block decode: the cold-query scan hot path
// ---------------------------------------------------------------------------

#define VM_MT_ZSTD_NEAREST_DELTA 5
#define VM_MT_ZSTD_NEAREST_DELTA2 6

// Decode one plain (non-zstd) payload into out[0..n). Returns n or -1.
static int64_t vm_decode_plain(const uint8_t* p, int64_t sz, int32_t mt,
                               int64_t first, int64_t n, int64_t* out) {
    switch (mt) {
    case VM_MT_CONST:
        for (int64_t i = 0; i < n; i++) out[i] = first;
        return n;
    case VM_MT_DELTA_CONST: {
        int64_t d;
        if (vm_varint_decode(p, sz, &d, 1) != 1) return -1;
        int64_t v = first;
        for (int64_t i = 0; i < n; i++) {
            out[i] = v;
            v = (int64_t)((uint64_t)v + (uint64_t)d);
        }
        return n;
    }
    case VM_MT_NEAREST_DELTA:
        return vm_delta_decode(p, sz, first, out, n);
    case VM_MT_NEAREST_DELTA2: {
        if (n == 1) { out[0] = first; return 1; }
        // leading varint = first_delta, remainder = d2 stream
        const uint8_t* q = p;
        const uint8_t* end = p + sz;
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (q >= end || shift > 63) return -1;
            uint8_t b = *q++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        int64_t fd = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
        return vm_delta2_decode(q, (int64_t)(end - q), first, fd, out, n);
    }
    default:
        return -1;
    }
}

// Decode K blocks in one call. Block i's payload lives at base[off[i]..
// off[i]+sz[i]) (zstd-compressed for types 5/6), decodes to cnt[i] int64s
// written contiguously into out (caller lays out offsets as cumsum(cnt)).
// validate_ts != 0 additionally clamps decoded sequences of the lossy
// UNcompressed types (3/4) to be non-decreasing, mirroring
// ops/encoding.py unmarshal_timestamps needs_validation.
// Returns total values decoded, or -(i+1) when block i is malformed.
int64_t vm_decode_blocks(const uint8_t* base, const int64_t* off,
                         const int64_t* sz, const int32_t* mt,
                         const int64_t* first, const int64_t* cnt,
                         int64_t k, int64_t* out, int32_t validate_ts) {
    int64_t pos = 0;
    std::vector<uint8_t> scratch;
    for (int64_t i = 0; i < k; i++) {
        int32_t t = mt[i];
        const uint8_t* p = base + off[i];
        int64_t n = cnt[i];
        int64_t s = sz[i];
        if (n <= 0) return -(i + 1);
        int64_t r;
        if (t == VM_MT_ZSTD_NEAREST_DELTA || t == VM_MT_ZSTD_NEAREST_DELTA2) {
            // decompressed payload is <= 10 bytes per varint (+lead varint)
            size_t cap = (size_t)(n + 1) * 10 + 16;
            if (scratch.size() < cap) scratch.resize(cap);
            int64_t got = vm_inflate(p, s, scratch.data(), (int64_t)cap);
            if (got < 0) return -(i + 1);
            r = vm_decode_plain(scratch.data(), got, t - 2, first[i],
                                n, out + pos);
        } else {
            r = vm_decode_plain(p, s, t, first[i], n, out + pos);
        }
        if (r != n) return -(i + 1);
        if (validate_ts &&
            (t == VM_MT_NEAREST_DELTA || t == VM_MT_NEAREST_DELTA2)) {
            int64_t* o = out + pos;
            for (int64_t j = 1; j < n; j++) {
                if (o[j] < o[j - 1]) o[j] = o[j - 1];
            }
        }
        pos += n;
    }
    return pos;
}

// ---------------------------------------------------------------------------
// decimal mantissas -> float64, batched over blocks with per-block exponents
// ---------------------------------------------------------------------------

#define VM_V_NAN       INT64_MIN
#define VM_V_STALE_NAN (INT64_MIN + 1)
#define VM_V_INF_NEG   (INT64_MIN + 2)
#define VM_V_INF_POS   INT64_MAX

// Convert n mantissas sharing decimal exponent `e` into float64, replicating
// ops/decimal.py decimal_to_float: exact integer division for e in [-18, -1]
// when it divides evenly (bit-exact round-trips for typical decimal values).
static void vm_d2f_one(const int64_t* m, int64_t n, int64_t e, double* out) {
    double stale;
    {
        uint64_t bits = 0x7FF0000000000002ULL;
        memcpy(&stale, &bits, 8);
    }
    double pos_scale = 1.0, neg_scale = 1.0;
    int64_t ipow = 1;
    bool have_ipow = false;
    if (e > 0) {
        // single pow call, matching np.power(10.0, e) bit-for-bit (same
        // libm; overflows to +inf above e=308 exactly like numpy)
        pos_scale = pow(10.0, (double)e);
    } else if (e < 0) {
        neg_scale = pow(10.0, (double)(-e));
        if (e >= -18) {
            ipow = 1;
            for (int64_t i = 0; i < -e; i++) ipow *= 10;
            have_ipow = true;
        }
    }
    for (int64_t i = 0; i < n; i++) {
        int64_t v = m[i];
        if (v == VM_V_STALE_NAN) { out[i] = stale; continue; }
        if (v == VM_V_NAN) { out[i] = NAN; continue; }
        if (v == VM_V_INF_POS) { out[i] = INFINITY; continue; }
        if (v == VM_V_INF_NEG) { out[i] = -INFINITY; continue; }
        if (e == 0) { out[i] = (double)v; continue; }
        if (e < 0) {
            if (e >= -22) {
                double r = (double)v / neg_scale;
                if (have_ipow) {
                    int64_t q = v / ipow;
                    // python floor-div semantics only differ for negatives
                    // with remainder, which also fail the exactness test
                    if (q * ipow == v) r = (double)q;
                }
                out[i] = r;
            } else {
                out[i] = (double)v * pow(10.0, (double)e);
            }
        } else {
            out[i] = (double)v * pos_scale;
        }
    }
}

// Batched: K groups; group i covers mantissas [go[i], go[i+1]) with exponent
// exps[i]. go has k+1 entries.
void vm_decimal_to_float_blocks(const int64_t* m, const int64_t* go,
                                const int64_t* exps, int64_t k, double* out) {
    for (int64_t i = 0; i < k; i++) {
        int64_t a = go[i];
        vm_d2f_one(m + a, go[i + 1] - a, exps[i], out + a);
    }
}

// ---------------------------------------------------------------------------
// per-block time clipping: the part_search.go block-pruning analog at ROW
// granularity. For K blocks over the concatenated timestamp column, find the
// [lo, hi]-inclusive kept row range of each block by binary search (each
// block's timestamps are sorted). Blocks fully inside the range cost two
// ~20-compare searches; the caller gathers only kept rows, so a tail fetch
// of M samples costs O(M + K log rows) instead of O(total decoded rows).
// ---------------------------------------------------------------------------

void vm_clip_blocks(const int64_t* ts, const int64_t* bstart,
                    const int64_t* bend, int64_t k, int64_t lo, int64_t hi,
                    int64_t* out_lo, int64_t* out_hi) {
    for (int64_t i = 0; i < k; i++) {
        int64_t a = bstart[i], b = bend[i];
        // first index with ts >= lo
        int64_t l = a, r = b;
        while (l < r) {
            int64_t m = l + ((r - l) >> 1);
            if (ts[m] < lo) l = m + 1; else r = m;
        }
        out_lo[i] = l;
        // first index with ts > hi
        r = b;
        while (l < r) {
            int64_t m = l + ((r - l) >> 1);
            if (ts[m] <= hi) l = m + 1; else r = m;
        }
        out_hi[i] = l;
    }
}

// Gather the kept row ranges of two parallel int64 columns into dense
// output (the companion of vm_clip_blocks): out gets a[keep_lo[i]:
// keep_hi[i]] for each block, concatenated. Pure per-segment memcpy — no
// index arrays materialize.
void vm_gather_rows2(const int64_t* a, const int64_t* b,
                     const int64_t* keep_lo, const int64_t* keep_hi,
                     int64_t k, int64_t* out_a, int64_t* out_b) {
    int64_t o = 0;
    for (int64_t i = 0; i < k; i++) {
        int64_t n = keep_hi[i] - keep_lo[i];
        if (n <= 0) continue;
        memcpy(out_a + o, a + keep_lo[i], (size_t)n * sizeof(int64_t));
        memcpy(out_b + o, b + keep_lo[i], (size_t)n * sizeof(int64_t));
        o += n;
    }
}

// Scatter K pre-grouped blocks into the padded (S, N) tile layout: block k
// appends its cnts[k] samples to row rows[k] (input order within a row is
// preserved), then every row's tail is padded (pad_ts / 0.0). fill must be
// zeroed S-sized scratch; it ends up holding the per-row valid counts.
void vm_scatter_pad(const int64_t* ts, const double* vals,
                    const int64_t* cnts, const int64_t* rows, int64_t K,
                    int64_t S, int64_t N, int64_t pad_ts,
                    int64_t* ts2, double* v2, int64_t* fill) {
    int64_t off = 0;
    for (int64_t k = 0; k < K; k++) {
        int64_t r = rows[k], n = cnts[k];
        memcpy(ts2 + r * N + fill[r], ts + off, (size_t)n * sizeof(int64_t));
        memcpy(v2 + r * N + fill[r], vals + off, (size_t)n * sizeof(double));
        fill[r] += n;
        off += n;
    }
    for (int64_t s = 0; s < S; s++) {
        for (int64_t j = fill[s]; j < N; j++) {
            ts2[s * N + j] = pad_ts;
            v2[s * N + j] = 0.0;
        }
    }
}

// ---------------------------------------------------------------------------
// counter-reset removal (rollup.go:921 removeCounterResets), row-batched
// ---------------------------------------------------------------------------

// For each of S rows of length N: out = v + shifted-cumsum(drop) where
// drop_j = (d<0) ? ((-d*8 < prev) ? -d : prev) : 0, d = v[j]-v[j-1].
// Bit-exact with the numpy diff/where/cumsum formulation in
// ops/rollup_np.py remove_counter_resets (sequential adds, NaN d -> 0).
void vm_counter_resets_2d(const double* v, int64_t S, int64_t N,
                          double* out) {
    for (int64_t s = 0; s < S; s++) {
        const double* r = v + s * N;
        double* o = out + s * N;
        if (N == 0) continue;
        double corr = 0.0;
        o[0] = r[0];
        for (int64_t j = 1; j < N; j++) {
            double d = r[j] - r[j - 1];
            if (d < 0.0) {  // false for NaN, matching np.where
                double md = -d;
                corr += (md * 8.0 < r[j - 1]) ? md : r[j - 1];
            }
            o[j] = r[j] + corr;
        }
    }
}

// ---------------------------------------------------------------------------
// fused window-walk for the counter/derivative rollup family
// ---------------------------------------------------------------------------

#define VM_RF_RATE 1
#define VM_RF_INCREASE 2
#define VM_RF_DELTA 3
#define VM_RF_DERIV_FAST 4
#define VM_RF_IRATE 5
#define VM_RF_IDELTA 6
#define VM_RF_INCREASE_PURE 7

// delta/increase baseline for a series whose first sample lies inside the
// window (no sample precedes it): assume the counter was born at 0 — unless
// the first value dwarfs the first in-window step, which marks an
// already-running counter surfacing mid-window (rollup.go:2129 rollupDelta).
// Mirrors _new_series_base in ops/rollup_np.py (must stay bit-exact).
static inline double vm_new_series_base(const double* w, int64_t nwin) {
    double d = nwin > 1 ? w[1] - w[0] : 0.0;
    return (fabs(w[0]) < 10.0 * (fabs(d) + 1.0)) ? 0.0 : w[0];
}

// One pass per row: counter-reset correction into scratch, then a
// two-pointer window walk over the T output steps. Semantics and float-op
// order mirror ops/rollup_np.py rollup_batch_packed's counter family
// (verified bit-exact by the batch-vs-oracle differential tests).
// ts: (S, N) int64 padded with INT64_MAX; v: (S, N) float64; counts (S,);
// mpi: (S,) maxPrevInterval for the gated-prev rule; out: (S, T).
// scratch: N doubles.
void vm_rollup_counter_2d(const int64_t* ts, const double* v,
                          const int64_t* counts, int64_t S, int64_t N,
                          int64_t start, int64_t end, int64_t step,
                          int64_t lookback, const int64_t* mpi, int32_t func,
                          double* out, double* scratch) {
    int64_t T = (end - start) / step + 1;
    bool needs_reset = (func == VM_RF_RATE || func == VM_RF_INCREASE ||
                        func == VM_RF_INCREASE_PURE || func == VM_RF_IRATE);
    for (int64_t s = 0; s < S; s++) {
        const int64_t* t = ts + s * N;
        const double* r = v + s * N;
        double* o = out + s * T;
        int64_t n = counts[s];
        const double* c = r;
        if (needs_reset && n > 0) {
            double corr = 0.0;
            scratch[0] = r[0];
            for (int64_t j = 1; j < n; j++) {
                double d = r[j] - r[j - 1];
                if (d < 0.0) {
                    double md = -d;
                    corr += (md * 8.0 < r[j - 1]) ? md : r[j - 1];
                }
                scratch[j] = r[j] + corr;
            }
            c = scratch;
        }
        int64_t a = 0, b = 0;
        for (int64_t j = 0; j < T; j++) {
            int64_t tj = start + j * step;
            int64_t w_lo = tj - lookback;
            while (a < n && t[a] <= w_lo) a++;
            if (b < a) b = a;
            while (b < n && t[b] <= tj) b++;
            double res = NAN;
            int64_t nwin = b - a;
            bool have = nwin > 0;
            int64_t prev = a - 1;
            bool has_prev = prev >= 0;
            bool gated = has_prev && t[prev] > w_lo - mpi[s];
            switch (func) {
            case VM_RF_DELTA:
                if (have) {
                    double base = has_prev ? r[prev]
                                           : vm_new_series_base(r + a, nwin);
                    res = r[b - 1] - base;
                }
                break;
            case VM_RF_INCREASE:
                if (have) {
                    double base = has_prev ? c[prev]
                                           : vm_new_series_base(c + a, nwin);
                    res = c[b - 1] - base;
                }
                break;
            case VM_RF_INCREASE_PURE:
                if (have) {
                    double base = has_prev ? c[prev] : 0.0;
                    res = c[b - 1] - base;
                }
                break;
            case VM_RF_RATE:
            case VM_RF_DERIV_FAST: {
                const double* arr = (func == VM_RF_RATE) ? c : r;
                if (have && (gated || nwin >= 2)) {
                    int64_t pi = gated ? prev : a;
                    double dt = (double)(t[b - 1] - t[pi]) / 1e3;
                    double dv = arr[b - 1] - arr[pi];
                    res = (dt > 0.0) ? dv / dt : NAN;
                }
                break;
            }
            case VM_RF_IRATE: {
                bool two = nwin >= 2;
                if (have && (two || gated)) {
                    int64_t hi2 = two ? b - 2 : prev;
                    double dt = (double)(t[b - 1] - t[hi2]) / 1e3;
                    double dv = c[b - 1] - c[hi2];
                    res = (dt > 0.0) ? dv / dt : NAN;
                }
                break;
            }
            case VM_RF_IDELTA:
                if (have) {
                    if (nwin >= 2) res = r[b - 1] - r[b - 2];
                    else if (gated) res = r[b - 1] - r[prev];
                }
                break;
            }
            o[j] = res;
        }
    }
}

// ---------------------------------------------------------------------------
// grouped float64 -> decimal (int64 mantissas + per-group common exponent)
// ---------------------------------------------------------------------------
// Mirrors ops/decimal.float_to_decimal_grouped exactly (the flush hot
// path): element-wise mantissa extraction (integer fast path, 15-digit
// round-trip check, 17-digit fallback, trailing-zero strip), then per-group
// common-exponent unification and rescale. Sentinels and rounding modes
// (nearbyint == np.round half-to-even under the default FP environment)
// match the Python pipeline bit for bit.

#define VM_F2D_MAX_MANTISSA 100000000000000000LL  // 10^17
#define VM_F2D_MIN_EXP (-320)
#define VM_F2D_MAX_EXP 310
#define VM_V_NAN INT64_MIN
#define VM_V_STALE_NAN (INT64_MIN + 1)
#define VM_V_INF_NEG (INT64_MIN + 2)
#define VM_V_INF_POS INT64_MAX

enum { VM_K_NORM = 0, VM_K_ZERO, VM_K_STALE, VM_K_NAN, VM_K_PINF,
       VM_K_NINF };

// Power-of-ten table built by the SAME recurrence as ops/decimal.py's
// _POW10_TABLE (T[k] = T[k-1]*10; T[-k] = 1/T[k] while finite, then /10
// into the subnormals): libm pow and numpy's SIMD pow differ by an ulp at
// large exponents, so a shared table is the only way both pipelines
// produce bit-identical mantissas.
#define VM_POW10_MAX 340
struct VmPow10Table {
    double t[2 * VM_POW10_MAX + 1];
    VmPow10Table() {
        t[VM_POW10_MAX] = 1.0;
        for (int k = 1; k <= VM_POW10_MAX; k++) {
            t[VM_POW10_MAX + k] = t[VM_POW10_MAX + k - 1] * 10.0;
            if (!std::isinf(t[VM_POW10_MAX + k]))
                t[VM_POW10_MAX - k] = 1.0 / t[VM_POW10_MAX + k];
            else
                t[VM_POW10_MAX - k] = t[VM_POW10_MAX - k + 1] / 10.0;
        }
    }
};
static const double* vm_pow10_table() {
    static VmPow10Table p;  // C++11 thread-safe init
    return p.t;
}

static inline double vm_pow10d(int64_t e) {
    if (e > VM_POW10_MAX) e = VM_POW10_MAX;
    if (e < -VM_POW10_MAX) e = -VM_POW10_MAX;
    return vm_pow10_table()[e + VM_POW10_MAX];
}

// x * 10^e for e >= 0 without overflowing the pow (split at 300), matching
// decimal._scale_up
static inline double vm_scale_up(double x, int64_t e) {
    int64_t e1 = e < 300 ? e : 300;
    return x * vm_pow10d(e1) * vm_pow10d(e - e1);
}

static void vm_f2d_decompose(double v, int64_t exp10, int digits,
                             int64_t* mo, int64_t* eo) {
    int64_t ei = exp10 - (digits - 1);
    if (ei < VM_F2D_MIN_EXP) ei = VM_F2D_MIN_EXP;
    if (ei > VM_F2D_MAX_EXP) ei = VM_F2D_MAX_EXP;
    double scaled = (ei < 0) ? vm_scale_up(v, -ei) : v / vm_pow10d(ei);
    double mi = nearbyint(scaled);
    double lim = vm_pow10d(digits);
    if (fabs(mi) >= lim) {  // 1-off exponent from floor(log10) at edges
        ei += 1;
        scaled = (ei < 0) ? vm_scale_up(v, -ei) : v / vm_pow10d(ei);
        mi = nearbyint(scaled);
    }
    if (mi > (double)VM_F2D_MAX_MANTISSA) mi = (double)VM_F2D_MAX_MANTISSA;
    if (mi < -(double)VM_F2D_MAX_MANTISSA) mi = -(double)VM_F2D_MAX_MANTISSA;
    *mo = (int64_t)mi;
    *eo = ei;
}

static inline void vm_f2d_elem(double x, int64_t* m, int64_t* e,
                               int* kind) {
    *m = 0;
    *e = 0;
    if (x != x) {
        uint64_t bits;
        memcpy(&bits, &x, 8);
        *kind = (bits == 0x7FF0000000000002ULL) ? VM_K_STALE : VM_K_NAN;
        return;
    }
    if (std::isinf(x)) { *kind = x > 0 ? VM_K_PINF : VM_K_NINF; return; }
    if (x == 0.0) { *kind = VM_K_ZERO; return; }
    *kind = VM_K_NORM;
    double ax = fabs(x);
    int64_t exp10 = (int64_t)floor(log10(ax));
    if (x == floor(x) && ax <= (double)VM_F2D_MAX_MANTISSA) {
        *m = (int64_t)x;
        *e = 0;
    } else {
        int64_t m15, e15;
        vm_f2d_decompose(x, exp10, 15, &m15, &e15);
        double recon = (e15 < 0) ? (double)m15 / vm_pow10d(-e15)
                                 : (double)m15 * vm_pow10d(e15);
        if (recon == x) {
            *m = m15;
            *e = e15;
        } else {
            vm_f2d_decompose(x, exp10, 17, m, e);
        }
    }
    while (*m != 0 && *m % 10 == 0) {
        *m /= 10;
        *e += 1;
    }
}

// v[n] float64 -> m_out[n] int64 mantissas + exps_out[n_groups]; group g
// covers v[starts[g]..starts[g+1]) (starts[n_groups] == n implied).
void vm_f2d_grouped(const double* v, const int64_t* starts,
                    int64_t n_groups, int64_t n, int64_t* m_out,
                    int64_t* exps_out) {
    std::vector<int64_t> es(n);
    std::vector<signed char> kinds(n);
    for (int64_t i = 0; i < n; i++) {
        int kind;
        vm_f2d_elem(v[i], &m_out[i], &es[i], &kind);
        kinds[i] = (signed char)kind;
    }
    for (int64_t g = 0; g < n_groups; g++) {
        int64_t a = starts[g];
        int64_t b = (g + 1 < n_groups) ? starts[g + 1] : n;
        int64_t emin = INT64_MAX, efloor = INT64_MIN;
        bool has_norm = false;
        for (int64_t i = a; i < b; i++) {
            if (kinds[i] != VM_K_NORM) continue;
            has_norm = true;
            if (es[i] < emin) emin = es[i];
            double absm = (double)(m_out[i] < 0 ? -m_out[i] : m_out[i]);
            if (absm < 1.0) absm = 1.0;
            int64_t allowed_up = (int64_t)floor(
                log10((double)VM_F2D_MAX_MANTISSA / absm));
            int64_t fl = es[i] - allowed_up;
            if (fl > efloor) efloor = fl;
        }
        int64_t exp = emin < VM_F2D_MAX_EXP ? emin : VM_F2D_MAX_EXP;
        if (efloor > exp) exp = efloor;
        if (exp > VM_F2D_MAX_EXP) exp = VM_F2D_MAX_EXP;
        if (exp < VM_F2D_MIN_EXP) exp = VM_F2D_MIN_EXP;
        if (!has_norm) exp = 0;
        exps_out[g] = exp;
        for (int64_t i = a; i < b; i++) {
            switch (kinds[i]) {
                case VM_K_STALE: m_out[i] = VM_V_STALE_NAN; continue;
                case VM_K_NAN: m_out[i] = VM_V_NAN; continue;
                case VM_K_PINF: m_out[i] = VM_V_INF_POS; continue;
                case VM_K_NINF: m_out[i] = VM_V_INF_NEG; continue;
                case VM_K_ZERO: m_out[i] = 0; continue;
            }
            int64_t shift = es[i] - exp;
            if (shift > 0) {
                int64_t factor = 1;
                for (int64_t k = 0; k < shift; k++) factor *= 10;
                m_out[i] *= factor;
            } else if (shift < 0) {
                int64_t dshift = -shift < 19 ? -shift : 19;
                m_out[i] = (int64_t)nearbyint(
                    (double)m_out[i] / vm_pow10d(dshift));
            }
        }
    }
}

// ---------------------------------------------------------------------------
// fused part assemble: fetch -> decode -> clip -> float, one call per part
// ---------------------------------------------------------------------------
// The served-read-path kernel (ROADMAP item 1): for K (header-selected)
// blocks of one immutable part, decode the timestamp stream, clamp lossy
// sequences, row-clip each block to the [lo, hi]-inclusive query range by
// binary search, decode the value stream ONLY for blocks that kept rows,
// convert the kept mantissas to float64 with the block's decimal exponent
// (vm_d2f_one — bit-exact with ops/decimal.decimal_to_float), and write the
// surviving rows densely into caller-provided columnar buffers.
//
// Buffer contract (the zero-copy handoff): out_ts / out_vals hold at least
// sum(cnt) entries — block i may be decoded in place at the current write
// head before compaction, which fits because the head only advances by
// kept rows. out_cnt[i] receives block i's kept-row count (callers drop
// zero-count blocks from their per-block id/exponent columns, mirroring
// clip_piece). Returns total kept rows, or -(i+1) when block i is
// malformed / needs an unavailable payload codec.
int64_t vm_assemble_part(
    const uint8_t* ts_base, const uint8_t* val_base,
    const int64_t* ts_off, const int64_t* ts_sz, const int32_t* ts_mt,
    const int64_t* ts_first,
    const int64_t* val_off, const int64_t* val_sz, const int32_t* val_mt,
    const int64_t* val_first,
    const int64_t* cnt, const int64_t* exps, int64_t k,
    int64_t lo, int64_t hi,
    int64_t* out_ts, double* out_vals, int64_t* out_cnt) {
    int64_t opos = 0;
    std::vector<int64_t> mant;
    std::vector<uint8_t> infl;
    for (int64_t i = 0; i < k; i++) {
        int64_t n = cnt[i];
        if (n <= 0) return -(i + 1);
        // timestamps decode straight into the output at the write head
        int32_t t = ts_mt[i];
        const uint8_t* p = ts_base + ts_off[i];
        int64_t r;
        if (t == VM_MT_ZSTD_NEAREST_DELTA || t == VM_MT_ZSTD_NEAREST_DELTA2) {
            int64_t cap = (n + 1) * 10 + 16;
            if ((int64_t)infl.size() < cap) infl.resize((size_t)cap);
            int64_t got = vm_inflate(p, ts_sz[i], infl.data(), cap);
            if (got < 0) return -(i + 1);
            r = vm_decode_plain(infl.data(), got, t - 2, ts_first[i], n,
                                out_ts + opos);
        } else {
            r = vm_decode_plain(p, ts_sz[i], t, ts_first[i], n,
                                out_ts + opos);
        }
        if (r != n) return -(i + 1);
        if (t == VM_MT_NEAREST_DELTA || t == VM_MT_NEAREST_DELTA2) {
            // lossy uncompressed types carry no checksum: re-validate
            // non-decreasing order (ops/encoding.py needs_validation)
            int64_t* o = out_ts + opos;
            for (int64_t j = 1; j < n; j++) {
                if (o[j] < o[j - 1]) o[j] = o[j - 1];
            }
        }
        // row clip to [lo, hi] inclusive (vm_clip_blocks semantics)
        int64_t* bt = out_ts + opos;
        int64_t a, b;
        {
            int64_t l = 0, r2 = n;
            while (l < r2) {
                int64_t m = l + ((r2 - l) >> 1);
                if (bt[m] < lo) l = m + 1; else r2 = m;
            }
            a = l;
            r2 = n;
            while (l < r2) {
                int64_t m = l + ((r2 - l) >> 1);
                if (bt[m] <= hi) l = m + 1; else r2 = m;
            }
            b = l;
        }
        int64_t kept = b - a;
        out_cnt[i] = kept;
        if (kept == 0) continue;  // fully clipped: value decode skipped
        if (a > 0) memmove(bt, bt + a, (size_t)kept * sizeof(int64_t));
        // values: full-block decode to scratch, convert only kept rows
        t = val_mt[i];
        p = val_base + val_off[i];
        if ((int64_t)mant.size() < n) mant.resize((size_t)n);
        if (t == VM_MT_ZSTD_NEAREST_DELTA || t == VM_MT_ZSTD_NEAREST_DELTA2) {
            int64_t cap = (n + 1) * 10 + 16;
            if ((int64_t)infl.size() < cap) infl.resize((size_t)cap);
            int64_t got = vm_inflate(p, val_sz[i], infl.data(), cap);
            if (got < 0) return -(i + 1);
            r = vm_decode_plain(infl.data(), got, t - 2, val_first[i], n,
                                mant.data());
        } else {
            r = vm_decode_plain(p, val_sz[i], t, val_first[i], n,
                                mant.data());
        }
        if (r != n) return -(i + 1);
        vm_d2f_one(mant.data() + a, kept, exps[i], out_vals + opos);
        opos += kept;
    }
    return opos;
}

// ---------------------------------------------------------------------------
// per-row query-time dedup over the padded (S, N) layout
// ---------------------------------------------------------------------------

static inline bool vm_is_stale(double x) {
    uint64_t b;
    memcpy(&b, &x, 8);
    return b == 0x7FF0000000000002ULL;
}

// right-inclusive dedup window id, bit-exact with storage/dedup.py
// _buckets (numpy // is floor division, C++ / truncates: adjust)
static inline int64_t vm_bucket(int64_t ts, int64_t interval) {
    int64_t x = ts + interval - 1;
    int64_t q = x / interval;
    if ((x % interval != 0) && ((x < 0) != (interval < 0))) q--;
    return q;
}

// For each listed row of the (S, N) ts/vals layout: apply interval dedup
// (keep the max-ts sample per window; on timestamp ties prefer the max
// non-stale value via the reference's backward scan — dedup.go:30-121 as
// mirrored by storage/dedup.py), then drop exact-duplicate timestamps
// keeping the LAST sample, compact the row in place, pad the freed tail
// with (pad_ts, 0.0) and rewrite counts[row]. Row strides are in elements
// (the arrays may be column-sliced views). interval <= 0 runs only the
// exact-duplicate pass — byte-for-byte what columnar.assemble()'s per-row
// Python loop does.
void vm_dedup_rows(int64_t* ts, int64_t ts_stride, double* v,
                   int64_t v_stride, int64_t* counts, const int64_t* rows,
                   int64_t n_rows, int64_t interval, int64_t pad_ts) {
    for (int64_t ri = 0; ri < n_rows; ri++) {
        int64_t s = rows[ri];
        int64_t n = counts[s];
        int64_t* t = ts + s * ts_stride;
        double* vv = v + s * v_stride;
        int64_t m = n;
        if (interval > 0 && n >= 2) {
            bool need = false;
            int64_t bprev = vm_bucket(t[0], interval);
            for (int64_t j = 1; j < n; j++) {
                int64_t bj = vm_bucket(t[j], interval);
                if (bj == bprev) { need = true; break; }
                bprev = bj;
            }
            if (need) {
                m = 0;
                int64_t a = 0;
                while (a < n) {
                    int64_t ba = vm_bucket(t[a], interval);
                    int64_t b = a + 1;
                    while (b < n && vm_bucket(t[b], interval) == ba) b++;
                    int64_t tmax = t[b - 1];
                    double val = vv[b - 1];
                    // tie run: rows are time-sorted, so the equal-tmax
                    // samples are the window's suffix
                    int64_t f = b - 1;
                    while (f > a && t[f - 1] == tmax) f--;
                    if (b - f >= 2) {
                        double vprev = vv[b - 1];
                        bool vprev_stale = vm_is_stale(vprev);
                        for (int64_t j = b - 2; j >= f; j--) {
                            if (vm_is_stale(vv[j])) continue;
                            if (vprev_stale) {
                                vprev = vv[j];
                                vprev_stale = false;
                            } else if (vv[j] > vprev) {
                                vprev = vv[j];
                            }
                        }
                        val = vprev;
                    }
                    t[m] = tmax;  // m <= a: never clobbers unread input
                    vv[m] = val;
                    m++;
                    a = b;
                }
            }
        }
        // exact-duplicate timestamps (replica merges): keep the LAST
        int64_t w = 0;
        for (int64_t j = 0; j < m; j++) {
            if (j + 1 < m && t[j + 1] == t[j]) continue;
            t[w] = t[j];
            vv[w] = vv[j];
            w++;
        }
        m = w;
        if (m != n) {
            for (int64_t j = m; j < n; j++) {
                t[j] = pad_ts;
                vv[j] = 0.0;
            }
            counts[s] = m;
        }
    }
}

}  // extern "C"
