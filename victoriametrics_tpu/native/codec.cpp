// Native host codecs: bulk zigzag-varint + delta2 encode/decode.
//
// The reference's hot host loops are hand-tuned Go (lib/encoding/int.go
// varint bulk codecs, nearest_delta2.go) with its only native code being cgo
// zstd (SURVEY §2.9). Here the ingest/scan hot loops get a real native
// implementation, exposed through a C ABI consumed via ctypes
// (victoriametrics_tpu/native/__init__.py). Build: `make -C native` or the
// lazy auto-build in the Python wrapper.
//
// All functions are thread-safe (no global state) and release-the-GIL safe
// (pure C, no Python API).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// zigzag varint
// ---------------------------------------------------------------------------

// Encode n int64s as zigzag varints into out (caller provides >= 10*n bytes).
// Returns bytes written.
int64_t vm_varint_encode(const int64_t* vals, int64_t n, uint8_t* out) {
    uint8_t* p = out;
    for (int64_t i = 0; i < n; i++) {
        uint64_t u = ((uint64_t)vals[i] << 1) ^ (uint64_t)(vals[i] >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

// Decode up to max_vals zigzag varints from data[0:len]. Returns number of
// values decoded, or -1 on malformed input (truncated / overlong varint).
int64_t vm_varint_decode(const uint8_t* data, int64_t len, int64_t* out,
                         int64_t max_vals) {
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    int64_t count = 0;
    while (p < end && count < max_vals) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        out[count++] = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
    }
    if (p != end && count < max_vals) return -1;
    return count;
}

// ---------------------------------------------------------------------------
// delta2 (double-delta) + varint, fused: the block encode/decode hot path
// ---------------------------------------------------------------------------

// vals[0..n) -> first, first_delta, varint(d2 stream) in out.
// Returns payload bytes written; first/first_delta via out params.
int64_t vm_delta2_encode(const int64_t* vals, int64_t n, uint8_t* out,
                         int64_t* first, int64_t* first_delta) {
    if (n < 2) return -1;
    *first = vals[0];
    int64_t prev_d = (int64_t)((uint64_t)vals[1] - (uint64_t)vals[0]);
    *first_delta = prev_d;
    uint8_t* p = out;
    for (int64_t i = 2; i < n; i++) {
        int64_t d = (int64_t)((uint64_t)vals[i] - (uint64_t)vals[i - 1]);
        int64_t d2 = (int64_t)((uint64_t)d - (uint64_t)prev_d);
        prev_d = d;
        uint64_t u = ((uint64_t)d2 << 1) ^ (uint64_t)(d2 >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

// Inverse: reconstruct n values from first, first_delta and the d2 varint
// stream. Returns n on success, -1 on malformed input.
int64_t vm_delta2_decode(const uint8_t* data, int64_t len, int64_t first,
                         int64_t first_delta, int64_t* out, int64_t n) {
    if (n < 1) return -1;
    out[0] = first;
    if (n == 1) return 1;
    int64_t v = first;
    int64_t d = first_delta;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    v = (int64_t)((uint64_t)v + (uint64_t)d);
    out[1] = v;
    for (int64_t i = 2; i < n; i++) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        int64_t d2 = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
        d = (int64_t)((uint64_t)d + (uint64_t)d2);
        v = (int64_t)((uint64_t)v + (uint64_t)d);
        out[i] = v;
    }
    return (p == end) ? n : -1;
}

// ---------------------------------------------------------------------------
// delta1 (single delta) + varint
// ---------------------------------------------------------------------------

int64_t vm_delta_encode(const int64_t* vals, int64_t n, uint8_t* out,
                        int64_t* first) {
    if (n < 1) return -1;
    *first = vals[0];
    uint8_t* p = out;
    for (int64_t i = 1; i < n; i++) {
        int64_t d = (int64_t)((uint64_t)vals[i] - (uint64_t)vals[i - 1]);
        uint64_t u = ((uint64_t)d << 1) ^ (uint64_t)(d >> 63);
        while (u >= 0x80) {
            *p++ = (uint8_t)(u) | 0x80;
            u >>= 7;
        }
        *p++ = (uint8_t)u;
    }
    return (int64_t)(p - out);
}

int64_t vm_delta_decode(const uint8_t* data, int64_t len, int64_t first,
                        int64_t* out, int64_t n) {
    if (n < 1) return -1;
    out[0] = first;
    int64_t v = first;
    const uint8_t* p = data;
    const uint8_t* end = data + len;
    for (int64_t i = 1; i < n; i++) {
        uint64_t u = 0;
        int shift = 0;
        for (;;) {
            if (p >= end || shift > 63) return -1;
            uint8_t b = *p++;
            u |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        int64_t d = (int64_t)(u >> 1) ^ -(int64_t)(u & 1);
        v = (int64_t)((uint64_t)v + (uint64_t)d);
        out[i] = v;
    }
    return (p == end) ? n : -1;
}


// ---------------------------------------------------------------------------
// batched block marshal: type choice + encode for K blocks in one call
// ---------------------------------------------------------------------------

// Marshal types (mirror ops/encoding.py MarshalType)
#define VM_MT_CONST 1
#define VM_MT_DELTA_CONST 2
#define VM_MT_NEAREST_DELTA 3
#define VM_MT_NEAREST_DELTA2 4

// For each block i with values vals[offsets[i]..offsets[i+1]):
// choose CONST / DELTA_CONST / NEAREST_DELTA (gauge: >1/8 negative deltas)
// / NEAREST_DELTA2 exactly like ops/encoding.py marshal_int64_array, encode
// the payload contiguously into out, and record (type, first_value,
// payload_len). Returns total bytes written, or -1 when out_cap would be
// exceeded. offsets has n_blocks+1 entries.
int64_t vm_marshal_i64_many(const int64_t* vals, const int64_t* offsets,
                            int64_t n_blocks, uint8_t* out, int64_t out_cap,
                            int32_t* types, int64_t* firsts, int64_t* lens) {
    int64_t pos = 0;
    for (int64_t i = 0; i < n_blocks; i++) {
        const int64_t* v = vals + offsets[i];
        int64_t n = offsets[i + 1] - offsets[i];
        if (n <= 0) return -1;
        // worst case: 10 bytes per varint
        if (pos + (n + 1) * 10 > out_cap) return -1;
        bool is_const = true;
        for (int64_t j = 1; j < n; j++) {
            if (v[j] != v[0]) { is_const = false; break; }
        }
        if (is_const) {
            types[i] = VM_MT_CONST;
            firsts[i] = v[0];
            lens[i] = 0;
            continue;
        }
        // delta-const (wrapping two's-complement deltas, like np.int64)
        if (n >= 2) {
            uint64_t d0 = (uint64_t)v[1] - (uint64_t)v[0];
            bool dconst = true;
            for (int64_t j = 2; j < n; j++) {
                if ((uint64_t)v[j] - (uint64_t)v[j - 1] != d0) {
                    dconst = false;
                    break;
                }
            }
            if (dconst) {
                int64_t d = (int64_t)d0;
                int64_t len = vm_varint_encode(&d, 1, out + pos);
                types[i] = VM_MT_DELTA_CONST;
                firsts[i] = v[0];
                lens[i] = len;
                pos += len;
                continue;
            }
        }
        int64_t neg = 0;
        for (int64_t j = 1; j < n; j++) {
            if (v[j] < v[j - 1]) neg++;
        }
        if (neg * 8 > n) {
            // gauge: first-order deltas
            int64_t first;
            int64_t len = vm_delta_encode(v, n, out + pos, &first);
            types[i] = VM_MT_NEAREST_DELTA;
            firsts[i] = first;
            lens[i] = len;
            pos += len;
        } else {
            // counter: varint(first_delta) + delta2 stream
            int64_t first, first_delta;
            uint8_t tmp[10];
            int64_t d2len = vm_delta2_encode(v, n, out + pos, &first,
                                             &first_delta);
            int64_t fdlen = vm_varint_encode(&first_delta, 1, tmp);
            // shift payload right to prepend the first_delta varint
            memmove(out + pos + fdlen, out + pos, d2len);
            memcpy(out + pos, tmp, fdlen);
            types[i] = VM_MT_NEAREST_DELTA2;
            firsts[i] = first;
            lens[i] = fdlen + d2len;
            pos += fdlen + d2len;
        }
    }
    return pos;
}

}  // extern "C"
