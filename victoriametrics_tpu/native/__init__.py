"""ctypes bindings for the native host codec kernels (codec.cpp).

Auto-builds libvmcodec.so with g++ on first import if missing (and a
compiler is available); falls back to None so callers keep their NumPy
paths. This mirrors the reference's cgo-zstd-with-pure-Go-fallback split
(lib/encoding/zstd/zstd_{cgo,pure}.go).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libvmcodec.so")

_lib = None


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO)
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    i64 = ctypes.c_int64
    p8 = ctypes.POINTER(ctypes.c_uint8)
    pi64 = ctypes.POINTER(i64)
    lib.vm_varint_encode.restype = i64
    lib.vm_varint_encode.argtypes = [pi64, i64, p8]
    lib.vm_varint_decode.restype = i64
    lib.vm_varint_decode.argtypes = [p8, i64, pi64, i64]
    lib.vm_delta2_encode.restype = i64
    lib.vm_delta2_encode.argtypes = [pi64, i64, p8, pi64, pi64]
    lib.vm_delta2_decode.restype = i64
    lib.vm_delta2_decode.argtypes = [p8, i64, i64, i64, pi64, i64]
    lib.vm_delta_encode.restype = i64
    lib.vm_delta_encode.argtypes = [pi64, i64, p8, pi64]
    lib.vm_delta_decode.restype = i64
    lib.vm_delta_decode.argtypes = [p8, i64, i64, pi64, i64]
    pi32 = ctypes.POINTER(ctypes.c_int32)
    pf64 = ctypes.POINTER(ctypes.c_double)
    lib.vm_parse_prom.restype = i64
    lib.vm_parse_prom.argtypes = [ctypes.c_char_p, i64, pi32, pi32,
                                  pf64, pi64, i64]
    lib.vm_marshal_i64_many.restype = i64
    lib.vm_marshal_i64_many.argtypes = [pi64, pi64, i64, p8, i64,
                                        pi32, pi64, pi64]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _as_i64_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _as_u8_ptr(b):
    return ctypes.cast(ctypes.c_char_p(bytes(b) if not isinstance(b, (bytes, bytearray)) else b),
                       ctypes.POINTER(ctypes.c_uint8))


def varint_encode(vals: np.ndarray) -> bytes:
    lib = _load()
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = ctypes.create_string_buffer(int(vals.size) * 10 or 1)
    n = lib.vm_varint_encode(_as_i64_ptr(vals), vals.size,
                             ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)))
    return out.raw[:n]


def varint_decode(data: bytes, count: int) -> np.ndarray:
    lib = _load()
    out = np.empty(count, dtype=np.int64)
    n = lib.vm_varint_decode(_as_u8_ptr(data), len(data), _as_i64_ptr(out),
                             count)
    if n != count:
        raise ValueError(f"native varint: expected {count} values, got {n}")
    return out


def delta2_encode(vals: np.ndarray) -> tuple[bytes, int, int]:
    lib = _load()
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = ctypes.create_string_buffer(int(vals.size) * 10 or 1)
    first = ctypes.c_int64()
    fd = ctypes.c_int64()
    n = lib.vm_delta2_encode(_as_i64_ptr(vals), vals.size,
                             ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
                             ctypes.byref(first), ctypes.byref(fd))
    if n < 0:
        raise ValueError("native delta2 encode failed")
    return out.raw[:n], first.value, fd.value


def delta2_decode(data: bytes, first: int, first_delta: int,
                  count: int) -> np.ndarray:
    lib = _load()
    out = np.empty(count, dtype=np.int64)
    n = lib.vm_delta2_decode(_as_u8_ptr(data), len(data), first, first_delta,
                             _as_i64_ptr(out), count)
    if n != count:
        raise ValueError("native delta2: malformed payload")
    return out


def delta_encode(vals: np.ndarray) -> tuple[bytes, int]:
    lib = _load()
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = ctypes.create_string_buffer(int(vals.size) * 10 or 1)
    first = ctypes.c_int64()
    n = lib.vm_delta_encode(_as_i64_ptr(vals), vals.size,
                            ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
                            ctypes.byref(first))
    if n < 0:
        raise ValueError("native delta encode failed")
    return out.raw[:n], first.value


def delta_decode(data: bytes, first: int, count: int) -> np.ndarray:
    lib = _load()
    out = np.empty(count, dtype=np.int64)
    n = lib.vm_delta_decode(_as_u8_ptr(data), len(data), first,
                            _as_i64_ptr(out), count)
    if n != count:
        raise ValueError("native delta: malformed payload")
    return out


_TS_ABSENT = -(2 ** 63)  # INT64_MIN sentinel from vm_parse_prom


def parse_prom_raw(data: bytes, default_ts: int):
    """Native prometheus text parse -> list of (series_key_bytes, ts_ms,
    value). Returns None when the native library is unavailable (callers
    fall back to the Python parser). The series key is the raw
    `name{labels}` prefix — the storage TSID cache is keyed on it directly,
    so repeat scrapes never materialize labels."""
    lib = _load()
    if lib is None:
        return None
    n_max = data.count(b"\n") + 2
    key_off = np.empty(n_max, dtype=np.int32)
    key_len = np.empty(n_max, dtype=np.int32)
    values = np.empty(n_max, dtype=np.float64)
    tss = np.empty(n_max, dtype=np.int64)
    n = lib.vm_parse_prom(
        data, len(data),
        key_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        key_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _as_i64_ptr(tss), n_max)
    out = []
    mv = memoryview(data)
    for i in range(n):
        o = key_off[i]
        ts = tss[i]
        # explicit 0 is "no timestamp" too, matching the Python ingest path
        # (Row.with_default_ts treats 0 as absent)
        out.append((bytes(mv[o:o + key_len[i]]),
                    default_ts if ts == _TS_ABSENT or ts == 0 else int(ts),
                    values[i]))
    return out


def marshal_i64_many(vals: np.ndarray, offsets: np.ndarray):
    """Batched block marshal: type choice + encode for K blocks in one
    native call. vals = int64 concatenation, offsets = K+1 boundaries.
    Returns (payload bytes, types int32[K], firsts int64[K], lens int64[K])
    or None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    k = offsets.size - 1
    cap = int(vals.size + k) * 10 + 16
    out = ctypes.create_string_buffer(cap)
    types = np.empty(k, dtype=np.int32)
    firsts = np.empty(k, dtype=np.int64)
    lens = np.empty(k, dtype=np.int64)
    n = lib.vm_marshal_i64_many(
        _as_i64_ptr(vals), _as_i64_ptr(offsets), k,
        ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), cap,
        types.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _as_i64_ptr(firsts), _as_i64_ptr(lens))
    if n < 0:
        raise ValueError("native batched marshal failed")
    return out.raw[:n], types, firsts, lens
