"""ctypes bindings for the native host codec kernels (codec.cpp).

Auto-builds libvmcodec.so with g++ on first import if missing (and a
compiler is available); falls back to None so callers keep their NumPy
paths. This mirrors the reference's cgo-zstd-with-pure-Go-fallback split
(lib/encoding/zstd/zstd_{cgo,pure}.go).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libvmcodec.so")

_lib = None


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO)
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO) and not _build():
        return None
    try:
        lib = _configure(ctypes.CDLL(_SO))
    except OSError:
        return None
    except AttributeError:
        # stale .so from older sources (the binary is untracked): rebuild
        # once, then give up and let callers keep their numpy paths
        try:
            os.remove(_SO)
        except OSError:
            return None
        if not _build():
            return None
        try:
            lib = _configure(ctypes.CDLL(_SO))
        except (OSError, AttributeError):
            return None
    # benign double-load: racing loaders dlopen the same .so and store
    # equivalent handles; the loser's handle is dropped, never used half-set
    _lib = lib  # vmt: disable=VMT015
    return lib


def _configure(lib):
    i64 = ctypes.c_int64
    p8 = ctypes.POINTER(ctypes.c_uint8)
    pi64 = ctypes.POINTER(i64)
    lib.vm_varint_encode.restype = i64
    lib.vm_varint_encode.argtypes = [pi64, i64, p8]
    lib.vm_varint_decode.restype = i64
    lib.vm_varint_decode.argtypes = [p8, i64, pi64, i64]
    lib.vm_delta2_encode.restype = i64
    lib.vm_delta2_encode.argtypes = [pi64, i64, p8, pi64, pi64]
    lib.vm_delta2_decode.restype = i64
    lib.vm_delta2_decode.argtypes = [p8, i64, i64, i64, pi64, i64]
    lib.vm_delta_encode.restype = i64
    lib.vm_delta_encode.argtypes = [pi64, i64, p8, pi64]
    lib.vm_delta_decode.restype = i64
    lib.vm_delta_decode.argtypes = [p8, i64, i64, pi64, i64]
    pi32 = ctypes.POINTER(ctypes.c_int32)
    pf64 = ctypes.POINTER(ctypes.c_double)
    lib.vm_parse_prom.restype = i64
    lib.vm_parse_prom.argtypes = [ctypes.c_char_p, i64, pi32, pi32,
                                  pf64, pi64, i64]
    lib.vm_marshal_i64_many.restype = i64
    lib.vm_marshal_i64_many.argtypes = [pi64, pi64, i64, p8, i64,
                                        pi32, pi64, pi64]
    lib.vm_has_zstd.restype = ctypes.c_int32
    lib.vm_has_zstd.argtypes = []
    lib.vm_decompress_caps.restype = ctypes.c_int32
    lib.vm_decompress_caps.argtypes = []
    lib.vm_zstd_compress_bound.restype = i64
    lib.vm_zstd_compress_bound.argtypes = [i64]
    lib.vm_zstd_compress.restype = i64
    lib.vm_zstd_compress.argtypes = [p8, i64, p8, i64, ctypes.c_int32]
    lib.vm_zstd_content_size.restype = i64
    lib.vm_zstd_content_size.argtypes = [p8, i64]
    lib.vm_zstd_decompress.restype = i64
    lib.vm_zstd_decompress.argtypes = [p8, i64, p8, i64]
    lib.vm_assemble_part.restype = i64
    lib.vm_assemble_part.argtypes = [p8, p8, pi64, pi64, pi32, pi64,
                                     pi64, pi64, pi32, pi64, pi64, pi64,
                                     i64, i64, i64, pi64, pf64, pi64]
    lib.vm_dedup_rows.restype = None
    lib.vm_dedup_rows.argtypes = [pi64, i64, pf64, i64, pi64, pi64, i64,
                                  i64, i64]
    lib.vm_decode_blocks.restype = i64
    lib.vm_decode_blocks.argtypes = [p8, pi64, pi64, pi32, pi64, pi64,
                                     i64, pi64, ctypes.c_int32]
    lib.vm_decimal_to_float_blocks.restype = None
    lib.vm_decimal_to_float_blocks.argtypes = [pi64, pi64, pi64, i64, pf64]
    lib.vm_clip_blocks.restype = None
    lib.vm_clip_blocks.argtypes = [pi64, pi64, pi64, i64, i64, i64,
                                   pi64, pi64]
    lib.vm_gather_rows2.restype = None
    lib.vm_gather_rows2.argtypes = [pi64, pi64, pi64, pi64, i64, pi64, pi64]
    lib.vm_scatter_pad.restype = None
    lib.vm_scatter_pad.argtypes = [pi64, pf64, pi64, pi64, i64, i64, i64,
                                   i64, pi64, pf64, pi64]
    lib.vm_counter_resets_2d.restype = None
    lib.vm_counter_resets_2d.argtypes = [pf64, i64, i64, pf64]
    lib.vm_f2d_grouped.restype = None
    lib.vm_f2d_grouped.argtypes = [pf64, pi64, i64, i64, pi64, pi64]
    lib.vm_rollup_counter_2d.restype = None
    lib.vm_rollup_counter_2d.argtypes = [pi64, pf64, pi64, i64, i64, i64,
                                         i64, i64, i64, pi64,
                                         ctypes.c_int32, pf64, pf64]
    lib.vm_snappy_uncompressed_len.restype = i64
    lib.vm_snappy_uncompressed_len.argtypes = [p8, i64]
    lib.vm_snappy_uncompress.restype = i64
    lib.vm_snappy_uncompress.argtypes = [p8, i64, p8, i64]
    lib.vm_parse_rw.restype = i64
    lib.vm_parse_rw.argtypes = [p8, i64, i64, p8, i64, pi64, pi64,
                                pf64, pi64, i64]
    lib.vm_parse_influx.restype = i64
    lib.vm_parse_influx.argtypes = [p8, i64, p8, i64, i64, p8, i64,
                                    pi64, pi64, pf64, pi64, i64]
    lib.vm_keymap_new.restype = i64
    lib.vm_keymap_new.argtypes = []
    lib.vm_keymap_free.restype = None
    lib.vm_keymap_free.argtypes = [i64]
    lib.vm_keymap_size.restype = i64
    lib.vm_keymap_size.argtypes = [i64]
    lib.vm_keymap_resolve.restype = i64
    lib.vm_keymap_resolve.argtypes = [i64, p8, pi64, pi64, i64, pi64]
    return lib


def available() -> bool:
    return _load() is not None


def has_zstd() -> bool:
    """True when zstd frames decode natively (linked libzstd or the
    runtime libzstd.so.1 resolved via dlopen); callers with zstd-marshaled
    blocks must otherwise take their Python path."""
    lib = _load()
    return bool(lib is not None and lib.vm_has_zstd())


def decompress_caps() -> int:
    """Bitmask of compressed-payload codecs the native decoder can
    inflate: bit 0 = zstd frames, bit 1 = zlib fallback streams."""
    lib = _load()
    return int(lib.vm_decompress_caps()) if lib is not None else 0


def assemble_enabled() -> bool:
    """Whether the fused native read kernel (vm_assemble_part) serves
    queries. ``VM_NATIVE_ASSEMBLE=0`` is the escape hatch AND the
    correctness oracle: it restores the split Python-orchestrated
    collect/decode/assemble path exactly. Re-read per call, like
    VM_SEARCH_WORKERS, so tests can flip modes without restarting."""
    return os.environ.get("VM_NATIVE_ASSEMBLE", "1") != "0" and available()


def zstd_compress(data: bytes, level: int = 1):
    """One-shot zstd compress via the runtime library; None when zstd is
    unavailable (callers fall back to zlib)."""
    lib = _load()
    if lib is None:
        return None
    cap = lib.vm_zstd_compress_bound(len(data))
    if cap < 0:
        return None
    out = ctypes.create_string_buffer(int(cap) or 1)
    n = lib.vm_zstd_compress(
        _as_u8_ptr(data), len(data),
        ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), cap, level)
    if n < 0:
        return None
    return out.raw[:n]


def zstd_decompress(data: bytes, max_size: int = 1 << 30):
    """One-shot zstd decompress, allocation-bounded by the frame's claimed
    content size (refused when unknown or above max_size — a hostile frame
    cannot balloon memory). None when zstd is unavailable; raises on a
    corrupt/oversized frame."""
    lib = _load()
    if lib is None or not lib.vm_has_zstd():
        return None
    src = _as_u8_ptr(data)
    size = lib.vm_zstd_content_size(src, len(data))
    if size < 0 or size > max_size:
        raise ValueError(
            f"zstd frame claims unknown or oversized content ({size})")
    out = ctypes.create_string_buffer(int(size) or 1)
    n = lib.vm_zstd_decompress(
        src, len(data), ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
        size)
    if n != size:
        raise ValueError("native zstd: malformed frame")
    return out.raw[:n]


def _as_i64_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _as_u8_ptr(b):
    return ctypes.cast(ctypes.c_char_p(bytes(b) if not isinstance(b, (bytes, bytearray)) else b),
                       ctypes.POINTER(ctypes.c_uint8))


def varint_encode(vals: np.ndarray) -> bytes:
    lib = _load()
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = ctypes.create_string_buffer(int(vals.size) * 10 or 1)
    n = lib.vm_varint_encode(_as_i64_ptr(vals), vals.size,
                             ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)))
    return out.raw[:n]


def varint_decode(data: bytes, count: int) -> np.ndarray:
    lib = _load()
    out = np.empty(count, dtype=np.int64)
    n = lib.vm_varint_decode(_as_u8_ptr(data), len(data), _as_i64_ptr(out),
                             count)
    if n != count:
        raise ValueError(f"native varint: expected {count} values, got {n}")
    return out


def delta2_encode(vals: np.ndarray) -> tuple[bytes, int, int]:
    lib = _load()
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = ctypes.create_string_buffer(int(vals.size) * 10 or 1)
    first = ctypes.c_int64()
    fd = ctypes.c_int64()
    n = lib.vm_delta2_encode(_as_i64_ptr(vals), vals.size,
                             ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
                             ctypes.byref(first), ctypes.byref(fd))
    if n < 0:
        raise ValueError("native delta2 encode failed")
    return out.raw[:n], first.value, fd.value


def delta2_decode(data: bytes, first: int, first_delta: int,
                  count: int) -> np.ndarray:
    lib = _load()
    out = np.empty(count, dtype=np.int64)
    n = lib.vm_delta2_decode(_as_u8_ptr(data), len(data), first, first_delta,
                             _as_i64_ptr(out), count)
    if n != count:
        raise ValueError("native delta2: malformed payload")
    return out


def delta_encode(vals: np.ndarray) -> tuple[bytes, int]:
    lib = _load()
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    out = ctypes.create_string_buffer(int(vals.size) * 10 or 1)
    first = ctypes.c_int64()
    n = lib.vm_delta_encode(_as_i64_ptr(vals), vals.size,
                            ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
                            ctypes.byref(first))
    if n < 0:
        raise ValueError("native delta encode failed")
    return out.raw[:n], first.value


def delta_decode(data: bytes, first: int, count: int) -> np.ndarray:
    lib = _load()
    out = np.empty(count, dtype=np.int64)
    n = lib.vm_delta_decode(_as_u8_ptr(data), len(data), first,
                            _as_i64_ptr(out), count)
    if n != count:
        raise ValueError("native delta: malformed payload")
    return out


_TS_ABSENT = -(2 ** 63)  # INT64_MIN sentinel from vm_parse_prom


def parse_prom_raw(data: bytes, default_ts: int):
    """Native prometheus text parse -> list of (series_key_bytes, ts_ms,
    value). Returns None when the native library is unavailable (callers
    fall back to the Python parser). The series key is the raw
    `name{labels}` prefix — the storage TSID cache is keyed on it directly,
    so repeat scrapes never materialize labels."""
    lib = _load()
    if lib is None:
        return None
    n_max = data.count(b"\n") + 2
    key_off = np.empty(n_max, dtype=np.int32)
    key_len = np.empty(n_max, dtype=np.int32)
    values = np.empty(n_max, dtype=np.float64)
    tss = np.empty(n_max, dtype=np.int64)
    n = lib.vm_parse_prom(
        data, len(data),
        key_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        key_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _as_i64_ptr(tss), n_max)
    out = []
    mv = memoryview(data)
    for i in range(n):
        o = key_off[i]
        ts = tss[i]
        # explicit 0 is "no timestamp" too, matching the Python ingest path
        # (Row.with_default_ts treats 0 as absent)
        out.append((bytes(mv[o:o + key_len[i]]),
                    default_ts if ts == _TS_ABSENT or ts == 0 else int(ts),
                    values[i]))
    return out


def decode_blocks(buf, off: np.ndarray, sz: np.ndarray, mt: np.ndarray,
                  first: np.ndarray, cnt: np.ndarray, out: np.ndarray,
                  validate_ts: bool) -> None:
    """Batched block decode: K payloads at buf[off[i]:off[i]+sz[i]] (zstd
    inline for MarshalType 5/6) -> int64s written contiguously into `out`
    (pre-sized to cnt.sum()). buf may be any buffer (bytes/mmap/ndarray).
    Raises ValueError naming the malformed block."""
    lib = _load()
    if isinstance(buf, np.ndarray):
        base = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    else:  # bytes: zero-copy via c_char_p
        base = ctypes.cast(ctypes.c_char_p(buf),
                           ctypes.POINTER(ctypes.c_uint8))
    k = int(off.size)
    r = lib.vm_decode_blocks(
        base, _as_i64_ptr(off), _as_i64_ptr(sz),
        mt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _as_i64_ptr(first), _as_i64_ptr(cnt), k, _as_i64_ptr(out),
        1 if validate_ts else 0)
    if r != int(cnt.sum()):
        raise ValueError(f"native decode_blocks: malformed block {-r - 1}")


def _as_base_ptr(buf):
    if isinstance(buf, np.ndarray):
        return buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    return ctypes.cast(ctypes.c_char_p(buf), ctypes.POINTER(ctypes.c_uint8))


def assemble_part(ts_buf, val_buf, ts_off, ts_sz, ts_mt, ts_first,
                  val_off, val_sz, val_mt, val_first, cnt, exps,
                  lo: int, hi: int):
    """Fused per-part read kernel (vm_assemble_part): decode K blocks'
    timestamp+value streams from the part's mmap'd payload buffers, clip
    each block to [lo, hi], convert kept mantissas to float64 with the
    block exponents, and compact into freshly allocated output columns —
    ONE GIL-released call per part. Returns (kept_per_block int64[K],
    ts int64[kept], vals float64[kept]); the ts/vals arrays are zero-copy
    views of the kernel-filled buffers. Raises on a malformed block."""
    lib = _load()
    k = int(cnt.size)
    total = int(cnt.sum())
    out_ts = np.empty(total, np.int64)
    out_vals = np.empty(total, np.float64)
    out_cnt = np.empty(k, np.int64)
    r = lib.vm_assemble_part(
        _as_base_ptr(ts_buf), _as_base_ptr(val_buf),
        _as_i64_ptr(ts_off), _as_i64_ptr(ts_sz),
        ts_mt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _as_i64_ptr(ts_first),
        _as_i64_ptr(val_off), _as_i64_ptr(val_sz),
        val_mt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _as_i64_ptr(val_first),
        _as_i64_ptr(cnt), _as_i64_ptr(exps), k, int(lo), int(hi),
        _as_i64_ptr(out_ts),
        out_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _as_i64_ptr(out_cnt))
    if r < 0:
        raise ValueError(f"native assemble_part: malformed block {-r - 1}")
    return out_cnt, out_ts[:r], out_vals[:r]


def dedup_rows(ts2: np.ndarray, v2: np.ndarray, counts: np.ndarray,
               rows: np.ndarray, interval_ms: int, pad_ts: int) -> None:
    """In-place per-row dedup + exact-duplicate removal over the padded
    (S, N) layout for the listed rows (vm_dedup_rows; bit-exact with
    storage/dedup.deduplicate + the keep-last pass). ts2/v2 may be
    column-sliced views (row stride is passed through); counts is
    rewritten in place."""
    lib = _load()
    if ts2.strides[1] != 8 or v2.strides[1] != 8:
        raise ValueError("dedup_rows needs row-contiguous columns")
    rows = np.ascontiguousarray(rows, np.int64)
    lib.vm_dedup_rows(
        _as_i64_ptr(ts2), ts2.strides[0] // 8,
        v2.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        v2.strides[0] // 8, _as_i64_ptr(counts), _as_i64_ptr(rows),
        int(rows.size), int(interval_ms), int(pad_ts))


def decimal_to_float_blocks(m: np.ndarray, group_offsets: np.ndarray,
                            exps: np.ndarray, out: np.ndarray) -> None:
    """Batched mantissa->float64: group i = m[group_offsets[i]:
    group_offsets[i+1]] with decimal exponent exps[i], written into out
    (same layout). Replicates ops/decimal.decimal_to_float bit-exactly."""
    lib = _load()
    k = int(group_offsets.size) - 1
    lib.vm_decimal_to_float_blocks(
        _as_i64_ptr(m), _as_i64_ptr(group_offsets), _as_i64_ptr(exps), k,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))


def f2d_grouped(values: np.ndarray, starts: np.ndarray):
    """Grouped float64 -> (int64 mantissas, per-group exponents), the
    native twin of ops/decimal.float_to_decimal_grouped (flush hot path).
    Returns None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    v = np.ascontiguousarray(values, dtype=np.float64)
    st = np.ascontiguousarray(starts, dtype=np.int64)
    m_out = np.empty(v.size, np.int64)
    exps = np.empty(st.size, np.int64)
    lib.vm_f2d_grouped(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _as_i64_ptr(st), st.size, v.size, _as_i64_ptr(m_out),
        _as_i64_ptr(exps))
    return m_out, exps


def clip_blocks(ts: np.ndarray, bstart: np.ndarray, bend: np.ndarray,
                lo: int, hi: int):
    """Per-block [lo, hi]-inclusive kept row range over the concatenated
    (per-block sorted) timestamp column: block i spans rows
    [bstart[i], bend[i]). Returns (keep_lo, keep_hi) index arrays."""
    lib = _load()
    k = int(bstart.size)
    out_lo = np.empty(k, np.int64)
    out_hi = np.empty(k, np.int64)
    lib.vm_clip_blocks(_as_i64_ptr(ts), _as_i64_ptr(bstart),
                       _as_i64_ptr(bend), k, int(lo), int(hi),
                       _as_i64_ptr(out_lo), _as_i64_ptr(out_hi))
    return out_lo, out_hi


def gather_rows2(a: np.ndarray, b: np.ndarray, keep_lo: np.ndarray,
                 keep_hi: np.ndarray, total: int):
    """Densely gather kept row ranges of two parallel int64 columns (per-
    segment memcpy; `total` = sum of range lengths)."""
    lib = _load()
    out_a = np.empty(total, np.int64)
    out_b = np.empty(total, np.int64)
    lib.vm_gather_rows2(_as_i64_ptr(a), _as_i64_ptr(b),
                        _as_i64_ptr(keep_lo), _as_i64_ptr(keep_hi),
                        int(keep_lo.size), _as_i64_ptr(out_a),
                        _as_i64_ptr(out_b))
    return out_a, out_b


def scatter_pad(ts_all: np.ndarray, vals_f: np.ndarray, cnts: np.ndarray,
                rows: np.ndarray, S: int, N: int, pad_ts: int):
    """Scatter pre-grouped blocks into padded (S, N) tiles; returns
    (ts2, v2, counts). Appends block k's samples to row rows[k] in input
    order, pads row tails with (pad_ts, 0.0)."""
    lib = _load()
    ts2 = np.empty((S, N), np.int64)
    v2 = np.empty((S, N), np.float64)
    fill = np.zeros(S, np.int64)
    lib.vm_scatter_pad(
        _as_i64_ptr(ts_all),
        vals_f.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _as_i64_ptr(cnts), _as_i64_ptr(rows), int(cnts.size), int(S),
        int(N), int(pad_ts), _as_i64_ptr(ts2),
        v2.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _as_i64_ptr(fill))
    return ts2, v2, fill


def counter_resets_2d(v: np.ndarray) -> np.ndarray:
    """Row-batched counter-reset removal; v is (S, N) or (N,) float64."""
    lib = _load()
    a = np.ascontiguousarray(v, dtype=np.float64)
    shape = a.shape
    if a.ndim == 1:
        a = a.reshape(1, -1)
    out = np.empty_like(a)
    lib.vm_counter_resets_2d(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        a.shape[0], a.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out.reshape(shape)


ROLLUP_COUNTER_FUNCS = {"rate": 1, "increase": 2, "increase_pure": 7,
                        "delta": 3, "deriv_fast": 4, "irate": 5, "idelta": 6}


def rollup_counter_2d(func: str, ts2: np.ndarray, v2: np.ndarray,
                      counts: np.ndarray, start: int, end: int, step: int,
                      lookback: int, mpi: np.ndarray) -> np.ndarray:
    """Fused native window-walk for the counter/derivative rollup family;
    returns (S, T) float64. Semantics match rollup_batch_packed bit-exactly
    (shared differential tests)."""
    lib = _load()
    S, N = ts2.shape
    T = (end - start) // step + 1
    ts2 = np.ascontiguousarray(ts2, dtype=np.int64)
    v2 = np.ascontiguousarray(v2, dtype=np.float64)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    mpi = np.ascontiguousarray(mpi, dtype=np.int64)
    out = np.empty((S, T), np.float64)
    scratch = np.empty(max(N, 1), np.float64)
    lib.vm_rollup_counter_2d(
        _as_i64_ptr(ts2), v2.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _as_i64_ptr(counts), S, N, start, end, step, lookback,
        _as_i64_ptr(mpi), ROLLUP_COUNTER_FUNCS[func],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), scratch.ctypes.
        data_as(ctypes.POINTER(ctypes.c_double)))
    return out


def snappy_uncompress(data: bytes):
    """Native snappy block-format decompress; None when unavailable or
    malformed (callers fall back to the Python decoder)."""
    lib = _load()
    if lib is None:
        return None
    src = _as_u8_ptr(data)
    n = lib.vm_snappy_uncompressed_len(src, len(data))
    if n < 0 or n > 1 << 31:
        # unreasonable claimed length (attacker-controlled varint): refuse
        # to allocate; the Python decoder raises the proper 400 downstream
        return None
    out = ctypes.create_string_buffer(int(n) or 1)
    w = lib.vm_snappy_uncompress(src, len(data),
                                 ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
                                 n)
    if w != n:
        return None
    return out.raw[:n]


class ColumnarRows:
    """Columnar ingest rows: keybuf[key_off[i]:key_off[i]+key_len[i]] is the
    canonical text series key of row i; tss/values are int64/float64."""

    __slots__ = ("keybuf", "key_off", "key_len", "tss", "values")

    def __init__(self, keybuf, key_off, key_len, tss, values):
        self.keybuf = keybuf
        self.key_off = key_off
        self.key_len = key_len
        self.tss = tss
        self.values = values

    def __len__(self):
        return self.key_off.size

    def to_rows(self):
        """Materialize per-row (key_bytes, ts, value) tuples (slow; tests
        and non-columnar storages only)."""
        mv = memoryview(self.keybuf)
        return [(bytes(mv[o:o + l]), int(t), float(v))
                for o, l, t, v in zip(self.key_off, self.key_len,
                                      self.tss, self.values)]


def _parse_columnar(call, data: bytes, est_rows: int):
    """Shared retry driver for the columnar parsers: grows keybuf (-2) and
    row capacity (-3); -1 = native asked for the Python fallback."""
    lib = _load()
    if lib is None:
        return None
    keybuf_cap = 2 * len(data) + 4096
    max_rows = est_rows
    for _ in range(6):
        keybuf = ctypes.create_string_buffer(keybuf_cap)
        key_off = np.empty(max_rows, dtype=np.int64)
        key_len = np.empty(max_rows, dtype=np.int64)
        values = np.empty(max_rows, dtype=np.float64)
        tss = np.empty(max_rows, dtype=np.int64)
        n = call(lib, keybuf, keybuf_cap, _as_i64_ptr(key_off),
                 _as_i64_ptr(key_len),
                 values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                 _as_i64_ptr(tss), max_rows)
        if n == -2:
            keybuf_cap *= 4
            continue
        if n == -3:
            max_rows *= 4
            continue
        if n < 0:
            return None
        return ColumnarRows(keybuf.raw[:_keybuf_used(key_off, key_len, n)],
                            key_off[:n], key_len[:n], tss[:n], values[:n])
    return None


def _keybuf_used(key_off, key_len, n):
    if n == 0:
        return 0
    return int(key_off[n - 1] + key_len[n - 1])


def parse_rw_columnar(data: bytes, default_ts: int):
    """Native remote-write WriteRequest parse (uncompressed protobuf) ->
    ColumnarRows; None = fall back to the Python parser."""
    return _parse_columnar(
        lambda lib, kb, kc, ko, kl, vs, ts, mr: lib.vm_parse_rw(
            _as_u8_ptr(data), len(data), default_ts, ctypes.cast(
                kb, ctypes.POINTER(ctypes.c_uint8)), kc, ko, kl, vs, ts, mr),
        data, max(data.count(b"\x12") + 16, 64))


def parse_influx_columnar(data: bytes, db: str, default_ts: int):
    """Native influx line-protocol parse -> ColumnarRows; None = fallback."""
    dbb = db.encode() if db else b""
    return _parse_columnar(
        lambda lib, kb, kc, ko, kl, vs, ts, mr: lib.vm_parse_influx(
            _as_u8_ptr(data), len(data), _as_u8_ptr(dbb), len(dbb),
            default_ts, ctypes.cast(kb, ctypes.POINTER(ctypes.c_uint8)),
            kc, ko, kl, vs, ts, mr),
        data, max(2 * data.count(b"\n") + 16, 64))


def parse_prom_columnar(data: bytes, default_ts: int):
    """Native prometheus text parse -> ColumnarRows (keys reference the
    request body itself); None = fallback."""
    lib = _load()
    if lib is None:
        return None
    n_max = data.count(b"\n") + 2
    key_off = np.empty(n_max, dtype=np.int32)
    key_len = np.empty(n_max, dtype=np.int32)
    values = np.empty(n_max, dtype=np.float64)
    tss = np.empty(n_max, dtype=np.int64)
    n = lib.vm_parse_prom(
        data, len(data),
        key_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        key_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _as_i64_ptr(tss), n_max)
    tss = tss[:n]
    # explicit 0 is "no timestamp" too (parity with parse_prom_raw)
    tss[(tss == _TS_ABSENT) | (tss == 0)] = default_ts
    return ColumnarRows(data, key_off[:n].astype(np.int64),
                        key_len[:n].astype(np.int64), tss, values[:n])


class KeyMap:
    """Native byte-string -> dense-id map (vm_keymap). Ids are assigned
    consecutively in first-occurrence order, so id arrays can index numpy
    side tables (TSID fields, per-day state) directly."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.vm_keymap_new()
        if not self._h:
            raise MemoryError("vm_keymap_new failed")

    def __len__(self):
        return int(self._lib.vm_keymap_size(self._h))

    def resolve(self, base, key_off: np.ndarray,
                key_len: np.ndarray) -> tuple[np.ndarray, int]:
        """Returns (ids int64[n], n_new). New keys get ids
        len-before..len-before+n_new-1 in first-occurrence order."""
        n = int(key_off.size)
        ids = np.empty(n, dtype=np.int64)
        if isinstance(base, np.ndarray):
            bp = base.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        else:
            bp = _as_u8_ptr(base)
        added = self._lib.vm_keymap_resolve(
            self._h, bp, _as_i64_ptr(np.ascontiguousarray(key_off, np.int64)),
            _as_i64_ptr(np.ascontiguousarray(key_len, np.int64)), n,
            _as_i64_ptr(ids))
        if added < 0:
            raise MemoryError("vm_keymap_resolve failed")
        return ids, int(added)

    def close(self):
        if self._h:
            self._lib.vm_keymap_free(self._h)
            self._h = 0

    def __del__(self):
        try:
            self.close()
        except (AttributeError, TypeError, OSError):
            # interpreter teardown: the ctypes lib handle may already
            # be gone; __del__ must never raise
            pass


def marshal_i64_many(vals: np.ndarray, offsets: np.ndarray):
    """Batched block marshal: type choice + encode for K blocks in one
    native call. vals = int64 concatenation, offsets = K+1 boundaries.
    Returns (payload bytes, types int32[K], firsts int64[K], lens int64[K])
    or None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    k = offsets.size - 1
    cap = int(vals.size + k) * 10 + 16
    out = ctypes.create_string_buffer(cap)
    types = np.empty(k, dtype=np.int32)
    firsts = np.empty(k, dtype=np.int64)
    lens = np.empty(k, dtype=np.int64)
    n = lib.vm_marshal_i64_many(
        _as_i64_ptr(vals), _as_i64_ptr(offsets), k,
        ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), cap,
        types.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _as_i64_ptr(firsts), _as_i64_ptr(lens))
    if n < 0:
        raise ValueError("native batched marshal failed")
    return out.raw[:n], types, firsts, lens
