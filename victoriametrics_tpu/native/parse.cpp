// Native Prometheus exposition-format line parser.
//
// The reference parses ingest protocols in Go with hand-rolled scanners
// (lib/protoparser/prometheus/parser.go) that run at hundreds of MB/s; the
// Python line parser tops out near 100k rows/s and dominates HTTP ingest
// cost. This scanner extracts, per sample line, the SERIES KEY byte range
// (the `name{labels}` prefix, quote-aware), the float value and the
// optional millisecond timestamp. Label decomposition is deferred to the
// slow path: the storage layer keys its TSID cache on the raw series bytes,
// so a cache hit never materializes labels at all (the
// MarshaledMetricNameRaw fast path of storage.go:1874, taken to its
// logical end).
//
// Build: part of libvmcodec.so (see Makefile).

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parses prometheus text exposition lines from data[0..len).
// For each accepted sample row i:
//   key_off[i], key_len[i]  — byte range of the series key within data
//   values[i]               — sample value (strtod semantics: inf/nan ok)
//   tss[i]                  — timestamp in ms, or INT64_MIN when absent
// Returns the number of rows written (<= max_rows); stops early when
// max_rows is reached (caller re-invokes with a bigger buffer).
int64_t vm_parse_prom(const char* data, int64_t len,
                      int32_t* key_off, int32_t* key_len,
                      double* values, int64_t* tss, int64_t max_rows) {
    int64_t n = 0;
    int64_t i = 0;
    while (i < len && n < max_rows) {
        // line bounds
        int64_t eol = i;
        while (eol < len && data[eol] != '\n') eol++;
        int64_t a = i, b = eol;
        i = eol + 1;
        // trim
        while (a < b && (data[a] == ' ' || data[a] == '\t' ||
                         data[a] == '\r')) a++;
        while (b > a && (data[b - 1] == ' ' || data[b - 1] == '\t' ||
                         data[b - 1] == '\r')) b--;
        if (a >= b || data[a] == '#') continue;
        // series key: up to the quote-aware closing '}' when a '{' appears
        // before any whitespace, else up to the first whitespace
        int64_t k = a;
        int64_t key_end = -1;
        while (k < b && data[k] != ' ' && data[k] != '\t' &&
               data[k] != '{') k++;
        if (k < b && data[k] == '{') {
            bool in_q = false;
            int64_t j = k + 1;
            for (; j < b; j++) {
                char c = data[j];
                if (in_q) {
                    if (c == '\\') { j++; continue; }
                    if (c == '"') in_q = false;
                } else if (c == '"') {
                    in_q = true;
                } else if (c == '}') {
                    break;
                }
            }
            if (j >= b) continue;  // unterminated label set
            key_end = j + 1;
        } else {
            key_end = k;
        }
        if (key_end <= a) continue;
        // value
        int64_t v = key_end;
        while (v < b && (data[v] == ' ' || data[v] == '\t')) v++;
        if (v >= b) continue;  // no value field
        char buf[64];
        int64_t vend = v;
        while (vend < b && data[vend] != ' ' && data[vend] != '\t') vend++;
        int64_t vlen = vend - v;
        if (vlen <= 0 || vlen >= (int64_t)sizeof(buf)) continue;
        memcpy(buf, data + v, vlen);
        buf[vlen] = 0;
        char* endp = nullptr;
        double val = strtod(buf, &endp);
        if (endp == buf || *endp != 0) continue;  // not a number
        // optional timestamp (ms; may be float like 1.7e12)
        int64_t ts = INT64_MIN;
        int64_t t = vend;
        while (t < b && (data[t] == ' ' || data[t] == '\t')) t++;
        if (t < b) {
            int64_t tend = t;
            while (tend < b && data[tend] != ' ' && data[tend] != '\t')
                tend++;
            int64_t tlen = tend - t;
            if (tlen > 0 && tlen < (int64_t)sizeof(buf)) {
                memcpy(buf, data + t, tlen);
                buf[tlen] = 0;
                char* tp = nullptr;
                double tsd = strtod(buf, &tp);
                if (tp != buf && *tp == 0) ts = (int64_t)tsd;
            }
        }
        key_off[n] = (int32_t)a;
        key_len[n] = (int32_t)(key_end - a);
        values[n] = val;
        tss[n] = ts;
        n++;
    }
    return n;
}

}  // extern "C"
