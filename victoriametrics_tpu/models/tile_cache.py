"""HBM-resident tile cache for the query engine.

The reference keeps decompressed index blocks in a RAM blockcache sized at
10% of memory (lib/blockcache, lib/storage/part.go:15-22) and relies on the
page cache for data blocks; repeated queries run hot. The TPU analog: packed
(series, sample) tiles live in HBM between queries, keyed by (part id, tile
id, revision). Evictions are LRU by bytes.

Uploads are chunked: the axon tunnel (and PCIe generally) sustains much
higher bandwidth on medium transfers than on one huge contiguous put
(measured on this host: ~1.4 GB/s at 8MB vs ~0.2 GB/s at 64MB), so
device_put goes up in <=8MB slices re-assembled on device.
"""

from __future__ import annotations

import collections
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..devtools.locktrace import make_lock
from ..devtools.racetrace import traced_fields
from ..utils import metrics as metricslib

UPLOAD_CHUNK_BYTES = 8 << 20

# cache self-metrics (reference vm_cache_{requests,misses}_total +
# vm_cache_{size_bytes,entries}{type=...}); gauges sum over every live
# TileCache so embedded/test setups with several engines stay correct
_instances: "weakref.WeakSet[TileCache]" = weakref.WeakSet()
_CACHE_REQUESTS = metricslib.REGISTRY.counter(
    'vm_cache_requests_total{type="tpu/tile_cache"}')
_CACHE_MISSES = metricslib.REGISTRY.counter(
    'vm_cache_misses_total{type="tpu/tile_cache"}')
metricslib.REGISTRY.gauge(
    'vm_cache_size_bytes{type="tpu/tile_cache"}',
    callback=lambda: sum(c.size_bytes for c in list(_instances)))
metricslib.REGISTRY.gauge(
    'vm_cache_entries{type="tpu/tile_cache"}',
    callback=lambda: sum(c.entry_count() for c in list(_instances)))


def chunked_device_put(x: np.ndarray, device=None) -> jax.Array:
    """device_put in <=8MB row-slices, concatenated on device."""
    device = device or jax.devices()[0]
    nbytes = x.nbytes
    if nbytes <= UPLOAD_CHUNK_BYTES or x.ndim == 0 or x.shape[0] <= 1:
        return jax.device_put(x, device)
    rows_per_chunk = max(1, UPLOAD_CHUNK_BYTES // max(x.nbytes // x.shape[0], 1))
    parts = [jax.device_put(x[i:i + rows_per_chunk], device)
             for i in range(0, x.shape[0], rows_per_chunk)]
    return jnp.concatenate(parts, axis=0)


@traced_fields("_entries", "_sizes", "_bytes")
class TileCache:
    """LRU byte-bounded cache of device-resident pytrees."""

    def __init__(self, capacity_bytes: int, device=None):
        self.capacity = capacity_bytes
        self.device = device or jax.devices()[0]
        # through the locktrace seam: the racetrace sanitizer needs the
        # release->acquire clock edge to see these accesses as ordered
        self._lock = make_lock("models.TileCache._lock")
        self._entries: collections.OrderedDict[object, tuple] = \
            collections.OrderedDict()
        self._sizes: dict[object, int] = {}
        self._bytes = 0
        # per-instance thread-safe counters (the global vm_cache_* metrics
        # above aggregate over instances; these feed per-cache stats)
        self._hits = metricslib.Counter("hits")
        self._misses = metricslib.Counter("misses")
        _instances.add(self)

    @property
    def hits(self) -> int:
        return self._hits.get()

    @property
    def misses(self) -> int:
        return self._misses.get()

    def _tree_bytes(self, tree) -> int:
        total = 0
        for a in jax.tree_util.tree_leaves(tree):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                total += int(np.prod(a.shape)) * a.dtype.itemsize
            elif hasattr(a, "offsets"):  # V0Info host companion
                total += a.offsets.nbytes
        return total

    def get(self, key):
        _CACHE_REQUESTS.inc()
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self._hits.inc()
                return self._entries[key]
        self._misses.inc()
        _CACHE_MISSES.inc()
        return None

    def put(self, key, host_tree):
        """Upload a pytree of numpy arrays; returns the device tree. A tree
        larger than the whole cache budget is uploaded and returned but NOT
        retained (it would evict everything and still overcommit HBM)."""
        dev_tree = jax.tree_util.tree_map(
            lambda a: chunked_device_put(np.asarray(a), self.device), host_tree)
        size = self._tree_bytes(dev_tree)
        if size > self.capacity:
            # too big to retain — but a stale entry under this key must not
            # keep serving old data
            self.invalidate(key)
            return dev_tree
        with self._lock:
            if key in self._entries:
                self._bytes -= self._sizes.pop(key)
                del self._entries[key]
            while self._bytes + size > self.capacity and self._entries:
                old, _ = self._entries.popitem(last=False)
                self._bytes -= self._sizes.pop(old)
            self._entries[key] = dev_tree
            self._sizes[key] = size
            self._bytes += size
        return dev_tree

    def put_device(self, key, dev_tree):
        """Retain an already-device-resident pytree (e.g. tiles decoded on
        device from compact planes)."""
        size = self._tree_bytes(dev_tree)
        if size > self.capacity:
            self.invalidate(key)
            return dev_tree
        with self._lock:
            if key in self._entries:
                self._bytes -= self._sizes.pop(key)
                del self._entries[key]
            while self._bytes + size > self.capacity and self._entries:
                old, _ = self._entries.popitem(last=False)
                self._bytes -= self._sizes.pop(old)
            self._entries[key] = dev_tree
            self._sizes[key] = size
            self._bytes += size
        return dev_tree

    def get_or_put(self, key, make_host_tree):
        cached = self.get(key)
        if cached is not None:
            return cached
        return self.put(key, make_host_tree())

    def invalidate(self, key=None):
        with self._lock:
            if key is None:
                self._entries.clear()
                self._sizes.clear()
                self._bytes = 0
            elif key in self._entries:
                self._bytes -= self._sizes.pop(key)
                del self._entries[key]

    def entry_count(self) -> int:
        # locked: a /metrics scrape must not read len() mid-evict
        with self._lock:
            return len(self._entries)

    @property
    def size_bytes(self) -> int:
        # locked: a /metrics scrape must not read mid-evict
        with self._lock:
            return self._bytes
