"""HBM-resident tile cache for the query engine.

The reference keeps decompressed index blocks in a RAM blockcache sized at
10% of memory (lib/blockcache, lib/storage/part.go:15-22) and relies on the
page cache for data blocks; repeated queries run hot. The TPU analog: packed
(series, sample) tiles live in HBM between queries, keyed by (part id, tile
id, revision). Evictions are LRU by bytes.

Uploads are chunked: the axon tunnel (and PCIe generally) sustains much
higher bandwidth on medium transfers than on one huge contiguous put
(measured on this host: ~1.4 GB/s at 8MB vs ~0.2 GB/s at 64MB), so
device_put goes up in <=8MB slices re-assembled on device.
"""

from __future__ import annotations

import collections
import os
import time as _time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..devtools.locktrace import make_lock
from ..devtools.racetrace import traced_fields
from ..utils import costacc as _costacc
from ..utils import flightrec as _flightrec
from ..utils import metrics as metricslib

UPLOAD_CHUNK_BYTES = 8 << 20

# device-plane link accounting: EVERY host->device and device->host byte
# of the query engine funnels through count_upload/count_download (the
# residency guard test asserts a rolling refresh uploads only tail
# columns, and a bench leg divides link traffic by refresh)
_BYTES_UPLOADED = metricslib.REGISTRY.counter(
    "vm_device_bytes_uploaded_total")
_BYTES_DOWNLOADED = metricslib.REGISTRY.counter(
    "vm_device_bytes_downloaded_total")


def count_upload(nbytes: int) -> None:
    _BYTES_UPLOADED.inc(int(nbytes))
    _costacc.add_device(up=int(nbytes))


def count_download(nbytes: int) -> None:
    _BYTES_DOWNLOADED.inc(int(nbytes))
    _costacc.add_device(down=int(nbytes))


def bytes_uploaded() -> int:
    return _BYTES_UPLOADED.get()


def bytes_downloaded() -> int:
    return _BYTES_DOWNLOADED.get()


def timed_transfer(span: str, nbytes: int, fn):
    """Run one H2D/D2H transfer `fn`, counting its bytes and recording a
    flight span for transfers big enough to matter — the ONE place the
    device:upload/device:download span shape is defined (shard_put,
    chunked_device_put and the kernel-result pull all funnel here)."""
    (count_upload if span == "device:upload" else count_download)(nbytes)
    if nbytes < (1 << 20):
        return fn()
    t0 = _time.perf_counter()
    try:
        return fn()
    finally:
        dt = _time.perf_counter() - t0
        _flightrec.rec(span, t0, dt, arg=nbytes)
        # cost plane: transfer wall is link time, not this thread's CPU
        tr = _costacc.current()
        if tr is not None:
            tr.lap(span, dt, 0.0)


# cache self-metrics (reference vm_cache_{requests,misses}_total +
# vm_cache_{size_bytes,entries}{type=...}); gauges sum over every live
# TileCache so embedded/test setups with several engines stay correct
_instances: "weakref.WeakSet[TileCache]" = weakref.WeakSet()
_CACHE_REQUESTS = metricslib.REGISTRY.counter(
    'vm_cache_requests_total{type="tpu/tile_cache"}')
_CACHE_MISSES = metricslib.REGISTRY.counter(
    'vm_cache_misses_total{type="tpu/tile_cache"}')
metricslib.REGISTRY.gauge(
    'vm_cache_size_bytes{type="tpu/tile_cache"}',
    callback=lambda: sum(c.size_bytes for c in list(_instances)))
metricslib.REGISTRY.gauge(
    'vm_cache_entries{type="tpu/tile_cache"}',
    callback=lambda: sum(c.entry_count() for c in list(_instances)))


def chunked_device_put(x: np.ndarray, device=None) -> jax.Array:
    """device_put in <=8MB row-slices, concatenated on device."""
    device = device or jax.devices()[0]
    return timed_transfer("device:upload", x.nbytes,
                          lambda: _chunked_device_put(x, device))


def _chunked_device_put(x: np.ndarray, device) -> jax.Array:
    nbytes = x.nbytes
    if nbytes <= UPLOAD_CHUNK_BYTES or x.ndim == 0 or x.shape[0] <= 1:
        return jax.device_put(x, device)
    rows_per_chunk = max(1, UPLOAD_CHUNK_BYTES // max(x.nbytes // x.shape[0], 1))
    parts = [jax.device_put(x[i:i + rows_per_chunk], device)
             for i in range(0, x.shape[0], rows_per_chunk)]
    return jnp.concatenate(parts, axis=0)


# device-resident window cache health: hits = refreshes served from an
# HBM-resident window (rolling advance or warm exact-key reuse) without
# re-uploading the window; evictions = resident windows dropped by the
# LRU bound; compactions = on-device window slides (samples older than
# the fetch bound dropped + tile origin rebased, instead of a full
# re-upload when headroom/int32 run out)
_WINDOW_HITS = metricslib.REGISTRY.counter(
    "vm_device_window_cache_hits_total")
_WINDOW_EVICTIONS = metricslib.REGISTRY.counter(
    "vm_device_window_cache_evictions_total")
_WINDOW_COMPACTIONS = metricslib.REGISTRY.counter(
    "vm_device_window_compactions_total")


def device_resident_enabled() -> bool:
    """Device data residency on?  VM_DEVICE_RESIDENT=0 disables every
    resident-window reuse path (rolling advance, warm exact-key tile
    reuse) so each query re-uploads its full window — the loud full-upload
    escape hatch AND the equality oracle the residency tests diff
    against."""
    return os.environ.get("VM_DEVICE_RESIDENT", "1") != "0"


def count_window_hit() -> None:
    _WINDOW_HITS.inc()


def count_window_compaction() -> None:
    _WINDOW_COMPACTIONS.inc()


class DeviceWindowCache:
    """Host-side registry of device-RESIDENT rolling windows (the
    DeviceWindowCache of ISSUE 12): each entry pins the device buffers of
    one query shape's packed (S, T) window (RollingTile) plus its group
    assignment and the host-side ring copy of the [G, T] aggregate, so a
    rolling refresh uploads only the suffix tail columns and the rollup
    never re-crosses the host boundary until the final [G, T] pull.

    Entry-count LRU (VM_DEVICE_WINDOWS, default 256): each window's HBM
    cost is bounded by the tile shapes, and the entries that matter (live
    dashboards) are re-touched every refresh.  Evictions tick
    vm_device_window_cache_evictions_total — a steadily climbing eviction
    counter on a stable dashboard fleet means the cap is too small."""

    def __init__(self, cap: int | None = None):
        if cap is None:
            try:
                cap = int(os.environ.get("VM_DEVICE_WINDOWS", "256"))
            except ValueError:
                cap = 256
        self.cap = max(cap, 1)
        self._lock = make_lock("models.DeviceWindowCache._lock")
        self._entries: collections.OrderedDict = collections.OrderedDict()

    def get(self, key):
        with self._lock:
            v = self._entries.get(key)
            if v is not None:
                self._entries.move_to_end(key)
            return v

    def peek(self, key):
        """get() without the LRU touch (readiness probes must not keep an
        otherwise-dead entry alive)."""
        with self._lock:
            return self._entries.get(key)

    def put(self, key, value) -> None:
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.cap:
                self._entries.popitem(last=False)
                _WINDOW_EVICTIONS.inc()

    def invalidate(self, key=None) -> None:
        with self._lock:
            if key is None:
                self._entries.clear()
            else:
                self._entries.pop(key, None)

    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)


@traced_fields("_entries", "_sizes", "_bytes")
class TileCache:
    """LRU byte-bounded cache of device-resident pytrees."""

    def __init__(self, capacity_bytes: int, device=None):
        self.capacity = capacity_bytes
        self.device = device or jax.devices()[0]
        # through the locktrace seam: the racetrace sanitizer needs the
        # release->acquire clock edge to see these accesses as ordered
        self._lock = make_lock("models.TileCache._lock")
        self._entries: collections.OrderedDict[object, tuple] = \
            collections.OrderedDict()
        self._sizes: dict[object, int] = {}
        self._bytes = 0
        # per-instance thread-safe counters (the global vm_cache_* metrics
        # above aggregate over instances; these feed per-cache stats)
        self._hits = metricslib.Counter("hits")
        self._misses = metricslib.Counter("misses")
        _instances.add(self)

    @property
    def hits(self) -> int:
        return self._hits.get()

    @property
    def misses(self) -> int:
        return self._misses.get()

    def _tree_bytes(self, tree) -> int:
        total = 0
        for a in jax.tree_util.tree_leaves(tree):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                total += int(np.prod(a.shape)) * a.dtype.itemsize
            elif hasattr(a, "offsets"):  # V0Info host companion
                total += a.offsets.nbytes
        return total

    def get(self, key):
        _CACHE_REQUESTS.inc()
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self._hits.inc()
                return self._entries[key]
        self._misses.inc()
        _CACHE_MISSES.inc()
        return None

    def put(self, key, host_tree):
        """Upload a pytree of numpy arrays; returns the device tree. A tree
        larger than the whole cache budget is uploaded and returned but NOT
        retained (it would evict everything and still overcommit HBM)."""
        dev_tree = jax.tree_util.tree_map(
            lambda a: chunked_device_put(np.asarray(a), self.device), host_tree)
        size = self._tree_bytes(dev_tree)
        if size > self.capacity:
            # too big to retain — but a stale entry under this key must not
            # keep serving old data
            self.invalidate(key)
            return dev_tree
        with self._lock:
            if key in self._entries:
                self._bytes -= self._sizes.pop(key)
                del self._entries[key]
            while self._bytes + size > self.capacity and self._entries:
                old, _ = self._entries.popitem(last=False)
                self._bytes -= self._sizes.pop(old)
            self._entries[key] = dev_tree
            self._sizes[key] = size
            self._bytes += size
        return dev_tree

    def put_device(self, key, dev_tree):
        """Retain an already-device-resident pytree (e.g. tiles decoded on
        device from compact planes)."""
        size = self._tree_bytes(dev_tree)
        if size > self.capacity:
            self.invalidate(key)
            return dev_tree
        with self._lock:
            if key in self._entries:
                self._bytes -= self._sizes.pop(key)
                del self._entries[key]
            while self._bytes + size > self.capacity and self._entries:
                old, _ = self._entries.popitem(last=False)
                self._bytes -= self._sizes.pop(old)
            self._entries[key] = dev_tree
            self._sizes[key] = size
            self._bytes += size
        return dev_tree

    def get_or_put(self, key, make_host_tree):
        cached = self.get(key)
        if cached is not None:
            return cached
        return self.put(key, make_host_tree())

    def invalidate(self, key=None):
        with self._lock:
            if key is None:
                self._entries.clear()
                self._sizes.clear()
                self._bytes = 0
            elif key in self._entries:
                self._bytes -= self._sizes.pop(key)
                del self._entries[key]

    def entry_count(self) -> int:
        # locked: a /metrics scrape must not read len() mid-evict
        with self._lock:
            return len(self._entries)

    @property
    def size_bytes(self) -> int:
        # locked: a /metrics scrape must not read mid-evict
        with self._lock:
            return self._bytes
