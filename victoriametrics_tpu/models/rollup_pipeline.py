"""The flagship device pipeline: aggr(rollup(selector[window])) as one
jittable program — the TPU replacement for the reference's query hot path
(netstorage unpack workers + rollupConfig.Do + incremental aggregation,
app/vmselect/promql/eval.go:1690-1900).

`QueryPipeline` binds the static query shape (window grid, rollup func,
aggregate, group count) and exposes:

- forward(ts, values, counts, group_ids) -> [G, T]   single-device
- sharded(mesh)(...) -> [G, T]                       series-sharded + psum

This module is what `__graft_entry__.entry()` and `bench.py` drive.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.device_rollup import pack_series, rollup_aggregate_tile
from ..ops.rollup_np import RollupConfig
from ..parallel import mesh as meshlib


@dataclasses.dataclass(frozen=True)
class QueryPipeline:
    cfg: RollupConfig
    rollup_func: str = "rate"
    aggr: str = "sum"
    num_groups: int = 256

    def forward(self, ts, values, counts, group_ids):
        return rollup_aggregate_tile(
            self.rollup_func, self.aggr, ts, values, counts, group_ids,
            self.cfg, self.num_groups)

    def jitted(self):
        """A forward function closing over the static config, directly
        jittable over its array args."""
        cfg, rf, ag, ng = self.cfg, self.rollup_func, self.aggr, self.num_groups

        def fn(ts, values, counts, group_ids):
            return rollup_aggregate_tile(rf, ag, ts, values, counts,
                                         group_ids, cfg, ng)
        return fn

    def sharded(self, mesh):
        fn = meshlib.sharded_rollup_aggregate(
            mesh, self.rollup_func, self.aggr, self.cfg, self.num_groups)

        from ..ops.device_rollup import MIN_TS_NONE

        def run(ts, values, counts, group_ids):
            return fn(ts, values, counts, group_ids, np.int32(0),
                      MIN_TS_NONE, jnp.zeros(ts.shape[0], values.dtype))
        return run


def synth_workload(n_series: int, n_samples: int, cfg: RollupConfig,
                   num_groups: int, dtype=np.float32, seed: int = 0):
    """Synthetic TSBS-devops-like tile: counter series at 15s-ish intervals,
    grouped n_series/num_groups-to-1 (the `by (instance)` shape)."""
    rng = np.random.default_rng(seed)
    interval = max((cfg.end - cfg.start) // max(n_samples - 1, 1), 1)
    base = np.arange(n_samples, dtype=np.int64) * interval + cfg.start
    series = []
    for _ in range(n_series):
        ts = base + rng.integers(-interval // 4, interval // 4 + 1, n_samples)
        ts.sort()
        v = np.cumsum(rng.integers(0, 50, n_samples)).astype(np.float64)
        series.append((ts, v))
    ts_t, v_t, counts = pack_series(series, cfg.start, dtype=dtype)
    gids = (np.arange(n_series) % num_groups).astype(np.int32)
    return ts_t, v_t, counts, gids
