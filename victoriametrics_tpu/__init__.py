"""victoriametrics_tpu — a TPU-native time-series monitoring framework.

A brand-new implementation of the capabilities of VictoriaMetrics
(reference: /root/reference), redesigned host/device:

- Host plane (Python + C-extensions): storage files, LSM index, wire
  protocols, HTTP APIs, cluster RPC.
- Device plane (JAX/XLA/Pallas on TPU): block decode, windowed rollups
  (``rate`` / ``*_over_time``), and segment-reduced aggregations
  (``sum/avg/topk by(...)``) over (series, step) tiles, sharded across a
  ``jax.sharding.Mesh``.

Layer map mirrors SURVEY.md:
  utils/    — L0 runtime utils (logging, time, memory)
  ops/      — L1 codecs (decimal, varint, nearest-delta) + device kernels
  storage/  — L2-L4 file formats, LSM partitions, inverted index
  parallel/ — L5 cluster RPC + mesh sharding
  ingest/   — L6 protocol parsers, relabeling, stream aggregation
  query/    — L7 MetricsQL parser + evaluator
  httpapi/  — L8 HTTP surface
  models/   — flagship jittable device pipelines (query "models")
  apps/     — L9 processes (vmsingle, vmstorage, vminsert, vmselect, ...)
"""

__version__ = "0.1.0"
