"""Monthly partition LSM (reference lib/storage/partition.go:75).

Write path per partition (partition.go:461-877 analog, single-writer):
  pending raw rows -> (flush, 2s or size cap) in-memory parts
  in-memory parts  -> (flush, 5s durability) small file parts
  small parts      -> merged into bigger parts (k-way by (tsid, min_ts)),
                      dropping deleted series and out-of-retention rows

parts.json lists live file parts; it is rewritten atomically after every
structural change so a crash leaves either the old or the new part set
(partition.go:282-295 analog). Unlisted dirs are removed at open.
"""

from __future__ import annotations

import heapq
import itertools
import json
import os
import shutil
import time

import numpy as np

from ..devtools import faultinject
from ..devtools.locktrace import make_rlock
from ..devtools.racetrace import traced_fields
from ..utils import flightrec, logger
from ..utils import fs as fslib
from ..utils import metrics as metricslib
from ..utils import workpool
from . import downsample as dslib
from .block import MAX_ROWS_PER_BLOCK, Block, rows_to_blocks
from .dedup import deduplicate
from .part import Part, PartWriter

# engine self-metrics (reference vm_active_merges / vm_merges_total per
# part type): flush = pending+mem parts -> one small file part; merge =
# small file parts -> one bigger part
_FLUSH_DURATION = metricslib.REGISTRY.histogram(
    'vm_storage_flush_duration_seconds{type="storage/small"}')
_MERGE_DURATION = metricslib.REGISTRY.histogram(
    'vm_storage_merge_duration_seconds{type="storage/file"}')
_MERGES_TOTAL = metricslib.REGISTRY.counter(
    'vm_merges_total{type="storage/file"}')
_ACTIVE_MERGES = metricslib.REGISTRY.gauge(
    'vm_active_merges{type="storage/file"}')
_ING_FLUSH = metricslib.ingest_phase("flush")
_ING_MERGE = metricslib.ingest_phase("merge")
_SPILL_ERRORS = metricslib.REGISTRY.counter("vm_ingest_spill_errors_total")
# torn/corrupt parts moved aside at open instead of being served or
# silently dropped (one series per store kind; mergeset ticks its own)
_PARTS_QUARANTINED = metricslib.REGISTRY.counter(
    'vm_parts_quarantined_total{store="storage"}')
# listed parts that failed to open but were KEPT IN PLACE (transient
# OSError / failed quarantine move): loud and partial, but NOT moved —
# the quarantined counter must mean what its name says
_PARTS_OPEN_ERRORS = metricslib.REGISTRY.counter(
    'vm_parts_open_errors_total{store="storage"}')

QUARANTINE_DIR = fslib.QUARANTINE_DIR
quarantine_dir_entry = fslib.quarantine_dir_entry

MAX_PENDING_ROWS = 256 << 10
MAX_SMALL_PARTS = 15
# async pending->InmemoryPart conversions in flight per partition before
# the ingest thread blocks on the oldest: 2 keeps the produce/convert
# pipeline full while bounding both resident raw rows (~3x cap) and how
# long a reader's visibility barrier can wait behind conversions
_MAX_INFLIGHT_PARTS = 2
# merged blocks span at most this much time, so tail fetches prune at the
# block-header level instead of decoding a series' whole history (0 = off).
# The rows floor keeps sparse series (e.g. 1/min scrapes) from exploding
# into tiny blocks: a span split never produces blocks under 256 rows, so
# header/index overhead stays <~0.4B per sample.
MAX_BLOCK_SPAN_MS = int(os.environ.get("VM_BLOCK_SPAN_MS", 3600 * 1000))
MIN_SPAN_SPLIT_ROWS = 256
# blocks buffered per bulk-marshal call on the flush/merge write path
# (bounds the transient concat memory: ~8k blocks x 8k rows x 16B = cap)
_BULK_WRITE_BLOCKS = 4096


class InmemoryPart:
    """Sorted blocks held in RAM (inmemoryPart analog)."""

    def __init__(self, blocks: list[Block]):
        self._blocks = blocks
        self._segs = None
        self._lazy = None
        self.rows = sum(b.rows for b in blocks)
        self.min_ts = min((int(b.timestamps[0]) for b in blocks),
                          default=1 << 62)
        self.max_ts = max((int(b.timestamps[-1]) for b in blocks),
                          default=-(1 << 62))
        self._cols = None

    @classmethod
    def from_columns(cls, segs, all_ts, mants, exps, precision_bits=64):
        """Columnar-first construction (the query-time pending view):
        Block objects are only materialized if a legacy per-block consumer
        iterates them; the batched fetch path reads the arrays directly."""
        self = cls.__new__(cls)
        self._blocks = None
        self._lazy = None
        self._segs = (segs, all_ts, mants, exps, precision_bits)
        self.rows = int(all_ts.size)
        self.min_ts = int(all_ts.min()) if all_ts.size else 1 << 62
        self.max_ts = int(all_ts.max()) if all_ts.size else -(1 << 62)
        K = len(segs)
        mids = np.fromiter((t.metric_id for t, _, _ in segs), np.uint64,
                           K).astype(np.int64)
        starts = np.fromiter((a for _, a, _ in segs), np.int64, K)
        ends = np.fromiter((b for _, _, b in segs), np.int64, K)
        cnts = ends - starts
        bmin = all_ts[starts] if K else np.zeros(0, np.int64)
        bmax = all_ts[ends - 1] if K else np.zeros(0, np.int64)
        self._cols = (mids, cnts, np.asarray(exps, np.int64), bmin, bmax,
                      starts, all_ts, mants)
        return self

    @classmethod
    def from_seg_arrays(cls, starts, ends, mids_sorted, tsid_at, all_ts,
                        mants, exps, precision_bits=64):
        """Fully array-backed construction: per-block TSID objects resolve
        LAZILY (tsid_at(row_index) -> TSID) only if a legacy per-block
        consumer iterates — the columnar fetch path never pays the
        per-series Python object loop."""
        self = cls.__new__(cls)
        self._blocks = None
        self._segs = None
        self._lazy = (starts, ends, tsid_at, precision_bits)
        self.rows = int(all_ts.size)
        self.min_ts = int(all_ts.min()) if all_ts.size else 1 << 62
        self.max_ts = int(all_ts.max()) if all_ts.size else -(1 << 62)
        cnts = ends - starts
        bmin = all_ts[starts] if starts.size else np.zeros(0, np.int64)
        bmax = all_ts[ends - 1] if starts.size else np.zeros(0, np.int64)
        self._cols = (mids_sorted[starts].astype(np.int64), cnts,
                      np.asarray(exps, np.int64), bmin, bmax, starts,
                      all_ts, mants)
        return self

    @property
    def block_list(self):
        if self._blocks is None:
            if self._segs is not None:
                segs, all_ts, mants, exps, prec = self._segs
                self._blocks = [
                    Block(tsid, all_ts[a:b], mants[a:b], int(exps[k]), prec)
                    for k, (tsid, a, b) in enumerate(segs)]
            else:
                starts, ends, tsid_at, prec = self._lazy
                _, _, exps, _, _, _, all_ts, mants = self._cols
                self._blocks = [
                    Block(tsid_at(int(a)), all_ts[a:b], mants[a:b],
                          int(exps[k]), prec)
                    for k, (a, b) in enumerate(zip(starts, ends))]
        return self._blocks

    def iter_blocks(self, tsid_set=None, min_ts=None, max_ts=None):
        for b in self.block_list:
            if tsid_set is not None and b.tsid.metric_id not in tsid_set:
                continue
            if min_ts is not None and int(b.timestamps[-1]) < min_ts:
                continue
            if max_ts is not None and int(b.timestamps[0]) > max_ts:
                continue
            yield b

    def columns(self):
        """Lazily built columnar view (the part is immutable): per-block
        metadata arrays + concatenated sample columns, so query-time block
        collection is numpy masking instead of per-block Python — the
        fixed per-series cost of the fresh-data fetch path."""
        c = self._cols
        if c is None:
            K = len(self.block_list)
            bl = self.block_list
            mids = np.fromiter((b.tsid.metric_id for b in bl), np.int64, K)
            cnts = np.fromiter((b.rows for b in bl), np.int64, K)
            scales = np.fromiter((b.scale for b in bl), np.int64, K)
            bmin = np.fromiter((b.timestamps[0] for b in bl), np.int64, K)
            bmax = np.fromiter((b.timestamps[-1] for b in bl), np.int64, K)
            if K:
                ts_all = np.concatenate([b.timestamps for b in bl])
                m_all = np.concatenate([b.values for b in bl])
            else:
                ts_all = np.zeros(0, np.int64)
                m_all = np.zeros(0, np.int64)
            offs = np.cumsum(cnts) - cnts
            c = (mids, cnts, scales, bmin, bmax, offs, ts_all, m_all)
            self._cols = c
        return c

    def collect_columns(self, mids_sorted, min_ts, max_ts):
        """Vectorized block selection -> (mids, cnts, scales, ts, mants)
        or None when nothing matches. `mids_sorted` is a sorted int64 array
        of wanted metric ids (None = all)."""
        from .part import sorted_member_mask
        mids, cnts, scales, bmin, bmax, offs, ts_all, m_all = self.columns()
        lo = -(1 << 62) if min_ts is None else min_ts
        hi = (1 << 62) if max_ts is None else max_ts
        mask = (bmax >= lo) & (bmin <= hi) & \
            sorted_member_mask(mids_sorted, mids)
        idx = np.flatnonzero(mask)
        if idx.size == 0:
            return None
        sel_cnts = cnts[idx]
        tot = int(sel_cnts.sum())
        excl = np.cumsum(sel_cnts) - sel_cnts
        pos = np.repeat(offs[idx] - excl, sel_cnts) + \
            np.arange(tot, dtype=np.int64)
        return (mids[idx], sel_cnts, scales[idx], ts_all[pos], m_all[pos])


class PendingChunk:
    """A columnar ingest batch parked in a partition's pending list: dense
    id rows resolved by the native key map (Storage.add_rows_columnar).
    Per-id TSID sort-key columns live in the owning id space, so chunk
    construction is pure numpy gathers — no per-row Python objects exist
    anywhere on the columnar ingest hot path."""

    __slots__ = ("space", "ids", "ts", "vals")

    def __init__(self, space, ids, ts, vals):
        self.space = space
        self.ids = ids
        self.ts = ts
        self.vals = vals

    def __len__(self):
        return int(self.ids.size)


def _rows_to_inmemory_part(rows: list, precision_bits: int = 64) -> InmemoryPart:
    """rows: list of (TSID, ts_ms, float_value) tuples and/or PendingChunks.
    Sorts by (tsid, ts) and builds <=8k-row blocks (createInmemoryPart,
    partition.go:877 analog).

    The float->decimal conversion is BATCHED across all blocks
    (float_to_decimal_grouped): per-series scrape flushes produce thousands
    of ~tens-of-rows blocks, where per-block conversion overhead dominates
    the flush."""
    if any(isinstance(r, PendingChunk) for r in rows):
        return _mixed_to_inmemory_part(rows, precision_bits)
    from ..ops.decimal import float_to_decimal_grouped
    from .block import MAX_ROWS_PER_BLOCK, Block
    n = len(rows)
    if n > 512:
        # vectorized (tsid sort_key, ts) ordering: the tuple-key list sort
        # costs ~25us/row in Python and dominates query-visible pending
        # conversion during live ingest
        acc = np.fromiter((r[0].account_id for r in rows), np.uint64, n)
        proj = np.fromiter((r[0].project_id for r in rows), np.uint64, n)
        grp = np.fromiter((r[0].metric_group_id for r in rows),
                          np.uint64, n)
        job = np.fromiter((r[0].job_id for r in rows), np.uint64, n)
        inst = np.fromiter((r[0].instance_id for r in rows), np.uint64, n)
        mid = np.fromiter((r[0].metric_id for r in rows), np.uint64, n)
        all_ts = np.fromiter((r[1] for r in rows), np.int64, n)
        all_vals = np.fromiter((r[2] for r in rows), np.float64, n)
        order = np.lexsort((all_ts, mid, inst, job, grp, proj, acc))
        rows = [rows[i] for i in order]
        all_ts = all_ts[order]
        all_vals = all_vals[order]
        mid = mid[order]
        series_starts = np.concatenate(
            [[0], np.flatnonzero(mid[1:] != mid[:-1]) + 1, [n]]) \
            if n else np.array([0, 0])
    else:
        rows.sort(key=lambda r: (r[0].sort_key(), r[1]))
        all_ts = np.fromiter((r[1] for r in rows), dtype=np.int64, count=n)
        all_vals = np.fromiter((r[2] for r in rows), dtype=np.float64,
                               count=n)
        series_starts = None
    segs = []          # (tsid, start, end) per block
    if series_starts is not None:
        for a, b in zip(series_starts[:-1], series_starts[1:]):
            tsid = rows[a][0]
            for x in range(a, b, MAX_ROWS_PER_BLOCK):
                segs.append((tsid, x, min(x + MAX_ROWS_PER_BLOCK, b)))
    else:
        i = 0
        while i < n:
            j = i
            tsid = rows[i][0]
            while j < n and rows[j][0].metric_id == tsid.metric_id:
                j += 1
            for a in range(i, j, MAX_ROWS_PER_BLOCK):
                segs.append((tsid, a, min(a + MAX_ROWS_PER_BLOCK, j)))
            i = j
    if not segs:
        return InmemoryPart([])
    starts = np.array([a for _, a, _ in segs], dtype=np.int64)
    m_all, exps = float_to_decimal_grouped(all_vals, starts)
    return InmemoryPart.from_columns(segs, all_ts, m_all, exps,
                                     precision_bits)


def _mixed_to_inmemory_part(items: list, precision_bits: int) -> InmemoryPart:
    """Columnar InmemoryPart construction over a mix of PendingChunks and
    legacy (TSID, ts, val) tuples: sort-key columns are gathered/concatenated
    and lexsorted; TSID objects are resolved per BLOCK (not per row) via
    (owner, loc) provenance arrays."""
    from ..ops.decimal import float_to_decimal_grouped
    from .block import MAX_ROWS_PER_BLOCK
    chunks = [x for x in items if isinstance(x, PendingChunk)]
    tups = [x for x in items if not isinstance(x, PendingChunk)]
    accs, projs, grps, jobs, insts, mids = [], [], [], [], [], []
    tss, valss, owners, locs = [], [], [], []
    n_t = len(tups)
    if n_t:
        accs.append(np.fromiter((r[0].account_id for r in tups), np.uint64, n_t))
        projs.append(np.fromiter((r[0].project_id for r in tups), np.uint64, n_t))
        grps.append(np.fromiter((r[0].metric_group_id for r in tups), np.uint64, n_t))
        jobs.append(np.fromiter((r[0].job_id for r in tups), np.uint64, n_t))
        insts.append(np.fromiter((r[0].instance_id for r in tups), np.uint64, n_t))
        mids.append(np.fromiter((r[0].metric_id for r in tups), np.uint64, n_t))
        tss.append(np.fromiter((r[1] for r in tups), np.int64, n_t))
        valss.append(np.fromiter((r[2] for r in tups), np.float64, n_t))
        owners.append(np.full(n_t, -1, np.int64))
        locs.append(np.arange(n_t, dtype=np.int64))
    for ci, ch in enumerate(chunks):
        ids = ch.ids
        sp = ch.space
        accs.append(sp.acc[ids])
        projs.append(sp.proj[ids])
        grps.append(sp.grp[ids])
        jobs.append(sp.job[ids])
        insts.append(sp.inst[ids])
        mids.append(sp.mid[ids])
        tss.append(ch.ts)
        valss.append(ch.vals)
        owners.append(np.full(ids.size, ci, np.int64))
        locs.append(ids)
    acc = np.concatenate(accs)
    proj = np.concatenate(projs)
    grp = np.concatenate(grps)
    job = np.concatenate(jobs)
    inst = np.concatenate(insts)
    mid = np.concatenate(mids)
    all_ts = np.concatenate(tss)
    all_vals = np.concatenate(valss)
    owner = np.concatenate(owners)
    loc = np.concatenate(locs)
    n = int(all_ts.size)
    if n == 0:
        return InmemoryPart([])
    order = np.lexsort((all_ts, mid, inst, job, grp, proj, acc))
    all_ts = all_ts[order]
    all_vals = all_vals[order]
    mid = mid[order]
    owner = owner[order]
    loc = loc[order]
    series_starts = np.concatenate(
        [[0], np.flatnonzero(mid[1:] != mid[:-1]) + 1, [n]]).astype(np.int64)

    def tsid_at(r: int):
        o = owner[r]
        return tups[loc[r]][0] if o < 0 else chunks[o].space.tsids[loc[r]]

    lens = np.diff(series_starts)
    if int(lens.max(initial=0)) <= MAX_ROWS_PER_BLOCK:
        # common case (scrape batches are tiny per series): one block per
        # series, fully vectorized — no per-series Python loop
        starts = series_starts[:-1]
        ends = series_starts[1:]
    else:
        pieces_s = []
        pieces_e = []
        for a, b in zip(series_starts[:-1], series_starts[1:]):
            xs = np.arange(a, b, MAX_ROWS_PER_BLOCK, dtype=np.int64)
            pieces_s.append(xs)
            pieces_e.append(np.minimum(xs + MAX_ROWS_PER_BLOCK, b))
        starts = np.concatenate(pieces_s)
        ends = np.concatenate(pieces_e)
    if starts.size == 0:
        return InmemoryPart([])
    m_all, exps = float_to_decimal_grouped(all_vals, starts)
    return InmemoryPart.from_seg_arrays(starts, ends, mid, tsid_at, all_ts,
                                        m_all, exps, precision_bits)


def _merge_block_streams(sources, deleted_ids: np.ndarray | None,
                         min_valid_ts: int | None,
                         dedup_interval: int = 0):
    """K-way merge of block iterators into (tsid, ts)-ordered blocks, with
    tombstone / retention / dedup filtering (mergeBlockStreams, merge.go:19
    analog). Yields Blocks."""
    del_set = set(int(x) for x in deleted_ids) if deleted_ids is not None else set()

    def keyed(src):
        for b in src:
            yield ((b.tsid.sort_key(), int(b.timestamps[0])), b)

    pending_tsid = None
    pend_ts: list[np.ndarray] = []
    pend_vals: list[np.ndarray] = []
    pend_scales: list[int] = []

    def flush():
        nonlocal pend_ts, pend_vals, pend_scales, pending_tsid
        if pending_tsid is None:
            return []
        from ..ops import decimal as dec
        # merge rows of one series across source blocks
        ts = np.concatenate(pend_ts)
        if len(set(pend_scales)) == 1:
            vals = np.concatenate(pend_vals)
            scale = pend_scales[0]
        else:
            floats = np.concatenate([
                dec.decimal_to_float(v, s)
                for v, s in zip(pend_vals, pend_scales)])
            vals, scale = dec.float_to_decimal(floats)
        order = np.argsort(ts, kind="stable")
        ts = ts[order]
        vals = vals[order]
        if min_valid_ts is not None:
            keep = ts >= min_valid_ts
            ts, vals = ts[keep], vals[keep]
        if dedup_interval > 0:
            ts, vals = deduplicate(ts, vals, dedup_interval)
        out = []
        tsid = pending_tsid
        # split by row cap AND time span: span-capped blocks keep the
        # header-level time pruning effective after big merges collapse a
        # series into few blocks, so a tail fetch decodes O(tail) rows (the
        # reference's 8k-row cap does this implicitly at real scrape rates,
        # lib/storage/block.go:15)
        i, n = 0, int(ts.size)
        while i < n:
            j = min(i + MAX_ROWS_PER_BLOCK, n)
            if MAX_BLOCK_SPAN_MS > 0 and j > i + MIN_SPAN_SPLIT_ROWS:
                j_span = i + int(np.searchsorted(
                    ts[i:j], ts[i] + MAX_BLOCK_SPAN_MS, side="left"))
                if j_span < j:
                    j = max(i + MIN_SPAN_SPLIT_ROWS, j_span)
            out.append(Block(tsid, ts[i:j], vals[i:j], scale))
            i = j
        pending_tsid = None
        pend_ts, pend_vals, pend_scales = [], [], []
        return out

    for _, b in heapq.merge(*(keyed(s) for s in sources), key=lambda kv: kv[0]):
        if b.tsid.metric_id in del_set:
            continue
        if pending_tsid is not None and b.tsid.metric_id != pending_tsid.metric_id:
            yield from flush()
        if pending_tsid is None:
            pending_tsid = b.tsid
        pend_ts.append(b.timestamps)
        pend_vals.append(b.values)
        pend_scales.append(b.scale)
    yield from flush()


@traced_fields("_pending", "_pending_nrows", "_pending_parts",
               "_pending_off", "_pending_gen", "_mem_parts", "_file_parts",
               "_pending_inflight", "_inflight_nrows", "_spill_done",
               "_spill_next")
class Partition:
    """One month of data ("2006_01" naming, time.go:79 analog)."""

    def __init__(self, path: str, name: str, dedup_interval_ms: int = 0):
        self.path = path
        self.name = name
        self.dedup_interval_ms = dedup_interval_ms
        self._lock = make_rlock("storage.Partition._lock")
        # serializes whole flush/merge operations (heavy part writes run
        # outside _lock so ingest/reads never stall behind them)
        self._flush_mutex = make_rlock("storage.Partition._flush_mutex")
        self._pending: list = []        # row tuples and/or PendingChunks
        self._pending_nrows = 0
        # incremental InmemoryPart views over _pending: each query converts
        # only rows ingested since the previous query (the flusher compacts
        # everything into one part every couple of seconds anyway);
        # _pending_gen detects a flush racing a lock-free conversion
        self._pending_parts: list = []
        self._pending_off = 0
        self._pending_gen = 0
        # cap-triggered pending conversions handed to the work pool.
        # Each conversion TASK lands its own part into _mem_parts under
        # _lock, strictly in spill-sequence order (_spill_done holds
        # out-of-order completions), so parts are byte-identical to the
        # sequential path; _pending_inflight only tracks completion
        # Futures for waiters — no consumer-side mutual exclusion is
        # needed, so waiters hold NO locks while pool-helping (a waiter
        # that held one could help-execute another partition's flush and
        # deadlock ABBA-style on the pair of consumer locks).
        self._pending_inflight: list = []
        self._inflight_nrows = 0
        self._spill_seq = 0       # next spill's sequence number
        self._spill_next = 0      # next sequence to land in _mem_parts
        self._spill_done: dict[int, tuple] = {}  # seq -> (part|None, nrows)
        self._mem_parts: list[InmemoryPart] = []
        self._file_parts: list[Part] = []
        self._seq = itertools.count()
        #: parts moved aside by the open-time integrity check (report
        #: entries; a non-empty list marks every result partial)
        self.quarantined: list[dict] = []
        #: listed parts that failed to open but were NOT moved (transient
        #: OSError, or the quarantine move itself failed): they must stay
        #: in parts.json — delisting them would hand the bytes to the
        #: next open's unlisted-dir sweep
        self._keep_listed: list[str] = []
        #: downsampled tiers by resolution_ms (ds_<res> dirs; see
        #: storage/downsample.py) — raw parts and tier parts never mix
        self._tiers: dict[int, "dslib.PartitionTier"] = {}
        os.makedirs(path, exist_ok=True)
        self._open_existing()

    # -- lifecycle ---------------------------------------------------------

    def _parts_json(self):
        return os.path.join(self.path, "parts.json")

    def _write_parts_json_locked(self):
        names = [os.path.basename(p.path) for p in self._file_parts]
        # broken-but-unmoved parts stay listed: the manifest is the only
        # thing standing between their bytes and the unlisted-dir sweep
        names += [n for n in self._keep_listed if n not in names]
        tmp = self._parts_json() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"parts": names}, f)
            f.flush()
            os.fsync(f.fileno())
        faultinject.fire("partition:parts_json:pre_replace")
        # replace + parent fsync: the manifest swap must be durable, not
        # just atomic — a crash after the rename but before the dir entry
        # hits disk could resurrect the OLD part list
        fslib.rename_durable(tmp, self._parts_json())

    def _open_existing(self):
        # parts quarantined by a PREVIOUS open still poison completeness:
        # report them (and serve partial) until the operator restores or
        # deletes them — a restart must not silently un-flag the loss
        self.quarantined.extend(fslib.resident_quarantine_entries(
            self.path, "storage", self.name))
        listed = []
        if os.path.exists(self._parts_json()):
            with open(self._parts_json()) as f:
                # corrupt parts.json = on-disk corruption, the same
                # true-internal-error class as a checksum mismatch: the
                # anonymous 500/error frame is the contract (operator
                # must inspect the partition, no client status helps)
                listed = json.load(f)["parts"]
        for name in listed:
            p = os.path.join(self.path, name)
            try:
                # open-phase: runs from __init__ before the Partition is
                # published to any other thread
                self._file_parts.append(Part(p))  # vmt: disable=VMT015
            except (fslib.IntegrityError, ValueError, KeyError) as e:
                # torn/corrupt/unparsable LISTED part: move it to the
                # quarantine dir and serve LOUDLY PARTIAL — never the old
                # behavior of logging once and silently dropping the data
                # from every future result
                try:
                    self.quarantined.append(quarantine_dir_entry(
                        self.path, name, e, "storage", self.name))
                    _PARTS_QUARANTINED.inc()
                except OSError as move_err:
                    # cannot even move it (permissions?): keep the dir in
                    # place AND LISTED (delisting would hand its bytes to
                    # the next open's unlisted-dir sweep) — still loud
                    logger.errorf("partition %s: cannot quarantine part "
                                  "%s: %s", self.name, name, move_err)
                    self.quarantined.append(
                        {"store": "storage", "in": self.name, "part": name,
                         "path": p, "error": str(e)})
                    # open-phase (see above): pre-publication
                    self._keep_listed.append(name)  # vmt: disable=VMT015
                    _PARTS_OPEN_ERRORS.inc()
            except OSError as e:
                # transient open failure (fd exhaustion, permissions) is
                # NOT evidence of torn bytes: keep the part in place and
                # listed so a fixed environment serves it again, but
                # report it — the data is missing from results NOW, and
                # that must be loud, not silent
                logger.errorf("partition %s: cannot open part %s (kept "
                              "listed, serving partial): %s",
                              self.name, name, e)
                self.quarantined.append(
                    {"store": "storage", "in": self.name, "part": name,
                     "path": p, "error": str(e)})
                self._keep_listed.append(name)
                _PARTS_OPEN_ERRORS.inc()
        # remove crash leftovers: only dirs NOT listed in parts.json
        # (the quarantine dir is bookkeeping, never a leftover; ds_* tier
        # dirs carry their OWN manifest + sweep — see PartitionTier.open)
        for name in os.listdir(self.path):
            full = os.path.join(self.path, name)
            if name == "parts.json" or name == QUARANTINE_DIR or \
                    not os.path.isdir(full):
                continue
            if name.startswith(dslib.TIER_DIR_PREFIX):
                try:
                    res = int(name[len(dslib.TIER_DIR_PREFIX):])
                except ValueError:
                    shutil.rmtree(full, ignore_errors=True)
                    continue
                # open-phase (see above): pre-publication
                self._tiers[res] = dslib.PartitionTier.open(  # vmt: disable=VMT015
                    full, res, self.quarantined, self.name)
                continue
            if name not in listed:
                shutil.rmtree(full, ignore_errors=True)
        if self.quarantined:
            # drop MOVED names from the manifest (kept-in-place failures
            # stay listed via _keep_listed) so a later restart doesn't
            # re-sweep or re-report healed state
            self._write_parts_json_locked()
        if self._file_parts:
            seqs = [int(os.path.basename(p.path).split("_")[1])
                    for p in self._file_parts]
            # open-phase (see above): pre-publication, thread-local
            self._seq = itertools.count(max(seqs) + 1)  # vmt: disable=VMT015

    def close(self):
        with self._lock:
            for p in self._file_parts:
                p.close()
            self._file_parts = []
            for st in self._tiers.values():
                st.close()
            self._tiers = {}

    # -- writes ------------------------------------------------------------

    def add_rows(self, rows) -> None:
        """rows: list of (TSID, ts_ms, float_value)."""
        with self._lock:
            self._pending.extend(rows)
            self._pending_nrows += len(rows)
            spill = self._pending_nrows >= MAX_PENDING_ROWS
            if spill:
                self._cap_flush_locked()
        if spill:
            self._drain_inflight(keep=_MAX_INFLIGHT_PARTS)

    def add_rows_columnar(self, chunk: PendingChunk) -> None:
        """Columnar ingest: the whole batch parks as ONE pending element
        (no per-row tuples), counted by its row total."""
        with self._lock:
            self._pending.append(chunk)
            self._pending_nrows += len(chunk)
            spill = self._pending_nrows >= MAX_PENDING_ROWS
            if spill:
                self._cap_flush_locked()
        if spill:
            self._drain_inflight(keep=_MAX_INFLIGHT_PARTS)

    def _cap_flush_locked(self):
        """Pending hit the row cap: convert to an InmemoryPart.  With the
        sharded write path enabled the conversion (lexsort + decimal
        encode — GIL-releasing numpy) runs on the work pool while ingest
        continues; the conversion task lands its part into _mem_parts in
        SPILL ORDER itself (_convert_spill), so part contents equal the
        sequential path's byte for byte.  VM_INGEST_SHARDS=1 (or the
        deterministic scheduler) keeps today's inline conversion."""
        if not self._pending_inflight and \
                not workpool.ingest_parallel_enabled():
            self._flush_pending_locked()
            return
        # NOTE: with older spills still in flight the conversion must go
        # through the spill sequence even when the pool is now disabled
        # (submit executes inline then), or _mem_parts would be appended
        # out of ingest order
        rows, n = self._take_pending_locked()
        seq = self._spill_seq
        self._spill_seq += 1
        self._inflight_nrows += n
        from functools import partial
        self._pending_inflight.append(
            workpool.POOL.submit(partial(self._convert_spill, rows, n,
                                         seq)))

    def _convert_spill(self, rows, n, seq):
        """Pool task: convert one spilled pending batch and land every
        ready part into _mem_parts in spill order (out-of-order
        completions park in _spill_done until their turn).  On a
        conversion error the batch is dropped with consistent
        bookkeeping — the same outcome as a failed inline conversion,
        whose rows were already swapped out — and the error propagates
        to whoever waits on the Future (the flusher logs it)."""
        part = err = None
        try:
            part = _rows_to_inmemory_part(rows)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            err = e
            _SPILL_ERRORS.inc()
            logger.errorf("partition %s: async pending conversion failed, "
                          "%d rows dropped: %s", self.name, n, e)
        with self._lock:
            self._spill_done[seq] = (part, n)
            while self._spill_next in self._spill_done:
                p, pn = self._spill_done.pop(self._spill_next)
                self._spill_next += 1
                self._inflight_nrows -= pn
                if self._pending_inflight:
                    self._pending_inflight.pop(0)
                if p is not None:
                    self._mem_parts.append(p)
        if err is not None:
            raise err
        return part

    def _drain_inflight(self, keep: int = 0) -> None:
        """Wait until at most `keep` conversions remain in flight (the
        tasks land their own parts; this only blocks on completion).
        keep=0 is the visibility barrier for queries/flushes; keep>0 is
        ingest backpressure.  Holds NO locks across the wait: the
        pool-helping wait may execute arbitrary queued tasks, including
        other partitions' flushes."""
        while True:
            with self._lock:
                if len(self._pending_inflight) <= keep:
                    return
                fut = self._pending_inflight[0]
            # multi-waiter safe (the completion token re-arms); when the
            # head future resolves its task has already landed the part
            # and popped itself, so the loop re-check makes progress
            try:
                # help-draining workpool future (bounded progress: the
                # waiter executes queued tasks, and conversion units are
                # small); the receiver comes out of a list so the taint
                # pass cannot resolve it to the workpool seam statically
                fut.result()  # vmt: disable=VMT012
            except Exception:  # vmt: disable=VMT003 — the failing task
                # already logged the error, counted it in
                # vm_ingest_spill_errors_total and dropped its batch with
                # consistent books; re-raising here would fail an
                # unrelated READER for an ingest-side error
                pass

    def _take_pending_locked(self):
        """Swap the pending rows out and invalidate the incremental
        query views; returns (rows, row_count)."""
        rows, self._pending = self._pending, []
        n = self._pending_nrows
        self._pending_nrows = 0
        self._pending_parts = []
        self._pending_off = 0
        self._pending_gen += 1
        return rows, n

    def _flush_pending_locked(self):
        if not self._pending:
            return
        rows, _ = self._take_pending_locked()
        self._mem_parts.append(_rows_to_inmemory_part(rows))

    def _pending_views(self):
        """InmemoryParts covering the current pending rows; only rows
        ingested since the last call are converted, and the conversion runs
        OUTSIDE the partition lock so concurrent add_rows never stalls
        behind it. Returns (views, generation): the caller re-checks the
        generation under the lock before combining with the part lists."""
        while True:
            with self._lock:
                gen = self._pending_gen
                off = self._pending_off
                n = len(self._pending)
                if off >= n:
                    return list(self._pending_parts), gen
                tail = list(self._pending[off:n])
            part = _rows_to_inmemory_part(tail)
            with self._lock:
                if self._pending_gen == gen and self._pending_off == off:
                    self._pending_parts.append(part)
                    self._pending_off = n
                # else: flushed (or another query converted) while we
                # worked — loop and re-snapshot

    def flush_pending(self):
        while True:
            self._drain_inflight()
            with self._lock:
                if not self._pending_inflight:
                    self._flush_pending_locked()
                    return
                # spilled between the drain and the lock: drain again so
                # _mem_parts keeps ingest order

    def flush_to_disk(self):
        """pending + in-memory parts -> one small file part (durable).

        The heavy encode+fsync runs OUTSIDE the partition data lock:
        ingest only pauses for the two brief list swaps, not the multi-
        second part write (the reference's background merger pool
        behavior, partition.go:663 — here the flusher thread is that
        pool, fanned across partitions by Table).  _flush_mutex
        serializes concurrent flushers/mergers per partition; the
        process-wide MERGE_GATE (VM_MERGE_WORKERS) bounds how many part
        writes run at once across all partitions and mergesets.

        In-flight async conversions are drained BEFORE taking
        _flush_mutex (never while holding it: the pool-helping wait may
        execute another partition's flush task, and flush-inside-drain
        plus drain-inside-flush would deadlock)."""
        while True:
            self._drain_inflight()
            if self._flush_to_disk_once():
                return

    def _flush_to_disk_once(self) -> bool:
        with self._flush_mutex:
            with self._lock:
                if self._pending_inflight:
                    return False  # spilled since the drain: retry
                self._flush_pending_locked()
                if not self._mem_parts:
                    return True
                mems = list(self._mem_parts)
            with workpool.MERGE_GATE:
                # timed inside the gate: the histograms mean pure write
                # time; queue wait is visible as vm_merge_pending
                t0 = time.perf_counter()
                p = self._write_part([m.iter_blocks() for m in mems])
                dt = time.perf_counter() - t0
            _FLUSH_DURATION.update(dt)
            _ING_FLUSH.inc(dt)
            flightrec.rec("flush:part", t0, dt, arg=self.name)
            with self._lock:
                if p is not None:
                    self._file_parts.append(p)
                    self._write_parts_json_locked()
                # drop exactly the flushed parts; newer mem parts appended
                # during the write stay (an ENOSPC abort keeps everything)
                flushed = {id(m) for m in mems}
                self._mem_parts = [m for m in self._mem_parts
                                   if id(m) not in flushed]
                merge_now = len(self._file_parts) > MAX_SMALL_PARTS
            if merge_now:
                self._merge_file_parts(self._file_parts)
            return True

    def _write_part(self, sources, deleted_ids=None, min_valid_ts=None):
        """Merge block streams into a new on-disk part (no data lock held;
        callers register the returned Part under the lock)."""
        name = f"p_{next(self._seq):016d}"
        w = PartWriter(os.path.join(self.path, name))
        wrote = False
        try:
            buf: list = []
            for b in _merge_block_streams(sources, deleted_ids, min_valid_ts,
                                          self.dedup_interval_ms):
                buf.append(b)
                if len(buf) >= _BULK_WRITE_BLOCKS:
                    w.write_blocks_bulk(buf)
                    wrote = True
                    buf = []
            if buf:
                w.write_blocks_bulk(buf)
                wrote = True
            if not wrote:
                w.abort()
                return None
            w.close()
        except BaseException:
            w.abort()
            raise
        # trusted: this process computed the checksums moments ago;
        # re-verifying would re-read the whole part per flush/merge
        return Part(os.path.join(self.path, name), trusted=True)

    def _merge_file_parts(self, parts, deleted_ids=None,
                          min_valid_ts=None):
        """Merge `parts` into one; the heavy merge runs outside the data
        lock (ingest and reads proceed), list swap + unlink under it."""
        with self._flush_mutex:
            with self._lock:
                olds = [p for p in parts if p in self._file_parts]
            if not olds:
                return
            _ACTIVE_MERGES.inc()
            try:
                with workpool.MERGE_GATE:
                    t0 = time.perf_counter()
                    merged = self._write_part(
                        [p.iter_blocks() for p in olds],
                        deleted_ids, min_valid_ts)
                    dt = time.perf_counter() - t0
                # counted only on success: an aborted merge (ENOSPC)
                # must not look like the compactor making progress
                _MERGE_DURATION.update(dt)
                _ING_MERGE.inc(dt)
                _MERGES_TOTAL.inc()
                flightrec.rec("merge:part", t0, dt, arg=self.name)
            finally:
                _ACTIVE_MERGES.dec()
            # the merged part dir is renamed into place but NOT yet in
            # parts.json: a crash here must recover to the OLD part set
            # (the unlisted merged dir is swept at reopen)
            faultinject.fire("merge:post_rename_pre_manifest")
            with self._lock:
                survivors = [p for p in self._file_parts if p not in olds]
                self._file_parts = survivors + (
                    [merged] if merged is not None else [])
                self._write_parts_json_locked()
            for old in olds:
                # Unlink only: concurrent readers may still iterate `old`;
                # open fds keep the data alive until the last reference
                # drops (the reference's part-refcount pattern, via GC).
                shutil.rmtree(old.path, ignore_errors=True)

    def force_merge(self, deleted_ids=None, min_valid_ts=None):
        """Merge everything into one part, applying tombstones/retention
        (the /internal/force_merge + final-dedup path)."""
        self.flush_to_disk()
        with self._flush_mutex:
            with self._lock:
                parts = list(self._file_parts)
            if parts:
                self._merge_file_parts(parts, deleted_ids, min_valid_ts)

    # -- downsampling (storage/downsample.py drives per-tier state) --------

    def run_downsample(self, tiers, deleted_ids=None, now_ms=None) -> int:
        """Re-rollup aged raw rows into coarser tier parts (the
        historicalMergeWatcher-shaped pass).  Consumes DURABLE file parts
        only — tier coverage must never run ahead of what raw has
        fsynced (callers flush first); the heavy merge+aggregate runs
        behind the process-wide MERGE_GATE so it defers to serving
        exactly like flush/merge.  Returns aggregated rows written."""
        from .table import _partition_bounds
        lo_p, hi_p = _partition_bounds(self.name)
        written = 0
        for tier in tiers:
            res = tier.resolution_ms
            # only COMPLETE buckets whose right edge has aged past the
            # tier offset (right-inclusive buckets: edge b*res covers
            # raw ts in ((b-1)*res, b*res])
            cutoff = ((now_ms - tier.offset_ms) // res) * res
            hi = min(cutoff, hi_p)
            with self._flush_mutex:
                with self._lock:
                    st = self._tiers.get(res)
                    covered = (st.covered_max_ts if st is not None
                               else -(1 << 62))
                    files = list(self._file_parts)
                lo = max(covered, lo_p - 1)
                if hi <= lo or not files:
                    continue
                if not any(p.min_ts <= hi and p.max_ts > lo
                           for p in files):
                    continue
                if st is None:
                    st = dslib.PartitionTier(
                        os.path.join(self.path,
                                     f"{dslib.TIER_DIR_PREFIX}{res}"), res)
                    os.makedirs(st.path, exist_ok=True)
                with workpool.MERGE_GATE:
                    t0 = time.perf_counter()
                    merged = _merge_block_streams(
                        [p.iter_blocks(min_ts=lo + 1, max_ts=hi)
                         for p in files],
                        deleted_ids, lo + 1, self.dedup_interval_ms)
                    _, rows_out, parts, names = dslib.rewrite_range(
                        st, merged, hi, res)
                    dt = time.perf_counter() - t0
                # tier part dirs are renamed into place but NOT yet in
                # tier.json: a crash here recovers to the OLD tier state
                # (the unlisted dirs are swept at reopen) — same seam
                # shape as merge:post_rename_pre_manifest
                faultinject.fire("downsample:post_rename_pre_manifest")
                with self._lock:
                    if names:
                        st.publish_parts(names, parts, hi)
                    else:
                        st.covered_max_ts = hi  # empty range: advance only
                    st.write_manifest()
                    self._tiers[res] = st
                dslib.note_pass(dt)
                flightrec.rec("downsample:part", t0, dt, arg=self.name)
                written += rows_out
        return written

    def tier_states(self) -> list:
        """Snapshot of open tiers (metrics/status; read-only)."""
        with self._lock:
            return list(self._tiers.values())

    def drop_raw_parts(self) -> int:
        """Raw retention expired while a downsampled tier still covers
        this partition: delist + delete every raw part (pending/mem rows
        included — they are older than raw retention too) and keep the
        tier dirs.  Returns 1 when anything was dropped."""
        self._drain_inflight()
        with self._flush_mutex:
            with self._lock:
                victims = self._file_parts
                had = bool(victims or self._mem_parts or self._pending)
                if not had:
                    return 0
                self._file_parts = []
                self._mem_parts = []
                self._take_pending_locked()
                self._write_parts_json_locked()
            for p in victims:
                # unlink only: concurrent readers holding the old Part
                # keep valid fds until the last reference drops
                shutil.rmtree(p.path, ignore_errors=True)
        return 1

    def drop_tier(self, resolution_ms: int) -> int:
        """Drop one tier past its own retention deadline."""
        with self._flush_mutex:
            with self._lock:
                st = self._tiers.pop(resolution_ms, None)
            if st is None:
                return 0
            st.close()
            shutil.rmtree(st.path, ignore_errors=True)
        return 1

    @property
    def has_tier_parts(self) -> bool:
        with self._lock:
            return any(st.has_parts for st in self._tiers.values())

    # -- reads -------------------------------------------------------------

    def iter_blocks(self, tsid_set=None, min_ts=None, max_ts=None,
                    tsid_lo=None, tsid_hi=None):
        """Blocks from all parts (NOT cross-part merged; the search layer
        merges rows per series)."""
        while True:
            self._drain_inflight()
            pend, gen = self._pending_views()
            with self._lock:
                if self._pending_gen == gen and not self._pending_inflight:
                    mems = list(self._mem_parts)
                    files = list(self._file_parts)
                    break
        mems = mems + pend
        for src in mems:
            yield from src.iter_blocks(tsid_set, min_ts, max_ts)
        for p in files:
            yield from p.iter_blocks(tsid_set, min_ts, max_ts,
                                     tsid_lo, tsid_hi)

    def collect_units(self, tsid_set=None, min_ts=None, max_ts=None,
                      tsid_lo=None, tsid_hi=None, mids_sorted=None,
                      as_float=False, ds=None, note=None):
        """Batched block collection, split into independent work units
        for the shared fetch pool (utils/workpool): returns a list of
        zero-arg callables, each yielding a list of (mids, cnts, scales,
        ts_concat, mant_concat) pieces.  Executing the units in ORDER and
        concatenating their outputs is bit-identical to the sequential
        collection — the pool preserves submit order, so parallel and
        sequential fetches return the same bytes.

        With ``as_float=True`` (the VM_NATIVE_ASSEMBLE fused read path)
        every unit instead yields FLOAT pieces (mids, cnts, ts_concat,
        vals_f64): file parts run the one-call native fetch→decode→clip→
        float kernel (Part.assemble_columns), and the in-memory /
        fallback sub-paths convert their mantissa pieces per block so the
        bytes match the split path exactly.

        Unit granularity: all in-memory parts form ONE unit (masked
        columnar views, pure numpy — cheap); each file part is its own
        unit (zstd + native decode release the GIL, so units genuinely
        overlap on workers).  Snapshotting the part lists (and converting
        pending rows) happens HERE on the calling thread, under the
        partition lock discipline; the returned closures touch only
        immutable parts.

        ``ds`` = ``(agg_column, max_resolution_ms)`` opts the fetch into
        downsampled tiers, CASCADING coarsest-to-finest: the coarsest
        tier whose resolution satisfies the bound serves up to its
        coverage watermark, each finer satisfying tier serves the span
        between the previous watermark and its own, and raw parts serve
        only past the finest contributing watermark.  Without any
        satisfying tier, a partition whose raw parts were dropped by
        retention falls back to the FINEST surviving tier (``last``
        column unless ``ds`` names one) and flags the result partial-
        resolution via ``note`` — loudly degraded, never silently wrong.
        ``note`` (dict) reports the choice: ``ds_res`` (max resolution
        actually served) and ``partial_res``."""
        while True:
            self._drain_inflight()
            pend, gen = self._pending_views()
            with self._lock:
                if self._pending_gen == gen and not self._pending_inflight:
                    mems = list(self._mem_parts)
                    files = list(self._file_parts)
                    tier_snap = [(st, st.covered_max_ts)
                                 for st in self._tiers.values()
                                 if st.has_parts]
                    break
        mems = mems + pend
        if mids_sorted is None and tsid_set is not None:
            mids_sorted = np.fromiter(tsid_set, np.int64, len(tsid_set))
            mids_sorted.sort()
        lo = -(1 << 62) if min_ts is None else min_ts
        hi = (1 << 62) if max_ts is None else max_ts
        from .part import _piece_to_float, clip_piece
        units = []

        # -- tier selection (see docstring) --------------------------------
        # chosen tier SEGMENTS, coarsest first: each (tier, seg_lo,
        # seg_hi) serves a disjoint span, the next finer tier picks up
        # at the previous watermark + 1, raw serves only past the FINEST
        # contributing watermark — a long-range query cascades
        # 1h-tier -> 5m-tier -> raw instead of paying raw for everything
        # the coarsest tier has not yet covered.
        chosen: list = []
        raw_lo = min_ts
        # COUNT-hinted fetch: raw samples contribute 1 each (see
        # downsample.count_tail_piece) — unconditional on whether a tier
        # serves, so the eval-level count->sum rewrite is always sound
        count_ones = (note is not None and ds is not None
                      and ds[0] == "count")
        # a note dict is the enable switch: Storage only passes one when
        # tiers are configured AND VM_DOWNSAMPLE_READ is on
        if tier_snap and note is not None:
            agg = ds[0] if ds is not None else "last"
            if ds is not None:
                cands = [(st, c) for st, c in tier_snap
                         if st.resolution_ms <= ds[1]]
                cands.sort(key=lambda tc: -tc[0].resolution_ms)
                cur_lo, cur_lo_i = min_ts, lo
                for st, c in cands:
                    if c < cur_lo_i:
                        continue  # extends nothing the cascade has
                    chosen.append((st, cur_lo, min(hi, c)))
                    cur_lo = cur_lo_i = c + 1
                    if c >= hi:
                        break
                if chosen:
                    raw_lo = cur_lo
            if not chosen and not mems and not files:
                # raw dropped by retention, no satisfying tier: finest
                # surviving tier, LOUDLY partial-resolution
                cands = [(st, c) for st, c in tier_snap if c >= lo]
                if cands:
                    st, c = min(cands,
                                key=lambda tc: tc[0].resolution_ms)
                    chosen = [(st, min_ts, min(hi, c))]
                    raw_lo = c + 1
                    note["partial_res"] = True
            if chosen:
                # coarsest resolution actually served
                note["ds_res"] = max(note.get("ds_res", 0),
                                     chosen[0][0].resolution_ms)
        raw_lo_i = -(1 << 62) if raw_lo is None else raw_lo

        mems = [src for src in mems
                if src.max_ts >= raw_lo_i and src.min_ts <= hi]
        if mems:
            def mem_unit(mems=mems, u_lo=raw_lo):
                pieces = []
                for src in mems:
                    piece = src.collect_columns(mids_sorted, u_lo, max_ts)
                    if piece is not None:
                        piece = clip_piece(*piece, u_lo, max_ts)
                        piece = (_piece_to_float(piece) if as_float
                                 else piece)
                        if count_ones:
                            piece = dslib.count_tail_piece(piece, as_float)
                        pieces.append(piece)
                return pieces
            units.append(mem_unit)
        for p, u_lo, u_hi, is_raw in (
                [(p, raw_lo, max_ts, True) for p in files] +
                [(p, s_lo, s_hi, False)
                 for st, s_lo, s_hi in chosen
                 for p in st.parts_for(agg)]):
            u_lo_i = -(1 << 62) if u_lo is None else u_lo
            u_hi_i = (1 << 62) if u_hi is None else u_hi
            if p.max_ts < u_lo_i or p.min_ts > u_hi_i:
                continue
            ones = count_ones and is_raw

            def file_unit(p=p, u_lo=u_lo, u_hi=u_hi, ones=ones):
                if as_float:
                    piece = p.assemble_columns(mids_sorted, u_lo, u_hi)
                else:
                    piece = p.collect_columns(mids_sorted, u_lo, u_hi)
                if piece is False:
                    return []  # vectorized path ran; nothing matched
                if piece is not None:  # already row-clipped
                    return [dslib.count_tail_piece(piece, as_float)
                            if ones else piece]
                # fallback: native decode unavailable — per-header path
                hdrs = list(p.iter_headers(tsid_set, u_lo, u_hi,
                                           tsid_lo, tsid_hi))
                if not hdrs:
                    return []
                K = len(hdrs)
                ts_c, m_c = p.read_blocks_columns(hdrs)
                piece = clip_piece(
                    np.fromiter((h.tsid.metric_id for h in hdrs),
                                np.int64, K),
                    np.fromiter((h.rows for h in hdrs), np.int64, K),
                    np.fromiter((h.scale for h in hdrs), np.int64, K),
                    ts_c, m_c, u_lo, u_hi)
                piece = _piece_to_float(piece) if as_float else piece
                return [dslib.count_tail_piece(piece, as_float)
                        if ones else piece]
            units.append(file_unit)
        return units

    def collect_columns(self, tsid_set=None, min_ts=None, max_ts=None,
                        tsid_lo=None, tsid_hi=None, mids_sorted=None,
                        as_float=False, ds=None, note=None):
        """Batched block collection: returns (mids, cnts, scales, ts_concat,
        mant_concat) numpy arrays over every matching block in this
        partition (float pieces under ``as_float`` — see collect_units).
        File parts decode ALL their matched blocks in one native
        call (part.read_blocks_columns); in-memory parts are masked
        columnar views with zero per-block Python.  (Sequential execution
        of collect_units; Table.collect_columns fans the same units across
        the shared work pool.)"""
        return [piece
                for unit in self.collect_units(tsid_set, min_ts, max_ts,
                                               tsid_lo, tsid_hi, mids_sorted,
                                               as_float, ds, note)
                for piece in unit()]

    @property
    def rows(self) -> int:
        with self._lock:
            return (self._pending_nrows + self._inflight_nrows
                    + sum(m.rows for m in self._mem_parts)
                    + sum(p.rows for p in self._file_parts))

    # -- live resharding (part migration) ----------------------------------

    def list_file_parts(self) -> list[dict]:
        """Finalized on-disk parts: migration inventory rows
        ``{part, rows, bytes, min_ts, max_ts}``."""
        with self._lock:
            parts = list(self._file_parts)
        return [{"part": os.path.basename(p.path), "rows": int(p.rows),
                 "bytes": p.file_bytes(), "min_ts": int(p.min_ts),
                 "max_ts": int(p.max_ts)} for p in parts]

    def get_file_part(self, name: str):
        """The open Part for one finalized part name (None when merged
        away/removed since listing — callers re-list and retry)."""
        with self._lock:
            for p in self._file_parts:
                if os.path.basename(p.path) == name:
                    return p
        return None

    def stage_part(self, files: list[tuple[str, bytes]]) -> str:
        """First half of adopting a part shipped from another node:
        write the files to a fresh local ``<name>.tmp`` dir, fsync, and
        VERIFY the recorded crc32s against the transferred bytes (the
        PR-10 integrity gate — a torn transfer is rejected here, before
        the caller commits ANY other state for the part, e.g. series
        registrations).  Returns the reserved part name; a crash leaves
        only a ``.tmp`` dir the next open sweeps."""
        for fname, _ in files:
            if os.sep in fname or fname != os.path.basename(fname) or \
                    fname.startswith("."):
                raise ValueError(f"bad part file name {fname!r}")
        with self._lock:
            name = f"p_{next(self._seq):016d}"
        tmp = os.path.join(self.path, name) + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        try:
            for fname, data in files:
                fp = os.path.join(tmp, fname)
                with open(fp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
            meta = fslib.load_meta_json(os.path.join(tmp, "metadata.json"))
            fslib.verify_checksums(tmp, meta)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return name

    def discard_staged(self, name: str) -> None:
        shutil.rmtree(os.path.join(self.path, name) + ".tmp",
                      ignore_errors=True)

    def publish_staged(self, name: str):
        """Second half: durably publish a verified staged part and
        register it in parts.json.  Returns the opened Part."""
        final = os.path.join(self.path, name)
        faultinject.fire("migrate:pre_publish")
        fslib.rename_durable(final + ".tmp", final)
        p = Part(final, trusted=True)  # checksums verified at staging
        with self._lock:
            self._file_parts.append(p)
            self._write_parts_json_locked()
        return p

    def adopt_part(self, files: list[tuple[str, bytes]]):
        """stage_part + publish_staged in one step (callers with no
        interleaved state to commit)."""
        return self.publish_staged(self.stage_part(files))

    def remove_parts(self, names: list[str]) -> int:
        """Delist + delete finalized parts (the source side of a part
        migration, after the receiver's durable ack).  Parts merged
        away since listing count as already gone.  Unlink only:
        concurrent readers holding the old Part keep valid fds until
        the last reference drops."""
        wanted = set(names)
        with self._flush_mutex:
            with self._lock:
                victims = [p for p in self._file_parts
                           if os.path.basename(p.path) in wanted]
                if victims:
                    self._file_parts = [p for p in self._file_parts
                                        if p not in victims]
                    self._write_parts_json_locked()
            for p in victims:
                shutil.rmtree(p.path, ignore_errors=True)
        return len(victims)

    # -- snapshots ---------------------------------------------------------

    def snapshot_to(self, dst: str):
        """Hardlink immutable parts (MustCreateSnapshotAt analog,
        partition.go:1992). Flush first so RAM state is included."""
        self.flush_to_disk()
        os.makedirs(dst, exist_ok=True)
        with self._lock:
            for p in self._file_parts:
                name = os.path.basename(p.path)
                pdst = os.path.join(dst, name)
                os.makedirs(pdst, exist_ok=True)
                for fn in os.listdir(p.path):
                    os.link(os.path.join(p.path, fn), os.path.join(pdst, fn))
            names = [os.path.basename(p.path) for p in self._file_parts]
        with open(os.path.join(dst, "parts.json"), "w") as f:
            json.dump({"parts": names}, f)
            f.flush()
            os.fsync(f.fileno())
        fslib.fsync_dir(dst)
