"""Series identity: metric name + sorted labels with canonical byte
marshaling (reference lib/storage/metric_name.go:75,137).

The canonical form is an escaped, separator-delimited byte string so that
(a) equal series marshal identically, (b) prefix scans over the index work
(escaping preserves prefixes, unlike length-prefixing), and (c) the metric
group (the __name__ value) is a leading prefix, clustering families.

Layout: esc(name) 0x00 esc(k1) 0x01 esc(v1) 0x00 esc(k2) 0x01 esc(v2) ...
with labels sorted by key. Escapes: 0x00->0x02 0x03, 0x01->0x02 0x04,
0x02->0x02 0x05.
"""

from __future__ import annotations

import re

SEP_TAG = b"\x00"
SEP_KV = b"\x01"
_ESC = b"\x02"

_ESC_MAP = {0x00: b"\x02\x03", 0x01: b"\x02\x04", 0x02: b"\x02\x05"}
_UNESC_MAP = {0x03: 0x00, 0x04: 0x01, 0x05: 0x02}
# one C-level scan for the (overwhelmingly common) nothing-to-escape case
_NEEDS_ESC = re.compile(rb"[\x00-\x02]")


def escape(b: bytes) -> bytes:
    if _NEEDS_ESC.search(b) is None:
        return b
    out = bytearray()
    for c in b:
        if c <= 0x02:
            out += _ESC_MAP[c]
        else:
            out.append(c)
    return bytes(out)


def unescape(b: bytes) -> bytes:
    if _ESC not in b:
        return b
    out = bytearray()
    i = 0
    while i < len(b):
        c = b[i]
        if c == 0x02:
            i += 1
            if i >= len(b) or b[i] not in _UNESC_MAP:
                raise ValueError("bad escape sequence in metric name")
            out.append(_UNESC_MAP[b[i]])
        else:
            out.append(c)
        i += 1
    return bytes(out)


class MetricName:
    """A metric group name plus sorted (key, value) labels.

    `labels` never contains __name__ — that is `metric_group`. Empty label
    values are dropped (Prometheus semantics: empty value == absent label).
    """

    __slots__ = ("metric_group", "labels")

    def __init__(self, metric_group: bytes = b"", labels=None):
        self.metric_group = metric_group
        self.labels: list[tuple[bytes, bytes]] = labels or []

    @classmethod
    def from_labels(cls, pairs) -> "MetricName":
        """Build from an iterable of (name, value) in any order; accepts str
        or bytes; drops empties; extracts __name__."""
        group = b""
        labels = []
        for k, v in pairs:
            kb = k.encode() if isinstance(k, str) else k
            vb = v.encode() if isinstance(v, str) else v
            if not vb:
                continue
            if kb == b"__name__":
                group = vb
            else:
                labels.append((kb, vb))
        labels.sort()
        return cls(group, labels)

    @classmethod
    def from_dict(cls, d) -> "MetricName":
        return cls.from_labels(d.items())

    def to_dict(self) -> dict[str, str]:
        out = {}
        if self.metric_group:
            out["__name__"] = self.metric_group.decode()
        for k, v in self.labels:
            out[k.decode()] = v.decode()
        return out

    def sort_labels(self) -> None:
        self.labels.sort()

    def get_label(self, key: bytes) -> bytes | None:
        if key == b"__name__":
            return self.metric_group or None
        for k, v in self.labels:
            if k == key:
                return v
        return None

    def marshal(self) -> bytes:
        parts = [escape(self.metric_group)]
        for k, v in self.labels:
            parts.append(SEP_TAG + escape(k) + SEP_KV + escape(v))
        return b"".join(parts)

    @classmethod
    def unmarshal(cls, data: bytes) -> "MetricName":
        chunks = data.split(SEP_TAG)
        mn = cls(unescape(chunks[0]))
        for c in chunks[1:]:
            k, _, v = c.partition(SEP_KV)
            mn.labels.append((unescape(k), unescape(v)))
        return mn

    def __eq__(self, other):
        return (self.metric_group == other.metric_group
                and self.labels == other.labels)

    def __hash__(self):
        return hash((self.metric_group, tuple(self.labels)))

    def __repr__(self):
        lbl = ", ".join(f"{k.decode()}={v.decode()!r}" for k, v in self.labels)
        return f"{self.metric_group.decode()}{{{lbl}}}"
