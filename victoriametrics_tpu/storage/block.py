"""Data blocks: up to 8k rows of one series, separately-encoded timestamp and
value columns (reference lib/storage/block.go:14-22, block_header.go:19).

A Block is the unit moving through parts, merges, RPC and the TPU packer:
  timestamps: int64 unix ms, non-decreasing
  values:     int64 decimal mantissas sharing `scale` (ops.decimal)
Header carries the codec metadata and the payload offsets inside the part's
timestamps.bin / values.bin.
"""

from __future__ import annotations

import struct

import numpy as np

from ..ops import decimal as dec
from ..ops import encoding as enc
from .tsid import TSID

MAX_ROWS_PER_BLOCK = 8192

# tsid(32) min_ts max_ts rows scale prec ts_mt val_mt ts_first val_first
# ts_off ts_size val_off val_size
_HDR = struct.Struct(">32sqqIhBBBqqQIQI")


class BlockHeader:
    __slots__ = ("tsid", "min_ts", "max_ts", "rows", "scale", "precision_bits",
                 "ts_marshal_type", "val_marshal_type", "ts_first",
                 "val_first", "ts_offset", "ts_size", "val_offset", "val_size")

    SIZE = _HDR.size

    def marshal(self) -> bytes:
        return _HDR.pack(
            self.tsid.marshal(), self.min_ts, self.max_ts, self.rows,
            self.scale, self.precision_bits, int(self.ts_marshal_type),
            int(self.val_marshal_type), self.ts_first, self.val_first,
            self.ts_offset, self.ts_size, self.val_offset, self.val_size)

    @classmethod
    def unmarshal(cls, data: bytes, offset: int = 0) -> "BlockHeader":
        (tsid_b, min_ts, max_ts, rows, scale, prec, ts_mt, val_mt, ts_first,
         val_first, ts_off, ts_size, val_off, val_size) = _HDR.unpack_from(
            data, offset)
        h = cls()
        h.tsid = TSID.unmarshal(tsid_b)
        h.min_ts, h.max_ts, h.rows = min_ts, max_ts, rows
        h.scale, h.precision_bits = scale, prec
        h.ts_marshal_type = enc.MarshalType(ts_mt)
        h.val_marshal_type = enc.MarshalType(val_mt)
        h.ts_first, h.val_first = ts_first, val_first
        h.ts_offset, h.ts_size = ts_off, ts_size
        h.val_offset, h.val_size = val_off, val_size
        return h


class Block:
    """Decoded (in-RAM) block."""

    __slots__ = ("tsid", "timestamps", "values", "scale", "precision_bits",
                 "_floats", "_has_stale")

    def __init__(self, tsid: TSID, timestamps: np.ndarray, values: np.ndarray,
                 scale: int, precision_bits: int = 64):
        self.tsid = tsid
        self.timestamps = timestamps
        self.values = values  # int64 mantissas
        self.scale = scale
        self.precision_bits = precision_bits
        self._floats = None
        self._has_stale = None

    @classmethod
    def from_floats(cls, tsid: TSID, timestamps: np.ndarray,
                    float_values: np.ndarray, precision_bits: int = 64) -> "Block":
        m, e = dec.float_to_decimal(np.asarray(float_values, dtype=np.float64))
        return cls(tsid, np.asarray(timestamps, dtype=np.int64), m, e,
                   precision_bits)

    def float_values(self) -> np.ndarray:
        # memoized: blocks live in the part block cache across queries
        if self._floats is None:
            f = dec.decimal_to_float(self.values, self.scale)
            f.setflags(write=False)
            self._floats = f
        return self._floats

    def has_stale(self) -> bool:
        """Whether any value is a staleness-marker NaN — memoized alongside
        the float decode so warm queries skip the per-query stale scan."""
        if self._has_stale is None:
            self._has_stale = bool(
                dec.is_stale_nan(self.float_values()).any())
        return self._has_stale

    @property
    def rows(self) -> int:
        return int(self.timestamps.size)

    def marshal(self) -> tuple[BlockHeader, bytes, bytes]:
        """Returns (header-without-offsets, ts_payload, val_payload)."""
        if not 0 < self.rows <= MAX_ROWS_PER_BLOCK:
            raise ValueError(f"block rows {self.rows} out of range")
        ts_data, ts_mt, ts_first = enc.marshal_timestamps(
            self.timestamps, 64)
        val_data, val_mt, val_first = enc.marshal_values(
            self.values, self.precision_bits)
        h = BlockHeader()
        h.tsid = self.tsid
        h.min_ts = int(self.timestamps[0])
        h.max_ts = int(self.timestamps[-1])
        h.rows = self.rows
        h.scale = self.scale
        h.precision_bits = self.precision_bits
        h.ts_marshal_type = ts_mt
        h.val_marshal_type = val_mt
        h.ts_first = ts_first
        h.val_first = val_first
        h.ts_offset = h.val_offset = 0
        h.ts_size = len(ts_data)
        h.val_size = len(val_data)
        return h, ts_data, val_data

    @classmethod
    def unmarshal(cls, h: BlockHeader, ts_data: bytes, val_data: bytes) -> "Block":
        ts = enc.unmarshal_timestamps(ts_data, h.ts_marshal_type, h.ts_first,
                                      h.rows)
        vals = enc.unmarshal_values(val_data, h.val_marshal_type, h.val_first,
                                    h.rows)
        return cls(h.tsid, ts, vals, h.scale, h.precision_bits)


def rows_to_blocks(tsid: TSID, timestamps: np.ndarray, values_f: np.ndarray,
                   precision_bits: int = 64):
    """Split one series' sorted rows into <=8k-row blocks."""
    n = timestamps.size
    for i in range(0, n, MAX_ROWS_PER_BLOCK):
        j = min(i + MAX_ROWS_PER_BLOCK, n)
        yield Block.from_floats(tsid, timestamps[i:j], values_f[i:j],
                                precision_bits)
