"""Immutable part files (reference lib/storage/part.go:30-48,
metaindex_row.go, part_header.go:19).

Anatomy (same as the reference):
  timestamps.bin  concatenated timestamp payloads
  values.bin      concatenated value payloads
  index.bin       zstd index blocks of up to 256 BlockHeaders each
  metaindex.bin   zstd array of metaindex rows: (first_tsid, block_count,
                  index_offset, index_size, min_ts, max_ts)
  metadata.json   {rows, blocks, min_ts, max_ts}

Parts are written once to a .tmp dir, fsynced, then renamed — the atomic
immutable-part property that makes snapshots hardlinks (fs.go:71,182).
"""

from __future__ import annotations

import os
import struct
import zlib
from collections import OrderedDict

import numpy as np

from ..devtools import faultinject
from ..ops import compress as zstd
from ..utils import fs as fslib
from .block import Block, BlockHeader
from .tsid import TSID

#: re-exported: callers catch this to quarantine torn/corrupt parts
PartIntegrityError = fslib.IntegrityError

HEADERS_PER_INDEX_BLOCK = 256
_META_ROW = struct.Struct(">32sIQIqq")

# global budget for whole-part decoded-row memos (Part._dec), shared across
# every open part so many hot parts cannot pin unbounded RAM (the
# lib/blockcache 25%-of-RAM role); released on part close/GC.  Guarded by
# a locktrace-made lock so the happens-before sanitizer sees the seam
# (concurrent pool workers race to memoize different parts; a bare
# threading.Lock would carry no vector clocks).
from ..devtools.locktrace import make_lock as _make_lock

DEC_CACHE_TOTAL_BYTES = int(os.environ.get("VM_DEC_CACHE_TOTAL_MB",
                                           2048)) << 20
_dec_budget_lock = _make_lock("storage.part._dec_budget")
_dec_budget_used = 0


def _dec_budget_take(cost: int) -> bool:
    global _dec_budget_used
    with _dec_budget_lock:
        if _dec_budget_used + cost > DEC_CACHE_TOTAL_BYTES:
            return False
        _dec_budget_used += cost
        return True


def _dec_budget_release(cost: int) -> None:
    global _dec_budget_used
    with _dec_budget_lock:
        _dec_budget_used -= cost

# numpy mirror of BlockHeader's struct layout (">32sqqIhBBBqqQIQI"); the
# TSID's trailing 8 bytes are the metric_id (tsid.py _FMT ">IIQIIQ"), split
# out so header selection is pure array masking
def sorted_member_mask(mids_sorted, mids: np.ndarray) -> np.ndarray:
    """Membership mask of each metric id in the SORTED wanted-id array
    (None = everything matches). Shared by the file-part and in-memory
    columnar block selectors so their semantics cannot diverge."""
    if mids_sorted is None:
        return np.ones(mids.shape, bool)
    if len(mids_sorted) == 0:
        return np.zeros(mids.shape, bool)
    pos = np.searchsorted(mids_sorted, mids)
    pos_c = np.minimum(pos, len(mids_sorted) - 1)
    return (mids_sorted[pos_c] == mids) & (pos < len(mids_sorted))


def _clip_gather(mids, scales, ts_src, m_src, bstart, bend, min_ts, max_ts,
                 unchanged=None):
    """Shared core of the row-granular time clip: block i of the piece
    lives at rows [bstart[i], bend[i]) of ts_src/m_src. Keeps only samples
    in [min_ts, max_ts], drops emptied blocks, densely gathers survivors.
    Returns (mids, cnts, scales, ts, mants) — or `unchanged` verbatim when
    nothing clips (callers pass their no-copy representation)."""
    k = int(bstart.size)
    lo = -(1 << 62) if min_ts is None else min_ts
    hi = (1 << 62) if max_ts is None else max_ts
    from .. import native as _native
    if _native.available():
        ts_src = np.ascontiguousarray(ts_src)
        m_src = np.ascontiguousarray(m_src)
        keep_lo, keep_hi = _native.clip_blocks(ts_src, bstart, bend, lo, hi)
    else:
        keep_lo = np.empty(k, np.int64)
        keep_hi = np.empty(k, np.int64)
        for i in range(k):
            a, b = int(bstart[i]), int(bend[i])
            seg = ts_src[a:b]
            keep_lo[i] = a + np.searchsorted(seg, lo, side="left")
            keep_hi[i] = a + np.searchsorted(seg, hi, side="right")
    new_cnts = keep_hi - keep_lo
    kept = int(new_cnts.sum())
    if unchanged is not None and kept == int(bend[-1] - bstart[0]) \
            and bool((bend[:-1] == bstart[1:]).all()):
        return unchanged
    nz = new_cnts > 0
    if not nz.all():
        mids, scales = mids[nz], scales[nz]
        keep_lo, keep_hi = keep_lo[nz], keep_hi[nz]
        new_cnts = new_cnts[nz]
    if kept == 0:
        return (mids, new_cnts, scales, np.zeros(0, np.int64),
                np.zeros(0, np.int64))
    if _native.available():
        ts_k, m_k = _native.gather_rows2(ts_src, m_src, keep_lo, keep_hi,
                                         kept)
    else:
        excl = np.cumsum(new_cnts) - new_cnts
        pos = np.repeat(keep_lo - excl, new_cnts) + \
            np.arange(kept, dtype=np.int64)
        ts_k, m_k = ts_src[pos], m_src[pos]
    return mids, new_cnts, scales, ts_k, m_k


def _piece_to_float(piece):
    """Mantissa piece (mids, cnts, scales, ts, mants) -> FLOAT piece
    (mids, cnts, ts, vals_f64), converting per block with the block
    exponent — the exact per-(value, exponent) conversion the split
    path's decode phase applies globally, so fused-mode pieces coming
    from fallback sub-paths stay bit-identical to the oracle."""
    mids, cnts, scales, ts, m = piece
    vals = np.empty(m.size, np.float64)
    goff = np.empty(cnts.size + 1, np.int64)
    goff[0] = 0
    np.cumsum(cnts, out=goff[1:])
    from .. import native as _native
    if _native.available():
        _native.decimal_to_float_blocks(
            np.ascontiguousarray(m), goff,
            np.ascontiguousarray(scales, dtype=np.int64), vals)
    else:
        from ..ops import decimal as dec_ops
        dec_ops.decimal_to_float_blocks_py(m, goff, scales, vals)
    return mids, cnts, ts, vals


def clip_piece(mids, cnts, scales, ts_all, m_all, min_ts, max_ts):
    """Row-granular time clip of one collected piece: keep only samples in
    [min_ts, max_ts] (the part_search.go pruning taken down to rows, so a
    tail fetch of M samples costs O(M) downstream — float conversion and
    (S, N) assembly never see out-of-range rows). Blocks left empty are
    dropped. No-ops (returning the inputs unchanged) when nothing clips."""
    k = int(cnts.size)
    if k == 0 or ts_all.size == 0:
        return mids, cnts, scales, ts_all, m_all
    goff = np.empty(k + 1, np.int64)
    goff[0] = 0
    np.cumsum(cnts, out=goff[1:])
    return _clip_gather(mids, scales, ts_all, m_all, goff[:-1].copy(),
                        goff[1:].copy(), min_ts, max_ts,
                        unchanged=(mids, cnts, scales, ts_all, m_all))


_HDR_DTYPE = np.dtype([
    ("tsid_pre", "S24"), ("mid", ">u8"),
    ("min_ts", ">i8"), ("max_ts", ">i8"), ("rows", ">u4"),
    ("scale", ">i2"), ("prec", "u1"), ("ts_mt", "u1"), ("val_mt", "u1"),
    ("ts_first", ">i8"), ("val_first", ">i8"),
    ("ts_off", ">u8"), ("ts_size", ">u4"), ("val_off", ">u8"),
    ("val_size", ">u4")])


class MetaindexRow:
    __slots__ = ("first_tsid", "block_count", "index_offset", "index_size",
                 "min_ts", "max_ts")


class PartWriter:
    """Streams blocks (sorted by (tsid, min_ts)) into a new part dir."""

    def __init__(self, path: str, resolution_ms: int = 0):
        self.path = path
        #: sample resolution this part stores: 0 = raw samples; >0 = one
        #: aggregated sample per resolution_ms bucket (downsampled tier)
        self.resolution_ms = resolution_ms
        self.tmp = path + ".tmp"
        os.makedirs(self.tmp, exist_ok=True)
        self._ts_f = open(os.path.join(self.tmp, "timestamps.bin"), "wb")
        self._val_f = open(os.path.join(self.tmp, "values.bin"), "wb")
        self._idx_f = open(os.path.join(self.tmp, "index.bin"), "wb")
        self._meta_rows = bytearray()
        self._hdrs: list[bytes] = []
        self._hdr_block_first: TSID | None = None
        self._hdr_min_ts = 1 << 62
        self._hdr_max_ts = -(1 << 62)
        self.rows = 0
        self.blocks = 0
        self.min_ts = 1 << 62
        self.max_ts = -(1 << 62)
        self._prev_key = None
        # incremental per-file crc32, folded as bytes stream out: the
        # finalize checksum costs no re-read of the part
        self._crc = {"timestamps.bin": 0, "values.bin": 0, "index.bin": 0}

    def write_block(self, blk: Block) -> None:
        h, ts_data, val_data = blk.marshal()
        self._write_marshaled(blk.tsid, h, ts_data, val_data)

    def write_blocks_bulk(self, blocks: list[Block]) -> None:
        """Marshal + write a (tsid, min_ts)-sorted run of blocks with ONE
        native call per stream (timestamps, mantissas) instead of
        per-block Python — the flush hot path spends its time in encode,
        and per-block overhead dominates at scrape-sized blocks. Falls
        back to write_block when the native codec is absent or a block
        needs the lossy (<64-bit precision) path."""
        from .. import native
        if (len(blocks) < 8 or not native.available() or
                any(b.precision_bits < 64 for b in blocks)):
            for b in blocks:
                self.write_block(b)
            return
        from ..ops.encoding import (MIN_COMPRESSIBLE_BLOCK_SIZE,
                                    _MIN_COMPRESS_RATIO, MarshalType, zstd)
        K = len(blocks)
        counts = np.fromiter((b.timestamps.size for b in blocks),
                             np.int64, K)
        offs = np.empty(K + 1, np.int64)
        offs[0] = 0
        np.cumsum(counts, out=offs[1:])
        ts_all = np.concatenate([b.timestamps for b in blocks])
        m_all = np.concatenate([np.asarray(b.values, np.int64)
                                for b in blocks])
        ts_pay, ts_t, ts_first, ts_len = native.marshal_i64_many(
            ts_all, offs)
        v_pay, v_t, v_first, v_len = native.marshal_i64_many(m_all, offs)
        ts_off = np.empty(K + 1, np.int64)
        ts_off[0] = 0
        np.cumsum(ts_len, out=ts_off[1:])
        v_off = np.empty(K + 1, np.int64)
        v_off[0] = 0
        np.cumsum(v_len, out=v_off[1:])
        zstd_map = {int(MarshalType.NEAREST_DELTA):
                    MarshalType.ZSTD_NEAREST_DELTA,
                    int(MarshalType.NEAREST_DELTA2):
                    MarshalType.ZSTD_NEAREST_DELTA2}
        for i, blk in enumerate(blocks):
            ts_data = ts_pay[ts_off[i]:ts_off[i + 1]]
            val_data = v_pay[v_off[i]:v_off[i + 1]]
            ts_mt, val_mt = int(ts_t[i]), int(v_t[i])
            if len(ts_data) >= MIN_COMPRESSIBLE_BLOCK_SIZE and \
                    ts_mt in zstd_map:
                packed = zstd.compress(ts_data)
                if len(packed) * _MIN_COMPRESS_RATIO < len(ts_data):
                    ts_data, ts_mt = packed, int(zstd_map[ts_mt])
            if len(val_data) >= MIN_COMPRESSIBLE_BLOCK_SIZE and \
                    val_mt in zstd_map:
                packed = zstd.compress(val_data)
                if len(packed) * _MIN_COMPRESS_RATIO < len(val_data):
                    val_data, val_mt = packed, int(zstd_map[val_mt])
            h = BlockHeader()
            h.tsid = blk.tsid
            h.min_ts = int(blk.timestamps[0])
            h.max_ts = int(blk.timestamps[-1])
            h.rows = int(counts[i])
            h.scale = blk.scale
            h.precision_bits = blk.precision_bits
            h.ts_marshal_type = ts_mt
            h.val_marshal_type = val_mt
            h.ts_first = int(ts_first[i])
            h.val_first = int(v_first[i])
            h.ts_offset = h.val_offset = 0
            h.ts_size = len(ts_data)
            h.val_size = len(val_data)
            self._write_marshaled(blk.tsid, h, ts_data, val_data)

    def _write_marshaled(self, tsid, h, ts_data: bytes,
                         val_data: bytes) -> None:
        key = (tsid.sort_key(), h.min_ts)
        if self._prev_key is not None and key < self._prev_key:
            raise ValueError("part writer: blocks out of order")
        self._prev_key = key
        h.ts_offset = self._ts_f.tell()
        h.val_offset = self._val_f.tell()
        self._ts_f.write(ts_data)
        self._val_f.write(val_data)
        self._crc["timestamps.bin"] = zlib.crc32(ts_data,
                                                 self._crc["timestamps.bin"])
        self._crc["values.bin"] = zlib.crc32(val_data,
                                             self._crc["values.bin"])
        if self._hdr_block_first is None:
            self._hdr_block_first = tsid
        self._hdrs.append(h.marshal())
        self._hdr_min_ts = min(self._hdr_min_ts, h.min_ts)
        self._hdr_max_ts = max(self._hdr_max_ts, h.max_ts)
        self.rows += h.rows
        self.blocks += 1
        self.min_ts = min(self.min_ts, h.min_ts)
        self.max_ts = max(self.max_ts, h.max_ts)
        if len(self._hdrs) >= HEADERS_PER_INDEX_BLOCK:
            self._flush_index_block()

    def _flush_index_block(self):
        if not self._hdrs:
            return
        data = zstd.compress(b"".join(self._hdrs))
        off = self._idx_f.tell()
        self._meta_rows += _META_ROW.pack(
            self._hdr_block_first.marshal(), len(self._hdrs), off, len(data),
            self._hdr_min_ts, self._hdr_max_ts)
        self._idx_f.write(data)
        self._crc["index.bin"] = zlib.crc32(data, self._crc["index.bin"])
        self._hdrs = []
        self._hdr_block_first = None
        self._hdr_min_ts = 1 << 62
        self._hdr_max_ts = -(1 << 62)

    def close(self) -> str:
        """Finalize: fsync everything, record per-file checksums in
        metadata.json, rename into place, fsync the parent dir (the
        rename alone is atomic but not durable).  Crashpoints bracket
        the rename so the kill -9 matrix can die on either side of the
        publish instant."""
        self._flush_index_block()
        for f in (self._ts_f, self._val_f, self._idx_f):
            f.flush()
            os.fsync(f.fileno())
            f.close()
        mi_data = zstd.compress(bytes(self._meta_rows))
        with open(os.path.join(self.tmp, "metaindex.bin"), "wb") as f:
            f.write(mi_data)
            f.flush()
            os.fsync(f.fileno())
        sums = dict(self._crc)
        sums["metaindex.bin"] = zlib.crc32(mi_data)
        fslib.write_meta_json(
            os.path.join(self.tmp, "metadata.json"),
            {"rows": self.rows, "blocks": self.blocks,
             "min_ts": self.min_ts, "max_ts": self.max_ts,
             "resolutionMs": self.resolution_ms,
             "checksums": sums})
        faultinject.fire("part:finalize:pre_rename")
        fslib.rename_durable(self.tmp, self.path)
        faultinject.fire("part:finalize:post_rename")
        return self.path

    def abort(self):
        import shutil
        for f in (self._ts_f, self._val_f, self._idx_f):
            try:
                f.close()
            except OSError:
                pass
        shutil.rmtree(self.tmp, ignore_errors=True)


class Part:
    """Open immutable part: metaindex in RAM, payloads read on demand."""

    def __init__(self, path: str, trusted: bool = False):
        self.path = path
        # integrity gate BEFORE any parsing: a torn/bit-flipped part must
        # fail here with PartIntegrityError (the opener quarantines it),
        # never misparse into wrong data.  metadata.json self-verifies
        # via meta_crc; the four payload files verify against the crc32s
        # recorded at finalize.  `trusted` skips the payload re-read for
        # parts THIS process just finalized (it computed the checksums
        # moments ago; re-reading would double flush/merge I/O) — cold
        # opens always verify.
        meta = fslib.load_meta_json(os.path.join(path, "metadata.json"))
        if not trusted:
            fslib.verify_checksums(path, meta)
        self.rows = meta["rows"]
        self.blocks = meta["blocks"]
        self.min_ts = meta["min_ts"]
        self.max_ts = meta["max_ts"]
        # additive field (wire-schema ratchet): parts written before
        # downsampling existed are raw
        self.resolution_ms = meta.get("resolutionMs", 0)
        raw = zstd.decompress(open(os.path.join(path, "metaindex.bin"), "rb").read())
        self.meta_rows: list[MetaindexRow] = []
        for off in range(0, len(raw), _META_ROW.size):
            tsid_b, cnt, ioff, isize, mn, mx = _META_ROW.unpack_from(raw, off)
            r = MetaindexRow()
            r.first_tsid = TSID.unmarshal(tsid_b)
            r.block_count = cnt
            r.index_offset = ioff
            r.index_size = isize
            r.min_ts = mn
            r.max_ts = mx
            self.meta_rows.append(r)
        self._idx_f = open(os.path.join(path, "index.bin"), "rb")
        self._ts_f = open(os.path.join(path, "timestamps.bin"), "rb")
        self._val_f = open(os.path.join(path, "values.bin"), "rb")
        # read-only mmaps for the batched columnar decode (parts are
        # immutable, so the mapping never goes stale); size-0 files (all
        # blocks CONST) map to empty arrays
        import mmap as _mmap
        self._ts_buf = self._val_buf = None
        try:
            for attr, f in (("_ts_buf", self._ts_f),
                            ("_val_buf", self._val_f)):
                size = os.fstat(f.fileno()).st_size
                if size == 0:
                    setattr(self, attr, np.zeros(0, dtype=np.uint8))
                else:
                    mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
                    setattr(self, attr, np.frombuffer(mm, dtype=np.uint8))
        except (OSError, ValueError):
            self._ts_buf = self._val_buf = None  # fall back to pread path
        from ..devtools.locktrace import make_lock
        self._lock = make_lock("storage.Part._lock")
        # serializes the one-time header-column build: with the shared
        # work pool, two workers routinely hit a cold part at once, and
        # racing duplicate builds would double the index decompression
        # (distinct from _lock, which read_headers takes inside the build)
        self._hdr_cols_lock = make_lock("storage.Part._hdr_cols_lock")
        # parts are immutable, so both caches never go stale (the reference
        # keeps compressed blocks in lib/blockcache sized to 25% RAM; here we
        # cache the *decoded* form so warm queries skip unmarshal entirely)
        self._hdr_cache: dict[int, list[BlockHeader]] = {}
        self._block_cache: "OrderedDict[tuple, Block]" = OrderedDict()
        self._block_cache_bytes = 0
        self._hdr_cols = None  # lazy columnar view of all block headers
        # memoized whole-part decode, tagged by representation:
        # ("mant", ts, mantissas, goff) from the split collect path or
        # ("float", ts, float64 values, goff) from the fused assemble
        # kernel; a memo only short-circuits the mode that can use it
        self._dec = None
        self._dec_cost = 0
        # memoized block-membership masks keyed by the wanted-id set:
        # a rolling refresh selects the SAME series every step, so the
        # O(#blocks) membership scan runs once per id set and only the
        # (cheap, vectorized) time clip reruns per refresh
        self._member_memo: "OrderedDict[tuple, np.ndarray]" = OrderedDict()

    def close(self):
        self._release_dec()
        for f in (self._idx_f, self._ts_f, self._val_f):
            f.close()

    def _release_dec(self):
        with self._lock:
            cost, self._dec_cost = self._dec_cost, 0
            self._dec = None
        if cost:
            _dec_budget_release(cost)

    def __del__(self):
        # merged-away parts are dropped by GC without close(); give their
        # memo budget back.  __del__ must never raise, and at interpreter
        # teardown module globals the release path touches may be gone
        try:
            self._release_dec()
        except (AttributeError, TypeError, OSError):
            pass

    def _read(self, f, off: int, size: int) -> bytes:
        with self._lock:
            f.seek(off)
            return f.read(size)

    # byte-bounded per part: decoded ts(8B) + mantissas(8B) + the memoized
    # float view (8B) per row; 768MB covers ~33M rows of hot data per part
    # (the reference's lib/blockcache budgets 25% of RAM globally).
    # Override with VM_BLOCK_CACHE_PART_MB for small hosts.
    MAX_BLOCK_CACHE_BYTES = int(os.environ.get(
        "VM_BLOCK_CACHE_PART_MB", 768)) << 20

    def read_headers(self, row: MetaindexRow) -> list[BlockHeader]:
        got = self._hdr_cache.get(row.index_offset)
        if got is not None:
            return got
        raw = zstd.decompress(self._read(self._idx_f, row.index_offset,
                                         row.index_size))
        hdrs = [BlockHeader.unmarshal(raw, o)
                for o in range(0, len(raw), BlockHeader.SIZE)]
        # benign memo race: racing fills decode the same immutable bytes
        # to equal header lists; last-writer-wins is identical content
        self._hdr_cache[row.index_offset] = hdrs  # vmt: disable=VMT015
        return hdrs

    def read_block(self, h: BlockHeader) -> Block:
        # offsets alone can collide: const-encoded payloads are 0 bytes, so
        # consecutive tiny blocks share offsets — include identity fields
        key = (h.tsid.metric_id, h.min_ts, h.rows, h.ts_offset, h.val_offset)
        with self._lock:
            blk = self._block_cache.get(key)
            if blk is not None:
                self._block_cache.move_to_end(key)
                return blk
        ts_data = self._read(self._ts_f, h.ts_offset, h.ts_size)
        val_data = self._read(self._val_f, h.val_offset, h.val_size)
        blk = Block.unmarshal(h, ts_data, val_data)
        # decoded arrays are shared across queries: freeze them so an
        # accidental in-place mutation fails loudly instead of corrupting
        blk.timestamps.setflags(write=False)
        blk.values.setflags(write=False)
        cost = 24 * h.rows
        with self._lock:
            if key not in self._block_cache:
                self._block_cache_bytes += cost
            self._block_cache[key] = blk
            self._block_cache.move_to_end(key)
            while self._block_cache_bytes > self.MAX_BLOCK_CACHE_BYTES and \
                    len(self._block_cache) > 1:
                _, old = self._block_cache.popitem(last=False)
                self._block_cache_bytes -= 24 * old.rows
        return blk

    def iter_headers(self, tsid_set: set | None = None,
                     min_ts: int | None = None, max_ts: int | None = None,
                     tsid_lo=None, tsid_hi=None):
        """Yield BlockHeaders matching the tsid set / time range, in
        (tsid, min_ts) order (partSearch analog). Metaindex rows are pruned
        by time range and, when tsid_lo/tsid_hi sort keys are given, by the
        first_tsid directory (blocks are (tsid, min_ts)-sorted)."""
        rows = self.meta_rows
        for i, row in enumerate(rows):
            if min_ts is not None and row.max_ts < min_ts:
                continue
            if max_ts is not None and row.min_ts > max_ts:
                continue
            if tsid_hi is not None and row.first_tsid.sort_key() > tsid_hi:
                break
            if tsid_lo is not None and i + 1 < len(rows) and \
                    rows[i + 1].first_tsid.sort_key() <= tsid_lo:
                continue  # whole row precedes the wanted tsid range
            for h in self.read_headers(row):
                if tsid_set is not None and h.tsid.metric_id not in tsid_set:
                    continue
                if min_ts is not None and h.max_ts < min_ts:
                    continue
                if max_ts is not None and h.min_ts > max_ts:
                    continue
                yield h

    def iter_blocks(self, tsid_set=None, min_ts=None, max_ts=None,
                    tsid_lo=None, tsid_hi=None):
        for h in self.iter_headers(tsid_set, min_ts, max_ts, tsid_lo, tsid_hi):
            yield self.read_block(h)

    def unique_tsids(self) -> list[TSID]:
        """Every distinct TSID referenced by this part's blocks (the
        registration manifest a part migration must ship alongside the
        bytes — metric_ids are node-local counters, so the receiving
        node cannot resolve them without it)."""
        out: dict[int, TSID] = {}
        for h in self.iter_headers():
            t = h.tsid
            out.setdefault(t.metric_id, t)
        return list(out.values())

    def file_bytes(self) -> int:
        """Total on-disk payload bytes (migration sizing/accounting)."""
        total = 0
        for name in os.listdir(self.path):
            try:
                total += os.path.getsize(os.path.join(self.path, name))
            except OSError:
                pass
        return total

    def header_columns(self):
        """Columnar view of every block header, built ONCE per part
        (immutable): header selection for the batched fetch becomes pure
        numpy masking instead of per-header Python objects."""
        hc = self._hdr_cols
        if hc is None:
            with self._hdr_cols_lock:
                hc = self._hdr_cols
                if hc is not None:
                    return hc
                bufs = []
                for row in self.meta_rows:
                    raw = zstd.decompress(self._read(self._idx_f,
                                                     row.index_offset,
                                                     row.index_size))
                    bufs.append(np.frombuffer(raw, dtype=_HDR_DTYPE))
                arr = (np.concatenate(bufs) if bufs
                       else np.zeros(0, dtype=_HDR_DTYPE))
                hc = {k: arr[k].astype(np.int64)
                      for k in ("mid", "min_ts", "max_ts", "rows", "scale",
                                "ts_first", "val_first", "ts_off", "ts_size",
                                "val_off", "val_size")}
                hc["ts_mt"] = arr["ts_mt"].astype(np.int32)
                hc["val_mt"] = arr["val_mt"].astype(np.int32)
                self._hdr_cols = hc
        return hc

    def collect_columns(self, mids_sorted, min_ts, max_ts):
        """Vectorized header selection + ONE native decode pass over every
        matched block, row-clipped to [min_ts, max_ts]. Returns (mids,
        cnts, scales, ts_concat, mant_concat); None when the native path is
        unavailable (caller falls back to the per-header object path);
        False when the vectorized path RAN and nothing matched (caller
        skips this part — do not collapse the two sentinels,
        Partition.collect_columns branches on them).

        When a whole-part decode fits MAX_BLOCK_CACHE_BYTES, the decoded
        (ts, mantissa) columns are memoized — the part is immutable, so
        every later fetch (rolling dashboard refreshes, cache tail merges,
        device tile slice loads) is a clip+gather with NO decode at all
        (the lib/blockcache role, but holding decoded rows)."""
        from .. import native as _native
        if self._ts_buf is None or not _native.available():
            return None
        if (min_ts is not None and self.max_ts < min_ts) or \
                (max_ts is not None and self.min_ts > max_ts):
            # suffix-aware early-out: a part wholly outside the tail
            # window never builds header columns or scans membership
            return False
        hc, lo, hi, idx = self._select_blocks(mids_sorted, min_ts, max_ts)
        if idx.size == 0:
            return False
        dec = self._dec
        if dec is not None and dec[0] == "mant":
            _, ts_full, m_full, goff_full = dec
            piece = _clip_gather(
                np.ascontiguousarray(hc["mid"][idx]),
                np.ascontiguousarray(hc["scale"][idx]),
                ts_full, m_full, goff_full[idx], goff_full[idx + 1],
                min_ts, max_ts)
            return piece if piece[3].size else False
        ts_mt = np.ascontiguousarray(hc["ts_mt"][idx])
        val_mt = np.ascontiguousarray(hc["val_mt"][idx])
        if not self._compressed_decodable(idx, ts_mt, val_mt):
            return None  # compressed payloads need a codec this build lacks
        cnt = np.ascontiguousarray(hc["rows"][idx])
        total = int(cnt.sum())
        ts_out = np.empty(total, np.int64)
        m_out = np.empty(total, np.int64)
        _native.decode_blocks(
            self._ts_buf, np.ascontiguousarray(hc["ts_off"][idx]),
            np.ascontiguousarray(hc["ts_size"][idx]), ts_mt,
            np.ascontiguousarray(hc["ts_first"][idx]), cnt, ts_out,
            validate_ts=True)
        _native.decode_blocks(
            self._val_buf, np.ascontiguousarray(hc["val_off"][idx]),
            np.ascontiguousarray(hc["val_size"][idx]), val_mt,
            np.ascontiguousarray(hc["val_first"][idx]), cnt, m_out,
            validate_ts=False)
        if idx.size == hc["mid"].size:
            self._maybe_memoize("mant", ts_out, m_out, cnt, idx.size, total)
        return clip_piece(np.ascontiguousarray(hc["mid"][idx]), cnt,
                          np.ascontiguousarray(hc["scale"][idx]),
                          ts_out, m_out, min_ts, max_ts)

    def _select_blocks(self, mids_sorted, min_ts, max_ts):
        """Shared header selection of the batched read paths: returns
        (hc, lo, hi, idx) where idx lists the blocks overlapping
        [min_ts, max_ts] for the wanted metric ids.  The membership mask
        is memoized per id set (suffix-aware fetch: a rolling refresh's
        repeated identical series set pays only the time clip)."""
        hc = self.header_columns()
        lo = -(1 << 62) if min_ts is None else min_ts
        hi = (1 << 62) if max_ts is None else max_ts
        mm = self._member_mask(mids_sorted, hc)
        mask = (hc["max_ts"] >= lo) & (hc["min_ts"] <= hi) & mm
        return hc, lo, hi, np.flatnonzero(mask)

    def _member_mask(self, mids_sorted, hc) -> np.ndarray:
        if mids_sorted is None:
            return sorted_member_mask(mids_sorted, hc["mid"])
        import xxhash
        key = (xxhash.xxh64_intdigest(np.ascontiguousarray(
            mids_sorted).tobytes()), int(mids_sorted.size))
        with self._lock:
            mm = self._member_memo.get(key)
            if mm is not None:
                self._member_memo.move_to_end(key)
                return mm
        mm = sorted_member_mask(mids_sorted, hc["mid"])
        mm.setflags(write=False)
        with self._lock:
            self._member_memo[key] = mm
            while len(self._member_memo) > 4:
                self._member_memo.popitem(last=False)
        return mm

    def _maybe_memoize(self, kind, ts_arr, data_arr, cnt, n_blocks,
                       total) -> None:
        """Publish a whole-part decode as the tagged _dec memo when the
        global budget allows (shared by the mantissa and float paths;
        loser of the publish race gives its budget back)."""
        if self._dec is not None or not _dec_budget_take(16 * total):
            return
        goff_full = np.empty(n_blocks + 1, np.int64)
        goff_full[0] = 0
        np.cumsum(cnt, out=goff_full[1:])
        ts_arr.setflags(write=False)
        data_arr.setflags(write=False)
        with self._lock:
            if self._dec is None:
                self._dec = (kind, ts_arr, data_arr, goff_full)
                self._dec_cost = 16 * total
            else:
                _dec_budget_release(16 * total)

    def _compressed_decodable(self, idx, ts_mt, val_mt) -> bool:
        """Whether every compressed (MarshalType>=5) payload among the
        selected blocks can be inflated natively: peek each one's leading
        byte (zstd frames start 0x28, the zlib fallback streams 0x78) and
        check the matching vm_decompress_caps bit. This replaces the old
        all-or-nothing has_zstd() exclusion: zstd AND zlib-compressed
        blocks now ride the native path whenever the runtime codec
        resolved."""
        from .. import native as _native
        if not (bool((ts_mt >= 5).any()) or bool((val_mt >= 5).any())):
            return True
        caps = _native.decompress_caps()
        if caps & 3 == 3:
            return True
        hc = self.header_columns()
        for buf, off_k, mt in ((self._ts_buf, "ts_off", ts_mt),
                               (self._val_buf, "val_off", val_mt)):
            comp = np.flatnonzero(mt >= 5)
            if comp.size == 0:
                continue
            first = buf[np.ascontiguousarray(hc[off_k][idx])[comp]]
            is_zstd = first == 0x28
            if bool(is_zstd.any()) and not caps & 1:
                return False
            if bool((~is_zstd).any()) and not caps & 2:
                return False
        return True

    def _hdrs_compressed_decodable(self, hdrs) -> bool:
        """Per-header twin of _compressed_decodable for the list-of-
        BlockHeaders fallback path (read_blocks_columns)."""
        from .. import native as _native
        caps = _native.decompress_caps()
        if caps & 3 == 3:
            return True
        for h in hdrs:
            for mt, off, buf in (
                    (int(h.ts_marshal_type), h.ts_offset, self._ts_buf),
                    (int(h.val_marshal_type), h.val_offset, self._val_buf)):
                if mt >= 5 and \
                        not caps & (1 if buf[off] == 0x28 else 2):
                    return False
        return True

    def assemble_columns(self, mids_sorted, min_ts, max_ts):
        """Fused native part read (vm_assemble_part): ONE GIL-released
        call decodes every selected block's timestamp+value streams from
        the mmap'd part, clips rows to [min_ts, max_ts], converts kept
        mantissas straight to float64 with the block exponents and
        compacts into freshly allocated columns — no per-block Python, no
        intermediate mantissa arrays, fully-clipped blocks never decode
        their value stream. Returns a FLOAT piece (mids, cnts, ts,
        vals_f64); None when the native fused path is unavailable (caller
        falls back to the split path and converts); False when it RAN and
        nothing matched.

        An unclipped whole-part call memoizes the decoded float columns
        (same budget as the mantissa memo), so warm rolling-window
        refreshes are a native clip+gather with no decode at all."""
        from .. import native as _native
        if self._ts_buf is None or not _native.available():
            return None
        if (min_ts is not None and self.max_ts < min_ts) or \
                (max_ts is not None and self.min_ts > max_ts):
            # suffix-aware early-out: a part wholly outside the tail
            # window never builds header columns or scans membership
            return False
        hc, lo, hi, idx = self._select_blocks(mids_sorted, min_ts, max_ts)
        if idx.size == 0:
            return False
        dec = self._dec
        if dec is not None:
            kind, ts_full, data_full, goff_full = dec
            mids, cnts, scales, ts_k, d_k = _clip_gather(
                np.ascontiguousarray(hc["mid"][idx]),
                np.ascontiguousarray(hc["scale"][idx]),
                ts_full,
                data_full.view(np.int64) if kind == "float" else data_full,
                goff_full[idx], goff_full[idx + 1], min_ts, max_ts)
            if not ts_k.size:
                return False
            if kind == "float":
                return mids, cnts, ts_k, d_k.view(np.float64)
            return _piece_to_float((mids, cnts, scales, ts_k, d_k))
        ts_mt = np.ascontiguousarray(hc["ts_mt"][idx])
        val_mt = np.ascontiguousarray(hc["val_mt"][idx])
        if not self._compressed_decodable(idx, ts_mt, val_mt):
            return None
        cnt = np.ascontiguousarray(hc["rows"][idx])
        total = int(cnt.sum())
        mids = np.ascontiguousarray(hc["mid"][idx])
        scales = np.ascontiguousarray(hc["scale"][idx])
        # when the query touches every block of the part, decode UNCLIPPED
        # so the whole-part float memo can build even though this query
        # clips rows (the split path memoizes its pre-clip decode the same
        # way) — the query is then served by clip+gather over the decode,
        # and every later rolling refresh skips the decode entirely
        whole = idx.size == hc["mid"].size
        klo, khi = (-(1 << 62), 1 << 62) if whole else (lo, hi)
        kept, ts_k, vals_k = _native.assemble_part(
            self._ts_buf, self._val_buf,
            np.ascontiguousarray(hc["ts_off"][idx]),
            np.ascontiguousarray(hc["ts_size"][idx]), ts_mt,
            np.ascontiguousarray(hc["ts_first"][idx]),
            np.ascontiguousarray(hc["val_off"][idx]),
            np.ascontiguousarray(hc["val_size"][idx]), val_mt,
            np.ascontiguousarray(hc["val_first"][idx]),
            cnt, scales, klo, khi)
        if whole:
            self._maybe_memoize("float", ts_k, vals_k, cnt, idx.size, total)
            goff = np.empty(idx.size + 1, np.int64)
            goff[0] = 0
            np.cumsum(cnt, out=goff[1:])
            mids, cnts, _, ts_c, d_c = _clip_gather(
                mids, scales, ts_k, vals_k.view(np.int64), goff[:-1],
                goff[1:], min_ts, max_ts,
                unchanged=(mids, cnt, scales, ts_k,
                           vals_k.view(np.int64)))
            if not ts_c.size:
                return False
            return mids, cnts, ts_c, d_c.view(np.float64)
        if ts_k.size == 0:
            return False
        nz = kept > 0
        if not nz.all():
            return mids[nz], kept[nz], ts_k, vals_k
        return mids, kept, ts_k, vals_k

    def read_blocks_columns(self, hdrs: list[BlockHeader]):
        """Batched decode of many blocks in ONE native call per stream
        (vm_decode_blocks): returns (ts_concat int64, mant_concat int64),
        laid out block-after-block in `hdrs` order. The netstorage
        unpack-worker analog (netstorage.go:374-404) — here the workers are
        replaced by a single vectorized native pass over the mmap'd part.
        Falls back to the per-block Python path when native/mmap is
        unavailable."""
        from .. import native as _native
        K = len(hdrs)
        cnt = np.fromiter((h.rows for h in hdrs), np.int64, K)
        total = int(cnt.sum())
        zstd_blocks = any(int(h.ts_marshal_type) >= 5 or
                          int(h.val_marshal_type) >= 5 for h in hdrs)
        if self._ts_buf is None or not _native.available() or \
                (zstd_blocks and not self._hdrs_compressed_decodable(hdrs)):
            blocks = [self.read_block(h) for h in hdrs]
            ts_all = (np.concatenate([b.timestamps for b in blocks])
                      if blocks else np.zeros(0, np.int64))
            m_all = (np.concatenate([b.values for b in blocks])
                     if blocks else np.zeros(0, np.int64))
            return ts_all, m_all
        ts_out = np.empty(total, np.int64)
        m_out = np.empty(total, np.int64)
        off = np.fromiter((h.ts_offset for h in hdrs), np.int64, K)
        sz = np.fromiter((h.ts_size for h in hdrs), np.int64, K)
        mt = np.fromiter((int(h.ts_marshal_type) for h in hdrs), np.int32, K)
        first = np.fromiter((h.ts_first for h in hdrs), np.int64, K)
        _native.decode_blocks(self._ts_buf, off, sz, mt, first, cnt, ts_out,
                              validate_ts=True)
        off = np.fromiter((h.val_offset for h in hdrs), np.int64, K)
        sz = np.fromiter((h.val_size for h in hdrs), np.int64, K)
        mt = np.fromiter((int(h.val_marshal_type) for h in hdrs), np.int32, K)
        first = np.fromiter((h.val_first for h in hdrs), np.int64, K)
        _native.decode_blocks(self._val_buf, off, sz, mt, first, cnt, m_out,
                              validate_ts=False)
        return ts_out, m_out
