"""Hourly/daily series cardinality limiters (reference
lib/bloomfilter/{filter,limiter}.go, wired at lib/storage/storage.go:2136
registerSeriesCardinality).

A limiter admits at most max_series distinct metricIDs per rotation
window; rows for ids beyond that are dropped with a counter. Membership is
a bloom filter sized at 16 bits per item with k=4 probes (the reference's
bloomfilter sizing), reset at each window rollover.
"""

from __future__ import annotations

from ..devtools.locktrace import make_lock
from ..utils import fasttime

K_PROBES = 4
BITS_PER_ITEM = 16


class BloomLimiter:
    def __init__(self, max_series: int, rotation_s: int, name: str = ""):
        self.max_series = max_series
        self.rotation_s = rotation_s
        self.name = name
        # floor well above BITS_PER_ITEM*k so tiny limits (tests, strict
        # quotas) don't degenerate into false-positive admissions
        nbits = max(max_series * BITS_PER_ITEM, 4096)
        self._nbits = nbits
        self._bits = bytearray((nbits + 7) // 8)
        self._tracked = 0
        self._bucket = fasttime.unix_timestamp() // rotation_s
        self.rows_dropped = 0
        # concurrent striped writers probe the same limiter; admissions
        # must be atomic or the budget can be oversubscribed
        self._lock = make_lock("storage.BloomLimiter._lock")

    def _rotate_if_needed_locked(self):
        b = fasttime.unix_timestamp() // self.rotation_s
        if b != self._bucket:
            self._bucket = b
            self._bits = bytearray(len(self._bits))
            self._tracked = 0

    def add(self, metric_id: int) -> bool:
        """True if the id is admitted (already tracked, or capacity left);
        False means the row must be dropped (limiter.go:62 Add)."""
        # splitmix64-style probe sequence off the (already well-mixed) id
        nbits = self._nbits
        h = (metric_id ^ (metric_id >> 33)) * 0xff51afd7ed558ccd & (2**64 - 1)
        probes = []
        for i in range(K_PROBES):
            h = (h + 0x9e3779b97f4a7c15) & (2**64 - 1)
            x = (h ^ (h >> 29)) * 0xbf58476d1ce4e5b9 & (2**64 - 1)
            pos = x % nbits
            probes.append((pos >> 3, 1 << (pos & 7)))
        with self._lock:
            self._rotate_if_needed_locked()
            bits = self._bits
            missing = [(byte, mask) for byte, mask in probes
                       if not bits[byte] & mask]
            if not missing:
                return True  # (probabilistically) already tracked
            if self._tracked >= self.max_series:
                self.rows_dropped += 1
                return False
            for byte, mask in missing:
                bits[byte] |= mask
            self._tracked += 1
            return True

    @property
    def current_series(self) -> int:
        with self._lock:
            self._rotate_if_needed_locked()
            return self._tracked

    def metrics(self) -> dict:
        p = f"vm_{self.name}_series_limit"
        return {
            f"{p}_max_series": self.max_series,
            f"{p}_current_series": self.current_series,
            f"{p}_rows_dropped_total": self.rows_dropped,
        }
