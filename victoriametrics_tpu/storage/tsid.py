"""TSID: the sortable numeric series identity (reference lib/storage/tsid.go:17,
generated at index_db.go:412).

Sort order clusters blocks of related series together on disk:
(metric_group_id, job_id, instance_id, metric_id). metric_id alone is
globally unique and is the key used by posting lists and caches.
"""

from __future__ import annotations

import struct
import time

import xxhash

from ..devtools.locktrace import make_lock

_FMT = struct.Struct(">IIQIIQ")  # account, project, group, job, instance, metric


class TSID:
    """Sort order starts with (account_id, project_id) so one tenant's
    blocks cluster together on disk (reference tsid.go:17 Less())."""

    __slots__ = ("account_id", "project_id", "metric_group_id", "job_id",
                 "instance_id", "metric_id")

    SIZE = _FMT.size

    def __init__(self, metric_group_id=0, job_id=0, instance_id=0,
                 metric_id=0, account_id=0, project_id=0):
        self.account_id = account_id
        self.project_id = project_id
        self.metric_group_id = metric_group_id
        self.job_id = job_id
        self.instance_id = instance_id
        self.metric_id = metric_id

    def marshal(self) -> bytes:
        return _FMT.pack(self.account_id, self.project_id,
                         self.metric_group_id, self.job_id, self.instance_id,
                         self.metric_id)

    @classmethod
    def unmarshal(cls, data: bytes, offset: int = 0) -> "TSID":
        a, p, g, j, i, m = _FMT.unpack_from(data, offset)
        return cls(g, j, i, m, a, p)

    def sort_key(self) -> tuple:
        return (self.account_id, self.project_id, self.metric_group_id,
                self.job_id, self.instance_id, self.metric_id)

    def __lt__(self, other):
        return self.sort_key() < other.sort_key()

    def __eq__(self, other):
        return self.sort_key() == other.sort_key()

    def __hash__(self):
        return hash(self.metric_id)

    def __repr__(self):
        return (f"TSID(g={self.metric_group_id:x}, j={self.job_id:x}, "
                f"i={self.instance_id:x}, m={self.metric_id:x})")


class MetricIDGenerator:
    """Unique metric_id source: coarse-time-seeded counter (reference
    generateUniqueMetricID uses an atomic counter seeded from nanotime so ids
    stay unique across restarts without persistence)."""

    def __init__(self):
        self._lock = make_lock("storage.MetricIDGenerator._lock")
        from ..utils import fasttime
        self._next = fasttime.unix_ns() & ((1 << 62) - 1)

    def next_id(self) -> int:
        with self._lock:
            self._next += 1
            return self._next

    def reserve_past(self, metric_id: int) -> None:
        """Advance the counter past a FOREIGN metric_id (series adopted
        from another node via part migration): ids this node generates
        later must never collide with ids it adopted."""
        with self._lock:
            if metric_id > self._next:
                self._next = metric_id


def generate_tsid(mn, metric_id: int, tenant=(0, 0)) -> TSID:
    """Derive the clustering hash fields from the metric name."""
    t = TSID(metric_id=metric_id, account_id=tenant[0], project_id=tenant[1])
    t.metric_group_id = xxhash.xxh64_intdigest(mn.metric_group)
    job = mn.get_label(b"job")
    if job:
        t.job_id = xxhash.xxh32_intdigest(job)
    inst = mn.get_label(b"instance")
    if inst:
        t.instance_id = xxhash.xxh32_intdigest(inst)
    return t
