"""Top-level Storage (reference lib/storage/storage.go:43,180).

Owns: monthly-partitioned data table, inverted index, TSID cache, per-day
index cache, deletion tombstones, snapshots, background flushers, retention.

The public API mirrors the reference's Storage surface: AddRows, Search
(here: search_series / iter_series_blocks), SearchLabelNames/Values,
DeleteSeries, CreateSnapshot, RegisterMetricNames, GetTSDBStatus, ForceFlush/
ForceMerge — re-shaped for a Python host plane feeding a TPU query engine.
"""

from __future__ import annotations

import fcntl
import os
import shutil
import threading
import time
from contextlib import contextmanager

import numpy as np

from ..devtools import faultinject
from ..devtools.locktrace import make_lock, make_rlock
from ..utils import costacc, fasttime, flightrec, logger
from ..utils import metrics as metricslib
from ..utils import workpool
from ..utils.deadline import Budget, DeadlineExceededError  # noqa: F401 —
# DeadlineExceededError re-exported: RPC handlers and tests catch the
# storage-side abort through the storage module's public surface
from ..utils.workingset import WorkingSetCache
from .dedup import deduplicate
from .index_db import IndexDB, date_of_ms
from .metric_name import MetricName
from .table import Table
from .tag_filters import TagFilter
from .tsid import MetricIDGenerator, TSID, generate_tsid

DEFAULT_RETENTION_MS = 31 * 13 * 86_400_000  # ~13 months, like the reference

# per-phase fetch attribution (bench.py and /metrics read these): seconds
# spent in each stage of the columnar read path, labeled like the
# reference's per-stage vmselect metrics.  The fused VM_NATIVE_ASSEMBLE
# kernel merges collect+decode+clip into one native call per part — its
# time reports under phase="assemble_native" so the split-path labels
# (collect / decode) stay accurate for the fallback/oracle path instead
# of silently absorbing fused time.
_PHASE = {
    ph: metricslib.REGISTRY.float_counter(
        f'vm_fetch_phase_seconds_total{{phase="{ph}"}}')
    for ph in ("index_search", "collect", "decode", "assemble",
               "assemble_native", "queue_wait")
}
# phase="queue_wait" (time queued at the SearchGate before the fetch
# starts) is INCREMENTED in utils/workpool.SearchGate — listed here so
# the family is complete at import and the split sums to wall time

# write-path twin of _PHASE: where ingest time goes (the flush/merge
# phases are fed by partition.py / mergeset.py)
_ING_PHASE = {ph: metricslib.ingest_phase(ph)
              for ph in ("resolve", "register", "append")}
_INGEST_ROWS = metricslib.REGISTRY.counter("vm_ingest_rows_total")
_SHARD_WAIT = metricslib.REGISTRY.float_counter(
    "vm_ingest_shard_lock_wait_seconds_total")

#: fan per-day registrations across the pool only past this size (small
#: batches lose more to task handoff than they gain)
_FANOUT_MIN_REGS = 64

# storage-side deadline aborts (ROADMAP item 3): a search whose shipped
# budget expires mid-index-scan/mid-fetch stops HERE instead of burning
# the dead query's full server-side cost
_DEADLINE_ABORTS = metricslib.REGISTRY.counter(
    "vm_storage_deadline_aborts_total")


class _ScanBudget(Budget):
    """Budget whose clock checks double as the ``storage:scan`` chaos
    seam: an injected delay there dilates the scan so the chaos suite
    can prove a query aborts within ~one check interval of expiry."""

    __slots__ = ()

    def check(self) -> None:
        if faultinject.active():
            faultinject.fire("storage:scan")
        super().check()


class _IngestShard:
    """One registration stripe of the sharded write path (the
    rawRowsShards analog, partition.go): the per-day cache slice for
    metric ids with ``hash(metric_id) % N == index``, guarded by its own
    lock so concurrent writers (and the striped fan-out of one large
    batch) only contend when they touch the same stripe."""

    __slots__ = ("lock", "day_cache")

    def __init__(self):
        # one role name for every stripe: same-role edges are exempt
        # from lock-order cycle checks (stripes are never nested)
        self.lock = make_lock("storage.Storage._ingest_shard")
        self.day_cache: set[tuple[int, int]] = set()  # (metric_id, date)


class _ColumnarSpace:
    """Per-tenant dense-id state for the columnar ingest path: a native
    byte-key -> id map plus per-id numpy columns (TSID sort-key fields,
    per-day index state, drop verdicts). Resolving a batch is ONE native
    call; everything downstream indexes these arrays.

    Drop verdicts are sticky per id (0 ok, 1 malformed key, 2 dropped by
    transform/relabel, 3 over cardinality budget at creation) — repeat rows
    of a dropped series are filtered with one mask, never re-judged."""

    __slots__ = ("keymap", "tsids", "acc", "proj", "grp", "job", "inst",
                 "mid", "drop", "last_date", "_cap", "lock", "retired")

    #: distinct raw keys per tenant space before the whole space is rebuilt
    #: — same bound (and rationale) as the legacy raw TSID cache clear at
    #: 1<<21 entries (add_rows): high-churn keys must not leak memory
    MAX_KEYS = 1 << 21

    def __init__(self):
        from .. import native
        self.keymap = native.KeyMap()
        # per-space lock: same-tenant columnar writers serialize HERE,
        # not on the storage-wide lock (cross-tenant ingest is parallel);
        # `retired` marks a rotated-out space whose key map is closed —
        # holders must re-fetch (pending chunks only read the numpy
        # columns, which stay alive)
        self.lock = make_lock("storage._ColumnarSpace.lock")
        self.retired = False
        self.tsids: list = []
        self._cap = 0
        z = np.zeros(0, np.uint64)
        self.acc = z
        self.proj = z.copy()
        self.grp = z.copy()
        self.job = z.copy()
        self.inst = z.copy()
        self.mid = z.copy()
        self.drop = np.zeros(0, np.uint8)
        self.last_date = np.zeros(0, np.int64)

    def _grow(self, need: int) -> None:
        """Amortized-doubling growth of the per-id columns (append_ids runs
        per new-series batch; O(total) reallocation there would make churny
        workloads quadratic)."""
        if need <= self._cap:
            return
        ncap = max(1024, self._cap * 2, need)
        for f in ("acc", "proj", "grp", "job", "inst", "mid", "drop",
                  "last_date"):
            old = getattr(self, f)
            new = np.empty(ncap, old.dtype)
            new[:len(self.tsids)] = old[:len(self.tsids)]
            setattr(self, f, new)
        self._cap = ncap

    def append_ids(self, tsids: list, drops: list) -> None:
        """Registers len(tsids) new ids (tsids[i] is None when drops[i]!=0)."""
        k = len(tsids)
        n = len(self.tsids)
        self._grow(n + k)
        for j, (t, d) in enumerate(zip(tsids, drops)):
            i = n + j
            if t is not None:
                self.acc[i] = t.account_id
                self.proj[i] = t.project_id
                self.grp[i] = t.metric_group_id
                self.job[i] = t.job_id
                self.inst[i] = t.instance_id
                self.mid[i] = t.metric_id
            else:
                self.acc[i] = self.proj[i] = self.grp[i] = 0
                self.job[i] = self.inst[i] = self.mid[i] = 0
            self.drop[i] = d
            self.last_date[i] = -(1 << 62)
        self.tsids.extend(tsids)

    def set_tsid(self, i: int, tsid) -> None:
        """Re-admits a previously dropped id (cardinality retry)."""
        self.tsids[i] = tsid
        self.acc[i] = tsid.account_id
        self.proj[i] = tsid.project_id
        self.grp[i] = tsid.metric_group_id
        self.job[i] = tsid.job_id
        self.inst[i] = tsid.instance_id
        self.mid[i] = tsid.metric_id
        self.drop[i] = 0
        self.last_date[i] = -(1 << 62)

    def close(self):
        # every caller holds self.lock via acquire/release bracketing the
        # static pass cannot see (_acquire_cspace returns with it HELD,
        # reset_columnar_spaces takes `with sp.lock`)
        km, self.keymap = self.keymap, None  # vmt: disable=VMT015
        if km is not None:
            km.close()


def _phase_lap(phase: str, t0: float) -> float:
    """Account wall time since t0 to a fetch phase (counter + flight
    event + the current query's CostTracker); returns the new t0."""
    now = time.perf_counter()
    _PHASE[phase].inc(now - t0)
    flightrec.rec("fetch:" + phase, t0, now - t0)
    costacc.lap("fetch:" + phase, now - t0)
    return now


def _ingest_lap(phase: str, t0: float) -> float:
    """Account wall time since t0 to an ingest phase; returns the new t0."""
    now = time.perf_counter()
    _ING_PHASE[phase].inc(now - t0)
    flightrec.rec("ingest:" + phase, t0, now - t0)
    return now


class SeriesData:
    """Decoded query result for one series."""

    __slots__ = ("metric_name", "timestamps", "values", "raw_name",
                 "_stale_blocks", "_maybe_stale")

    def __init__(self, metric_name: MetricName, timestamps: np.ndarray,
                 values: np.ndarray, raw_name: bytes | None = None,
                 stale_blocks=None, maybe_stale: bool | None = None):
        self.metric_name = metric_name
        self.timestamps = timestamps
        self.values = values
        self.raw_name = raw_name  # marshaled name (sort/fingerprint key)
        # lazily computed from the contributing blocks' memoized stale
        # scans: default_rollup (the common case) never consults it, so it
        # costs nothing there; sealed-part blocks amortize across queries
        self._stale_blocks = stale_blocks
        if maybe_stale is not None:  # precomputed by the columnar path
            self._maybe_stale = maybe_stale
        else:
            self._maybe_stale = None if stale_blocks is not None else True

    @property
    def maybe_stale(self) -> bool:
        """False when every contributing block is known stale-marker-free
        (block-level memo): lets the eval skip the per-query stale scan."""
        if self._maybe_stale is None:
            self._maybe_stale = any(b.has_stale()
                                    for b in self._stale_blocks)
            self._stale_blocks = None
        return self._maybe_stale


class Storage:
    def __init__(self, path: str, retention_ms: int = DEFAULT_RETENTION_MS,
                 dedup_interval_ms: int = 0, max_hourly_series: int = 0,
                 max_daily_series: int = 0, downsample: str | None = None):
        self.path = path
        self.retention_ms = retention_ms
        self.dedup_interval_ms = dedup_interval_ms
        # downsampling tiers (storage/downsample.py): offset:res[:keep],
        # finest first; None reads the VM_DOWNSAMPLE env grammar
        from . import downsample as _ds
        self.downsample_tiers = _ds.parse_spec(
            os.environ.get("VM_DOWNSAMPLE", "") if downsample is None
            else downsample)
        self._downsample_interval_s = float(
            os.environ.get("VM_DOWNSAMPLE_INTERVAL_S", "60"))
        self._last_downsample = time.monotonic()
        # per-request partial-RESOLUTION flag (reset_partial clears it):
        # set when a fetch fell back to a coarser tier than the query's
        # step allows (raw dropped, no satisfying tier)
        self._partial_res_flag = False
        from .cardinality import BloomLimiter
        self.hourly_limiter = (BloomLimiter(max_hourly_series, 3600, "hourly")
                               if max_hourly_series > 0 else None)
        self.daily_limiter = (BloomLimiter(max_daily_series, 86400, "daily")
                              if max_daily_series > 0 else None)
        os.makedirs(path, exist_ok=True)
        self._flock_f = open(os.path.join(path, "flock.lock"), "w")
        try:
            fcntl.flock(self._flock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except BlockingIOError:
            raise RuntimeError(f"storage at {path} is locked by another process")
        self._check_format()
        self.idb = IndexDB(os.path.join(path, "indexdb"))
        self.table = Table(os.path.join(path, "data"), dedup_interval_ms)
        # open-time integrity verdict, frozen for the process lifetime:
        # quarantine/open-error state only changes at open (see
        # last_partial) and the flag is read per query
        self._has_quarantine = bool(self.table.quarantined() or
                                    self.idb.quarantined())
        self._tsid_cache: dict[bytes, TSID] = {}
        # fast-path cache keyed by the UNMARSHALED label identity (the
        # reference's MetricNameRaw-keyed tsidCache, storage.go:1874): rows
        # with a cached label tuple skip MetricName construction entirely.
        # Two-generation rotation (workingsetcache analog) instead of a
        # multi-million-entry clear() on overflow.
        self._tsid_cache_raw = WorkingSetCache(1 << 21, "storage.tsid_raw")
        # per-tenant columnar id spaces (native key map + per-id numpy
        # state), lazily created by add_rows_columnar
        self._cspaces: dict[tuple, "_ColumnarSpace"] = {}
        # striped registration shards: the per-day cache is split by
        # hash(metric_id) % VM_INGEST_SHARDS, each slice with its own
        # lock (VM_INGEST_SHARDS=1 restores the single-stripe layout)
        self._shards = [_IngestShard()
                        for _ in range(workpool.configured_shards())]
        self._mid_gen = MetricIDGenerator()
        self._lock = make_rlock("storage.Storage._lock")
        self._stop = threading.Event()
        self._readonly = False
        self.rows_added = 0
        # bumped on every data mutation (ingest/delete/retention): cheap
        # content token for device tile-cache fingerprints
        self.data_version = 0
        # bumped only on mutations that REMOVE visible data (delete,
        # retention): append-only ingest keeps it stable so rolling device
        # tiles can advance incrementally instead of rebuilding
        self.structural_version = 0
        # (data_version, min inserted ts) per append batch, bounded: lets a
        # rolling tile ask "was anything since version v older than my
        # covered range?" (late/backfill data forces a rebuild)
        from collections import deque
        self._append_log: deque = deque(maxlen=4096)
        self._append_log_floor = 0  # appends at versions <= floor may be
        #                             missing from the bounded log
        # memoized name-resolution/row-order products per fetched id set
        # (suffix-aware fetch; see _resolve_ordered_names)
        from collections import OrderedDict
        self._name_memo: OrderedDict = OrderedDict()
        self._name_memo_lock = make_lock("storage.Storage._name_memo")
        self.slow_row_inserts = 0
        self.new_series_created = 0
        # metric-name usage stats + TYPE/HELP metadata (storage-resident
        # so cluster RPCs can serve them; lib/storage/metricnamestats)
        self._name_usage: dict = {}
        self.metadata: dict[str, dict] = {}
        from ..query.rollup_result_cache import next_storage_token
        self.cache_token = next_storage_token()
        # series this node must ALWAYS serve regardless of ring
        # ownership (parallel/ringfilter): adopted via part migration or
        # landed here by a write reroute — this node may hold the only
        # copy of some of their samples.  Persisted (append-only) so a
        # restart keeps serving them.
        self._ring_exempt: set[bytes] = set()
        self._ring_exempt_lock = make_lock("storage.Storage._ring_exempt")
        self._load_ring_exempt()
        # adopted-foreign-id watermark: the id generator's restart
        # uniqueness comes from nanotime reseeding, which only covers
        # LOCALLY generated ids — ids adopted from a clock-ahead node
        # must stay reserved across restarts too
        self._load_adopted_watermark()
        self._load_caches()
        # long-lived service timer, not hot-path fan-out: it owns the
        # periodic flush cadence and is joined cleanly in close() (the
        # daemon flag only covers processes that never call close)
        self._flusher = threading.Thread(  # vmt: disable=VMT011 — service
            target=self._flush_loop, daemon=True,  # timer; close() joins it
            name="vm-storage-flusher")
        self._flusher.start()

    FORMAT_VERSION = 3  # v2: 32-byte tenant TSID; v3: indexdb/global layout

    def _check_format(self):
        """Refuse to open data directories written with an incompatible
        on-disk format instead of misparsing them (format.json marker)."""
        import json as _json
        marker = os.path.join(self.path, "format.json")
        has_data = any(os.path.isdir(os.path.join(self.path, d))
                       for d in ("data", "indexdb"))
        if os.path.exists(marker):
            with open(marker) as f:
                v = _json.load(f).get("format_version")
            if v != self.FORMAT_VERSION:
                raise RuntimeError(
                    f"storage at {self.path} uses on-disk format v{v}; this "
                    f"build reads v{self.FORMAT_VERSION} — restore from a "
                    f"snapshot or re-ingest")
        elif has_data:
            raise RuntimeError(
                f"storage at {self.path} predates the versioned on-disk "
                f"format (v{self.FORMAT_VERSION}) — restore from a snapshot "
                f"or re-ingest")
        else:
            with open(marker, "w") as f:
                _json.dump({"format_version": self.FORMAT_VERSION}, f)

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        self._stop.set()
        self._flusher.join(timeout=10)
        self._save_caches()
        self.table.flush_to_disk()
        self.idb.flush()
        self.table.close()
        self.idb.close()
        with self._lock:
            spaces, self._cspaces = self._cspaces, {}
        for sp in spaces.values():
            sp.close()
        fcntl.flock(self._flock_f, fcntl.LOCK_UN)
        self._flock_f.close()

    def _flush_loop(self):
        last_disk = time.monotonic()
        while not self._stop.wait(2.0):
            try:
                self.table.flush_pending()
                if time.monotonic() - last_disk >= 5.0:
                    self.table.flush_to_disk()
                    self.idb.flush()
                    last_disk = time.monotonic()
                if self.downsample_tiers and \
                        time.monotonic() - self._last_downsample >= \
                        self._downsample_interval_s:
                    self.run_downsample_cycle()
            except Exception as e:  # pragma: no cover
                logger.errorf("storage flusher: %s", e)

    def run_downsample_cycle(self, now_ms: int | None = None) -> int:
        """One background re-rollup pass over every partition x tier
        (the historicalMergeWatcher cadence; also called directly by
        tests/bench/smoke to force aging).  Flushes first — tier
        coverage must only ever run over DURABLE raw parts."""
        if not self.downsample_tiers:
            return 0
        self.table.flush_to_disk()
        written = self.table.run_downsample(
            self.downsample_tiers, self.idb.deleted_metric_ids,
            fasttime.unix_ms() if now_ms is None else now_ms)
        self._last_downsample = time.monotonic()
        if written:
            with self._lock:
                # new tier parts change what a query may read
                self.data_version += 1
        return written

    # -- cache persistence (storage.go:1026-1041 mustSaveCache analogs) ----

    _CACHE_MAGIC = b"vmtpu-cache-v2\n"

    def _save_caches(self):
        """Persist the tsid and per-day caches so a restart does not
        re-resolve every live series through the index."""
        import struct as _st
        d = os.path.join(self.path, "cache")
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, "tsid_cache.bin.tmp")
        with self._lock:
            tsid_items = list(self._tsid_cache.items())
        day_items = []
        for shard in self._shards:
            with shard.lock:
                day_items.extend(shard.day_cache)
        with open(tmp, "wb") as f:
            f.write(self._CACHE_MAGIC)
            f.write(_st.pack("<Q", len(tsid_items)))
            for (tenant, raw), t in tsid_items:
                f.write(_st.pack("<III", tenant[0], tenant[1], len(raw)))
                f.write(raw)
                f.write(t.marshal())
            f.write(_st.pack("<Q", len(day_items)))
            for mid, date in day_items:
                f.write(_st.pack("<QI", mid, date))
        os.rename(tmp, os.path.join(d, "tsid_cache.bin"))

    def _load_caches(self):
        import struct as _st
        fp = os.path.join(self.path, "cache", "tsid_cache.bin")
        try:
            with open(fp, "rb") as f:
                data = f.read()
        except OSError:
            return
        if not data.startswith(self._CACHE_MAGIC):
            return
        try:
            off = len(self._CACHE_MAGIC)
            (n,) = _st.unpack_from("<Q", data, off)
            off += 8
            for _ in range(n):
                a, p, ln = _st.unpack_from("<III", data, off)
                off += 12
                raw = data[off:off + ln]
                off += ln
                t = TSID.unmarshal(data[off:off + TSID.SIZE])
                off += TSID.SIZE
                self._tsid_cache[((a, p), raw)] = t
            (n,) = _st.unpack_from("<Q", data, off)
            off += 8
            nsh = len(self._shards)
            for _ in range(n):
                mid, date = _st.unpack_from("<QI", data, off)
                off += 12
                self._shards[mid % nsh].day_cache.add((mid, date))
        except (_st.error, IndexError):
            # torn write: caches are an optimization, start cold
            self._tsid_cache.clear()
            for shard in self._shards:
                shard.day_cache.clear()

    @property
    def is_readonly(self) -> bool:
        return self._readonly

    def set_readonly(self, ro: bool):
        self._readonly = ro

    # -- writes ------------------------------------------------------------

    def _resolve_tsid(self, mn: MetricName, raw: bytes,
                      tenant=(0, 0), limited=False) -> TSID | None:
        """Resolve or create the TSID. With limited=True the cardinality
        limiter is consulted BEFORE any index writes, so an over-budget
        NEW series creates no index entries at all (storage.go:2136
        ordering); returns None when the limiter rejects.

        This is the slow index path; it serializes on the storage lock,
        which fast-path (cache-hit) rows no longer take at all."""
        ck = (tenant, raw)
        with self._lock:
            tsid = self._tsid_cache.get(ck)
            if tsid is not None:
                if limited and not self._cardinality_ok(tsid.metric_id):
                    return None
                return tsid
            # monotonic stat, written under _lock; the /metrics reader
            # takes a lock-free int snapshot — staleness, not corruption
            self.slow_row_inserts += 1  # vmt: disable=VMT015
            tsid = self.idb.get_tsid_by_name(raw, tenant)
            if tsid is None:
                tsid = generate_tsid(mn, self._mid_gen.next_id(), tenant)
                if limited and not self._cardinality_ok(tsid.metric_id):
                    return None
                self.idb.create_indexes_for_metric(mn, tsid)
                # monotonic stat (see slow_row_inserts above)
                self.new_series_created += 1  # vmt: disable=VMT015
            elif limited and not self._cardinality_ok(tsid.metric_id):
                return None
            self._tsid_cache[ck] = tsid
            return tsid

    #: add_rows accepts raw `name{labels}` BYTES keys (native parser fast
    #: path); ClusterStorage does NOT — it must decompose labels to shard
    #: and marshal the RPC payload, so the HTTP layer gates on this.
    supports_raw_keys = True

    def add_rows(self, rows, tenant=(0, 0)) -> int:
        """rows: iterable of (MetricName | dict | list[(k,v)], ts_ms, value).
        Returns rows added (AddRows/Storage.add analog, storage.go:1655).

        Sharded write path (rawRowsShards analog). Three phases:

        1. **resolve** — input-order pass over the batch with NO
           storage-wide lock: raw-label cache lookups (rotating
           working-set cache), cardinality probes, per-day cache checks.
           Only first-seen series drop into the slow index path, which
           serializes on the storage lock — fast-path rows from
           concurrent writers never wait behind it.
        2. **register** — per-day index registration striped by
           ``hash(metric_id) % VM_INGEST_SHARDS``, each stripe under its
           own lock; large batches fan stripes across the shared work
           pool.  Index items are set-semantic, so stripe order never
           changes what the index contains.
        3. **append** — rows land in the partitions in input order, so
           part contents are byte-identical to the sequential path
           (``VM_INGEST_SHARDS=1`` restores it exactly).
        """
        if self._readonly:
            raise RuntimeError("storage is read-only")
        t0 = time.perf_counter()
        out = []
        regs = []       # (mn, tsid, date) needing per-day registration
        reg_seen = set()  # batch-local (mid, date) dedup: one regs entry
        #                   per distinct rollover, not per row
        raw_cache = self._tsid_cache_raw
        nsh = len(self._shards)
        for labels, ts, val in rows:
            key = None
            if type(labels) is dict:
                key = (tenant, *labels.items())
            elif type(labels) is list:
                key = (tenant, *labels)
            elif type(labels) is bytes:
                # raw `name{labels}` series key from the native parser:
                # cache hits never materialize labels at all
                key = (tenant, labels)
            tsid = raw_cache.get(key) if key is not None else None
            date = ts // 86_400_000
            mn = None
            if tsid is not None:
                if not self._cardinality_ok(tsid.metric_id):
                    continue
                mid = tsid.metric_id
                # OPTIMISTIC day-cache probe, no stripe lock: GIL-atomic
                # set membership against adds that happen only under the
                # stripe lock; a stale miss merely routes the row through
                # _register_days, which re-checks under the lock (entries
                # are never removed during ingest).  Taking the stripe
                # lock here would re-serialize the whole fast path.
                if (mid, date) in reg_seen or \
                        (mid, date) in self._shards[mid % nsh].day_cache:
                    out.append((tsid, ts, val))
                    continue
                # day rollover: rebuild the name from the index cache
                mn = self.idb.get_metric_name_by_id(mid)
            if mn is None:
                if isinstance(labels, MetricName):
                    mn = labels
                elif isinstance(labels, dict):
                    mn = MetricName.from_dict(labels)
                elif isinstance(labels, bytes):
                    from ..ingest.parsers import labels_from_series_key
                    try:
                        mn = MetricName.from_labels(
                            labels_from_series_key(labels))
                    except ValueError:
                        continue  # malformed key: skip row, keep batch
                else:
                    mn = MetricName.from_labels(labels)
                tsid = self._resolve_tsid(mn, mn.marshal(), tenant,
                                          limited=True)
                if tsid is None:
                    continue  # over the cardinality budget
                if key is not None:
                    raw_cache.put(key, tsid)
                mid = tsid.metric_id
                if (mid, date) in reg_seen or \
                        (mid, date) in self._shards[mid % nsh].day_cache:
                    out.append((tsid, ts, val))
                    continue
            reg_seen.add((mid, date))
            regs.append((mn, tsid, date))
            out.append((tsid, ts, val))
        t0 = _ingest_lap("resolve", t0)
        if regs:
            self._register_days(regs)
        t0 = _ingest_lap("register", t0)
        n = len(out)
        if n == 0:
            return 0
        # backfill older than the result-cache offset invalidates cached
        # rollup tails (ResetRollupResultCacheIfNeeded) — at STORAGE
        # level so library/embedded writers are covered too; the batch
        # minimum is computed ONCE and reused for the append log
        oldest = min(r[1] for r in out)
        from ..query.rollup_result_cache import GLOBAL, OFFSET_MS
        if oldest < fasttime.unix_ms() - OFFSET_MS:
            GLOBAL.reset()
        self.table.add_rows(out)
        _ingest_lap("append", t0)
        _INGEST_ROWS.inc(n)
        with self._lock:
            # monotonic stat, written under _lock; the /metrics reader
            # takes a lock-free int snapshot — staleness, not corruption
            self.rows_added += n  # vmt: disable=VMT015
            self.data_version += 1
            log = self._append_log
            if log.maxlen is not None and len(log) == log.maxlen:
                self._append_log_floor = log[0][0]
            log.append((self.data_version, oldest))
        return n

    @contextmanager
    def _shard_locked(self, si: int):
        """Acquire stripe si's lock, accounting the wait time to
        vm_ingest_shard_lock_wait_seconds_total."""
        shard = self._shards[si]
        tw = time.perf_counter()
        shard.lock.acquire()
        _SHARD_WAIT.inc(time.perf_counter() - tw)
        try:
            yield shard
        finally:
            shard.lock.release()

    def _fan_stripes(self, by_shard: dict, run_stripe, total: int) -> None:
        """Execute run_stripe(shard_index, payload) for every stripe —
        across the shared pool for large batches (>= _FANOUT_MIN_REGS
        items, several stripes, pool enabled), inline otherwise.  Stripe
        execution order is unobservable: per-day index items collapse
        set-semantically in the mergeset."""
        stripes = sorted(by_shard.items())
        if len(stripes) > 1 and total >= _FANOUT_MIN_REGS and \
                workpool.ingest_parallel_enabled():
            from functools import partial
            workpool.POOL.run([partial(run_stripe, si, payload)
                               for si, payload in stripes])
        else:
            for si, payload in stripes:
                run_stripe(si, payload)

    def _register_days(self, regs) -> None:
        """Per-day index registration, striped by hash(metric_id) % N:
        each stripe runs under its own lock (in input order within the
        stripe), large batches fanned across the shared work pool."""
        nsh = len(self._shards)
        by_shard: dict[int, list] = {}
        for reg in regs:
            by_shard.setdefault(reg[1].metric_id % nsh, []).append(reg)

        def run_stripe(si, items):
            with self._shard_locked(si) as shard:
                for mn, tsid, date in items:
                    dk = (tsid.metric_id, date)
                    if dk in shard.day_cache:
                        continue
                    self.idb.create_per_day_indexes(mn, tsid, date)
                    shard.day_cache.add(dk)

        self._fan_stripes(by_shard, run_stripe, len(regs))

    #: add_rows_columnar accepts native.ColumnarRows batches; ClusterStorage
    #: does not (it must decompose labels to shard), so HTTP gates on this.
    supports_columnar = True

    def add_rows_columnar(self, cr, tenant=(0, 0), transform=None,
                          drop_stats: dict | None = None) -> int:
        """Columnar ingest batch (native.ColumnarRows): resolves every raw
        series key to a dense id with ONE native hash-map call, then runs
        filtering/day-index bookkeeping as numpy masking. Per-row Python
        exists only for NEW series and day rollovers.

        `transform(labels) -> labels | None` runs ONCE per new series (None
        = drop); the verdict is cached under the raw key, which is how
        relabeling composes with the fast path (relabel rules are pure
        functions of the label set). Callers must reset the columnar spaces
        when the transform config changes (reset_columnar_spaces).

        `drop_stats`: optional dict, incremented per dropped ROW by reason
        ("malformed" / "transform" / "cardinality" / "limiter").
        """
        if self._readonly:
            raise RuntimeError("storage is read-only")
        t0 = time.perf_counter()
        sp = self._acquire_cspace(tenant)  # returns with sp.lock HELD
        try:
            ids, n_new = sp.keymap.resolve(cr.keybuf, cr.key_off, cr.key_len)
            if n_new:
                self._register_columnar_ids(sp, cr, ids, tenant, transform)
            drop = sp.drop[ids]
            if (drop == 3).any():
                # cardinality rejections are transient (limiter windows
                # rotate hourly/daily): re-judge once per id per batch,
                # matching the legacy path's per-batch retry
                retried = set()
                for r in np.flatnonzero(drop == 3):
                    i = int(ids[r])
                    if i in retried:
                        continue
                    retried.add(i)
                    key = bytes(memoryview(cr.keybuf)[
                        int(cr.key_off[r]):
                        int(cr.key_off[r]) + int(cr.key_len[r])])
                    tsid, verdict = self._judge_key(key, tenant, transform)
                    if tsid is not None:
                        sp.set_tsid(i, tsid)
                drop = sp.drop[ids]
            tss, vals = cr.tss, cr.values
            sel = None  # surviving-row indices into cr (None = all)
            if drop.any():
                if drop_stats is not None:
                    for code, name in ((1, "malformed"), (2, "transform"),
                                       (3, "cardinality")):
                        c = int((drop == code).sum())
                        if c:
                            drop_stats[name] = drop_stats.get(name, 0) + c
                keep = drop == 0
                sel = np.flatnonzero(keep)
                ids = ids[keep]
                tss = tss[keep]
                vals = vals[keep]
            if ids.size and (self.hourly_limiter is not None or
                             self.daily_limiter is not None):
                # one limiter probe per DISTINCT series per batch preserves
                # the limiters' distinct-count semantics at columnar cost
                uniq = np.unique(ids)
                bad = [i for i in uniq
                       if not self._cardinality_ok(int(sp.mid[i]))]
                if bad:
                    keep = ~np.isin(ids, bad)
                    if drop_stats is not None:
                        c = int(ids.size - keep.sum())
                        drop_stats["limiter"] = drop_stats.get(
                            "limiter", 0) + c
                    sel = (np.flatnonzero(keep) if sel is None
                           else sel[keep])
                    ids = ids[keep]
                    tss = tss[keep]
                    vals = vals[keep]
            if ids.size == 0:
                return 0
            dates = tss // 86_400_000
            roll = np.flatnonzero(sp.last_date[ids] != dates)
            if roll.size:
                # touch each distinct (id, date) pair ONCE: a fresh
                # series' first batch used to walk every ROW here (the
                # memo only updates after the first row, but the Python
                # loop still visited all of them)
                d_clip = np.clip(dates[roll], -(1 << 20), (1 << 20) - 1)
                key = (ids[roll].astype(np.int64) * (1 << 21) +
                       d_clip + (1 << 20))
                _, first = np.unique(key, return_index=True)
                roll = roll[first]
            t0 = _ingest_lap("resolve", t0)
            if roll.size:
                self._register_columnar_days(sp, cr, ids, dates, sel, roll,
                                             transform)
            t0 = _ingest_lap("register", t0)
        finally:
            sp.lock.release()
        oldest = int(tss.min())
        from ..query.rollup_result_cache import GLOBAL, OFFSET_MS
        if oldest < fasttime.unix_ms() - OFFSET_MS:
            GLOBAL.reset()
        self.table.add_rows_columnar(sp, ids, tss, vals)
        _ingest_lap("append", t0)
        n = int(ids.size)
        _INGEST_ROWS.inc(n)
        with self._lock:
            self.rows_added += n
            self.data_version += 1
            log = self._append_log
            if log.maxlen is not None and len(log) == log.maxlen:
                self._append_log_floor = log[0][0]
            log.append((self.data_version, oldest))
        return n

    def _acquire_cspace(self, tenant) -> "_ColumnarSpace":
        """The tenant's columnar id space with its lock HELD (caller
        releases): same-tenant columnar writers serialize here instead
        of on the storage-wide lock.  Spaces whose native key map
        outgrew MAX_KEYS are retired under their lock (the raw-cache
        rotation analog) and replaced with a fresh one; in-flight
        PendingChunks keep the retired space's numpy columns alive."""
        while True:
            with self._lock:
                sp = self._cspaces.get(tenant)
                if sp is None:
                    sp = self._cspaces[tenant] = _ColumnarSpace()
            sp.lock.acquire()
            if sp.retired:
                sp.lock.release()
                continue  # lost the race with a rotation: re-fetch
            if len(sp.keymap) < sp.MAX_KEYS:
                return sp
            # bound churny key spaces (raw-cache clear analog)
            sp.retired = True
            sp.close()
            with self._lock:
                if self._cspaces.get(tenant) is sp:
                    del self._cspaces[tenant]
            sp.lock.release()

    def _register_columnar_days(self, sp, cr, ids, dates, sel, roll,
                                transform) -> None:
        """Columnar per-day registration for the distinct (id, date)
        rollovers in `roll`, striped by hash(metric_id) % N.  Runs with
        sp.lock held — the per-id `last_date` memo is batch-exclusive —
        and fans stripes across the shared pool for large rollover sets
        (first batch of a high-cardinality scrape)."""
        nsh = len(self._shards)
        by_shard: dict[int, list] = {}
        for r in roll:
            by_shard.setdefault(
                int(sp.mid[int(ids[r])]) % nsh, []).append(int(r))

        def run_stripe(si, rs):
            with self._shard_locked(si) as shard:
                for r in rs:
                    i = int(ids[r])
                    d = int(dates[r])
                    if sp.last_date[i] == d:
                        continue
                    mid = int(sp.mid[i])
                    if (mid, d) not in shard.day_cache:
                        mn = self.idb.get_metric_name_by_id(mid)
                        if mn is None:
                            # index name cache miss: rebuild from this
                            # batch's raw key (+ transform, for
                            # relabeled series)
                            mn = self._rebuild_mn_from_row(cr, sel, r,
                                                           transform)
                        if mn is not None:
                            self.idb.create_per_day_indexes(
                                mn, sp.tsids[i], d)
                        shard.day_cache.add((mid, d))
                    sp.last_date[i] = d

        self._fan_stripes(by_shard, run_stripe, int(roll.size))

    def _rebuild_mn_from_row(self, cr, sel, r, transform):
        """MetricName from row r's raw series key (sel maps surviving
        rows back to cr rows); None on malformed/transform-dropped."""
        from ..ingest.parsers import labels_from_series_key
        rr = int(sel[r]) if sel is not None else int(r)
        try:
            labels = labels_from_series_key(bytes(
                memoryview(cr.keybuf)[
                    int(cr.key_off[rr]):
                    int(cr.key_off[rr]) + int(cr.key_len[rr])]))
            if transform is not None:
                labels = transform(labels)
            if labels:
                return MetricName.from_labels(labels)
        except ValueError:
            pass
        return None

    def _judge_key(self, key: bytes, tenant, transform):
        """Raw key -> (tsid | None, verdict): materialize labels, run the
        transform, resolve the TSID. Verdicts: 0 ok, 1 malformed, 2 dropped
        by transform, 3 over the cardinality budget (re-triable)."""
        from ..ingest.parsers import labels_from_series_key
        try:
            labels = labels_from_series_key(key)
        except ValueError:
            return None, 1
        if transform is not None:
            labels = transform(labels)
            if labels is None:
                return None, 2
        mn = MetricName.from_labels(labels)
        tsid = self._resolve_tsid(mn, mn.marshal(), tenant, limited=True)
        if tsid is None:
            return None, 3
        return tsid, 0

    def _register_columnar_ids(self, sp, cr, ids, tenant, transform) -> None:
        """Slow path for first-seen raw keys: materialize labels, run the
        transform, resolve TSIDs, create indexes. Ids arrive in
        first-occurrence order, so one ascending pass assigns them all."""
        old = len(sp.tsids)
        mv = memoryview(cr.keybuf)
        new_tsids: list = []
        drops: list = []
        mask = ids >= old
        if not mask.any():
            return
        # touch only the FIRST row of each new id, not every row of the
        # (typically sample-dense) first batch: ids are assigned in
        # first-occurrence order, so ascending unique ids == registration
        # order (a 1440-sample first batch used to cost 1440 iterations
        # per new series here)
        rows = np.flatnonzero(mask)
        uniq, first = np.unique(ids[rows], return_index=True)
        for i, r in zip(uniq, rows[first]):
            if int(i) != old + len(new_tsids):
                continue  # defensive: gap means a concurrent registration
            key = bytes(mv[int(cr.key_off[r]):
                           int(cr.key_off[r]) + int(cr.key_len[r])])
            tsid, verdict = self._judge_key(key, tenant, transform)
            new_tsids.append(tsid)
            drops.append(verdict)
        sp.append_ids(new_tsids, drops)

    def reset_columnar_spaces(self) -> None:
        """Invalidate all cached raw-key -> TSID verdicts (call after the
        ingest transform config — relabel rules, series limits — changes).
        In-flight PendingChunks keep the old space objects alive; spaces
        are retired under their own lock so a concurrent columnar writer
        either finishes its batch first or re-fetches a fresh space."""
        with self._lock:
            spaces = list(self._cspaces.values())
            self._cspaces = {}
        for sp in spaces:
            with sp.lock:
                sp.retired = True
                sp.close()

    def min_appended_since(self, version: int):
        """Minimum timestamp inserted after data_version `version`, or None
        when nothing was appended since. Raises LookupError when `version`
        predates the bounded append log (caller must rebuild)."""
        with self._lock:
            # under _lock: concurrent ingest appends to _append_log, and
            # a deque mutated mid-iteration raises RuntimeError
            if version < self._append_log_floor:
                raise LookupError("append log does not cover version")
            lo = None
            for v, mn in reversed(self._append_log):
                if v <= version:
                    break
                lo = mn if lo is None else min(lo, mn)
            return lo

    def _cardinality_ok(self, metric_id: int) -> bool:
        """registerSeriesCardinality (storage.go:2136): hourly/daily bloom
        limiters drop rows for ids beyond the distinct-series budget."""
        # BloomLimiter.add is internally locked (admissions are atomic);
        # the fields themselves are rebound only at configure time
        if self.hourly_limiter is not None and \
                not self.hourly_limiter.add(metric_id):  # vmt: disable=VMT015
            return False
        if self.daily_limiter is not None and \
                not self.daily_limiter.add(metric_id):  # vmt: disable=VMT015
            return False
        return True

    def register_metric_names(self, metric_names, tenant=(0, 0)) -> None:
        """Create index entries without samples (RegisterMetricNames,
        storage.go:1524)."""
        with self._lock:
            for labels in metric_names:
                mn = labels if isinstance(labels, MetricName) else \
                    MetricName.from_dict(labels)
                self._resolve_tsid(mn, mn.marshal(), tenant)

    # -- reads -------------------------------------------------------------

    # selector-level `or` filters ({a="b" or c="d"}) arrive as a list of
    # filter SETS; this store unions them at the tsid level (one assemble
    # pass over the merged id set — the reference's index union)
    supports_filter_union = True

    @staticmethod
    def _filter_sets(filters):
        """Normalize filters into a list of filter sets: a plain
        list[TagFilter] is one set; a list of lists is an OR union."""
        if filters and isinstance(filters[0], (list, tuple)):
            return list(filters)
        return [filters]

    def _search_tsids_union(self, filters, min_ts, max_ts, tenant,
                            check=None, scan_check=None):
        """search_tsids over one or many OR'd filter sets, deduped by
        metric id and returned in sort_key order (the invariant every
        caller's tsid_lo/tsid_hi clamping relies on)."""
        sets = self._filter_sets(filters)
        if len(sets) == 1:
            return self.idb.search_tsids(sets[0], min_ts, max_ts, tenant,
                                         check=check,
                                         scan_check=scan_check)
        seen: dict = {}
        for fs in sets:
            for t in self.idb.search_tsids(fs, min_ts, max_ts, tenant,
                                           check=check,
                                           scan_check=scan_check):
                seen.setdefault(t.metric_id, t)
        return sorted(seen.values(), key=lambda t: t.sort_key())

    def search_metric_names(self, filters: list[TagFilter], min_ts: int,
                            max_ts: int, limit: int = 2**31,
                            tenant=(0, 0)) -> list[MetricName]:
        mids = self._search_mids_union(filters, min_ts, max_ts, tenant)
        out = []
        for mid in mids[:limit]:
            mn = self.idb.get_metric_name_by_id(int(mid))
            if mn is not None:
                out.append(mn)
        return out

    def _search_mids_union(self, filters, min_ts, max_ts, tenant):
        sets = self._filter_sets(filters)
        if len(sets) == 1:
            return self.idb.search_metric_ids(sets[0], min_ts, max_ts,
                                              tenant)
        out: set = set()
        for fs in sets:
            out.update(self.idb.search_metric_ids(fs, min_ts, max_ts,
                                                  tenant))
        return sorted(out)

    def iter_series_blocks(self, filters: list[TagFilter], min_ts: int,
                           max_ts: int, tenant=(0, 0)):
        """Raw matching blocks in (tsid, min_ts) order — the input to the
        TPU tile packer (Search.NextMetricBlock analog, search.go:275)."""
        tsids = self._search_tsids_union(filters, min_ts, max_ts, tenant)
        tsid_set = {t.metric_id for t in tsids}
        if not tsid_set:
            return
        yield from self.table.iter_blocks(
            tsid_set, min_ts, max_ts,
            tsid_lo=tsids[0].sort_key(), tsid_hi=tsids[-1].sort_key())

    def estimate_series(self, filters: list[TagFilter], min_ts: int,
                        max_ts: int, tenant=(0, 0)) -> int:
        """Matching-series count without fetching samples (the tsid
        search is cached, so a following search_columns* reuses it)."""
        return len(self._search_tsids_union(filters, min_ts, max_ts,
                                            tenant))

    def search_columns_chunked(self, filters: list[TagFilter], min_ts: int,
                               max_ts: int,
                               dedup_interval_ms: int | None = None,
                               max_series: int | None = None, tenant=(0, 0),
                               max_chunk_samples: int = 50_000_000,
                               deadline: float = 0.0):
        """Bounded-memory fetch: yields ColumnarSeries chunks over
        disjoint series subsets, each holding at most ~max_chunk_samples
        resident samples (the tmp-blocks-spool role,
        app/vmselect/netstorage/tmp_blocks_file.go — here the spool is
        the on-disk part itself and each chunk decodes only its own
        blocks). The per-series density estimate starts at the 15s scrape
        grid and adapts to what the first chunk actually returned."""
        tsids = self._search_tsids_union(filters, min_ts, max_ts, tenant)
        if not tsids:
            return
        est = max((max_ts - min_ts) // 15_000 + 2, 1)
        i, S = 0, len(tsids)
        seen = 0

        def fetch(lo: int, k: int):
            return self.search_columns(filters, min_ts, max_ts,
                                       dedup_interval_ms, None, tenant,
                                       _tsids=tsids[lo:lo + k],
                                       deadline=deadline)

        # pipelined prefetch: chunk i+1's fetch/decode runs on the shared
        # work pool while the consumer rolls chunk i up (the netstorage
        # fetch/compute overlap); chunk boundaries, results and error
        # behavior are identical to the sequential loop because est is
        # updated from chunk i BEFORE chunk i+1's size is computed in
        # both modes.  With VM_SEARCH_WORKERS=1 there is no prefetch.
        pool = workpool.POOL
        pending = None
        try:
            k = max(int(max_chunk_samples // est), 64)
            cols = fetch(i, k)
            while True:
                # limit counts series WITH DATA in range (cumulative),
                # matching search_columns' post-collection semantics
                seen += cols.n_series
                if max_series is not None and seen > max_series:
                    raise ResourceWarning(
                        f"query matches more than {max_series} series")
                if cols.n_series:
                    est = max(cols.n_samples // cols.n_series, 1)
                i += k
                if i >= S:
                    yield cols
                    return
                k = max(int(max_chunk_samples // est), 64)
                if pool.parallel_enabled():
                    from functools import partial
                    pending = pool.submit(partial(fetch, i, k))
                    yield cols
                    cols, pending = pending.result(), None
                else:
                    yield cols
                    cols = fetch(i, k)
        except GeneratorExit:
            # consumer abandoned the generator: drain the in-flight
            # prefetch so no background fetch outlives the query (it may
            # race a storage close)
            if pending is not None:
                try:
                    pending.result()
                except BaseException:  # vmt: disable=VMT003 — the query
                    pass               # was abandoned; its error has no
                #                        consumer and must not mask the
                #                        GeneratorExit being re-raised
            raise

    #: eval threads the query deadline down (see ClusterStorage): an
    #: expired budget aborts the scan/fetch mid-flight with the typed
    #: DeadlineExceededError instead of completing for a dead caller
    supports_search_deadline = True
    #: eval may pass ``ds=(agg_column, max_resolution_ms)`` to opt a
    #: fetch into downsampled tiers (storage/downsample.py); absent on
    #: ClusterStorage, so the hint never crosses the RPC untranslated
    supports_downsample_read = True

    @property
    def downsample_active(self) -> bool:
        return bool(self.downsample_tiers)

    def search_columns(self, filters: list[TagFilter], min_ts: int,
                       max_ts: int, dedup_interval_ms: int | None = None,
                       max_series: int | None = None, tenant=(0, 0),
                       _tsids=None, deadline: float = 0.0, ds=None):
        """Batched columnar search: one native decode pass per part, one
        vectorized assembly into padded (S, N) columns — no per-series
        Python on the fetch path (the netstorage.go:374-421 unpack-worker
        role, done as array passes). Returns a ColumnarSeries with rows
        ordered by raw metric name (same order as search_series).

        ``deadline`` (time.monotonic cutoff, 0 = none) is the storage-
        side half of deadline propagation: the budget is checked every
        N series during the index scan and once per fetch unit, and an
        expired query raises :class:`DeadlineExceededError` (counted in
        ``vm_storage_deadline_aborts_total``) instead of burning the
        dead query's full server-side cost."""
        from .columnar import ColumnarSeries, assemble
        interval = (self.dedup_interval_ms if dedup_interval_ms is None
                    else dedup_interval_ms)
        budget = (_ScanBudget(deadline, on_abort=_DEADLINE_ABORTS.inc)
                  if deadline else None)
        # per-tenant QoS admission: a tenant at its VM_TENANT_QUOTAS cap
        # queues (and sheds) against itself instead of starving others
        with workpool.SEARCH_GATE.admit(tenant):
            # chaos seam, INSIDE the admission slot: an injected delay
            # occupies real gate capacity, which is how the chaos suite
            # saturates one tenant's quota without touching another's
            if faultinject.active():
                faultinject.fire(
                    f"storage:search:{tenant[0]}:{tenant[1]}")
            return self._search_columns_gated(
                filters, min_ts, max_ts, interval, max_series, tenant,
                _tsids, ColumnarSeries, assemble, budget, ds)

    def _resolve_ordered_names(self, uniq: np.ndarray):
        """Raw-name resolution + canonical (raw-sorted) row order for a
        fetched metric-id set: (have, kept, rank, ordered_mids,
        raws_in_row_order, names_in_row_order).  Memoized on the id set +
        structural version (metric id -> name is immutable; deletes and
        retention bump structural_version), LRU-bounded — the
        suffix-aware fetch's answer to per-refresh O(S) resolution."""
        import xxhash
        key = (xxhash.xxh64_intdigest(np.ascontiguousarray(uniq).tobytes()),
               int(uniq.size), self.structural_version)
        with self._name_memo_lock:
            got = self._name_memo.get(key)
            if got is not None:
                self._name_memo.move_to_end(key)
                return got
        names = self.idb.get_metric_names_by_ids([int(m) for m in uniq])
        have = np.array([int(m) in names for m in uniq], bool)
        kept = uniq[have]
        raws = [names[int(m)][1] for m in kept]
        if len(raws) > 1:
            # fixed-width bytes argsort (C memcmp) instead of a Python-object
            # compare per element; numpy's S dtype strips trailing NULs, so
            # names ending in \0 (never produced by MetricName.marshal, but
            # cheap to guard) take the object path
            if any(r[-1:] == b"\x00" for r in raws):
                arr = np.array(raws, dtype=object)
            else:
                arr = np.array(raws)
            perm = np.argsort(arr, kind="stable")
        else:
            perm = np.arange(len(raws), dtype=np.int64)
        ordered_mids = kept[perm]
        # rank[j] = final row of kept[j]
        rank = np.empty(perm.size, np.int64)
        rank[perm] = np.arange(perm.size)
        raws_final = [raws[i] for i in perm]
        names_final = [names[int(m)][0] for m in ordered_mids]
        val = (have, kept, rank, ordered_mids, raws_final, names_final)
        with self._name_memo_lock:
            self._name_memo[key] = val
            while len(self._name_memo) > 64:
                self._name_memo.popitem(last=False)
        return val

    def _search_columns_gated(self, filters, min_ts, max_ts, interval,
                              max_series, tenant, _tsids, ColumnarSeries,
                              assemble, budget=None, ds=None):
        t_ph = time.perf_counter()
        costacc.restamp()  # start of this thread's phase-lap chain
        if budget is not None:
            budget.check()  # gate queue wait burned the budget already?
        tsids = (self._search_tsids_union(
                     filters, min_ts, max_ts, tenant,
                     check=budget.tick if budget is not None else None,
                     scan_check=budget.check if budget is not None
                     else None)
                 if _tsids is None else _tsids)
        t_ph = _phase_lap("index_search", t_ph)
        empty = ColumnarSeries.empty()
        if not tsids:
            return empty
        tsid_set = {t.metric_id for t in tsids}
        # downsampled-tier serving: a note dict both ENABLES per-
        # partition tier selection and reports back what was chosen;
        # VM_DOWNSAMPLE_READ=0 (the raw-oracle escape hatch) keeps every
        # fetch raw-only, fallback included
        note = None
        if self.downsample_tiers:
            from . import downsample as _dsmod
            if _dsmod.read_enabled():
                note = {}
            else:
                ds = None
        else:
            ds = None
        # the fused native read kernel (vm_assemble_part) merges the
        # collect+decode+clip stages into one GIL-released call per part
        # and hands back float pieces; VM_NATIVE_ASSEMBLE=0 (or a missing
        # native library) runs the split Python-orchestrated path — the
        # correctness oracle the equality tests diff against
        from .. import native as _native
        fused = _native.assemble_enabled()
        pieces = self.table.collect_columns(
            tsid_set, min_ts, max_ts,
            tsid_lo=tsids[0].sort_key(), tsid_hi=tsids[-1].sort_key(),
            as_float=fused,
            check=budget.check if budget is not None else None,
            ds=ds, note=note)
        t_ph = _phase_lap("assemble_native" if fused else "collect", t_ph)
        if note:
            if note.get("partial_res"):
                # per-request flag, surfaced as partialResolution in the
                # HTTP response metadata (reset_partial clears it).
                # Benign race: sticky advisory boolean — concurrent
                # writers all store True, readers only consume it after
                # their own search returned, and a lost reset merely
                # over-reports partial resolution (never under-reports).
                self._partial_res_flag = True  # vmt: disable=VMT015
        if budget is not None:
            budget.check()  # before the decode/assembly tail
        if not pieces:
            self._note_to_cols(empty, note)
            return empty
        if fused:
            if len(pieces) == 1:
                mids, cnts, ts_all, vals_f = pieces[0]
                piece_ids = None  # one piece: every block shares provenance
            else:
                mids = np.concatenate([p[0] for p in pieces])
                cnts = np.concatenate([p[1] for p in pieces])
                ts_all = np.concatenate([p[2] for p in pieces])
                vals_f = np.concatenate([p[3] for p in pieces])
                piece_ids = np.repeat(np.arange(len(pieces)),
                                      [p[0].size for p in pieces])
        else:
            if len(pieces) == 1:
                mids, cnts, scales, ts_all, mant_all = pieces[0]
                piece_ids = None  # one piece: every block shares provenance
            else:
                mids = np.concatenate([p[0] for p in pieces])
                cnts = np.concatenate([p[1] for p in pieces])
                scales = np.concatenate([p[2] for p in pieces])
                ts_all = np.concatenate([p[3] for p in pieces])
                mant_all = np.concatenate([p[4] for p in pieces])
                piece_ids = np.repeat(np.arange(len(pieces)),
                                      [p[0].size for p in pieces])
            # mantissas -> float64 with per-block exponents, one native pass
            vals_f = np.empty(mant_all.size, np.float64)
            goff = np.empty(cnts.size + 1, np.int64)
            goff[0] = 0
            np.cumsum(cnts, out=goff[1:])
            if _native.available():
                _native.decimal_to_float_blocks(
                    np.ascontiguousarray(mant_all), goff, scales, vals_f)
            else:
                # one sort-by-scale pass, split across the work pool (every
                # task writes a disjoint out region: bit-identical results)
                from ..ops import decimal as dec_ops
                dec_ops.decimal_to_float_blocks_py(mant_all, goff, scales,
                                                   vals_f, pool=workpool.POOL)
            t_ph = _phase_lap("decode", t_ph)
        # cost accounting: the raw column bytes this fetch pulled out of
        # parts (timestamps + decoded values) — the "bytesRead" column
        # of top_queries/usage
        costacc.add_part_bytes(int(ts_all.nbytes) + int(vals_f.nbytes))
        # resolve names FIRST and bake the canonical raw-name row order into
        # the assembly scatter (no post-assembly reorder pass); memoized
        # on the fetched id set — a rolling refresh's per-step cost stays
        # O(new samples), not O(S) name lookups + argsort
        uniq = np.unique(mids)
        if max_series is not None and uniq.size > max_series:
            raise ResourceWarning(
                f"query matches {uniq.size} series, limit {max_series}")
        have, kept, rank, ordered_mids, raws_final, names_final = \
            self._resolve_ordered_names(uniq)
        # per-block target row; blocks of name-less series are dropped
        pos_in_uniq = np.searchsorted(uniq, mids)
        if not have.all():
            bkeep = have[pos_in_uniq]
            if not bkeep.all():
                sample_keep = np.repeat(bkeep, cnts)
                mids, cnts = mids[bkeep], cnts[bkeep]
                ts_all = ts_all[sample_keep]
                vals_f = vals_f[sample_keep]
                if piece_ids is not None:
                    piece_ids = piece_ids[bkeep]
            pos_in_kept = np.searchsorted(kept, mids)
        else:
            pos_in_kept = pos_in_uniq
        block_rows = rank[pos_in_kept]
        # coalesce adjacent same-series blocks within one piece: a part's
        # blocks are (tsid, min_ts)-sorted, so a series' span-capped blocks
        # concatenate in time order — assemble then sees one block per
        # (series, part) and its uniform-grid reshape fast path survives
        # the block-span cap (never across pieces: cross-part rows overlap
        # in time and must keep the per-row sort fix)
        K = int(block_rows.size)
        if K > 1:
            same = block_rows[1:] == block_rows[:-1]
            if piece_ids is not None:
                same &= piece_ids[1:] == piece_ids[:-1]
            if bool(same.any()):
                # Coalescing disables assemble()'s per-row disorder sort
                # for the merged rows, so VERIFY the invariant it rests on
                # (intra-part blocks of one tsid are time-ordered and
                # non-overlapping): last ts of block j must not exceed
                # first ts of block j+1 across every merged boundary.
                # O(#boundaries) gather; on violation keep blocks separate
                # and let the sort fix handle them.
                ends = np.cumsum(cnts)
                j = np.flatnonzero(same)
                pos = ends[j]
                same[j[ts_all[pos - 1] > ts_all[pos]]] = False
            if bool(same.any()):
                starts_blk = np.empty(K, bool)
                starts_blk[0] = True
                np.logical_not(same, out=starts_blk[1:])
                seg = np.cumsum(starts_blk) - 1
                cnts = np.bincount(seg, weights=cnts).astype(np.int64)
                block_rows = block_rows[starts_blk]
        cols = assemble(block_rows, int(kept.size), cnts, ts_all, vals_f,
                        min_ts, max_ts, interval, metric_ids=ordered_mids)
        if cols.dropped_rows is not None:
            live = np.delete(np.arange(ordered_mids.size),
                             cols.dropped_rows)
            cols.raw_names = [raws_final[i] for i in live]
            cols.metric_names = [names_final[i] for i in live]
        else:
            # fresh list objects: the memoized products must never alias
            # a caller-mutable ColumnarSeries field
            cols.raw_names = list(raws_final)
            cols.metric_names = list(names_final)
        cols.compute_stale_rows()
        self._note_to_cols(cols, note)
        if cols.metric_names:
            self.track_name_usage(
                {mn.metric_group for mn in cols.metric_names})
        _phase_lap("assemble", t_ph)
        return cols

    @staticmethod
    def _note_to_cols(cols, note) -> None:
        """Stamp the tier-selection outcome onto the result (eval keys
        its cache and the avg/count rewrites off these)."""
        if note:
            cols.ds_res = int(note.get("ds_res", 0))
            cols.partial_res = bool(note.get("partial_res", False))

    def search_series(self, filters: list[TagFilter], min_ts: int,
                      max_ts: int, dedup_interval_ms: int | None = None,
                      max_series: int | None = None,
                      tenant=(0, 0),
                      deadline: float = 0.0) -> list[SeriesData]:
        """Decoded per-series rows, cross-part merged, deduped, clipped —
        thin per-series view over search_columns."""
        cols = self.search_columns(filters, min_ts, max_ts,
                                   dedup_interval_ms, max_series, tenant,
                                   deadline=deadline)
        return cols.to_series_list()

    def _search_series_blocks(self, filters: list[TagFilter], min_ts: int,
                              max_ts: int,
                              dedup_interval_ms: int | None = None,
                              max_series: int | None = None,
                              tenant=(0, 0)) -> list[SeriesData]:
        """Per-block reference implementation (kept as the differential
        oracle for the columnar path; tests compare both)."""
        from ..ops import decimal as dec_ops
        interval = (self.dedup_interval_ms if dedup_interval_ms is None
                    else dedup_interval_ms)
        per_mid: dict[int, list] = {}
        for blk in self.iter_series_blocks(filters, min_ts, max_ts, tenant):
            per_mid.setdefault(blk.tsid.metric_id, []).append(blk)
        if max_series is not None and len(per_mid) > max_series:
            raise ResourceWarning(
                f"query matches {len(per_mid)} series, limit {max_series}")
        names = self.idb.get_metric_names_by_ids(per_mid.keys())
        out = []
        for mid, blocks in per_mid.items():
            got = names.get(mid)
            if got is None:
                continue
            mn, raw = got
            if len(blocks) == 1:
                # fast path: one block is already time-sorted
                b = blocks[0]
                ts, vals = b.timestamps, b.float_values()
                if ts[0] < min_ts or ts[-1] > max_ts:
                    lo = np.searchsorted(ts, min_ts, side="left")
                    hi = np.searchsorted(ts, max_ts, side="right")
                    ts, vals = ts[lo:hi], vals[lo:hi]
            else:
                ts = np.concatenate([b.timestamps for b in blocks])
                vals = np.concatenate([b.float_values() for b in blocks])
                order = np.argsort(ts, kind="stable")
                ts, vals = ts[order], vals[order]
                keep = (ts >= min_ts) & (ts <= max_ts)
                ts, vals = ts[keep], vals[keep]
            if ts.size == 0:
                continue
            if interval > 0:
                ts, vals = deduplicate(ts, vals, interval)
            # collapse exact-duplicate timestamps (replica merges)
            if ts.size > 1:
                dup = np.concatenate([ts[1:] == ts[:-1], [False]])
                if dup.any():
                    ts, vals = ts[~dup], vals[~dup]
            out.append((raw, SeriesData(mn, ts, vals, raw,
                                        stale_blocks=blocks)))
        out.sort(key=lambda rs: rs[0])
        return [sd for _, sd in out]

    # -- integrity / partial-result surface ------------------------------

    def quarantine_report(self) -> list[dict]:
        """Every part moved aside by the open-time integrity check,
        across all three stores (data partitions, the global mergeset,
        indexdb month tables) — the /api/v1/status/quarantine payload."""
        return self.table.quarantined() + self.idb.quarantined()

    @property
    def last_partial(self) -> bool:
        """A store that quarantined anything serves LOUDLY partial:
        every result carries isPartial=True until the operator restores
        or discards the quarantined parts (the opposite of the old
        silent-drop behavior).  Cached at open — quarantine only happens
        at open time (partitions/tables created later start empty), and
        this property sits on the serving hot path (meta frames, eval
        partial capture, result-cache puts)."""
        return self._has_quarantine

    @property
    def last_partial_resolution(self) -> bool:
        """A fetch since the last reset_partial() fell back to a coarser
        tier than the query's effective step allows (raw dropped by
        retention, no satisfying tier) — the response carries
        ``partialResolution: true`` so degraded data is never silent."""
        return self._partial_res_flag

    def reset_partial(self) -> None:
        """Per-request reset hook (ClusterStorage protocol): quarantine
        partiality is persistent state (nothing to clear), but the
        partial-RESOLUTION flag is per-request."""
        self._partial_res_flag = False

    def label_names(self, min_ts=None, max_ts=None,
                    tenant=(0, 0)) -> list[str]:
        return self.idb.label_names(min_ts, max_ts, tenant)

    def label_values(self, key: str, min_ts=None, max_ts=None,
                     tenant=(0, 0)) -> list[str]:
        return self.idb.label_values(key, min_ts, max_ts, tenant)

    def tag_value_suffixes(self, tag_key: str, tag_value_prefix: str,
                           delimiter: str = ".", max_suffixes: int = 100_000,
                           min_ts=None, max_ts=None,
                           tenant=(0, 0)) -> list[str]:
        """Graphite path expansion (GetTagValueSuffixes,
        lib/storage/index_db.go): distinct suffixes of `tag_key` values
        that start with `tag_value_prefix`, cut AFTER the next delimiter
        (suffix keeps the trailing delimiter, marking a non-leaf)."""
        key = "__name__" if tag_key in ("", "__name__") else tag_key
        vals = self.idb.label_values(key, min_ts, max_ts, tenant)
        plen = len(tag_value_prefix)
        out: set[str] = set()
        for v in vals:
            if not v.startswith(tag_value_prefix):
                continue
            rest = v[plen:]
            i = rest.find(delimiter)
            out.add(rest if i < 0 else rest[:i + 1])
            if len(out) >= max_suffixes:
                break
        return sorted(out)

    # -- metric-name usage stats (lib/storage/metricnamestats) -----------

    _MAX_NAME_USAGE = 100_000

    def track_name_usage(self, metric_groups) -> None:
        """Record a query hit for each distinct metric name (called by
        the search paths; drives /api/v1/status/metric_names_stats and
        the metricNamesUsageStats RPC)."""
        now = fasttime.unix_timestamp()
        with self._lock:
            # under _lock: the stats/RPC readers iterate this dict, and
            # a concurrent insert mid-iteration raises RuntimeError
            nu = self._name_usage
            for g in metric_groups:
                e = nu.get(g)
                if e is None:
                    if len(nu) >= self._MAX_NAME_USAGE:
                        continue
                    e = nu[g] = [0, 0]
                e[0] += 1
                e[1] = now

    def metric_names_usage_stats(self, limit: int = 1000,
                                 le: int | None = None) -> list[dict]:
        with self._lock:
            items = [{"metricName": (g.decode("utf-8", "replace")
                                     if isinstance(g, bytes) else g),
                      "requestsCount": c, "lastRequestTimestamp": t}
                     for g, (c, t) in self._name_usage.items()]
        if le is not None:
            items = [x for x in items if x["requestsCount"] <= le]
        items.sort(key=lambda x: x["requestsCount"])
        return items[:limit]

    def reset_metric_names_stats(self) -> None:
        with self._lock:
            self._name_usage.clear()

    # -- metric metadata (TYPE/HELP; /api/v1/metadata storage side) ------

    def set_metadata(self, metadata: dict) -> None:
        """Merge parsed # TYPE / # HELP exposition metadata."""
        with self._lock:
            # under _lock: search_metadata iterates this dict, and a
            # concurrent merge mid-iteration raises RuntimeError
            if len(self.metadata) < 100_000:
                self.metadata.update(metadata)

    def search_metadata(self, limit: int = 1000,
                        metric: str = "") -> dict:
        with self._lock:
            if metric:
                md = self.metadata.get(metric)
                return {metric: md} if md else {}
            out = {}
            for name, md in self.metadata.items():
                if len(out) >= limit:
                    break
                out[name] = md
            return out

    def series_count(self, tenant=(0, 0)) -> int:
        return int(self.idb._all_metric_ids(tenant).size)

    def tenants(self) -> list[tuple[int, int]]:
        return self.idb.tenants()

    def tsdb_status(self, date: int | None = None, topn: int = 10,
                    tenant=(0, 0), filters=None,
                    focus_label: str = "") -> dict:
        """Cardinality explorer data (GetTSDBStatus, index_db.go:1284).
        `filters` (match[] selectors) restrict the series set — the
        explorer's drill-down; `focus_label` adds a per-value breakdown of
        that label (focusLabel)."""
        by_metric: dict[bytes, int] = {}
        by_label: dict[bytes, int] = {}
        by_pair: dict[bytes, int] = {}
        by_focus: dict[bytes, int] = {}
        values_per_label: dict[bytes, set] = {}
        fl = focus_label.encode()
        if filters:
            mids = self.idb.search_metric_ids(filters, tenant=tenant)
            if date is not None:
                day = self.idb._metric_ids_for_date(date, tenant)
                mids = np.intersect1d(mids, day, assume_unique=True)
        else:
            mids = (self.idb._metric_ids_for_date(date, tenant)
                    if date is not None
                    else self.idb._all_metric_ids(tenant))
        for mid in mids:
            mn = self.idb.get_metric_name_by_id(int(mid))
            if mn is None:
                continue
            by_metric[mn.metric_group] = by_metric.get(mn.metric_group, 0) + 1
            for k, v in mn.labels:
                by_label[k] = by_label.get(k, 0) + 1
                pair = k + b"=" + v
                by_pair[pair] = by_pair.get(pair, 0) + 1
                values_per_label.setdefault(k, set()).add(v)
                if fl and k == fl:
                    by_focus[v] = by_focus.get(v, 0) + 1

        def top(d):
            return [{"name": k.decode("utf-8", "replace"), "count": c}
                    for k, c in sorted(d.items(), key=lambda kv: -kv[1])[:topn]]

        out = {
            "totalSeries": int(mids.size),
            "seriesCountByMetricName": top(by_metric),
            "seriesCountByLabelName": top(by_label),
            "seriesCountByLabelValuePair": top(by_pair),
            "labelValueCountByLabelName": top(
                {k: len(v) for k, v in values_per_label.items()}),
        }
        if fl:
            out["seriesCountByFocusLabelValue"] = top(by_focus)
        return out

    # -- deletes -----------------------------------------------------------

    def delete_series(self, filters: list[TagFilter], tenant=(0, 0)) -> int:
        """Tombstone matching series (DeleteSeries, storage.go:1345). Data
        blocks are dropped at the next merge."""
        mids = self.idb.search_metric_ids(filters, tenant=tenant)
        if mids.size:
            self.idb.delete_series_by_ids(mids)
            dead = set(int(m) for m in mids)
            with self._lock:
                self._tsid_cache = {
                    k: t for k, t in self._tsid_cache.items()
                    if t.metric_id not in dead}
            # the raw-label cache would resurrect tombstoned metric_ids
            self._tsid_cache_raw.filter(
                lambda k, t: t.metric_id not in dead)
            # AFTER the tombstones land: a racing query that fetched the
            # old data keys its tile under the pre-delete version
            with self._lock:
                self.data_version += 1
                # monotonic version, bumped under _lock; cache keying
                # reads a lock-free int snapshot — a stale read keys a
                # tile one version back, which the ratchet re-checks
                self.structural_version += 1  # vmt: disable=VMT015
        return int(mids.size)

    # -- live resharding (part migration + ring-ownership exemptions) ------

    #: this backend holds ring-placed data, so it honors (and acks) the
    #: ring-ownership read filter shipped by vmselects — a multilevel
    #: ClusterStorage backend does not (see parallel/ringfilter)
    supports_ring_filter = True

    @property
    def ring_exempt_names(self) -> set[bytes]:
        """Canonical marshals exempt from ring-ownership filtering.
        Append-only for the process lifetime — handlers may read it
        without the lock."""
        return self._ring_exempt

    def _ring_exempt_path(self) -> str:
        return os.path.join(self.path, "ring_exempt.bin")

    def _load_ring_exempt(self) -> None:
        from ..ops.varint import unmarshal_varuint64
        try:
            with open(self._ring_exempt_path(), "rb") as f:
                data = f.read()
        except OSError:
            return
        off = 0
        try:
            while off < len(data):
                n, off = unmarshal_varuint64(data, off)
                if off + n > len(data):
                    break  # torn tail append: keep the complete prefix
                self._ring_exempt.add(data[off:off + n])
                off += n
        except (ValueError, IndexError):
            pass  # torn record: the loaded prefix still serves

    def add_ring_exempt_names(self, raws) -> int:
        """Mark canonical metric-name marshals as always-served (write
        reroutes, adopted parts).  Returns how many were new."""
        from ..ops.varint import marshal_varuint64
        with self._ring_exempt_lock:
            fresh = [r for r in raws if r not in self._ring_exempt]
            if not fresh:
                return 0
            # the durable append IS the critical section: the in-memory
            # publish must be ordered after it, and concurrent appends
            # to one file must serialize (reroutes/adoptions are rare —
            # never a hot path)
            with open(self._ring_exempt_path(),  # vmt: disable=VMT004
                      "ab") as f:
                for r in fresh:
                    f.write(marshal_varuint64(len(r)) + r)
                f.flush()
                os.fsync(f.fileno())
            # publish AFTER the durable append: a crash between the two
            # re-derives the entries from the next reroute/adoption
            self._ring_exempt.update(fresh)
        return len(fresh)

    def _adopted_watermark_path(self) -> str:
        return os.path.join(self.path, "adopted_mid.json")

    def _load_adopted_watermark(self) -> None:
        import json as _json
        try:
            with open(self._adopted_watermark_path()) as f:
                self._mid_gen.reserve_past(int(_json.load(f)["max"]))
        except (OSError, ValueError, KeyError, TypeError):
            pass  # no adoptions yet (or torn write: adoption re-writes)

    def _persist_adopted_watermark(self, max_id: int) -> None:
        """Durably record the highest adopted foreign metric_id (only
        ratchets upward) so reserve_past survives restarts."""
        import json as _json

        # rare path (one write per adoption batch); the file I/O IS the
        # critical section — the ratchet check and the durable replace
        # must not interleave between concurrent adoptions
        with self._ring_exempt_lock:
            try:
                with open(  # vmt: disable=VMT004 — see above
                        self._adopted_watermark_path()) as f:
                    if int(_json.load(f)["max"]) >= max_id:
                        return
            except (OSError, ValueError, KeyError, TypeError):
                pass
            from ..utils import fs as fslib
            tmp = self._adopted_watermark_path() + ".tmp"
            with open(tmp, "w") as f:  # vmt: disable=VMT004 — see above
                _json.dump({"max": int(max_id)}, f)
                f.flush()
                os.fsync(f.fileno())
            fslib.rename_durable(tmp, self._adopted_watermark_path())

    def list_file_parts(self) -> list[dict]:
        """Migration inventory: every finalized part across partitions."""
        return self.table.list_file_parts()

    def export_part(self, partition: str, part: str):
        """One finalized part as transferable state: (files as
        [(name, bytes)], series registrations as [(tsid_marshal,
        name_marshal)], meta dict).  Raises KeyError when the part was
        merged away since listing (callers re-list and retry)."""
        pt = self.table.partition_by_name(partition)
        p = pt.get_file_part(part) if pt is not None else None
        if p is None:
            raise KeyError(f"part {partition}/{part} not found "
                           f"(merged away since listing?)")
        files = []
        for fname in sorted(os.listdir(p.path)):
            with open(os.path.join(p.path, fname), "rb") as f:
                files.append((fname, f.read()))
        entries = []
        for t in p.unique_tsids():
            got = self.idb.get_metric_name_raw_by_id(t.metric_id)
            if got is not None:
                entries.append((t.marshal(), got[1]))
        meta = {"partition": partition, "part": part, "rows": int(p.rows),
                "bytes": p.file_bytes(), "min_ts": int(p.min_ts),
                "max_ts": int(p.max_ts)}
        return files, entries, meta

    def adopt_series(self, entries, min_ts=None, max_ts=None) -> int:
        """Register series shipped alongside a migrated part UNDER THEIR
        FOREIGN metric_ids (ids are node-local counters, so the part's
        blocks are unreadable without this).  A colliding id bound to a
        DIFFERENT name rejects the whole adoption — the driver leaves
        the part on its source node.  Per-day indexes are registered for
        every day of the part's span (over-inclusive is harmless: the
        per-day index is a pruning filter, and a part spans at most its
        monthly partition)."""
        from .index_db import MS_PER_DAY
        fresh = []
        for tsid_b, raw in entries:
            t = TSID.unmarshal(tsid_b)
            got = self.idb.get_metric_name_raw_by_id(t.metric_id)
            if got is not None:
                if got[1] != raw:
                    raise ValueError(
                        f"metric_id collision adopting series: id "
                        f"{t.metric_id} is already bound to another name")
                continue
            self._mid_gen.reserve_past(t.metric_id)
            fresh.append((MetricName.unmarshal(raw), t))
        if fresh:
            # durable BEFORE the index registrations land: a restart
            # must never re-generate into the adopted id range
            self._persist_adopted_watermark(
                max(t.metric_id for _, t in fresh))
        for mn, t in fresh:
            self.idb.create_indexes_for_metric(mn, t)
        if min_ts is not None and max_ts is not None:
            days = range(int(min_ts) // MS_PER_DAY,
                         int(max_ts) // MS_PER_DAY + 1)
            for mn, t in fresh:
                for d in days:
                    self.idb.create_per_day_indexes(mn, t, d)
        return len(fresh)

    def adopt_part(self, partition: str, files, entries,
                   min_ts=None, max_ts=None) -> tuple[int, int]:
        """Adopt one migrated part.  Ordering: STAGE + crc-verify the
        bytes first (a torn transfer must be rejected before any other
        state lands — index registrations are not rolled back), then
        register the series (reads of the adopted blocks must resolve
        the moment the part is published), then durably publish and
        exempt the series from ring filtering (this node may now hold
        their only copy).  The heavy write runs under the MergeGate so
        adoption yields to in-flight serving.  Returns (rows, bytes)."""
        pt = self.table.partition_by_name(partition, create=True)
        if pt is None:
            raise ValueError(f"bad partition name {partition!r}")
        with workpool.MERGE_GATE:
            staged = pt.stage_part(files)
            try:
                self.adopt_series(entries, min_ts, max_ts)
            except BaseException:
                pt.discard_staged(staged)
                raise
            p = pt.publish_staged(staged)
        self.add_ring_exempt_names([raw for _, raw in entries])
        oldest = int(p.min_ts)
        with self._lock:
            self.rows_added += int(p.rows)
            self.data_version += 1
            log = self._append_log
            if log.maxlen is not None and len(log) == log.maxlen:
                self._append_log_floor = log[0][0]
            # adopted parts carry OLD timestamps: record the append like
            # a backfill so rolling device tiles rebuild instead of
            # serving a stale suffix
            log.append((self.data_version, oldest))
        return int(p.rows), p.file_bytes()

    def remove_parts(self, partition: str, names: list[str]) -> int:
        """Source side of a part migration: delist + delete after the
        receiver's durable ack."""
        pt = self.table.partition_by_name(partition)
        if pt is None:
            return 0
        n = pt.remove_parts(names)
        if n:
            with self._lock:
                self.data_version += 1
                self.structural_version += 1  # visible data moved away
        return n

    # -- maintenance -------------------------------------------------------

    def force_flush(self):
        self.table.flush_to_disk()
        self.idb.flush()

    def force_merge(self):
        self.table.force_merge(self.idb.deleted_metric_ids,
                               self.min_valid_ts)

    @property
    def min_valid_ts(self) -> int:
        return fasttime.unix_ms() - self.retention_ms

    def tier_deadlines(self, now_ms: int | None = None) -> list:
        """``[(resolution_ms, tier_min_valid_ts_or_None)]`` for the
        configured tiers (None = that tier keeps its data forever)."""
        now = fasttime.unix_ms() if now_ms is None else now_ms
        return [(t.resolution_ms,
                 (now - t.retention_ms) if t.retention_ms > 0 else None)
                for t in self.downsample_tiers]

    def enforce_retention(self, now_ms: int | None = None) -> int:
        now = fasttime.unix_ms() if now_ms is None else now_ms
        min_valid = now - self.retention_ms
        deadlines = self.tier_deadlines(now)
        n = self.table.enforce_retention(min_valid, deadlines)
        # the index (metric names, per-day entries) must outlive every
        # tier that still serves samples: months are dropped at the
        # OLDEST live deadline, and never while a tier keeps-forever
        idb_min = min_valid
        for _, d in deadlines:
            if d is None:
                idb_min = None
                break
            idb_min = min(idb_min, d)
        dropped_months = (self.idb.drop_months_before(idb_min)
                          if idb_min is not None else 0)
        n += dropped_months
        if dropped_months:
            # a later backfill into a dropped date must recreate its
            # per-day index entries
            min_date = idb_min // 86_400_000
            for shard in self._shards:
                with shard.lock:
                    dead = {dk for dk in shard.day_cache
                            if dk[1] < min_date}
                    shard.day_cache -= dead
        if n:
            with self._lock:
                # after the drop; no-op sweeps keep tiles
                self.data_version += 1
                self.structural_version += 1
        return n

    # -- snapshots ---------------------------------------------------------

    def snapshots_dir(self) -> str:
        return os.path.join(self.path, "snapshots")

    def create_snapshot(self) -> str:
        """Instant snapshot via hardlinks (MustCreateSnapshot,
        storage.go:411); name format YYYYMMDDhhmmss-seq."""
        name = time.strftime("%Y%m%d%H%M%S") + \
            f"-{fasttime.unix_ns() % 10000:04d}"
        dst = os.path.join(self.snapshots_dir(), name)
        self.table.snapshot_to(os.path.join(dst, "data"))
        # crashpoint: dying here leaves a half-built snapshot dir — the
        # live store is untouched (hardlinks only) and the partial
        # snapshot is inert, never auto-restored
        faultinject.fire("snapshot:mid")
        self.idb.table.create_snapshot_at(
            os.path.join(dst, "indexdb", "global"))
        for mname, t in self.idb.snapshot_month_tables():
            t.create_snapshot_at(os.path.join(dst, "indexdb", "months",
                                              mname))
        shutil.copy(os.path.join(self.path, "format.json"),
                    os.path.join(dst, "format.json"))
        logger.infof("storage: created snapshot %s", name)
        return name

    def list_snapshots(self) -> list[str]:
        d = self.snapshots_dir()
        if not os.path.isdir(d):
            return []
        return sorted(os.listdir(d))

    def delete_snapshot(self, name: str) -> bool:
        full = os.path.join(self.snapshots_dir(), name)
        if not os.path.isdir(full):
            return False
        shutil.rmtree(full)
        return True

    # -- metrics -----------------------------------------------------------

    def metrics(self) -> dict[str, float]:
        out = {
            "vm_rows_added_to_storage_total": self.rows_added,
            "vm_rows": self.table.rows,
            "vm_new_timeseries_created_total": self.new_series_created,
            "vm_slow_row_inserts_total": self.slow_row_inserts,
            "vm_timeseries_total": self.idb.all_series_count(),
            "vm_partitions": len(self.table.partition_names),
        }
        if self.downsample_tiers:
            by_res: dict[int, int] = {}
            with self.table._lock:
                parts = list(self.table._partitions.values())
            for p in parts:
                for st in p.tier_states():
                    by_res[st.resolution_ms] = \
                        by_res.get(st.resolution_ms, 0) + st.rows
            for res, rows in sorted(by_res.items()):
                out[f'vm_downsample_tier_rows{{resolution="{res}"}}'] = rows
        for lim in (self.hourly_limiter, self.daily_limiter):
            if lim is not None:
                out.update(lim.metrics())
        return out
