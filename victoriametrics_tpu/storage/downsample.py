"""Background downsampling & per-resolution retention tiers.

Modeled on the reference's historicalMergeWatcher final-dedup pass
(lib/storage/table.go:474) and the -downsampling.period flag family: aged
raw data is re-rolled into coarser-resolution parts, one aggregated sample
per bucket, keeping FIVE aggregate columns (last/min/max/count/sum) so
avg/min/max/count/rate/increase rollups stay answerable without the raw
stream.

Grammar (``VM_DOWNSAMPLE``): ``offset:resolution[:retention],...`` — e.g.
``30d:5m,180d:1h`` keeps data older than 30 days at 5-minute resolution
and data older than 180 days at 1-hour resolution. Offsets and resolutions
must be strictly increasing. A tier's retention defaults to the NEXT
tier's offset (its samples become redundant once the coarser tier covers
that age); the last tier keeps its data forever unless an explicit third
field bounds it. Raw retention (``Storage.retention_ms``) is unchanged.

Bucketing REUSES the query-time dedup window (dedup._buckets): windows are
right-inclusive at exact interval multiples, and the ``last`` column is
literally ``dedup.deduplicate`` at the tier resolution (highest timestamp
wins; timestamp ties prefer the max non-stale value), so query-time dedup
and downsampling can never disagree on a boundary. min/max/count/sum
aggregate the NON-stale samples of each bucket (the eval drops staleness
markers before those rollups, so the coarse columns must too); a bucket
whose samples are all staleness markers appears only in the ``last``
column, carrying the marker so ``default_rollup`` still terminates the
series.

On-disk layout, inside each monthly partition dir::

    <partition>/ds_<resolution_ms>/
        tier.json                  # manifest: resolution, coverage, parts
        p_<seq>_last/ ... p_<seq>_sum/   # ordinary Parts (PR-10 format)

tier.json carries a meta_crc like every other manifest; parts carry the
full per-file crc32 set.  The rewrite publishes part dirs first (each via
the PartWriter tmp+rename_durable seam), fires the
``downsample:post_rename_pre_manifest`` crashpoint, then commits tier.json
— a crash between the two leaves unlisted part dirs that the next open
sweeps, identical to the merge discipline.  A torn tier (bad tier.json or
a bad listed part) is quarantined WHOLE and the tier resets to empty
coverage: the next pass rebuilds it from whatever raw survives, and the
quarantine is reported loudly like any PR-10 quarantine.
"""

from __future__ import annotations

import os
import re
import shutil

import numpy as np

from ..utils import fs as fslib
from ..utils import logger
from ..utils import metrics as metricslib
from ..ops import decimal as dec
from .block import Block, rows_to_blocks
from .dedup import _buckets, deduplicate
from .part import Part, PartWriter

#: tier dir name prefix inside a partition dir: ds_<resolution_ms>
TIER_DIR_PREFIX = "ds_"
#: aggregate columns kept per bucket (part name suffix = column)
AGG_COLUMNS = ("last", "min", "max", "count", "sum")

_PASSES = metricslib.REGISTRY.counter("vm_downsample_passes_total")
_ROWS_IN = metricslib.REGISTRY.counter("vm_downsample_rows_in_total")
_ROWS_OUT = metricslib.REGISTRY.counter("vm_downsample_rows_out_total")
_PARTS = metricslib.REGISTRY.counter("vm_downsample_parts_total")
_DURATION = metricslib.REGISTRY.float_counter(
    "vm_downsample_duration_seconds_total")
_TIERS_QUARANTINED = metricslib.REGISTRY.counter(
    'vm_parts_quarantined_total{store="downsample"}')

_DUR_RE = re.compile(r"^(\d+)(ms|s|m|h|d|w|y)$")
_DUR_UNITS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000,
              "d": 86_400_000, "w": 7 * 86_400_000, "y": 365 * 86_400_000}


def parse_duration_ms(s: str) -> int:
    """``30d`` / ``5m`` / ``90s`` -> milliseconds (single unit, like the
    reference's -downsampling.period fields)."""
    m = _DUR_RE.match(s.strip())
    if m is None:
        raise ValueError(f"bad duration {s!r} (want <int><ms|s|m|h|d|w|y>)")
    return int(m.group(1)) * _DUR_UNITS[m.group(2)]


class Tier:
    """One downsampling tier: data older than ``offset_ms`` is kept at
    ``resolution_ms``; its parts are dropped once older than
    ``retention_ms`` (0 = kept forever)."""

    __slots__ = ("offset_ms", "resolution_ms", "retention_ms")

    def __init__(self, offset_ms: int, resolution_ms: int,
                 retention_ms: int = 0):
        self.offset_ms = offset_ms
        self.resolution_ms = resolution_ms
        self.retention_ms = retention_ms

    def __repr__(self):
        return (f"Tier(offset={self.offset_ms}ms, "
                f"res={self.resolution_ms}ms, keep={self.retention_ms}ms)")


def parse_spec(spec: str) -> list[Tier]:
    """``VM_DOWNSAMPLE`` grammar -> ordered tier list (finest first).

    ``offset:resolution[:retention]`` per tier, comma-separated; offsets
    and resolutions must be strictly increasing (the reference rejects
    non-monotonic -downsampling.period sets the same way), and each
    coarser resolution must be an integer MULTIPLE of the next finer
    one: the read path cascades coarse-tier -> fine-tier -> raw at the
    coarse tier's bucket-aligned watermark, which splits the finer
    tier's buckets cleanly only when the resolutions nest."""
    spec = (spec or "").strip()
    if not spec:
        return []
    tiers = []
    for item in spec.split(","):
        fields = item.strip().split(":")
        if len(fields) not in (2, 3):
            raise ValueError(
                f"bad VM_DOWNSAMPLE item {item!r} "
                f"(want offset:resolution[:retention])")
        off = parse_duration_ms(fields[0])
        res = parse_duration_ms(fields[1])
        keep = parse_duration_ms(fields[2]) if len(fields) == 3 else -1
        if res <= 0 or off <= 0:
            raise ValueError(f"bad VM_DOWNSAMPLE item {item!r}: "
                             f"offset/resolution must be positive")
        if keep >= 0 and keep <= off:
            raise ValueError(f"bad VM_DOWNSAMPLE item {item!r}: "
                             f"retention must exceed the offset")
        tiers.append((off, res, keep))
    tiers.sort()
    out = []
    for i, (off, res, keep) in enumerate(tiers):
        if i and res <= out[-1].resolution_ms:
            raise ValueError(
                "VM_DOWNSAMPLE resolutions must increase with offsets")
        if i and res % out[-1].resolution_ms:
            raise ValueError(
                "VM_DOWNSAMPLE resolutions must nest: each coarser "
                "resolution must be a multiple of the next finer one")
        if keep < 0:
            # default: redundant once the NEXT tier covers this age
            keep = tiers[i + 1][0] if i + 1 < len(tiers) else 0
        out.append(Tier(off, res, keep))
    return out


def note_pass(duration_s: float) -> None:
    """Account one completed per-partition/per-tier rewrite pass."""
    _PASSES.inc()
    _DURATION.inc(duration_s)


def read_enabled() -> bool:
    """``VM_DOWNSAMPLE_READ=0`` disables tier SELECTION at query time (the
    raw oracle escape hatch); the background rewrite keeps running.
    Re-read per call so tests and bench A/B legs can flip it live."""
    return os.environ.get("VM_DOWNSAMPLE_READ", "1") != "0"


def count_tail_piece(piece, as_float: bool):
    """Raw rows serving a COUNT-hinted fetch: each non-stale sample
    contributes 1 (its VALUE is not a count), so summing the mixed
    tier-count-column + raw-tail stream yields the true sample count.
    Staleness markers survive untouched — the eval-side stale drop must
    still see them.  Applied to every raw/mem piece of a count fetch
    (even when no tier ends up serving: a sum of ones IS the count, so
    the eval-level count->sum rewrite stays unconditional)."""
    if as_float:
        mids, cnts, ts_c, vals = piece
        return (mids, cnts, ts_c,
                np.where(dec.is_stale_nan(vals), vals, 1.0))
    mids, cnts, scales, ts_c, mant = piece
    mant = np.where(mant == dec.V_STALE_NAN, mant,
                    np.int64(1)).astype(np.int64)
    return (mids, cnts, np.zeros_like(scales), ts_c, mant)


# -- per-bucket aggregation ------------------------------------------------

def aggregate_series(ts: np.ndarray, vals: np.ndarray, res_ms: int):
    """One series' sorted raw rows -> per-bucket aggregate columns.

    Returns ``{agg: (out_ts, out_vals)}`` for the five AGG_COLUMNS.
    Output samples are stamped at the bucket's right edge (``bucket*res``)
    — the only timestamp guaranteed inside every right-inclusive rollup
    window that fully covers the bucket.

    ``last`` is exactly ``dedup.deduplicate(ts, vals, res_ms)`` restamped,
    so the query-time dedup path and the downsample path share one
    boundary/tie/stale-marker semantics by construction (the golden test
    pins this).  min/max/count/sum cover non-stale samples only."""
    keep_ts, keep_vals = deduplicate(ts, vals, res_ms)
    last_ts = _buckets(keep_ts, res_ms) * res_ms
    out = {"last": (last_ts, np.asarray(keep_vals, np.float64))}
    ns = ~dec.is_stale_nan(vals)
    if not ns.all():
        ts, vals = ts[ns], vals[ns]
    if ts.size == 0:
        empty = (np.zeros(0, np.int64), np.zeros(0, np.float64))
        for agg in ("min", "max", "count", "sum"):
            out[agg] = empty
        return out
    b = _buckets(ts, res_ms)
    starts = np.flatnonzero(np.r_[True, b[1:] != b[:-1]])
    ends = np.r_[starts[1:], ts.size]
    out_ts = b[starts] * res_ms
    vals = np.asarray(vals, np.float64)
    out["min"] = (out_ts, np.minimum.reduceat(vals, starts))
    out["max"] = (out_ts, np.maximum.reduceat(vals, starts))
    out["count"] = (out_ts, (ends - starts).astype(np.float64))
    # sequential per-bucket sums (np.add.reduceat): the batched rollup's
    # cumsum formulation matches this bit-exactly only for values without
    # accumulated rounding (the oracle tests use integer-representable
    # values; general floats agree to ~ulp — documented tolerance)
    out["sum"] = (out_ts, np.add.reduceat(vals, starts))
    return out


# -- one tier inside one partition -----------------------------------------

class PartitionTier:
    """Open state of ``<partition>/ds_<res>/``: manifest + Parts.

    NOT thread-safe on its own — the owning Partition serializes mutation
    under its flush mutex and snapshots ``parts_for`` under its data lock
    (same discipline as the raw part list)."""

    def __init__(self, path: str, resolution_ms: int):
        self.path = path
        self.resolution_ms = resolution_ms
        #: highest raw timestamp consumed into this tier (bucket-aligned
        #: right edge); rewrites resume strictly after it
        self.covered_max_ts = -(1 << 62)
        self._seq = 0
        #: agg column -> open Parts (time-ordered by construction)
        self._parts: dict[str, list[Part]] = {a: [] for a in AGG_COLUMNS}
        self._names: list[str] = []

    # -- lifecycle ---------------------------------------------------------

    def _manifest(self) -> str:
        return os.path.join(self.path, "tier.json")

    @classmethod
    def open(cls, path: str, resolution_ms: int, quarantined: list,
             partition_name: str) -> "PartitionTier":
        """Open an existing tier dir; integrity failures quarantine the
        WHOLE tier (coverage resets, the pass rebuilds from raw)."""
        self = cls(path, resolution_ms)
        listed: list[str] = []
        try:
            if os.path.exists(self._manifest()):
                meta = fslib.load_meta_json(self._manifest())
                if int(meta["resolutionMs"]) != resolution_ms:
                    raise fslib.IntegrityError(
                        f"tier dir {path} says resolutionMs="
                        f"{meta['resolutionMs']}")
                self.covered_max_ts = int(meta["coveredMaxTs"])
                listed = list(meta["parts"])
                for name in listed:
                    p = Part(os.path.join(path, name))
                    self._register_open_part(name, p)
        except (fslib.IntegrityError, ValueError, KeyError, OSError) as e:
            # torn tier: move the whole dir aside (PR-10 discipline) and
            # reset — downsampled data is derived, so the quarantine is
            # self-healing as long as raw survives, but it is REPORTED
            # like any other quarantine (results flagged partial)
            parent = os.path.dirname(path)
            name = os.path.basename(path)
            try:
                quarantined.append(fslib.quarantine_dir_entry(
                    parent, name, e, "downsample", partition_name))
                _TIERS_QUARANTINED.inc()
            except OSError as move_err:
                logger.errorf("downsample: cannot quarantine tier %s: %s",
                              path, move_err)
                shutil.rmtree(path, ignore_errors=True)
            return cls(path, resolution_ms)
        # sweep crash leftovers: part dirs (or .tmp dirs) not in tier.json
        for name in os.listdir(path):
            full = os.path.join(path, name)
            if name == "tier.json" or not os.path.isdir(full):
                continue
            if name not in listed:
                shutil.rmtree(full, ignore_errors=True)
        return self

    def _register_open_part(self, name: str, p: Part) -> None:
        agg = name.rsplit("_", 1)[-1]
        if agg not in AGG_COLUMNS:
            raise ValueError(f"tier part {name!r} has no aggregate suffix")
        self._parts[agg].append(p)
        self._names.append(name)
        seq = int(name.split("_")[1])
        self._seq = max(self._seq, seq + 1)

    def close(self) -> None:
        for parts in self._parts.values():
            for p in parts:
                p.close()
            parts.clear()
        self._names = []

    # -- reads -------------------------------------------------------------

    @property
    def has_parts(self) -> bool:
        return bool(self._names)

    def parts_for(self, agg: str) -> list[Part]:
        return list(self._parts[agg])

    @property
    def rows(self) -> int:
        return sum(p.rows for parts in self._parts.values() for p in parts)

    # -- rewrite -----------------------------------------------------------

    def next_part_name(self) -> str:
        name = f"p_{self._seq:016d}"
        self._seq += 1
        return name

    def write_manifest(self) -> None:
        """Durably (re)commit tier.json via the standard tmp+rename seam.
        Callers fire ``downsample:post_rename_pre_manifest`` BETWEEN part
        publication and this commit."""
        os.makedirs(self.path, exist_ok=True)
        tmp = self._manifest() + ".tmp"
        fslib.write_meta_json(
            tmp,
            {"resolutionMs": self.resolution_ms,
             "coveredMaxTs": self.covered_max_ts,
             "parts": list(self._names)})
        fslib.rename_durable(tmp, self._manifest())

    def publish_parts(self, names: list[str], parts: dict[str, Part],
                      covered_max_ts: int) -> None:
        """Register freshly renamed part dirs + advance coverage (the
        manifest commit itself is the caller's write_manifest call)."""
        for name in names:
            self._register_open_part(name, parts[name.rsplit("_", 1)[-1]])
        self.covered_max_ts = covered_max_ts
        # keep _seq monotonic even when publish order races reopen
        self._seq = max(self._seq,
                        max(int(n.split("_")[1]) for n in names) + 1)


def rewrite_range(tier_state: PartitionTier, merged_blocks, hi: int,
                  resolution_ms: int) -> tuple[int, int, dict[str, Part],
                                               list[str]]:
    """Aggregate a (tsid, ts)-ordered merged block stream into one new
    part per aggregate column.

    ``merged_blocks`` yields Blocks already tombstone-filtered, deduped
    and left-clipped (``_merge_block_streams`` output); rows above ``hi``
    (the bucket-aligned age cutoff) are clipped here so a later pass
    re-reads them once their buckets complete.

    Returns ``(rows_in, rows_out, {agg: Part}, part_names)`` — parts are
    renamed into place (durable) but NOT yet listed in tier.json; the
    caller fires the crash seam and commits the manifest.  Returns
    ``(0, 0, {}, [])`` when the range holds no rows."""
    base = tier_state.next_part_name()
    writers = {agg: PartWriter(os.path.join(tier_state.path,
                                            f"{base}_{agg}"),
                               resolution_ms=resolution_ms)
               for agg in AGG_COLUMNS}
    bufs: dict[str, list[Block]] = {agg: [] for agg in AGG_COLUMNS}
    rows_in = rows_out = 0

    def emit(tsid, ts_cat, val_cat):
        nonlocal rows_in, rows_out
        rows_in += int(ts_cat.size)
        for agg, (ots, ovals) in aggregate_series(
                ts_cat, val_cat, resolution_ms).items():
            if ots.size == 0:
                continue
            # clamp the final bucket's stamp into the rewritten range:
            # at a partition seam `hi` is the partition's last inclusive
            # ms, NOT bucket-aligned, and the right-inclusive bucket
            # ending at the next midnight belongs to the NEXT partition
            # too — an unclamped stamp would collide with that
            # partition's first bucket and assembly would drop one of
            # the duplicate-ts rows (under-counting the seam window).
            # Ordering survives: only the last bucket can exceed `hi`.
            np.minimum(ots, hi, out=ots)
            if agg == "last":
                rows_out += int(ots.size)
            for blk in rows_to_blocks(tsid, ots, ovals):
                bufs[agg].append(blk)
            if len(bufs[agg]) >= 1024:
                writers[agg].write_blocks_bulk(bufs[agg])
                bufs[agg] = []

    try:
        cur_tsid = None
        ts_acc: list[np.ndarray] = []
        val_acc: list[np.ndarray] = []
        for b in merged_blocks:
            ts = b.timestamps
            if int(ts[0]) > hi:
                continue
            vals = b.float_values()
            if int(ts[-1]) > hi:
                n = int(np.searchsorted(ts, hi, side="right"))
                ts, vals = ts[:n], vals[:n]
            if cur_tsid is not None and \
                    b.tsid.metric_id != cur_tsid.metric_id:
                emit(cur_tsid, np.concatenate(ts_acc),
                     np.concatenate(val_acc))
                ts_acc, val_acc = [], []
            cur_tsid = b.tsid
            ts_acc.append(ts)
            val_acc.append(vals)
        if cur_tsid is not None and ts_acc:
            emit(cur_tsid, np.concatenate(ts_acc), np.concatenate(val_acc))
        if rows_out == 0:
            for w in writers.values():
                w.abort()
            return 0, 0, {}, []
        parts: dict[str, Part] = {}
        names: list[str] = []
        for agg in AGG_COLUMNS:
            if bufs[agg]:
                writers[agg].write_blocks_bulk(bufs[agg])
            if writers[agg].rows == 0:
                # possible only when every bucket in range was all-stale
                # for this column; publish no dir for it
                writers[agg].abort()
                continue
            writers[agg].close()
            parts[agg] = Part(writers[agg].path, trusted=True)
            names.append(f"{base}_{agg}")
    except BaseException:
        for w in writers.values():
            w.abort()
        raise
    _ROWS_IN.inc(rows_in)
    _ROWS_OUT.inc(rows_out)
    _PARTS.inc(len(names))
    return rows_in, rows_out, parts, names
