"""Tag filters: the compiled form of `{label op "value"}` selectors
(reference lib/storage/tag_filters.go; regex or-suffix expansion
regexutil analog).

A TagFilter matches label values for one key with one of four ops:
  =  (negate=False, regex=False)     != (negate=True, regex=False)
  =~ (negate=False, regex=True)      !~ (negate=True, regex=True)

The metric group (__name__) is filter key b"" in the index, matching the
reference's convention of indexing the name as the empty tag key.

Regexes that are plain literal alternations (`a|b|c`, possibly with a common
literal prefix like `api_(get|put)`) expand to exact-value lists so they use
posting lookups instead of full value scans (the reference's or-values
optimization, regexutil.Simplify).
"""

from __future__ import annotations

import re


def _try_literal_alternation(expr: str) -> list[str] | None:
    """Expand a pure literal alternation regex into its values, else None."""
    # strip one redundant non-capturing/capturing group around the whole expr
    if not expr:
        return [""]
    specials = set(".+*?[]{}^$\\()|")
    # split on top-level | inside at most one group level
    def split_top(e: str) -> list[str] | None:
        parts, depth, cur = [], 0, []
        for ch in e:
            if ch == "(":
                depth += 1
                if depth == 1:
                    continue
            elif ch == ")":
                depth -= 1
                if depth < 0:
                    return None
                if depth == 0:
                    continue
            elif ch == "|" and depth <= 0:
                parts.append("".join(cur))
                cur = []
                continue
            cur.append(ch)
        if depth != 0:
            return None
        parts.append("".join(cur))
        return parts

    # common case: prefix(group of alternatives) with literal prefix
    m = re.fullmatch(r"([^.+*?\[\]{}^$\\()|]*)\(([^()]*)\)", expr)
    if m and "|" in m.group(2):
        prefix, alts = m.group(1), m.group(2).split("|")
        if all(not (set(a) & specials) for a in alts):
            return [prefix + a for a in alts]
    parts = split_top(expr)
    if parts is None:
        return None
    if any(set(p) & specials for p in parts):
        return None
    return parts


class TagFilter:
    __slots__ = ("key", "value", "negate", "regex", "_re", "or_values")

    def __init__(self, key: bytes, value: bytes, negate: bool = False,
                 regex: bool = False):
        self.key = key
        self.value = value
        self.negate = negate
        self.regex = regex
        self._re = None
        self.or_values: list[bytes] | None = None
        if regex:
            expr = value.decode()
            vals = _try_literal_alternation(expr)
            if vals is not None:
                self.or_values = [v.encode() for v in vals]
            else:
                # fully-anchored match, Prometheus semantics
                self._re = re.compile("(?:" + expr + ")\\Z")
        else:
            self.or_values = [value]

    @property
    def is_empty_match(self) -> bool:
        """Does this filter match a missing label? (e.g. x="" or x=~"a?")"""
        if not self.regex:
            return (self.value == b"") != self.negate
        if self.or_values is not None:
            return (b"" in self.or_values) != self.negate
        return bool(self._re.match("")) != self.negate

    def match_value(self, v: bytes) -> bool:
        if self.or_values is not None:
            ok = v in self.or_values
        else:
            try:
                ok = bool(self._re.match(v.decode("utf-8", "replace")))
            except re.error:  # pragma: no cover
                ok = False
        return ok != self.negate

    def __repr__(self):
        op = {(False, False): "=", (True, False): "!=",
              (False, True): "=~", (True, True): "!~"}[(self.negate, self.regex)]
        return f"{self.key.decode() or '__name__'}{op}{self.value.decode()!r}"


def filters_from_dict(d: dict) -> list[TagFilter]:
    """Convenience: {'__name__': 'http_requests', 'job': ('=~', 'a|b')}."""
    out = []
    for k, v in d.items():
        key = b"" if k == "__name__" else k.encode()
        if isinstance(v, tuple):
            op, val = v
            out.append(TagFilter(key, val.encode(), negate=op in ("!=", "!~"),
                                 regex=op in ("=~", "!~")))
        else:
            out.append(TagFilter(key, v.encode()))
    return out
