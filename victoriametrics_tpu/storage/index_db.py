"""Inverted index over the mergeset (reference lib/storage/index_db.go).

Nine key namespaces (index_db.go:35-71 analog), all items in one mergeset
table, 1-byte namespace prefix. T = tenant prefix accountID(4B BE)
projectID(4B BE) (marshalCommonPrefix analog) — metricID-keyed namespaces
are global because metricIDs are unique across tenants:

  0  T metricName(marshaled)        -> TSID          per-tenant registry
  1  T tag(k 0x01 v) 0x00 metricID  -> (exists)      posting lists
  2  metricID(8B BE)                -> TSID
  3  metricID(8B BE)                -> metricName
  4  metricID(8B BE)                -> (deleted)     tombstones
  5  T date(4B BE) metricID         -> (exists)      per-day series
  6  T date(4B BE) tag 0x00 metricID-> (exists)      per-day postings
  7  T date(4B BE) metricName       -> TSID          per-day registry
  8  T                              -> (exists)      tenant listing

The metric group is indexed as tag key b"" (like the reference). Values use
the escaped metric-name encoding so 0x00/0x01 separators are unambiguous and
prefix scans work.

Set algebra over posting lists uses sorted uint64 numpy arrays — the
uint64set analog; intersections/unions/subtractions are vectorized.
"""

from __future__ import annotations

import os
import struct

import numpy as np

from ..devtools import faultinject
from ..devtools.locktrace import make_lock
from ..devtools.racetrace import traced_fields
from ..utils import metrics as metricslib
from ..utils.workingset import WorkingSetCache
from .mergeset import Table
from .metric_name import MetricName, escape, unescape
from .tag_filters import TagFilter
from .tsid import TSID

# posting-cache traffic, reference vm_cache_{requests,misses}_total shape
# (global across IndexDB instances; per-instance counts come from the
# read-only filter_cache_* property shims)
_FILTER_CACHE_REQUESTS = metricslib.REGISTRY.counter(
    'vm_cache_requests_total{type="indexdb/tagFilters"}')
_FILTER_CACHE_MISSES = metricslib.REGISTRY.counter(
    'vm_cache_misses_total{type="indexdb/tagFilters"}')

NS_NAME_TO_TSID = b"\x00"
NS_TAG_TO_MID = b"\x01"
NS_MID_TO_TSID = b"\x02"
NS_MID_TO_NAME = b"\x03"
NS_DELETED = b"\x04"
NS_DATE_TO_MID = b"\x05"
NS_DATE_TAG_TO_MID = b"\x06"
NS_DATE_NAME_TO_TSID = b"\x07"
NS_TENANTS = b"\x08"

_U64 = struct.Struct(">Q")
_U32 = struct.Struct(">I")
_TEN = struct.Struct(">II")  # accountID, projectID


def tenant_prefix(tenant) -> bytes:
    return _TEN.pack(tenant[0], tenant[1])

MS_PER_DAY = 86_400_000


def date_of_ms(ts_ms: int) -> int:
    return ts_ms // MS_PER_DAY


def _tag_key_bytes(key: bytes, value: bytes) -> bytes:
    return escape(key) + b"\x01" + escape(value) + b"\x00"


@traced_fields("_deleted", "_gen", "_filter_cache", "_filter_cache_prev",
               "_tsids_result_cache")
class IndexDB:
    """One index table + in-memory caches.

    Caches (reference lib/storage/index_db.go:306-361 analogs):
    - metricID->MetricName / metricID->TSID maps: entries are immutable
      once created (append-only LSM), so they never go stale; bounded by
      two-generation rotation at MAX_ID_CACHE (workingsetcache analog —
      no multi-million-entry wipe on the hot path, the working set
      survives each rotation).
    - tagFilters->metricIDs posting cache: keyed by (filters, date range),
      invalidated via a generation counter bumped on every index write —
      steady-state ingest (no new series) leaves the generation stable.
      Also generation-rotated on overflow instead of cleared.
    """

    MAX_ID_CACHE = 1 << 20
    MAX_FILTER_CACHE = 1024

    def __init__(self, path: str):
        self.path = path
        # global table in its own subdir: the month tables live under
        # months/ and must not be scanned as parts of the global table
        self.table = Table(os.path.join(path, "global"))
        # per-month tables hold the per-day namespaces (5/6/7) so retention
        # can drop a month's index with its data partition (the reference's
        # per-partition indexDB, storage.go:1094); the global table keeps
        # the registry namespaces (0/2/3/4/8) and undated postings (1).
        self._month_tables: dict[str, Table] = {}
        months_dir = os.path.join(path, "months")
        if os.path.isdir(months_dir):
            for name in sorted(os.listdir(months_dir)):
                if len(name) == 7 and name[4] == "_":
                    self._month_tables[name] = Table(
                        os.path.join(months_dir, name))
        self._lock = make_lock("storage.IndexDB._lock")
        self._deleted = self._load_deleted()
        self._gen = 0
        self._name_cache = WorkingSetCache(self.MAX_ID_CACHE,
                                           "indexdb.name_cache")
        self._tsid_cache = WorkingSetCache(self.MAX_ID_CACHE,
                                           "indexdb.tsid_cache")
        self._filter_cache: "dict[tuple, tuple[int, np.ndarray]]" = {}
        self._filter_cache_prev: "dict[tuple, tuple[int, np.ndarray]]" = {}
        self._tsids_result_cache: "dict[tuple, tuple[int, list]]" = {}
        # registry-backed traffic counters with per-instance shims (the
        # legacy filter_cache_requests/filter_cache_hits attributes are
        # read-only properties over these)
        self._filter_cache_requests = metricslib.Counter("requests")
        self._filter_cache_hits = metricslib.Counter("hits")

    @property
    def filter_cache_requests(self) -> int:
        return self._filter_cache_requests.get()

    @property
    def filter_cache_hits(self) -> int:
        return self._filter_cache_hits.get()

    def close(self):
        self.table.close()
        for t in self._month_tables.values():
            t.close()

    def flush(self):
        self.table.flush_to_disk()
        for t in self._month_tables.values():
            t.flush_to_disk()

    @staticmethod
    def _month_of_date(date: int) -> str:
        import datetime as _dt
        d = _dt.datetime.fromtimestamp(date * 86_400,
                                       tz=_dt.timezone.utc)
        return f"{d.year:04d}_{d.month:02d}"

    def _day_table(self, date: int) -> Table:
        """Month table for writes (created on demand)."""
        name = self._month_of_date(date)
        # racy-by-design fast path of a double-checked create: a stale
        # miss re-checks under _lock; a published Table is immutable here
        t = self._month_tables.get(name)  # vmt: disable=VMT015
        if t is None:
            with self._lock:
                t = self._month_tables.get(name)
                if t is None:
                    t = Table(os.path.join(self.path, "months", name))
                    self._month_tables[name] = t
        return t

    def _day_table_ro(self, date: int) -> Table | None:
        """Month table for reads: None when the month has no index (never
        written or dropped by retention) — reads must not create dirs."""
        return self._month_tables.get(self._month_of_date(date))

    def snapshot_month_tables(self) -> list:
        with self._lock:
            return list(self._month_tables.items())

    def quarantined(self) -> list[dict]:
        """Open-time integrity quarantines across the global table and
        every month table (recovery parity: the indexdb stores get the
        same loud torn-part handling as data parts)."""
        with self._lock:
            tables = [self.table] + list(self._month_tables.values())
        return [q for t in tables for q in t.quarantined]

    def drop_months_before(self, min_valid_ts: int) -> int:
        """Drop whole month index tables older than retention (the
        per-partition indexDB rotation; returns count)."""
        import shutil
        min_month = self._month_of_date(min_valid_ts // MS_PER_DAY)
        dropped = 0
        with self._lock:
            for name in list(self._month_tables):
                if name < min_month:
                    t = self._month_tables.pop(name)
                    t.close()
                    # crashpoint: dying between unlist and rmtree leaves
                    # the month dir on disk — it is rediscovered (and
                    # re-dropped) at the next open, never half-deleted
                    # under a live table object
                    faultinject.fire("indexdb:rotate")
                    shutil.rmtree(t.path, ignore_errors=True)
                    dropped += 1
                    self._gen += 1
        return dropped

    def _bump_gen(self):
        with self._lock:
            self._gen += 1

    def _cache_ids(self, cache: WorkingSetCache, key: int, value) -> None:
        # two-generation rotation on overflow (no wipe): see WorkingSetCache
        cache.put(key, value)

    # -- writes ------------------------------------------------------------

    def create_indexes_for_metric(self, mn: MetricName, tsid: TSID) -> None:
        """Global (date-independent) indexes for a new series
        (createGlobalIndexes, index_db.go:428 analog). The tenant rides in
        the TSID (account_id/project_id)."""
        ten = _TEN.pack(tsid.account_id, tsid.project_id)
        name_raw = mn.marshal()
        tsid_b = tsid.marshal()
        mid = _U64.pack(tsid.metric_id)
        items = [
            NS_NAME_TO_TSID + ten + name_raw + b"\x00" + tsid_b,
            NS_MID_TO_TSID + mid + tsid_b,
            NS_MID_TO_NAME + mid + name_raw,
            NS_TAG_TO_MID + ten + _tag_key_bytes(b"", mn.metric_group) + mid,
            NS_TENANTS + ten,
        ]
        for k, v in mn.labels:
            items.append(NS_TAG_TO_MID + ten + _tag_key_bytes(k, v) + mid)
        self.table.add_items(items)
        self._bump_gen()

    def create_per_day_indexes(self, mn: MetricName, tsid: TSID, date: int) -> None:
        """(date, X) indexes binding the series to one day
        (updatePerDateData analog, storage.go:2261)."""
        ten = _TEN.pack(tsid.account_id, tsid.project_id)
        d = ten + _U32.pack(date)
        mid = _U64.pack(tsid.metric_id)
        items = [
            NS_DATE_TO_MID + d + mid,
            NS_DATE_NAME_TO_TSID + d + mn.marshal() + b"\x00" + tsid.marshal(),
            NS_DATE_TAG_TO_MID + d + _tag_key_bytes(b"", mn.metric_group) + mid,
        ]
        for k, v in mn.labels:
            items.append(NS_DATE_TAG_TO_MID + d + _tag_key_bytes(k, v) + mid)
        self._day_table(date).add_items(items)
        self._bump_gen()

    def delete_series_by_ids(self, metric_ids: np.ndarray) -> int:
        items = [NS_DELETED + _U64.pack(int(m)) for m in metric_ids]
        self.table.add_items(items)
        with self._lock:
            self._deleted = np.union1d(self._deleted, metric_ids)
        self._bump_gen()
        return len(items)

    # -- point lookups -----------------------------------------------------

    def get_tsid_by_name(self, mn_marshaled: bytes,
                         tenant=(0, 0)) -> TSID | None:
        prefix = NS_NAME_TO_TSID + tenant_prefix(tenant) + \
            mn_marshaled + b"\x00"
        item = self.table.first_with_prefix(prefix)
        if item is None:
            return None
        return TSID.unmarshal(item[len(prefix):])

    def get_metric_name_by_id(self, metric_id: int) -> MetricName | None:
        got = self.get_metric_name_raw_by_id(metric_id)
        return got[0] if got is not None else None

    def get_metric_name_raw_by_id(self, metric_id: int
                                  ) -> tuple[MetricName, bytes] | None:
        """(MetricName, marshaled bytes) — the raw form doubles as a cheap
        sort/group key so hot paths skip re-marshaling."""
        got = self._name_cache.get(metric_id)
        if got is not None:
            return got
        prefix = NS_MID_TO_NAME + _U64.pack(metric_id)
        item = self.table.first_with_prefix(prefix)
        if item is None:
            return None
        raw = item[len(prefix):]
        got = (MetricName.unmarshal(raw), raw)
        self._cache_ids(self._name_cache, metric_id, got)
        return got

    def get_tsid_by_id(self, metric_id: int) -> TSID | None:
        t = self._tsid_cache.get(metric_id)
        if t is not None:
            return t
        prefix = NS_MID_TO_TSID + _U64.pack(metric_id)
        item = self.table.first_with_prefix(prefix)
        if item is None:
            return None
        t = TSID.unmarshal(item[len(prefix):])
        self._cache_ids(self._tsid_cache, metric_id, t)
        return t

    def get_metric_names_by_ids(self, metric_ids
                                ) -> dict[int, tuple[MetricName, bytes]]:
        """Batched metricID->(MetricName, raw) resolution: one cached-block
        bisect per missing id instead of a merge-iteration per id."""
        out: dict[int, tuple[MetricName, bytes]] = {}
        for mid in metric_ids:
            mid = int(mid)
            got = self.get_metric_name_raw_by_id(mid)
            if got is not None:
                out[mid] = got
        return out

    # -- deleted set -------------------------------------------------------

    def _load_deleted(self) -> np.ndarray:
        ids = [_U64.unpack(item[1:9])[0]
               for item in self.table.search_prefix(NS_DELETED)]
        return np.array(sorted(ids), dtype=np.uint64)

    @property
    def deleted_metric_ids(self) -> np.ndarray:
        with self._lock:
            return self._deleted

    # -- posting scans -----------------------------------------------------

    def _postings_for_tag(self, key: bytes, value: bytes,
                          date: int | None = None,
                          tenant=(0, 0)) -> np.ndarray:
        ten = tenant_prefix(tenant)
        if date is None:
            table = self.table
            prefix = NS_TAG_TO_MID + ten + _tag_key_bytes(key, value)
        else:
            table = self._day_table_ro(date)
            if table is None:
                return np.array([], dtype=np.uint64)
            prefix = NS_DATE_TAG_TO_MID + ten + _U32.pack(date) + \
                _tag_key_bytes(key, value)
        ids = [_U64.unpack(item[-8:])[0]
               for item in table.search_prefix(prefix)]
        return np.array(sorted(ids), dtype=np.uint64)

    def _iter_tag_values(self, key: bytes, date: int | None = None,
                         tenant=(0, 0)):
        """Yield (value, metric_id) pairs for one tag key."""
        ten = tenant_prefix(tenant)
        if date is None:
            table = self.table
            prefix = NS_TAG_TO_MID + ten + escape(key) + b"\x01"
        else:
            table = self._day_table_ro(date)
            if table is None:
                return
            prefix = NS_DATE_TAG_TO_MID + ten + _U32.pack(date) + \
                escape(key) + b"\x01"
        plen = len(prefix)
        for item in table.search_prefix(prefix):
            body = item[plen:]
            # fixed-width tail: 0x00 separator + 8-byte BE metric_id (which
            # itself may contain 0x00 bytes, so never search for the NUL)
            sep = len(body) - 9
            if sep < 0 or body[sep] != 0:
                raise ValueError("corrupted tag->metricID index item")
            yield unescape(body[:sep]), _U64.unpack(body[sep + 1:])[0]

    def _metric_ids_for_date(self, date: int, tenant=(0, 0)) -> np.ndarray:
        table = self._day_table_ro(date)
        if table is None:
            return np.array([], dtype=np.uint64)
        prefix = NS_DATE_TO_MID + tenant_prefix(tenant) + _U32.pack(date)
        ids = [_U64.unpack(item[-8:])[0]
               for item in table.search_prefix(prefix)]
        return np.array(sorted(ids), dtype=np.uint64)

    def _all_metric_ids(self, tenant=(0, 0)) -> np.ndarray:
        # every series has exactly one metric-group posting (tag key b"");
        # scanning it under the tenant prefix enumerates the tenant
        ids = [_U64.unpack(item[-8:])[0] for item in self.table.search_prefix(
            NS_TAG_TO_MID + tenant_prefix(tenant) + b"\x01")]
        return np.array(sorted(ids), dtype=np.uint64)

    def all_series_count(self) -> int:
        """Global series count across every tenant (vm_timeseries_total)."""
        return sum(1 for _ in self.table.search_prefix(NS_MID_TO_TSID))

    def tenants(self) -> list[tuple[int, int]]:
        """Distinct (accountID, projectID) pairs (tenants_v1 analog)."""
        out = []
        for item in self.table.search_prefix(NS_TENANTS):
            a, p = _TEN.unpack(item[1:9])
            out.append((a, p))
        return sorted(set(out))

    def _metric_ids_for_filter(self, tf: TagFilter, date: int | None,
                               tenant=(0, 0)) -> np.ndarray:
        """Posting set for the *positive form* of the filter, i.e. ids whose
        label value matches value/regex ignoring negation (negation is set
        subtraction in the caller)."""
        if tf.or_values is not None:
            sets = [self._postings_for_tag(tf.key, v, date, tenant)
                    for v in tf.or_values if v != b""]
            sets = [s for s in sets if s.size]
            return (np.unique(np.concatenate(sets))
                    if sets else np.array([], dtype=np.uint64))
        ids = [mid for v, mid in self._iter_tag_values(tf.key, date, tenant)
               if bool(tf._re.match(v.decode("utf-8", "replace")))]
        return np.unique(np.array(ids, dtype=np.uint64)) if ids else \
            np.array([], dtype=np.uint64)

    # -- search ------------------------------------------------------------

    MAX_DAYS_PER_DAY_INDEX = 40

    def search_metric_ids(self, filters: list[TagFilter],
                          min_ts: int | None = None,
                          max_ts: int | None = None,
                          tenant=(0, 0), check=None) -> np.ndarray:
        """Resolve tag filters to a sorted metricID array
        (searchMetricIDs, index_db.go:1685 analog), memoized in the
        tagFilters->metricIDs cache (index_db.go:336-361 analog).

        ``check`` (optional zero-arg callable) is the storage-side
        deadline budget's UNCONDITIONAL clock check: invoked between
        posting scans — each one a whole mergeset prefix iteration, so
        the per-call clock read is noise — so an expired query aborts
        mid-index-scan instead of completing the whole resolution for a
        dead caller.  (The cheap amortized tick belongs to per-item
        loops like search_tsids' resolution, not here: a filter x day
        matrix rarely reaches the tick's every-Nth threshold.)"""
        ckey = (tenant,
                tuple((tf.key, tf.value, tf.negate, tf.regex)
                      for tf in filters),
                None if min_ts is None else date_of_ms(min_ts),
                None if max_ts is None else date_of_ms(max_ts))
        self._filter_cache_requests.inc()
        _FILTER_CACHE_REQUESTS.inc()
        with self._lock:
            got = self._filter_cache.get(ckey)
            if got is None:
                # previous generation: promote hits instead of losing the
                # whole working set to an overflow wipe
                got = self._filter_cache_prev.get(ckey)
                if got is not None and got[0] == self._gen:
                    if len(self._filter_cache) >= self.MAX_FILTER_CACHE:
                        self._filter_cache_prev = self._filter_cache
                        self._filter_cache = {}
                    self._filter_cache[ckey] = got
            if got is not None and got[0] == self._gen:
                self._filter_cache_hits.inc()
                return got[1]
            gen = self._gen  # capture BEFORE the search: a concurrent index
            # write during the scan must invalidate what we store
        _FILTER_CACHE_MISSES.inc()
        result = self._search_metric_ids_uncached(filters, min_ts, max_ts,
                                                  tenant, check)
        with self._lock:
            # rotate only when inserting a NEW key into a full current
            # generation (refreshing a resident stale entry must not
            # discard the whole previous generation)
            if ckey not in self._filter_cache and \
                    len(self._filter_cache) >= self.MAX_FILTER_CACHE:
                self._filter_cache_prev = self._filter_cache
                self._filter_cache = {}
            self._filter_cache[ckey] = (gen, result)
        return result

    def _search_metric_ids_uncached(self, filters: list[TagFilter],
                                    min_ts: int | None = None,
                                    max_ts: int | None = None,
                                    tenant=(0, 0),
                                    check=None) -> np.ndarray:
        if check is None:
            def check():
                pass
        use_dates: list[int] | None = None
        if min_ts is not None and max_ts is not None:
            d0, d1 = date_of_ms(min_ts), date_of_ms(max_ts)
            if d1 - d0 + 1 <= self.MAX_DAYS_PER_DAY_INDEX:
                use_dates = list(range(d0, d1 + 1))

        def filter_set(tf: TagFilter) -> np.ndarray:
            if use_dates is not None:
                sets = []
                for d in use_dates:
                    check()  # budget: one check per per-day posting scan
                    sets.append(self._metric_ids_for_filter(tf, d, tenant))
                sets = [s for s in sets if s.size]
                return (np.unique(np.concatenate(sets)) if sets
                        else np.array([], dtype=np.uint64))
            check()
            return self._metric_ids_for_filter(tf, None, tenant)

        # Strong positives (don't match a missing label) seed the result via
        # posting intersections; everything else refines it. A missing label
        # reads as empty value "" (Prometheus matcher semantics).
        strong = [tf for tf in filters
                  if not tf.negate and not tf.is_empty_match]
        rest = [tf for tf in filters if tf not in strong]

        if strong:
            result: np.ndarray | None = None
            for tf in strong:
                s = filter_set(tf)
                result = s if result is None else \
                    np.intersect1d(result, s, assume_unique=True)
                if result.size == 0:
                    return result
        else:
            # no strong positive: start from the day universe (or everything)
            if use_dates is not None:
                sets = [self._metric_ids_for_date(d, tenant)
                        for d in use_dates]
                sets = [s for s in sets if s.size]
                result = (np.unique(np.concatenate(sets)) if sets
                          else np.array([], dtype=np.uint64))
            else:
                result = self._all_metric_ids(tenant)

        for tf in rest:
            if result.size == 0:
                break
            pos = TagFilter(tf.key, tf.value, negate=False, regex=tf.regex)
            matched = filter_set(pos)
            if tf.negate:
                survivors = np.setdiff1d(result, matched, assume_unique=True)
                if not tf.is_empty_match:
                    # e.g. x!="" / x!~"a?": a missing label would match the
                    # positive form, so only ids that HAVE the key survive
                    have_key = self._ids_with_key(tf.key, use_dates, tenant)
                    survivors = np.intersect1d(survivors, have_key,
                                               assume_unique=True)
                result = survivors
            else:
                # positive filter matching empty (x="" or x=~"a?"): keep ids
                # that either match the positive form or lack the label
                have_key = self._ids_with_key(tf.key, use_dates, tenant)
                lacking = np.setdiff1d(result, have_key, assume_unique=True)
                matching = np.intersect1d(result, matched, assume_unique=True)
                result = np.union1d(lacking, matching)

        # drop tombstoned series (snapshot under the lock: the deleted
        # array is replaced wholesale by delete_series_by_ids, so a
        # locked reference read is race-free and cheap)
        with self._lock:
            deleted = self._deleted
        if deleted.size:
            result = np.setdiff1d(result, deleted, assume_unique=True)
        return result

    def _ids_with_key(self, key: bytes, use_dates, tenant=(0, 0)) -> np.ndarray:
        ids = set()
        dates = use_dates if use_dates is not None else [None]
        for d in dates:
            for _, mid in self._iter_tag_values(key, d, tenant):
                ids.add(mid)
        return np.array(sorted(ids), dtype=np.uint64)

    MAX_TSIDS_CACHE = 256

    def search_tsids(self, filters: list[TagFilter],
                     min_ts: int | None = None,
                     max_ts: int | None = None, tenant=(0, 0),
                     check=None, scan_check=None) -> list[TSID]:
        # gen-validated result memo: a rolling dashboard repeats the same
        # selector every refresh; the id->TSID resolution + sort (~ms per
        # 10k series) would otherwise run every time
        ckey = (tenant,
                tuple((tf.key, tf.value, tf.negate, tf.regex)
                      for tf in filters),
                None if min_ts is None else date_of_ms(min_ts),
                None if max_ts is None else date_of_ms(max_ts))
        with self._lock:
            got = self._tsids_result_cache.get(ckey)
            if got is not None and got[0] == self._gen:
                return got[1]
            gen = self._gen
        # the posting scans get the UNCONDITIONAL clock check (coarse,
        # expensive units); the per-series loop below gets the amortized
        # tick (cheap, every Nth call reads the clock)
        mids = self.search_metric_ids(filters, min_ts, max_ts, tenant,
                                      scan_check if scan_check is not None
                                      else check)
        out = []
        for mid in mids:
            if check is not None:
                check()
            t = self.get_tsid_by_id(int(mid))
            if t is not None:
                out.append(t)
        out.sort(key=TSID.sort_key)
        with self._lock:
            if len(self._tsids_result_cache) >= self.MAX_TSIDS_CACHE:
                self._tsids_result_cache.clear()
            self._tsids_result_cache[ckey] = (gen, out)
        return out

    # -- label APIs --------------------------------------------------------

    def _date_range(self, min_ts, max_ts) -> list[int] | None:
        """Day list when the range is narrow enough for the per-day index."""
        if min_ts is None or max_ts is None:
            return None
        d0, d1 = date_of_ms(min_ts), date_of_ms(max_ts)
        if d1 - d0 + 1 > self.MAX_DAYS_PER_DAY_INDEX:
            return None
        return list(range(d0, d1 + 1))

    def label_names(self, min_ts=None, max_ts=None,
                    tenant=(0, 0)) -> list[str]:
        """Distinct label keys, time-scoped via the per-day index when the
        range is narrow (SearchLabelNames analog, index_db.go:507)."""
        ten = tenant_prefix(tenant)
        dates = self._date_range(min_ts, max_ts)
        seen_keys = set()
        if dates is None:
            prefix = NS_TAG_TO_MID + ten
            for item in self.table.search_prefix(prefix):
                body = item[len(prefix):]
                seen_keys.add(body[:body.index(b"\x01")])
        else:
            for d in dates:
                table = self._day_table_ro(d)
                if table is None:
                    continue
                prefix = NS_DATE_TAG_TO_MID + ten + _U32.pack(d)
                for item in table.search_prefix(prefix):
                    body = item[len(prefix):]
                    seen_keys.add(body[:body.index(b"\x01")])
        names = {unescape(k).decode("utf-8", "replace")
                 for k in seen_keys if k != b""}
        names.add("__name__")
        return sorted(names)

    def label_values(self, key: str, min_ts=None, max_ts=None,
                     tenant=(0, 0)) -> list[str]:
        kb = b"" if key == "__name__" else key.encode()
        dates = self._date_range(min_ts, max_ts)
        vals = set()
        for d in (dates if dates is not None else [None]):
            vals |= {v for v, _ in self._iter_tag_values(kb, d, tenant)}
        return sorted(v.decode("utf-8", "replace") for v in vals)
