"""Table: monthly partitions + retention (reference lib/storage/table.go:27,
retentionWatcher table.go:428)."""

from __future__ import annotations

import datetime
import os
import shutil
import numpy as np

from ..devtools.locktrace import make_rlock
from ..devtools.racetrace import traced_fields
from ..utils import flightrec, logger
from .partition import Partition


def partition_name_for_ts(ts_ms: int) -> str:
    d = datetime.datetime.fromtimestamp(ts_ms / 1e3, tz=datetime.timezone.utc)
    return f"{d.year:04d}_{d.month:02d}"


def _partition_bounds(name: str) -> tuple[int, int]:
    y, m = int(name[:4]), int(name[5:7])
    start = datetime.datetime(y, m, 1, tzinfo=datetime.timezone.utc)
    end = (datetime.datetime(y + 1, 1, 1, tzinfo=datetime.timezone.utc)
           if m == 12 else
           datetime.datetime(y, m + 1, 1, tzinfo=datetime.timezone.utc))
    return int(start.timestamp() * 1e3), int(end.timestamp() * 1e3) - 1


@traced_fields("_partitions", "_day_to_partition")
class Table:
    def __init__(self, path: str, dedup_interval_ms: int = 0):
        self.path = path
        self.dedup_interval_ms = dedup_interval_ms
        self._lock = make_rlock("storage.Table._lock")
        self._partitions: dict[str, Partition] = {}
        self._day_to_partition: dict[int, str] = {}
        os.makedirs(path, exist_ok=True)
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            if os.path.isdir(full) and len(name) == 7 and name[4] == "_":
                self._partitions[name] = Partition(full, name,
                                                   dedup_interval_ms)

    def close(self):
        with self._lock:
            for p in self._partitions.values():
                p.close()
            self._partitions.clear()

    def partition_for_ts(self, ts_ms: int) -> Partition:
        name = partition_name_for_ts(ts_ms)
        with self._lock:
            p = self._partitions.get(name)
            if p is None:
                p = Partition(os.path.join(self.path, name), name,
                              self.dedup_interval_ms)
                self._partitions[name] = p
            return p

    def add_rows(self, rows) -> None:
        """rows: [(TSID, ts_ms, float)] — routed to monthly partitions
        (MustAddRows, table.go:300). Day->name memo avoids a datetime
        conversion per row."""
        day_names = self._day_to_partition
        by_part: dict[str, list] = {}
        for r in rows:
            day = r[1] // 86_400_000
            name = day_names.get(day)
            if name is None:
                name = partition_name_for_ts(r[1])
                if len(day_names) > 4096:
                    day_names.clear()
                day_names[day] = name
            by_part.setdefault(name, []).append(r)
        for name, rs in by_part.items():
            self.partition_for_ts(rs[0][1]).add_rows(rs)

    def add_rows_columnar(self, space, ids, tss, vals) -> None:
        """Columnar batch -> monthly partitions. The common case (whole
        batch inside one month) routes with two scalar checks; straddling
        batches split by partition name over the distinct days."""
        from .partition import PendingChunk
        n = int(ids.size)
        if n == 0:
            return
        t_lo = int(tss.min())
        t_hi = int(tss.max())
        lo_name = partition_name_for_ts(t_lo)
        if partition_name_for_ts(t_hi) == lo_name:
            self.partition_for_ts(t_lo).add_rows_columnar(
                PendingChunk(space, ids, tss, vals))
            return
        days = tss // 86_400_000
        by_name: dict[str, list[int]] = {}
        for d in np.unique(days):
            by_name.setdefault(
                partition_name_for_ts(int(d) * 86_400_000), []).append(int(d))
        for name, ds in by_name.items():
            mask = np.isin(days, ds)
            self.partition_for_ts(int(ds[0]) * 86_400_000).add_rows_columnar(
                PendingChunk(space, ids[mask], tss[mask], vals[mask]))

    def partitions_for_range(self, min_ts: int, max_ts: int) -> list[Partition]:
        with self._lock:
            out = []
            for name, p in sorted(self._partitions.items()):
                lo, hi = _partition_bounds(name)
                if hi >= min_ts and lo <= max_ts:
                    out.append(p)
            return out

    def iter_blocks(self, tsid_set=None, min_ts=None, max_ts=None,
                    tsid_lo=None, tsid_hi=None):
        parts = (self.partitions_for_range(min_ts if min_ts is not None else -(1 << 62),
                                           max_ts if max_ts is not None else 1 << 62))
        for p in parts:
            yield from p.iter_blocks(tsid_set, min_ts, max_ts,
                                     tsid_lo, tsid_hi)

    def collect_columns(self, tsid_set=None, min_ts=None, max_ts=None,
                        tsid_lo=None, tsid_hi=None, mids_sorted=None,
                        as_float=False, check=None, ds=None, note=None):
        """Batched per-partition block collection (see
        Partition.collect_units); returns a flat list of pieces —
        mantissa 5-tuples, or float 4-tuples under ``as_float`` (the
        VM_NATIVE_ASSEMBLE fused kernel).

        ``check`` (optional zero-arg callable, the storage-side deadline
        budget) runs before each fetch unit: an expired query aborts
        between part decodes instead of fetching every remaining part
        for a dead caller (the exception propagates through the pool).

        The per-partition/per-part units fan across the shared work pool
        (utils/workpool — the netstorage unpack-worker role): the fused
        kernel / zstd + native decode release the GIL, so a cold
        multi-part fetch scales with cores.  The pool returns unit
        results in submit order, so the flattened piece list is
        bit-identical to sequential collection; VM_SEARCH_WORKERS=1 runs
        the exact sequential path."""
        parts = self.partitions_for_range(
            min_ts if min_ts is not None else -(1 << 62),
            max_ts if max_ts is not None else 1 << 62)
        if mids_sorted is None and tsid_set is not None:
            mids_sorted = np.fromiter(tsid_set, np.int64, len(tsid_set))
            mids_sorted.sort()
        units = []
        for p in parts:
            units.extend(p.collect_units(tsid_set, min_ts, max_ts,
                                         tsid_lo, tsid_hi, mids_sorted,
                                         as_float, ds, note))
        if check is not None:
            units = [(lambda u=u: (check(), u())[1]) for u in units]
        from ..utils import workpool
        return [piece for pieces in workpool.POOL.run(units)
                for piece in pieces]

    def enforce_retention(self, min_valid_ts: int,
                          tier_deadlines=None) -> int:
        """Drop data older than retention, PER TIER (retentionWatcher
        analog).  ``tier_deadlines`` is ``[(resolution_ms, tier_min_ts)]``
        with ``tier_min_ts=None`` meaning "keep forever".  A partition dir
        is removed whole only once EVERY tier (and raw) has expired;
        partitions past the raw deadline but inside a tier deadline lose
        only their raw parts, and each tier is dropped at its own
        deadline.  Returns the number of drop actions."""
        dropped = 0
        deadlines = list(tier_deadlines or ())
        full_drop_before = min_valid_ts
        for _, d in deadlines:
            if d is None:
                full_drop_before = None
                break
            full_drop_before = min(full_drop_before, d)
        with self._lock:
            items = list(self._partitions.items())
        for name, p in items:
            _, hi = _partition_bounds(name)
            if full_drop_before is not None and hi < full_drop_before:
                with self._lock:
                    p = self._partitions.pop(name, None)
                if p is None:
                    continue
                p.close()
                shutil.rmtree(p.path, ignore_errors=True)
                logger.infof("table: dropped partition %s (retention)",
                             name)
                dropped += 1
                continue
            if hi < min_valid_ts and deadlines:
                if p.drop_raw_parts():
                    logger.infof("table: dropped raw parts of %s "
                                 "(raw retention; tiers kept)", name)
                    dropped += 1
            for res, d in deadlines:
                if d is not None and hi < d and p.drop_tier(res):
                    logger.infof("table: dropped tier ds_%d of %s "
                                 "(tier retention)", res, name)
                    dropped += 1
        return dropped

    def run_downsample(self, tiers, deleted_ids=None,
                       now_ms=None) -> int:
        """One downsampling cycle across every partition (see
        Partition.run_downsample); returns aggregated rows written."""
        with self._lock:
            parts = list(self._partitions.values())
        written = 0
        with flightrec.span("downsample:table", arg=len(parts)):
            for p in parts:
                written += p.run_downsample(tiers, deleted_ids, now_ms)
        return written

    @staticmethod
    def _fan_partitions(parts, fn):
        """Run fn(partition) for every partition — across the shared
        work pool when the sharded write path is on and several
        partitions exist (flush/merge of different months are
        independent; the MERGE_GATE inside each bounds total disk
        concurrency at VM_MERGE_WORKERS).  Callers hold NO locks here,
        so the pool-helping wait is safe."""
        from ..utils import workpool
        if len(parts) > 1 and workpool.ingest_parallel_enabled():
            from functools import partial
            workpool.POOL.run([partial(fn, p) for p in parts])
        else:
            for p in parts:
                fn(p)

    def flush_pending(self):
        with self._lock:
            parts = list(self._partitions.values())
        self._fan_partitions(parts, lambda p: p.flush_pending())

    def flush_to_disk(self):
        with self._lock:
            parts = list(self._partitions.values())
        # the fan span shows the WHOLE flush window on the flight
        # timeline (per-partition flush:part spans nest inside it on
        # whichever threads the pool ran them)
        with flightrec.span("flush:table", arg=len(parts)):
            self._fan_partitions(parts, lambda p: p.flush_to_disk())

    def force_merge(self, deleted_ids=None, min_valid_ts=None):
        with self._lock:
            parts = list(self._partitions.values())
        with flightrec.span("merge:table", arg=len(parts)):
            self._fan_partitions(
                parts, lambda p: p.force_merge(deleted_ids, min_valid_ts))

    def snapshot_to(self, dst: str):
        os.makedirs(dst, exist_ok=True)
        with self._lock:
            parts = list(self._partitions.values())
        for p in parts:
            p.snapshot_to(os.path.join(dst, p.name))

    # -- live resharding (part migration) ----------------------------------

    def list_file_parts(self) -> list[dict]:
        """Migration inventory across every partition:
        ``{partition, part, rows, bytes, min_ts, max_ts}`` rows."""
        with self._lock:
            parts = list(self._partitions.items())
        out = []
        for name, p in sorted(parts):
            for row in p.list_file_parts():
                out.append(dict(row, partition=name))
        return out

    @staticmethod
    def is_partition_name(name: str) -> bool:
        """Strictly YYYY_MM — the form partition_name_for_ts emits.
        Anything else (in particular path-traversal bytes arriving in
        a migratePart_v1 partition field) is rejected."""
        return (len(name) == 7 and name[4] == "_" and
                name[:4].isdigit() and name[5:7].isdigit())

    def partition_by_name(self, name: str, create: bool = False):
        """Partition lookup by month name (adoption targets use
        create=True — the receiving node may not have the month yet).
        Non-YYYY_MM names never create (and never resolve) a
        partition: the name may come off the wire."""
        if not self.is_partition_name(name):
            return None
        with self._lock:
            p = self._partitions.get(name)
            if p is None and create:
                p = Partition(os.path.join(self.path, name), name,
                              self.dedup_interval_ms)
                self._partitions[name] = p
            return p

    def quarantined(self) -> list[dict]:
        """Open-time integrity quarantines across every partition (the
        loud replacement for silently dropping unopenable parts)."""
        with self._lock:
            parts = list(self._partitions.values())
        return [q for p in parts for q in p.quarantined]

    @property
    def rows(self) -> int:
        with self._lock:
            return sum(p.rows for p in self._partitions.values())

    @property
    def partition_names(self) -> list[str]:
        with self._lock:
            return sorted(self._partitions)
