"""Columnar series assembly: matched blocks -> padded (S, N) device-ready
columns in O(total_samples) vectorized passes.

This is the TPU-first replacement for the reference's per-series unpack
worker pool (app/vmselect/netstorage/netstorage.go:374-421): instead of
fanning per-series block unpacking across goroutines, ALL matched blocks are
decoded in one native call per part (part.read_blocks_columns) and scattered
into a padded (S, N) tile layout that the batched host rollup
(ops/rollup_np.rollup_batch_packed) and the device tile packer consume
without any per-series Python work.

Layout contract (shared with rollup_batch_packed and ops/device_rollup):
  ts    (S, N) int64, per-row sorted, padded with INT64_MAX
  vals  (S, N) float64, padding zeros (harmless for cumsum formulations)
  counts (S,) valid lengths
"""

from __future__ import annotations

import numpy as np

PAD_TS = np.iinfo(np.int64).max


class ColumnarSeries:
    """Padded columnar form of a search result; row order matches
    metric_ids/raw_names/metric_names."""

    __slots__ = ("metric_ids", "ts", "vals", "counts", "raw_names",
                 "metric_names", "stale_rows", "dropped_rows", "ds_res",
                 "partial_res")

    def __init__(self, metric_ids, ts, vals, counts, raw_names=None,
                 metric_names=None, stale_rows=None):
        self.metric_ids = metric_ids
        self.ts = ts
        self.vals = vals
        self.counts = counts
        self.raw_names = raw_names
        self.metric_names = metric_names
        # None = no staleness markers anywhere; else (S,) bool
        self.stale_rows = stale_rows
        # row indices (pre-drop numbering) removed as empty by the clip
        self.dropped_rows = None
        # downsampled-tier provenance (storage/downsample.py): coarsest
        # resolution actually served (0 = raw only), and whether a fetch
        # fell back to a tier coarser than the query's step allows
        self.ds_res = 0
        self.partial_res = False

    @classmethod
    def empty(cls) -> "ColumnarSeries":
        return cls(np.zeros(0, np.int64), np.zeros((0, 0), np.int64),
                   np.zeros((0, 0), np.float64), np.zeros(0, np.int64),
                   [], [])

    def compute_stale_rows(self) -> None:
        """Set stale_rows from the decoded values (staleness-marker
        presence per row; skips eval-side scans in the no-stale case)."""
        if not self.n_series:
            return
        from ..ops.decimal import is_stale_nan
        if bool(np.isnan(self.vals).any()):
            stale = is_stale_nan(self.vals)
            stale &= self.ts != PAD_TS
            rows = stale.any(axis=1)
            self.stale_rows = rows if bool(rows.any()) else None

    @property
    def n_series(self) -> int:
        return int(self.metric_ids.size)

    @property
    def n_samples(self) -> int:
        return int(self.counts.sum()) if self.counts.size else 0

    def ts_list(self) -> list[np.ndarray]:
        """Per-series timestamp views (for adjusted_windows etc.)."""
        c = self.counts
        return [self.ts[s, :c[s]] for s in range(self.n_series)]

    def to_series_list(self):
        """Materialize SeriesData views for per-series fallback paths."""
        from .storage import SeriesData
        out = []
        c = self.counts
        stale = self.stale_rows
        for s in range(self.n_series):
            n = int(c[s])
            sd = SeriesData(self.metric_names[s], self.ts[s, :n],
                            self.vals[s, :n], self.raw_names[s],
                            maybe_stale=bool(stale[s])
                            if stale is not None else False)
            out.append(sd)
        return out

    def drop_stale_nans(self):
        """Remove Prometheus staleness-marker samples in place (the
        eval-side dropStaleNaNs analog, but batched)."""
        if self.stale_rows is None:
            return
        from ..ops.decimal import is_stale_nan
        bad_rows = np.flatnonzero(self.stale_rows)
        for s in bad_rows:
            n = int(self.counts[s])
            stale = is_stale_nan(self.vals[s, :n])
            keep = ~stale
            m = int(keep.sum())
            if m == n:
                continue
            self.ts[s, :m] = self.ts[s, :n][keep]
            self.vals[s, :m] = self.vals[s, :n][keep]
            self.ts[s, m:n] = PAD_TS
            self.vals[s, m:n] = 0.0
            self.counts[s] = m
        self.stale_rows = None


def _ranges(cnts: np.ndarray, total: int) -> np.ndarray:
    """[0..c0) ++ [0..c1) ++ ... as one array."""
    excl = np.cumsum(cnts) - cnts
    return np.arange(total, dtype=np.int64) - np.repeat(excl, cnts)


def assemble(rows: np.ndarray, S: int, cnts: np.ndarray, ts_all: np.ndarray,
             vals_f: np.ndarray, min_ts: int, max_ts: int,
             dedup_interval_ms: int = 0,
             metric_ids: np.ndarray | None = None) -> ColumnarSeries:
    """Scatter per-block decoded samples into the padded (S, N) layout,
    then per-row sort-fix / range-clip / dedup — all mostly-vectorized with
    per-row work only on the (rare) rows that need it.

    `rows` assigns each block its target row (callers bake the final
    output ordering in here, so no post-assembly reorder pass is needed);
    `metric_ids` is the per-ROW id array (S,) carried through."""
    rows = np.asarray(rows, dtype=np.int64)
    cnts = np.asarray(cnts, dtype=np.int64)
    tot = int(cnts.sum())
    if metric_ids is None:
        metric_ids = np.zeros(S, np.int64)
    if S == 0 or tot == 0:
        return ColumnarSeries(metric_ids[:0], np.zeros((0, 0), np.int64),
                              np.zeros((0, 0), np.float64),
                              np.zeros(0, np.int64))
    blocks_per_row = np.bincount(rows, minlength=S)
    series_tot = np.bincount(rows, weights=cnts,
                             minlength=S).astype(np.int64)
    N = int(series_tot.max())
    from .. import native as _native
    single_block = bool((blocks_per_row <= 1).all())
    if _native.available():
        # one native pass: per-block memcpy into the padded layout (no
        # index arrays, no PAD prefill) — the scatter cost is pure sample
        # bandwidth for every block shape
        ts2, v2, _fill = _native.scatter_pad(
            np.ascontiguousarray(ts_all, np.int64),
            np.ascontiguousarray(vals_f, np.float64),
            cnts, rows, S, N, PAD_TS)
    elif single_block and tot == S * N:
        # one block per series, uniform length: a single row-scatter of the
        # reshaped decode output (the common scrape-grid case)
        ts2 = np.empty((S, N), dtype=np.int64)
        v2 = np.empty((S, N), dtype=np.float64)
        ts2[rows] = ts_all.reshape(-1, N)
        v2[rows] = vals_f.reshape(-1, N)
    else:
        order = np.argsort(rows, kind="stable")
        rows_o = rows[order]
        cnts_o = cnts[order]
        excl_o = np.cumsum(cnts_o) - cnts_o
        grp_first = np.searchsorted(rows_o, np.arange(S), side="left")
        base = excl_o[grp_first]            # samples before each series
        within = excl_o - base[rows_o]      # offset inside its series
        dest_start = rows_o * N + within
        local = _ranges(cnts_o, tot)
        dst_idx = np.repeat(dest_start, cnts_o) + local
        ts2 = np.full(S * N, PAD_TS, dtype=np.int64)
        v2 = np.zeros(S * N, dtype=np.float64)
        if bool((order == np.arange(order.size)).all()):
            ts2[dst_idx] = ts_all
            v2[dst_idx] = vals_f
        else:
            src_excl = np.cumsum(cnts) - cnts
            src_idx = np.repeat(src_excl[order], cnts_o) + local
            ts2[dst_idx] = ts_all[src_idx]
            v2[dst_idx] = vals_f[src_idx]
        ts2 = ts2.reshape(S, N)
        v2 = v2.reshape(S, N)
    counts = series_tot

    # per-row sortedness fix: only rows assembled from >1 block can violate
    multi = blocks_per_row > 1
    if multi.any():
        cand = np.flatnonzero(multi)
        sub = ts2[cand]
        disorder = (np.diff(sub, axis=1) < 0).any(axis=1)
        bad = cand[disorder]
        if bad.size:
            sub = ts2[bad]
            ordr = np.argsort(sub, axis=1, kind="stable")  # PAD sorts last
            ts2[bad] = np.take_along_axis(sub, ordr, axis=1)
            v2[bad] = np.take_along_axis(v2[bad], ordr, axis=1)

    # range clip (blocks overhang [min_ts, max_ts]); rows are sorted so the
    # kept region is contiguous
    lo_i = (ts2 < min_ts).sum(axis=1)
    hi_i = (ts2 <= max_ts).sum(axis=1)
    new_counts = hi_i - lo_i
    if bool((lo_i > 0).any()) or bool((new_counts < counts).any()):
        lo0 = int(lo_i[0])
        n0 = int(new_counts[0])
        if bool((lo_i == lo0).all()) and bool((new_counts == n0).all()):
            # shared scrape grid: the kept region is the same column slice
            # for every row — pure views, no copy
            ts2 = ts2[:, lo0:lo0 + n0]
            v2 = v2[:, lo0:lo0 + n0]
            N = n0
        else:
            idx = np.minimum(lo_i[:, None] + np.arange(N)[None, :], N - 1)
            ts2 = np.take_along_axis(ts2, idx, axis=1)
            v2 = np.take_along_axis(v2, idx, axis=1)
            tail = np.arange(N)[None, :] >= new_counts[:, None]
            ts2[tail] = PAD_TS
            v2[tail] = 0.0
        counts = new_counts

    # exact-duplicate timestamps (replica merges): keep the LAST sample of
    # each run, matching search_series semantics
    W = ts2.shape[1]
    dup_rows = ((ts2[:, 1:] == ts2[:, :-1]) &
                (ts2[:, 1:] != PAD_TS)).any(axis=1) if W > 1 else \
        np.zeros(S, bool)
    if dedup_interval_ms > 0 and W > 1:
        # batched needs_dedup: a row pays the per-row pass only when two
        # consecutive samples share a dedup bucket (ordinary well-spaced
        # scrapes stay fully vectorized)
        valid_next = np.arange(1, W)[None, :] < counts[:, None]
        b = (np.where(ts2 == PAD_TS, 0, ts2) + (dedup_interval_ms - 1)) \
            // dedup_interval_ms
        dup_rows |= ((b[:, 1:] == b[:, :-1]) & valid_next).any(axis=1)
    need_dedup = dedup_interval_ms > 0
    if dup_rows.any():
        from .dedup import deduplicate
        rows_iter = np.flatnonzero(dup_rows)
        if _native.available():
            # one GIL-released pass over the flagged rows (vm_dedup_rows):
            # interval dedup + exact-duplicate keep-last, compaction and
            # tail padding in place — bit-exact with the loop below (the
            # no-native oracle the equality tests diff against)
            counts = np.ascontiguousarray(counts, dtype=np.int64)
            _native.dedup_rows(ts2, v2, counts, rows_iter,
                               dedup_interval_ms if need_dedup else 0,
                               PAD_TS)
            rows_iter = ()
        for s in rows_iter:
            n = int(counts[s])
            t = ts2[s, :n]
            v = v2[s, :n]
            if need_dedup:
                t, v = deduplicate(t, v, dedup_interval_ms)
            if t.size > 1:
                keep = np.concatenate([t[1:] != t[:-1], [True]])
                if not keep.all():
                    t, v = t[keep], v[keep]
            m = t.size
            if m != n:  # only ever shrinks; shrunk t/v are fresh copies
                ts2[s, :m] = t
                v2[s, :m] = v
                ts2[s, m:n] = PAD_TS
                v2[s, m:n] = 0.0
                counts[s] = m

    # drop series left empty by the clip (callers' row-aligned lists are
    # rebuilt from metric_ids/empty_rows)
    empty_rows = None
    if bool((counts == 0).any()):
        keep = counts > 0
        empty_rows = np.flatnonzero(~keep)
        metric_ids, ts2, v2, counts = (metric_ids[keep], ts2[keep], v2[keep],
                                       counts[keep])
    # trim the padded width after clipping
    if counts.size:
        n_max = int(counts.max())
        if n_max < ts2.shape[1]:
            ts2 = ts2[:, :n_max]
            v2 = v2[:, :n_max]
    out = ColumnarSeries(metric_ids, ts2, v2, counts)
    out.dropped_rows = empty_rows
    return out
