"""Sorted-byte-string LSM (capability of reference lib/mergeset: Table with
AddItems/search/CreateSnapshotAt, table.go:74,349,663; prefix-compressed 64KB
blocks, encoding.go:18-47).

Design (simplified for a single-writer host plane, same observable shape):

- pending items -> sorted in-memory parts (list[bytes]) -> immutable file
  parts, with merges collapsing duplicates (set semantics).
- file part layout: `items.bin` = concatenated zstd blocks of prefix-
  compressed items; `index.bin` = zstd'd block directory (first item,
  offset, size, count per block); `metadata.json`.
- search: merged iteration over pending/memory/file parts via heapq.merge;
  prefix scans binary-search the block directory.
- snapshots: hardlinks of immutable part files (fs.go:182 analog).
"""

from __future__ import annotations

import bisect
import heapq
import itertools
import os
import struct
import time
import zlib
from collections import OrderedDict

from ..devtools import faultinject
from ..devtools.locktrace import make_lock, make_rlock
from ..devtools.racetrace import traced_fields
from ..ops import compress as zstd
from ..ops.varint import marshal_varuint64, unmarshal_varuint64
from ..utils import flightrec, logger
from ..utils import fs as fslib
from ..utils import metrics as metricslib
from ..utils import workpool

_FLUSH_DURATION = metricslib.REGISTRY.histogram(
    'vm_storage_flush_duration_seconds{type="indexdb/mergeset"}')
_MERGE_DURATION = metricslib.REGISTRY.histogram(
    'vm_storage_merge_duration_seconds{type="indexdb/mergeset"}')
_MERGES_TOTAL = metricslib.REGISTRY.counter(
    'vm_merges_total{type="indexdb/mergeset"}')
_ACTIVE_MERGES = metricslib.REGISTRY.gauge(
    'vm_active_merges{type="indexdb/mergeset"}')
_ING_FLUSH = metricslib.ingest_phase("flush")
_ING_MERGE = metricslib.ingest_phase("merge")
_PARTS_QUARANTINED = metricslib.REGISTRY.counter(
    'vm_parts_quarantined_total{store="mergeset"}')
_PARTS_OPEN_ERRORS = metricslib.REGISTRY.counter(
    'vm_parts_open_errors_total{store="mergeset"}')

MAX_BLOCK_BYTES = 64 << 10
MAX_INMEMORY_PARTS = 15
MAX_PENDING_ITEMS = 64 << 10
# decoded-block cache: ~64KB of items per block; 512 blocks ~ 32MB+overhead
# (the indexdb/data blockcache analog of reference lib/blockcache)
MAX_CACHED_BLOCKS = 512


def _encode_block(items: list[bytes]) -> bytes:
    """Prefix-compress a run of sorted items, then zstd."""
    out = bytearray()
    prev = b""
    for it in items:
        common = os.path.commonprefix([prev, it])
        cp = len(common)
        out += marshal_varuint64(cp)
        out += marshal_varuint64(len(it) - cp)
        out += it[cp:]
        prev = it
    return zstd.compress(bytes(out))


def _decode_block(data: bytes, count: int) -> list[bytes]:
    raw = zstd.decompress(data)
    items = []
    prev = b""
    off = 0
    for _ in range(count):
        cp, off = unmarshal_varuint64(raw, off)
        sl, off = unmarshal_varuint64(raw, off)
        it = prev[:cp] + raw[off:off + sl]
        off += sl
        items.append(it)
        prev = it
    if off != len(raw):
        raise ValueError("mergeset block: trailing garbage")
    return items


@traced_fields("_block_cache")
class _FilePart:
    """Immutable on-disk sorted run."""

    def __init__(self, path: str, trusted: bool = False):
        self.path = path
        # integrity gate first: torn/bit-flipped parts must fail loudly
        # here (IntegrityError) so the table opener quarantines them.
        # `trusted` skips the payload re-read for parts THIS process just
        # finalized (it computed the checksums moments ago) — cold opens
        # always verify.
        meta = fslib.load_meta_json(os.path.join(path, "metadata.json"))
        if not trusted:
            fslib.verify_checksums(path, meta)
        self.item_count = meta["item_count"]
        idx_raw = zstd.decompress(
            open(os.path.join(path, "index.bin"), "rb").read())
        self.blocks = []  # (first_item, offset, size, count)
        off = 0
        while off < len(idx_raw):
            flen, off = unmarshal_varuint64(idx_raw, off)
            first = idx_raw[off:off + flen]
            off += flen
            boff, off = unmarshal_varuint64(idx_raw, off)
            bsize, off = unmarshal_varuint64(idx_raw, off)
            cnt, off = unmarshal_varuint64(idx_raw, off)
            self.blocks.append((first, boff, bsize, cnt))
        self._firsts = [b[0] for b in self.blocks]
        self._f = open(os.path.join(path, "items.bin"), "rb")
        self._lock = make_lock("mergeset._FilePart._lock")
        self._block_cache: "OrderedDict[int, list[bytes]]" = OrderedDict()

    def close(self):
        self._f.close()

    def _read_block(self, i: int) -> list[bytes]:
        with self._lock:
            got = self._block_cache.get(i)
            if got is not None:
                self._block_cache.move_to_end(i)
                return got
            first, off, size, cnt = self.blocks[i]
            self._f.seek(off)
            data = self._f.read(size)
        items = _decode_block(data, cnt)
        with self._lock:
            self._block_cache[i] = items
            self._block_cache.move_to_end(i)
            while len(self._block_cache) > MAX_CACHED_BLOCKS:
                self._block_cache.popitem(last=False)
        return items

    def iter_from(self, start: bytes):
        """Yield items >= start in order."""
        i = bisect.bisect_right(self._firsts, start) - 1
        i = max(i, 0)
        for bi in range(i, len(self.blocks)):
            items = self._read_block(bi)
            j = bisect.bisect_left(items, start) if bi == i else 0
            yield from items[j:]

    def first_ge(self, key: bytes) -> bytes | None:
        """First item >= key, or None (point-lookup fast path: decodes at
        most one cached block instead of setting up a merge iteration)."""
        i = max(bisect.bisect_right(self._firsts, key) - 1, 0)
        for bi in (i, i + 1):
            if bi >= len(self.blocks):
                return None
            items = self._read_block(bi)
            j = bisect.bisect_left(items, key)
            if j < len(items):
                return items[j]
        return None

    def iter_all(self):
        for bi in range(len(self.blocks)):
            yield from self._read_block(bi)

    @staticmethod
    def write(path: str, items_iter, tmp_suffix=".tmp") -> int:
        """Stream sorted unique items into a new part dir; returns count."""
        tmp = path + tmp_suffix
        os.makedirs(tmp, exist_ok=True)
        index = bytearray()
        count = 0
        items_crc = 0
        with open(os.path.join(tmp, "items.bin"), "wb") as f:
            block: list[bytes] = []
            bbytes = 0

            def flush_block():
                nonlocal block, bbytes, items_crc
                if not block:
                    return
                data = _encode_block(block)
                off = f.tell()
                index.extend(marshal_varuint64(len(block[0])))
                index.extend(block[0])
                index.extend(marshal_varuint64(off))
                index.extend(marshal_varuint64(len(data)))
                index.extend(marshal_varuint64(len(block)))
                f.write(data)
                items_crc = zlib.crc32(data, items_crc)
                block = []
                bbytes = 0

            for it in items_iter:
                block.append(it)
                bbytes += len(it) + 4
                count += 1
                if bbytes >= MAX_BLOCK_BYTES:
                    flush_block()
            flush_block()
            f.flush()
            os.fsync(f.fileno())
        idx_data = zstd.compress(bytes(index))
        with open(os.path.join(tmp, "index.bin"), "wb") as f:
            f.write(idx_data)
            f.flush()
            os.fsync(f.fileno())
        fslib.write_meta_json(
            os.path.join(tmp, "metadata.json"),
            {"item_count": count,
             "checksums": {"items.bin": items_crc,
                           "index.bin": zlib.crc32(idx_data)}})
        faultinject.fire("mergeset:flush")
        # atomic AND durable publish: rename + parent-dir fsync
        fslib.rename_durable(tmp, path)
        return count


def _dedup_sorted(it):
    prev = None
    for x in it:
        if x != prev:
            yield x
            prev = x


@traced_fields("_pending", "_pending_sorted", "_mem_parts", "_file_parts")
class Table:
    """The mergeset table: add_items / prefix search / snapshot."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._lock = make_rlock("mergeset.Table._lock")
        # serializes heavy mem->file / file->file merges per table; the
        # merge itself runs OUTSIDE _lock (immutable inputs), so adds
        # and searches proceed while a part is being written.  Ordering
        # is strictly _merge_mutex -> _lock, never the reverse.
        self._merge_mutex = make_rlock("mergeset.Table._merge_mutex")
        self._pending: list[bytes] = []
        self._pending_sorted: list[bytes] | None = []  # None = dirty
        self._mem_parts: list[list[bytes]] = []
        self._file_parts: list[_FilePart] = []
        self._part_seq = itertools.count()
        #: parts moved aside by the open-time integrity check (same
        #: quarantine semantics as storage data parts — loud, partial)
        self.quarantined: list[dict] = []
        self._open_existing()

    # -- lifecycle ---------------------------------------------------------

    def _open_existing(self):
        # previously quarantined parts keep the store loudly partial
        # across restarts (same persistence rule as data partitions)
        where = os.path.basename(self.path)
        self.quarantined.extend(fslib.resident_quarantine_entries(
            self.path, "mergeset", where))
        names = sorted(n for n in os.listdir(self.path)
                       if not n.endswith(".tmp") and
                       n != fslib.QUARANTINE_DIR and
                       os.path.isdir(os.path.join(self.path, n)))
        for n in names:
            try:
                # open-phase: runs from __init__ before the Table is
                # published to any other thread
                self._file_parts.append(  # vmt: disable=VMT015
                    _FilePart(os.path.join(self.path, n)))
            except (fslib.IntegrityError, ValueError, KeyError) as e:
                # torn/corrupt part: quarantine it LOUDLY (counter +
                # partial flag + status listing) instead of the old
                # warn-and-drop that silently lost index entries
                try:
                    self.quarantined.append(fslib.quarantine_dir_entry(
                        self.path, n, e, "mergeset", where))
                    _PARTS_QUARANTINED.inc()
                except OSError as move_err:
                    logger.errorf("mergeset: cannot quarantine part "
                                  "%s: %s", n, move_err)
                    self.quarantined.append(
                        {"store": "mergeset", "in": where, "part": n,
                         "path": os.path.join(self.path, n),
                         "error": str(e)})
                    _PARTS_OPEN_ERRORS.inc()
            except OSError as e:
                # transient open failure (fd exhaustion, permissions):
                # keep the part in place — a fixed environment serves it
                # again — but report it loudly meanwhile
                logger.errorf("mergeset %s: cannot open part %s (kept in "
                              "place, serving partial): %s", where, n, e)
                self.quarantined.append(
                    {"store": "mergeset", "in": where, "part": n,
                     "path": os.path.join(self.path, n), "error": str(e)})
                _PARTS_OPEN_ERRORS.inc()
        # tmp dirs are leftovers from a crash mid-write
        for n in os.listdir(self.path):
            if n.endswith(".tmp"):
                import shutil
                shutil.rmtree(os.path.join(self.path, n), ignore_errors=True)
        if self._file_parts:
            seqs = [int(os.path.basename(p.path).split("_")[1])
                    for p in self._file_parts]
            # open-phase (see above): pre-publication, thread-local
            self._part_seq = itertools.count(max(seqs) + 1)  # vmt: disable=VMT015

    def close(self):
        self.flush_to_disk()
        with self._lock:
            for p in self._file_parts:
                p.close()
            self._file_parts.clear()

    # -- writes ------------------------------------------------------------

    def add_items(self, items) -> None:
        with self._lock:
            items = list(items)
            self._pending.extend(items)
            # keep the sorted view incrementally: series churn interleaves
            # point lookups with small add batches, and a full re-sort per
            # lookup would be quadratic in churn
            if self._pending_sorted is not None and len(items) <= 64:
                for it in items:
                    bisect.insort(self._pending_sorted, it)
            else:
                self._pending_sorted = None
            compact = False
            if len(self._pending) >= MAX_PENDING_ITEMS:
                self._flush_pending_locked()
                compact = len(self._mem_parts) > MAX_INMEMORY_PARTS
        if compact:
            # the heavy merge runs OUTSIDE _lock: concurrent add_items
            # and searches proceed while the part is written; the
            # threshold is re-checked under the merge mutex so queued
            # adders don't stampede into serial tiny compactions
            self._compact_mem_parts(min_parts=MAX_INMEMORY_PARTS + 1)

    def _flush_pending_locked(self):
        if not self._pending:
            return
        part = sorted(set(self._pending))
        self._pending = []
        self._pending_sorted = []
        self._mem_parts.append(part)

    def _sorted_pending_locked(self) -> list[bytes]:
        if self._pending_sorted is None:
            self._pending_sorted = sorted(set(self._pending))
        return self._pending_sorted

    def _compact_mem_parts(self, min_parts: int = 1):
        """Merge the in-memory parts into one file part.  The write runs
        with no data lock held (mem parts are immutable once listed) and
        under the process-wide MERGE_GATE, so index compactions and data
        part writes together stay bounded at VM_MERGE_WORKERS.

        `min_parts` is re-checked AFTER the merge mutex is acquired:
        concurrent adders that all crossed the threshold queue here, and
        the first compaction usually swallows every mem part — the rest
        must not each write a near-empty file part."""
        with self._merge_mutex:
            with self._lock:
                mems = list(self._mem_parts)
            if len(mems) < min_parts:
                return
            with workpool.MERGE_GATE:
                # timed inside the gate: pure write time (queue wait is
                # visible as vm_merge_pending)
                t0 = time.perf_counter()
                merged = _dedup_sorted(heapq.merge(*mems))
                name = f"part_{next(self._part_seq):016d}"
                p = os.path.join(self.path, name)
                _FilePart.write(p, merged)
                dt = time.perf_counter() - t0
            with self._lock:
                flushed = {id(m) for m in mems}
                self._mem_parts = [m for m in self._mem_parts
                                   if id(m) not in flushed]
                self._file_parts.append(_FilePart(p, trusted=True))
                merge_files = len(self._file_parts) > MAX_INMEMORY_PARTS
            _FLUSH_DURATION.update(dt)
            _ING_FLUSH.inc(dt)
            flightrec.rec("flush:index", t0, dt)
        if merge_files:
            self._merge_file_parts()

    def _merge_file_parts(self):
        """Collapse every file part into one (set semantics); the k-way
        merge runs outside _lock — readers keep iterating the old parts
        (open fds keep the bytes alive) until the swap."""
        with self._merge_mutex:
            with self._lock:
                olds = list(self._file_parts)
            if len(olds) <= 1:
                return
            _ACTIVE_MERGES.inc()
            try:
                with workpool.MERGE_GATE:
                    t0 = time.perf_counter()
                    merged = _dedup_sorted(
                        heapq.merge(*[p.iter_all() for p in olds]))
                    name = f"part_{next(self._part_seq):016d}"
                    p = os.path.join(self.path, name)
                    _FilePart.write(p, merged)
                    dt = time.perf_counter() - t0
                new_part = _FilePart(p, trusted=True)
                with self._lock:
                    keep = [q for q in self._file_parts if q not in olds]
                    self._file_parts = [new_part] + keep
                # success only: aborted merges must not count as progress
                _MERGE_DURATION.update(dt)
                _ING_MERGE.inc(dt)
                _MERGES_TOTAL.inc()
                flightrec.rec("merge:index", t0, dt)
            finally:
                _ACTIVE_MERGES.dec()
            for old in olds:
                # Unlink only: concurrent readers may still iterate `old`;
                # the open fds keep the data alive until the last
                # reference drops (the part-refcount pattern, via GC).
                import shutil
                shutil.rmtree(old.path, ignore_errors=True)

    def flush_to_disk(self):
        """Durably persist everything buffered (shutdown / snapshot prep)."""
        with self._merge_mutex:
            with self._lock:
                self._flush_pending_locked()
            self._compact_mem_parts()

    def force_merge(self):
        self.flush_to_disk()
        self._merge_file_parts()

    # -- reads -------------------------------------------------------------

    def _sources_from(self, start: bytes):
        with self._lock:
            # copy: the live sorted-pending list mutates under concurrent
            # add_items insorts while these iterators are being consumed
            pending = list(self._sorted_pending_locked())
            mems = list(self._mem_parts)
            files = list(self._file_parts)
        srcs = []
        if pending:
            i = bisect.bisect_left(pending, start)
            srcs.append(iter(pending[i:]))
        for m in mems:
            i = bisect.bisect_left(m, start)
            srcs.append(iter(m[i:]))
        for fp in files:
            srcs.append(fp.iter_from(start))
        return srcs

    def iter_from(self, start: bytes):
        """All items >= start, sorted, deduped."""
        return _dedup_sorted(heapq.merge(*self._sources_from(start)))

    def search_prefix(self, prefix: bytes):
        """All items with the given prefix."""
        for it in self.iter_from(prefix):
            if not it.startswith(prefix):
                return
            yield it

    def has_item(self, item: bytes) -> bool:
        return self.first_with_prefix(item) == item

    def first_with_prefix(self, prefix: bytes) -> bytes | None:
        """Point lookup: the smallest item with the given prefix, or None.
        Bisects each source directly (no merge-iterator setup, cached block
        decode) — the hot path for unique-key namespaces."""
        best: bytes | None = None
        with self._lock:
            # bisect the mutable lists while still holding the lock —
            # concurrent insorts would shift indices under our feet
            pending = self._sorted_pending_locked()
            for lst in ([pending] if pending else []) + self._mem_parts:
                i = bisect.bisect_left(lst, prefix)
                if i < len(lst) and (best is None or lst[i] < best):
                    best = lst[i]
            files = list(self._file_parts)
        for fp in files:
            it = fp.first_ge(prefix)
            if it is not None and (best is None or it < best):
                best = it
        if best is not None and best.startswith(prefix):
            return best
        return None

    def item_count(self) -> int:
        with self._lock:
            n = len(self._pending) + sum(len(m) for m in self._mem_parts)
            n += sum(p.item_count for p in self._file_parts)
        return n  # approximate: duplicates across parts counted once each

    # -- snapshots ---------------------------------------------------------

    def create_snapshot_at(self, dst: str):
        """Hardlink-copy all immutable file parts (in-memory state is flushed
        first, like reference CreateSnapshotAt table.go:349)."""
        self.flush_to_disk()
        os.makedirs(dst, exist_ok=True)
        with self._lock:
            for fp in self._file_parts:
                name = os.path.basename(fp.path)
                pdst = os.path.join(dst, name)
                os.makedirs(pdst, exist_ok=True)
                for fn in os.listdir(fp.path):
                    os.link(os.path.join(fp.path, fn), os.path.join(pdst, fn))
        fslib.fsync_dir(dst)  # snapshot dir entries durable, like parts
