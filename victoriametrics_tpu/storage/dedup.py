"""Sample deduplication (reference lib/storage/dedup.go:14-85).

Keeps one sample per dedup interval: the one with the highest timestamp;
on equal timestamps the larger value wins unless one is a staleness marker
(stale markers take precedence so series-end is preserved).
Applied at merge time (final dedup) and query time.
"""

from __future__ import annotations

import numpy as np

from ..ops import decimal as dec


def needs_dedup(timestamps: np.ndarray, interval_ms: int) -> bool:
    if interval_ms <= 0 or timestamps.size < 2:
        return False
    d = np.diff(timestamps // interval_ms)
    return bool((d == 0).any())


def deduplicate(timestamps: np.ndarray, values: np.ndarray, interval_ms: int
                ) -> tuple[np.ndarray, np.ndarray]:
    """values may be float64 or int64 mantissas; rows must be time-sorted."""
    if not needs_dedup(timestamps, interval_ms):
        return timestamps, values
    buckets = timestamps // interval_ms
    # last index of each bucket run
    last = np.flatnonzero(np.diff(buckets, append=buckets[-1] + 1) != 0)
    keep_ts = timestamps[last]
    keep_vals = values[last].copy()
    # within a run ending at `last[i]`, if several samples share the max
    # timestamp, prefer stale marker then larger value
    starts = np.concatenate([[0], last[:-1] + 1])
    for i, (a, b) in enumerate(zip(starts, last + 1)):
        if b - a < 2:
            continue
        tmax = timestamps[b - 1]
        ties = np.flatnonzero(timestamps[a:b] == tmax) + a
        if ties.size < 2:
            continue
        vals = values[ties]
        if np.issubdtype(vals.dtype, np.floating):
            stale = dec.is_stale_nan(vals)
        else:
            stale = vals == dec.V_STALE_NAN
        if stale.any():
            keep_vals[i] = vals[np.flatnonzero(stale)[-1]]
        else:
            keep_vals[i] = vals.max()
    return keep_ts, keep_vals
