"""Sample deduplication (reference lib/storage/dedup.go:30-121).

Keeps one sample per dedup interval. Windows are right-inclusive at exact
interval multiples: a sample at k*interval closes the window ending there
(tsNext = (ts0+interval-1) - (ts0+interval-1) % interval in the reference).
The kept sample is the one with the highest timestamp in the window; on
equal timestamps the maximum value wins, always preferring a non-stale
value over a staleness marker (issues 3333, 10196).
Applied at merge time (final dedup) and query time.
"""

from __future__ import annotations

import numpy as np

from ..ops import decimal as dec


def _buckets(timestamps: np.ndarray, interval_ms: int) -> np.ndarray:
    # right-inclusive window id: ceil(ts / interval), exact multiples map
    # to their own boundary
    return (timestamps + (interval_ms - 1)) // interval_ms


def needs_dedup(timestamps: np.ndarray, interval_ms: int) -> bool:
    if interval_ms <= 0 or timestamps.size < 2:
        return False
    d = np.diff(_buckets(timestamps, interval_ms))
    return bool((d == 0).any())


def deduplicate(timestamps: np.ndarray, values: np.ndarray, interval_ms: int
                ) -> tuple[np.ndarray, np.ndarray]:
    """values may be float64 or int64 mantissas; rows must be time-sorted."""
    if not needs_dedup(timestamps, interval_ms):
        return timestamps, values
    buckets = _buckets(timestamps, interval_ms)
    # last index of each bucket run
    last = np.flatnonzero(np.diff(buckets, append=buckets[-1] + 1) != 0)
    keep_ts = timestamps[last]
    keep_vals = values[last].copy()
    # within a run ending at `last[i]`, if several samples share the max
    # timestamp, prefer the max non-stale value (stale only if all stale)
    starts = np.concatenate([[0], last[:-1] + 1])
    for i, (a, b) in enumerate(zip(starts, last + 1)):
        if b - a < 2:
            continue
        tmax = timestamps[b - 1]
        ties = np.flatnonzero(timestamps[a:b] == tmax) + a
        if ties.size < 2:
            continue
        vals = values[ties]
        if np.issubdtype(vals.dtype, np.floating):
            stale = dec.is_stale_nan(vals)
        else:
            stale = vals == dec.V_STALE_NAN
        # backward scan exactly as the reference: skip stale candidates,
        # a non-stale value always replaces a stale vPrev, otherwise only
        # strictly-greater values win (plain NaN never compares greater)
        vprev = vals[-1]
        vprev_stale = bool(stale[-1])
        for j in range(vals.size - 2, -1, -1):
            if stale[j]:
                continue
            if vprev_stale:
                vprev = vals[j]
                vprev_stale = False
            elif vals[j] > vprev:
                vprev = vals[j]
        keep_vals[i] = vprev
    return keep_ts, keep_vals
