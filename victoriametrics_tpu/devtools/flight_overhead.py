"""Flight-recorder overhead smoke check (tools/lint.sh gate).

The flightrec contract is "a few hundred ns per event, invisible at
serving granularity": the record path is one flag check, one TLS
lookup, five slot stores and a cursor bump — no allocation, no lock.
This microbench enforces that contract two ways:

1. **Per-event budget**: the absolute cost of one ``rec()`` call with
   the recorder ON must stay under ``VM_FLIGHT_SMOKE_NS`` (default
   5000 ns — an order of magnitude of slack over the measured ~500 ns,
   so only a real regression, e.g. an allocation or a lock sneaking
   onto the record path, trips it).

2. **Workload delta**: a simulated serving operation shaped like a real
   refresh (~1 ms of numpy work bracketed by a realistic number of
   phase spans) is timed with the recorder ON vs ``VM_FLIGHTREC=0``;
   the delta must stay under ``VM_FLIGHT_SMOKE_PCT`` (default 2%).
   Trials are interleaved on/off and each side keeps its MINIMUM (the
   noise-robust statistic for timing), with a few full retries before
   declaring failure — CI boxes are noisy, regressions are not.

Run directly: ``python -m victoriametrics_tpu.devtools.flight_overhead``
(prints one JSON line; exit 0 = within budget, 1 = overhead regression).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from ..utils import flightrec


def _per_event_ns(n: int = 50_000) -> float:
    """Amortized cost of one rec() call, recorder ON."""
    rec = flightrec.rec
    t = time.perf_counter()
    t0 = time.perf_counter()
    for _ in range(n):
        rec("smoke:event", t, 1e-6)
    return (time.perf_counter() - t0) / n * 1e9


def _workload(arr: np.ndarray, spans: int) -> None:
    """One simulated instrumented refresh: numpy work dominated, with
    `spans` flight events around it (the real serving path records
    ~10-20 spans per ~100ms refresh; this compresses the same ratio
    into a ~1ms op so the smoke finishes in seconds)."""
    rec = flightrec.rec
    t0 = time.perf_counter()
    for k in range(spans):
        # the "work": what a phase actually does between laps
        arr[k % 8] = np.sqrt(arr[(k + 1) % 8]).sum()
        now = time.perf_counter()
        rec("smoke:phase", t0, now - t0)
        t0 = now


def _time_workload(reps: int, spans: int, arr: np.ndarray) -> float:
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _workload(arr, spans)
        best = min(best, time.perf_counter() - t0)
    return best


def run_smoke(max_event_ns: float, max_delta_pct: float,
              retries: int = 3) -> dict:
    """Returns the result dict; ``result["ok"]`` is the verdict."""
    arr = np.random.default_rng(7).random((8, 65_536))
    spans = 16
    reps = 30
    prev_env = os.environ.get("VM_FLIGHTREC")
    try:
        event_ns = delta_pct = float("inf")
        for _attempt in range(retries):
            os.environ.pop("VM_FLIGHTREC", None)
            flightrec.reconfigure()
            _time_workload(5, spans, arr)           # warm-up both paths
            # best across attempts: noise only inflates a measurement,
            # so the minimum is the honest estimate and a real
            # regression raises every attempt's floor
            event_ns = min(event_ns, _per_event_ns())
            # interleave on/off so clock drift hits both sides equally
            t_on = t_off = float("inf")
            for _ in range(4):
                os.environ.pop("VM_FLIGHTREC", None)
                flightrec.reconfigure()
                t_on = min(t_on, _time_workload(reps, spans, arr))
                os.environ["VM_FLIGHTREC"] = "0"
                flightrec.reconfigure()
                t_off = min(t_off, _time_workload(reps, spans, arr))
            delta_pct = min(delta_pct, (t_on - t_off) / t_off * 1e2)
            if event_ns <= max_event_ns and delta_pct <= max_delta_pct:
                break
    finally:
        if prev_env is None:
            os.environ.pop("VM_FLIGHTREC", None)
        else:
            os.environ["VM_FLIGHTREC"] = prev_env
        flightrec.reconfigure()
    return {
        "per_event_ns": round(event_ns, 1),
        "max_event_ns": max_event_ns,
        "workload_delta_pct": round(delta_pct, 3),
        "max_delta_pct": max_delta_pct,
        "ok": event_ns <= max_event_ns and delta_pct <= max_delta_pct,
    }


def main() -> int:
    try:
        max_event_ns = float(os.environ.get("VM_FLIGHT_SMOKE_NS", "5000"))
    except ValueError:
        max_event_ns = 5000.0
    try:
        max_delta_pct = float(os.environ.get("VM_FLIGHT_SMOKE_PCT", "2"))
    except ValueError:
        max_delta_pct = 2.0
    res = run_smoke(max_event_ns, max_delta_pct)
    res["check"] = "flightrec_overhead"
    print(json.dumps(res))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
