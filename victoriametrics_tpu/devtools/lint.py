"""Project lint engine (the `go vet` analog for this repo).

Rules live in sibling ``rules_*`` modules, one module per rule family;
each exposes ``RULES``, a list of objects with a ``rule_id``, a one-line
``summary`` and a ``check(ctx)`` generator yielding :class:`Finding`.

Usage::

    python -m victoriametrics_tpu.devtools.lint victoriametrics_tpu/
    python -m victoriametrics_tpu.devtools.lint --update-baseline
    python -m victoriametrics_tpu.devtools.lint --no-baseline file.py

Findings are ``path:line: VMTxxx message``.  A finding is silenced
either by an inline comment on the offending line::

    t = time.time()  # vmt: disable=VMT001

or by the checked-in grandfather baseline
(``devtools/lint_baseline.txt``, per-file per-rule counts — line-number
free so unrelated edits don't invalidate it).  The check fails only when
a (file, rule) pair exceeds its baselined count, so the suite starts
green and ratchets: fixing findings shrinks the baseline via
``--update-baseline``, new code can't add any.

The ratchet cuts both ways: a baseline entry whose findings no longer
fire is STALE debt shielding future regressions, so the CLI fails with
exit code 3 (distinct from 1 = new findings) until the baseline is
regenerated.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import os
import re
import sys
import tokenize
from collections import Counter

_DEVTOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(os.path.dirname(_DEVTOOLS_DIR))
DEFAULT_BASELINE = os.path.join(_DEVTOOLS_DIR, "lint_baseline.txt")

_SUPPRESS_RE = re.compile(r"#\s*vmt:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str     # repo-root-relative when under the repo, else as given
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def dotted_name(node) -> str | None:
    """"a.b.c" for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def normalize_path(path: str) -> str:
    """Repo-root-relative (the baseline key) when under the repo, else
    the path as given."""
    rel = os.path.relpath(os.path.abspath(path), REPO_ROOT)
    return path if rel.startswith("..") else rel.replace(os.sep, "/")


class FileContext:
    """One parsed source file handed to every rule."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.rel_path = normalize_path(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of rule ids disabled on that line.  Only REAL
        # comment tokens count: a disable spelled inside a docstring or
        # string literal (rule documentation, examples) is inert
        self.suppressed: dict[int, set[str]] = {}
        # (line, rule) pairs whose disable comment actually silenced a
        # finding this run — VMT013 flags the ones that never fire
        self.used_suppressions: set[tuple[int, str]] = set()
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    ids = {s.strip().upper()
                           for s in m.group(1).split(",")}
                    self.suppressed[tok.start[0]] = {s for s in ids if s}
        except tokenize.TokenError:  # parsed fine; tolerate odd tails
            pass

    def finding(self, node, rule: str, message: str) -> Finding:
        line = getattr(node, "lineno", 0) if not isinstance(node, int) else node
        return Finding(self.rel_path, line, rule, message)

    def is_suppressed(self, f: Finding) -> bool:
        if f.rule in self.suppressed.get(f.line, ()):
            self.used_suppressions.add((f.line, f.rule))
            return True
        return False


def all_rules() -> list:
    from . import (rules_jax, rules_locks, rules_metrics, rules_pyflaws,
                   rules_threads, rules_time)
    rules = []
    for mod in (rules_time, rules_pyflaws, rules_locks, rules_jax,
                rules_metrics, rules_threads):
        rules.extend(mod.RULES)
    return sorted(rules, key=lambda r: r.rule_id)


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__" and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_source(source: str, path: str = "<string>",
                rules=None) -> list[Finding]:
    """Lint one source string; returns unsuppressed findings."""
    ctx = FileContext(path, source)
    out = []
    for rule in rules if rules is not None else all_rules():
        for f in rule.check(ctx):
            if not ctx.is_suppressed(f):
                out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def lint_paths(paths, rules=None,
               collect_ctxs: list | None = None) -> list[Finding]:
    """Lint files/dirs.  ``collect_ctxs`` (when a list) receives every
    successfully-parsed :class:`FileContext` — the whole-program checks
    (VMT013/VMT014) reuse them instead of re-parsing."""
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            print(f"lint: cannot read {path}: {e}", file=sys.stderr)
            continue
        try:
            ctx = FileContext(path, src)
        except SyntaxError as e:
            findings.append(Finding(normalize_path(path), e.lineno or 0,
                                    "VMT000", f"syntax error: {e.msg}"))
            continue
        if collect_ctxs is not None:
            collect_ctxs.append(ctx)
        for rule in rules if rules is not None else all_rules():
            for f in rule.check(ctx):
                if not ctx.is_suppressed(f):
                    findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


# -- whole-program checks (need every file, or files outside the lint) ------

STALE_DISABLE_RULE = "VMT013"
ENV_FLAG_RULE = "VMT014"

#: whole-program rule id -> one-line summary.  The per-file rules carry
#: their own ``summary``; --list-rules and the SARIF rule catalog need
#: the program passes' ids in one place too.
PROGRAM_RULE_SUMMARIES = {
    "VMT012": "blocking primitive reachable from a serving entry "
              "without a deadline seam (whole-program)",
    STALE_DISABLE_RULE: "stale '# vmt: disable=' comment that silences "
                        "nothing (whole-program)",
    ENV_FLAG_RULE: "VM_*/VMT_* env flag read in code but missing from "
                   "README.md (whole-program)",
    "VMT015": "field written from >=2 concurrency roots with no "
              "consistent guarding lock (whole-program)",
    "VMT016": "exception type reaching the HTTP/RPC boundary without "
              "a typed-status mapping (whole-program)",
}

#: an env-flag literal: VM_/VMT_ prefix then SCREAMING_SNAKE (rule ids
#: like "VMT012" don't match — no underscore after the prefix)
_FLAG_RE = re.compile(r"^VMT?_[A-Z][A-Z0-9_]*$")
_README = os.path.join(REPO_ROOT, "README.md")


def stale_disable_findings(ctxs, extra_used: dict | None = None,
                           ran_rules: set | None = None) -> list[Finding]:
    """VMT013: a ``# vmt: disable=X`` comment that silenced nothing.

    Dead disables are worse than dead code — they LOOK like an active
    exemption and will silently swallow the next real finding on that
    line.  Only judged for rule ids that actually ran this invocation
    (``ran_rules``); program-pass suppressions consumed outside the
    per-file machinery arrive via ``extra_used``
    (``{rel_path: {(line, rule), ...}}``)."""
    if ran_rules is None:
        ran_rules = {r.rule_id for r in all_rules()}
    out = []
    for ctx in ctxs:
        used = set(ctx.used_suppressions)
        if extra_used:
            used |= extra_used.get(ctx.rel_path, set())
        for line, rules in sorted(ctx.suppressed.items()):
            for rule in sorted(rules):
                if rule == STALE_DISABLE_RULE or rule not in ran_rules:
                    continue
                if (line, rule) not in used:
                    f = Finding(
                        ctx.rel_path, line, STALE_DISABLE_RULE,
                        f"stale '# vmt: disable={rule}': {rule} no "
                        f"longer fires here; drop the comment (it would "
                        f"silently swallow the next real finding)")
                    if not ctx.is_suppressed(f):
                        out.append(f)
    return out


def readme_flags() -> set[str]:
    """Every VM_*/VMT_* token mentioned anywhere in README.md."""
    try:
        with open(_README, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return set()
    return {t for t in re.findall(r"\bVMT?_[A-Z][A-Z0-9_]*\b", text)
            if _FLAG_RE.match(t)}


def env_flag_inventory(ctxs) -> dict[str, list[tuple[str, int]]]:
    """flag -> sorted (rel_path, line) occurrences, from string literals
    in the code (docstrings/comments don't count: the regex anchors the
    WHOLE constant, and only env-flag reads carry the bare token)."""
    inv: dict[str, list[tuple[str, int]]] = {}
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _FLAG_RE.match(node.value):
                inv.setdefault(node.value, []).append(
                    (ctx.rel_path, node.lineno))
    for locs in inv.values():
        locs.sort()
    return inv


def env_flag_findings(ctxs) -> list[Finding]:
    """VMT014: a VM_*/VMT_* flag read in code but absent from README.md.

    The README flag table is the operator surface — a knob that isn't
    in it effectively doesn't exist (nobody can discover it), and knobs
    documented nowhere rot into booby traps.  One finding per flag, at
    its first occurrence."""
    documented = readme_flags()
    by_rel = {ctx.rel_path: ctx for ctx in ctxs}
    out = []
    for flag, locs in sorted(env_flag_inventory(ctxs).items()):
        if flag in documented:
            continue
        rel, line = locs[0]
        f = Finding(rel, line, ENV_FLAG_RULE,
                    f"env flag {flag} is read here but missing from "
                    f"README.md's flag table; document it (or rename "
                    f"it out of the VM_*/VMT_* namespace)")
        if not by_rel[rel].is_suppressed(f):
            out.append(f)
    return out


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> Counter:
    """Baseline lines are ``relpath:RULE:count``; '#' starts a comment."""
    counts: Counter = Counter()
    if not os.path.exists(path):
        return counts
    with open(path, encoding="utf-8") as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rel, rule, n = line.rsplit(":", 2)
                counts[(rel, rule)] = int(n)
            except ValueError:
                print(f"lint: bad baseline line skipped: {line!r}",
                      file=sys.stderr)
    return counts


def write_baseline(path: str, findings: list[Finding],
                   linted_files: set[str] | None = None) -> None:
    """Rewrite the baseline. When ``linted_files`` is given (a subset
    lint), entries for files OUTSIDE the subset are carried over
    unchanged instead of being silently dropped."""
    counts = Counter((f.path, f.rule) for f in findings)
    if linted_files is not None:
        for key, n in load_baseline(path).items():
            if key[0] not in linted_files:
                counts[key] = n
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# Grandfathered lint findings: relpath:RULE:count.\n"
                 "# Regenerate with: python -m victoriametrics_tpu.devtools."
                 "lint --update-baseline\n")
        for (rel, rule), n in sorted(counts.items()):
            if n:
                fh.write(f"{rel}:{rule}:{n}\n")


def new_findings(findings: list[Finding], baseline: Counter) -> list[Finding]:
    """Findings in (file, rule) groups that exceed their baselined count.

    The whole group is returned when it exceeds (line numbers drift, so
    individual findings can't be matched against the baseline)."""
    groups: dict[tuple, list[Finding]] = {}
    for f in findings:
        groups.setdefault((f.path, f.rule), []).append(f)
    out = []
    for key, fs in groups.items():
        if len(fs) > baseline.get(key, 0):
            out.extend(fs)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def stale_baseline_entries(findings: list[Finding], baseline: Counter,
                           linted_files: set[str] | None = None) -> list[tuple]:
    """Baseline entries whose count exceeds what the lint found — only
    meaningful for files that were actually linted this run."""
    counts = Counter((f.path, f.rule) for f in findings)
    return sorted(k for k, n in baseline.items()
                  if counts.get(k, 0) < n and
                  (linted_files is None or k[0] in linted_files))


# -- CLI --------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m victoriametrics_tpu.devtools.lint",
        description="Project-specific AST lint (rules VMT001..VMT011).")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: devtools/lint_baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-flags", action="store_true",
                    help="print the VM_*/VMT_* env-flag inventory "
                         "(flag -> read sites) and exit")
    ap.add_argument("--no-program-passes", action="store_true",
                    help="skip the whole-program passes (deadline taint, "
                         "lockset, errorflow, wire schema) on a "
                         "full-package run")
    ap.add_argument("--scoped-program-passes", action="store_true",
                    help="with an explicit path list, still run the "
                         "call-graph passes (built over the whole "
                         "package) but report only their findings in "
                         "the listed files")
    ap.add_argument("--format", choices=("text", "sarif"), default="text",
                    help="finding output: text lines (default) or one "
                         "SARIF 2.1.0 log on stdout (same exit codes)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.rule_id}  {r.summary}")
        for rid, summary in sorted(PROGRAM_RULE_SUMMARIES.items()):
            print(f"{rid}  {summary}")
        return 0

    # the whole-program passes only make sense over the whole package:
    # an explicit path list lints just those files (fast editor loop)
    full_run = not args.paths
    paths = args.paths or [os.path.join(REPO_ROOT, "victoriametrics_tpu")]
    linted = {normalize_path(p) for p in iter_py_files(paths)}
    ctxs: list[FileContext] = []
    findings = lint_paths(paths, collect_ctxs=ctxs)

    if args.list_flags:
        if not full_run:
            ctxs = []
            lint_paths([os.path.join(REPO_ROOT, "victoriametrics_tpu")],
                       rules=[], collect_ctxs=ctxs)
        documented = readme_flags()
        for flag, locs in sorted(env_flag_inventory(ctxs).items()):
            mark = " " if flag in documented else "!"
            sites = ", ".join(f"{rel}:{line}" for rel, line in locs[:3])
            if len(locs) > 3:
                sites += f", +{len(locs) - 3} more"
            print(f"{mark} {flag:32s} {sites}")
        print(f"\n('!' = missing from README.md's flag table)")
        return 0

    ran_rules = {r.rule_id for r in all_rules()}
    extra_used: dict[str, set] = {}
    schema_exit = 0
    if full_run:
        findings.extend(env_flag_findings(ctxs))
        ran_rules.add(ENV_FLAG_RULE)
        if not args.no_program_passes:
            from . import deadline_taint, errorflow, lockset, wireschema
            from .callgraph import build_callgraph
            # ONE shared graph: the three call-graph passes see the
            # same build (and pay its cost once)
            g = build_callgraph(
                [os.path.join(REPO_ROOT, "victoriametrics_tpu")])
            for mod in (deadline_taint, lockset, errorflow):
                pass_findings, pass_used = mod.run_pass(g)
                findings.extend(pass_findings)
                for rel, pairs in pass_used.items():
                    extra_used.setdefault(rel, set()).update(pairs)
                ran_rules.add(mod.RULE_ID)
            schema_exit, schema_msgs, _ = wireschema.check()
            for m in schema_msgs:
                print(f"wireschema: {m}", file=sys.stderr)
        findings.extend(stale_disable_findings(ctxs, extra_used,
                                               ran_rules))
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
    elif args.scoped_program_passes and not args.no_program_passes:
        # editor/changed-only loop: the graph is whole-package (the
        # passes are interprocedural — a subset graph would lie), the
        # report is scoped to the listed files.  VMT013 is judged only
        # on full runs, so consumed suppressions need no merging here.
        from . import deadline_taint, errorflow, lockset
        from .callgraph import build_callgraph
        g = build_callgraph(
            [os.path.join(REPO_ROOT, "victoriametrics_tpu")])
        for mod in (deadline_taint, lockset, errorflow):
            pass_findings, _used = mod.run_pass(g)
            findings.extend(f for f in pass_findings if f.path in linted)
        findings.sort(key=lambda f: (f.path, f.line, f.rule))

    if args.update_baseline:
        write_baseline(args.baseline, findings, linted)
        print(f"baseline updated: {len(findings)} finding(s) grandfathered "
              f"-> {args.baseline}")
        return 0

    stale = []
    if args.no_baseline:
        fresh = findings
    else:
        baseline = load_baseline(args.baseline)
        fresh = new_findings(findings, baseline)
        stale = stale_baseline_entries(findings, baseline, linted)

    if args.format == "sarif":
        import json

        from .sarif import to_sarif
        summaries = {r.rule_id: r.summary for r in all_rules()}
        summaries.update(PROGRAM_RULE_SUMMARIES)
        print(json.dumps(to_sarif(fresh, summaries),
                         indent=2, sort_keys=True))
    else:
        for f in fresh:
            print(f)
    if fresh:
        print(f"\n{len(fresh)} new finding(s) "
              f"({len(findings)} total incl. baseline). "
              f"Fix, add '# vmt: disable=<RULE>' with a reason, or "
              f"--update-baseline if truly grandfathered.", file=sys.stderr)
        return 1
    if schema_exit:
        # wireschema's own message (breaking vs regenerate) already
        # printed above; its exit codes (4 breaking, 2 additive-drift)
        # are distinct from lint's 1/3
        return schema_exit
    if stale:
        for rel, rule in stale:
            print(f"stale baseline entry: {rel}:{rule} no longer fires "
                  f"at its baselined count", file=sys.stderr)
        print(f"\nBASELINE STALE: {len(stale)} grandfathered entr"
              f"{'y' if len(stale) == 1 else 'ies'} exceed what the lint "
              f"finds; the ratchet has slack that would hide regressions. "
              f"Regenerate with --update-baseline.", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
