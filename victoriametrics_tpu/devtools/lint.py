"""Project lint engine (the `go vet` analog for this repo).

Rules live in sibling ``rules_*`` modules, one module per rule family;
each exposes ``RULES``, a list of objects with a ``rule_id``, a one-line
``summary`` and a ``check(ctx)`` generator yielding :class:`Finding`.

Usage::

    python -m victoriametrics_tpu.devtools.lint victoriametrics_tpu/
    python -m victoriametrics_tpu.devtools.lint --update-baseline
    python -m victoriametrics_tpu.devtools.lint --no-baseline file.py

Findings are ``path:line: VMTxxx message``.  A finding is silenced
either by an inline comment on the offending line::

    t = time.time()  # vmt: disable=VMT001

or by the checked-in grandfather baseline
(``devtools/lint_baseline.txt``, per-file per-rule counts — line-number
free so unrelated edits don't invalidate it).  The check fails only when
a (file, rule) pair exceeds its baselined count, so the suite starts
green and ratchets: fixing findings shrinks the baseline via
``--update-baseline``, new code can't add any.

The ratchet cuts both ways: a baseline entry whose findings no longer
fire is STALE debt shielding future regressions, so the CLI fails with
exit code 3 (distinct from 1 = new findings) until the baseline is
regenerated.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import os
import re
import sys
from collections import Counter

_DEVTOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(os.path.dirname(_DEVTOOLS_DIR))
DEFAULT_BASELINE = os.path.join(_DEVTOOLS_DIR, "lint_baseline.txt")

_SUPPRESS_RE = re.compile(r"#\s*vmt:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str     # repo-root-relative when under the repo, else as given
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def dotted_name(node) -> str | None:
    """"a.b.c" for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def normalize_path(path: str) -> str:
    """Repo-root-relative (the baseline key) when under the repo, else
    the path as given."""
    rel = os.path.relpath(os.path.abspath(path), REPO_ROOT)
    return path if rel.startswith("..") else rel.replace(os.sep, "/")


class FileContext:
    """One parsed source file handed to every rule."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.rel_path = normalize_path(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of rule ids disabled on that line
        self.suppressed: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                ids = {s.strip().upper() for s in m.group(1).split(",")}
                self.suppressed[i] = {s for s in ids if s}

    def finding(self, node, rule: str, message: str) -> Finding:
        line = getattr(node, "lineno", 0) if not isinstance(node, int) else node
        return Finding(self.rel_path, line, rule, message)

    def is_suppressed(self, f: Finding) -> bool:
        return f.rule in self.suppressed.get(f.line, ())


def all_rules() -> list:
    from . import (rules_jax, rules_locks, rules_metrics, rules_pyflaws,
                   rules_threads, rules_time)
    rules = []
    for mod in (rules_time, rules_pyflaws, rules_locks, rules_jax,
                rules_metrics, rules_threads):
        rules.extend(mod.RULES)
    return sorted(rules, key=lambda r: r.rule_id)


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__" and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_source(source: str, path: str = "<string>",
                rules=None) -> list[Finding]:
    """Lint one source string; returns unsuppressed findings."""
    ctx = FileContext(path, source)
    out = []
    for rule in rules if rules is not None else all_rules():
        for f in rule.check(ctx):
            if not ctx.is_suppressed(f):
                out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def lint_paths(paths, rules=None) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            print(f"lint: cannot read {path}: {e}", file=sys.stderr)
            continue
        try:
            findings.extend(lint_source(src, path, rules))
        except SyntaxError as e:
            findings.append(Finding(normalize_path(path), e.lineno or 0,
                                    "VMT000", f"syntax error: {e.msg}"))
    return findings


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> Counter:
    """Baseline lines are ``relpath:RULE:count``; '#' starts a comment."""
    counts: Counter = Counter()
    if not os.path.exists(path):
        return counts
    with open(path, encoding="utf-8") as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rel, rule, n = line.rsplit(":", 2)
                counts[(rel, rule)] = int(n)
            except ValueError:
                print(f"lint: bad baseline line skipped: {line!r}",
                      file=sys.stderr)
    return counts


def write_baseline(path: str, findings: list[Finding],
                   linted_files: set[str] | None = None) -> None:
    """Rewrite the baseline. When ``linted_files`` is given (a subset
    lint), entries for files OUTSIDE the subset are carried over
    unchanged instead of being silently dropped."""
    counts = Counter((f.path, f.rule) for f in findings)
    if linted_files is not None:
        for key, n in load_baseline(path).items():
            if key[0] not in linted_files:
                counts[key] = n
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# Grandfathered lint findings: relpath:RULE:count.\n"
                 "# Regenerate with: python -m victoriametrics_tpu.devtools."
                 "lint --update-baseline\n")
        for (rel, rule), n in sorted(counts.items()):
            if n:
                fh.write(f"{rel}:{rule}:{n}\n")


def new_findings(findings: list[Finding], baseline: Counter) -> list[Finding]:
    """Findings in (file, rule) groups that exceed their baselined count.

    The whole group is returned when it exceeds (line numbers drift, so
    individual findings can't be matched against the baseline)."""
    groups: dict[tuple, list[Finding]] = {}
    for f in findings:
        groups.setdefault((f.path, f.rule), []).append(f)
    out = []
    for key, fs in groups.items():
        if len(fs) > baseline.get(key, 0):
            out.extend(fs)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def stale_baseline_entries(findings: list[Finding], baseline: Counter,
                           linted_files: set[str] | None = None) -> list[tuple]:
    """Baseline entries whose count exceeds what the lint found — only
    meaningful for files that were actually linted this run."""
    counts = Counter((f.path, f.rule) for f in findings)
    return sorted(k for k, n in baseline.items()
                  if counts.get(k, 0) < n and
                  (linted_files is None or k[0] in linted_files))


# -- CLI --------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m victoriametrics_tpu.devtools.lint",
        description="Project-specific AST lint (rules VMT001..VMT011).")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: devtools/lint_baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.rule_id}  {r.summary}")
        return 0

    paths = args.paths or [os.path.join(REPO_ROOT, "victoriametrics_tpu")]
    linted = {normalize_path(p) for p in iter_py_files(paths)}
    findings = lint_paths(paths)

    if args.update_baseline:
        write_baseline(args.baseline, findings, linted)
        print(f"baseline updated: {len(findings)} finding(s) grandfathered "
              f"-> {args.baseline}")
        return 0

    stale = []
    if args.no_baseline:
        fresh = findings
    else:
        baseline = load_baseline(args.baseline)
        fresh = new_findings(findings, baseline)
        stale = stale_baseline_entries(findings, baseline, linted)

    for f in fresh:
        print(f)
    if fresh:
        print(f"\n{len(fresh)} new finding(s) "
              f"({len(findings)} total incl. baseline). "
              f"Fix, add '# vmt: disable=<RULE>' with a reason, or "
              f"--update-baseline if truly grandfathered.", file=sys.stderr)
        return 1
    if stale:
        for rel, rule in stale:
            print(f"stale baseline entry: {rel}:{rule} no longer fires "
                  f"at its baselined count", file=sys.stderr)
        print(f"\nBASELINE STALE: {len(stale)} grandfathered entr"
              f"{'y' if len(stale) == 1 else 'ies'} exceed what the lint "
              f"finds; the ratchet has slack that would hide regressions. "
              f"Regenerate with --update-baseline.", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
