"""Happens-before race sanitizer (the vector-clock half of devtools'
`-race` analog; FastTrack-style, Flanagan & Freund PLDI'09).

Every thread carries a vector clock.  Clocks are synchronized at the
project's existing injection seams:

- ``locktrace.make_lock``/``make_rlock`` locks (acquire joins the lock's
  clock into the thread; release publishes the thread's clock into the
  lock) — the whole storage/RPC lock hierarchy is covered for free;
- ``threading.Thread.start``/``join`` (fork publishes the parent clock
  to the child; join publishes the child's final clock to the joiner);
- ``queue.Queue.put``/``get`` (a queue is one coarse sync object: put
  publishes, get subscribes).

Shared state is observed through :func:`traced_fields`, a class
decorator naming the hot mutable fields of a class (partition part
lists, mergeset pending buffers, cache dicts, RPC connection state).
When the sanitizer is OFF — ``VMT_RACETRACE`` unset — the decorator
returns the class untouched and ``enable()`` was never called, so
production code pays **zero** cost: no descriptor, no patched stdlib,
plain ``threading`` locks.  When ON, each named field becomes a data
descriptor whose reads/writes are checked against the last write (and
the reads since it): two accesses to the same field, at least one a
write, with neither ordered before the other by the happens-before
relation, are a data race.  Reports carry BOTH stack traces and are
counted in the ``vm_race_reports_total`` registry counter.

Granularity note: the sanitizer sees *field* reads and writes.  A
``self._pending.extend(...)`` is a field READ (the list object itself
is mutated); unsynchronized concurrent extends are only flagged when
some racing access also *rebinds* or reads-then-writes the field.  The
hot structures here are swapped wholesale under their locks
(``rows, self._pending = self._pending, []``), which is exactly the
pattern field granularity catches.

Deterministic replay: each access is also a preemption point for
``devtools.sched.DeterministicScheduler`` (see that module), so the
interleaving that produced a report can be replayed from its seed.
"""

from __future__ import annotations

import itertools
import os
import queue as _queue_mod
import sys
import threading
import traceback
import weakref

__all__ = ["RaceWarning", "RaceReport", "traced_fields", "traced_field",
           "enabled", "enable", "disable", "reports", "reset",
           "racetrace_env_enabled"]

_STACK_LIMIT = 16


class RaceWarning(UserWarning):
    """A happens-before data race was observed."""


class RaceReport:
    """One racy access pair: ``first`` happened earlier (program order of
    detection), ``second`` is the access that exposed the race."""

    __slots__ = ("cls_name", "field", "kind", "first_thread", "first_op",
                 "first_stack", "second_thread", "second_op", "second_stack")

    def __init__(self, cls_name, field, kind, first_thread, first_op,
                 first_stack, second_thread, second_op, second_stack):
        self.cls_name = cls_name
        self.field = field
        self.kind = kind                    # "write-write" | "read-write" | "write-read"
        self.first_thread = first_thread
        self.first_op = first_op            # "read" | "write"
        self.first_stack = first_stack      # traceback.StackSummary
        self.second_thread = second_thread
        self.second_op = second_op
        self.second_stack = second_stack

    def format(self) -> str:
        return (
            f"DATA RACE ({self.kind}) on {self.cls_name}.{self.field}\n"
            f"  {self.second_op} by thread {self.second_thread!r}:\n"
            + "".join("    " + ln for ln in self.second_stack.format())
            + f"  previous {self.first_op} by thread {self.first_thread!r}:\n"
            + "".join("    " + ln for ln in self.first_stack.format()))

    def __repr__(self):
        return (f"<RaceReport {self.kind} {self.cls_name}.{self.field} "
                f"{self.first_thread!r} vs {self.second_thread!r}>")


# -- detector state -----------------------------------------------------------

# One coarse lock guards every vector clock and shadow cell.  This is a
# debug sanitizer: correctness and simplicity beat parallelism here.
_DET = threading.RLock()
_enabled = False
_reports: list[RaceReport] = []
_seen: set[tuple] = set()           # dedup key per racy pair
_next_tid = itertools.count(1)
_tls = threading.local()            # .st: _ThreadState, .sched: scheduler
_SHADOW = "_vmt$shadow"


class _ThreadState:
    __slots__ = ("tid", "vc", "name")

    def __init__(self, name: str, parent_vc: dict | None = None):
        self.tid = next(_next_tid)
        self.name = name
        self.vc = dict(parent_vc) if parent_vc else {}
        self.vc[self.tid] = 1


def _state() -> _ThreadState:
    st = getattr(_tls, "st", None)
    if st is None:
        cur = threading.current_thread()
        st = _ThreadState(cur.name, getattr(cur, "_vmt_parent_vc", None))
        _tls.st = st
    return st


def _join_vc(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if v > dst.get(k, 0):
            dst[k] = v


class _Cell:
    """FastTrack shadow word for one (object, field)."""

    __slots__ = ("w_tid", "w_clock", "w_thread", "w_stack", "reads")

    def __init__(self):
        self.w_tid = 0
        self.w_clock = 0
        self.w_thread = ""
        self.w_stack = None
        self.reads = {}             # tid -> (clock, thread_name, stack)


def _capture(depth: int):
    # lookup_lines=False: no linecache I/O on the hot access path; source
    # lines resolve lazily when (and only when) a report is formatted
    stack = traceback.StackSummary.extract(
        traceback.walk_stack(sys._getframe(depth)), limit=_STACK_LIMIT,
        lookup_lines=False)
    stack.reverse()
    return stack


def _report(cls_name, field, kind, first_thread, first_op, first_stack,
            st, stack, second_op):
    f_line = first_stack[-1] if first_stack else None
    s_line = stack[-1] if stack else None
    key = (cls_name, field, kind,
           f_line and (f_line.filename, f_line.lineno),
           s_line and (s_line.filename, s_line.lineno))
    if key in _seen:
        return
    _seen.add(key)
    rep = RaceReport(cls_name, field, kind, first_thread, first_op,
                     first_stack, st.name, second_op, stack)
    _reports.append(rep)
    from .locktrace import _inc_counter
    _inc_counter("vm_race_reports_total")
    import warnings
    warnings.warn(rep.format(), RaceWarning, stacklevel=4)


def _on_access(obj, field: str, is_write: bool) -> None:
    st = _state()
    stack = _capture(3)
    with _DET:
        shadow = obj.__dict__.get(_SHADOW)
        if shadow is None:
            shadow = obj.__dict__[_SHADOW] = {}
        cell = shadow.get(field)
        if cell is None:
            cell = shadow[field] = _Cell()
        my = st.vc
        cls_name = type(obj).__name__
        if is_write:
            if cell.w_tid and cell.w_tid != st.tid and \
                    cell.w_clock > my.get(cell.w_tid, 0):
                _report(cls_name, field, "write-write", cell.w_thread,
                        "write", cell.w_stack, st, stack, "write")
            for rt, (rc, rname, rstack) in cell.reads.items():
                if rt != st.tid and rc > my.get(rt, 0):
                    _report(cls_name, field, "read-write", rname,
                            "read", rstack, st, stack, "write")
            cell.w_tid = st.tid
            cell.w_clock = my[st.tid]
            cell.w_thread = st.name
            cell.w_stack = stack
            cell.reads = {}
        else:
            if cell.w_tid and cell.w_tid != st.tid and \
                    cell.w_clock > my.get(cell.w_tid, 0):
                _report(cls_name, field, "write-read", cell.w_thread,
                        "write", cell.w_stack, st, stack, "read")
            cell.reads[st.tid] = (my[st.tid], st.name, stack)
    sched = getattr(_tls, "sched", None)
    if sched is not None:
        sched.point()


# -- traced fields ------------------------------------------------------------

class _TracedField:
    """Data descriptor proxying one instance attribute through the
    detector; the value itself lives in the instance ``__dict__`` under
    its ordinary name, so enabling/disabling tracing at any time leaves
    existing instances fully usable."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        _on_access(obj, self.name, False)
        try:
            return obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None

    def __set__(self, obj, value):
        _on_access(obj, self.name, True)
        obj.__dict__[self.name] = value

    def __delete__(self, obj):
        _on_access(obj, self.name, True)
        try:
            del obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None


_registry: list[tuple[type, tuple[str, ...]]] = []


def traced_fields(*names: str):
    """Class decorator declaring which mutable fields the sanitizer
    observes.  A no-op (the class is returned untouched) unless/until the
    sanitizer is enabled; ``enable()`` retrofits every registered class."""

    def deco(cls):
        _registry.append((cls, names))
        if _enabled:
            _install(cls, names)
        return cls

    return deco


traced_field = traced_fields  # accessor-wrapper alias


def _install(cls, names):
    for n in names:
        if not isinstance(getattr(cls, n, None), _TracedField):
            setattr(cls, n, _TracedField(n))


def _remove(cls, names):
    for n in names:
        if isinstance(cls.__dict__.get(n), _TracedField):
            delattr(cls, n)


# -- stdlib sync seams --------------------------------------------------------

_orig_thread_start = threading.Thread.start
_orig_thread_join = threading.Thread.join
_orig_queue_put = _queue_mod.Queue.put
_orig_queue_get = _queue_mod.Queue.get
_queue_vcs: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _traced_start(self):
    st = _state()
    with _DET:
        self._vmt_parent_vc = dict(st.vc)
        st.vc[st.tid] += 1          # the fork is a release on the parent
    orig_run = self.run

    def _run_and_publish():
        try:
            orig_run()
        finally:
            try:
                s = _state()
                with _DET:
                    self._vmt_final_vc = dict(s.vc)
            except Exception:  # vmt: disable=VMT003 — a publish failure in
                pass           # this finally must not mask the run() outcome

    self.run = _run_and_publish
    return _orig_thread_start(self)


def _traced_join(self, timeout=None):
    r = _orig_thread_join(self, timeout)
    if not self.is_alive():
        fin = getattr(self, "_vmt_final_vc", None)
        if fin is not None:
            st = _state()
            with _DET:
                _join_vc(st.vc, fin)
    return r


def _traced_put(self, item, block=True, timeout=None):
    # publish BEFORE the item becomes visible to a consumer
    st = _state()
    with _DET:
        vc = _queue_vcs.get(self)
        if vc is None:
            vc = _queue_vcs[self] = {}
        _join_vc(vc, st.vc)
        st.vc[st.tid] += 1
    return _orig_queue_put(self, item, block, timeout)


def _traced_get(self, block=True, timeout=None):
    item = _orig_queue_get(self, block, timeout)
    st = _state()
    with _DET:
        vc = _queue_vcs.get(self)
        if vc is not None:
            _join_vc(st.vc, vc)
    return item


# -- lock hooks (installed into devtools.locktrace) ---------------------------

class _LockHooks:
    """Installed as ``locktrace._race_hooks`` while the sanitizer is on;
    TracedLock routes its inner acquire/release bracketing through these."""

    @staticmethod
    def acquire_inner(inner, blocking, timeout):
        sched = getattr(_tls, "sched", None)
        if sched is None or not blocking or (timeout is not None
                                             and timeout >= 0):
            return inner.acquire(blocking, timeout)
        # under the deterministic scheduler a blocking wait would deadlock
        # the turnstile (the holder is parked at a preemption point), so
        # spin: try, deschedule, retry once rescheduled
        while not inner.acquire(False):
            sched.lock_spin()
        return True

    @staticmethod
    def acquired(lock):
        st = _state()
        with _DET:
            vc = getattr(lock, "_vmt_vc", None)
            if vc:
                _join_vc(st.vc, vc)

    @staticmethod
    def released(lock):
        st = _state()
        with _DET:
            vc = getattr(lock, "_vmt_vc", None)
            if vc is None:
                vc = lock._vmt_vc = {}
            _join_vc(vc, st.vc)
            st.vc[st.tid] += 1


# -- lifecycle ----------------------------------------------------------------

def racetrace_env_enabled() -> bool:
    return os.environ.get("VMT_RACETRACE", "") not in ("", "0")


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn the sanitizer on: install field descriptors on every
    registered class and patch the stdlib sync seams.  Locks created
    through ``make_lock``/``make_rlock`` AFTER this call are traced."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    for cls, names in _registry:
        _install(cls, names)
    threading.Thread.start = _traced_start
    threading.Thread.join = _traced_join
    _queue_mod.Queue.put = _traced_put
    _queue_mod.Queue.get = _traced_get
    from . import locktrace
    locktrace._race_hooks = _LockHooks


def disable() -> None:
    """Undo ``enable()``.  Instances created while tracing was on keep
    working: their values live under the plain attribute names."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    for cls, names in _registry:
        _remove(cls, names)
    threading.Thread.start = _orig_thread_start
    threading.Thread.join = _orig_thread_join
    _queue_mod.Queue.put = _orig_queue_put
    _queue_mod.Queue.get = _orig_queue_get
    from . import locktrace
    locktrace._race_hooks = None


def reports() -> list[RaceReport]:
    with _DET:
        return list(_reports)


def reset() -> None:
    """Drop accumulated reports and dedup state (between test cases)."""
    with _DET:
        _reports.clear()
        _seen.clear()


if racetrace_env_enabled():
    enable()
