"""VMT015 — static lockset / guarded-by inference over the call graph
(the RacerD-shaped half of the `go test -race` replacement; the dynamic
half is devtools/racetrace.py, which only sees interleavings that
actually execute).

For every mutable field (``self.attr`` plus module-level mutable
globals) the pass collects each access with the *lockset* held at that
access: the locks lexically held inside the function (``with lock:``
regions, identified by their ``make_lock``/``make_rlock`` registry
name) plus the locks guaranteed held on entry — the intersection,
over every call edge reaching the function from the current root, of
the caller's entry lockset and the locks held at the call site.

Concurrency roots are the places a fresh thread of control enters the
code:

- the serving entries deadline-taint already discovers (HTTP routes,
  RPC dispatch dicts, matstream advance), and
- every target of a ``thread``/``submit`` edge — service threads and
  pool-worker units run concurrently with their spawner, so each
  target is its own root and lock context does NOT flow across the
  spawn edge.

A field is flagged when it has at least one write reachable from a
root, is touched from **two or more distinct roots**, and the
intersection of the locksets over *all* its accesses is empty — i.e.
no single lock consistently guards it.  Findings carry both witness
chains (one per root), RacerD-style, and anchor at the first
unguarded write so the fix site is the report site.

Exemptions (by construction, not suppression):

- accesses inside ``__init__``/``__new__`` — the object is
  thread-local until published, and fields only ever written during
  construction are immutable-after-publish;
- lock-looking fields themselves and bound methods;
- fields never written outside construction (read-only config);
- fields of classes that own no lock at all.  This is RacerD's
  ownership bet adapted to this codebase: a class that never
  constructs or holds a lock has made no thread-safety claim — its
  instances are per-request value objects (``Row``, wire ``Writer``,
  ring blocks) whose confinement VMT009 and code review police, and
  flagging every such field would drown the signal.  A class that
  DOES own a lock has declared itself shared, so every one of its
  mutable fields must be consistently guarded.  Module-level globals
  are shared by construction and always eligible.

Real findings get FIXED and pinned by a seeded
``DeterministicScheduler`` regression test; benign ones (idempotent
memo double-creates, monotonic stats tolerating a lost increment)
carry ``# vmt: disable=VMT015`` with a one-line invariant argument on
any access site of the field.  VMT013 flags the comment when the
finding stops firing.
"""

from __future__ import annotations

import argparse
import os
import sys

from .callgraph import CallGraph, build_callgraph, source_suppressed
from .deadline_taint import find_entries
from .lint import Finding

RULE_ID = "VMT015"


# -- roots ------------------------------------------------------------------

def find_roots(g: CallGraph) -> dict[str, str]:
    """qname -> human-readable description of the concurrency root."""
    roots = dict(find_entries(g))
    for q in sorted(g.edges):
        for e in g.edges[q]:
            if e.kind in ("thread", "submit", "cbref") and \
                    e.target in g.defs:
                fd = g.defs[e.target]
                roots.setdefault(e.target, f"{e.kind} {fd.name}")
    return roots


# -- per-root lockset propagation -------------------------------------------

def _root_closure(g: CallGraph, root: str):
    """(entry_lockset, parent) maps for everything reachable from
    ``root`` via call/ref edges.  ``entry_lockset[q]`` is the set of
    locks guaranteed held whenever ``q`` runs on behalf of this root:
    the intersection over all discovered call paths.  Monotone
    (locksets only shrink), so the worklist terminates."""
    entry: dict[str, frozenset] = {root: frozenset()}
    parent: dict[str, tuple | None] = {root: None}
    work = [root]
    while work:
        q = work.pop()
        base = entry[q]
        for e in g.callees(q):
            if e.kind not in ("call", "ref") or e.target not in g.defs:
                continue
            new = frozenset(base | set(e.locks))
            old = entry.get(e.target)
            if old is None:
                entry[e.target] = new
                parent[e.target] = (q, e.lineno)
                work.append(e.target)
            else:
                merged = old & new
                if merged != old:
                    entry[e.target] = merged
                    work.append(e.target)
    return entry, parent


def _chain(g: CallGraph, parent: dict, q: str) -> str:
    names = []
    cur: str | None = q
    while cur is not None:
        names.append(g.defs[cur].name if cur in g.defs else cur)
        nxt = parent.get(cur)
        cur = nxt[0] if nxt else None
    names.reverse()
    if len(names) > 5:
        names = names[:2] + ["..."] + names[-2:]
    return " -> ".join(names)


# -- the pass ---------------------------------------------------------------

def _short(lock: str) -> str:
    return lock.rpartition("/")[2]


def locked_classes(g: CallGraph) -> set[str]:
    """Class qnames that own a lock: a ``self.attr = make_lock(...)``
    binding, or any ``with self.<lockish>`` region in a method (covers
    bare ``threading.Lock()`` attributes via the lexical fallback
    identity ``cls_q.attr``)."""
    out = {scope for (scope, _attr) in g.lock_names if "::" in scope}
    for accs in g.accesses.values():
        for (_field, _kind, _line, locks) in accs:
            for lid in locks:
                if "::" in lid and "." in lid.rpartition("::")[2]:
                    out.add(lid.rpartition(".")[0])
    return out


def collect_accesses(g: CallGraph, roots: dict[str, str]):
    """field -> [(root, qname, kind, rel, line, lockset)] for every
    access reachable from a concurrency root."""
    eligible_cls = locked_classes(g)

    def eligible(field: str) -> bool:
        if "::" not in field:
            return False
        tail = field.rpartition("::")[2]
        if "." not in tail:
            return True    # module global: shared by construction
        return field.rpartition(".")[0] in eligible_cls

    fields: dict[str, list] = {}
    parents: dict[str, dict] = {}
    for r in sorted(roots):
        if r not in g.defs:
            continue
        entry, parent = _root_closure(g, r)
        parents[r] = parent
        for q, base in entry.items():
            fd = g.defs[q]
            if fd.name in ("__init__", "__new__", "__del__"):
                continue   # construction: thread-local until published
            for (field, kind, line, locks) in g.accesses.get(q, ()):
                if not eligible(field):
                    continue
                fields.setdefault(field, []).append(
                    (r, q, kind, fd.rel_path, line,
                     frozenset(base | set(locks))))
    return fields, parents


def run_pass(g: CallGraph | None = None, paths=None):
    """Returns (findings, used_suppressions); the latter is
    ``{rel_path: {(line, RULE_ID), ...}}`` for VMT013's bookkeeping."""
    if g is None:
        g = build_callgraph(paths or _default_paths())
    roots = find_roots(g)
    fields, parents = collect_accesses(g, roots)

    findings: list[Finding] = []
    used: dict[str, set] = {}
    for field in sorted(fields):
        accs = fields[field]
        root_set = sorted({a[0] for a in accs})
        accs = sorted(accs, key=lambda a: (a[3], a[4], a[2], a[0]))
        writes = [a for a in accs if a[2] == "write"]
        if not writes or len(root_set) < 2:
            continue
        # the race condition proper, pairwise: a write and another
        # access on DIFFERENT roots whose locksets are disjoint — no
        # common lock orders the two
        pair = None
        for w in sorted(writes, key=lambda a: (len(a[5]), a[3], a[4])):
            for a2 in accs:
                if a2[0] != w[0] and not (w[5] & a2[5]):
                    pair = (w, a2)
                    break
            if pair:
                break
        if pair is None:
            continue   # every conflicting pair shares a lock
        # a disable on ANY access site of the field suppresses it (the
        # invariant argument reads best next to the access it excuses)
        sites = sorted({(a[3], a[4]) for a in accs})
        sup = [(rel, ln) for rel, ln in sites
               if source_suppressed(g, rel, ln, RULE_ID)]
        if sup:
            for rel, ln in sup:
                used.setdefault(rel, set()).add((ln, RULE_ID))
            continue
        bad, other = pair
        held = ", ".join(sorted(_short(x) for x in bad[5])) or "none"
        oheld = ", ".join(sorted(_short(x) for x in other[5])) or "none"
        msg = (f"field {_short(field)} has no consistent guard across "
               f"{len(root_set)} concurrency roots: "
               f"write here holds {{{held}}} on "
               f"[{roots[bad[0]]}] via {_chain(g, parents[bad[0]], bad[1])}"
               f"; {other[2]} at {other[3]}:{other[4]} holds "
               f"{{{oheld}}} on [{roots[other[0]]}] via "
               f"{_chain(g, parents[other[0]], other[1])}"
               " — guard every access with one lock, or disable with "
               "the invariant that makes the race benign")
        findings.append(Finding(bad[3], bad[4], RULE_ID, msg))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings, used


def _default_paths():
    from .lint import REPO_ROOT
    return [os.path.join(REPO_ROOT, "victoriametrics_tpu")]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m victoriametrics_tpu.devtools.lockset",
        description="VMT015: fields written from >=2 concurrency roots "
                    "with no consistent guarding lock (static lockset "
                    "inference over the project call graph).")
    ap.add_argument("paths", nargs="*")
    ap.add_argument("--list-roots", action="store_true")
    ap.add_argument("--explain", metavar="FIELD_SUBSTR",
                    help="dump every reachable access of matching "
                         "fields with roots and locksets")
    ap.add_argument("--format", choices=("text", "sarif"), default="text")
    args = ap.parse_args(argv)

    g = build_callgraph(args.paths or _default_paths())
    if args.list_roots:
        for q, why in sorted(find_roots(g).items(), key=lambda kv: kv[1]):
            print(f"{why:40s} {q}")
        return 0
    if args.explain:
        fields, _parents = collect_accesses(g, find_roots(g))
        roots = find_roots(g)
        for field in sorted(fields):
            if args.explain not in field:
                continue
            print(field)
            for (r, q, kind, rel, line, ls) in sorted(
                    fields[field], key=lambda a: (a[3], a[4])):
                locks = ", ".join(sorted(_short(x) for x in ls)) or "-"
                print(f"  {kind:5s} {rel}:{line}  [{roots[r]}]  "
                      f"locks={{{locks}}}")
        return 0
    findings, _used = run_pass(g)
    if args.format == "sarif":
        import json

        from .sarif import to_sarif
        print(json.dumps(to_sarif(
            findings, {RULE_ID: "unguarded cross-root field access"}),
            indent=2, sort_keys=True))
        return 1 if findings else 0
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} lockset finding(s): fix the race or "
              f"disable with the invariant that makes it benign.",
              file=sys.stderr)
        return 1
    print(f"lockset clean: {len(find_roots(g))} roots, "
          f"{len(g.defs)} defs analyzed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
