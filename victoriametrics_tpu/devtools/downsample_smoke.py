"""Downsample tier read-path smoke (tools/lint.sh gate): the background
re-rollup machinery and the tier-selecting read path must not rot
between full pytest runs.

One in-process pass against a real Storage (~3s):

1. ingest 2 days of 60s raw data (3 series) aged well past the 1d tier
   offset, flush, run one downsample cycle;
2. the 5m tier must exist on disk and the pass metrics must tick;
3. a long-range fetch with a downsample hint must be served FROM the
   tier: ``ds_res`` == 5m and the raw oracle reads >=4x more samples
   (60s -> 5m buckets is 5x);
4. ``sum_over_time`` over a bucket-aligned grid must be BIT-EXACT
   between the tier-served path and the raw oracle
   (``VM_DOWNSAMPLE_READ=0``), with no partial-resolution flag.

Exit 0 on success, 1 on any violated invariant.
``VMT_NO_DOWNSAMPLE_SMOKE=1`` skips from tools/lint.sh.
"""

from __future__ import annotations

import os
import shutil
import tempfile

import numpy as np

NOW = 1_754_000_000_000
RES = 300_000                     # 5m tier resolution
STEP = 3_600_000                  # 1h query step (bucket-aligned)


def _fail(msg: str) -> int:
    print(f"downsample smoke: FAIL: {msg}")
    return 1


def _run_query(s, start, end):
    from ..query.exec import exec_query
    from ..query.types import EvalConfig
    s.reset_partial()
    ec = EvalConfig(start=start, end=end, step=STEP, storage=s,
                    disable_cache=True)
    rows = exec_query(ec, "sum_over_time(m[1h])")
    return ({bytes(r.metric_name.marshal()): r.values for r in rows}, ec)


def main() -> int:
    from ..storage.storage import Storage
    from ..storage.tag_filters import TagFilter
    from ..utils import metrics as metricslib

    rows_out = metricslib.REGISTRY.counter("vm_downsample_rows_out_total")
    tmp = tempfile.mkdtemp(prefix="ds-smoke-")
    base = NOW - 10 * 86_400_000
    try:
        s = Storage(os.path.join(tmp, "s"), retention_ms=10 ** 15,
                    downsample="1d:5m")
        rows = []
        for i in range(0, 2 * 86_400_000, 60_000):
            for k in range(3):
                rows.append(({"__name__": "m", "i": str(k)}, base + i,
                             float((i // 60_000 + k) % 997)))
        s.add_rows(rows)
        s.table.flush_to_disk()
        s.run_downsample_cycle(now_ms=NOW)
        if rows_out.get() <= 0:
            return _fail("downsample cycle produced no tier rows")

        # 3. long-range fetch with a hint is served from the 5m tier
        flt = [TagFilter(b"", b"m")]
        lo, hi = base, base + 2 * 86_400_000
        s.reset_partial()
        cols = s.search_columns(flt, lo, hi, ds=("sum", STEP))
        raw = s.search_columns(flt, lo, hi)
        if cols.ds_res != RES:
            return _fail(f"hinted fetch not tier-served (ds_res="
                         f"{cols.ds_res}, want {RES})")
        if raw.n_samples < 4 * max(cols.n_samples, 1):
            return _fail(f"tier read not cheaper: raw={raw.n_samples} "
                         f"tier={cols.n_samples} samples")
        ratio = raw.n_samples / max(cols.n_samples, 1)
        print(f"downsample smoke: tier serves {cols.n_samples} samples "
              f"vs {raw.n_samples} raw ({ratio:.1f}x fewer)")

        # 4. bit-exact oracle equality on a bucket-aligned grid
        start = ((base // RES) + 2) * RES
        start += (STEP - (start % STEP)) % STEP
        tier, ec = _run_query(s, start, hi)
        if ec._partial_res[0]:
            return _fail("tier-served query flagged partial-resolution")
        os.environ["VM_DOWNSAMPLE_READ"] = "0"
        try:
            oracle, _ = _run_query(s, start, hi)
        finally:
            del os.environ["VM_DOWNSAMPLE_READ"]
        if tier.keys() != oracle.keys() or len(tier) != 3:
            return _fail("series sets differ between tier and raw oracle")
        for k in sorted(tier):
            a, b = tier[k], oracle[k]
            if not (np.isnan(a) == np.isnan(b)).all():
                return _fail("NaN grids differ between tier and oracle")
            m = ~np.isnan(a)
            if not (a[m] == b[m]).all():
                return _fail("sum_over_time not bit-exact vs raw oracle")
        print("downsample smoke: PASS (tier served, oracle bit-exact)")
        s.close()
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
