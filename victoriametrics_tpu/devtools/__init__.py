"""Static-analysis & race-detection suite for the project (the role
`go vet` + `-race` play in the reference repo).

Four pieces:

- :mod:`lint` — an AST lint engine with project-specific rules
  (VMT001..VMT010) covering deterministic-time discipline, classic
  Python foot-guns, lock discipline, JAX host-sync anti-patterns,
  metrics-registry discipline, and thread/queue lifecycle.  Run as
  ``python -m victoriametrics_tpu.devtools.lint victoriametrics_tpu/``.
  The grandfather baseline ratchets both ways: new findings fail (exit
  1), stale grandfathered entries fail distinctly (exit 3).
- :mod:`locktrace` — a runtime lock-order tracer: ``TracedLock`` is a
  drop-in for ``threading.Lock``/``RLock`` that records the per-thread
  lock-acquisition graph and fails fast on cycles (potential deadlock).
  Enabled by running any entry point with ``VMT_LOCKTRACE=1``; findings
  are counted as ``vm_locktrace_*`` registry metrics.
- :mod:`racetrace` — a FastTrack-style happens-before sanitizer:
  vector clocks synchronized at the ``make_lock`` seam, Thread
  start/join, and queue put/get; unsynchronized access pairs to
  ``traced_fields``-declared storage/RPC state are reported with both
  stacks and counted as ``vm_race_reports_total``.  Enabled with
  ``VMT_RACETRACE=1`` (zero cost when unset); ``tools/race.sh`` runs
  the race-marked tests under it.
- :mod:`sched` — a seeded deterministic cooperative scheduler (simple
  PCT) preempting at racetrace's traced points, so the interleaving
  that produced a race report is replayed from its seed.
"""
