"""Static-analysis suite for the project (the role `go vet` + `-race`
play in the reference repo).

Two halves:

- :mod:`lint` — an AST lint engine with project-specific rules
  (VMT001..VMT006) covering deterministic-time discipline, classic
  Python foot-guns, lock discipline, and JAX host-sync anti-patterns.
  Run as ``python -m victoriametrics_tpu.devtools.lint victoriametrics_tpu/``.
- :mod:`locktrace` — a runtime lock-order tracer: ``TracedLock`` is a
  drop-in for ``threading.Lock``/``RLock`` that records the per-thread
  lock-acquisition graph and fails fast on cycles (potential deadlock).
  Enabled by running any entry point with ``VMT_LOCKTRACE=1``.
"""
