"""Deterministic cooperative interleaving scheduler (the replay half of
the race tooling; a simple probabilistic-concurrency-testing — PCT —
variant).

The OS scheduler only exposes the races it happens to interleave;
``DeterministicScheduler`` serializes a set of worker threads through a
turnstile so that exactly one *scheduled* thread runs between traced
points, and all scheduling decisions come from one seeded RNG.  The
traced points are the racetrace sanitizer's observation sites (traced
field accesses and ``TracedLock`` operations), so enabling
``racetrace`` densely instruments real storage code with preemption
opportunities for free.

At each point the running thread is, with probability ``change_prob``,
demoted below every previously demoted thread (the PCT "change point"),
and control passes to the highest-priority runnable thread.  Because
every decision is drawn from the seeded RNG *in schedule order*, the
whole interleaving is a pure function of (seed, program): running the
same seeded workload twice yields the identical ``trace``, which is how
a reported race is replayed — rerun with the seed printed in the
report/test failure.

Usage::

    racetrace.enable()
    sched = DeterministicScheduler(seed=1234)
    sched.spawn("w0", worker, arg0)
    sched.spawn("w1", worker, arg1)
    sched.run()                  # starts all, drives to completion
    assert sched.trace == expected_replay

Threads must go through ``spawn`` (registration order feeds the RNG);
unregistered threads — e.g. the main thread — pass traced points
without participating in the turnstile.

A scheduled thread that blocks on a ``TracedLock`` is spun via
``lock_spin()`` (try-acquire, deschedule, retry) instead of parking in
the kernel, because its holder is itself parked in the turnstile.  A
thread that blocks anywhere the scheduler cannot see (bare
``threading`` primitives, socket reads) is covered by ``step_timeout``:
waiters seize the turnstile after it elapses, trading determinism for
progress on that pathological step.
"""

from __future__ import annotations

import random
import threading
import time

from . import racetrace

__all__ = ["DeterministicScheduler"]


class DeterministicScheduler:
    def __init__(self, seed: int = 0, change_prob: float = 0.15,
                 step_timeout: float = 5.0):
        self.seed = seed
        self.change_prob = change_prob
        self.step_timeout = step_timeout
        self.rng = random.Random(seed)
        self.trace: list[str] = []      # thread name per executed point
        self._cv = threading.Condition()
        self._threads: list[threading.Thread] = []
        self._prio: dict[str, float] = {}
        self._alive: set[str] = set()
        self._entered = 0
        self._current: str | None = None
        self._low = 0.0                 # monotonically decreasing demotion floor
        self._started = False
        self._errors: list[tuple[str, BaseException]] = []

    # -- test-facing API ---------------------------------------------------

    def spawn(self, name: str, fn, *args, **kwargs) -> threading.Thread:
        """Register a worker; priorities are drawn from the seeded RNG in
        spawn order, so spawn calls must be deterministic too."""
        if self._started:
            raise RuntimeError("spawn() after run()")
        if name in self._prio:
            raise ValueError(f"duplicate scheduled thread name {name!r}")
        self._prio[name] = self.rng.random()

        def body():
            racetrace._tls.sched = self
            try:
                self._enter(name)
                fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — re-raised in run()
                self._errors.append((name, e))
            finally:
                racetrace._tls.sched = None
                self._leave(name)

        t = threading.Thread(target=body, name=name, daemon=True)
        self._threads.append(t)
        self._alive.add(name)
        return t

    def run(self, timeout: float = 60.0) -> None:
        """Start every spawned thread and drive the workload to completion
        (raises if a worker wedges past ``timeout``)."""
        self._started = True
        for t in self._threads:
            t.start()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(max(0.1, deadline - time.monotonic()))
            if t.is_alive():
                raise RuntimeError(
                    f"scheduled thread {t.name!r} wedged (seed={self.seed}, "
                    f"trace so far: {self.trace[-20:]})")
        if self._errors:
            name, err = self._errors[0]
            raise RuntimeError(
                f"scheduled thread {name!r} raised under seed "
                f"{self.seed}") from err

    # -- turnstile ---------------------------------------------------------

    def _enter(self, name: str) -> None:
        """Start barrier: every thread parks here until ALL spawned threads
        arrived, so the first RNG draw never races thread startup."""
        with self._cv:
            self._entered += 1
            self._cv.notify_all()
            while self._entered < len(self._threads):
                self._cv.wait(self.step_timeout)
            if self._current is None:
                self._pick_locked()
            self._wait_for_turn_locked(name)

    def _leave(self, name: str) -> None:
        with self._cv:
            self._alive.discard(name)
            if self._current == name:
                self._pick_locked()
            self._cv.notify_all()

    def _pick_locked(self) -> None:
        self._current = max(self._alive, key=self._prio.__getitem__) \
            if self._alive else None

    def _wait_for_turn_locked(self, name: str) -> None:
        while self._current != name:
            if not self._cv.wait(self.step_timeout):
                # the chosen thread is stuck somewhere untraced: seize the
                # turnstile rather than deadlock (non-deterministic fallback,
                # only reachable when the workload blocks outside trace
                # points for step_timeout straight); recorded in the trace
                # so a replay divergence is self-diagnosing
                self.trace.append(name + "/seized")
                self._current = name
                break

    def point(self) -> None:
        """One traced point: maybe a PCT change point, then yield the
        turnstile to the highest-priority runnable thread."""
        name = threading.current_thread().name
        with self._cv:
            if name not in self._alive:
                return
            self.trace.append(name)
            if self.rng.random() < self.change_prob:
                self._demote_locked(name)
            self._cv.notify_all()
            self._wait_for_turn_locked(name)

    def lock_spin(self) -> None:
        """Called (via racetrace's lock hooks) when a scheduled thread
        fails a try-acquire: unconditionally demote so the lock holder —
        parked in the turnstile — gets to run and release."""
        name = threading.current_thread().name
        with self._cv:
            if name not in self._alive:
                return
            self.trace.append(name + "/blocked")
            self._demote_locked(name)
            self._cv.notify_all()
            self._wait_for_turn_locked(name)

    def _demote_locked(self, name: str) -> None:
        self._low -= 1.0
        self._prio[name] = self._low
        self._pick_locked()
