"""VMT001 — deterministic-time discipline.

Hot paths must read the clock through ``utils/fasttime`` (cached, and
the single seam fake-clock tests patch); direct ``time.time()`` /
``datetime.now()`` calls anywhere else defeat both.  The reference repo
gets this for free by funnelling everything through ``lib/fasttime``.
"""

from __future__ import annotations

import ast

from .lint import dotted_name

# the one module allowed to touch the wall clock
_ALLOWED_SUFFIXES = ("utils/fasttime.py",)

_WALL_CLOCK_CALLS = {
    "time.time", "time.time_ns", "_time.time", "_time.time_ns",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "dt.now", "dt.utcnow", "dt.datetime.now", "dt.datetime.utcnow",
}


class WallClockRule:
    rule_id = "VMT001"
    summary = ("direct time.time()/datetime.now() outside utils/fasttime "
               "(use fasttime.unix_timestamp()/unix_ms())")

    def check(self, ctx):
        if ctx.rel_path.endswith(_ALLOWED_SUFFIXES):
            return
        for node in ast.walk(ctx.tree):
            # `from time import time` would make every later wall-clock
            # read an undetectable bare `time()` call — flag the import
            if isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for alias in node.names:
                        if alias.name in ("time", "time_ns"):
                            yield ctx.finding(
                                node, self.rule_id,
                                f"'from time import {alias.name}' hides "
                                f"wall-clock reads from this rule; import "
                                f"the module (or better, use "
                                f"utils.fasttime)")
                continue
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _WALL_CLOCK_CALLS:
                yield ctx.finding(
                    node, self.rule_id,
                    f"direct wall-clock read {name}(); route through "
                    f"utils.fasttime (unix_timestamp is cached; unix_ms/"
                    f"unix_seconds share the seam) so fake-clock tests "
                    f"patch one point")


RULES = [WallClockRule()]
