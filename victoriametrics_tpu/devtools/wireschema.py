"""Wire/format schema ratchet (the compatibility contract, made checkable).

The cluster plane speaks a hand-evolved binary protocol whose
compatibility rules used to live only in prose: search_v1 alone grew
four generations of trailing fields (trace flag -> budget_ms -> or_sets
-> RingConfig), each guarded by bespoke ``Reader.remaining`` tolerance,
and the on-disk formats (metadata.json, parts.json, ring_exempt.bin,
adopted_mid.json) carry the same implicit old-reader/new-writer rules.
This module EXTRACTS those schemas from the marshal/unmarshal code
itself — field order, op types, repeat groups, optionality, and whether
the reader tolerates a field's absence — and ratchets them against the
committed ``devtools/wire_schema.lock.json``.

Extraction is a symbolic, order-preserving walk of the AST:

- **server request schema** — reader ops (``r.u64()``, ``r.bytes_()``,
  ...) in each RPC handler, with module/nested helper calls that take
  the reader (``_read_tenant(r)``, ``_read_or_sets(r)``) inlined, and
  guard context tracked: an op under ``if r.remaining`` (or after an
  early ``return`` on ``not r.remaining``) is an *optional, tolerated*
  trailing field — exactly the rolling-upgrade contract.
- **server response schema** — writer ops (op calls WITH arguments) in
  the handler and its nested frame generators, ``_meta_frame`` inlined.
- **client request schema** — writer ops in the function that invokes
  ``.call("method", w)`` / ``.call_stream(...)``, helpers inlined
  (helpers that themselves issue RPC calls are fallback paths, not part
  of this request, and are NOT inlined).
- **persisted formats** — json dict-literal keys at the write sites vs
  required (``d["k"]``) and tolerated (``d.get("k")`` / KeyError-guarded)
  keys at the read sites; ring_exempt.bin's varint record layout with
  its torn-tail tolerance.

Checks, in increasing severity:

- **pairing** (lockfile-independent): the client's written fields must
  match the server's read fields position-by-position (op + repeat
  group); a writer field the paired reader never consumes is breaking.
  Same for format writer keys vs reader-required keys.
- **ratchet** (vs the lockfile): field removal, reorder, a new
  NON-trailing field, a required new trailing field, or LOST trailing
  tolerance (an optional field becoming required strands every old
  peer) — all breaking, exit :data:`EXIT_BREAKING` (4).  Purely
  additive trailing extensions exit :data:`EXIT_ADDITIVE` (2) until the
  lockfile is regenerated with ``--update-schema`` (which refuses
  breaking diffs unless ``--allow-breaking`` spells out the intent).

ROADMAP items 4-5 (anti-entropy, streamed part transfer, persistentqueue
chunk format) add more wire and disk formats; they land by extending
:data:`RPC_MODULES`/:data:`FORMATS` so the ratchet covers them on day
one.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys

from .lint import REPO_ROOT, normalize_path

LOCKFILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "wire_schema.lock.json")

EXIT_OK = 0
EXIT_ADDITIVE = 2     # schema grew (trailing, tolerated): --update-schema
EXIT_BREAKING = 4     # compatibility break: old peers/files would misparse

#: Writer/Reader op vocabulary (parallel/rpc.py): zero-arg calls on the
#: tracked reader are reads, op calls WITH arguments are writes
OPS = ("u64", "i64", "f64", "bytes_", "str_", "array")

#: modules holding RPC marshal/unmarshal code
RPC_MODULES = (
    "victoriametrics_tpu/parallel/cluster_api.py",
    "victoriametrics_tpu/parallel/rpc.py",
)

#: persisted formats: extraction sites for writer keys and reader
#: required/tolerated keys (see _extract_formats)
FORMATS = {
    "metadata.json": {
        "kind": "json",
        # dict literal passed to write_meta_json + keys the fs helper
        # itself injects (meta["meta_crc"] = ...)
        "write_dict_args": [
            ("victoriametrics_tpu/storage/part.py", "write_meta_json", 1)],
        "write_key_assigns": [
            ("victoriametrics_tpu/utils/fs.py", "write_meta_json", "meta")],
        # vars assigned from these calls (or params with these names)
        # are format dicts; d["k"] reads are required, d.get("k")
        # tolerated
        "read_seed_calls": {
            "victoriametrics_tpu/storage/part.py": ("load_meta_json",),
            "victoriametrics_tpu/utils/fs.py": ("load_meta_json",)},
        "read_seed_params": {
            "victoriametrics_tpu/utils/fs.py": ("meta",)},
    },
    "parts.json": {
        "kind": "json",
        "write_dict_args": [
            ("victoriametrics_tpu/storage/partition.py", "dump", 0)],
        "read_seed_calls": {
            "victoriametrics_tpu/storage/partition.py": ("load",)},
    },
    # downsampled-tier manifest (storage/downsample.py): written via the
    # same write_meta_json/meta_crc seam as metadata.json, committed
    # after part publication (downsample:post_rename_pre_manifest)
    "tier.json": {
        "kind": "json",
        "write_dict_args": [
            ("victoriametrics_tpu/storage/downsample.py",
             "write_meta_json", 1)],
        "write_key_assigns": [
            ("victoriametrics_tpu/utils/fs.py", "write_meta_json", "meta")],
        "read_seed_calls": {
            "victoriametrics_tpu/storage/downsample.py":
                ("load_meta_json",),
            "victoriametrics_tpu/utils/fs.py": ("load_meta_json",)},
        "read_seed_params": {
            "victoriametrics_tpu/utils/fs.py": ("meta",)},
    },
    "adopted_mid.json": {
        "kind": "json",
        "only_funcs": ("_persist_adopted_watermark",
                       "_load_adopted_watermark"),
        "write_dict_args": [
            ("victoriametrics_tpu/storage/storage.py", "dump", 0)],
        "read_seed_calls": {
            "victoriametrics_tpu/storage/storage.py": ("load",)},
    },
    "ring_config": {
        "kind": "json",
        "write_dict_args": [
            ("victoriametrics_tpu/parallel/ringfilter.py", "dumps", 0)],
        "read_seed_calls": {
            "victoriametrics_tpu/parallel/ringfilter.py": ("loads",)},
    },
    "ring_exempt.bin": {
        "kind": "varint_records",
        "module": "victoriametrics_tpu/storage/storage.py",
        "writer_func": "add_ring_exempt_names",
        "reader_func": "_load_ring_exempt",
    },
    # health_v1 RPC response body (PR 17): built incrementally as a
    # local dict (out = {...}; out["k"] = ...) by local_health, widened
    # by the cluster_health roll-up, and tagged with the node name by
    # the fan-out.  The authoritative consumers are operators and
    # dashboards hitting /api/v1/status/health — external_readers keeps
    # the dead-writer-key pairing check from demanding an in-repo read
    # of every key — while the in-repo roll-up still ratchets what it
    # reads back from the nodes (verdict/reasons stay tolerated, never
    # required: an old node answering health_v1 without them must keep
    # working).
    "health_v1_report": {
        "kind": "json",
        "external_readers": True,
        "write_dict_assigns": [
            ("victoriametrics_tpu/query/sloplane.py",
             "local_health", "out"),
            ("victoriametrics_tpu/query/sloplane.py",
             "cluster_health", "out")],
        "write_key_assigns": [
            ("victoriametrics_tpu/parallel/cluster_api.py", "one", "rep")],
        "read_seed_params": {
            "victoriametrics_tpu/query/sloplane.py": ("rep",)},
    },
    # incident record (PR 17): frozen once at burn-breach time by
    # _freeze_incident, id-stamped by IncidentRing.open, then served
    # verbatim over /api/v1/status/incidents — the diagnosis blob keys
    # (objective, topQueries, tenantUsage, ...) are read by whoever
    # triages the incident, not by repo code, hence external_readers.
    # The ring's own reads (id/slo required; the summary projection's
    # .get()s tolerated) still ratchet: removing a key an old record
    # carries is breaking.
    "incident_record": {
        "kind": "json",
        "external_readers": True,
        "write_dict_assigns": [
            ("victoriametrics_tpu/query/sloplane.py",
             "_freeze_incident", "rec")],
        "write_key_assigns": [
            ("victoriametrics_tpu/query/sloplane.py", "open", "rec"),
            ("victoriametrics_tpu/query/sloplane.py", "resolve", "rec")],
        "read_seed_params": {
            "victoriametrics_tpu/query/sloplane.py": ("rec",)},
    },
}


def _load_sources(sources=None) -> dict[str, str]:
    """rel_path -> source for every module the extraction touches.
    ``sources`` overrides individual files (the mutation tests inject a
    reordered field without touching the tree)."""
    rels = set(RPC_MODULES)
    for spec in FORMATS.values():
        for key in ("write_dict_args", "write_key_assigns",
                    "write_dict_assigns"):
            rels.update(s[0] for s in spec.get(key, ()))
        rels.update(spec.get("read_seed_calls", {}))
        rels.update(spec.get("read_seed_params", {}))
        if "module" in spec:
            rels.add(spec["module"])
    out = {}
    for rel in sorted(rels):
        if sources is not None and rel in sources:
            out[rel] = sources[rel]
            continue
        path = os.path.join(REPO_ROOT, rel)
        with open(path, encoding="utf-8") as fh:
            out[rel] = fh.read()
    return out


# -- field model ------------------------------------------------------------

def _field(op, via=None, repeat=False, optional=False, guard=None):
    f = {"op": op}
    if via:
        f["via"] = via
    if repeat:
        f["repeat"] = True
    if optional:
        f["optional"] = True
    if guard:
        f["guard"] = guard
    return f


def _mentions_remaining(test, reader: str | None) -> bool:
    if reader is None:
        return False
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr == "remaining" and \
                isinstance(n.value, ast.Name) and n.value.id == reader:
            return True
    return False


class _OpScanner:
    """Order-preserving reader/writer op extraction for one function.

    ``reader`` is the tracked Reader param name (None when extracting
    writer-only).  Helpers (same-module defs) are inlined: for reader
    ops only when the tracked reader is passed through; for writer ops
    unless the helper issues its own RPC call (a fallback path)."""

    def __init__(self, helpers: dict[str, ast.AST], want: str):
        self.helpers = helpers
        self.want = want            # "read" | "write"
        self.fields: list[dict] = []
        self._stack: list[str] = []  # helper recursion guard

    def scan_function(self, func, reader: str | None, via=None,
                      repeat=False, optional=False, guard=None):
        self._stmts(func.body, reader, via, repeat, optional, guard)

    def _stmts(self, stmts, reader, via, repeat, optional, guard):
        # an early `return` guarded on `not r.remaining` makes every
        # field BELOW it optional: old peers stop the frame here
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested frame generators: their yields ARE the wire
                self._stmts(st.body, reader, via, repeat, optional, guard)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                self._expr(st.iter, reader, via, repeat, optional, guard)
                self._stmts(st.body, reader, via, True, optional, guard)
                self._stmts(st.orelse, reader, via, repeat, optional,
                            guard)
                continue
            if isinstance(st, ast.While):
                self._expr(st.test, reader, via, repeat, optional, guard)
                self._stmts(st.body, reader, via, True, optional, guard)
                continue
            if isinstance(st, ast.If):
                g = "remaining" if _mentions_remaining(st.test, reader) \
                    else guard or "value"
                self._expr(st.test, reader, via, repeat, optional, guard)
                ends_flow = st.body and isinstance(
                    st.body[-1], (ast.Return, ast.Raise, ast.Continue,
                                  ast.Break))
                self._stmts(st.body, reader, via, repeat, True, g)
                self._stmts(st.orelse, reader, via, repeat, True, g)
                if ends_flow and _mentions_remaining(st.test, reader):
                    # everything after `if not r.remaining: return` is
                    # a tolerated trailing extension
                    optional, guard = True, "remaining"
                continue
            if isinstance(st, ast.Try):
                self._stmts(st.body, reader, via, repeat, optional, guard)
                for h in st.handlers:
                    self._stmts(h.body, reader, via, repeat, True,
                                guard or "value")
                self._stmts(st.finalbody, reader, via, repeat, optional,
                            guard)
                continue
            for child in ast.iter_child_nodes(st):
                self._expr(child, reader, via, repeat, optional, guard)

    def _expr(self, node, reader, via, repeat, optional, guard):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.IfExp):
            g = "remaining" if _mentions_remaining(node.test, reader) \
                else guard or "value"
            self._expr(node.test, reader, via, repeat, optional, guard)
            self._expr(node.body, reader, via, repeat, True, g)
            self._expr(node.orelse, reader, via, repeat, True, g)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            for gen in node.generators:
                self._expr(gen.iter, reader, via, repeat, optional, guard)
            elts = [node.key, node.value] if isinstance(node, ast.DictComp) \
                else [node.elt]
            for e in elts:
                self._expr(e, reader, via, True, optional, guard)
            return
        if isinstance(node, ast.Call):
            self._call(node, reader, via, repeat, optional, guard)
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child, reader, via, repeat, optional, guard)

    def _call(self, node, reader, via, repeat, optional, guard):
        f = node.func
        # evaluation order: receiver/args first (w.u64(a).u64(b) chains
        # emit the inner op before the outer)
        for child in ast.iter_child_nodes(f):
            self._expr(child, reader, via, repeat, optional, guard)
        for a in node.args:
            self._expr(a, reader, via, repeat, optional, guard)
        for kw in node.keywords:
            self._expr(kw.value, reader, via, repeat, optional, guard)

        if isinstance(f, ast.Attribute) and f.attr in OPS:
            is_read = not node.args
            if self.want == "read" and is_read and \
                    self._reader_rooted(f.value, reader):
                self.fields.append(_field(f.attr, via, repeat, optional,
                                          guard))
            elif self.want == "write" and not is_read:
                self.fields.append(_field(f.attr, via, repeat, optional,
                                          guard))
            return

        # helper inlining
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        helper = self.helpers.get(name) if name else None
        if helper is None or name in self._stack:
            return
        if self.want == "read":
            # only when the tracked reader is passed through
            params = [a.arg for a in helper.args.args
                      if a.arg not in ("self", "cls")]
            sub_reader = None
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Name) and a.id == reader and \
                        i < len(params):
                    sub_reader = params[i]
                    break
            if sub_reader is None:
                return
            self._stack.append(name)
            self._stmts(helper.body, sub_reader, via or name, repeat,
                        optional, guard)
            self._stack.pop()
        else:
            if _issues_rpc_call(helper):
                return  # fallback path issuing its own request
            self._stack.append(name)
            self._stmts(helper.body, None, via or name, repeat, optional,
                        guard)
            self._stack.pop()

    @staticmethod
    def _reader_rooted(value, reader) -> bool:
        return reader is not None and isinstance(value, ast.Name) and \
            value.id == reader


def _issues_rpc_call(func) -> bool:
    for n in ast.walk(func):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in ("call", "call_stream") and n.args and \
                isinstance(n.args[0], ast.Constant) and \
                isinstance(n.args[0].value, str):
            return True
    return False


# -- RPC extraction ---------------------------------------------------------

def _collect_defs(tree) -> dict[str, ast.AST]:
    """Every def in the module by bare name (module level, class
    methods, and defs nested in factory functions) — the helper
    resolution map.  Later defs win; bare names are unique enough in
    the RPC modules."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    return defs


def _handler_map(tree) -> dict[str, str]:
    """method name -> handler func bare name, from dispatch dict
    literals with ``*_v<N>`` string keys."""
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and "_v" in k.value and k.value.rsplit("_v", 1)[-1] \
                    .isdigit() and isinstance(v, ast.Name):
                out[k.value] = v.id
    return out


def _reader_param(func) -> str | None:
    args = [a.arg for a in func.args.args if a.arg not in ("self", "cls")]
    return args[0] if args else None


def extract_rpc(srcs: dict[str, str]) -> dict:
    """{"method": {"request": [...], "response": [...],
    "client_request": [...]}} across RPC_MODULES."""
    schemas: dict[str, dict] = {}
    client_cands: dict[str, list[list[dict]]] = {}
    for rel in RPC_MODULES:
        tree = ast.parse(srcs[rel], filename=rel)
        helpers = _collect_defs(tree)
        for method, hname in _handler_map(tree).items():
            h = helpers.get(hname)
            if h is None:
                continue
            rd = _OpScanner(helpers, "read")
            reader = _reader_param(h)
            if reader:
                rd.scan_function(h, reader)
            wr = _OpScanner(helpers, "write")
            wr.scan_function(h, None)
            schemas[method] = {"request": rd.fields,
                               "response": wr.fields}
        # client request builders: any def invoking .call("m", ...)
        for func in (n for n in ast.walk(tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))):
            methods = set()
            for n in ast.walk(func):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in ("call", "call_stream") and \
                        n.args and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    methods.add(n.args[0].value)
            if not methods:
                continue
            wr = _OpScanner(helpers, "write")
            wr.scan_function(func, None)
            if wr.fields:
                for m in methods:
                    client_cands.setdefault(m, []).append(wr.fields)
    for m, cands in client_cands.items():
        if m in schemas:
            # the real builder is the candidate with the most fields
            # (fallback shims re-invoke with fewer)
            schemas[m]["client_request"] = max(cands, key=len)
    return schemas


# -- persisted-format extraction --------------------------------------------

def _last_name(func) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _scope_funcs(tree, only):
    if not only:
        yield tree
        return
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                n.name in only:
            yield n


def _extract_json_format(spec, trees) -> dict:
    writer_keys: list[str] = []
    for rel, callee, argidx in spec.get("write_dict_args", ()):
        for scope in _scope_funcs(trees[rel], spec.get("only_funcs")):
            for n in ast.walk(scope):
                if isinstance(n, ast.Call) and \
                        _last_name(n.func) == callee and \
                        len(n.args) > argidx and \
                        isinstance(n.args[argidx], ast.Dict):
                    for k in n.args[argidx].keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str) and \
                                k.value not in writer_keys:
                            writer_keys.append(k.value)
    for rel, fname, param in spec.get("write_key_assigns", ()):
        for n in ast.walk(trees[rel]):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.name == fname:
                for a in ast.walk(n):
                    if isinstance(a, ast.Assign) and \
                            isinstance(a.targets[0], ast.Subscript) and \
                            isinstance(a.targets[0].value, ast.Name) and \
                            a.targets[0].value.id == param and \
                            isinstance(a.targets[0].slice, ast.Constant):
                        k = a.targets[0].slice.value
                        if isinstance(k, str) and k not in writer_keys:
                            writer_keys.append(k)
    # write_dict_assigns: a format dict BUILT as a named local —
    # ``var = {...}`` literal init plus every ``var["k"] = ...`` widening
    # — inside the named function (health_v1 reports and incident
    # records are assembled this way rather than passed as a literal to
    # one call).
    for rel, fname, var in spec.get("write_dict_assigns", ()):
        for n in ast.walk(trees[rel]):
            if not (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and n.name == fname):
                continue
            for a in ast.walk(n):
                if not isinstance(a, ast.Assign):
                    continue
                t = a.targets[0]
                if isinstance(t, ast.Name) and t.id == var and \
                        isinstance(a.value, ast.Dict):
                    for k in a.value.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str) and \
                                k.value not in writer_keys:
                            writer_keys.append(k.value)
                elif isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == var and \
                        isinstance(t.slice, ast.Constant) and \
                        isinstance(t.slice.value, str) and \
                        t.slice.value not in writer_keys:
                    writer_keys.append(t.slice.value)

    required: set[str] = set()
    tolerated: set[str] = set()
    for rel, calls in spec.get("read_seed_calls", {}).items():
        for scope in _scope_funcs(trees[rel], spec.get("only_funcs")):
            _key_reads(scope, calls,
                       spec.get("read_seed_params", {}).get(rel, ()),
                       required, tolerated)
    for rel, params in spec.get("read_seed_params", {}).items():
        if rel not in spec.get("read_seed_calls", {}):
            _key_reads(trees[rel], (), params, required, tolerated)
    tolerated -= required
    out = {"writer_keys": writer_keys,
           "reader_required": sorted(required),
           "reader_tolerated": sorted(tolerated)}
    if spec.get("external_readers"):
        # the blob's primary consumers live outside the repo
        # (dashboards, operators): recorded in the lockfile so the
        # relaxed dead-writer-key pairing is visible in the contract
        out["external_readers"] = True
    return out


def _key_reads(scope, seed_calls, seed_params, required, tolerated):
    """Collect d["k"] / d.get("k") accesses where d is seeded from a
    configured loader call or parameter name.  A required read under a
    ``try`` that catches KeyError counts as tolerated (torn/absent file
    accepted)."""
    def seeded_names(func):
        names = {p for p in seed_params}
        for n in ast.walk(func):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and _last_name(n.value.func) in seed_calls:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
        return names

    def guarded_by_keyerror(path) -> bool:
        return any(isinstance(p, ast.Try) and any(
            h.type is not None and "KeyError" in ast.dump(h.type)
            for h in p.handlers) for p in path)

    def walk(node, path, names):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names = names | seeded_names(node)
            fparams = {a.arg for a in node.args.args}
            names |= (fparams & set(seed_params))
        is_seed_root = lambda v: (
            (isinstance(v, ast.Name) and v.id in names) or
            (isinstance(v, ast.Attribute) and v.attr in names) or
            (isinstance(v, ast.Call) and _last_name(v.func) in seed_calls) or
            # the ``(rep or {}).get("k")`` none-tolerant idiom
            (isinstance(v, ast.BoolOp) and
             any(is_seed_root(x) for x in v.values)))
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                is_seed_root(node.value) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str):
            (tolerated if guarded_by_keyerror(path) else
             required).add(node.slice.value)
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                is_seed_root(node.func.value) and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            tolerated.add(node.args[0].value)
        for child in ast.iter_child_nodes(node):
            walk(child, path + [node], names)

    walk(scope, [], set(seed_params))


def _extract_varint_format(spec, trees) -> dict:
    tree = trees[spec["module"]]
    record: list[str] = []
    tolerant = False
    for n in ast.walk(tree):
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if n.name == spec["writer_func"]:
            # f.write(marshal_varuint64(len(r)) + r): varuint length
            # prefix concatenated with the payload bytes
            for c in ast.walk(n):
                if isinstance(c, ast.BinOp) and isinstance(c.op, ast.Add) \
                        and isinstance(c.left, ast.Call) and \
                        _last_name(c.left.func) == "marshal_varuint64":
                    record = ["varuint64", "bytes"]
        elif n.name == spec["reader_func"]:
            has_unmarshal = any(
                isinstance(c, ast.Call) and
                _last_name(c.func) == "unmarshal_varuint64"
                for c in ast.walk(n))
            # torn-tail tolerance: a bounds guard that breaks out, or a
            # ValueError/IndexError handler around the record loop
            has_guard = any(
                isinstance(c, ast.If) and c.body and
                isinstance(c.body[0], ast.Break)
                for c in ast.walk(n)) or any(
                isinstance(h, ast.ExceptHandler) and h.type is not None
                and "ValueError" in ast.dump(h.type)
                for c in ast.walk(n) if isinstance(c, ast.Try)
                for h in c.handlers)
            tolerant = has_unmarshal and has_guard
    return {"record": record, "reader_tolerates_torn_tail": tolerant}


def _extract_formats(srcs: dict[str, str]) -> dict:
    trees = {rel: ast.parse(src, filename=rel)
             for rel, src in srcs.items()}
    out = {}
    for name, spec in FORMATS.items():
        if spec["kind"] == "json":
            out[name] = dict(kind="json", **_extract_json_format(spec,
                                                                 trees))
        else:
            out[name] = dict(kind="varint_records",
                             **_extract_varint_format(spec, trees))
    return out


def extract_all(sources=None) -> dict:
    srcs = _load_sources(sources)
    return {"version": 1,
            "rpc": extract_rpc(srcs),
            "formats": _extract_formats(srcs)}


# -- checks -----------------------------------------------------------------

def _pairing_problems(schema: dict) -> list[str]:
    """Lockfile-independent writer-vs-reader consistency."""
    out = []
    for method, entry in sorted(schema["rpc"].items()):
        cw = entry.get("client_request")
        sr = entry.get("request")
        if not cw or sr is None:
            continue
        n = min(len(cw), len(sr))
        for i in range(n):
            if cw[i]["op"] != sr[i]["op"] or \
                    cw[i].get("repeat", False) != sr[i].get("repeat",
                                                            False):
                out.append(
                    f"{method}: client writes field {i} as "
                    f"{cw[i]['op']}{'[]' if cw[i].get('repeat') else ''} "
                    f"but the server reads "
                    f"{sr[i]['op']}"
                    f"{'[]' if sr[i].get('repeat') else ''}")
                break
        else:
            if len(cw) > len(sr):
                out.append(
                    f"{method}: client writes {len(cw) - len(sr)} "
                    f"trailing field(s) the server handler never "
                    f"consumes (fields {n}..{len(cw) - 1})")
            elif len(sr) > len(cw):
                for f in sr[n:]:
                    if not f.get("optional"):
                        out.append(
                            f"{method}: server requires field "
                            f"{sr.index(f)} ({f['op']}) that the client "
                            f"never writes")
    for name, entry in sorted(schema["formats"].items()):
        if entry.get("kind") != "json":
            continue
        missing = [k for k in entry["reader_required"]
                   if k not in entry["writer_keys"]]
        if missing:
            out.append(f"{name}: reader requires key(s) "
                       f"{missing} the writer never writes")
        dead = [k for k in entry["writer_keys"]
                if k not in entry["reader_required"] and
                k not in entry["reader_tolerated"]]
        if dead and not entry.get("external_readers"):
            out.append(f"{name}: writer key(s) {dead} no reader ever "
                       f"consumes")
    return out


def _diff_fields(where, lock, cur, breaking, additive):
    n = min(len(lock), len(cur))
    for i in range(n):
        lf, cf = lock[i], cur[i]
        if lf["op"] != cf["op"]:
            breaking.append(f"{where}: field {i} changed "
                            f"{lf['op']} -> {cf['op']} (reorder/retype)")
            return
        if lf.get("repeat", False) != cf.get("repeat", False):
            breaking.append(f"{where}: field {i} ({lf['op']}) repeat "
                            f"grouping changed")
            return
        if lf.get("optional") and not cf.get("optional"):
            breaking.append(
                f"{where}: field {i} ({lf['op']}) lost its trailing "
                f"tolerance (optional -> required strands old peers)")
        elif not lf.get("optional") and cf.get("optional"):
            additive.append(f"{where}: field {i} ({lf['op']}) became "
                            f"optional")
    if len(cur) < len(lock):
        breaking.append(f"{where}: field(s) {len(cur)}..{len(lock) - 1} "
                        f"removed")
    elif len(cur) > len(lock):
        for i in range(n, len(cur)):
            if cur[i].get("optional"):
                additive.append(f"{where}: new optional trailing field "
                                f"{i} ({cur[i]['op']})")
            else:
                breaking.append(
                    f"{where}: new REQUIRED trailing field {i} "
                    f"({cur[i]['op']}) — old peers don't send/expect it")


def diff_schema(lock: dict, cur: dict) -> tuple[list[str], list[str]]:
    """(breaking, additive) messages for cur vs the committed lock."""
    breaking: list[str] = []
    additive: list[str] = []
    for method in sorted(set(lock.get("rpc", {})) | set(cur["rpc"])):
        le, ce = lock.get("rpc", {}).get(method), cur["rpc"].get(method)
        if le is None:
            additive.append(f"{method}: new RPC method")
            continue
        if ce is None:
            breaking.append(f"{method}: RPC method removed")
            continue
        for part in ("request", "response", "client_request"):
            lf, cf = le.get(part), ce.get(part)
            if lf is None and cf is not None:
                additive.append(f"{method}.{part}: newly extracted")
            elif lf is not None and cf is None:
                breaking.append(f"{method}.{part}: no longer extracted")
            elif lf is not None:
                _diff_fields(f"{method}.{part}", lf, cf, breaking,
                             additive)
    for name in sorted(set(lock.get("formats", {})) | set(cur["formats"])):
        lf = lock.get("formats", {}).get(name)
        cf = cur["formats"].get(name)
        if lf is None:
            additive.append(f"format {name}: new")
            continue
        if cf is None:
            breaking.append(f"format {name}: removed")
            continue
        if lf.get("kind") == "json":
            for k in lf["writer_keys"]:
                if k not in cf["writer_keys"]:
                    breaking.append(f"format {name}: writer key {k!r} "
                                    f"removed (old files carry it, old "
                                    f"readers may require it)")
            for k in cf["writer_keys"]:
                if k not in lf["writer_keys"]:
                    additive.append(f"format {name}: new writer key {k!r}")
            for k in cf["reader_required"]:
                if k not in lf["reader_required"]:
                    breaking.append(
                        f"format {name}: reader now REQUIRES key {k!r} "
                        f"(files written before it existed fail to load)")
            for k in lf["reader_required"]:
                if k not in cf["reader_required"] and \
                        k in cf["reader_tolerated"]:
                    additive.append(f"format {name}: key {k!r} became "
                                    f"tolerated")
        else:
            if lf["record"] != cf["record"]:
                breaking.append(f"format {name}: record layout changed "
                                f"{lf['record']} -> {cf['record']}")
            if lf["reader_tolerates_torn_tail"] and \
                    not cf["reader_tolerates_torn_tail"]:
                breaking.append(f"format {name}: torn-tail tolerance "
                                f"dropped (a crashed append would brick "
                                f"the load)")
    return breaking, additive


def check(sources=None, lockfile=None):
    """(exit_code, messages, current_schema)."""
    cur = extract_all(sources)
    msgs = []
    pairing = _pairing_problems(cur)
    if pairing:
        return EXIT_BREAKING, [f"PAIRING: {m}" for m in pairing], cur
    lockfile = lockfile or LOCKFILE
    if not os.path.exists(lockfile):
        return EXIT_ADDITIVE, [
            f"no lockfile at {normalize_path(lockfile)}; generate with "
            f"--update-schema"], cur
    with open(lockfile, encoding="utf-8") as fh:
        lock = json.load(fh)
    breaking, additive = diff_schema(lock, cur)
    if breaking:
        msgs = [f"BREAKING: {m}" for m in breaking] + \
               [f"additive: {m}" for m in additive]
        return EXIT_BREAKING, msgs, cur
    if additive:
        return EXIT_ADDITIVE, [f"additive: {m}" for m in additive], cur
    return EXIT_OK, [], cur


def write_lockfile(schema: dict, lockfile=None) -> None:
    lockfile = lockfile or LOCKFILE
    with open(lockfile, "w", encoding="utf-8") as fh:
        json.dump(schema, fh, indent=1, sort_keys=True)
        fh.write("\n")


# -- CLI --------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m victoriametrics_tpu.devtools.wireschema",
        description="Wire/format schema ratchet: extracted marshal/"
                    "unmarshal schemas vs wire_schema.lock.json.")
    ap.add_argument("--update-schema", action="store_true",
                    help="regenerate the lockfile (additive changes "
                         "only, unless --allow-breaking)")
    ap.add_argument("--allow-breaking", action="store_true",
                    help="with --update-schema: accept a compatibility "
                         "break (spell out the rollout plan in the PR)")
    ap.add_argument("--print", dest="print_", action="store_true",
                    help="dump the extracted schema json")
    ap.add_argument("--lockfile", default=None)
    args = ap.parse_args(argv)

    if args.print_:
        print(json.dumps(extract_all(), indent=1, sort_keys=True))
        return 0

    code, msgs, cur = check(lockfile=args.lockfile)
    if args.update_schema:
        if code == EXIT_BREAKING and not args.allow_breaking:
            for m in msgs:
                print(m, file=sys.stderr)
            print("\nrefusing to lock in a BREAKING schema change; "
                  "re-run with --allow-breaking if the compatibility "
                  "break is intentional", file=sys.stderr)
            return EXIT_BREAKING
        write_lockfile(cur, args.lockfile)
        n = len(cur["rpc"])
        print(f"schema lockfile updated: {n} RPC methods, "
              f"{len(cur['formats'])} persisted formats")
        return 0

    for m in msgs:
        print(m, file=sys.stderr)
    if code == EXIT_BREAKING:
        print(f"\nWIRE SCHEMA BREAK (exit {EXIT_BREAKING}): old peers or "
              f"old files would misparse. Revert, or make the change "
              f"additive-trailing with Reader tolerance.",
              file=sys.stderr)
    elif code == EXIT_ADDITIVE:
        print(f"\nschema drifted (additively). Regenerate the lockfile: "
              f"python -m victoriametrics_tpu.devtools.wireschema "
              f"--update-schema", file=sys.stderr)
    else:
        print(f"wire schema OK: {len(cur['rpc'])} RPC methods, "
              f"{len(cur['formats'])} formats match the lockfile")
    return code


if __name__ == "__main__":
    sys.exit(main())
