"""Single-node -> 2-node reshard smoke (tools/lint.sh gate): the
elastic-cluster machinery must not rot between full tools/chaos.sh
runs.

One in-process pass over real loopback RPC (~5s):

1. a 1-node "cluster" ingests a small corpus;
2. a second vmstorage JOINS without a restart — new writes shard to
   it, ring-filtered reads stay bit-equal to the pre-join result;
3. rebalance_to moves finalized parts onto the joiner through the
   migrateParts_v1 family (crc-verified adoption, grace-deferred
   source delete) — reads stay byte-exact and vm_parts_migrated_total
   ticks;
4. with RF bumped via a fresh 2-node RF=2 router, a down node serves
   COMPLETE results through the explicit reroute path
   (vm_reroute_reads_total ticks).

Exit 0 on success, 1 on any violated invariant; a missing zstd codec
(no python binding AND no dlopen'd libzstd) skips loudly with exit 0 —
the smoke needs the RPC frame layer.  ``VMT_NO_RESHARD_SMOKE=1`` skips
from tools/lint.sh.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

import numpy as np

T0 = 1_753_700_000_000


def main() -> int:
    try:
        from ..ops import compress as _c
        _c.compress(b"probe")
    except Exception as e:  # pragma: no cover - env without any zstd
        print(f"reshard smoke: SKIP (no zstd codec: {e})")
        return 0
    os.environ.setdefault("VM_MIGRATE_GRACE_MS", "50")
    from ..parallel.cluster_api import (ClusterStorage, StorageNodeClient,
                                        make_storage_handlers,
                                        parse_node_spec)
    from ..parallel.rpc import HELLO_INSERT, HELLO_SELECT, RPCServer
    from ..storage.storage import Storage
    from ..storage.tag_filters import TagFilter
    from ..utils import metrics as metricslib

    migrated = metricslib.REGISTRY.counter("vm_parts_migrated_total")
    reroutes = metricslib.REGISTRY.counter("vm_reroute_reads_total")
    tmp = tempfile.mkdtemp(prefix="reshard-smoke-")
    stores, servers = [], []

    def spawn():
        s = Storage(tempfile.mkdtemp(dir=tmp))
        h = make_storage_handlers(s)
        ins = RPCServer("127.0.0.1", 0, HELLO_INSERT, h)
        sel = RPCServer("127.0.0.1", 0, HELLO_SELECT, h)
        ins.start()
        sel.start()
        stores.append(s)
        servers.extend((ins, sel))
        return s, f"127.0.0.1:{ins.port}:{sel.port}"

    def fetch(cluster):
        return cluster.search_columns([TagFilter(b"", b"rs")], T0,
                                      T0 + 10 * 15_000)

    try:
        s1, spec1 = spawn()
        cluster = ClusterStorage([StorageNodeClient(
            *parse_node_spec(spec1))])
        for b in range(3):  # several flushes -> several movable parts
            cluster.add_rows(
                [({"__name__": "rs", "series": str(i)},
                  T0 + (3 * b + j) * 15_000, float(i * 10 + b + j))
                 for i in range(50) for j in range(3)])
            s1.force_flush()
        want = fetch(cluster)
        assert want.n_series == 50, want.n_series

        # JOIN without restart; ring-filtered reads stay bit-equal
        s2, spec2 = spawn()
        cluster.add_node(spec2)
        got = fetch(cluster)
        assert got.raw_names == want.raw_names
        assert np.array_equal(got.vals, want.vals)
        cluster.add_rows([({"__name__": "rs2", "series": str(i)}, T0,
                           float(i)) for i in range(40)])
        assert s2.rows_added > 0, "joiner took no writes"

        # rebalance moves real parts; reads stay byte-exact
        m0 = migrated.get()
        stat = cluster.rebalance_to(cluster.node_names()[1])
        assert stat["parts"] >= 1, f"rebalance moved nothing: {stat}"
        assert migrated.get() > m0
        assert s2.list_file_parts(), "no adopted parts on the joiner"
        got = fetch(cluster)
        assert got.raw_names == want.raw_names
        assert np.array_equal(got.vals, want.vals)

        # RF=2 reroute: a down node still serves COMPLETE results
        rf2 = ClusterStorage(
            [StorageNodeClient(*parse_node_spec(sp))
             for sp in (spec1, spec2)], replication_factor=2)
        rf2.add_rows([({"__name__": "rr", "series": str(i)},
                       T0 + j * 15_000, float(i + j))
                      for i in range(30) for j in range(3)])
        f = [TagFilter(b"", b"rr")]
        before = rf2.search_columns(f, T0, T0 + 60_000)
        r0 = reroutes.get()
        rf2.nodes[0].mark_down(30.0)
        rf2.reset_partial()
        after = rf2.search_columns(f, T0, T0 + 60_000)
        assert after.raw_names == before.raw_names
        assert np.array_equal(after.vals, before.vals)
        assert not rf2.last_partial, "reroute read flagged partial"
        assert reroutes.get() > r0, "vm_reroute_reads_total never ticked"
        print(f"reshard smoke: OK (rebalanced {stat['parts']} parts / "
              f"{stat['bytes']} bytes; reroute served "
              f"{after.n_series} series complete)")
        return 0
    except AssertionError as e:
        print(f"reshard smoke: FAIL: {e}")
        return 1
    finally:
        for srv in servers:
            srv.stop()
        for s in stores:
            s.close()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
