"""VMT012 — deadline-taint pass over the whole-program call graph.

PR 10 made query deadlines a *dynamic* property: the select plane clips
every RPC socket op to the remaining budget and vmstorage aborts
mid-scan via :class:`utils.deadline.Budget`.  This pass makes the
complementary invariant *static*: *no blocking primitive is reachable
from a serving entry point except through a deadline-aware seam*.

Entry points (discovered, not hardcoded):

- HTTP handlers — every ``srv.route(path, fn)`` registration, including
  the ``r = srv.route`` alias idiom and lambda handlers.  Operator/debug
  surfaces (``/internal/``, ``/debug/``) are out of scope: they are
  invoked by humans running diagnostics, and e.g. the pprof profile
  handler's bounded capture sleep is its contract, not a bug.
- RPC server dispatch — the ``make_storage_handlers`` dict: every value
  under a ``*_v<N>`` string key.
- Matstream advance — ``MatStream._advance`` /
  ``MatStreamRegistry.advance_due`` run per-subscription evaluation on
  pool workers with live readers waiting on the push queue.

Blocking primitives flagged when reachable without a seam:
``time.sleep``; raw socket ``recv/recv_into/accept/connect/sendall``
and ``create_connection``/``urlopen`` without a timeout; ``queue.get()``
with neither timeout nor ``block=False``; queue ``put()`` without
timeout; ``Future.result()`` that does NOT resolve to the workpool's
help-draining future; zero-arg ``.join()``; ``.wait()`` without
timeout; and semaphore/gate ``.acquire()`` without timeout.

Plain mutex ``lock.acquire()`` is deliberately NOT flagged: short
critical sections are the locking discipline VMT004/VMT005 and the
locktrace hold-time tracer already police, and timing out a mutex would
turn every lock site into an error path.  Semaphores are different —
they model *capacity*, can be held across I/O for seconds, and a full
pool plus a dead peer means an unbounded stall, which is exactly the
hang this pass exists to prevent.

Seams (the BFS does not descend into them):

- ``utils/workpool.py`` — admission gates and ``Future.result`` help
  drain: a waiter executes queued work instead of parking, and the
  submitted units carry their own ``Budget`` checks.
- ``utils/deadline.py`` — the budget itself.
- any function that calls ``.settimeout(X)`` with a non-None ``X`` —
  the RPC client's per-op socket-deadline clipping idiom.  A function
  that re-arms the socket timeout around its reads IS the wrapper this
  pass wants everything else to go through.

Findings are real bugs, not style: they get fixed, never baselined.
``# vmt: disable=VMT012`` on the blocking line is honored for the rare
sanctioned case (with the consumed-suppression set reported so VMT013
can spot stale ones).
"""

from __future__ import annotations

import argparse
import ast
import os
import re
import sys

from .callgraph import CallGraph, build_callgraph
from .lint import _SUPPRESS_RE, Finding

RULE_ID = "VMT012"

#: modules that ARE the deadline/admission machinery — descending into
#: them would flag the implementation of the very seams we require
SEAM_MODULES = (
    "victoriametrics_tpu/utils/workpool.py",
    "victoriametrics_tpu/utils/deadline.py",
)

#: route prefixes excluded from the serving entry set (operator/debug
#: surfaces; see module docstring)
EXCLUDED_ROUTE_PREFIXES = ("/internal/", "/debug/")

_RPC_METHOD_RE = re.compile(r"_v\d+$")


# -- entry discovery --------------------------------------------------------

def _lambda_qname(g: CallGraph, rel: str, lineno: int) -> str | None:
    suffix = f"<lambda@{lineno}>"
    for q in g.defs:
        if q.startswith(rel + "::") and q.endswith(suffix):
            return q
    return None


def find_entries(g: CallGraph) -> dict[str, str]:
    """qname -> human-readable entry description."""
    entries: dict[str, str] = {}

    class _RouteFinder(ast.NodeVisitor):
        def __init__(self, rel):
            self.rel = rel
            self.cls_q = None
            self.aliases: set[str] = set()   # local names bound to .route

        def visit_ClassDef(self, node):
            prev, self.cls_q = self.cls_q, f"{self.rel}::{node.name}"
            self.generic_visit(node)
            self.cls_q = prev

        def visit_Assign(self, node):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "route":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.aliases.add(t.id)
            self.generic_visit(node)

        def visit_Call(self, node):
            f = node.func
            is_route = (isinstance(f, ast.Attribute) and
                        f.attr == "route") or \
                       (isinstance(f, ast.Name) and f.id in self.aliases)
            if is_route and len(node.args) >= 2 and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                path = node.args[0].value
                if not path.startswith(EXCLUDED_ROUTE_PREFIXES):
                    self._add(path, node.args[1])
            self.generic_visit(node)

        def _add(self, path, handler):
            q = None
            if isinstance(handler, ast.Attribute) and \
                    isinstance(handler.value, ast.Name) and \
                    handler.value.id == "self" and self.cls_q:
                q = g.class_method(self.cls_q, handler.attr)
            elif isinstance(handler, ast.Name):
                q = g.lookup(self.rel, handler.id)
            elif isinstance(handler, ast.Lambda):
                q = _lambda_qname(g, self.rel, handler.lineno)
            if q is not None:
                entries.setdefault(q, f"http {path}")

    for rel, tree in g.module_trees.items():
        _RouteFinder(rel).visit(tree)
        # RPC dispatch dicts: {"search_v1": h_search, ...}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Dict):
                continue
            keyed = [(k, v) for k, v in zip(node.keys, node.values)
                     if isinstance(k, ast.Constant) and
                     isinstance(k.value, str) and
                     _RPC_METHOD_RE.search(k.value)]
            if len(keyed) < 3:
                continue
            for k, v in keyed:
                if not isinstance(v, ast.Name):
                    continue
                for q in g.by_name.get(v.id, ()):
                    fd = g.defs[q]
                    if fd.rel_path == rel and \
                            abs(fd.lineno - node.lineno) < 2000:
                        entries.setdefault(q, f"rpc {k.value}")
                        break

    # matstream advance: subscription evaluation with readers waiting
    for cls, meth in (("MatStream", "_advance"),
                      ("MatStreamRegistry", "advance_due")):
        for rel in g.module_trees:
            q = g.class_method(f"{rel}::{cls}", meth)
            if q is not None:
                entries.setdefault(q, f"matstream {cls}.{meth}")
    return entries


# -- seams ------------------------------------------------------------------

def _sets_socket_timeout(fd) -> bool:
    if isinstance(fd.node, ast.Lambda):
        return False
    for node in ast.walk(fd.node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "settimeout" and node.args:
            a = node.args[0]
            if not (isinstance(a, ast.Constant) and a.value is None):
                return True
    return False


def find_seams(g: CallGraph) -> set[str]:
    seams = set()
    for q, fd in g.defs.items():
        if fd.rel_path in SEAM_MODULES or _sets_socket_timeout(fd):
            seams.add(q)
    return seams


# -- blocking-primitive detection -------------------------------------------

def _kw(node, name):
    for kw in node.keywords:
        if kw.arg == name:
            return kw
    return None


def _has_timeout(node) -> bool:
    return _kw(node, "timeout") is not None


def _receiver_name(func) -> str:
    """Last segment of the receiver expression of an Attribute call."""
    v = func.value
    while isinstance(v, ast.Attribute):
        return v.attr
    return v.id if isinstance(v, ast.Name) else ""


def _own_nodes(fd):
    """The function's own statements, nested defs excluded (they are
    separate graph nodes, reached only if actually invoked)."""
    body = [fd.node.body] if isinstance(fd.node, ast.Lambda) \
        else list(fd.node.body)
    stack = body
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


#: method-shaped primitives eligible for project-resolution bypass: when
#: the receiver resolves to a project class defining the method, the BFS
#: already descends into that method's body — the name is not the
#: stdlib primitive (PersistentQueue.put appends to disk; Counter.get
#: reads a value under a mutex)
_METHOD_PRIMS = ("get", "put", "result", "join", "wait", "acquire")


def _project_resolved(g: CallGraph, fd, f) -> bool:
    """True when ``f`` (an Attribute callee) resolves through the graph
    to a project-defined method: ``self.m()`` via the enclosing class,
    ``self.attr.m()`` via __init__ constructor type hints."""
    v = f.value
    if isinstance(v, ast.Name) and v.id == "self" and fd.cls:
        cls_q = f"{fd.rel_path}::{fd.cls}"
        return g.class_method(cls_q, f.attr) is not None
    if isinstance(v, ast.Attribute) and \
            isinstance(v.value, ast.Name) and v.value.id == "self" and \
            fd.cls:
        cls_q = f"{fd.rel_path}::{fd.cls}"
        t = g._attr_types.get(cls_q, {}).get(v.attr)
        return t is not None and g.class_method(t, f.attr) is not None
    return False


def _submit_futures(fd) -> set[str]:
    """Local names assigned from ``<pool>.submit(...)`` — workpool
    futures whose ``result()`` helps drain the queue (bounded progress,
    and the submitted units carry their own Budget checks)."""
    futures: set[str] = set()
    for node in _own_nodes(fd):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                isinstance(node.value.func, ast.Attribute) and \
                node.value.func.attr == "submit":
            for t in node.targets:
                if isinstance(t, ast.Name):
                    futures.add(t.id)
    return futures


def blocking_calls(fd, g: CallGraph, seams: set[str]):
    """Yield (lineno, description) for unbounded blocking primitives in
    this function's own body."""
    pool_futures = _submit_futures(fd)
    for node in _own_nodes(fd):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if not name:
            continue
        if name in _METHOD_PRIMS and isinstance(f, ast.Attribute) and \
                _project_resolved(g, fd, f):
            continue  # resolves to project code the BFS walks itself
        if name == "sleep":
            yield node.lineno, "time.sleep() (unconditional wall-clock stall)"
        elif name in ("recv", "recv_into", "accept", "connect", "sendall") \
                and isinstance(f, ast.Attribute):
            yield node.lineno, (f"socket .{name}() outside a "
                                "settimeout-clipping wrapper")
        elif name in ("create_connection", "urlopen") and \
                not _has_timeout(node):
            yield node.lineno, f"{name}() without timeout="
        elif name == "get" and isinstance(f, ast.Attribute) and \
                not node.args and not _has_timeout(node) and \
                _kw(node, "block") is None:
            yield node.lineno, "queue .get() without timeout"
        elif name == "put" and isinstance(f, ast.Attribute) and \
                not _has_timeout(node) and _kw(node, "block") is None and \
                "queue" in _receiver_name(f).lower():
            yield node.lineno, "queue .put() without timeout"
        elif name == "result" and isinstance(f, ast.Attribute) and \
                not node.args and not _has_timeout(node) and \
                not (isinstance(f.value, ast.Name) and
                     f.value.id in pool_futures):
            yield node.lineno, ".result() without timeout on an unresolved future"
        elif name == "join" and isinstance(f, ast.Attribute) and \
                not node.args and not _has_timeout(node):
            yield node.lineno, "zero-arg .join() (unbounded thread/queue wait)"
        elif name == "wait" and isinstance(f, ast.Attribute) and \
                not node.args and not _has_timeout(node):
            yield node.lineno, ".wait() without timeout"
        elif name == "acquire" and isinstance(f, ast.Attribute) and \
                not node.args and not _has_timeout(node):
            recv = _receiver_name(f).lower()
            if "sem" in recv or "gate" in recv:
                yield node.lineno, (f"semaphore {_receiver_name(f)}"
                                    ".acquire() without timeout")


# -- the pass ---------------------------------------------------------------

def run_pass(g: CallGraph | None = None, paths=None):
    """Returns (findings, used_suppressions) where used_suppressions is
    ``{rel_path: {(line, RULE_ID), ...}}`` for VMT013's bookkeeping."""
    if g is None:
        g = build_callgraph(paths or _default_paths())
    entries = find_entries(g)
    seams = find_seams(g)

    # BFS with parent pointers so findings carry a witness path
    parent: dict[str, tuple[str | None, str]] = {}
    order = []
    for q, why in entries.items():
        if q in g.defs and q not in seams and q not in parent:
            parent[q] = (None, why)
            order.append(q)
    i = 0
    while i < len(order):
        q = order[i]
        i += 1
        for e in g.callees(q):
            t = e.target
            if t not in parent and t not in seams and t in g.defs:
                parent[t] = (q, parent[q][1])
                order.append(t)

    def witness(q: str) -> tuple[str, str]:
        chain = []
        cur: str | None = q
        while cur is not None:
            chain.append(g.defs[cur].name if g.defs.get(cur) else cur)
            cur = parent[cur][0]
        chain.reverse()
        entry_why = parent[q][1]
        if len(chain) > 5:
            chain = chain[:2] + ["..."] + chain[-2:]
        return entry_why, " -> ".join(chain)

    findings: list[Finding] = []
    used: dict[str, set[tuple[int, str]]] = {}
    seen_sites = set()
    for q in order:
        fd = g.defs[q]
        for lineno, what in blocking_calls(fd, g, seams):
            site = (fd.rel_path, lineno)
            if site in seen_sites:
                continue
            seen_sites.add(site)
            if _suppressed(g, fd.rel_path, lineno):
                used.setdefault(fd.rel_path, set()).add((lineno, RULE_ID))
                continue
            entry_why, path = witness(q)
            findings.append(Finding(
                fd.rel_path, lineno, RULE_ID,
                f"{what} reachable from serving entry [{entry_why}] "
                f"via {path}"))
    findings.sort(key=lambda f: (f.path, f.line))
    # a disable comment on a blocking site OUTSIDE the reachable set
    # still guards a real primitive — mark it consumed so VMT013 only
    # flags comments whose primitive vanished, not ones whose def
    # merely fell out of the entry closure
    reached = set(order)
    for q, fd in g.defs.items():
        if q in reached:
            continue
        for lineno, _what in blocking_calls(fd, g, seams):
            if _suppressed(g, fd.rel_path, lineno):
                used.setdefault(fd.rel_path, set()).add((lineno, RULE_ID))
    return findings, used


def _suppressed(g: CallGraph, rel: str, lineno: int) -> bool:
    src = g.sources.get(rel)
    if src is None:
        return False
    lines = src.splitlines()
    if not (1 <= lineno <= len(lines)):
        return False
    m = _SUPPRESS_RE.search(lines[lineno - 1])
    return bool(m) and RULE_ID in {
        s.strip().upper() for s in m.group(1).split(",")}


def _default_paths():
    from .lint import REPO_ROOT
    return [os.path.join(REPO_ROOT, "victoriametrics_tpu")]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m victoriametrics_tpu.devtools.deadline_taint",
        description="VMT012: blocking primitives reachable from serving "
                    "entry points without a deadline-aware seam.")
    ap.add_argument("paths", nargs="*")
    ap.add_argument("--list-entries", action="store_true")
    ap.add_argument("--list-seams", action="store_true")
    args = ap.parse_args(argv)

    g = build_callgraph(args.paths or _default_paths())
    if args.list_entries:
        for q, why in sorted(find_entries(g).items(),
                             key=lambda kv: kv[1]):
            print(f"{why:40s} {q}")
        return 0
    if args.list_seams:
        for q in sorted(find_seams(g)):
            print(q)
        return 0
    findings, _ = run_pass(g)
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} deadline-taint finding(s): fix them "
              f"(these are real hangs waiting for a slow peer), do not "
              f"baseline them.", file=sys.stderr)
        return 1
    print(f"deadline-taint clean: {len(find_entries(g))} entries, "
          f"{len(g.defs)} defs analyzed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
