"""Persistent compile-cache smoke (tools/lint.sh + tools/check.sh gate):
a second cold process must compile ZERO kernels for a bucket shape the
first process warmed.  Without this gate a jax upgrade or a config drift
(min-compile-time threshold, cache-key salt) silently reverts every
restart to paying the full fused-kernel compile storm.

Two phases, two child processes each (same ``VM_COMPILE_CACHE_DIR``):

1. ``native``  — jax's own persistent compilation cache, the production
   path on supported runtimes;
2. ``ownfmt``  — ``VM_OWN_EXEC_CACHE=1`` forces the own-format
   serialized-executable fallback (query.tpu_engine.OwnExecutableCache),
   the path for backends whose runtime jax's cache refuses.

Each child compiles ONE small fleet bucket through the real mesh path
(parallel.mesh.cached_fleet_rollup_aggregate) and reports the
backend-compile / cache-hit counters.  The warm child must report
0 compiles and >= 1 hits.  A runtime where neither mechanism can work
(compile-event telemetry unavailable, or the native cache refuses the
backend AND serialization is unsupported) skips LOUDLY with exit 0.
``VMT_NO_COMPILE_CACHE_SMOKE=1`` skips from tools/lint.sh / check.sh.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile


def _child() -> int:
    import jax
    import numpy as np

    from ..ops.device_rollup import TS_PAD, normalized_cfg
    from ..ops.rollup_np import RollupConfig
    from ..parallel.mesh import cached_fleet_rollup_aggregate, make_fleet_mesh
    from ..query import tpu_engine as te

    te.enable_compilation_cache()
    # the smoke kernel is tiny; cache it regardless of compile speed
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from ..query.fleet import bucket_up

    # the bucket axis shards across the mesh, so B must land on the same
    # device-aware rung query.fleet uses (a caller-inherited XLA_FLAGS
    # device count > 2 would otherwise make B=2 unshardable)
    B = bucket_up(2, len(jax.devices()))
    S, N, G, T = 8, 64, 4, 10
    step = 60_000
    cfg = normalized_cfg("rate", RollupConfig(0, (T - 1) * step, step,
                                              300_000))
    rng = np.random.default_rng(7)
    ts = np.full((B, S, N), TS_PAD, np.int32)
    vals = np.zeros((B, S, N))
    counts = np.full((B, S), N // 2, np.int32)
    for b in range(B):
        for s in range(S):
            ts[b, s, :N // 2] = np.sort(
                rng.integers(-300_000, (T - 1) * step, N // 2)).astype(
                    np.int32)
            vals[b, s, :N // 2] = np.cumsum(rng.integers(0, 20, N // 2))
    gids = (np.arange(S, dtype=np.int32) % G)[None, :].repeat(B, 0)
    # sum / max alternating: aggr codes are data, one program serves both
    aggr = np.resize(np.array([0, 4], np.int32), B)
    shift = np.zeros(B, np.int32)
    min_ts = np.full(B, -(2**31) + 1, np.int32)
    v0 = np.zeros((B, S))

    mesh = make_fleet_mesh(jax.devices())
    fn = cached_fleet_rollup_aggregate(mesh, "rate", cfg, G)
    out = np.asarray(fn(ts, vals, counts, gids, aggr, shift, min_ts, v0))
    assert out.shape == (B, G, T), out.shape
    assert np.isfinite(out).any(), "fleet smoke kernel produced no values"
    print(json.dumps({
        "compiles": te.backend_compiles(),
        "hits": te.compile_cache_hits(),
        "telemetry": te._COMPILE_EVENTS_SET,
        "native_refused": te.jax_cache_refused(),
    }))
    return 0


def _warmup() -> int:
    """``tools/device.sh warmup``: pre-compile the fleet kernel for the
    deployment's common bucket shapes into the persistent cache
    (``VM_COMPILE_CACHE_DIR``), so the serving process after the next
    restart deserializes instead of paying the cold compile storm.
    ``VM_WARMUP_FUNCS`` (default rate), ``VM_WARMUP_SHAPE`` ("B,S,N,T,G"
    ladder rungs), ``VM_WARMUP_STEP_MS`` and ``VM_WARMUP_WINDOW_MS``
    pick the shapes — they must land on the SAME rungs query.fleet
    derives or the warmed entries are dead weight."""
    import jax
    import numpy as np

    from ..ops.device_rollup import TS_PAD, normalized_cfg
    from ..ops.rollup_np import RollupConfig
    from ..parallel.mesh import cached_fleet_rollup_aggregate, make_fleet_mesh
    from ..query import fleet as fleetmod
    from ..query import tpu_engine as te

    te.enable_compilation_cache()
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    funcs = os.environ.get("VM_WARMUP_FUNCS", "rate").split(",")
    shape = [int(x) for x in os.environ.get(
        "VM_WARMUP_SHAPE", "8,512,384,24,64").split(",")]
    B, S, N, T, G = (fleetmod.bucket_up(shape[0], len(jax.devices())),
                     fleetmod.bucket_up(shape[1]),
                     fleetmod.bucket_up(shape[2], 64),
                     fleetmod.bucket_up(shape[3]),
                     fleetmod.bucket_up(shape[4]))
    step = int(os.environ.get("VM_WARMUP_STEP_MS", "60000"))
    window = int(os.environ.get("VM_WARMUP_WINDOW_MS", "300000"))
    mesh = make_fleet_mesh(jax.devices())
    ts = np.full((B, S, N), TS_PAD, np.int32)
    vals = np.zeros((B, S, N))
    counts = np.zeros((B, S), np.int32)
    gids = np.zeros((B, S), np.int32)
    aggr = np.zeros(B, np.int32)
    shift = np.zeros(B, np.int32)
    min_ts = np.full(B, -(2**31) + 1, np.int32)
    v0 = np.zeros((B, S))
    for func in funcs:
        cfg = normalized_cfg(func, RollupConfig(0, (T - 1) * step, step,
                                                window))
        fn = cached_fleet_rollup_aggregate(mesh, func, cfg, G)
        np.asarray(fn(ts, vals, counts, gids, aggr, shift, min_ts, v0))
    print(f"compile-cache warmup: {len(funcs)} func(s) x "
          f"[B={B},S={S},N={N},T={T},G={G}] -> "
          f"{te.backend_compiles()} compiled, "
          f"{te.compile_cache_hits()} already cached")
    return 0


def _spawn(cache_dir: str, own_fmt: bool) -> dict:
    env = dict(os.environ)
    env.update(VM_COMPILE_CACHE_DIR=cache_dir,
               JAX_PLATFORMS=env.get("JAX_PLATFORMS", "cpu"),
               JAX_ENABLE_X64="1")
    if own_fmt:
        env["VM_OWN_EXEC_CACHE"] = "1"
    else:
        env.pop("VM_OWN_EXEC_CACHE", None)
    p = subprocess.run(
        [sys.executable, "-m",
         "victoriametrics_tpu.devtools.compile_cache_smoke", "--child"],
        env=env, capture_output=True, text=True, timeout=600)
    if p.returncode != 0:
        raise RuntimeError(f"child failed rc={p.returncode}:\n"
                           f"{p.stdout}\n{p.stderr}")
    return json.loads(p.stdout.strip().splitlines()[-1])


def main() -> int:
    if "--child" in sys.argv:
        return _child()
    if "--warmup" in sys.argv:
        return _warmup()
    failures = []
    for phase in ("native", "ownfmt"):
        tmp = tempfile.mkdtemp(prefix=f"ccache-smoke-{phase}-")
        try:
            cold = _spawn(tmp, own_fmt=phase == "ownfmt")
            if not cold["telemetry"]:
                print("compile-cache smoke: SKIP (jax compile-event "
                      "telemetry unavailable; counters are meaningless)")
                return 0
            if cold["compiles"] < 1:
                failures.append(f"{phase}: cold child reported "
                                f"{cold['compiles']} compiles; expected >=1")
                continue
            if phase == "native" and cold["native_refused"]:
                print("compile-cache smoke: SKIP native phase (backend "
                      "refuses jax's persistent cache; own-format phase "
                      "still gates)")
                continue
            warm = _spawn(tmp, own_fmt=phase == "ownfmt")
            if warm["compiles"] != 0:
                failures.append(
                    f"{phase}: warm child recompiled "
                    f"{warm['compiles']} kernels for a warmed shape")
            elif warm["hits"] < 1:
                failures.append(f"{phase}: warm child never ticked "
                                "vm_device_fleet_compile_cache_hits_total")
            else:
                print(f"compile-cache smoke: {phase} OK "
                      f"(cold {cold['compiles']} compiles -> warm "
                      f"{warm['compiles']}, {warm['hits']} cache hits)")
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        print("compile-cache smoke: FAIL\n  " + "\n  ".join(failures))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
