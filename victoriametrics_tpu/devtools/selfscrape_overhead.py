"""Self-monitoring plane overhead smoke check (tools/lint.sh gate).

The self-scrape + SLO plane contract is "<2% overhead": the plane is a
single background thread that wakes once per ``-selfScrapeInterval``
(15s default), snapshots the registry, ingests the rows locally and
runs one SLO eval round.  Its steady-state cost is therefore a duty
cycle — ``(scrape_cost + eval_cost) / interval`` — and that is what
this smoke measures and gates, against a REAL Storage and a REAL
SLOEngine (not mocks), with several warm rounds of scraped history in
place so the burn-rate queries touch actual series.

Duty cycle is the noise-robust form of an on/off workload delta for a
background plane: an on/off A-B of a foreground workload mostly dodges
the 15s ticks entirely (the minimum statistic sees zero ticks), while
the duty cycle is exactly the fraction of one core the plane consumes.
Each cost is the MINIMUM over several cycles (noise only inflates a
timing; a real regression raises every cycle's floor), with full
retries before declaring failure.

Gates:

1. **Duty cycle**: ``(min scrape + min eval) / 15s`` must stay under
   ``VM_SELFSCRAPE_SMOKE_PCT`` (default 2 — the ISSUE's budget).
2. **Per-cycle budget**: one scrape+eval cycle must finish inside
   ``VM_SELFSCRAPE_SMOKE_MS`` (default 300 ms — a cycle that slow
   would also skew the sub-second intervals tests use).

``VMT_NO_SELFSCRAPE_SMOKE=1`` skips (exit 0) for boxes where even the
tiny tmpdir Storage is unwanted.

Run directly:
``python -m victoriametrics_tpu.devtools.selfscrape_overhead``
(prints one JSON line; exit 0 = within budget, 1 = regression).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time


def _min_cost_s(fn, cycles: int) -> float:
    best = float("inf")
    for _ in range(cycles):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_smoke(max_duty_pct: float, max_cycle_ms: float,
              retries: int = 3) -> dict:
    from ..httpapi.prometheus_api import PrometheusAPI
    from ..storage.storage import Storage
    from ..utils import selfscrape

    interval_s = selfscrape.DEFAULT_INTERVAL_S
    tmp = tempfile.mkdtemp(prefix="vmt-selfscrape-smoke-")
    try:
        s = Storage(tmp)
        try:
            api = PrometheusAPI(s)
            engine = api.init_sloplane()
            scraper = selfscrape.SelfScraper(
                s.add_rows, instance="smoke", interval_s=interval_s,
                extra=api.app_metrics)
            # warm history: a few spaced samples so increase()/rate()
            # burn queries see real series, not an empty index
            from ..utils import fasttime
            now_ms = fasttime.unix_ms()
            for k in range(3):
                scraper.scrape_once(ts_ms=now_ms - (3 - k) * 15_000)
            engine.maybe_eval(force=True)

            scrape_s = eval_s = float("inf")
            duty_pct = cycle_ms = float("inf")
            for _attempt in range(retries):
                # interleave the two sides so clock drift hits both
                for _ in range(4):
                    scrape_s = min(scrape_s,
                                   _min_cost_s(scraper.scrape_once, 2))
                    eval_s = min(eval_s, _min_cost_s(
                        lambda: engine.maybe_eval(force=True), 2))
                duty_pct = (scrape_s + eval_s) / interval_s * 1e2
                cycle_ms = (scrape_s + eval_s) * 1e3
                if duty_pct <= max_duty_pct and cycle_ms <= max_cycle_ms:
                    break
            return {
                "scrape_ms": round(scrape_s * 1e3, 3),
                "eval_ms": round(eval_s * 1e3, 3),
                "cycle_ms": round(cycle_ms, 3),
                "max_cycle_ms": max_cycle_ms,
                "interval_s": interval_s,
                "duty_pct": round(duty_pct, 4),
                "max_duty_pct": max_duty_pct,
                "slo_exprs_per_round": engine.exprs_last_round,
                "ok": duty_pct <= max_duty_pct and cycle_ms <= max_cycle_ms,
            }
        finally:
            s.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    if os.environ.get("VMT_NO_SELFSCRAPE_SMOKE") == "1":
        print(json.dumps({"check": "selfscrape_overhead",
                          "skipped": True, "ok": True}))
        return 0
    try:
        max_duty_pct = float(
            os.environ.get("VM_SELFSCRAPE_SMOKE_PCT", "2"))
    except ValueError:
        max_duty_pct = 2.0
    try:
        max_cycle_ms = float(
            os.environ.get("VM_SELFSCRAPE_SMOKE_MS", "300"))
    except ValueError:
        max_cycle_ms = 300.0
    res = run_smoke(max_duty_pct, max_cycle_ms)
    res["check"] = "selfscrape_overhead"
    print(json.dumps(res))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
