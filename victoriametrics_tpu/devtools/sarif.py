"""SARIF 2.1.0 emission for the lint engine and program passes.

One emitter for everything: VMT001–VMT011 line rules, the wire-schema
ratchet, and the whole-program passes (deadline-taint, lockset,
errorflow) all produce :class:`lint.Finding` rows, so one
``to_sarif()`` turns any of their outputs into a single-run SARIF log
that CI annotators and editors ingest directly.

The output is the minimal *valid* subset of the spec: ``version`` +
``$schema``, one ``run`` with a ``tool.driver`` carrying the rule
catalog, and one ``result`` per finding with ``ruleId``, ``level``,
``message.text`` and a ``physicalLocation`` (repo-relative URI +
1-based ``startLine``).  ``tests/test_sarif.py`` validates it against
the vendored structural subset of the official 2.1.0 schema
(``sarif_schema_2.1.0.json``).
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")

TOOL_NAME = "vmt-lint"


def to_sarif(findings, rule_summaries: dict[str, str] | None = None,
             tool_name: str = TOOL_NAME) -> dict:
    """Findings -> a SARIF 2.1.0 log dict (caller json.dumps it).

    ``rule_summaries`` maps rule id -> one-line description for the
    driver's rule catalog; rules appearing only in findings get a
    catalog entry with an empty description so every ``ruleId`` in
    ``results`` resolves via ``rules``.
    """
    summaries = dict(rule_summaries or {})
    for f in findings:
        summaries.setdefault(f.rule, "")
    rules = [{"id": rid,
              "shortDescription": {"text": summaries[rid] or rid}}
             for rid in sorted(summaries)]
    index = {r["id"]: i for i, r in enumerate(rules)}
    results = [{
        "ruleId": f.rule,
        "ruleIndex": index[f.rule],
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(int(f.line), 1)},
            },
        }],
    } for f in findings]
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": tool_name,
                "informationUri":
                    "https://github.com/VictoriaMetrics/VictoriaMetrics",
                "rules": rules,
            }},
            "columnKind": "utf16CodeUnits",
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
