"""Fault-injection seams for chaos testing.

The chaos harness (``tests/test_chaos_cluster.py``, ``tools/chaos.sh``)
needs the cluster to misbehave ON DEMAND: slow nodes, stalled RPCs,
connection resets, injected error returns.  This module is the single
seam — production code calls :func:`fire` at a handful of well-known
points and pays one attribute read when no faults are armed.

Fault points currently wired:

- ``rpc:<method>`` — the RPC server dispatch (parallel/rpc.py), fired
  after the method name is parsed and before the handler runs.  A
  ``reset`` here closes the connection without a response frame (the
  client sees a mid-frame close); ``delay``/``stall`` hold the
  connection thread so the client's socket deadline trips.
- ``storage:search:<accountID>:<projectID>`` — the storage engine's
  search entry (storage/storage.py), fired INSIDE the TenantGate slot
  so an injected delay occupies real admission capacity (how the QoS
  chaos scenario saturates one tenant without touching another).
- ``storage:scan`` — the storage-side deadline budget check (fired at
  every Budget check while a deadline-carrying search runs): a
  ``delay`` here dilates the scan so a chaos run can prove a query
  aborts within ~one check interval of its budget expiring.
- Crashpoints in the part lifecycle (the kill -9 recovery matrix,
  tools/chaos.sh): ``part:finalize:pre_rename``,
  ``part:finalize:post_rename``, ``partition:parts_json:pre_replace``,
  ``merge:post_rename_pre_manifest``, ``mergeset:flush``,
  ``indexdb:rotate``, ``snapshot:mid``.  Armed with the ``crash``
  action they hard-kill the process (``os._exit``) at that instant, so
  a subprocess harness can die at every interesting point of the
  write-to-tmp -> fsync -> rename discipline and assert clean reopen.

Spec grammar (``VM_FAULTS`` env var at process start, or swapped live
over HTTP via ``/internal/faults?set=...``)::

    spec    := entry (';' entry)*
    entry   := point '=' action [':' param [':' probability]]
    action  := 'delay' | 'stall' | 'error' | 'reset' | 'crash'

``point`` may end in ``*`` for a prefix match (``rpc:*`` hits every
RPC method; ``storage:search:*`` every tenant).  ``param`` is the
sleep in ms for ``delay``/``stall`` (stall defaults to 300000 —
"forever" at query timescales) and the exit code for ``crash``
(default 86, the harness's "died at an armed crashpoint" signature);
probability defaults to 1.0.

Examples::

    VM_FAULTS='rpc:searchColumns_v1=delay:500'        # slow node
    VM_FAULTS='rpc:*=reset::0.3'                      # flaky transport
    VM_FAULTS='storage:search:1:0=delay:300'          # one slow tenant
    VM_FAULTS='part:finalize:pre_rename=crash'        # kill -9 mid-flush
    VM_FAULTS='merge:*=crash::0.25'                   # randomized crash

Injections count into ``vm_fault_injections_total{point=,action=}`` so
a chaos run can assert its faults actually fired.
"""

from __future__ import annotations

import os
import random
import threading
import time

from ..utils import metrics as metricslib

__all__ = ["ConnectionAbort", "InjectedError", "configure", "spec",
           "fire", "active", "http_enabled", "handle_http"]


class InjectedError(RuntimeError):
    """Injected handler failure: surfaces as a normal error response."""


class ConnectionAbort(Exception):
    """Injected connection reset: the transport must drop the peer
    without a response (NOT an error frame — the point is to exercise
    the client's reconnect path, not its error path)."""


_ACTIONS = ("delay", "stall", "error", "reset", "crash")

#: exit code for an armed ``crash`` action (overridable per entry via the
#: param field): distinctive enough that the recovery harness can tell
#: "died at the crashpoint" from an ordinary failure
CRASH_EXIT_CODE = 86


class _Fault:
    __slots__ = ("point", "action", "param_ms", "prob")

    def __init__(self, point: str, action: str, param_ms: float,
                 prob: float):
        self.point = point
        self.action = action
        self.param_ms = param_ms
        self.prob = prob

    def matches(self, point: str) -> bool:
        if self.point.endswith("*"):
            return point.startswith(self.point[:-1])
        return point == self.point

    def __str__(self) -> str:
        s = f"{self.point}={self.action}:{self.param_ms:g}"
        if self.prob < 1.0:
            s += f":{self.prob:g}"
        return s


_lock = threading.Lock()
_faults: list[_Fault] = []
#: fast-path guard: fire() reads this one attribute when nothing is armed
_armed = False

_metric_memo: dict[tuple, object] = {}


def _injections(point: str, action: str):
    key = (point, action)
    m = _metric_memo.get(key)
    if m is None:
        # benign double-create: REGISTRY.counter dedups by name, so two
        # racing fills store the same object
        m = _metric_memo[key] = metricslib.REGISTRY.counter(  # vmt: disable=VMT015
            metricslib.format_name("vm_fault_injections_total",
                                   {"point": point, "action": action}))
    return m


def parse(raw: str) -> list[_Fault]:
    """Parse a fault spec; raises ValueError with a pointed message on a
    malformed entry (the HTTP toggle surfaces it as a 400)."""
    out: list[_Fault] = []
    for entry in raw.replace("\n", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        point, eq, rhs = entry.partition("=")
        point = point.strip()
        if not eq or not point:
            raise ValueError(f"bad fault entry {entry!r} "
                             f"(want point=action[:ms[:prob]])")
        parts = rhs.strip().split(":")
        action = parts[0]
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} "
                             f"(want one of {', '.join(_ACTIONS)})")
        param_ms = 300_000.0 if action == "stall" else \
            float(CRASH_EXIT_CODE) if action == "crash" else 0.0
        prob = 1.0
        if len(parts) > 1 and parts[1]:
            param_ms = float(parts[1])
        if len(parts) > 2 and parts[2]:
            prob = float(parts[2])
        if len(parts) > 3:
            raise ValueError(f"bad fault entry {entry!r}: too many fields")
        out.append(_Fault(point, action, param_ms, prob))
    return out


def configure(raw: str) -> None:
    """Replace the armed fault table ('' clears everything)."""
    global _armed
    faults = parse(raw)
    with _lock:
        _faults[:] = faults
        _armed = bool(faults)


def spec() -> str:
    """The armed fault table, re-serialized to the spec grammar."""
    with _lock:
        return ";".join(str(f) for f in _faults)


def active() -> bool:
    return _armed


def fire(point: str) -> None:
    """Trip any armed fault matching `point`.  No-op (one attribute
    read) unless faults are configured."""
    if not _armed:
        return
    with _lock:
        matched = [f for f in _faults if f.matches(point)]
    for f in matched:
        if f.prob < 1.0 and random.random() >= f.prob:
            continue
        _injections(f.point, f.action).inc()
        if f.action in ("delay", "stall"):
            # the injected stall IS the configured fault: an operator
            # armed VM_FAULTS to model exactly this hang
            time.sleep(f.param_ms / 1e3)  # vmt: disable=VMT012
        elif f.action == "error":
            # chaos tool: the anonymous 500/error frame IS the injected
            # failure mode the harness asserts on — never map it
            raise InjectedError(  # vmt: disable=VMT016
                f"injected fault at {point} (devtools/faultinject)")
        elif f.action == "reset":
            # models a peer dropping the TCP connection mid-call; on an
            # HTTP-reachable point the resulting 500 is the modeled fault
            raise ConnectionAbort(  # vmt: disable=VMT016
                f"injected connection reset at {point}")
        elif f.action == "crash":
            # hard kill, NOW: no atexit, no finally blocks, no flusher
            # shutdown — the whole point is to model kill -9 at this
            # exact instant.  Write the marker line unbuffered so the
            # recovery harness can attribute the death.
            try:
                os.write(2, f"faultinject: CRASH at {point}\n".encode())
            except OSError:
                pass
            os._exit(int(f.param_ms) or CRASH_EXIT_CODE)


def http_enabled() -> bool:
    """Whether the live ``/internal/faults`` toggle may mutate the
    table.  Opt-in only — a production process must not be stallable by
    one unauthenticated HTTP request: enabled when ``VM_FAULT_INJECT``
    is truthy (re-read per request) or a fault table was armed from
    ``VM_FAULTS`` at process start (the process already consented to
    chaos)."""
    return os.environ.get("VM_FAULT_INJECT", "") not in ("", "0") \
        or bool(_env_spec)


def handle_http(req, response_cls):
    """The shared ``/internal/faults`` handler (vmstorage's bare HTTP
    server and PrometheusAPI both route here): GET lists the armed
    table, ``?set=<spec>`` replaces it, ``?clear=1`` disarms; 403
    unless :func:`http_enabled`."""
    if not http_enabled():
        return response_cls.error(
            "fault injection disabled (start the process with "
            "VM_FAULT_INJECT=1 or VM_FAULTS set to enable the live "
            "toggle)", 403, "forbidden")
    if req.arg("clear") == "1":
        configure("")
    elif "set" in req.query:
        try:
            configure(req.arg("set"))
        except ValueError as e:
            return response_cls.error(f"bad fault spec: {e}", 400)
    return response_cls.json({"status": "ok", "faults": spec()})


# arm from the environment at import so subprocess apptests configure
# faults without an HTTP round trip (AppProc passes env overrides)
_env_spec = os.environ.get("VM_FAULTS", "")
if _env_spec:
    try:
        configure(_env_spec)
    except ValueError:
        # a typo in the env must not brick the process at import; the
        # operator sees the empty table via /internal/faults
        pass
