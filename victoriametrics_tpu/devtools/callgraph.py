"""Project-wide AST-derived call graph (the whole-program substrate the
PR-1/PR-3 per-function lint rules could never see).

Every ``.py`` file under the package is parsed once; every function,
method, nested function and lambda becomes a :class:`FuncDef` with a
stable qualified name (``relpath::Class.method``, ``relpath::func``,
``relpath::outer.inner``).  Call edges are resolved in decreasing order
of confidence:

1. **Lexical names** — local defs, enclosing-scope defs, module-level
   defs, and imports (``from ..utils import fs as fslib`` makes
   ``fslib.write_meta_json(...)`` resolve into ``utils/fs.py``).
2. **self/cls methods** — ``self.m()`` resolves within the enclosing
   class, then through project base classes.
3. **Receiver-type hints** — parameter annotations (``x: RingConfig``),
   local constructor assignments (``c = RPCClient(...)``), and
   ``self.attr = ClassName(...)`` bindings collected from ``__init__``
   (so ``self.insert.call(...)`` resolves through ``RPCClient``).
4. **Attribute-name fallback** — ``storage.search_series(...)`` links to
   every project class defining ``search_series`` when the name is
   distinctive (few definers, not in the ubiquitous-name stoplist).
   Duck-typed seams (the ``storage`` protocol) stay covered without
   annotations; ``.get``/``.close``-style names never explode the graph.

Concurrency edges are calls: ``threading.Thread(target=f)``,
``POOL.run([partial(f, x) for ...])`` and ``pool.submit(f)`` all add an
edge to ``f`` — work handed to a thread or the shared workpool still
runs on behalf of the submitting path, which is exactly what the
deadline-taint pass (VMT012) needs to see.

Consumers: :mod:`devtools.deadline_taint` (serving-path blocking-call
reachability) and :mod:`devtools.wireschema` (marshal/unmarshal helper
resolution).  Build cost is one AST parse per file (~100 files, well
under a second) — cheap enough for every full lint run.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from .lint import dotted_name, iter_py_files, normalize_path

#: attribute names too generic to resolve by name alone: linking every
#: ``.get()`` to every class with a ``get`` method would connect the
#: whole graph through dict-shaped noise
_GENERIC_ATTRS = {
    "get", "put", "items", "keys", "values", "append", "extend", "add",
    "pop", "remove", "clear", "copy", "update", "setdefault", "close",
    "read", "write", "flush", "seek", "tell", "join", "split", "strip",
    "encode", "decode", "sort", "sorted", "index", "count", "format",
    "result", "wait", "acquire", "release", "send", "recv", "sendall",
    "connect", "accept", "start", "stop", "run", "submit", "info",
    "debug", "warning", "error", "sum", "min", "max", "mean", "all",
    "any", "tobytes", "astype", "reshape", "item", "fire", "inc", "dec",
    "set", "name", "startswith", "endswith", "lower", "upper", "replace",
}

#: max distinct project definers for attribute-name fallback resolution;
#: past this the name is effectively generic and edges would be noise
_MAX_ATTR_CANDIDATES = 8


@dataclasses.dataclass
class FuncDef:
    qname: str                  # "relpath::Class.method" / "relpath::func"
    rel_path: str
    name: str                   # bare name ("method", "func", "<lambda>")
    cls: str | None             # enclosing class name, if any
    node: object                # ast.FunctionDef/AsyncFunctionDef/Lambda
    lineno: int


@dataclasses.dataclass(frozen=True)
class Edge:
    target: str                 # callee qname
    lineno: int
    kind: str                   # "call" | "thread" | "submit" | "ref"


class CallGraph:
    def __init__(self):
        self.defs: dict[str, FuncDef] = {}
        #: attr/method name -> qnames of project defs with that name
        self.by_name: dict[str, list[str]] = {}
        self.edges: dict[str, list[Edge]] = {}
        #: class qname ("relpath::Class") -> list of base-class qnames
        self.bases: dict[str, list[str]] = {}
        #: class qname -> {method name -> qname}
        self.methods: dict[str, dict[str, str]] = {}
        #: (relpath, local dotted alias) -> target, for module aliases
        self._imports: dict[tuple[str, str], str] = {}
        #: "relpath::Class" -> {attr -> class qname} from __init__ hints
        self._attr_types: dict[str, dict[str, str]] = {}
        #: module rel_path -> {top-level def/class name -> qname}
        self._module_scope: dict[str, dict[str, str]] = {}
        #: rel_path -> module ast (for passes that re-walk, e.g. wireschema)
        self.module_trees: dict[str, object] = {}
        self.sources: dict[str, str] = {}

    # -- queries ----------------------------------------------------------

    def callees(self, qname: str) -> list[Edge]:
        return self.edges.get(qname, [])

    def lookup(self, rel_path: str, dotted: str) -> str | None:
        """Resolve a dotted name as seen from ``rel_path`` module scope
        (``Class.method``, ``func``, imported ``mod.func``)."""
        scope = self._module_scope.get(rel_path, {})
        head, _, rest = dotted.partition(".")
        q = scope.get(head)
        if q is None:
            # `from mod import Name` binding
            bound = self._imports.get((rel_path, head + "@from"))
            if bound is not None:
                tgt_rel, _, tgt_name = bound.partition("::")
                q = self._module_scope.get(tgt_rel, {}).get(tgt_name)
            if q is None:
                return self._resolve_import(rel_path, dotted)
        if not rest:
            return q
        # Class.method within this module
        m = self.methods.get(q, {})
        return m.get(rest)

    def class_method(self, cls_qname: str, method: str) -> str | None:
        """Resolve a method through the project class hierarchy."""
        seen = set()
        stack = [cls_qname]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            got = self.methods.get(c, {}).get(method)
            if got is not None:
                return got
            stack.extend(self.bases.get(c, []))
        return None

    def reachable(self, entries, stop=frozenset()) -> set[str]:
        """Qnames reachable from ``entries`` without descending INTO any
        function in ``stop`` (the deadline-aware wrapper seams)."""
        seen: set[str] = set()
        stack = [q for q in entries if q in self.defs]
        while stack:
            q = stack.pop()
            if q in seen or q in stop:
                continue
            seen.add(q)
            for e in self.edges.get(q, ()):
                if e.target not in seen and e.target not in stop:
                    stack.append(e.target)
        return seen

    def _resolve_import(self, rel_path: str, dotted: str) -> str | None:
        """``alias.func`` where alias is an imported module."""
        head, _, rest = dotted.partition(".")
        target = self._imports.get((rel_path, head))
        if target is None or not rest:
            return None
        # target is a module rel_path; rest may be func or Class.method
        first, _, tail = rest.partition(".")
        scope = self._module_scope.get(target, {})
        q = scope.get(first)
        if q is None:
            return None
        if not tail:
            return q
        return self.methods.get(q, {}).get(tail)


# -- builder ----------------------------------------------------------------

def _module_rel(pkg_root: str, module: str, cur_rel: str,
                level: int) -> str | None:
    """Rel-path of an imported module inside the package, else None."""
    if level:  # relative import: anchor at the current module's package
        base = cur_rel.rsplit("/", 1)[0]
        for _ in range(level - 1):
            base = base.rsplit("/", 1)[0] if "/" in base else ""
        parts = ([base] if base else []) + \
            ([p for p in module.split(".")] if module else [])
        dotted = "/".join(p for p in parts if p)
    else:
        dotted = module.replace(".", "/") if module else ""
    if not dotted:
        return None
    for cand in (dotted + ".py", dotted + "/__init__.py"):
        if os.path.exists(os.path.join(pkg_root, cand)):
            return cand
    return None


class _ModuleIndexer(ast.NodeVisitor):
    """Pass 1: defs, classes, imports, __init__ attr-type hints."""

    def __init__(self, g: CallGraph, rel: str, repo_root: str):
        self.g = g
        self.rel = rel
        self.repo_root = repo_root
        self.scope: list[str] = []       # qname parts under the module
        self.cls_stack: list[str] = []   # class qnames

    def _q(self, name: str) -> str:
        return f"{self.rel}::" + ".".join(self.scope + [name])

    def _add_def(self, node, name: str):
        q = self._q(name)
        cls = self.cls_stack[-1].split("::")[-1] if self.cls_stack else None
        fd = FuncDef(q, self.rel, name, cls, node, node.lineno)
        self.g.defs[q] = fd
        self.g.by_name.setdefault(name, []).append(q)
        if self.cls_stack and len(self.scope) == 1:
            self.g.methods.setdefault(self.cls_stack[-1], {})[name] = q
        if not self.scope:
            self.g._module_scope.setdefault(self.rel, {})[name] = q
        return q

    def visit_FunctionDef(self, node):
        self._add_def(node, node.name)
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._add_def(node, f"<lambda@{node.lineno}>")
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        q = f"{self.rel}::{node.name}"
        if not self.scope:
            self.g._module_scope.setdefault(self.rel, {})[node.name] = q
            self.g.by_name.setdefault(node.name, []).append(q)
        self.g.methods.setdefault(q, {})
        self.cls_stack.append(q)
        self.scope.append(node.name)
        # base names resolved in pass 2 (they may be imports)
        self.g.bases.setdefault(q, [])
        for b in node.bases:
            dn = dotted_name(b)
            if dn:
                self.g.bases[q].append(f"?{self.rel}?{dn}")
        self.generic_visit(node)
        self.scope.pop()
        self.cls_stack.pop()

    def visit_Import(self, node):
        for alias in node.names:
            tgt = _module_rel(self.repo_root, alias.name, self.rel, 0)
            if tgt:
                local = alias.asname or alias.name.split(".")[0]
                self.g._imports[(self.rel, local)] = tgt

    def visit_ImportFrom(self, node):
        mod_rel = _module_rel(self.repo_root, node.module or "", self.rel,
                              node.level)
        if mod_rel is None:
            return
        for alias in node.names:
            local = alias.asname or alias.name
            # imported def/class: alias directly into module scope later
            # (pass 2 may need it before the target module is indexed,
            # so record as a deferred import binding)
            sub = _module_rel(self.repo_root, (node.module or "") + "." +
                              alias.name, self.rel, node.level)
            if sub is not None:   # `from ..pkg import module`
                self.g._imports[(self.rel, local)] = sub
            else:                 # `from ..pkg.module import name`
                self.g._imports[(self.rel, local + "@from")] = \
                    mod_rel + "::" + alias.name


class _EdgeBuilder:
    """Pass 2: call edges for every def."""

    def __init__(self, g: CallGraph, rel: str):
        self.g = g
        self.rel = rel

    def _resolve_name(self, name: str, scope_defs: list[dict]) -> str | None:
        for frame in reversed(scope_defs):
            if name in frame:
                return frame[name]
        q = self.g._module_scope.get(self.rel, {}).get(name)
        if q is not None:
            return q
        # `from mod import name` binding
        bound = self.g._imports.get((self.rel, name + "@from"))
        if bound is not None:
            tgt_rel, _, tgt_name = bound.partition("::")
            return self.g._module_scope.get(tgt_rel, {}).get(tgt_name)
        return None

    def _resolve_dotted(self, dn: str, scope_defs, cls_q, types) -> \
            list[str]:
        """Candidate qnames for a dotted callee name."""
        head, _, rest = dn.partition(".")
        if not rest:
            q = self._resolve_name(dn, scope_defs)
            return [q] if q else []
        if head in ("self", "cls") and cls_q:
            if "." not in rest:
                q = self.g.class_method(cls_q, rest)
                if q:
                    return [q]
            else:  # self.attr.method(): __init__ type hints
                attr, _, meth = rest.partition(".")
                t = self.g._attr_types.get(cls_q, {}).get(attr)
                if t and "." not in meth:
                    q = self.g.class_method(t, meth)
                    if q:
                        return [q]
                return self._by_attr_name(meth.rpartition(".")[2])
            return self._by_attr_name(rest)
        # typed local receiver
        t = types.get(head)
        if t is not None and "." not in rest:
            q = self.g.class_method(t, rest)
            if q:
                return [q]
        # imported module alias / module-scope class
        q = self.g.lookup(self.rel, dn)
        if q is not None:
            return [q]
        return self._by_attr_name(rest.rpartition(".")[2])

    def _by_attr_name(self, name: str) -> list[str]:
        if not name or name in _GENERIC_ATTRS:
            return []
        cands = [q for q in self.g.by_name.get(name, ())
                 if self.g.defs.get(q) and self.g.defs[q].cls]
        if 0 < len(cands) <= _MAX_ATTR_CANDIDATES:
            return cands
        return []

    def _callable_refs(self, node) -> list[object]:
        """Callable-reference expressions inside a submit/run argument:
        bare names, ``partial(f, ...)``, list/comprehension elements."""
        out = []
        if isinstance(node, (ast.Name, ast.Attribute)):
            out.append(node)
        elif isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn and dn.rpartition(".")[2] == "partial" and node.args:
                out.extend(self._callable_refs(node.args[0]))
        elif isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            for e in node.elts:
                out.extend(self._callable_refs(e))
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                               ast.SetComp)):
            out.extend(self._callable_refs(node.elt))
        elif isinstance(node, ast.Starred):
            out.extend(self._callable_refs(node.value))
        return out

    def build(self, fd: FuncDef, scope_defs: list[dict], cls_q,
              types: dict):
        edges = self.g.edges.setdefault(fd.qname, [])
        seen = set()

        def add(q: str | None, lineno: int, kind: str):
            if q and q != fd.qname and (q, kind) not in seen:
                seen.add((q, kind))
                edges.append(Edge(q, lineno, kind))

        body = fd.node.body if not isinstance(fd.node, ast.Lambda) \
            else [fd.node.body]
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs get their own edge sets
            if isinstance(node, ast.Lambda):
                continue
            # local constructor type hints: x = ClassName(...)
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                dn = dotted_name(node.value.func)
                if dn:
                    tq = self.g.lookup(self.rel, dn) or \
                        self._resolve_name(dn, scope_defs)
                    if tq in self.g.methods:  # it's a class
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                types[t.id] = tq
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
                if dn:
                    last = dn.rpartition(".")[2]
                    if last == "Thread":
                        for kw in node.keywords:
                            if kw.arg == "target":
                                for ref in self._callable_refs(kw.value):
                                    rdn = dotted_name(ref)
                                    if rdn:
                                        for q in self._resolve_dotted(
                                                rdn, scope_defs, cls_q,
                                                types):
                                            add(q, node.lineno, "thread")
                    elif last in ("submit", "run") and \
                            isinstance(node.func, ast.Attribute):
                        for a in list(node.args):
                            for ref in self._callable_refs(a):
                                rdn = dotted_name(ref)
                                if rdn:
                                    for q in self._resolve_dotted(
                                            rdn, scope_defs, cls_q, types):
                                        add(q, node.lineno, "submit")
                    for q in self._resolve_dotted(dn, scope_defs, cls_q,
                                                  types):
                        # constructor call -> edge to __init__
                        if q in self.g.methods:
                            q = self.g.methods[q].get("__init__")
                        add(q, node.lineno, "call")
                # callback handoff: a bare function name passed as an
                # argument (``self._fan_stripes(by_shard, do_register)``)
                # still runs on behalf of this caller — lexical
                # resolution only, so dict/str arguments add no noise
                for a in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(a, ast.Name):
                        q = self._resolve_name(a.id, scope_defs)
                        if q is not None and q in self.g.defs:
                            add(q, node.lineno, "ref")
            stack.extend(ast.iter_child_nodes(node))


def _annotation_types(g: CallGraph, rel: str, node) -> dict[str, str]:
    """Param-annotation receiver types (``x: RingConfig``)."""
    types: dict[str, str] = {}
    if isinstance(node, ast.Lambda):
        return types
    args = node.args
    for a in list(args.args) + list(args.posonlyargs) + \
            list(args.kwonlyargs):
        if a.annotation is not None:
            dn = dotted_name(a.annotation)
            if dn is None and isinstance(a.annotation, ast.Constant) and \
                    isinstance(a.annotation.value, str):
                dn = a.annotation.value.strip("'\" ").split("|")[0].strip()
            if dn:
                q = g.lookup(rel, dn)
                if q in g.methods:
                    types[a.arg] = q
    return types


def _collect_attr_types(g: CallGraph):
    """``self.attr = ClassName(...)`` hints from every method (the
    ``__init__``-heavy case plus lazy constructions elsewhere)."""
    for cls_q, methods in g.methods.items():
        hints = g._attr_types.setdefault(cls_q, {})
        for mq in methods.values():
            fd = g.defs.get(mq)
            if fd is None or isinstance(fd.node, ast.Lambda):
                continue
            for node in ast.walk(fd.node):
                if not (isinstance(node, ast.Assign) and
                        isinstance(node.value, ast.Call)):
                    continue
                dn = dotted_name(node.value.func)
                if not dn:
                    continue
                tq = g.lookup(fd.rel_path, dn)
                if tq not in g.methods:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        hints.setdefault(t.attr, tq)


def _resolve_bases(g: CallGraph):
    for cls_q, bases in g.bases.items():
        out = []
        for b in bases:
            if b.startswith("?"):
                _, rel, dn = b.split("?", 2)
                q = g.lookup(rel, dn)
                if q in g.methods:
                    out.append(q)
            elif b in g.methods:
                out.append(b)
        g.bases[cls_q] = out


def build_callgraph(paths, repo_root: str | None = None) -> CallGraph:
    """Build the graph over every ``.py`` file under ``paths``."""
    from .lint import REPO_ROOT
    repo_root = repo_root or REPO_ROOT
    g = CallGraph()
    trees: list[tuple[str, object]] = []
    for path in iter_py_files(paths):
        rel = normalize_path(path)
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError):
            continue
        g.sources[rel] = src
        g.module_trees[rel] = tree
        trees.append((rel, tree))
        _ModuleIndexer(g, rel, repo_root).visit(tree)
    _resolve_bases(g)
    _collect_attr_types(g)
    for rel, tree in trees:
        eb = _EdgeBuilder(g, rel)

        # walk defs with their lexical scope chains; `scope_names` is the
        # dotted path OF `node` (empty for the module), so a def is built
        # against frames that include its OWN nested defs — h_search can
        # call its local `frames()` helper and POOL.run list-comps over
        # nested workers resolve
        def walk(node, scope_defs, scope_names, cls_q):
            local: dict[str, str] = {}
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    local[child.name] = f"{rel}::" + ".".join(
                        scope_names + [child.name])
            frames = scope_defs + [local]
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fd = g.defs.get(f"{rel}::" + ".".join(scope_names))
                if fd is not None:
                    eb.build(fd, frames, cls_q,
                             _annotation_types(g, rel, node))
            elif isinstance(node, ast.Lambda):
                fd = g.defs.get(f"{rel}::" + ".".join(scope_names))
                if fd is not None:
                    eb.build(fd, frames, cls_q, {})
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    walk(child, frames, scope_names + [child.name], cls_q)
                elif isinstance(child, ast.ClassDef):
                    walk(child, frames, scope_names + [child.name],
                         f"{rel}::{child.name}")
                elif isinstance(child, ast.Lambda):
                    walk(child, frames,
                         scope_names + [f"<lambda@{child.lineno}>"],
                         cls_q)
                else:
                    walk(child, frames, scope_names, cls_q)
        walk(tree, [], [], None)
    return g
