"""Project-wide AST-derived call graph (the whole-program substrate the
PR-1/PR-3 per-function lint rules could never see).

Every ``.py`` file under the package is parsed once; every function,
method, nested function and lambda becomes a :class:`FuncDef` with a
stable qualified name (``relpath::Class.method``, ``relpath::func``,
``relpath::outer.inner``).  Call edges are resolved in decreasing order
of confidence:

1. **Lexical names** — local defs, enclosing-scope defs, module-level
   defs, and imports (``from ..utils import fs as fslib`` makes
   ``fslib.write_meta_json(...)`` resolve into ``utils/fs.py``).
2. **self/cls methods** — ``self.m()`` resolves within the enclosing
   class, then through project base classes.
3. **Receiver-type hints** — parameter annotations (``x: RingConfig``),
   local constructor assignments (``c = RPCClient(...)``), and
   ``self.attr = ClassName(...)`` bindings collected from ``__init__``
   (so ``self.insert.call(...)`` resolves through ``RPCClient``).
4. **Attribute-name fallback** — ``storage.search_series(...)`` links to
   every project class defining ``search_series`` when the name is
   distinctive (few definers, not in the ubiquitous-name stoplist).
   Duck-typed seams (the ``storage`` protocol) stay covered without
   annotations; ``.get``/``.close``-style names never explode the graph.

Concurrency edges are calls: ``threading.Thread(target=f)``,
``POOL.run([partial(f, x) for ...])`` and ``pool.submit(f)`` all add an
edge to ``f`` — work handed to a thread or the shared workpool still
runs on behalf of the submitting path, which is exactly what the
deadline-taint pass (VMT012) needs to see.

Since PR 18 every edge also carries its *context*:

- ``locks`` — the lock identities lexically held at the call site
  (``with self._lock:`` regions; identities resolve through the
  ``make_lock``/``make_rlock`` name registry, so ``self._lock`` in two
  modules guarding the same ``make_lock("storage.Storage._lock")``
  instance unify).  The lockset pass (VMT015) intersects these along
  call chains to infer which lock guards each field.
- ``caught`` — the exception-type keys of every enclosing
  ``try/except`` at the call site.  The errorflow pass (VMT016) stops
  propagating an escaping exception type at the first frame that
  catches it.

Alongside edges the builder now records per-def *field accesses*
(``self.attr`` and module-global mutable containers, read vs write,
with the lexically-held locks), *raise sites* (resolved exception-type
keys with their enclosing handlers) and the ``make_lock`` name
bindings + exception base-class map those passes resolve against.

Consumers: :mod:`devtools.deadline_taint` (serving-path blocking-call
reachability), :mod:`devtools.lockset` (VMT015 guarded-by inference),
:mod:`devtools.errorflow` (VMT016 exception-escape audit) and
:mod:`devtools.wireschema` (marshal/unmarshal helper resolution).
Build cost is one AST parse per file (~120 files, well under a
second) — cheap enough for every full lint run.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from .lint import _SUPPRESS_RE, dotted_name, iter_py_files, normalize_path
from .rules_locks import lockish_name

#: attribute names too generic to resolve by name alone: linking every
#: ``.get()`` to every class with a ``get`` method would connect the
#: whole graph through dict-shaped noise
_GENERIC_ATTRS = {
    "get", "put", "items", "keys", "values", "append", "extend", "add",
    "pop", "remove", "clear", "copy", "update", "setdefault", "close",
    "read", "write", "flush", "seek", "tell", "join", "split", "strip",
    "encode", "decode", "sort", "sorted", "index", "count", "format",
    "result", "wait", "acquire", "release", "send", "recv", "sendall",
    "connect", "accept", "start", "stop", "run", "submit", "info",
    "debug", "warning", "error", "sum", "min", "max", "mean", "all",
    "any", "tobytes", "astype", "reshape", "item", "fire", "inc", "dec",
    "set", "name", "startswith", "endswith", "lower", "upper", "replace",
}

#: max distinct project definers for attribute-name fallback resolution;
#: past this the name is effectively generic and edges would be noise
_MAX_ATTR_CANDIDATES = 8

#: receiver methods that mutate their container in place — a call like
#: ``self._cache.pop(k)`` is a WRITE access to the ``_cache`` field
_MUTATORS = {
    "append", "extend", "insert", "remove", "discard", "clear", "pop",
    "popitem", "popleft", "appendleft", "update", "setdefault", "sort",
    "reverse", "add",
}

#: constructor names whose module-level result is shared mutable state
_GLOBAL_CTORS = {
    "dict", "list", "set", "OrderedDict", "defaultdict", "deque",
    "Counter", "WeakValueDictionary",
}

#: keyword-argument names that hand a callable over for deferred
#: invocation on some other thread of control (service-thread ticks,
#: completion hooks) — matched literally or by the on_* prefix
_CALLBACK_KW_RE = re.compile(r"^on_[a-z0-9_]+$|^(callback|cb|hook)$")

#: external (non-project) callables with a documented raise contract the
#: errorflow pass should see: wire/payload parsing that throws on bad
#: input.  Kept deliberately tiny — flagging every int()/float() guard
#: in the tree would drown the real boundary gaps.
EXT_RAISERS = {
    "json.loads": "ValueError",
    "json.load": "ValueError",
}


def _make_lock_name(call) -> str | None:
    """The registry name of a ``make_lock("...")``/``make_rlock("...")``
    construction, else None."""
    if not isinstance(call, ast.Call):
        return None
    dn = dotted_name(call.func)
    if dn and dn.rpartition(".")[2] in ("make_lock", "make_rlock") and \
            call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


@dataclasses.dataclass
class FuncDef:
    qname: str                  # "relpath::Class.method" / "relpath::func"
    rel_path: str
    name: str                   # bare name ("method", "func", "<lambda>")
    cls: str | None             # enclosing class name, if any
    node: object                # ast.FunctionDef/AsyncFunctionDef/Lambda
    lineno: int


@dataclasses.dataclass(frozen=True)
class Edge:
    target: str                 # callee qname
    lineno: int
    #: "call" | "thread" | "submit" | "ref" | "cbref" — cbref marks a
    #: callable handed over via a callback-shaped keyword argument
    #: (``on_tick=...``): it runs later on whatever thread the receiver
    #: invokes it from, so lockset treats the target as its own root
    kind: str
    #: lock identities lexically held at the call site (VMT015);
    #: empty for thread/submit edges — the spawned work runs in its own
    #: context and does not inherit the spawner's critical section
    locks: tuple = ()
    #: exception-type keys of enclosing try/except handlers (VMT016)
    caught: tuple = ()


class CallGraph:
    def __init__(self):
        self.defs: dict[str, FuncDef] = {}
        #: attr/method name -> qnames of project defs with that name
        self.by_name: dict[str, list[str]] = {}
        self.edges: dict[str, list[Edge]] = {}
        #: class qname ("relpath::Class") -> list of base-class qnames
        self.bases: dict[str, list[str]] = {}
        #: class qname -> {method name -> qname}
        self.methods: dict[str, dict[str, str]] = {}
        #: (relpath, local dotted alias) -> target, for module aliases
        self._imports: dict[tuple[str, str], str] = {}
        #: "relpath::Class" -> {attr -> class qname} from __init__ hints
        self._attr_types: dict[str, dict[str, str]] = {}
        #: module rel_path -> {top-level def/class name -> qname}
        self._module_scope: dict[str, dict[str, str]] = {}
        #: rel_path -> module ast (for passes that re-walk, e.g. wireschema)
        self.module_trees: dict[str, object] = {}
        self.sources: dict[str, str] = {}
        #: qname -> [(field_id, "read"|"write", lineno, locks)] — accesses
        #: to self.* fields / module-global containers (VMT015)
        self.accesses: dict[str, list[tuple]] = {}
        #: qname -> [(type_key, lineno, caught)] raise sites (VMT016);
        #: type_key is a project class qname or a builtin exception name
        self.raises: dict[str, list[tuple]] = {}
        #: qname -> [(dotted, lineno, caught)] calls into EXT_RAISERS
        self.ext_calls: dict[str, list[tuple]] = {}
        #: ("relpath::Class", attr) / (relpath, var) -> make_lock name
        self.lock_names: dict[tuple[str, str], str] = {}
        #: class qname -> base names with builtins KEPT as bare names
        #: (g.bases drops non-project bases; exception-hierarchy walks
        #: need RuntimeError/ValueError/... to stay visible)
        self.exc_bases: dict[str, list[str]] = {}
        #: rel_path -> {module-level mutable-global name -> lineno}
        self.module_globals: dict[str, dict[str, int]] = {}

    # -- queries ----------------------------------------------------------

    def callees(self, qname: str) -> list[Edge]:
        return self.edges.get(qname, [])

    def lookup(self, rel_path: str, dotted: str) -> str | None:
        """Resolve a dotted name as seen from ``rel_path`` module scope
        (``Class.method``, ``func``, imported ``mod.func``)."""
        scope = self._module_scope.get(rel_path, {})
        head, _, rest = dotted.partition(".")
        q = scope.get(head)
        if q is None:
            # `from mod import Name` binding
            bound = self._imports.get((rel_path, head + "@from"))
            if bound is not None:
                tgt_rel, _, tgt_name = bound.partition("::")
                q = self._module_scope.get(tgt_rel, {}).get(tgt_name)
            if q is None:
                return self._resolve_import(rel_path, dotted)
        if not rest:
            return q
        # Class.method within this module
        m = self.methods.get(q, {})
        return m.get(rest)

    def class_method(self, cls_qname: str, method: str) -> str | None:
        """Resolve a method through the project class hierarchy."""
        seen = set()
        stack = [cls_qname]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            got = self.methods.get(c, {}).get(method)
            if got is not None:
                return got
            stack.extend(self.bases.get(c, []))
        return None

    def reachable(self, entries, stop=frozenset()) -> set[str]:
        """Qnames reachable from ``entries`` without descending INTO any
        function in ``stop`` (the deadline-aware wrapper seams)."""
        seen: set[str] = set()
        stack = [q for q in entries if q in self.defs]
        while stack:
            q = stack.pop()
            if q in seen or q in stop:
                continue
            seen.add(q)
            for e in self.edges.get(q, ()):
                if e.target not in seen and e.target not in stop:
                    stack.append(e.target)
        return seen

    def _resolve_import(self, rel_path: str, dotted: str) -> str | None:
        """``alias.func`` where alias is an imported module."""
        head, _, rest = dotted.partition(".")
        target = self._imports.get((rel_path, head))
        if target is None or not rest:
            return None
        # target is a module rel_path; rest may be func or Class.method
        first, _, tail = rest.partition(".")
        scope = self._module_scope.get(target, {})
        q = scope.get(first)
        if q is None:
            return None
        if not tail:
            return q
        return self.methods.get(q, {}).get(tail)


# -- shared pass helpers -----------------------------------------------------

def source_suppressed(g: CallGraph, rel: str, lineno: int,
                      rule_id: str) -> bool:
    """True when the source line carries ``# vmt: disable=<rule_id>`` —
    the inline-suppression check shared by the whole-program passes."""
    src = g.sources.get(rel)
    if src is None:
        return False
    lines = src.splitlines()
    if not (1 <= lineno <= len(lines)):
        return False
    m = _SUPPRESS_RE.search(lines[lineno - 1])
    return bool(m) and rule_id in {
        s.strip().upper() for s in m.group(1).split(",")}


def lock_identity(g: CallGraph, rel: str, cls_q: str | None, expr,
                  local_locks: dict[str, str]) -> str | None:
    """Stable identity of a lock-looking ``with`` context expression.

    A lock constructed via ``make_lock("storage.Storage._lock")`` is
    identified by that registry name wherever it is held — the name is
    the cross-module identity.  Unregistered locks fall back to a
    lexical id (``relpath::Class.attr`` / ``relpath::var``), which still
    unifies accesses within one class/module."""
    dn = lockish_name(expr)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    if head in ("self", "cls") and cls_q and rest:
        seen: set[str] = set()
        stack = [cls_q]
        while stack:  # inherited locks bind in a base's __init__
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            named = g.lock_names.get((c, rest))
            if named is not None:
                return named
            stack.extend(g.bases.get(c, []))
        return f"{cls_q}.{rest}"
    if not rest and dn in local_locks:
        return local_locks[dn]
    named = g.lock_names.get((rel, head if not rest else dn))
    return named or f"{rel}::{dn}"


# -- builder ----------------------------------------------------------------

def _module_rel(pkg_root: str, module: str, cur_rel: str,
                level: int) -> str | None:
    """Rel-path of an imported module inside the package, else None."""
    if level:  # relative import: anchor at the current module's package
        base = cur_rel.rsplit("/", 1)[0]
        for _ in range(level - 1):
            base = base.rsplit("/", 1)[0] if "/" in base else ""
        parts = ([base] if base else []) + \
            ([p for p in module.split(".")] if module else [])
        dotted = "/".join(p for p in parts if p)
    else:
        dotted = module.replace(".", "/") if module else ""
    if not dotted:
        return None
    for cand in (dotted + ".py", dotted + "/__init__.py"):
        if os.path.exists(os.path.join(pkg_root, cand)):
            return cand
    return None


class _ModuleIndexer(ast.NodeVisitor):
    """Pass 1: defs, classes, imports, __init__ attr-type hints."""

    def __init__(self, g: CallGraph, rel: str, repo_root: str):
        self.g = g
        self.rel = rel
        self.repo_root = repo_root
        self.scope: list[str] = []       # qname parts under the module
        self.cls_stack: list[str] = []   # class qnames

    def _q(self, name: str) -> str:
        return f"{self.rel}::" + ".".join(self.scope + [name])

    def _add_def(self, node, name: str):
        q = self._q(name)
        cls = self.cls_stack[-1].split("::")[-1] if self.cls_stack else None
        fd = FuncDef(q, self.rel, name, cls, node, node.lineno)
        self.g.defs[q] = fd
        self.g.by_name.setdefault(name, []).append(q)
        if self.cls_stack and len(self.scope) == 1:
            self.g.methods.setdefault(self.cls_stack[-1], {})[name] = q
        if not self.scope:
            self.g._module_scope.setdefault(self.rel, {})[name] = q
        return q

    def visit_FunctionDef(self, node):
        self._add_def(node, node.name)
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._add_def(node, f"<lambda@{node.lineno}>")
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        q = f"{self.rel}::{node.name}"
        if not self.scope:
            self.g._module_scope.setdefault(self.rel, {})[node.name] = q
            self.g.by_name.setdefault(node.name, []).append(q)
        self.g.methods.setdefault(q, {})
        self.cls_stack.append(q)
        self.scope.append(node.name)
        # base names resolved in pass 2 (they may be imports)
        self.g.bases.setdefault(q, [])
        for b in node.bases:
            dn = dotted_name(b)
            if dn:
                self.g.bases[q].append(f"?{self.rel}?{dn}")
        self.generic_visit(node)
        self.scope.pop()
        self.cls_stack.pop()

    def visit_Import(self, node):
        for alias in node.names:
            tgt = _module_rel(self.repo_root, alias.name, self.rel, 0)
            if tgt:
                local = alias.asname or alias.name.split(".")[0]
                self.g._imports[(self.rel, local)] = tgt

    def visit_ImportFrom(self, node):
        mod_rel = _module_rel(self.repo_root, node.module or "", self.rel,
                              node.level)
        if mod_rel is None:
            return
        for alias in node.names:
            local = alias.asname or alias.name
            # imported def/class: alias directly into module scope later
            # (pass 2 may need it before the target module is indexed,
            # so record as a deferred import binding)
            sub = _module_rel(self.repo_root, (node.module or "") + "." +
                              alias.name, self.rel, node.level)
            if sub is not None:   # `from ..pkg import module`
                self.g._imports[(self.rel, local)] = sub
            else:                 # `from ..pkg.module import name`
                self.g._imports[(self.rel, local + "@from")] = \
                    mod_rel + "::" + alias.name


class _EdgeBuilder:
    """Pass 2: call edges for every def."""

    def __init__(self, g: CallGraph, rel: str):
        self.g = g
        self.rel = rel

    def _resolve_name(self, name: str, scope_defs: list[dict]) -> str | None:
        for frame in reversed(scope_defs):
            if name in frame:
                return frame[name]
        q = self.g._module_scope.get(self.rel, {}).get(name)
        if q is not None:
            return q
        # `from mod import name` binding
        bound = self.g._imports.get((self.rel, name + "@from"))
        if bound is not None:
            tgt_rel, _, tgt_name = bound.partition("::")
            return self.g._module_scope.get(tgt_rel, {}).get(tgt_name)
        return None

    def _resolve_dotted(self, dn: str, scope_defs, cls_q, types) -> \
            list[str]:
        """Candidate qnames for a dotted callee name."""
        head, _, rest = dn.partition(".")
        if not rest:
            q = self._resolve_name(dn, scope_defs)
            return [q] if q else []
        if head in ("self", "cls") and cls_q:
            if "." not in rest:
                q = self.g.class_method(cls_q, rest)
                if q:
                    return [q]
            else:  # self.attr.method(): __init__ type hints
                attr, _, meth = rest.partition(".")
                t = self.g._attr_types.get(cls_q, {}).get(attr)
                if t and "." not in meth:
                    q = self.g.class_method(t, meth)
                    if q:
                        return [q]
                return self._by_attr_name(meth.rpartition(".")[2])
            return self._by_attr_name(rest)
        # typed local receiver
        t = types.get(head)
        if t is not None and "." not in rest:
            q = self.g.class_method(t, rest)
            if q:
                return [q]
        # imported module alias / module-scope class
        q = self.g.lookup(self.rel, dn)
        if q is not None:
            return [q]
        return self._by_attr_name(rest.rpartition(".")[2])

    def _by_attr_name(self, name: str) -> list[str]:
        if not name or name in _GENERIC_ATTRS:
            return []
        cands = [q for q in self.g.by_name.get(name, ())
                 if self.g.defs.get(q) and self.g.defs[q].cls]
        if 0 < len(cands) <= _MAX_ATTR_CANDIDATES:
            return cands
        return []

    def _callable_refs(self, node) -> list[object]:
        """Callable-reference expressions inside a submit/run argument:
        bare names, ``partial(f, ...)``, list/comprehension elements."""
        out = []
        if isinstance(node, (ast.Name, ast.Attribute, ast.Lambda)):
            out.append(node)
        elif isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn and dn.rpartition(".")[2] == "partial" and node.args:
                out.extend(self._callable_refs(node.args[0]))
        elif isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            for e in node.elts:
                out.extend(self._callable_refs(e))
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                               ast.SetComp)):
            out.extend(self._callable_refs(node.elt))
        elif isinstance(node, ast.Starred):
            out.extend(self._callable_refs(node.value))
        return out

    def _lambda_q(self, lineno: int) -> str | None:
        suffix = f"<lambda@{lineno}>"
        for q in self.g.defs:
            if q.startswith(self.rel + "::") and q.endswith(suffix):
                return q
        return None

    def _ref_qnames(self, ref, scope_defs, cls_q, types) -> list[str]:
        if isinstance(ref, ast.Lambda):
            q = self._lambda_q(ref.lineno)
            return [q] if q else []
        rdn = dotted_name(ref)
        if not rdn:
            return []
        return self._resolve_dotted(rdn, scope_defs, cls_q, types)

    def build(self, fd: FuncDef, scope_defs: list[dict], cls_q,
              types: dict):
        edges = self.g.edges.setdefault(fd.qname, [])
        accesses = self.g.accesses.setdefault(fd.qname, [])
        raise_sites = self.g.raises.setdefault(fd.qname, [])
        ext_calls = self.g.ext_calls.setdefault(fd.qname, [])
        seen = set()
        local_locks: dict[str, str] = {}
        skip_reads: set[int] = set()    # node ids already counted

        node0 = fd.node
        body = [node0.body] if isinstance(node0, ast.Lambda) \
            else list(node0.body)

        # names the function binds locally: a bare Name only refers to a
        # module global when the function neither assigns it nor takes
        # it as a parameter (or re-exports it via `global`)
        local_names: set[str] = set()
        global_names: set[str] = set()
        a = node0.args
        for arg in (list(a.args) + list(a.posonlyargs) +
                    list(a.kwonlyargs) +
                    ([a.vararg] if a.vararg else []) +
                    ([a.kwarg] if a.kwarg else [])):
            local_names.add(arg.arg)
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_names.add(n.name)
                continue
            if isinstance(n, ast.Lambda):
                continue
            if isinstance(n, ast.Global):
                global_names.update(n.names)
            elif isinstance(n, ast.Name) and \
                    isinstance(n.ctx, (ast.Store, ast.Del)):
                local_names.add(n.id)
            stack.extend(ast.iter_child_nodes(n))
        local_names -= global_names
        mod_globals = self.g.module_globals.get(self.rel, {})

        def add(q: str | None, lineno: int, kind: str,
                locks: tuple = (), caught: tuple = ()):
            key = (q, kind, locks, caught)
            if q and q != fd.qname and key not in seen:
                seen.add(key)
                edges.append(Edge(q, lineno, kind, locks, caught))

        def field_of(expr):
            """Field id for a self-attribute / module-global access,
            else None.  Subscript chains unwrap to their base
            (``self._cache[k]`` accesses ``_cache``)."""
            while isinstance(expr, ast.Subscript):
                expr = expr.value
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id in ("self", "cls") and cls_q:
                if lockish_name(expr) or \
                        self.g.class_method(cls_q, expr.attr) is not None:
                    return None   # the lock itself / a bound method
                return f"{cls_q}.{expr.attr}"
            if isinstance(expr, ast.Name) and expr.id in mod_globals and \
                    expr.id not in local_names and \
                    lockish_name(expr) is None:
                return f"{self.rel}::{expr.id}"
            return None

        def exc_keys(tnode) -> tuple:
            """Type keys of an except clause: project class qnames when
            resolvable, bare builtin names otherwise; "*" for bare
            except / Exception / BaseException."""
            if tnode is None:
                return ("*",)
            elts = tnode.elts if isinstance(tnode, ast.Tuple) else [tnode]
            keys = []
            for t in elts:
                dn = dotted_name(t)
                if not dn:
                    continue
                last = dn.rpartition(".")[2]
                if last in ("Exception", "BaseException"):
                    keys.append("*")
                    continue
                q = self.g.lookup(self.rel, dn)
                if q is None and "." not in dn:
                    q = self._resolve_name(dn, scope_defs)
                keys.append(q if q in self.g.methods else last)
            return tuple(keys) or ("*",)

        def record_raise(node, caught, hvars, htypes):
            if node.exc is None:       # bare re-raise inside a handler
                for k in htypes:
                    if k != "*":
                        raise_sites.append((k, node.lineno, caught))
                return
            e = node.exc
            target = e.func if isinstance(e, ast.Call) else e
            dn = dotted_name(target)
            if not dn:
                return
            if dn in hvars:            # `raise e` of the caught exc
                for k in hvars[dn]:
                    if k != "*":
                        raise_sites.append((k, node.lineno, caught))
                return
            q = self.g.lookup(self.rel, dn)
            if q is None and "." not in dn:
                q = self._resolve_name(dn, scope_defs)
            key = q if q in self.g.methods else dn.rpartition(".")[2]
            raise_sites.append((key, node.lineno, caught))

        def visit(node, locks, caught, hvars, htypes):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return   # nested defs get their own edge sets
            if isinstance(node, ast.Try):
                handler_keys = tuple(k for h in node.handlers
                                     for k in exc_keys(h.type))
                # `try: ... finally: X.release()` brackets a lock region
                # even when the acquire is out of line (a conditional
                # try-acquire, or a helper returning with the lock HELD,
                # e.g. Storage._acquire_cspace) — the body runs under X
                body_locks = locks
                for n in node.finalbody:
                    if isinstance(n, ast.Expr) \
                            and isinstance(n.value, ast.Call) \
                            and isinstance(n.value.func, ast.Attribute) \
                            and n.value.func.attr == "release" \
                            and lockish_name(n.value.func.value):
                        lid = lock_identity(self.g, self.rel, cls_q,
                                            n.value.func.value, local_locks)
                        if lid and lid not in body_locks:
                            body_locks = body_locks + (lid,)
                for n in node.body:
                    visit(n, body_locks, caught + handler_keys, hvars,
                          htypes)
                for h in node.handlers:
                    keys = exc_keys(h.type)
                    hv = dict(hvars)
                    if h.name:
                        hv[h.name] = keys
                    for n in h.body:   # handler body: outer tries only
                        visit(n, locks, caught, hv, keys)
                for n in node.orelse:  # else runs before finally: held
                    visit(n, body_locks, caught, hvars, htypes)
                for n in node.finalbody:
                    visit(n, locks, caught, hvars, htypes)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_locks = locks
                for item in node.items:
                    visit(item.context_expr, locks, caught, hvars, htypes)
                    lid = lock_identity(self.g, self.rel, cls_q,
                                        item.context_expr, local_locks)
                    if lid and lid not in new_locks:
                        new_locks = new_locks + (lid,)
                for n in node.body:
                    visit(n, new_locks, caught, hvars, htypes)
                return
            if isinstance(node, ast.Raise):
                record_raise(node, caught, hvars, htypes)
            # local lock construction + constructor type hints
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                lname = _make_lock_name(node.value)
                dn = dotted_name(node.value.func)
                tq = None
                if dn:
                    tq = self.g.lookup(self.rel, dn) or \
                        self._resolve_name(dn, scope_defs)
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if lname:
                        local_locks[t.id] = lname
                    if tq in self.g.methods:  # it's a class
                        types[t.id] = tq
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
                if dn:
                    last = dn.rpartition(".")[2]
                    if last == "Thread":
                        for kw in node.keywords:
                            if kw.arg == "target":
                                for ref in self._callable_refs(kw.value):
                                    for q in self._ref_qnames(
                                            ref, scope_defs, cls_q, types):
                                        add(q, node.lineno, "thread")
                    elif last in ("submit", "run") and \
                            isinstance(node.func, ast.Attribute):
                        for arg in list(node.args):
                            for ref in self._callable_refs(arg):
                                for q in self._ref_qnames(
                                        ref, scope_defs, cls_q, types):
                                    add(q, node.lineno, "submit")
                    # callback-shaped keyword: the callable escapes into
                    # the receiver and runs on ITS thread later
                    for kw in node.keywords:
                        if kw.arg and _CALLBACK_KW_RE.match(kw.arg):
                            for ref in self._callable_refs(kw.value):
                                for q in self._ref_qnames(
                                        ref, scope_defs, cls_q, types):
                                    add(q, node.lineno, "cbref")
                    resolved = self._resolve_dotted(dn, scope_defs, cls_q,
                                                    types)
                    for q in resolved:
                        # constructor call -> edge to __init__
                        if q in self.g.methods:
                            q = self.g.methods[q].get("__init__")
                        add(q, node.lineno, "call", locks, caught)
                    if not resolved and dn in EXT_RAISERS:
                        ext_calls.append((dn, node.lineno, caught))
                elif isinstance(node.func, ast.Attribute):
                    # method call on a computed receiver — e.g.
                    # ``api.init_sloplane().maybe_eval(...)`` — falls
                    # back to distinctive-attribute-name resolution
                    for q in self._by_attr_name(node.func.attr):
                        add(q, node.lineno, "call", locks, caught)
                # callback handoff: a bare function name passed as an
                # argument (``self._fan_stripes(by_shard, do_register)``)
                # still runs on behalf of this caller — lexical
                # resolution only, so dict/str arguments add no noise
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        q = self._resolve_name(arg.id, scope_defs)
                        if q is not None and q in self.g.defs:
                            add(q, node.lineno, "ref", locks, caught)
                # in-place mutation through a container method is a
                # write to the container field
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                    base = f.value
                    fld = field_of(base)
                    if fld:
                        accesses.append((fld, "write", node.lineno, locks))
                        while isinstance(base, ast.Subscript):
                            base = base.value
                        skip_reads.add(id(base))
            # field reads/writes: ctx tells stores from loads
            if isinstance(node, (ast.Attribute, ast.Name, ast.Subscript)):
                ctx = getattr(node, "ctx", None)
                if isinstance(ctx, (ast.Store, ast.Del)):
                    fld = field_of(node)
                    if fld is None and isinstance(node, ast.Attribute):
                        # `self.stats.hits = 3` mutates what `stats`
                        # refers to — a write to the outer field
                        fld = field_of(node.value)
                        if fld:
                            skip_reads.add(id(node.value))
                    if fld:
                        accesses.append((fld, "write", node.lineno, locks))
                    base = node
                    while isinstance(base, ast.Subscript):
                        base = base.value       # self._c[k] = v: the
                        skip_reads.add(id(base))  # Load of _c is the write
                elif isinstance(ctx, ast.Load) and \
                        not isinstance(node, ast.Subscript) and \
                        id(node) not in skip_reads:
                    fld = field_of(node)
                    if fld:
                        accesses.append((fld, "read", node.lineno, locks))
            for child in ast.iter_child_nodes(node):
                visit(child, locks, caught, hvars, htypes)

        for n in body:
            visit(n, (), (), {}, ())


def _annotation_types(g: CallGraph, rel: str, node) -> dict[str, str]:
    """Param-annotation receiver types (``x: RingConfig``)."""
    types: dict[str, str] = {}
    if isinstance(node, ast.Lambda):
        return types
    args = node.args
    for a in list(args.args) + list(args.posonlyargs) + \
            list(args.kwonlyargs):
        if a.annotation is not None:
            dn = dotted_name(a.annotation)
            if dn is None and isinstance(a.annotation, ast.Constant) and \
                    isinstance(a.annotation.value, str):
                dn = a.annotation.value.strip("'\" ").split("|")[0].strip()
            if dn:
                q = g.lookup(rel, dn)
                if q in g.methods:
                    types[a.arg] = q
    return types


def _collect_attr_types(g: CallGraph):
    """``self.attr = ClassName(...)`` hints from every method (the
    ``__init__``-heavy case plus lazy constructions elsewhere)."""
    for cls_q, methods in g.methods.items():
        hints = g._attr_types.setdefault(cls_q, {})
        for mq in methods.values():
            fd = g.defs.get(mq)
            if fd is None or isinstance(fd.node, ast.Lambda):
                continue
            for node in ast.walk(fd.node):
                if not (isinstance(node, ast.Assign) and
                        isinstance(node.value, ast.Call)):
                    continue
                dn = dotted_name(node.value.func)
                if not dn:
                    continue
                tq = g.lookup(fd.rel_path, dn)
                if tq not in g.methods:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        hints.setdefault(t.attr, tq)


def _resolve_bases(g: CallGraph):
    for cls_q, bases in g.bases.items():
        out, raw = [], []
        for b in bases:
            if b.startswith("?"):
                _, rel, dn = b.split("?", 2)
                q = g.lookup(rel, dn)
                if q in g.methods:
                    out.append(q)
                    raw.append(q)
                else:   # builtin/stdlib base: keep the bare name for
                    raw.append(dn.rpartition(".")[2])  # hierarchy walks
            elif b in g.methods:
                out.append(b)
                raw.append(b)
        g.bases[cls_q] = out
        g.exc_bases[cls_q] = raw


def _mutable_global_value(val) -> bool:
    if isinstance(val, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp, ast.Constant)):
        return True
    if isinstance(val, ast.Call):
        dn = dotted_name(val.func)
        return bool(dn) and dn.rpartition(".")[2] in _GLOBAL_CTORS
    return False


def _index_module_level(g: CallGraph, rel: str, tree):
    """Module-level ``make_lock`` bindings and mutable globals (shared
    state a function can reach without going through ``self``).  Scalar
    constants are included too: ``_N = 0`` rebound via ``global _N`` is
    just as much shared state as a dict."""
    globs = g.module_globals.setdefault(rel, {})
    for node in tree.body:
        if isinstance(node, ast.Assign):
            tgts, val = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgts, val = [node.target], node.value
        else:
            continue
        lname = _make_lock_name(val)
        for t in tgts:
            if not isinstance(t, ast.Name):
                continue
            if lname:
                g.lock_names.setdefault((rel, t.id), lname)
            elif _mutable_global_value(val):
                globs.setdefault(t.id, node.lineno)


def _collect_lock_names(g: CallGraph):
    """``self.attr = make_lock("name")`` bindings from every method —
    the registry name is the lock's cross-module identity."""
    for fd in g.defs.values():
        if isinstance(fd.node, ast.Lambda) or fd.cls is None:
            continue
        cls_q = f"{fd.rel_path}::{fd.cls}"
        for node in ast.walk(fd.node):
            if not isinstance(node, ast.Assign):
                continue
            lname = _make_lock_name(node.value)
            if not lname:
                continue
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    g.lock_names.setdefault((cls_q, t.attr), lname)


def build_callgraph(paths, repo_root: str | None = None) -> CallGraph:
    """Build the graph over every ``.py`` file under ``paths``."""
    from .lint import REPO_ROOT
    repo_root = repo_root or REPO_ROOT
    g = CallGraph()
    trees: list[tuple[str, object]] = []
    for path in iter_py_files(paths):
        rel = normalize_path(path)
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError):
            continue
        g.sources[rel] = src
        g.module_trees[rel] = tree
        trees.append((rel, tree))
        _ModuleIndexer(g, rel, repo_root).visit(tree)
    _resolve_bases(g)
    _collect_attr_types(g)
    _collect_lock_names(g)
    for rel, tree in trees:
        _index_module_level(g, rel, tree)
    for rel, tree in trees:
        eb = _EdgeBuilder(g, rel)

        # walk defs with their lexical scope chains; `scope_names` is the
        # dotted path OF `node` (empty for the module), so a def is built
        # against frames that include its OWN nested defs — h_search can
        # call its local `frames()` helper and POOL.run list-comps over
        # nested workers resolve
        def walk(node, scope_defs, scope_names, cls_q):
            local: dict[str, str] = {}
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    local[child.name] = f"{rel}::" + ".".join(
                        scope_names + [child.name])
            frames = scope_defs + [local]
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fd = g.defs.get(f"{rel}::" + ".".join(scope_names))
                if fd is not None:
                    eb.build(fd, frames, cls_q,
                             _annotation_types(g, rel, node))
            elif isinstance(node, ast.Lambda):
                fd = g.defs.get(f"{rel}::" + ".".join(scope_names))
                if fd is not None:
                    eb.build(fd, frames, cls_q, {})
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    walk(child, frames, scope_names + [child.name], cls_q)
                elif isinstance(child, ast.ClassDef):
                    walk(child, frames, scope_names + [child.name],
                         f"{rel}::{child.name}")
                elif isinstance(child, ast.Lambda):
                    walk(child, frames,
                         scope_names + [f"<lambda@{child.lineno}>"],
                         cls_q)
                else:
                    walk(child, frames, scope_names, cls_q)
        walk(tree, [], [], None)
    return g
