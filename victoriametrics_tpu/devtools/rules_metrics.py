"""VMT007 — self-observability discipline.

Ad-hoc instance-attribute counters (``self.<name>_total += 1``,
``self.request_count += 1``, ``self.errors += 1``) are invisible to
``/metrics`` unless someone remembers to splice them into an exposition
dict by hand, and they race under threads unless each site grows its own
lock.  The central registry (``utils/metrics.py``) gives every counter a
name, a lock, and automatic exposition — new counting code must go
through it.  Existing sites are grandfathered via the lint baseline.
"""

from __future__ import annotations

import ast

# the registry implementation itself is the one place allowed to count
# by attribute mutation
_ALLOWED_SUFFIXES = ("utils/metrics.py",)

# attribute names that mark a counter: the reference's *_total /*_count
# naming, plus the bare counter words this codebase has used
_COUNTER_SUFFIXES = ("_total", "_count")
_COUNTER_NAMES = {"hits", "misses", "errors", "pushes", "reroutes",
                  "rejected", "retries"}


class AdHocCounterRule:
    rule_id = "VMT007"
    summary = ("ad-hoc 'self.<x>_total += 1'-style counter outside "
               "utils/metrics.py (use REGISTRY.counter(...).inc())")

    def check(self, ctx):
        if ctx.rel_path.endswith(_ALLOWED_SUFFIXES):
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Add)
                    and isinstance(node.target, ast.Attribute)):
                continue
            attr = node.target.attr
            if not (attr.endswith(_COUNTER_SUFFIXES)
                    or attr in _COUNTER_NAMES):
                continue
            yield ctx.finding(
                node, self.rule_id,
                f"ad-hoc counter '.{attr} +=' is invisible to /metrics "
                f"and unsynchronized; use utils.metrics REGISTRY."
                f"counter(...).inc() (or keep the attribute AND mirror it "
                f"into the registry)")


RULES = [AdHocCounterRule()]
