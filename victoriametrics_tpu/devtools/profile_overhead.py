"""Continuous-profiler overhead smoke check (tools/lint.sh gate; the
profiler sibling of flight_overhead.py).

The profiler contract is "default-on and invisible": one
``sys._current_frames()`` walk per thread per 1/VM_PROFILE_HZ seconds
(default 10 Hz) must not dent serving throughput.  The smoke times a
serving-shaped workload (numpy-dominated ops bracketed by cost-
accounting laps, the same seams the real refresh path runs) with the
sampling thread RUNNING vs STOPPED; the delta must stay under
``VM_PROFILE_SMOKE_PCT`` (default 2%).  Trials are interleaved on/off
and each side keeps its MINIMUM across retries — noise inflates
measurements, regressions raise the floor.

Run directly: ``python -m victoriametrics_tpu.devtools.profile_overhead``
(prints one JSON line; exit 0 = within budget, 1 = overhead
regression).  ``VMT_NO_PROFILE_SMOKE=1`` skips it in tools/lint.sh.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from ..utils import costacc, profiler


def _workload(arr: np.ndarray, laps: int) -> None:
    """One simulated refresh: numpy work + the cost-accounting laps the
    real serving path records (a tracker is installed, so the laps take
    their real, non-short-circuited path)."""
    t0 = time.perf_counter()
    for k in range(laps):
        arr[k % 8] = np.sqrt(arr[(k + 1) % 8]).sum()
        now = time.perf_counter()
        costacc.lap("smoke:phase", now - t0)
        t0 = now


def _time_workload(reps: int, laps: int, arr: np.ndarray) -> float:
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _workload(arr, laps)
        best = min(best, time.perf_counter() - t0)
    return best


def run_smoke(max_delta_pct: float, retries: int = 3) -> dict:
    """Returns the result dict; ``result["ok"]`` is the verdict."""
    arr = np.random.default_rng(11).random((8, 65_536))
    laps = 16
    reps = 30
    hz = profiler.configured_hz() or 10.0
    prev_cost = costacc.set_current(costacc.CostTracker())
    try:
        delta_pct = float("inf")
        for _attempt in range(retries):
            _time_workload(5, laps, arr)  # warm-up
            t_on = t_off = float("inf")
            for _ in range(4):
                # interleave so clock drift hits both sides equally
                if not profiler.PROFILER.ensure_started():
                    # hz forced to 0 in the environment: nothing to
                    # measure, the no-thread no-op IS the contract
                    return {"skipped": "VM_PROFILE_HZ=0", "ok": True}
                t_on = min(t_on, _time_workload(reps, laps, arr))
                profiler.PROFILER.stop()
                t_off = min(t_off, _time_workload(reps, laps, arr))
            delta_pct = min(delta_pct, (t_on - t_off) / t_off * 1e2)
            if delta_pct <= max_delta_pct:
                break
    finally:
        profiler.PROFILER.stop()
        costacc.set_current(prev_cost)
    return {
        "hz": hz,
        "workload_delta_pct": round(delta_pct, 3),
        "max_delta_pct": max_delta_pct,
        "ok": delta_pct <= max_delta_pct,
    }


def main() -> int:
    try:
        max_delta_pct = float(os.environ.get("VM_PROFILE_SMOKE_PCT", "2"))
    except ValueError:
        max_delta_pct = 2.0
    res = run_smoke(max_delta_pct)
    res["check"] = "profiler_overhead"
    print(json.dumps(res))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
